"""Deterministic relay fault injection (``APEX_FAULT_PLAN``) — TEST ONLY.

Every recorded round-3/4/5 relay failure mode (PERF.md §6) can be
replayed on CPU, deterministically, through the REAL drivers: the env
var holds a JSON fault plan (or a path to one), inherited across the
subprocess boundary (bench.py's ``_attempt_once``, autotune's rung
subprocesses, warm_cache's targets), and the drivers call the hook
points below at the places the live relay actually fails. The chaos
suite (``tests/test_resilience.py``) is built on this.

NEVER set ``APEX_FAULT_PLAN`` during scored collection:
``benchmarks/run_all_tpu.sh`` and ``probe_and_collect.sh`` refuse to
start under it, every ledger record written while a plan is active is
stamped ``fault_plan: <hash>`` (inside the content-hashed id, so the
stamp cannot be stripped after the fact), and
``tools/check_bench_labels.py`` fails tier-1 if PERF.md or the dispatch
table ever cites a stamped record — an injected run can never
masquerade as a measurement.

Plan format — a JSON object ``{"faults": [...]}`` (or bare list); each
fault::

    {"site":  "backend_init" | "mid_attempt" | "large_program" |
              "compile" | "calibration_overhead" | "emit" | "verdict" |
              "autotune_budget" | "ckpt_commit" | "ckpt_manifest" |
              "ckpt_data" | "final_save" | "serve_alloc" |
              "serve_prefill" | "serve_decode" | "serve_burst" |
              "serve_swap" |
              "router_kill" | "router_wedge" | "router_slow",
     "kind":  "hang" | "raise" | "exit" | "fabricate" |
              "sigterm_parent" | "sigkill" | "inflate" | "truncate" |
              "degraded" | "set_budget" | "set_field" |
              "truncate_file" | "corrupt_file" | "deny" | "burst" |
              "corrupt",
     "match_env": {"VAR": "value" | null},   # null = must be unset
     "match_ctx": {"step": 2, "phase": "data_visible"},  # hook kwargs
     ... kind-specific fields ...}

Failure-mode map (the §6 catalogue):

=======================================  ================================
recorded failure mode                     scripted as
=======================================  ================================
backend-init hang (round 3)               backend_init/hang
relay-init crash (connection reset)       backend_init/raise or exit
inflated per-dispatch overhead            calibration_overhead/inflate
  (relay-degraded, calibration flap)        (→ bench's calibration-flap
                                            error line)
selective large-HBM starvation            large_program/hang with
  (day-2/round-5 mode)                      min_batch
remote-compile HTTP-500 (b=32 stall)      compile/raise
mid-attempt SIGTERM (outer budget)        mid_attempt/sigterm_parent
full-timeout wedge                        mid_attempt/hang
truncated/corrupt JSON output             emit/truncate, or fabricate
                                            with truncate_bytes
relay-degraded / implausible verdict      verdict/degraded
autotune budget starved                   autotune_budget/set_budget
scripted window replay                    backend_init/fabricate
                                            (prints a canned record,
                                            stamped, and exits)
SIGKILL mid-checkpoint-commit             ckpt_commit/sigkill with
  (wedge teardown during save)              match_ctx phase
slow-disk commit stall                    ckpt_commit/hang (seconds)
truncated/corrupt checkpoint file         ckpt_data/truncate_file or
  (disk rot, torn write)                    corrupt_file
stale-step restore (tampered manifest)    ckpt_manifest/set_field
SIGTERM during the final save             final_save/hang + outer kill
KV-page exhaustion at a chosen round      serve_alloc/deny with
  (serving, ISSUE 15)                       match_ctx tick/phase + times
decode dispatch hang / exception          serve_decode/hang or raise
  (relay wedge mid-serving-round)           with match_ctx step
prefill failure mid-admission             serve_prefill/raise or hang
  (also fired by speculative VERIFY         (one site — verify rides
  dispatches of the same program)           the same compiled program)
trace burst overload (submit storm)       serve_burst/burst with
                                            match_ctx tick (the engine
                                            fabricates + submits the
                                            scripted burst)
heartbeat-silent wedge (ISSUE 16:         flight_silent/hang — fired by
  beats arrived, then the stream            bench.py AFTER the boundary-1
  stopped; flight_watch reaps at the        partial commit, so the reaped
  silence threshold)                        child has beats AND a banked
                                            partial behind it
slow-but-beating run (degraded relay;     heartbeat/hang with seconds=N
  flight_watch must NOT reap before         — the hook fires inside
  the full cap)                             flight.beat AFTER the beat
                                            lands: wall time stretches,
                                            beats keep arriving
whole-replica death mid-trace             router_kill/raise with
  (fleet serving, ISSUE 19; the             match_ctx tick/replica —
  router's failover drains + replays        fired inside the replica's
  through survivors)                        round closure
replica round wedge (the router's         router_wedge/hang — forever
  step watchdog times it out to a           under step_timeout_s, the
  classified DispatchFailure)               breaker trips at the cap
replica running slow, still serving       router_slow/hang with
  (degraded, NOT dead — the breaker         seconds=N + times (bounded
  must not trip on a bounded stall)         stall, round returns clean)
host-copy failure banking a preempted     serve_swap/raise or hang with
  victim's KV pages (swap tier,             match_ctx phase="swap_out"
  ISSUE 20 — falls back to recompute        — the engine classifies it
  preemption, a ``swap_failed`` event)      ``swap_failed``, never hangs
                                            the round (tokens preserved)
host-copy failure restoring swapped       serve_swap/raise or hang with
  pages at re-admission                     match_ctx phase="swap_in"
swapped page bytes rot on the host        serve_swap/corrupt with
  (the handle's checksum catches it;        match_ctx phase="swap_in" —
  restore falls back to recompute)          flips the banked bytes
=======================================  ================================

Kind-specific fields: ``seconds`` (hang: sleep N then continue; absent
= forever), ``message``/``rc`` (raise/exit), ``record``/``rc``/
``truncate_bytes`` (fabricate), ``add_s`` (inflate), ``bytes``
(truncate), ``degraded_kind`` (degraded: relay|implausible|large_hbm),
``budget_s`` (set_budget), ``min_batch`` (large_program matcher),
``field``/``value`` (set_field: tamper one JSON field pre-write),
``keep_bytes`` (truncate_file), ``offset`` (corrupt_file: XOR one
byte), ``times`` (deny: fire at most N times — one scripted refusal
forces exactly one preemption), ``count``/``prompt_len``/``max_new``/
``rid_base`` (burst: the fabricated submit storm's shape).

Stdlib-only, and every check is a no-op dict lookup when the env var is
unset — the hooks cost nothing on the scored path.
"""

import hashlib
import json
import os
import signal
import sys
import time

ENV = "APEX_FAULT_PLAN"

_cache = {"raw": None, "plan": None, "hash": None, "fired": {}}


def active():
    return bool(os.environ.get(ENV))


def plan():
    """The parsed fault list (possibly empty). Raises ValueError on an
    unparseable plan — a chaos test with a broken plan must fail, not
    silently run healthy."""
    raw = os.environ.get(ENV)
    if not raw:
        return []
    if _cache["raw"] == raw:
        return _cache["plan"]
    text = raw
    if not raw.lstrip().startswith(("{", "[")):
        with open(raw) as f:
            text = f.read()
    parsed = json.loads(text)
    faults = parsed.get("faults", []) if isinstance(parsed, dict) \
        else parsed
    if not isinstance(faults, list):
        raise ValueError(f"{ENV}: fault plan must be a list of faults")
    canon = json.dumps(faults, sort_keys=True)
    _cache.update(
        raw=raw, plan=faults, fired={},
        hash="fp-" + hashlib.sha1(canon.encode()).hexdigest()[:10])
    return faults


def plan_hash():
    """``fp-<sha1[:10]>`` of the canonical active plan, or None. Stamped
    by the ledger into every record written under injection."""
    if not active():
        return None
    plan()
    return _cache["hash"]


def _match(fault, ctx):
    for k, want in (fault.get("match_env") or {}).items():
        if os.environ.get(k) != want:
            return False
    if "min_batch" in fault and ctx.get("batch") is not None \
            and ctx["batch"] < fault["min_batch"]:
        return False
    for k, want in (fault.get("match_ctx") or {}).items():
        # hook-kwarg matcher (e.g. the checkpoint commit's step/phase):
        # a plan can target exactly "step 2's commit, after the data
        # rename" — determinism is the whole point of scripted chaos
        if ctx.get(k) != want:
            return False
    return True


def _say(fault, extra=""):
    print(f"# FAULT[{plan_hash()}] site={fault.get('site')} "
          f"kind={fault.get('kind')}{extra}", file=sys.stderr, flush=True)


def _hang(fault):
    _say(fault, f" (sleep {fault.get('seconds', 'forever')})")
    if "seconds" in fault:
        time.sleep(float(fault["seconds"]))
        return
    while True:
        time.sleep(60)


def fire(site, **ctx):
    """Execute any matching faults at *site*. May hang, raise, exit, or
    print a fabricated record and exit — exactly what the live relay
    does to the process at that point."""
    if not active():
        return
    for fault in plan():
        if fault.get("site") != site or not _match(fault, ctx):
            continue
        kind = fault.get("kind")
        if kind == "hang":
            _hang(fault)
        elif kind == "raise":
            _say(fault)
            raise RuntimeError(fault.get(
                "message", f"injected fault at {site}"))
        elif kind == "exit":
            _say(fault)
            sys.exit(int(fault.get("rc", 3)))
        elif kind == "sigterm_parent":
            _say(fault, f" -> SIGTERM pid {os.getppid()}")
            os.kill(os.getppid(), signal.SIGTERM)
            # stay in-flight: the parent's handler decides our fate
            # (bench's on_term SIGKILLs exactly the in-flight child)
            _hang(dict(fault, kind="hang"))
        elif kind == "sigkill":
            # the un-catchable death (wedge teardown, OOM-killer): no
            # Python cleanup runs — exactly what the checkpoint commit
            # protocol's atomicity invariants are tested against
            _say(fault, " -> SIGKILL self")
            os.kill(os.getpid(), signal.SIGKILL)
        elif kind == "fabricate":
            # scripted window replay: print a canned driver record —
            # STAMPED with the plan hash inside the line itself — and
            # exit, without ever touching a backend
            rec = dict(fault.get("record") or {})
            rec.setdefault("fault_plan", plan_hash())
            line = json.dumps(rec)
            if "truncate_bytes" in fault:
                line = line[:int(fault["truncate_bytes"])]
            _say(fault)
            print(line, flush=True)
            sys.exit(int(fault.get("rc", 0)))


def transform(site, value, **ctx):
    """Value-transforming faults (e.g. ``calibration_overhead/inflate``:
    the relay flap that inflates the measured per-dispatch overhead so
    the subtraction straddles — bench's calibration-flap line)."""
    if not active():
        return value
    for fault in plan():
        if fault.get("site") != site or not _match(fault, ctx):
            continue
        if fault.get("kind") == "inflate":
            _say(fault, f" (+{fault.get('add_s', 1e6)}s)")
            value = value + float(fault.get("add_s", 1e6))
    return value


def transform_output(line):
    """``emit``-site faults: corrupt/truncate the driver's one JSON line
    the way a wedging relay teardown does."""
    if not active():
        return line
    for fault in plan():
        if fault.get("site") != "emit" or not _match(fault, {}):
            continue
        if fault.get("kind") == "truncate":
            _say(fault)
            line = line[:int(fault.get("bytes", 20))]
    return line


def transform_json(site, obj, **ctx):
    """``set_field``-kind faults: tamper one field of a JSON-bound dict
    before it is written (e.g. the checkpoint manifest's ``step`` — the
    stale-step restore mode). Returns a (possibly modified) copy; the
    original is never mutated."""
    if not active():
        return obj
    for fault in plan():
        if fault.get("site") != site or not _match(fault, ctx):
            continue
        if fault.get("kind") == "set_field" and "field" in fault:
            _say(fault, f" ({fault['field']} -> {fault.get('value')!r})")
            obj = dict(obj, **{fault["field"]: fault.get("value")})
    return obj


def damage_file(site, path, **ctx):
    """File-damage faults fired AFTER a commit: ``truncate_file``
    (keep the first ``keep_bytes`` bytes — a torn write the rename
    protocol could not see) and ``corrupt_file`` (XOR the byte at
    ``offset`` — silent disk rot). The durability invariant under test:
    a file that no longer hashes to its manifest is never restored."""
    if not active():
        return
    for fault in plan():
        if fault.get("site") != site or not _match(fault, ctx):
            continue
        kind = fault.get("kind")
        if kind == "truncate_file":
            keep = int(fault.get("keep_bytes", 16))
            _say(fault, f" (truncate {path} to {keep}B)")
            with open(path, "r+b") as f:
                f.truncate(keep)
        elif kind == "corrupt_file":
            off = int(fault.get("offset", 0))
            _say(fault, f" (flip byte {off} of {path})")
            with open(path, "r+b") as f:
                f.seek(off)
                b = f.read(1)
                f.seek(off)
                f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")


def _spend(idx, fault):
    """True when *fault* (at plan index *idx*) still has budget under
    its optional ``times`` cap, consuming one firing. Unbounded faults
    always fire — the cap exists so a scripted refusal (``deny``) can
    force exactly N preemptions instead of denying every retry of the
    same round."""
    if "times" not in fault:
        return True
    n = _cache["fired"].get(idx, 0)
    if n >= int(fault["times"]):
        return False
    _cache["fired"][idx] = n + 1
    return True


def denied(site, **ctx):
    """``deny``-kind faults (serving KV-pressure chaos, ISSUE 15):
    True when a matching fault refuses this allocation — the scheduler
    treats it exactly like an empty free list, so the preemption path
    runs under scripted page pressure without shrinking the pool."""
    if not active():
        return False
    for idx, fault in enumerate(plan()):
        if fault.get("site") != site or fault.get("kind") != "deny" \
                or not _match(fault, ctx):
            continue
        if _spend(idx, fault):
            _say(fault, f" (alloc refused, ctx={ctx})")
            return True
    return False


def corrupt(site, **ctx):
    """``corrupt``-kind faults (host swap tier chaos, ISSUE 20): True
    when a matching fault wants the caller's in-memory banked bytes
    damaged — the ENGINE flips the swapped pages' host buffer so the
    handle's checksum catches exactly the silent-rot mode, and the
    restore falls back to recompute instead of resuming from garbage.
    Honors the ``times`` cap like :func:`denied`."""
    if not active():
        return False
    for idx, fault in enumerate(plan()):
        if fault.get("site") != site or fault.get("kind") != "corrupt" \
                or not _match(fault, ctx):
            continue
        if _spend(idx, fault):
            _say(fault, f" (corrupt banked bytes, ctx={ctx})")
            return True
    return False


def burst(site, **ctx):
    """``burst``-kind faults (serving overload chaos, ISSUE 15): the
    matching fault dict — the ENGINE fabricates and submits the
    scripted request storm (count/prompt_len/max_new/rid_base fields)
    so admission control is exercised through the real submit path —
    or None."""
    if not active():
        return None
    for idx, fault in enumerate(plan()):
        if fault.get("site") != site or fault.get("kind") != "burst" \
                or not _match(fault, ctx):
            continue
        if _spend(idx, fault):
            _say(fault, f" (burst ctx={ctx})")
            return fault
    return None


def injected_degraded():
    """``verdict``-site degraded kind (``relay | implausible |
    large_hbm``) or None — consulted by
    :func:`apex_tpu.resilience.classify_measurement`."""
    if not active():
        return None
    for fault in plan():
        if fault.get("site") == "verdict" \
                and fault.get("kind") == "degraded" and _match(fault, {}):
            return fault.get("degraded_kind", "relay")
    return None


def override_budget(budget_s):
    """``autotune_budget``-site faults: starve the autotune pass's
    global budget so the LOUD-drop path is exercised."""
    if not active():
        return budget_s
    for fault in plan():
        if fault.get("site") == "autotune_budget" \
                and fault.get("kind") == "set_budget" \
                and _match(fault, {}):
            _say(fault, f" (budget {budget_s} -> {fault.get('budget_s', 0)})")
            budget_s = float(fault.get("budget_s", 0))
    return budget_s
