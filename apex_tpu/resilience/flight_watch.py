"""Heartbeat-driven rung supervisor (ISSUE 16) — replaces the bare
``timeout`` in ``run_all_tpu.sh``'s ``run()``.

``python -m apex_tpu.resilience.flight_watch --timeout T --row NAME
--flight-dir DIR -- <cmd...>`` runs the rung command with the flight
recorder armed (child env gains ``APEX_FLIGHT_DIR`` + the row label in
``APEX_FLIGHT_ROW``) and supervises its heartbeat stream
(apex_tpu.telemetry.flight):

* the FULL per-rung cap is kept while beats arrive — a slow-but-beating
  run (degraded relay, long compile) is never reaped early;
* a child whose stream goes heartbeat-silent for the silence threshold
  (``resilience.FLIGHT_SILENCE_S``; ``--silence``/``APEX_FLIGHT_SILENCE``
  override) is reaped at that threshold instead of burning the rest of
  its fixed slot — the round-5 gpt_rows wedge sat silent for 15.0 of
  71.4 window minutes that owed rows never got;
* a child that emitted NO beats keeps pre-PR semantics (full cap, reap
  only at timeout): only a stream that STOPPED proves instrumentation
  was there to go quiet — uninstrumented rows lose nothing.

A reap is SIGTERM -> grace (``FLIGHT_GRACE_S``, sized past bench's 15 s
inner-child emergency-flush wait so the PR 6 partial still banks) ->
SIGKILL, then a classified ``flight_reap`` ledger record (verdict from
``resilience.classify_inflight`` at the decision moment, reaped row
named; ``ledger.make_record`` stamps any active fault plan), and exit
143 — a ``resilience.TIMEOUT_RCS`` member, so the collection manifest
classifies the row WEDGED and keeps it owed, exactly as the bare
``timeout`` did.

Relay-proofing: the shell starts this interpreter under
``PALLAS_AXON_POOL_IPS=`` (a wedged relay must not hang the supervisor
at startup) and passes the variable's ORIGINAL state in
``APEX_FLIGHT_POOL_RESTORE`` (``__unset__`` sentinel when it was
absent); the supervisor restores that state into the child env so a
TPU rung dials the relay exactly as before.

Stdlib-only at module level; beats are read from files, never sockets.
"""

import os
import signal
import subprocess
import sys
import time

from apex_tpu import resilience
from apex_tpu.telemetry import flight
from apex_tpu.telemetry import ledger as _tledger

POOL_VAR = "PALLAS_AXON_POOL_IPS"
POOL_UNSET = "__unset__"


def _threshold(cli_value, raw_env, default):
    """--flag > APEX_FLIGHT_* env > the §6 constant. Raw float read:
    zero and fractional thresholds are legal (chaos tests pin seconds-
    scale silence), which the positive-int helpers cannot express."""
    if cli_value is not None:
        return float(cli_value)
    if raw_env:
        try:
            return float(raw_env)
        except ValueError:
            pass
    return float(default)


def _child_env(flight_dir, row):
    env = dict(os.environ)
    if flight_dir:
        env["APEX_FLIGHT_DIR"] = flight_dir
    if row:
        env["APEX_FLIGHT_ROW"] = row
    restore = env.pop("APEX_FLIGHT_POOL_RESTORE", None)
    if restore is not None:
        # undo the supervisor's own relay-proofing for the child: a TPU
        # rung must dial the relay exactly as it did under bare timeout
        if restore == POOL_UNSET:
            env.pop(POOL_VAR, None)
        else:
            env[POOL_VAR] = restore
    return env


def _reap(child, grace_s):
    """SIGTERM -> grace -> SIGKILL; returns the child's exit status if
    it surfaced one inside the grace (the emergency-flush path exits
    143 on its own), else None."""
    try:
        child.terminate()
    except OSError:
        pass
    try:
        return child.wait(timeout=grace_s)
    except (subprocess.TimeoutExpired, OSError):
        pass
    try:
        child.kill()
    except OSError:
        pass
    try:
        return child.wait(timeout=10)
    except (subprocess.TimeoutExpired, OSError):
        return None


def _reap_record(row, reason, verdict, beats, now, silence_s, timeout_s,
                 elapsed_s):
    stamps = [b["mono"] for b in beats
              if isinstance(b.get("mono"), (int, float))
              and not isinstance(b.get("mono"), bool)]
    block = {
        "row": row or "?",
        "verdict": verdict,
        "reason": reason,
        "silence_s": silence_s,
        "timeout_s": timeout_s,
        "elapsed_s": round(elapsed_s, 1),
        "beats": len(beats),
        "age_s": round(now - max(stamps), 1) if stamps else None,
        "last_phase": beats[-1].get("phase") if beats else None,
    }
    # never raises; smoke runs skip the write unless
    # APEX_TELEMETRY_LEDGER is set (the ledger's own rule)
    rec_id = _tledger.append_record(
        harness="flight_reap", platform="shell",
        dispatch_overhead_ms=None, k=None,
        extra={"flight_reap": block})
    print(f"# flight_watch: reaped row={block['row']} reason={reason} "
          f"verdict={verdict} after {block['elapsed_s']}s "
          f"(beats={block['beats']}, last_phase={block['last_phase']}, "
          f"age={block['age_s']}s, ledger={rec_id})",
          file=sys.stderr, flush=True)
    return block


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.resilience.flight_watch",
        description="Run a rung command under heartbeat supervision: "
                    "full cap while beats arrive, early reap on "
                    "heartbeat silence.")
    ap.add_argument("--timeout", type=float, required=True,
                    help="full per-rung cap in seconds")
    ap.add_argument("--row", default=None,
                    help="collection-row label (stamped into beats and "
                         "the flight_reap record)")
    ap.add_argument("--flight-dir", default=None,
                    help="flight dir for the child (default: inherit "
                         "APEX_FLIGHT_DIR)")
    ap.add_argument("--silence", type=float, default=None,
                    help="heartbeat-silence reap threshold in seconds "
                         "(default: APEX_FLIGHT_SILENCE or the §6 "
                         "constant)")
    ap.add_argument("--grace", type=float, default=None,
                    help="SIGTERM->SIGKILL grace in seconds")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="-- <command...>")
    args = ap.parse_args(argv)

    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given after --")

    timeout_s = float(args.timeout)
    silence_s = _threshold(args.silence,
                           os.environ.get("APEX_FLIGHT_SILENCE"),
                           resilience.FLIGHT_SILENCE_S)
    grace_s = _threshold(args.grace, os.environ.get("APEX_FLIGHT_GRACE"),
                         resilience.FLIGHT_GRACE_S)
    fdir = args.flight_dir or os.environ.get("APEX_FLIGHT_DIR")
    if fdir:
        try:
            os.makedirs(fdir, exist_ok=True)
        except OSError:
            fdir = None

    start = time.monotonic()
    try:
        child = subprocess.Popen(cmd, env=_child_env(fdir, args.row))
    except OSError as e:
        print(f"# flight_watch: cannot start {cmd[0]!r}: {e}",
              file=sys.stderr, flush=True)
        return 127

    got = {"sig": None}

    def _forward(signum, frame):
        got["sig"] = signum

    signal.signal(signal.SIGTERM, _forward)
    signal.signal(signal.SIGINT, _forward)

    poll_s = min(2.0, max(0.2, silence_s / 4.0))
    while True:
        rc = child.poll()
        if rc is not None:
            # normal exit: propagate (negative = signal death; report
            # it the way a shell would, 128+sig)
            return rc if rc >= 0 else 128 - rc
        now = time.monotonic()
        beats = [b for b in flight.read_beats(fdir)
                 if isinstance(b.get("mono"), (int, float))
                 and not isinstance(b.get("mono"), bool)
                 and b["mono"] >= start] if fdir else []
        reason = None
        if got["sig"] is not None:
            reason = "signal"       # the outer backstop timeout fired
        elif now - start >= timeout_s:
            reason = "cap"          # full per-rung cap — pre-PR rule
        elif beats and resilience.classify_inflight(
                beats, now, silence_s=silence_s) == resilience.SILENT:
            # >=1 beat seen AND the stream stopped: the wedge
            # signature. A beat-free child never lands here — it keeps
            # its full cap (uninstrumented rows lose nothing).
            reason = "silence"
        if reason is not None:
            verdict = resilience.classify_inflight(
                beats, now, silence_s=silence_s)
            _reap(child, grace_s)
            _reap_record(args.row, reason, verdict, beats, now,
                         silence_s, timeout_s, now - start)
            # 143 regardless of what the emergency flush exited with:
            # a reaped rung is a TIMEOUT_RCS member so the manifest
            # keeps the row owed (the flush banks partials, it does
            # not cash the row)
            return 143
        time.sleep(poll_s)


if __name__ == "__main__":
    sys.exit(main())
