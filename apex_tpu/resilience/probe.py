"""Verdict CLI over the resilience classifier — the thin interface
``benchmarks/probe_and_collect.sh`` consults so the shell driver holds
no health logic of its own.

Run relay-proof (a wedged relay hangs even CPU interpreter start via
the sitecustomize axon registration — CLAUDE.md)::

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \\
        python -m apex_tpu.resilience.probe <cmd> ...

Subcommands:

``log FILE [--smoke]``
    Classify the last JSON line of a driver log (bench.log /
    bench_first.log). Prints the verdict; exits 0 iff healthy — the
    probe loop's collection gate.

``stamp --rc RC [--detail STR] [--out FILE]``
    Classify one matmul-probe run from its exit status (0 = healthy,
    124/timeout = wedged, other = degraded when the probe printed a
    marginal-rate line, else wedged) and write the structured
    probe-state JSON ``{"ts", "verdict", "rc", "detail"}``. Prints the
    verdict; exits 0 iff healthy.

``status [--state FILE] [--bench LOG]``
    Report the classifier verdict of the LAST probe plus its age —
    ``probe_and_collect.sh --status`` calls this instead of dumping the
    raw state file. With ``--bench``, also classifies the window: a
    healthy probe next to a wedged/degraded bench log is the §6
    *selective large-HBM starvation* mode (small programs at device
    speed, the large training-step program starved). Exits 0 iff the
    last probe was healthy.
"""

import argparse
import json
import os
import sys
import time

from apex_tpu import resilience


def default_state():
    """Probe-state path (``APEX_PROBE_STATE``), read when the CLI
    builds its parser — not at import (the APX001 trace-time rule:
    probe_and_collect.sh exports the override per round, and a
    module-level read would freeze the first round's path into any
    long-lived process)."""
    return os.environ.get("APEX_PROBE_STATE", "/tmp/apex_tpu_probe_state")


def classify_probe(rc, detail=""):
    """Verdict for one marginal-rate matmul probe run (the shell's
    ``probe()`` heredoc): exit 0 = healthy band; a timeout killed it =
    wedged; a completed probe outside the band (it printed its marginal
    line) = degraded relay; anything else (no output, init hang killed
    early) = wedged."""
    if rc == 0:
        return resilience.HEALTHY
    if rc in resilience.TIMEOUT_RCS:
        return resilience.WEDGED
    return (resilience.DEGRADED_RELAY
            if "marginal" in (detail or "") else resilience.WEDGED)


def cmd_log(args):
    try:
        with open(args.file) as f:
            text = f.read()
    except OSError as e:
        print(f"{resilience.WEDGED}: no driver log ({e})")
        return 1
    _, rec = resilience.last_json(text)
    verdict = resilience.classify(rec, smoke=args.smoke)
    detail = ""
    if rec is not None:
        detail = (f" value={rec.get('value')} "
                  f"mfu={rec.get('mfu')}"
                  + (f" fault_plan={rec['fault_plan']}"
                     if rec.get("fault_plan") else ""))
    print(f"{verdict}:{detail or ' no JSON line in log'}")
    return 0 if verdict == resilience.HEALTHY else 1


def cmd_stamp(args):
    verdict = classify_probe(args.rc, args.detail)
    state = {"ts": round(time.time(), 3), "verdict": verdict,
             "rc": args.rc, "detail": (args.detail or "")[:500]}
    if args.out:
        resilience.atomic_write_json(args.out, state)
    print(verdict)
    return 0 if verdict == resilience.HEALTHY else 1


def read_state(path):
    """Parsed probe-state JSON, or a best-effort wrapper around a legacy
    plain-text state line (verdict unknown)."""
    with open(path) as f:
        text = f.read()
    try:
        state = json.loads(text)
        if isinstance(state, dict):
            return state
    except ValueError:
        pass
    return {"ts": os.path.getmtime(path), "verdict": None,
            "detail": text.strip()[:500]}


def cmd_status(args):
    try:
        state = read_state(args.state)
    except OSError:
        print("no probe has run yet (no state file)")
        return 1
    age = max(0, int(time.time() - (state.get("ts") or 0)))
    verdict = state.get("verdict") or "unknown (legacy state format)"
    print(f"last probe: {verdict} (age {age}s) — "
          f"{state.get('detail') or 'no detail'}")
    if args.bench and os.path.exists(args.bench):
        try:
            with open(args.bench) as f:
                _, rec = resilience.last_json(f.read())
        except OSError:
            rec = None
        bench_verdict = resilience.classify(
            rec, small_hbm_ok=(state.get("verdict") == resilience.HEALTHY))
        print(f"last bench: {bench_verdict}")
        if state.get("verdict") == resilience.HEALTHY \
                and bench_verdict in (resilience.WEDGED,
                                      resilience.DEGRADED_LARGE_HBM,
                                      resilience.DEGRADED_RELAY):
            print(f"window: {resilience.DEGRADED_LARGE_HBM} — probe "
                  "healthy but the large-HBM bench program starved "
                  "(PERF.md §6 selective starvation)")
    return 0 if state.get("verdict") == resilience.HEALTHY else 1


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.resilience.probe",
        description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("log", help="classify a driver log's last JSON line")
    p.add_argument("file")
    p.add_argument("--smoke", action="store_true",
                   help="CPU is the requested backend")
    p.set_defaults(fn=cmd_log)

    p = sub.add_parser("stamp", help="classify a probe run; write state")
    p.add_argument("--rc", type=int, required=True)
    p.add_argument("--detail", default="")
    p.add_argument("--out", default=default_state())
    p.set_defaults(fn=cmd_stamp)

    p = sub.add_parser("status", help="verdict + age of the last probe")
    p.add_argument("--state", default=default_state())
    p.add_argument("--bench", default=None,
                   help="bench log to cross-classify (large-HBM mode)")
    p.set_defaults(fn=cmd_status)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
