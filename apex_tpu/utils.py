"""Small shared utilities used across apex_tpu subpackages."""

import jax
import jax.numpy as jnp


def train_dropout(rng, x, p, zero=0.0):
    """Inverted dropout: keep with prob (1-p), rescale survivors by
    1/(1-p). The single implementation behind the contrib fmha /
    transducer / mask_softmax_dropout training paths (each of which
    gates on its own is-training flag and raises its own error when the
    rng is missing)."""
    keep = jax.random.bernoulli(rng, 1.0 - p, x.shape)
    return jnp.where(keep, x / (1.0 - p), zero)
