"""Pallas TPU fused linear + cross-entropy (the LM head without logits).

The GPT loss head computes ``logits = X @ E^T`` ([n, V], the largest
activation in the model — 825 MB bf16 at b=8, s=1024, V=50304) and then
a softmax cross entropy over it. This kernel fuses the two so the [n, V]
logits NEVER exist in HBM: vocab is processed in lane-aligned chunks with
a flash-style online (max, sumexp) accumulator per row, and the target
logit is gathered in-register from the chunk that holds each row's label.

Goes beyond the reference (whose contrib/csrc/xentropy still takes
materialized logits): this is the fused-LM-head design the TPU memory
hierarchy wants — the logits tile lives in VMEM only, HBM traffic drops
from O(n*V) to O((n + V) * h), and the freed ~GBs raise the trainable
batch. Backward splits into two kernels with opposite accumulation
orders (dX accumulates over vocab chunks, dE over row blocks — the TPU
grid is sequential, so each output block accumulates while its index is
constant in the innermost dim), both recomputing the probability tile
from the saved per-row LSE, exactly the flash-attention bwd structure.

Semantics match ``-log_softmax(x @ e^T)[i, labels[i]]`` per row (fp32
softmax), with optional label smoothing (contrib-xentropy semantics —
the uniform term's logits sum rides the same chunk pass) and a
vocab-parallel variant for tensor parallelism
(``linear_cross_entropy_sharded``: per-shard online stats + pmax/psum
combine; shard logits never materialize either). Tested against the jnp
and contrib references in interpret mode (tests/test_xent_pallas.py).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.dispatch import tiles


# Row block sizes the number of full passes over E (n/br passes of
# V*h*2 bytes each in fwd and again in dx): bigger blocks cut that
# traffic linearly, so the cap is VMEM-derived per (h, bv) rather than a
# constant — at GPT-2 shapes (h=768, bv=384) it resolves to 512, ~5 MB
# in the worst kernel (dx: x + dx out + fp32 acc + logits + p tiles).
# The model (and the 8 MB budget / 512 caps) lives in the shared tile
# module (apex_tpu/dispatch/tiles.py) so sweeps and the label checker
# judge exactly what this file lowers. APEX_XENT_ROW_BLOCK overrides
# the CAP (escape hatch if Mosaic's double-buffering pushes the modeled
# 6.5 MB over real VMEM on device) — read at TRACE time, never import
# time, so autotune subprocesses and tests vary it without re-import.
# The vocab chunk is the largest lane-aligned divisor of V <= 512
# (GPT-2's 50304 = 2^7*3*131 gives 384).
_MAX_VCHUNK = tiles.XENT_MAX_VCHUNK
_VMEM_BUDGET = tiles.XENT_VMEM_BUDGET


def _env_row_cap():
    """Trace-time APEX_XENT_ROW_BLOCK (the heuristic's cap; shared
    parser tiles.env_int — a preference, not a raise)."""
    return tiles.env_int("APEX_XENT_ROW_BLOCK")


def _v_chunk(V):
    """Largest multiple-of-128 divisor of V that is <= _MAX_VCHUNK
    (0 → unsupported)."""
    return tiles.xent_v_chunk(V)


def _row_block(n, h, bv):
    """The heuristic row block (shared VMEM model, capped by the
    trace-time APEX_XENT_ROW_BLOCK escape hatch; 0 → unsupported)."""
    return tiles.xent_row_block(n, h, bv,
                                cap=_env_row_cap() or tiles.XENT_ROW_CAP)


# Process-wide exact-row-block preference (tri-state; falls back per
# shape — only the per-call ``row_block=`` raises on an illegal tile)
_ROW_BLOCK_PREF = None


def set_row_block(value):
    """Pin the process-wide row-block preference (exact block, int), or
    un-pin with None. Illegal for a shape → heuristic, silently."""
    global _ROW_BLOCK_PREF
    tiles.check_setter_value(value, "row_block")
    _ROW_BLOCK_PREF = value


def _resolve_br(n, V, h, bv, row_block, vmem_budget, row_block_pref):
    """The effective row block: per-call ``row_block`` (raises on an
    illegal tile, judged under ``vmem_budget`` when given) >
    ``set_row_block`` > table pref > the heuristic (env-capped, sized
    under ``vmem_budget`` when given). Returns 0 when even the
    heuristic finds no block (caller raises unsupported)."""
    dims = {"n": n, "v": V, "h": h}
    if vmem_budget is not None:
        problems = tiles.legal("lm_head", dims, None,
                               {"vmem_budget": vmem_budget})
        if problems:
            raise ValueError("xent_pallas: illegal vmem_budget: "
                             + "; ".join(problems))
    if row_block is not None:
        params = {"row_block": row_block}
        if vmem_budget is not None:
            params["vmem_budget"] = vmem_budget
        problems = tiles.legal("lm_head", dims, None, params)
        if problems:
            raise ValueError("xent_pallas: illegal row_block: "
                             + "; ".join(problems))
        return row_block
    budget = vmem_budget or _VMEM_BUDGET
    for pref in (_ROW_BLOCK_PREF, row_block_pref):
        if pref is None:
            continue
        params = {"row_block": pref}
        if vmem_budget is not None:
            params["vmem_budget"] = vmem_budget
        if not tiles.legal("lm_head", dims, None, params):
            return pref
    br = tiles.xent_row_block(
        n, h, bv, cap=_env_row_cap() or tiles.XENT_ROW_CAP,
        budget=budget)
    if not br:
        # only reachable through an explicit vmem_budget (a no-knob
        # call already passed supported(), which sizes under the
        # default budget): an in-range budget this shape cannot tile
        # under must raise cleanly, not ZeroDivisionError mid-trace
        raise ValueError(
            f"xent_pallas: no legal row block for [{n},{h}]x[{V},{h}] "
            f"under vmem_budget={budget} (fixed [bv={bv}, h] tiles "
            f"alone need {6 * bv * h} B)")
    return br


def supported(n, V, h):
    """Whether the fused head handles X [n, h] x E [V, h]."""
    bv = _v_chunk(V)
    return bv != 0 and h % 128 == 0 and _row_block(n, h, bv) != 0


def _hit(labels, iv, bv, rows):
    """[rows, bv] one-hot of each row's label within vocab chunk iv
    (all-zero for rows whose label lives in another chunk)."""
    local = labels - iv * bv
    cols = lax.broadcasted_iota(jnp.int32, (rows, bv), 1)
    return (cols == local).astype(jnp.float32)


def _accumulate_chunk(x_ref, e_ref, lab_ref, m_scr, s_scr, t_scr, u_scr,
                      bv):
    """One vocab chunk's online (max, sumexp) update + target gather —
    plus, ONLY when smoothing is active (``u_scr`` not None), the running
    logits sum for the uniform term. The shared core of the full and
    partial (vocab-sharded) forwards; the smoothing=0 path is
    bit-identical to the pre-smoothing kernel."""
    iv = pl.program_id(1)
    x = x_ref[...]
    e = e_ref[...]
    logits = lax.dot_general(x, e, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    rows = logits.shape[0]

    @pl.when(iv == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        s_scr[...] = jnp.zeros_like(s_scr)
        t_scr[...] = jnp.zeros_like(t_scr)
        if u_scr is not None:
            u_scr[...] = jnp.zeros_like(u_scr)

    m_old = m_scr[...]
    tile_max = jnp.max(logits, axis=1, keepdims=True)
    m_new = jnp.maximum(m_old, tile_max)
    s_scr[...] = (s_scr[...] * jnp.exp(m_old - m_new)
                  + jnp.sum(jnp.exp(logits - m_new), axis=1, keepdims=True))
    m_scr[...] = m_new

    hit = _hit(lab_ref[...], iv, bv, rows)
    t_scr[...] += jnp.sum(logits * hit, axis=1, keepdims=True)
    if u_scr is not None:
        u_scr[...] += jnp.sum(logits, axis=1, keepdims=True)


def _fwd_kernel(x_ref, e_ref, lab_ref, loss_ref, lse_ref, m_scr, s_scr,
                t_scr, *maybe_u, bv, nv, eps, v_total):
    u_scr = maybe_u[0] if maybe_u else None
    _accumulate_chunk(x_ref, e_ref, lab_ref, m_scr, s_scr, t_scr, u_scr,
                      bv)

    @pl.when(pl.program_id(1) == nv - 1)
    def _():
        lse = m_scr[...] + jnp.log(s_scr[...])
        lse_ref[...] = lse
        if eps:
            # label smoothing (contrib xentropy semantics):
            # (1-eps)*(lse - x_y) + eps*(lse - mean_j x_j)
            loss_ref[...] = (lse - (1.0 - eps) * t_scr[...]
                             - eps * u_scr[...] / v_total)
        else:
            loss_ref[...] = lse - t_scr[...]


def _fwd_partial_kernel(*refs, bv, nv, eps):
    """Vocab-SHARD forward: emit this shard's per-row partials — (rowmax,
    sumexp-at-rowmax, target-logit partial) plus, when smoothing is
    active, the logits-sum partial — for the caller's cross-rank
    combine."""
    n_out = 4 if eps else 3
    x_ref, e_ref, lab_ref = refs[:3]
    outs = refs[3:3 + n_out]
    scrs = refs[3 + n_out:]
    u_scr = scrs[3] if eps else None
    _accumulate_chunk(x_ref, e_ref, lab_ref, scrs[0], scrs[1], scrs[2],
                      u_scr, bv)

    @pl.when(pl.program_id(1) == nv - 1)
    def _():
        for ref, scr in zip(outs, scrs):
            ref[...] = scr[...]


def _dx_kernel(x_ref, e_ref, lab_ref, lse_ref, dl_ref, dx_ref, acc_scr,
               *, bv, nv, eps, v_total):
    iv = pl.program_id(1)
    x = x_ref[...]
    e = e_ref[...]
    logits = lax.dot_general(x, e, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    rows = logits.shape[0]
    p = jnp.exp(logits - lse_ref[...])
    coeff = (p - (1.0 - eps) * _hit(lab_ref[...], iv, bv, rows)
             - eps / v_total).astype(e.dtype)

    @pl.when(iv == 0)
    def _():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    acc_scr[...] += lax.dot_general(coeff, e, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)

    @pl.when(iv == nv - 1)
    def _():
        dx_ref[...] = (dl_ref[...] * acc_scr[...]).astype(dx_ref.dtype)


def _de_kernel(x_ref, e_ref, lab_ref, lse_ref, dl_ref, de_ref, *, bv,
               eps, v_total):
    # grid (nv, nb): row blocks innermost so each dE chunk accumulates
    # while its block index is constant
    iv = pl.program_id(0)
    ib = pl.program_id(1)
    x = x_ref[...]
    e = e_ref[...]
    logits = lax.dot_general(x, e, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    rows = logits.shape[0]
    p = jnp.exp(logits - lse_ref[...])
    coeff = (p - (1.0 - eps) * _hit(lab_ref[...], iv, bv, rows)
             - eps / v_total)
    wx = (dl_ref[...] * x.astype(jnp.float32))

    @pl.when(ib == 0)
    def _():
        de_ref[...] = jnp.zeros_like(de_ref[...])

    de_ref[...] += lax.dot_general(
        coeff.astype(x.dtype), wx.astype(x.dtype),
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)


def _common_specs(br, bv, h):
    xspec = pl.BlockSpec((br, h), lambda ib, iv: (ib, 0))
    espec = pl.BlockSpec((bv, h), lambda ib, iv: (iv, 0))
    lspec = pl.BlockSpec((br, 1), lambda ib, iv: (ib, 0))
    return xspec, espec, lspec


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def linear_cross_entropy_sharded(x, embedding_shard, labels, axis_name,
                                 interpret=False, smoothing=0.0,
                                 reduce_dx=True, row_block=None,
                                 vmem_budget=None, row_block_pref=None):
    """Vocab-parallel fused linear+CE: the tensor-parallel form of
    ``linear_cross_entropy`` (reference analog:
    tensor_parallel/cross_entropy.py over materialized logit shards —
    here the shard logits never exist in HBM either).

    Call inside ``shard_map`` with ``embedding_shard`` [V/tp, h] sharded
    over ``axis_name`` and ``x`` [n, h] / ``labels`` [n] (GLOBAL vocab
    ids) replicated along it. Each rank runs the row-blocked kernel over
    its shard emitting per-row partials — (rowmax, sumexp, target
    partial), plus the logits-sum partial when ``smoothing`` is active;
    the cross-rank combine (pmax + two or three psums over [n] vectors —
    tiny) forms the global LSE and loss. Backward reuses the
    single-shard kernels with the GLOBAL lse: dX is the psum of the
    per-shard dx, dE stays shard-local. Check ``supported(n, V_shard,
    h)`` on the SHARD dims.

    ``smoothing`` uses CONTRIB-xentropy semantics ((1-eps)*nll +
    eps*(lse - mean logits)) — NOT vocab_parallel_cross_entropy's
    Megatron semantics (which rescales eps by V/(V-1) against mean
    log-probs); the two differ numerically for the same eps.

    ``reduce_dx``: True (default) psums dX across ``axis_name`` inside
    the vjp — for callers whose upstream hidden is tp-replicated. Pass
    False when a downstream mapping performs the cross-rank reduction
    itself (e.g. a sequence-parallel gather whose backward
    reduce-scatters): the vjp then returns this rank's PARTIAL dX,
    halving collective traffic on the model's hottest bwd tensor.

    Tile knobs (``row_block``/``vmem_budget`` raise, ``row_block_pref``
    falls back) match :func:`linear_cross_entropy`; legality is judged
    on the SHARD dims, like ``supported``.
    """
    del reduce_dx  # backward-only knob
    return _fwd_sharded(x, embedding_shard, labels, axis_name,
                        interpret, smoothing, row_block, vmem_budget,
                        row_block_pref)[0]


def _fwd_sharded(x, embedding_shard, labels, axis_name, interpret,
                 smoothing=0.0, row_block=None, vmem_budget=None,
                 row_block_pref=None):
    n, h = x.shape
    Vs = embedding_shard.shape[0]
    if not supported(n, Vs, h):
        raise ValueError(
            f"xent_pallas sharded: unsupported [{n},{h}]x[{Vs},{h}]")
    bv = _v_chunk(Vs)
    br = _resolve_br(n, Vs, h, bv, row_block, vmem_budget,
                     row_block_pref)
    nb, nv = n // br, Vs // bv
    # shift labels into SHARD-local ids: out-of-shard rows match no
    # column in any chunk, so their hit (and target partial) is zero
    rank = lax.axis_index(axis_name)
    labs = (labels.astype(jnp.int32) - rank * Vs).reshape(n, 1)
    xspec, espec, lspec = _common_specs(br, bv, h)
    n_part = 4 if smoothing else 3
    parts = pl.pallas_call(
        functools.partial(_fwd_partial_kernel, bv=bv, nv=nv,
                          eps=float(smoothing)),
        grid=(nb, nv),
        in_specs=[xspec, espec, lspec],
        out_specs=(lspec,) * n_part,
        out_shape=(jax.ShapeDtypeStruct((n, 1), jnp.float32),) * n_part,
        scratch_shapes=[pltpu.VMEM((br, 1), jnp.float32)] * n_part,
        interpret=interpret,
    )(x, embedding_shard, labs)
    m, s_, t = parts[:3]
    # cross-rank online-softmax combine on [n] vectors
    m_g = lax.pmax(m, axis_name)
    l_g = lax.psum(s_ * jnp.exp(m - m_g), axis_name)
    t_g = lax.psum(t, axis_name)
    lse = m_g + jnp.log(l_g)
    if smoothing:
        u_g = lax.psum(parts[3], axis_name)
        v_total = Vs * lax.axis_size(axis_name)
        loss = (lse - (1.0 - smoothing) * t_g
                - smoothing * u_g / v_total)
    else:
        loss = lse - t_g
    return loss[:, 0], (x, embedding_shard, labs, lse)


def _fwd_sharded_rule(x, embedding_shard, labels, axis_name, interpret,
                      smoothing, reduce_dx=True, row_block=None,
                      vmem_budget=None, row_block_pref=None):
    return _fwd_sharded(x, embedding_shard, labels, axis_name, interpret,
                        smoothing, row_block, vmem_budget,
                        row_block_pref)


def _bwd_sharded_rule(axis_name, interpret, smoothing, reduce_dx,
                      row_block, vmem_budget, row_block_pref, res, g):
    x, embedding_shard, labs, lse = res
    v_total = embedding_shard.shape[0] * lax.axis_size(axis_name)
    dx_local, de, _ = _bwd_kernels(x, embedding_shard, labs, lse, g,
                                   interpret, smoothing, v_total,
                                   row_block, vmem_budget,
                                   row_block_pref)
    # dX sums every shard's p_shard @ E_shard contribution; dE is local.
    # With reduce_dx=False the caller's downstream mapping (e.g. an sp
    # gather's reduce-scatter bwd) performs the sum instead.
    dx = lax.psum(dx_local, axis_name) if reduce_dx else dx_local
    return dx, de, None


linear_cross_entropy_sharded.defvjp(_fwd_sharded_rule, _bwd_sharded_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def linear_cross_entropy(x, embedding, labels, interpret=False,
                         smoothing=0.0, row_block=None, vmem_budget=None,
                         row_block_pref=None):
    """Fused ``-log_softmax(x @ embedding^T)[i, labels[i]]`` -> [n] fp32.

    x: [n, h]; embedding: [V, h]; labels: [n] int32. The [n, V] logits
    are never materialized. Check ``supported(n, V, h)`` first.
    ``interpret=True`` for CPU tests. ``smoothing``: label smoothing with
    CONTRIB-xentropy semantics ((1-eps)*nll + eps*(lse - mean logits) —
    NOT vocab_parallel_cross_entropy's Megatron rescale). When active it
    costs one extra row-vector accumulator riding the same vocab-chunk
    pass; at the default 0.0 the kernels are bit-identical to the
    pre-smoothing ones (the accumulator is not even allocated).

    Tile knobs: ``row_block`` demands an exact row block and
    ``vmem_budget`` the model budget it is judged under — both raise on
    illegal values (``apex_tpu.dispatch.tiles``). ``row_block_pref`` is
    the preference form (table params; falls back), with
    ``set_row_block`` above it and the heuristic (whose cap stays the
    trace-time ``APEX_XENT_ROW_BLOCK`` escape hatch) below.
    """
    return _fwd(x, embedding, labels, interpret, smoothing, row_block,
                vmem_budget, row_block_pref)[0]


def _fwd(x, embedding, labels, interpret, smoothing=0.0, row_block=None,
         vmem_budget=None, row_block_pref=None):
    n, h = x.shape
    V = embedding.shape[0]
    if not supported(n, V, h):
        raise ValueError(f"xent_pallas: unsupported [{n},{h}]x[{V},{h}]")
    bv = _v_chunk(V)
    br = _resolve_br(n, V, h, bv, row_block, vmem_budget, row_block_pref)
    nb, nv = n // br, V // bv
    labs = labels.astype(jnp.int32).reshape(n, 1)
    xspec, espec, lspec = _common_specs(br, bv, h)
    n_scr = 4 if smoothing else 3
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, bv=bv, nv=nv,
                          eps=float(smoothing), v_total=float(V)),
        grid=(nb, nv),
        in_specs=[xspec, espec, lspec],
        out_specs=(lspec, lspec),
        out_shape=(jax.ShapeDtypeStruct((n, 1), jnp.float32),
                   jax.ShapeDtypeStruct((n, 1), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((br, 1), jnp.float32)] * n_scr,
        interpret=interpret,
    )(x, embedding, labs)
    return loss[:, 0], (x, embedding, labs, lse)


def _fwd_rule(x, embedding, labels, interpret, smoothing, row_block=None,
              vmem_budget=None, row_block_pref=None):
    return _fwd(x, embedding, labels, interpret, smoothing, row_block,
                vmem_budget, row_block_pref)


def _bwd_kernels(x, embedding, labs, lse, g, interpret, smoothing=0.0,
                 v_total=None, row_block=None, vmem_budget=None,
                 row_block_pref=None):
    """The two backward pallas calls, shared by the single-slab and the
    vocab-sharded vjp rules (``embedding`` is the full table or one
    shard — the kernels only see its leading dim; ``v_total`` is the
    GLOBAL vocab for the smoothed uniform term, defaulting to the local
    table size)."""
    n, h = x.shape
    V = embedding.shape[0]
    if v_total is None:
        v_total = V
    bv = _v_chunk(V)
    br = _resolve_br(n, V, h, bv, row_block, vmem_budget, row_block_pref)
    nb, nv = n // br, V // bv
    xspec, espec, lspec = _common_specs(br, bv, h)
    dl = g.astype(jnp.float32).reshape(n, 1)

    dx = pl.pallas_call(
        functools.partial(_dx_kernel, bv=bv, nv=nv,
                          eps=float(smoothing), v_total=float(v_total)),
        grid=(nb, nv),
        in_specs=[xspec, espec, lspec, lspec, lspec],
        out_specs=xspec,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        scratch_shapes=[pltpu.VMEM((br, h), jnp.float32)],
        interpret=interpret,
    )(x, embedding, labs, lse, dl)

    # transposed grid for dE: row blocks innermost (see _de_kernel)
    xspec_t = pl.BlockSpec((br, h), lambda iv, ib: (ib, 0))
    espec_t = pl.BlockSpec((bv, h), lambda iv, ib: (iv, 0))
    lspec_t = pl.BlockSpec((br, 1), lambda iv, ib: (ib, 0))
    de = pl.pallas_call(
        functools.partial(_de_kernel, bv=bv, eps=float(smoothing),
                          v_total=float(v_total)),
        grid=(nv, nb),
        in_specs=[xspec_t, espec_t, lspec_t, lspec_t, lspec_t],
        out_specs=espec_t,
        out_shape=jax.ShapeDtypeStruct((V, h), jnp.float32),
        interpret=interpret,
    )(x, embedding, labs, lse, dl)
    return dx, de.astype(embedding.dtype), None


def _bwd_rule(interpret, smoothing, row_block, vmem_budget,
              row_block_pref, res, g):
    x, embedding, labs, lse = res
    return _bwd_kernels(x, embedding, labs, lse, g, interpret, smoothing,
                        row_block=row_block, vmem_budget=vmem_budget,
                        row_block_pref=row_block_pref)


linear_cross_entropy.defvjp(_fwd_rule, _bwd_rule)
