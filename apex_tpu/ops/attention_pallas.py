"""Pallas TPU fused attention, VMEM-resident rows (fwd + bwd).

Self-authored alternative to the bundled multi-pass flash kernel for the
sequence lengths the reference's fused attention actually targets
(contrib/csrc/fmha supports seq <= 512; fast_multihead_attn seq ~64-1024):
at those lengths a whole [block_q, sk] score row fits in VMEM, so each
(batch, head, q-block) grid step computes scores, the exact fp32 softmax
over the FULL key row, and the output matmul in one kernel — no online
max/sum rescaling passes, no [s, s] tensor in HBM.

Backward comes in two structures behind the measured ``BWD_IMPL`` knob
(monolithic is the default until the queued TPU A/B decides — see the
knob's comment):

* ``"split"``: a q-major dq pass that recomputes S and P from
  (q, k, v), forms dP = dO V^T, uses D = rowsum(dO * O) = rowsum(P * dP)
  to avoid needing O, writes dQ = dS K — and emits the per-row softmax
  stats (m, l, D) as [b, h, sq] fp32 byproducts; then a k-major dk/dv
  pass where each (b, h, k-block) grid step reconstructs P row-exactly
  from those stats and owns its [bk, d] dk/dv outputs outright (no
  accumulation across grid steps). Eligibility is VMEM-gated
  (``_split_ok``): the k-major pass keeps the full [sq, d] q and dO
  resident, so very long sq falls back to monolithic.
* ``"monolithic"``: one self-contained q-major kernel (no saved stats)
  that additionally accumulates dK += dS^T Q, dV += P^T dO across
  q-blocks — safe because the TPU grid executes sequentially and the
  dk/dv blocks stay VMEM-resident while the innermost (q) index varies.

dk/dv accumulate in fp32 regardless of the input dtype in both.

Masking matches ops.attention._dense_attention exactly: causal triangle
(generated from iota, no mask operand), optional segment ids (packed
varlen batches), masked positions excluded from the softmax, fully-masked
rows → 0.

Trade-off vs flash: with causal masking the kernel still computes the
full [block_q, sk] score block (the masked half is wasted MXU work), so
it targets moderate sequence lengths where the single-pass structure wins
more than the causal skip would save. benchmarks/profile_attention.py
measures the crossover; ops.attention routes to this kernel via its
``impl="rows"`` knob / ``set_default_impl`` (the measured winner is the
default there).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


_VMEM_BUDGET = 10 * 1024 * 1024  # fp32 [bq, sk] working-set bytes
_BWD_ARRAYS = 4  # S/P, dP, dS live + headroom (bwd is the tight pass)


def _q_block(sq, sk):
    """Largest power-of-two q block dividing sq whose bwd working set
    ([bq, sk] fp32 x _BWD_ARRAYS) fits the budget (0 → unsupported)."""
    from apex_tpu.ops.attention import _block

    cap = max(1, _VMEM_BUDGET // (4 * sk * _BWD_ARRAYS))
    b = _block(sq, cap)
    return b if b >= 8 else 0


def supported(sq, sk, d):
    """Whether the VMEM-row kernel handles [.., sq, d] x [.., sk, d].
    sk must be lane-aligned; d bounded so the [sk, d] K/V operands and
    fp32 dk/dv accumulators stay small next to the score rows."""
    return sk % 128 == 0 and d <= 256 and _q_block(sq, sk) != 0


def _masks(iq, bq, rows, sk, causal, seg_q, seg_kv, col0=0,
           seg_rows=None):
    """Boolean masked-out matrix for one [rows, sk] score block (True =
    excluded), or None when unmasked. seg_* are refs or None. ``col0``
    offsets the absolute column index (k-major blocks); ``seg_rows``
    overrides the row-id slice taken from seg_q (q chunks)."""
    masked = None
    if causal:
        row = iq * bq + lax.broadcasted_iota(jnp.int32, (rows, sk), 0)
        col = col0 + lax.broadcasted_iota(jnp.int32, (rows, sk), 1)
        masked = col > row
    if seg_q is not None:
        sq_row = seg_q[0, :] if seg_rows is None else seg_rows
        skv_row = seg_kv[0, :]
        diff = sq_row[:, None] != skv_row[None, :]
        masked = diff if masked is None else masked | diff
    return masked


def _softmax_stats(s, masked):
    """Exact fp32 softmax over the full key row with dense-reference
    semantics (masked excluded, fully-masked rows -> 0). Returns
    (p, rowmax m, rowsum l) — m/l let a k-major pass reconstruct p
    row-exactly without the full row."""
    if masked is not None:
        s = jnp.where(masked, jnp.finfo(jnp.float32).min, s)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    if masked is not None:
        e = jnp.where(masked, 0.0, e)
    tot = jnp.sum(e, axis=-1, keepdims=True)
    p = jnp.where(tot > 0, e / jnp.where(tot > 0, tot, 1.0), 0.0)
    return p, m, tot


def _softmax(s, masked):
    return _softmax_stats(s, masked)[0]


def _p_from_stats(s, m, tot, masked):
    """Row-exact P reconstruction from saved (rowmax m, rowsum tot)
    [rows, 1] stats — same exclusion and zero-row semantics as
    ``_softmax_stats`` (whose outputs m/tot must come from the same
    mask)."""
    # Fully-masked rows save m = finfo.min, so an unclamped s - m
    # overflows to +inf in the k-major pass before the where() discards
    # it. s - m <= 0 holds for every live row (m is that row's max), so
    # clamping at 0 is exact — and keeps e finite for any future
    # arithmetic inserted before the mask (e.g. a fused scale).
    e = jnp.exp(jnp.minimum(s - m, 0.0))
    if masked is not None:
        e = jnp.where(masked, 0.0, e)
    return jnp.where(tot > 0, e / jnp.where(tot > 0, tot, 1.0), 0.0)


def _fwd_kernel(*refs, scale, causal, has_seg, bq):
    if has_seg:
        q_ref, k_ref, v_ref, sq_ref, skv_ref, o_ref = refs
    else:
        (q_ref, k_ref, v_ref, o_ref), sq_ref, skv_ref = refs, None, None
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    s = s * jnp.float32(scale)
    masked = _masks(pl.program_id(2), bq, q.shape[0], k.shape[0],
                    causal, sq_ref, skv_ref)
    p = _softmax(s, masked).astype(v.dtype)
    o = lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
    o_ref[0, 0] = o.astype(o_ref.dtype)


def _fwd_kernel_chunked(*refs, scale, causal, has_seg, bq):
    """Causal-skip fwd: keys are processed in bq-sized chunks and a chunk
    whose columns are all beyond this q-block's causal reach is never
    computed (the guarded branch genuinely skips — the TPU grid is
    sequential scalar control flow). Skipped chunks leave garbage in the
    score scratch; the softmax's causal `where` overwrites exactly those
    positions, so the garbage is never observed."""
    if has_seg:
        q_ref, k_ref, v_ref, sq_ref, skv_ref, o_ref, s_scr, o_scr = refs
    else:
        q_ref, k_ref, v_ref, o_ref, s_scr, o_scr = refs
        sq_ref = skv_ref = None
    q = q_ref[0, 0]
    rows = q.shape[0]
    sk = k_ref.shape[2]
    nk = sk // bq
    iq = pl.program_id(2)
    reach = iq * bq + rows - 1  # last (absolute) row of this q block

    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            kc = k_ref[0, 0, c * bq:(c + 1) * bq, :]
            s_scr[:, c * bq:(c + 1) * bq] = lax.dot_general(
                q, kc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * jnp.float32(scale)

    masked = _masks(iq, bq, rows, sk, causal, sq_ref, skv_ref)
    p = _softmax(s_scr[...], masked).astype(v_ref.dtype)

    o_scr[...] = jnp.zeros_like(o_scr)
    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            vc = v_ref[0, 0, c * bq:(c + 1) * bq, :]
            o_scr[...] += lax.dot_general(
                p[:, c * bq:(c + 1) * bq], vc, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    o_ref[0, 0] = o_scr[...].astype(o_ref.dtype)


def _bwd_kernel(*refs, scale, causal, has_seg, bq):
    if has_seg:
        (q_ref, k_ref, v_ref, sq_ref, skv_ref, do_ref,
         dq_ref, dk_ref, dv_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, dq_ref, dk_ref, dv_ref) = refs
        sq_ref = skv_ref = None
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    do = do_ref[0, 0]

    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    s = s * jnp.float32(scale)
    masked = _masks(pl.program_id(2), bq, q.shape[0], k.shape[0],
                    causal, sq_ref, skv_ref)
    p = _softmax(s, masked)
    p_lo = p.astype(q.dtype)

    # dP in fp32; D = rowsum(P * dP) == rowsum(dO * O) so O is not needed
    dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    dcol = jnp.sum(p * dp, axis=-1, keepdims=True)
    ds = (p * (dp - dcol) * jnp.float32(scale)).astype(q.dtype)

    dq = lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)

    # dk/dv accumulate across the (innermost, sequential) q grid axis;
    # their block index is constant in iq so the block stays resident
    @pl.when(pl.program_id(2) == 0)
    def _init():
        dk_ref[0, 0] = jnp.zeros_like(dk_ref[0, 0])
        dv_ref[0, 0] = jnp.zeros_like(dv_ref[0, 0])

    dk_ref[0, 0] += lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dv_ref[0, 0] += lax.dot_general(
        p_lo, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _bwd_kernel_chunked(*refs, scale, causal, has_seg, bq):
    """Causal-skip bwd (see _fwd_kernel_chunked). The score scratch is
    reused for dP once P is materialized; skipped chunks hold garbage in
    dP, so P*dP is masked to 0 there before the D reduction (P alone is
    exactly 0 at masked positions, but 0 * garbage could be NaN)."""
    if has_seg:
        (q_ref, k_ref, v_ref, sq_ref, skv_ref, do_ref,
         dq_ref, dk_ref, dv_ref, s_scr, acc_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref,
         dq_ref, dk_ref, dv_ref, s_scr, acc_scr) = refs
        sq_ref = skv_ref = None
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    rows = q.shape[0]
    sk = k_ref.shape[2]
    nk = sk // bq
    iq = pl.program_id(2)
    reach = iq * bq + rows - 1

    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            kc = k_ref[0, 0, c * bq:(c + 1) * bq, :]
            s_scr[:, c * bq:(c + 1) * bq] = lax.dot_general(
                q, kc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * jnp.float32(scale)

    masked = _masks(iq, bq, rows, sk, causal, sq_ref, skv_ref)
    p = _softmax(s_scr[...], masked)
    p_lo = p.astype(q.dtype)

    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            vc = v_ref[0, 0, c * bq:(c + 1) * bq, :]
            s_scr[:, c * bq:(c + 1) * bq] = lax.dot_general(
                do, vc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
    dp = s_scr[...]
    pdp = jnp.where(masked, 0.0, p * dp) if masked is not None else p * dp
    dcol = jnp.sum(pdp, axis=-1, keepdims=True)
    ds = (pdp - p * dcol) * jnp.float32(scale)

    @pl.when(iq == 0)
    def _init():
        dk_ref[0, 0] = jnp.zeros_like(dk_ref[0, 0])
        dv_ref[0, 0] = jnp.zeros_like(dv_ref[0, 0])

    acc_scr[...] = jnp.zeros_like(acc_scr)
    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            sl = slice(c * bq, (c + 1) * bq)
            dsc = ds[:, sl].astype(q.dtype)
            kc = k_ref[0, 0, sl, :]
            acc_scr[...] += lax.dot_general(
                dsc, kc, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dk_ref[0, 0, sl, :] += lax.dot_general(
                dsc, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dv_ref[0, 0, sl, :] += lax.dot_general(
                p_lo[:, sl], do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    dq_ref[0, 0] = acc_scr[...].astype(dq_ref.dtype)


def _bwd_dq_kernel(*refs, scale, causal, has_seg, bq):
    """Split backward, pass 1 (q-major): dq plus the per-row softmax
    stats (rowmax m, rowsum l) and D = rowsum(P*dP) the k-major pass
    needs to reconstruct P and dS row-exactly."""
    if has_seg:
        (q_ref, k_ref, v_ref, sq_ref, skv_ref, do_ref,
         dq_ref, m_ref, l_ref, dcol_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref,
         dq_ref, m_ref, l_ref, dcol_ref) = refs
        sq_ref = skv_ref = None
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    do = do_ref[0, 0]

    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    s = s * jnp.float32(scale)
    masked = _masks(pl.program_id(2), bq, q.shape[0], k.shape[0],
                    causal, sq_ref, skv_ref)
    p, m, tot = _softmax_stats(s, masked)

    dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    dcol = jnp.sum(p * dp, axis=-1, keepdims=True)
    ds = (p * (dp - dcol) * jnp.float32(scale)).astype(q.dtype)

    dq = lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)
    m_ref[0, 0] = m[:, 0]
    l_ref[0, 0] = tot[:, 0]
    dcol_ref[0, 0] = dcol[:, 0]


def _bwd_dq_kernel_chunked(*refs, scale, causal, has_seg, bq):
    """Causal-skip variant of the split dq pass (see _bwd_kernel_chunked
    for the skip/garbage rules) — without it the split default would pay
    the full-score causal tax the monolithic chunked kernel avoids."""
    if has_seg:
        (q_ref, k_ref, v_ref, sq_ref, skv_ref, do_ref,
         dq_ref, m_ref, l_ref, dcol_ref, s_scr, acc_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref,
         dq_ref, m_ref, l_ref, dcol_ref, s_scr, acc_scr) = refs
        sq_ref = skv_ref = None
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    rows = q.shape[0]
    sk = k_ref.shape[2]
    nk = sk // bq
    iq = pl.program_id(2)
    reach = iq * bq + rows - 1

    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            kc = k_ref[0, 0, c * bq:(c + 1) * bq, :]
            s_scr[:, c * bq:(c + 1) * bq] = lax.dot_general(
                q, kc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * jnp.float32(scale)

    masked = _masks(iq, bq, rows, sk, causal, sq_ref, skv_ref)
    p, m, tot = _softmax_stats(s_scr[...], masked)

    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            vc = v_ref[0, 0, c * bq:(c + 1) * bq, :]
            s_scr[:, c * bq:(c + 1) * bq] = lax.dot_general(
                do, vc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
    dp = s_scr[...]
    pdp = jnp.where(masked, 0.0, p * dp) if masked is not None else p * dp
    dcol = jnp.sum(pdp, axis=-1, keepdims=True)
    ds = (pdp - p * dcol) * jnp.float32(scale)

    acc_scr[...] = jnp.zeros_like(acc_scr)
    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            sl = slice(c * bq, (c + 1) * bq)
            kc = k_ref[0, 0, sl, :]
            acc_scr[...] += lax.dot_general(
                ds[:, sl].astype(q.dtype), kc, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    dq_ref[0, 0] = acc_scr[...].astype(dq_ref.dtype)
    m_ref[0, 0] = m[:, 0]
    l_ref[0, 0] = tot[:, 0]
    dcol_ref[0, 0] = dcol[:, 0]


def _bwd_dkv_kernel(*refs, scale, causal, has_seg, bq, sq):
    """Split backward, pass 2 (k-major): each (b, h, k-block) grid step
    owns its [bk, d] dk/dv blocks outright — no accumulation across grid
    steps, no block revisiting. P and dS are reconstructed from the saved
    (m, l, D) row stats; q is processed in bq-sized chunks so causal
    blocks skip the strictly-below-diagonal chunks entirely."""
    if has_seg:
        (q_ref, k_ref, v_ref, sq_ref, skv_ref, do_ref, m_ref, l_ref,
         dcol_ref, dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, m_ref, l_ref, dcol_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        sq_ref = skv_ref = None
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    bk = k.shape[0]
    ik = pl.program_id(2)
    nq = sq // bq

    dk_scr[...] = jnp.zeros_like(dk_scr)
    dv_scr[...] = jnp.zeros_like(dv_scr)

    for c in range(nq):
        def _chunk(c=c):
            qc = q_ref[0, 0, c * bq:(c + 1) * bq, :]
            doc = do_ref[0, 0, c * bq:(c + 1) * bq, :]
            m = m_ref[0, 0, c * bq:(c + 1) * bq]
            tot = l_ref[0, 0, c * bq:(c + 1) * bq]
            dcol = dcol_ref[0, 0, c * bq:(c + 1) * bq]

            s = lax.dot_general(qc, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
            s = s * jnp.float32(scale)

            seg_rows = (None if sq_ref is None
                        else sq_ref[0, c * bq:(c + 1) * bq])
            masked = _masks(c, bq, bq, bk, causal, sq_ref, skv_ref,
                            col0=ik * bk, seg_rows=seg_rows)
            p = _p_from_stats(s, m[:, None], tot[:, None], masked)

            dp = lax.dot_general(doc, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
            ds = (p * (dp - dcol[:, None]) * jnp.float32(scale)).astype(
                qc.dtype)
            p_lo = p.astype(qc.dtype)

            dk_scr[...] += lax.dot_general(
                ds, qc, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dv_scr[...] += lax.dot_general(
                p_lo, doc, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        if causal:
            # q rows < this k-block's first column contribute nothing —
            # skip the chunk (the grid is sequential scalar control flow)
            pl.when((c + 1) * bq - 1 >= ik * bk)(_chunk)
        else:
            _chunk()

    dk_ref[0, 0] = dk_scr[...]
    dv_ref[0, 0] = dv_scr[...]


def _specs(b, h, bq, sq, sk, d, has_seg):
    """(in_specs for q,k,v[,seg_q,seg_kv], qblk-spec, kvblk-spec)."""
    qspec = pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0))
    kvspec = pl.BlockSpec((1, 1, sk, d), lambda ib, ih, iq: (ib, ih, 0, 0))
    ins = [qspec, kvspec, kvspec]
    if has_seg:
        ins.append(pl.BlockSpec((1, bq), lambda ib, ih, iq: (ib, iq)))
        ins.append(pl.BlockSpec((1, sk), lambda ib, ih, iq: (ib, 0)))
    return ins, qspec, kvspec


def _seg_ops(segment_ids):
    if segment_ids is None:
        return []
    seg_q, seg_kv = segment_ids
    return [seg_q.astype(jnp.int32), seg_kv.astype(jnp.int32)]


def _chunked(causal, bq, sq, sk):
    """Causal-skip applies when chunk boundaries are lane-aligned and
    there are >= 2 q blocks (a single block has nothing to skip)."""
    return causal and bq % 128 == 0 and sk % bq == 0 and sq >= 2 * bq


def _pick_bq(sq, sk, block_q):
    bq = _q_block(sq, sk)
    if block_q is not None:
        if sq % block_q or block_q > bq:
            raise ValueError(
                f"block_q={block_q} must divide sq={sq} and fit the VMEM "
                f"budget (max {bq})")
        bq = block_q
    return bq


# Backward structure: "monolithic" = one q-major kernel accumulating
# dk/dv across the sequential grid; "split" = a q-major dq pass (emitting
# the (m, l, D) row stats) + a k-major dk/dv pass where each k-block is
# computed exactly once. Measured knob (PERF.md §3/§7): the winner on the
# fwd+d(q,k,v) protocol becomes the default — monolithic holds the seat
# until the split A/B lands (split is interpret-parity-proven but its
# TPU timing is queued on the relay; profile_attention.py carries the
# decision rows).
BWD_IMPL = "monolithic"


def set_bwd_impl(impl):
    """Set the process-wide backward-structure *preference*. Shapes that
    fail ``_split_ok`` fall back to monolithic silently (a model may mix
    eligible and ineligible layers); a per-call ``bwd_impl=`` is a strict
    demand and raises instead — benchmark rows use the per-call form so
    their labels stay truthful."""
    global BWD_IMPL
    if impl not in ("monolithic", "split"):
        raise ValueError(f"unknown rows bwd impl {impl!r}")
    BWD_IMPL = impl


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 6, 7, 8))
def fused_attention_rows(q, k, v, causal, sm_scale, segment_ids=None,
                         interpret=False, block_q=None, bwd_impl=None):
    """VMEM-row fused attention. q: [b, h, sq, d]; k, v: [b, h, sk, d];
    segment_ids: None or (seg_q [b, sq], seg_kv [b, sk]). Check
    ``supported(sq, sk, d)`` first. ``interpret=True`` for CPU tests.
    ``block_q`` overrides the auto q-block (benchmark sweeps);
    ``bwd_impl`` overrides the module-level ``BWD_IMPL``."""
    if bwd_impl is not None and bwd_impl not in ("monolithic", "split"):
        raise ValueError(f"unknown rows bwd impl {bwd_impl!r}")
    return _fwd(q, k, v, causal, sm_scale, segment_ids, interpret,
                block_q)[0]


def _fwd(q, k, v, causal, sm_scale, segment_ids, interpret, block_q=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if not supported(sq, sk, d):
        raise ValueError(f"attention_pallas: unsupported {q.shape}x{k.shape}")
    bq = _pick_bq(sq, sk, block_q)
    has_seg = segment_ids is not None
    ins, qspec, _ = _specs(b, h, bq, sq, sk, d, has_seg)
    kern, scratch = _fwd_kernel, []
    if _chunked(causal, bq, sq, sk):
        kern = _fwd_kernel_chunked
        scratch = [pltpu.VMEM((bq, sk), jnp.float32),
                   pltpu.VMEM((bq, d), jnp.float32)]
    o = pl.pallas_call(
        functools.partial(kern, scale=float(sm_scale), causal=causal,
                          has_seg=has_seg, bq=bq),
        grid=(b, h, sq // bq),
        in_specs=ins,
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v, *_seg_ops(segment_ids))
    return o, (q, k, v, segment_ids)


def _fwd_rule(q, k, v, causal, sm_scale, segment_ids, interpret,
              block_q=None, bwd_impl=None):
    return _fwd(q, k, v, causal, sm_scale, segment_ids, interpret, block_q)


def _bwd_monolithic(causal, sm_scale, interpret, block_q, res, g):
    q, k, v, segment_ids = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = _pick_bq(sq, sk, block_q)
    has_seg = segment_ids is not None
    ins, qspec, kvspec = _specs(b, h, bq, sq, sk, d, has_seg)
    kern, scratch = _bwd_kernel, []
    if _chunked(causal, bq, sq, sk):
        kern = _bwd_kernel_chunked
        scratch = [pltpu.VMEM((bq, sk), jnp.float32),
                   pltpu.VMEM((bq, d), jnp.float32)]
    dq, dk, dv = pl.pallas_call(
        functools.partial(kern, scale=float(sm_scale), causal=causal,
                          has_seg=has_seg, bq=bq),
        grid=(b, h, sq // bq),
        in_specs=ins + [qspec],
        out_specs=(qspec, kvspec, kvspec),
        out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct(k.shape, jnp.float32),
                   jax.ShapeDtypeStruct(v.shape, jnp.float32)),
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v, *_seg_ops(segment_ids), g)
    return (dq, dk.astype(k.dtype), dv.astype(v.dtype), None)


def _bwd_split(causal, sm_scale, interpret, block_q, res, g):
    q, k, v, segment_ids = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = _pick_bq(sq, sk, block_q)
    has_seg = segment_ids is not None
    ins, qspec, kvspec = _specs(b, h, bq, sq, sk, d, has_seg)
    vecspec = pl.BlockSpec((1, 1, bq), lambda ib, ih, iq: (ib, ih, iq))
    vecshape = jax.ShapeDtypeStruct((b, h, sq), jnp.float32)

    dq_kern, dq_scratch = _bwd_dq_kernel, []
    if _chunked(causal, bq, sq, sk):
        dq_kern = _bwd_dq_kernel_chunked
        dq_scratch = [pltpu.VMEM((bq, sk), jnp.float32),
                      pltpu.VMEM((bq, d), jnp.float32)]
    dq, m, l, dcol = pl.pallas_call(
        functools.partial(dq_kern, scale=float(sm_scale),
                          causal=causal, has_seg=has_seg, bq=bq),
        grid=(b, h, sq // bq),
        in_specs=ins + [qspec],
        out_specs=(qspec, vecspec, vecspec, vecspec),
        out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),
                   vecshape, vecshape, vecshape),
        scratch_shapes=dq_scratch,
        interpret=interpret,
    )(q, k, v, *_seg_ops(segment_ids), g)

    bk = bq  # k-blocks reuse the VMEM-validated row block size
    fullq = pl.BlockSpec((1, 1, sq, d), lambda ib, ih, ik: (ib, ih, 0, 0))
    kvblk = pl.BlockSpec((1, 1, bk, d), lambda ib, ih, ik: (ib, ih, ik, 0))
    fullvec = pl.BlockSpec((1, 1, sq), lambda ib, ih, ik: (ib, ih, 0))
    dkv_ins = [fullq, kvblk, kvblk]
    if has_seg:
        dkv_ins.append(pl.BlockSpec((1, sq), lambda ib, ih, ik: (ib, 0)))
        dkv_ins.append(pl.BlockSpec((1, bk), lambda ib, ih, ik: (ib, ik)))
    dkv_ins += [fullq, fullvec, fullvec, fullvec]

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=float(sm_scale),
                          causal=causal, has_seg=has_seg, bq=bq, sq=sq),
        grid=(b, h, sk // bk),
        in_specs=dkv_ins,
        out_specs=(kvblk, kvblk),
        out_shape=(jax.ShapeDtypeStruct(k.shape, jnp.float32),
                   jax.ShapeDtypeStruct(v.shape, jnp.float32)),
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, *_seg_ops(segment_ids), g, m, l, dcol)
    return (dq, dk.astype(k.dtype), dv.astype(v.dtype), None)


def _split_ok(sq, sk, d, bq, itemsize):
    """VMEM eligibility of the split k-major pass: it keeps the full
    [sq, d] q and dO resident per grid step (the monolithic backward
    streams q instead), holds 3 [bq, bq] fp32 chunk arrays + 2 [bq, d]
    accumulators + 3 [sq] stat vectors, and unrolls sq/bq chunks."""
    # bq % 128: the stat vectors are emitted as [1, 1, bq] minor-dim
    # blocks, which Mosaic requires lane-aligned
    if sk % bq or bq % 128 or sq // bq > 32:
        return False
    resident = (2 * sq * d * itemsize      # q, dO
                + 3 * bq * bq * 4          # s/p, dp, ds
                + 2 * bq * d * 4           # dk/dv accumulators
                + 3 * sq * 4)              # m, l, D
    return resident <= _VMEM_BUDGET


def _bwd_rule(causal, sm_scale, interpret, block_q, bwd_impl, res, g):
    if bwd_impl is not None and bwd_impl not in ("monolithic", "split"):
        raise ValueError(f"unknown rows bwd impl {bwd_impl!r}")
    impl = bwd_impl or BWD_IMPL
    q, k, v, _ = res
    sq, sk = q.shape[2], k.shape[2]
    bq = _pick_bq(sq, sk, block_q)
    ok = _split_ok(sq, sk, q.shape[3], bq, q.dtype.itemsize)
    if bwd_impl == "split" and not ok:
        # an explicit request must be honored or error — silently running
        # monolithic would mislabel A/B benchmark rows
        raise ValueError(
            f"split bwd ineligible for {q.shape}x{k.shape} (bq={bq})")
    if impl == "split" and ok:
        return _bwd_split(causal, sm_scale, interpret, block_q, res, g)
    return _bwd_monolithic(causal, sm_scale, interpret, block_q, res, g)


fused_attention_rows.defvjp(_fwd_rule, _bwd_rule)
