"""Pallas TPU fused attention, VMEM-resident rows (fwd + bwd).

Self-authored alternative to the bundled multi-pass flash kernel for the
sequence lengths the reference's fused attention actually targets
(contrib/csrc/fmha supports seq <= 512; fast_multihead_attn seq ~64-1024):
at those lengths a whole [block_q, sk] score row fits in VMEM, so each
(batch, head, q-block) grid step computes scores, the exact fp32 softmax
over the FULL key row, and the output matmul in one kernel — no online
max/sum rescaling passes, no [s, s] tensor in HBM.

Backward is one kernel over the same grid, fully self-contained: it
recomputes S and P from (q, k, v) (no saved LSE — the softmax residual is
reconstructed row-exactly), forms dP = dO V^T, uses the identity
D = rowsum(dO * O) = rowsum(P * dP) to avoid needing O, then
dS = P * (dP - D) * scale, dQ = dS K, and accumulates dK += dS^T Q,
dV += P^T dO across q-blocks. The accumulation is safe because the TPU
grid executes sequentially and the dk/dv output blocks stay VMEM-resident
while the innermost (q) grid index varies; they are written back once per
(b, h). dk/dv accumulate in fp32 regardless of the input dtype.

Masking matches ops.attention._dense_attention exactly: causal triangle
(generated from iota, no mask operand), optional segment ids (packed
varlen batches), masked positions excluded from the softmax, fully-masked
rows → 0.

Trade-off vs flash: with causal masking the kernel still computes the
full [block_q, sk] score block (the masked half is wasted MXU work), so
it targets moderate sequence lengths where the single-pass structure wins
more than the causal skip would save. benchmarks/profile_attention.py
measures the crossover; ops.attention routes to this kernel via its
``impl="rows"`` knob / ``set_default_impl`` (the measured winner is the
default there).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


_VMEM_BUDGET = 10 * 1024 * 1024  # fp32 [bq, sk] working-set bytes
_BWD_ARRAYS = 4  # S/P, dP, dS live + headroom (bwd is the tight pass)


def _q_block(sq, sk):
    """Largest power-of-two q block dividing sq whose bwd working set
    ([bq, sk] fp32 x _BWD_ARRAYS) fits the budget (0 → unsupported)."""
    from apex_tpu.ops.attention import _block

    cap = max(1, _VMEM_BUDGET // (4 * sk * _BWD_ARRAYS))
    b = _block(sq, cap)
    return b if b >= 8 else 0


def supported(sq, sk, d):
    """Whether the VMEM-row kernel handles [.., sq, d] x [.., sk, d].
    sk must be lane-aligned; d bounded so the [sk, d] K/V operands and
    fp32 dk/dv accumulators stay small next to the score rows."""
    return sk % 128 == 0 and d <= 256 and _q_block(sq, sk) != 0


def _masks(iq, bq, rows, sk, causal, seg_q, seg_kv):
    """Boolean masked-out matrix for one [rows, sk] score block (True =
    excluded), or None when unmasked. seg_* are refs or None."""
    masked = None
    if causal:
        row = iq * bq + lax.broadcasted_iota(jnp.int32, (rows, sk), 0)
        col = lax.broadcasted_iota(jnp.int32, (rows, sk), 1)
        masked = col > row
    if seg_q is not None:
        sq_row = seg_q[0, :]
        skv_row = seg_kv[0, :]
        diff = sq_row[:, None] != skv_row[None, :]
        masked = diff if masked is None else masked | diff
    return masked


def _softmax(s, masked):
    """Exact fp32 softmax over the full key row with dense-reference
    semantics (masked excluded, fully-masked rows -> 0)."""
    if masked is not None:
        s = jnp.where(masked, jnp.finfo(jnp.float32).min, s)
    e = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    if masked is not None:
        e = jnp.where(masked, 0.0, e)
    tot = jnp.sum(e, axis=-1, keepdims=True)
    return jnp.where(tot > 0, e / jnp.where(tot > 0, tot, 1.0), 0.0)


def _fwd_kernel(*refs, scale, causal, has_seg, bq):
    if has_seg:
        q_ref, k_ref, v_ref, sq_ref, skv_ref, o_ref = refs
    else:
        (q_ref, k_ref, v_ref, o_ref), sq_ref, skv_ref = refs, None, None
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    s = s * jnp.float32(scale)
    masked = _masks(pl.program_id(2), bq, q.shape[0], k.shape[0],
                    causal, sq_ref, skv_ref)
    p = _softmax(s, masked).astype(v.dtype)
    o = lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
    o_ref[0, 0] = o.astype(o_ref.dtype)


def _fwd_kernel_chunked(*refs, scale, causal, has_seg, bq):
    """Causal-skip fwd: keys are processed in bq-sized chunks and a chunk
    whose columns are all beyond this q-block's causal reach is never
    computed (the guarded branch genuinely skips — the TPU grid is
    sequential scalar control flow). Skipped chunks leave garbage in the
    score scratch; the softmax's causal `where` overwrites exactly those
    positions, so the garbage is never observed."""
    if has_seg:
        q_ref, k_ref, v_ref, sq_ref, skv_ref, o_ref, s_scr, o_scr = refs
    else:
        q_ref, k_ref, v_ref, o_ref, s_scr, o_scr = refs
        sq_ref = skv_ref = None
    q = q_ref[0, 0]
    rows = q.shape[0]
    sk = k_ref.shape[2]
    nk = sk // bq
    iq = pl.program_id(2)
    reach = iq * bq + rows - 1  # last (absolute) row of this q block

    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            kc = k_ref[0, 0, c * bq:(c + 1) * bq, :]
            s_scr[:, c * bq:(c + 1) * bq] = lax.dot_general(
                q, kc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * jnp.float32(scale)

    masked = _masks(iq, bq, rows, sk, causal, sq_ref, skv_ref)
    p = _softmax(s_scr[...], masked).astype(v_ref.dtype)

    o_scr[...] = jnp.zeros_like(o_scr)
    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            vc = v_ref[0, 0, c * bq:(c + 1) * bq, :]
            o_scr[...] += lax.dot_general(
                p[:, c * bq:(c + 1) * bq], vc, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    o_ref[0, 0] = o_scr[...].astype(o_ref.dtype)


def _bwd_kernel(*refs, scale, causal, has_seg, bq):
    if has_seg:
        (q_ref, k_ref, v_ref, sq_ref, skv_ref, do_ref,
         dq_ref, dk_ref, dv_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, dq_ref, dk_ref, dv_ref) = refs
        sq_ref = skv_ref = None
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    do = do_ref[0, 0]

    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    s = s * jnp.float32(scale)
    masked = _masks(pl.program_id(2), bq, q.shape[0], k.shape[0],
                    causal, sq_ref, skv_ref)
    p = _softmax(s, masked)
    p_lo = p.astype(q.dtype)

    # dP in fp32; D = rowsum(P * dP) == rowsum(dO * O) so O is not needed
    dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    dcol = jnp.sum(p * dp, axis=-1, keepdims=True)
    ds = (p * (dp - dcol) * jnp.float32(scale)).astype(q.dtype)

    dq = lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)

    # dk/dv accumulate across the (innermost, sequential) q grid axis;
    # their block index is constant in iq so the block stays resident
    @pl.when(pl.program_id(2) == 0)
    def _init():
        dk_ref[0, 0] = jnp.zeros_like(dk_ref[0, 0])
        dv_ref[0, 0] = jnp.zeros_like(dv_ref[0, 0])

    dk_ref[0, 0] += lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dv_ref[0, 0] += lax.dot_general(
        p_lo, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _bwd_kernel_chunked(*refs, scale, causal, has_seg, bq):
    """Causal-skip bwd (see _fwd_kernel_chunked). The score scratch is
    reused for dP once P is materialized; skipped chunks hold garbage in
    dP, so P*dP is masked to 0 there before the D reduction (P alone is
    exactly 0 at masked positions, but 0 * garbage could be NaN)."""
    if has_seg:
        (q_ref, k_ref, v_ref, sq_ref, skv_ref, do_ref,
         dq_ref, dk_ref, dv_ref, s_scr, acc_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref,
         dq_ref, dk_ref, dv_ref, s_scr, acc_scr) = refs
        sq_ref = skv_ref = None
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    rows = q.shape[0]
    sk = k_ref.shape[2]
    nk = sk // bq
    iq = pl.program_id(2)
    reach = iq * bq + rows - 1

    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            kc = k_ref[0, 0, c * bq:(c + 1) * bq, :]
            s_scr[:, c * bq:(c + 1) * bq] = lax.dot_general(
                q, kc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * jnp.float32(scale)

    masked = _masks(iq, bq, rows, sk, causal, sq_ref, skv_ref)
    p = _softmax(s_scr[...], masked)
    p_lo = p.astype(q.dtype)

    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            vc = v_ref[0, 0, c * bq:(c + 1) * bq, :]
            s_scr[:, c * bq:(c + 1) * bq] = lax.dot_general(
                do, vc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
    dp = s_scr[...]
    pdp = jnp.where(masked, 0.0, p * dp) if masked is not None else p * dp
    dcol = jnp.sum(pdp, axis=-1, keepdims=True)
    ds = (pdp - p * dcol) * jnp.float32(scale)

    @pl.when(iq == 0)
    def _init():
        dk_ref[0, 0] = jnp.zeros_like(dk_ref[0, 0])
        dv_ref[0, 0] = jnp.zeros_like(dv_ref[0, 0])

    acc_scr[...] = jnp.zeros_like(acc_scr)
    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            sl = slice(c * bq, (c + 1) * bq)
            dsc = ds[:, sl].astype(q.dtype)
            kc = k_ref[0, 0, sl, :]
            acc_scr[...] += lax.dot_general(
                dsc, kc, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dk_ref[0, 0, sl, :] += lax.dot_general(
                dsc, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dv_ref[0, 0, sl, :] += lax.dot_general(
                p_lo[:, sl], do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    dq_ref[0, 0] = acc_scr[...].astype(dq_ref.dtype)


def _specs(b, h, bq, sq, sk, d, has_seg):
    """(in_specs for q,k,v[,seg_q,seg_kv], qblk-spec, kvblk-spec)."""
    qspec = pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0))
    kvspec = pl.BlockSpec((1, 1, sk, d), lambda ib, ih, iq: (ib, ih, 0, 0))
    ins = [qspec, kvspec, kvspec]
    if has_seg:
        ins.append(pl.BlockSpec((1, bq), lambda ib, ih, iq: (ib, iq)))
        ins.append(pl.BlockSpec((1, sk), lambda ib, ih, iq: (ib, 0)))
    return ins, qspec, kvspec


def _seg_ops(segment_ids):
    if segment_ids is None:
        return []
    seg_q, seg_kv = segment_ids
    return [seg_q.astype(jnp.int32), seg_kv.astype(jnp.int32)]


def _chunked(causal, bq, sq, sk):
    """Causal-skip applies when chunk boundaries are lane-aligned and
    there are >= 2 q blocks (a single block has nothing to skip)."""
    return causal and bq % 128 == 0 and sk % bq == 0 and sq >= 2 * bq


def _pick_bq(sq, sk, block_q):
    bq = _q_block(sq, sk)
    if block_q is not None:
        if sq % block_q or block_q > bq:
            raise ValueError(
                f"block_q={block_q} must divide sq={sq} and fit the VMEM "
                f"budget (max {bq})")
        bq = block_q
    return bq


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 6, 7))
def fused_attention_rows(q, k, v, causal, sm_scale, segment_ids=None,
                         interpret=False, block_q=None):
    """VMEM-row fused attention. q: [b, h, sq, d]; k, v: [b, h, sk, d];
    segment_ids: None or (seg_q [b, sq], seg_kv [b, sk]). Check
    ``supported(sq, sk, d)`` first. ``interpret=True`` for CPU tests.
    ``block_q`` overrides the auto q-block (benchmark sweeps)."""
    return _fwd(q, k, v, causal, sm_scale, segment_ids, interpret,
                block_q)[0]


def _fwd(q, k, v, causal, sm_scale, segment_ids, interpret, block_q=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if not supported(sq, sk, d):
        raise ValueError(f"attention_pallas: unsupported {q.shape}x{k.shape}")
    bq = _pick_bq(sq, sk, block_q)
    has_seg = segment_ids is not None
    ins, qspec, _ = _specs(b, h, bq, sq, sk, d, has_seg)
    kern, scratch = _fwd_kernel, []
    if _chunked(causal, bq, sq, sk):
        kern = _fwd_kernel_chunked
        scratch = [pltpu.VMEM((bq, sk), jnp.float32),
                   pltpu.VMEM((bq, d), jnp.float32)]
    o = pl.pallas_call(
        functools.partial(kern, scale=float(sm_scale), causal=causal,
                          has_seg=has_seg, bq=bq),
        grid=(b, h, sq // bq),
        in_specs=ins,
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v, *_seg_ops(segment_ids))
    return o, (q, k, v, segment_ids)


def _fwd_rule(q, k, v, causal, sm_scale, segment_ids, interpret,
              block_q=None):
    return _fwd(q, k, v, causal, sm_scale, segment_ids, interpret, block_q)


def _bwd_rule(causal, sm_scale, interpret, block_q, res, g):
    q, k, v, segment_ids = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = _pick_bq(sq, sk, block_q)
    has_seg = segment_ids is not None
    ins, qspec, kvspec = _specs(b, h, bq, sq, sk, d, has_seg)
    kern, scratch = _bwd_kernel, []
    if _chunked(causal, bq, sq, sk):
        kern = _bwd_kernel_chunked
        scratch = [pltpu.VMEM((bq, sk), jnp.float32),
                   pltpu.VMEM((bq, d), jnp.float32)]
    dq, dk, dv = pl.pallas_call(
        functools.partial(kern, scale=float(sm_scale), causal=causal,
                          has_seg=has_seg, bq=bq),
        grid=(b, h, sq // bq),
        in_specs=ins + [qspec],
        out_specs=(qspec, kvspec, kvspec),
        out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct(k.shape, jnp.float32),
                   jax.ShapeDtypeStruct(v.shape, jnp.float32)),
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v, *_seg_ops(segment_ids), g)
    return (dq, dk.astype(k.dtype), dv.astype(v.dtype), None)


fused_attention_rows.defvjp(_fwd_rule, _bwd_rule)
