"""Pallas TPU fused attention, VMEM-resident rows (fwd + bwd).

Self-authored alternative to the bundled multi-pass flash kernel for the
sequence lengths the reference's fused attention actually targets
(contrib/csrc/fmha supports seq <= 512; fast_multihead_attn seq ~64-1024):
at those lengths a whole [block_q, sk] score row fits in VMEM, so each
(batch, head, q-block) grid step computes scores, the exact fp32 softmax
over the FULL key row, and the output matmul in one kernel — no online
max/sum rescaling passes, no [s, s] tensor in HBM.

Backward comes in two structures behind the measured ``BWD_IMPL`` knob
(monolithic is the device-measured training-protocol winner and the
default — see the knob's comment and PERF.md §10):

* ``"split"``: a q-major dq pass that recomputes S and P from
  (q, k, v), forms dP = dO V^T, uses D = rowsum(dO * O) = rowsum(P * dP)
  to avoid needing O, writes dQ = dS K — and emits the per-row softmax
  stats (m, l, D) as [b, h, sq, 1] fp32 byproducts (the trailing 1 keeps
  the block's last dim equal to the array dim, satisfying Mosaic's
  last-two-dims tiling rule); then a k-major dk/dv
  pass where each (b, h, k-block) grid step reconstructs P row-exactly
  from those stats and owns its [bk, d] dk/dv outputs outright (no
  accumulation across grid steps). Eligibility is VMEM-gated
  (``_split_ok``): the k-major pass keeps the full [sq, d] q and dO
  resident, so very long sq falls back to monolithic.
* ``"monolithic"``: one self-contained q-major kernel (no saved stats)
  that additionally accumulates dK += dS^T Q, dV += P^T dO across
  q-blocks — safe because the TPU grid executes sequentially and the
  dk/dv blocks stay VMEM-resident while the innermost (q) index varies.

dk/dv accumulate in fp32 regardless of the input dtype in both.

Masking matches ops.attention._dense_attention exactly: causal triangle
(generated from iota, no mask operand), optional segment ids (packed
varlen batches), masked positions excluded from the softmax, fully-masked
rows → 0.

Trade-off vs flash: with causal masking the kernel still computes the
full [block_q, sk] score block (the masked half is wasted MXU work), so
it targets moderate sequence lengths where the single-pass structure wins
more than the causal skip would save. benchmarks/profile_attention.py
measures the crossover; ops.attention routes to this kernel via its
``impl="rows"`` knob / ``set_default_impl`` (the measured winner is the
default there).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.dispatch import tiles

# budget/working-set constants live in the shared tile model
# (apex_tpu/dispatch/tiles.py) — the sweeper, the label checker and
# this lowering judge tiles with the same arithmetic
_VMEM_BUDGET = tiles.ATTN_VMEM_BUDGET
_BWD_ARRAYS = tiles.ATTN_BWD_ARRAYS
# dropout keeps two extra [bq, sk] fp32 arrays live in the backward (the
# keep-scale tile and the dropped probs), so its q block is sized for a
# 6-array working set
_DROP_BWD_ARRAYS = tiles.ATTN_DROP_BWD_ARRAYS


def _q_block(sq, sk, n_arrays=_BWD_ARRAYS):
    """Largest power-of-two q block dividing sq whose bwd working set
    ([bq, sk] fp32 x n_arrays) fits the budget (0 → unsupported)."""
    return tiles.attn_q_block(sq, sk, n_arrays, budget=_VMEM_BUDGET)


# Process-wide q-block preference (tri-state; falls back per shape —
# only per-call tile knobs raise on an illegal tile)
_BLOCK_Q = None


def set_block_q(value):
    """Pin the process-wide q-block preference (int), or un-pin with
    None (table params / the heuristic apply again). Shapes the pinned
    tile can't block fall back to the heuristic silently."""
    global _BLOCK_Q
    tiles.check_setter_value(value, "block_q")
    _BLOCK_Q = value


def _env_block_q():
    return tiles.env_int("APEX_ATTN_BLOCK_Q")


def _pref_get(tile_pref, name):
    """Read one key out of a ``tile_pref`` tuple (the hashable
    ``((name, value), ...)`` form table params travel in — custom_vjp
    nondiff args must hash)."""
    if not tile_pref:
        return None
    return dict(tile_pref).get(name)


def supported(sq, sk, d, dropout=False):
    """Whether the VMEM-row kernel handles [.., sq, d] x [.., sk, d].
    sk must be lane-aligned; d bounded so the [sk, d] K/V operands and
    fp32 dk/dv accumulators stay small next to the score rows. Pass
    ``dropout=True`` when a dropout_p > 0 call is intended — the dropout
    backward's larger working set shrinks the viable q block and can
    push a shape that fits the plain kernel out of budget."""
    n_arrays = _DROP_BWD_ARRAYS if dropout else _BWD_ARRAYS
    return sk % 128 == 0 and d <= 256 and _q_block(sq, sk, n_arrays) != 0


def _masks(iq, bq, rows, sk, causal, seg_q, seg_kv, col0=0,
           seg_rows=None):
    """Boolean masked-out matrix for one [rows, sk] score block (True =
    excluded), or None when unmasked. seg_* are refs or None. ``col0``
    offsets the absolute column index (k-major blocks); ``seg_rows``
    overrides the row-id slice taken from seg_q (q chunks)."""
    masked = None
    if causal:
        row = iq * bq + lax.broadcasted_iota(jnp.int32, (rows, sk), 0)
        col = col0 + lax.broadcasted_iota(jnp.int32, (rows, sk), 1)
        masked = col > row
    if seg_q is not None:
        # seg_q is [1, bq|sq, 1] (sublane-major), seg_kv [1, 1, sk|bk]
        # (lane-major) — block sizes depend on the call site (q-major
        # passes tile seg_q; the k-major pass tiles seg_kv instead and
        # overrides rows via seg_rows); each layout matches the axis it
        # broadcasts along below
        sq_row = seg_q[0, :, 0] if seg_rows is None else seg_rows
        skv_row = seg_kv[0, 0, :]
        diff = sq_row[:, None] != skv_row[None, :]
        masked = diff if masked is None else masked | diff
    return masked


def _softmax_stats(s, masked):
    """Exact fp32 softmax over the full key row with dense-reference
    semantics (masked excluded, fully-masked rows -> 0). Returns
    (p, rowmax m, rowsum l) — m/l let a k-major pass reconstruct p
    row-exactly without the full row."""
    if masked is not None:
        s = jnp.where(masked, jnp.finfo(jnp.float32).min, s)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    if masked is not None:
        e = jnp.where(masked, 0.0, e)
    tot = jnp.sum(e, axis=-1, keepdims=True)
    p = jnp.where(tot > 0, e / jnp.where(tot > 0, tot, 1.0), 0.0)
    return p, m, tot


def _softmax(s, masked):
    return _softmax_stats(s, masked)[0]


def _p_from_stats(s, m, tot, masked):
    """Row-exact P reconstruction from saved (rowmax m, rowsum tot)
    [rows, 1] stats — same exclusion and zero-row semantics as
    ``_softmax_stats`` (whose outputs m/tot must come from the same
    mask)."""
    # Fully-masked rows save m = finfo.min, so an unclamped s - m
    # overflows to +inf in the k-major pass before the where() discards
    # it. s - m <= 0 holds for every live row (m is that row's max), so
    # clamping at 0 is exact — and keeps e finite for any future
    # arithmetic inserted before the mask (e.g. a fused scale).
    e = jnp.exp(jnp.minimum(s - m, 0.0))
    if masked is not None:
        e = jnp.where(masked, 0.0, e)
    return jnp.where(tot > 0, e / jnp.where(tot > 0, tot, 1.0), 0.0)


# ---------------------------------------------------------------------------
# attention dropout: counter-based PRNG, replayed exactly in backward
# ---------------------------------------------------------------------------
#
# The mask is a pure chained hash of the GLOBAL element coordinate
# (b, h, row, col) and the step seed — one murmur3 fmix32 avalanche per
# level, never a flat multiplied counter (which would wrap uint32 at
# large b·h·sq·sk). Tile-layout independent by construction: the
# backward pass (any block size, any q-major/k-major order) regenerates
# bit-identical keep decisions without storing the [sq, sk] mask in HBM
# — the same replay-from-offsets design as fmhalib's Philox states
# (reference apex/contrib/fmha/fmha.py:33-61 saves rng_state instead).
# Plain jnp uint32 ops so it lowers on Mosaic AND in interpret mode
# (pltpu.prng_* has no CPU interpret rule), and tests can rebuild the
# dense mask with the very same function.

def _fmix32(x):
    """murmur3 32-bit finalizer: full avalanche on distinct inputs."""
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> jnp.uint32(13))
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> jnp.uint32(16))
    return x


def _dropout_mscale(seed, ib, ih, row0, rows, sk, p, n_heads, col0=0):
    """fp32 [rows, sk] inverted-dropout scale (keep/(1-p), drop→0) for
    the score block whose global rows start at ``row0`` and columns at
    ``col0`` (ring-attention blocks pass a nonzero col0 so every rank
    regenerates the same global mask). ``seed`` is a traced
    uint32/int32 scalar; ``ib``/``ih`` the batch/head indices.

    The hash is CHAINED, not a flat element counter: seed → per-(b, h)
    key → per-row key → per-element bits, one fmix32 avalanche per
    level. A flat ``((b·H + h)·sq + row)·sk + col`` counter silently
    wraps uint32 once b·h·sq·sk > 2^32 (shapes the supported() gate
    admits), correlating far-apart elements; the chain never multiplies
    coordinates, so no level can overflow.

    Every index input is coerced to uint32 BEFORE any arithmetic: a
    traced int32 (``pl.program_id``) in the chain silently demotes the
    whole hash to int32, and the ``bits >= thresh`` compare then wraps
    thresh negative — an always-keep mask that drops nothing.
    """
    u32 = lambda x: jnp.asarray(x).astype(jnp.uint32)
    row = u32(row0) + lax.broadcasted_iota(jnp.uint32, (rows, 1), 0)
    col = u32(col0) + lax.broadcasted_iota(jnp.uint32, (rows, sk), 1)
    s = _fmix32(jnp.uint32(0x9E3779B9) ^ u32(seed))
    s_bh = _fmix32(s ^ (u32(ib) * jnp.uint32(n_heads) + u32(ih)))
    rowkey = _fmix32(s_bh ^ row)            # [rows, 1]
    bits = _fmix32(rowkey ^ col)            # [rows, sk]
    assert bits.dtype == jnp.uint32, bits.dtype
    thresh = jnp.uint32(min(max(p, 0.0), 1.0) * 4294967296.0)
    keep = bits >= thresh
    return jnp.where(keep, jnp.float32(1.0 / (1.0 - p)), jnp.float32(0.0))


def _fwd_kernel(*refs, scale, causal, has_seg, bq, dropout_p=0.0,
                n_heads=1):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    i = 3
    sq_ref = skv_ref = seed_ref = None
    if has_seg:
        sq_ref, skv_ref = refs[i:i + 2]
        i += 2
    if dropout_p > 0.0:
        seed_ref = refs[i]
        i += 1
    o_ref = refs[i]
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    s = s * jnp.float32(scale)
    masked = _masks(pl.program_id(2), bq, q.shape[0], k.shape[0],
                    causal, sq_ref, skv_ref)
    p = _softmax(s, masked)
    if dropout_p > 0.0:
        p = p * _dropout_mscale(
            seed_ref[0, 0], pl.program_id(0), pl.program_id(1),
            pl.program_id(2) * bq, q.shape[0], k.shape[0], dropout_p,
            n_heads)
    o = lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
    o_ref[0, 0] = o.astype(o_ref.dtype)


def _fwd_kernel_chunked(*refs, scale, causal, has_seg, bq):
    """Causal-skip fwd: keys are processed in bq-sized chunks and a chunk
    whose columns are all beyond this q-block's causal reach is never
    computed (the guarded branch genuinely skips — the TPU grid is
    sequential scalar control flow). Skipped chunks leave garbage in the
    score scratch; the softmax's causal `where` overwrites exactly those
    positions, so the garbage is never observed."""
    if has_seg:
        q_ref, k_ref, v_ref, sq_ref, skv_ref, o_ref, s_scr, o_scr = refs
    else:
        q_ref, k_ref, v_ref, o_ref, s_scr, o_scr = refs
        sq_ref = skv_ref = None
    q = q_ref[0, 0]
    rows = q.shape[0]
    sk = k_ref.shape[2]
    nk = sk // bq
    iq = pl.program_id(2)
    reach = iq * bq + rows - 1  # last (absolute) row of this q block

    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            kc = k_ref[0, 0, c * bq:(c + 1) * bq, :]
            s_scr[:, c * bq:(c + 1) * bq] = lax.dot_general(
                q, kc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * jnp.float32(scale)

    masked = _masks(iq, bq, rows, sk, causal, sq_ref, skv_ref)
    p = _softmax(s_scr[...], masked).astype(v_ref.dtype)

    o_scr[...] = jnp.zeros_like(o_scr)
    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            vc = v_ref[0, 0, c * bq:(c + 1) * bq, :]
            o_scr[...] += lax.dot_general(
                p[:, c * bq:(c + 1) * bq], vc, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    o_ref[0, 0] = o_scr[...].astype(o_ref.dtype)


def _bwd_kernel(*refs, scale, causal, has_seg, bq, dropout_p=0.0,
                n_heads=1):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    i = 3
    sq_ref = skv_ref = seed_ref = None
    if has_seg:
        sq_ref, skv_ref = refs[i:i + 2]
        i += 2
    if dropout_p > 0.0:
        seed_ref = refs[i]
        i += 1
    do_ref, dq_ref, dk_ref, dv_ref = refs[i:i + 4]
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    do = do_ref[0, 0]

    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    s = s * jnp.float32(scale)
    masked = _masks(pl.program_id(2), bq, q.shape[0], k.shape[0],
                    causal, sq_ref, skv_ref)
    p = _softmax(s, masked)

    # dP in fp32; D = rowsum(P * dP) == rowsum(dO * O) so O is not needed
    dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    if dropout_p > 0.0:
        # replay the fwd keep mask from the counter hash: out = (P∘m)V,
        # so dV uses the dropped probs, dL/dP = m∘(dO V^T), and the
        # softmax-bwd row term rowsum(P ∘ dL/dP) == rowsum(Pd ∘ dP_raw)
        mscale = _dropout_mscale(
            seed_ref[0, 0], pl.program_id(0), pl.program_id(1),
            pl.program_id(2) * bq, q.shape[0], k.shape[0], dropout_p,
            n_heads)
        pd = p * mscale
        p_lo = pd.astype(q.dtype)          # feeds dV
        dcol = jnp.sum(pd * dp, axis=-1, keepdims=True)
        dp = dp * mscale
    else:
        p_lo = p.astype(q.dtype)
        dcol = jnp.sum(p * dp, axis=-1, keepdims=True)
    ds = (p * (dp - dcol) * jnp.float32(scale)).astype(q.dtype)

    dq = lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)

    # dk/dv accumulate across the (innermost, sequential) q grid axis;
    # their block index is constant in iq so the block stays resident
    @pl.when(pl.program_id(2) == 0)
    def _init():
        dk_ref[0, 0] = jnp.zeros_like(dk_ref[0, 0])
        dv_ref[0, 0] = jnp.zeros_like(dv_ref[0, 0])

    dk_ref[0, 0] += lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dv_ref[0, 0] += lax.dot_general(
        p_lo, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _bwd_kernel_chunked(*refs, scale, causal, has_seg, bq):
    """Causal-skip bwd (see _fwd_kernel_chunked). The score scratch is
    reused for dP once P is materialized; skipped chunks hold garbage in
    dP, so P*dP is masked to 0 there before the D reduction (P alone is
    exactly 0 at masked positions, but 0 * garbage could be NaN)."""
    if has_seg:
        (q_ref, k_ref, v_ref, sq_ref, skv_ref, do_ref,
         dq_ref, dk_ref, dv_ref, s_scr, acc_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref,
         dq_ref, dk_ref, dv_ref, s_scr, acc_scr) = refs
        sq_ref = skv_ref = None
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    rows = q.shape[0]
    sk = k_ref.shape[2]
    nk = sk // bq
    iq = pl.program_id(2)
    reach = iq * bq + rows - 1

    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            kc = k_ref[0, 0, c * bq:(c + 1) * bq, :]
            s_scr[:, c * bq:(c + 1) * bq] = lax.dot_general(
                q, kc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * jnp.float32(scale)

    masked = _masks(iq, bq, rows, sk, causal, sq_ref, skv_ref)
    p = _softmax(s_scr[...], masked)
    p_lo = p.astype(q.dtype)

    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            vc = v_ref[0, 0, c * bq:(c + 1) * bq, :]
            s_scr[:, c * bq:(c + 1) * bq] = lax.dot_general(
                do, vc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
    dp = s_scr[...]
    pdp = jnp.where(masked, 0.0, p * dp) if masked is not None else p * dp
    dcol = jnp.sum(pdp, axis=-1, keepdims=True)
    ds = (pdp - p * dcol) * jnp.float32(scale)

    @pl.when(iq == 0)
    def _init():
        dk_ref[0, 0] = jnp.zeros_like(dk_ref[0, 0])
        dv_ref[0, 0] = jnp.zeros_like(dv_ref[0, 0])

    acc_scr[...] = jnp.zeros_like(acc_scr)
    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            sl = slice(c * bq, (c + 1) * bq)
            dsc = ds[:, sl].astype(q.dtype)
            kc = k_ref[0, 0, sl, :]
            acc_scr[...] += lax.dot_general(
                dsc, kc, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dk_ref[0, 0, sl, :] += lax.dot_general(
                dsc, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dv_ref[0, 0, sl, :] += lax.dot_general(
                p_lo[:, sl], do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    dq_ref[0, 0] = acc_scr[...].astype(dq_ref.dtype)


def _bwd_dq_kernel(*refs, scale, causal, has_seg, bq):
    """Split backward, pass 1 (q-major): dq plus the per-row softmax
    stats (rowmax m, rowsum l) and D = rowsum(P*dP) the k-major pass
    needs to reconstruct P and dS row-exactly."""
    if has_seg:
        (q_ref, k_ref, v_ref, sq_ref, skv_ref, do_ref,
         dq_ref, m_ref, l_ref, dcol_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref,
         dq_ref, m_ref, l_ref, dcol_ref) = refs
        sq_ref = skv_ref = None
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    do = do_ref[0, 0]

    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    s = s * jnp.float32(scale)
    masked = _masks(pl.program_id(2), bq, q.shape[0], k.shape[0],
                    causal, sq_ref, skv_ref)
    p, m, tot = _softmax_stats(s, masked)

    dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    dcol = jnp.sum(p * dp, axis=-1, keepdims=True)
    ds = (p * (dp - dcol) * jnp.float32(scale)).astype(q.dtype)

    dq = lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)
    # stats refs are [bq, 1] (the stats arrays carry a trailing 1 so the
    # block's last dim equals the array dim — Mosaic requires the last
    # two block dims be (8, 128)-divisible or full; a 3-D (1, 1, bq)
    # block has a bare 1 against the h axis and fails to lower)
    m_ref[0, 0] = m
    l_ref[0, 0] = tot
    dcol_ref[0, 0] = dcol


def _bwd_dq_kernel_chunked(*refs, scale, causal, has_seg, bq):
    """Causal-skip variant of the split dq pass (see _bwd_kernel_chunked
    for the skip/garbage rules) — without it the split default would pay
    the full-score causal tax the monolithic chunked kernel avoids."""
    if has_seg:
        (q_ref, k_ref, v_ref, sq_ref, skv_ref, do_ref,
         dq_ref, m_ref, l_ref, dcol_ref, s_scr, acc_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref,
         dq_ref, m_ref, l_ref, dcol_ref, s_scr, acc_scr) = refs
        sq_ref = skv_ref = None
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    rows = q.shape[0]
    sk = k_ref.shape[2]
    nk = sk // bq
    iq = pl.program_id(2)
    reach = iq * bq + rows - 1

    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            kc = k_ref[0, 0, c * bq:(c + 1) * bq, :]
            s_scr[:, c * bq:(c + 1) * bq] = lax.dot_general(
                q, kc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * jnp.float32(scale)

    masked = _masks(iq, bq, rows, sk, causal, sq_ref, skv_ref)
    p, m, tot = _softmax_stats(s_scr[...], masked)

    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            vc = v_ref[0, 0, c * bq:(c + 1) * bq, :]
            s_scr[:, c * bq:(c + 1) * bq] = lax.dot_general(
                do, vc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
    dp = s_scr[...]
    pdp = jnp.where(masked, 0.0, p * dp) if masked is not None else p * dp
    dcol = jnp.sum(pdp, axis=-1, keepdims=True)
    ds = (pdp - p * dcol) * jnp.float32(scale)

    acc_scr[...] = jnp.zeros_like(acc_scr)
    for c in range(nk):
        @pl.when(c * bq <= reach)
        def _(c=c):
            sl = slice(c * bq, (c + 1) * bq)
            kc = k_ref[0, 0, sl, :]
            acc_scr[...] += lax.dot_general(
                ds[:, sl].astype(q.dtype), kc, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    dq_ref[0, 0] = acc_scr[...].astype(dq_ref.dtype)
    m_ref[0, 0] = m          # [bq, 1] refs — see _bwd_dq_kernel
    l_ref[0, 0] = tot
    dcol_ref[0, 0] = dcol


def _bwd_dkv_kernel(*refs, scale, causal, has_seg, bq, sq):
    """Split backward, pass 2 (k-major): each (b, h, k-block) grid step
    owns its [bk, d] dk/dv blocks outright — no accumulation across grid
    steps, no block revisiting. P and dS are reconstructed from the saved
    (m, l, D) row stats; q is processed in bq-sized chunks so causal
    blocks skip the strictly-below-diagonal chunks entirely."""
    if has_seg:
        (q_ref, k_ref, v_ref, sq_ref, skv_ref, do_ref, m_ref, l_ref,
         dcol_ref, dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, m_ref, l_ref, dcol_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        sq_ref = skv_ref = None
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    bk = k.shape[0]
    ik = pl.program_id(2)
    nq = sq // bq

    dk_scr[...] = jnp.zeros_like(dk_scr)
    dv_scr[...] = jnp.zeros_like(dv_scr)

    for c in range(nq):
        def _chunk(c=c):
            qc = q_ref[0, 0, c * bq:(c + 1) * bq, :]
            doc = do_ref[0, 0, c * bq:(c + 1) * bq, :]
            m = m_ref[0, 0, c * bq:(c + 1) * bq, :]       # [bq, 1]
            tot = l_ref[0, 0, c * bq:(c + 1) * bq, :]
            dcol = dcol_ref[0, 0, c * bq:(c + 1) * bq, :]

            s = lax.dot_general(qc, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
            s = s * jnp.float32(scale)

            seg_rows = (None if sq_ref is None
                        else sq_ref[0, c * bq:(c + 1) * bq, 0])
            masked = _masks(c, bq, bq, bk, causal, sq_ref, skv_ref,
                            col0=ik * bk, seg_rows=seg_rows)
            p = _p_from_stats(s, m, tot, masked)

            dp = lax.dot_general(doc, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
            ds = (p * (dp - dcol) * jnp.float32(scale)).astype(
                qc.dtype)
            p_lo = p.astype(qc.dtype)

            dk_scr[...] += lax.dot_general(
                ds, qc, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dv_scr[...] += lax.dot_general(
                p_lo, doc, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        if causal:
            # q rows < this k-block's first column contribute nothing —
            # skip the chunk (the grid is sequential scalar control flow)
            pl.when((c + 1) * bq - 1 >= ik * bk)(_chunk)
        else:
            _chunk()

    dk_ref[0, 0] = dk_scr[...]
    dv_ref[0, 0] = dv_scr[...]


def _specs(b, h, bq, sq, sk, d, has_seg):
    """(in_specs for q,k,v[,seg_q,seg_kv], qblk-spec, kvblk-spec)."""
    qspec = pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq: (ib, ih, iq, 0))
    kvspec = pl.BlockSpec((1, 1, sk, d), lambda ib, ih, iq: (ib, ih, 0, 0))
    ins = [qspec, kvspec, kvspec]
    if has_seg:
        # Mosaic's last-two-dims rule: each block dim must be (8, 128)-
        # divisible or span the full array dim. A 2-D (1, s) block over
        # [b, s] puts a bare 1 against the batch axis and fails it, so
        # seg_q travels SUBLANE-major as [b, sq, 1] — its (1, bq, 1)
        # block needs only 8-divisibility on bq, legal for every block
        # size _pick_bq can produce — while seg_kv stays LANE-major as
        # [b, 1, sk] with the always-full (and always-legal) (1, 1, sk)
        # block. Each layout matches the axis _masks broadcasts it along.
        ins.append(pl.BlockSpec((1, bq, 1), lambda ib, ih, iq: (ib, iq, 0)))
        ins.append(pl.BlockSpec((1, 1, sk), lambda ib, ih, iq: (ib, 0, 0)))
    return ins, qspec, kvspec


def _seg_ops(segment_ids):
    if segment_ids is None:
        return []
    seg_q, seg_kv = segment_ids
    # seg_q [b, s] -> [b, s, 1] (sublane-major), seg_kv -> [b, 1, s]
    # (lane-major): see the seg BlockSpec note in _specs
    return [seg_q.astype(jnp.int32)[:, :, None],
            seg_kv.astype(jnp.int32)[:, None, :]]


def _chunked(causal, bq, sq, sk):
    """Causal-skip applies when chunk boundaries are lane-aligned and
    there are >= 2 q blocks (a single block has nothing to skip)."""
    return causal and bq % 128 == 0 and sk % bq == 0 and sq >= 2 * bq


def _pick_bq(sq, sk, block_q, n_arrays=_BWD_ARRAYS, tile_pref=None,
             pref_keys=("block_q",)):
    """The effective q block: per-call ``block_q`` (raises on an
    illegal tile — the shared model's verdict) > ``set_block_q`` /
    ``APEX_ATTN_BLOCK_Q`` (fall back per shape) > ``tile_pref`` (table
    params, first legal of ``pref_keys``) > the heuristic."""
    if block_q is not None:
        problems = tiles.attn_q_problems("block_q", block_q, sq, sk,
                                         n_arrays, budget=_VMEM_BUDGET)
        if problems:
            raise ValueError("attention_pallas: " + "; ".join(problems))
        return block_q
    prefs = [_BLOCK_Q, _env_block_q()]
    prefs += [_pref_get(tile_pref, k) for k in pref_keys]
    for p in prefs:
        if p is not None and not tiles.attn_q_problems(
                "block_q", p, sq, sk, n_arrays, budget=_VMEM_BUDGET):
            return p
    return _q_block(sq, sk, n_arrays)


# Backward structure: "monolithic" = one q-major kernel accumulating
# dk/dv across the sequential grid; "split" = a q-major dq pass (emitting
# the (m, l, D) row stats) + a k-major dk/dv pass where each k-block is
# computed exactly once. Measured knob — the device A/B landed (PERF.md
# §10): monolithic wins the fwd+d(q,k,v) training protocol (1.509 vs
# 2.071 ms at the GPT-2 shape) and keeps the default; split wins the
# dq-only protocol 1.5x and remains the choice for no-kv-grad paths.
# Unpinned calls also consult the per-shape dispatch table
# (apex_tpu.dispatch, op "attention_bwd") below set_bwd_impl.
BWD_IMPL = "monolithic"
_BWD_PINNED = False  # True once set_bwd_impl was called


def set_bwd_impl(impl):
    """Set the process-wide backward-structure *preference*. Shapes that
    fail ``_split_ok`` fall back to monolithic silently (a model may mix
    eligible and ineligible layers); a per-call ``bwd_impl=`` is a strict
    demand and raises instead — benchmark rows use the per-call form so
    their labels stay truthful. Pins the choice above the dispatch
    table."""
    global BWD_IMPL, _BWD_PINNED
    if impl not in ("monolithic", "split"):
        raise ValueError(f"unknown rows bwd impl {impl!r}")
    BWD_IMPL = impl
    _BWD_PINNED = True


def reset_bwd_impl():
    """Back to the unpinned built-in default (tests / knob teardown)."""
    global BWD_IMPL, _BWD_PINNED
    BWD_IMPL = "monolithic"
    _BWD_PINNED = False


def _bwd_table_consult(q, k):
    """``(choice_or_None, tile_pref_tuple_or_None)`` from the
    dispatch-table "attention_bwd" entry for this bucket — the params
    half feeds the backward's tile resolution even when the impl itself
    is pinned (the impl pin and the tile axis are independent knobs)."""
    from apex_tpu import dispatch

    choice, params = dispatch.lookup_params(
        "attention_bwd", dtype=q.dtype, b=q.shape[0], h=q.shape[1],
        sq=q.shape[2], sk=k.shape[2], d=q.shape[3])
    pref = tuple(sorted(params.items())) if params else None
    return choice, pref


def _effective_bwd_impl(q, k):
    """Table-aware resolution for an unpinned backward: set_bwd_impl >
    dispatch-table "attention_bwd" entry for this bucket > built-in.
    Like the setter, a table "split" is a preference — ineligible shapes
    fall back to monolithic in _bwd_rule."""
    if _BWD_PINNED:
        return BWD_IMPL
    return _bwd_table_consult(q, k)[0] or BWD_IMPL


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 6, 7, 8, 9, 11, 12, 13))
def fused_attention_rows(q, k, v, causal, sm_scale, segment_ids=None,
                         interpret=False, block_q=None, bwd_impl=None,
                         dropout_p=0.0, dropout_seed=None,
                         bwd_block_q=None, block_k=None, tile_pref=None):
    """VMEM-row fused attention. q: [b, h, sq, d]; k, v: [b, h, sk, d];
    segment_ids: None or (seg_q [b, sq], seg_kv [b, sk]). Check
    ``supported(sq, sk, d)`` first. ``interpret=True`` for CPU tests.
    ``block_q`` overrides the auto q-block (benchmark sweeps);
    ``bwd_impl`` overrides the module-level ``BWD_IMPL``.

    ``dropout_p`` > 0 applies inverted attention-probability dropout
    INSIDE the kernel (counter-hash mask, replayed in backward — no
    [sq, sk] mask in HBM); requires a traced int32 ``dropout_seed``
    of shape (1, 1). Dropout forces the monolithic backward (an
    explicit ``bwd_impl="split"`` request raises).

    Tile knobs (all judged by ``apex_tpu.dispatch.tiles``; per-call
    values raise on an illegal tile): ``block_q`` sizes the fwd AND
    (absent ``bwd_block_q``) backward q blocks; ``bwd_block_q``
    overrides the backward only; ``block_k`` sizes the split backward's
    k-major dk/dv block (requires the split structure to stay
    eligible). ``tile_pref`` is the preference form — a hashable
    ``((name, value), ...)`` tuple the dispatch-table consumer passes;
    illegal entries fall back per shape, and ``set_block_q`` /
    ``APEX_ATTN_BLOCK_Q`` resolve above it."""
    if bwd_impl is not None and bwd_impl not in ("monolithic", "split"):
        raise ValueError(f"unknown rows bwd impl {bwd_impl!r}")
    if not 0.0 <= dropout_p < 1.0:
        raise ValueError(f"dropout_p={dropout_p} outside [0, 1)")
    if dropout_p > 0.0 and bwd_impl == "split":
        raise ValueError("dropout requires the monolithic backward")
    if block_k is not None and bwd_impl == "monolithic":
        raise ValueError("block_k tiles the split backward; it cannot "
                         "be honored with bwd_impl='monolithic'")
    return _fwd(q, k, v, causal, sm_scale, segment_ids, interpret,
                block_q, dropout_p, dropout_seed, tile_pref)[0]


def _drop_ops(dropout_p, dropout_seed):
    if dropout_p <= 0.0:
        return []
    if dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed")
    seed = jnp.asarray(dropout_seed).reshape(1, 1)
    return [seed.astype(jnp.int32)]


def _drop_spec(dropout_p):
    if dropout_p <= 0.0:
        return []
    return [pl.BlockSpec((1, 1), lambda ib, ih, iq: (0, 0))]


def _fwd(q, k, v, causal, sm_scale, segment_ids, interpret, block_q=None,
         dropout_p=0.0, dropout_seed=None, tile_pref=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if not supported(sq, sk, d, dropout=dropout_p > 0.0):
        raise ValueError(f"attention_pallas: unsupported {q.shape}x{k.shape}"
                         + (" with dropout" if dropout_p > 0.0 else ""))
    n_arrays = _DROP_BWD_ARRAYS if dropout_p > 0.0 else _BWD_ARRAYS
    bq = _pick_bq(sq, sk, block_q, n_arrays, tile_pref)
    has_seg = segment_ids is not None
    ins, qspec, _ = _specs(b, h, bq, sq, sk, d, has_seg)
    kern = functools.partial(_fwd_kernel, dropout_p=dropout_p, n_heads=h)
    scratch = []
    if dropout_p <= 0.0 and _chunked(causal, bq, sq, sk):
        kern = _fwd_kernel_chunked
        scratch = [pltpu.VMEM((bq, sk), jnp.float32),
                   pltpu.VMEM((bq, d), jnp.float32)]
    o = pl.pallas_call(
        functools.partial(kern, scale=float(sm_scale), causal=causal,
                          has_seg=has_seg, bq=bq),
        grid=(b, h, sq // bq),
        in_specs=ins + _drop_spec(dropout_p),
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v, *_seg_ops(segment_ids), *_drop_ops(dropout_p, dropout_seed))
    return o, (q, k, v, segment_ids, dropout_seed)


def _fwd_rule(q, k, v, causal, sm_scale, segment_ids, interpret,
              block_q=None, bwd_impl=None, dropout_p=0.0,
              dropout_seed=None, bwd_block_q=None, block_k=None,
              tile_pref=None):
    return _fwd(q, k, v, causal, sm_scale, segment_ids, interpret, block_q,
                dropout_p, dropout_seed, tile_pref)


def _pick_bwd_bq(sq, sk, block_q, bwd_block_q, n_arrays=_BWD_ARRAYS,
                 tile_pref=None):
    """Backward q block: per-call ``bwd_block_q`` (raise) > per-call
    ``block_q`` (raise — shared with fwd) > setter/env > table
    ``bwd_block_q`` then ``block_q`` prefs > heuristic."""
    if bwd_block_q is not None:
        problems = tiles.attn_q_problems("bwd_block_q", bwd_block_q, sq,
                                         sk, n_arrays,
                                         budget=_VMEM_BUDGET)
        if problems:
            raise ValueError("attention_pallas: " + "; ".join(problems))
        return bwd_block_q
    return _pick_bq(sq, sk, block_q, n_arrays, tile_pref,
                    pref_keys=("bwd_block_q", "block_q"))


def _bwd_monolithic(causal, sm_scale, interpret, block_q, res, g,
                    dropout_p=0.0, bwd_block_q=None, tile_pref=None):
    q, k, v, segment_ids, dropout_seed = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    n_arrays = _DROP_BWD_ARRAYS if dropout_p > 0.0 else _BWD_ARRAYS
    bq = _pick_bwd_bq(sq, sk, block_q, bwd_block_q, n_arrays, tile_pref)
    has_seg = segment_ids is not None
    ins, qspec, kvspec = _specs(b, h, bq, sq, sk, d, has_seg)
    kern = functools.partial(_bwd_kernel, dropout_p=dropout_p, n_heads=h)
    scratch = []
    if dropout_p <= 0.0 and _chunked(causal, bq, sq, sk):
        kern = _bwd_kernel_chunked
        scratch = [pltpu.VMEM((bq, sk), jnp.float32),
                   pltpu.VMEM((bq, d), jnp.float32)]
    dq, dk, dv = pl.pallas_call(
        functools.partial(kern, scale=float(sm_scale), causal=causal,
                          has_seg=has_seg, bq=bq),
        grid=(b, h, sq // bq),
        in_specs=ins + _drop_spec(dropout_p) + [qspec],
        out_specs=(qspec, kvspec, kvspec),
        out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct(k.shape, jnp.float32),
                   jax.ShapeDtypeStruct(v.shape, jnp.float32)),
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v, *_seg_ops(segment_ids),
      *_drop_ops(dropout_p, dropout_seed), g)
    return (dq, dk.astype(k.dtype), dv.astype(v.dtype), None, None)


def _bwd_split(causal, sm_scale, interpret, block_q, res, g,
               bwd_block_q=None, block_k=None, tile_pref=None):
    q, k, v, segment_ids, _ = res  # no dropout on the split path
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = _pick_bwd_bq(sq, sk, block_q, bwd_block_q,
                      tile_pref=tile_pref)
    has_seg = segment_ids is not None
    ins, qspec, kvspec = _specs(b, h, bq, sq, sk, d, has_seg)
    # stats carry a trailing 1 (block last dim == array dim) so the
    # (m, l, D) outputs satisfy Mosaic's last-two-dims rule on device
    vecspec = pl.BlockSpec((1, 1, bq, 1), lambda ib, ih, iq: (ib, ih, iq, 0))
    vecshape = jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32)

    dq_kern, dq_scratch = _bwd_dq_kernel, []
    if _chunked(causal, bq, sq, sk):
        dq_kern = _bwd_dq_kernel_chunked
        dq_scratch = [pltpu.VMEM((bq, sk), jnp.float32),
                      pltpu.VMEM((bq, d), jnp.float32)]
    dq, m, l, dcol = pl.pallas_call(
        functools.partial(dq_kern, scale=float(sm_scale),
                          causal=causal, has_seg=has_seg, bq=bq),
        grid=(b, h, sq // bq),
        in_specs=ins + [qspec],
        out_specs=(qspec, vecspec, vecspec, vecspec),
        out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),
                   vecshape, vecshape, vecshape),
        scratch_shapes=dq_scratch,
        interpret=interpret,
    )(q, k, v, *_seg_ops(segment_ids), g)

    # k blocks default to the VMEM-validated row block; block_k decouples
    # them (per-call raises via _bwd_rule's eligibility gate, a table
    # pref falls back there)
    bk = block_k if block_k is not None else bq
    fullq = pl.BlockSpec((1, 1, sq, d), lambda ib, ih, ik: (ib, ih, 0, 0))
    kvblk = pl.BlockSpec((1, 1, bk, d), lambda ib, ih, ik: (ib, ih, ik, 0))
    fullvec = pl.BlockSpec((1, 1, sq, 1), lambda ib, ih, ik: (ib, ih, 0, 0))
    dkv_ins = [fullq, kvblk, kvblk]
    if has_seg:
        # seg_q full-length sublane-major (q is chunked in-kernel);
        # seg_kv's (1, 1, bk) lane-dim block relies on _split_ok's
        # bq % 128 gate (bk = bq) for alignment
        dkv_ins.append(
            pl.BlockSpec((1, sq, 1), lambda ib, ih, ik: (ib, 0, 0)))
        dkv_ins.append(
            pl.BlockSpec((1, 1, bk), lambda ib, ih, ik: (ib, 0, ik)))
    dkv_ins += [fullq, fullvec, fullvec, fullvec]

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=float(sm_scale),
                          causal=causal, has_seg=has_seg, bq=bq, sq=sq),
        grid=(b, h, sk // bk),
        in_specs=dkv_ins,
        out_specs=(kvblk, kvblk),
        out_shape=(jax.ShapeDtypeStruct(k.shape, jnp.float32),
                   jax.ShapeDtypeStruct(v.shape, jnp.float32)),
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, *_seg_ops(segment_ids), g, m, l, dcol)
    return (dq, dk.astype(k.dtype), dv.astype(v.dtype), None, None)


def _split_ok(sq, sk, d, bq, itemsize, bk=None):
    """VMEM eligibility of the split k-major pass: it keeps the full
    [sq, d] q and dO resident per grid step (the monolithic backward
    streams q instead), holds 3 [bq, bk] fp32 chunk arrays + 2 [bk, d]
    accumulators + 3 [sq] stat vectors, and unrolls sq/bq chunks.
    The model lives in the shared tile module (``tiles.split_ok``);
    bq % 128: the k-major pass tiles seg_kv into (.., bk) LANE-dim
    blocks (bk = bq by default) and every in-kernel
    [:, c*bq:(c+1)*bq] chunk slice in the q-major dq pass cuts the
    lane axis — both need 128-alignment under Mosaic."""
    return tiles.split_ok(sq, sk, d, bq, itemsize, bk,
                          budget=_VMEM_BUDGET)


def _bwd_rule(causal, sm_scale, interpret, block_q, bwd_impl, dropout_p,
              bwd_block_q, block_k, tile_pref, res, g):
    if bwd_impl is not None and bwd_impl not in ("monolithic", "split"):
        raise ValueError(f"unknown rows bwd impl {bwd_impl!r}")
    q, k, v, _, _ = res
    if dropout_p > 0.0:
        # the split structure has no dropout replay wired through its two
        # passes; the per-call demand raises (fused_attention_rows already
        # pre-checks this), the process-wide preference falls back.
        # BEFORE any table consult: dropout forces monolithic, and a
        # consult whose choice could never be honored would still land
        # in dispatch.snapshot()'s consult log — mislabeling what the
        # measured backward actually ran
        if bwd_impl == "split":
            raise ValueError("dropout requires the monolithic backward")
        if block_k is not None:
            raise ValueError("block_k tiles the split backward; it "
                             "cannot be honored with dropout")
        return _bwd_monolithic(causal, sm_scale, interpret, block_q, res,
                               g, dropout_p, bwd_block_q, tile_pref)
    if not _BWD_PINNED and bwd_impl is None:
        # the attention_bwd table entry's params feed the backward tile
        # resolution (below per-call knobs and setter/env), merged over
        # any call-level pref: bwd-specific keys win
        table_choice, table_pref = _bwd_table_consult(q, k)
        if table_pref:
            merged = dict(tile_pref or ())
            merged.update(dict(table_pref))
            tile_pref = tuple(sorted(merged.items()))
    else:
        table_choice = None
    impl = bwd_impl or (BWD_IMPL if _BWD_PINNED
                        else table_choice or BWD_IMPL)
    sq, sk = q.shape[2], k.shape[2]
    bq = _pick_bwd_bq(sq, sk, block_q, bwd_block_q, tile_pref=tile_pref)
    if block_k is not None:
        # an explicit k block is a demand on the split structure
        problems = []
        if not isinstance(block_k, int) or block_k % 128 or block_k < 128:
            problems.append(f"block_k={block_k!r} must be a multiple "
                            f"of 128")
        elif sk % block_k:
            problems.append(f"block_k={block_k} does not divide sk={sk}")
        elif not _split_ok(sq, sk, q.shape[3], bq, q.dtype.itemsize,
                           block_k):
            problems.append(
                f"block_k={block_k}: split bwd ineligible for "
                f"{q.shape}x{k.shape} (bq={bq})")
        if problems:
            raise ValueError("attention_pallas: " + "; ".join(problems))
        if bwd_impl is None and impl != "split":
            impl = "split"  # an explicit block_k selects the structure
    eff_bk = block_k if block_k is not None \
        else _pref_get(tile_pref, "block_k")
    if eff_bk is not None and block_k is None and not _split_ok(
            sq, sk, q.shape[3], bq, q.dtype.itemsize, eff_bk):
        eff_bk = None  # table pref falls back per shape
    ok = _split_ok(sq, sk, q.shape[3], bq, q.dtype.itemsize, eff_bk)
    if bwd_impl == "split" and not ok:
        # an explicit request must be honored or error — silently running
        # monolithic would mislabel A/B benchmark rows
        raise ValueError(
            f"split bwd ineligible for {q.shape}x{k.shape} (bq={bq})")
    if impl == "split" and ok:
        return _bwd_split(causal, sm_scale, interpret, block_q, res, g,
                          bwd_block_q, eff_bk, tile_pref)
    return _bwd_monolithic(causal, sm_scale, interpret, block_q, res, g,
                           0.0, bwd_block_q, tile_pref)


fused_attention_rows.defvjp(_fwd_rule, _bwd_rule)
