"""Pallas TPU decode attention over a paged KV cache (q_len = 1).

The FIFTH dispatch family (ISSUE 10): serving decode is a genuinely
different program shape from every training kernel in ops/ — one query
row per sequence, the whole cost is streaming the KV cache out of HBM,
and the cache is PAGED (block-granular allocation,
``apex_tpu.serving.kv_cache``) so the key/value rows of one sequence
are scattered across non-contiguous pages named by a page table.

Kernel structure: grid ``(b, h/block_h, pages)``; the page table and
per-sequence context lengths ride as SCALAR-PREFETCH operands
(``pltpu.PrefetchScalarGridSpec``) so the K/V BlockSpec index maps do
the gather — grid step ``(i, hb, j)`` DMAs page ``page_table[i, j]``
for ``block_h`` heads directly from the paged arrays; allocation is
pure index arithmetic, never a reshape. Online-softmax accumulators
(fp32 m/l/acc) live in VMEM scratch across the sequential page axis;
pages at or beyond the sequence's context length are skipped
(``pl.when`` — the padded page-table tail points at the reserved null
page 0, fetched but never read into the accumulators).

Scores and the context reduction are computed as broadcast-multiply +
lane reductions rather than 1-row MXU matmuls: with q_len = 1 the MXU
would idle on a [1, d] operand anyway, and decode is bandwidth-bound —
the VPU keeps pace with the DMA stream.

Dispatch (the same shape as the four existing families):

    per-call ``impl=`` (raises on un-honorable)
      > ``set_decode_impl`` / ``APEX_DECODE_ATTN_IMPL`` (fall back)
      > dispatch-table entry (op "decode_attention")
      > built-in ``jnp``

The built-in default is the XLA gather-attention reference
(:func:`decode_attention_reference`) per the measured-dispatch rule —
no device A/B has landed for this family yet (queued in PERF.md §2);
the Pallas kernel engages via knob or a measured table entry. Tile
axis: ``block_h`` (heads per grid step), judged by
``apex_tpu.dispatch.tiles`` (op "decode_attention") with the usual
asymmetry — per-call raises, setter/env/table fall back per shape.

Layouts:
  q                [b, h, d]          (one query row per sequence slot)
  k_pages/v_pages  [h, pages, page_size, d]
  page_table       [b, max_pages]     int32 (padding -> null page 0)
  lengths          [b]                int32 (0 = inactive slot -> 0 out)
  k_scale/v_scale  [h, pages]         per-(page, head) scales of the
                                      int8 KV tier (ISSUE 20), or None

int8 KV tier (serving.kv_tier): when the pages are int8 codes, the
per-(page, head) scales ride as two more scalar-prefetch-INDEXED
operands — the same ``page_table[i, j]`` gather as the page blocks,
one bf16 scalar per head per grid step — and both impls dequantize at
read (fp32 multiply next to the existing widening cast; no
dequantized page copy is ever materialized). The VMEM model budgets
the scale blocks at the int8 itemsize (tiles.decode_vmem_bytes).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.dispatch import tiles

NEG_INF = -1e30  # python float: jnp scalars would be captured consts
                 # inside the pallas kernel (Mosaic requires operands)

# Process-wide impl preference (tri-state; falls back per shape — only
# per-call impl= raises on un-honorable requests, CLAUDE.md asymmetry)
_IMPL = None


def set_decode_impl(impl):
    """Pin the process-wide decode-attention impl preference ("jnp" |
    "pallas"), or un-pin with None (env/table/built-in apply again).
    Shapes the pinned kernel can't run fall back to the jnp reference
    silently; a setter CALL with an unknown impl still raises."""
    global _IMPL
    if impl not in (None, "jnp", "pallas"):
        raise ValueError(f"unknown decode-attention impl {impl!r}")
    _IMPL = impl


def _env_impl():
    """APEX_DECODE_ATTN_IMPL preference (tiles.env_choice: unknown
    values warn once and are ignored — an env knob is a preference,
    never a raise)."""
    return tiles.env_choice("APEX_DECODE_ATTN_IMPL", ("jnp", "pallas"))


# Process-wide head-block preference (same fall-back semantics as the
# other families' tile setters)
_BLOCK_H = None


def set_block_h(value):
    """Pin the process-wide head-block preference (positive int), or
    un-pin with None. Judged per shape by the shared tile model; an
    illegal pin falls back to the heuristic silently."""
    global _BLOCK_H
    tiles.check_setter_value(value, "block_h")
    _BLOCK_H = value


def supported(h, pages, page_size, d, dtype=None):
    """Whether the Pallas kernel handles this cache geometry: the page
    block's last two dims span full array axes (always Mosaic-legal),
    so the gate is the VMEM working set at the minimum one-head tile
    plus a bounded head_dim (the fp32 accumulators scale with d).
    ``dtype`` is the cache dtype — the SAME itemsize the tile model
    (and ``_pick_bh``) judges with, so this gate and the block picker
    cannot disagree at the VMEM boundary (fp32 assumed when absent)."""
    itembytes = tiles.itemsize(dtype) if dtype is not None else 4
    return (d <= 512 and page_size >= 1 and pages >= 1
            and tiles.decode_block_h(h, page_size, d, itembytes) != 0)


def _pick_bh(h, ps, d, dtype, block_h, tile_pref):
    """Effective head block: per-call (raises via the shared model) >
    setter/env (fall back) > table pref (falls back) > heuristic."""
    dims = {"b": 1, "h": h, "pages": 1, "ps": ps, "d": d}
    if block_h is not None:
        problems = tiles.legal("decode_attention", dims, dtype,
                               {"block_h": block_h})
        if problems:
            raise ValueError("decode_attention_pallas: "
                             + "; ".join(problems))
        return block_h
    prefs = [_BLOCK_H, tiles.env_int("APEX_DECODE_ATTN_BLOCK_H")]
    if tile_pref:
        prefs.append(dict(tile_pref).get("block_h"))
    for p in prefs:
        if p is not None and not tiles.legal(
                "decode_attention", dims, dtype, {"block_h": p}):
            return p
    return tiles.decode_block_h(h, ps, d, tiles.itemsize(dtype))


def _kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, *rest,
            scale, ps, n_pages, quant):
    if quant:
        ks_ref, vs_ref, o_ref, acc_scr, m_scr, l_scr = rest
    else:
        o_ref, acc_scr, m_scr, l_scr = rest
    i = pl.program_id(0)   # sequence slot
    j = pl.program_id(2)   # page index within the slot's table

    @pl.when(j == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)
        m_scr[...] = jnp.full_like(m_scr, jnp.float32(NEG_INF))
        l_scr[...] = jnp.zeros_like(l_scr)

    length = len_ref[i]

    @pl.when(j * ps < length)
    def _page():
        q = q_ref[0, :, 0, :].astype(jnp.float32) * jnp.float32(scale)
        k = k_ref[:, 0].astype(jnp.float32)          # [bh, ps, d]
        v = v_ref[:, 0].astype(jnp.float32)
        if quant:
            # dequantize at read: one bf16 scale per head for THIS
            # page (scalar-prefetch-indexed like the page blocks)
            k = k * ks_ref[:, 0, 0].astype(jnp.float32)[:, None, None]
            v = v * vs_ref[:, 0, 0].astype(jnp.float32)[:, None, None]
        # [bh, ps] scores: broadcast-multiply + lane reduction (see
        # module docstring — q_len=1 makes the MXU moot)
        s = jnp.sum(q[:, None, :] * k, axis=-1)
        col = j * ps + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        masked = col >= length
        s = jnp.where(masked, jnp.float32(NEG_INF), s)
        m_new = jnp.maximum(m_scr[...], jnp.max(s, axis=-1,
                                                keepdims=True))
        alpha = jnp.exp(m_scr[...] - m_new)
        p = jnp.exp(s - m_new)
        p = jnp.where(masked, 0.0, p)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1,
                                                  keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.sum(
            p[:, :, None] * v, axis=1)
        m_scr[...] = m_new

    @pl.when(j == n_pages - 1)
    def _finish():
        l = l_scr[...]
        o = acc_scr[...] / jnp.where(l > 0, l, 1.0)
        o_ref[0, :, 0, :] = o.astype(o_ref.dtype)


def decode_attention_pallas(q, k_pages, v_pages, page_table, lengths,
                            sm_scale, *, k_scale=None, v_scale=None,
                            block_h=None, interpret=False,
                            tile_pref=None):
    """The Pallas paged-decode kernel (layouts in the module
    docstring). Call :func:`decode_attention` for the dispatched
    surface; this entry raises on unsupported geometry. With
    ``k_scale``/``v_scale`` (``[h, pages]`` — the int8 KV tier) the
    scales ride as two extra operands whose BlockSpec gathers the
    SAME ``page_table[i, j]`` page the K/V blocks do, and the kernel
    dequantizes at read."""
    b, h, d = q.shape
    n_pages_total, ps = k_pages.shape[1], k_pages.shape[2]
    max_pages = page_table.shape[1]
    quant = k_scale is not None
    if not supported(h, n_pages_total, ps, d, k_pages.dtype):
        raise ValueError(
            f"decode_attention_pallas: unsupported geometry h={h} "
            f"ps={ps} d={d} ({k_pages.dtype})")
    # judged at the CACHE dtype — the K/V pages are the streamed
    # working set the VMEM model budgets (same itemsize supported()
    # gates with; the int8 itemsize implies the scale operands, which
    # tiles.decode_vmem_bytes budgets too)
    bh = _pick_bh(h, ps, d, k_pages.dtype, block_h, tile_pref)
    q4 = q[:, :, None, :]                   # [b, h, 1, d]
    grid = (b, h // bh, max_pages)

    def q_map(i, hb, j, pt, ln):
        return (i, hb, 0, 0)

    def kv_map(i, hb, j, pt, ln):
        return (hb, pt[i, j], 0, 0)

    def sc_map(i, hb, j, pt, ln):
        return (hb, pt[i, j], 0)

    in_specs = [
        pl.BlockSpec((1, bh, 1, d), q_map),
        pl.BlockSpec((bh, 1, ps, d), kv_map),
        pl.BlockSpec((bh, 1, ps, d), kv_map),
    ]
    operands = [q4, k_pages, v_pages]
    if quant:
        # [h, pages] -> [h, pages, 1]: a trailing unit axis keeps the
        # block's minor dim spanning its full array axis (the same
        # Mosaic last-two-dims legality argument as the page blocks)
        in_specs += [pl.BlockSpec((bh, 1, 1), sc_map)] * 2
        operands += [k_scale[:, :, None], v_scale[:, :, None]]

    kern = functools.partial(_kernel, scale=float(sm_scale), ps=ps,
                             n_pages=max_pages, quant=quant)
    out = pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, bh, 1, d), q_map),
            scratch_shapes=[
                pltpu.VMEM((bh, d), jnp.float32),
                pltpu.VMEM((bh, 1), jnp.float32),
                pltpu.VMEM((bh, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(q4.shape, q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      *operands)
    return out[:, :, 0, :]


def decode_attention_reference(q, k_pages, v_pages, page_table,
                               lengths, sm_scale, k_scale=None,
                               v_scale=None):
    """The jnp gather-attention reference (and the family's built-in
    default impl): gather each slot's pages, mask past the context
    length, exact fp32 softmax. Inactive slots (length 0) return 0 —
    the same fully-masked-row semantics as every attention kernel in
    ops/. ``k_scale``/``v_scale`` (``[h, pages]``, the int8 KV tier)
    gather through the SAME page table and dequantize at read."""
    b, h, d = q.shape
    ps = k_pages.shape[2]
    # [h, b, max_pages, ps, d] -> [b, h, S, d]
    k = k_pages[:, page_table].transpose(1, 0, 2, 3, 4).reshape(
        b, h, -1, d).astype(jnp.float32)
    v = v_pages[:, page_table].transpose(1, 0, 2, 3, 4).reshape(
        b, h, -1, d).astype(jnp.float32)
    if k_scale is not None:
        # [h, b, max_pages] -> [b, h, S] (one scale per page, repeated
        # over the page's positions)
        ks = jnp.repeat(k_scale[:, page_table].transpose(1, 0, 2)
                        .astype(jnp.float32), ps, axis=-1)
        vs = jnp.repeat(v_scale[:, page_table].transpose(1, 0, 2)
                        .astype(jnp.float32), ps, axis=-1)
        k = k * ks[..., None]
        v = v * vs[..., None]
    s = jnp.sum(
        (q.astype(jnp.float32) * jnp.float32(sm_scale))[:, :, None, :]
        * k, axis=-1)                              # [b, h, S]
    col = jnp.arange(s.shape[-1], dtype=jnp.int32)[None, None, :]
    masked = col >= lengths.astype(jnp.int32)[:, None, None]
    s = jnp.where(masked, NEG_INF, s)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    e = jnp.where(masked, 0.0, e)
    tot = jnp.sum(e, axis=-1, keepdims=True)
    p = jnp.where(tot > 0, e / jnp.where(tot > 0, tot, 1.0), 0.0)
    return jnp.sum(p[..., None] * v, axis=2).astype(q.dtype)


def _effective_impl(impl, q, k_pages, page_table):
    """``(impl, from_table, tile_pref)``: per-call > setter > env >
    dispatch-table entry for this cache-geometry bucket > built-in
    "jnp". A table "pallas" measured on CPU runs in interpret mode —
    the way it was measured (same contract as ops.attention)."""
    if impl is not None:
        return impl, False, None
    pref = _IMPL or _env_impl()
    if pref is not None:
        return pref, False, None
    from apex_tpu import dispatch

    b, h, d = q.shape
    choice, params = dispatch.lookup_params(
        "decode_attention", dtype=q.dtype, b=b, h=h,
        pages=page_table.shape[1], ps=k_pages.shape[2], d=d)
    pref_t = tuple(sorted(params.items())) if params else None
    if choice:
        return choice, True, pref_t
    return "jnp", False, pref_t


def decode_attention(q, k_pages, v_pages, page_table, lengths, *,
                     sm_scale=None, k_scale=None, v_scale=None,
                     impl=None, block_h=None,
                     interpret=None, tile_pref=None):
    """Dispatched paged decode attention (q: [b, h, d]; pages:
    [h, P, ps, d]; page_table: [b, max_pages]; lengths: [b]).

    ``impl`` is a per-call DEMAND ("jnp" | "pallas"; "pallas" on an
    unsupported geometry raises); ``set_decode_impl`` /
    ``APEX_DECODE_ATTN_IMPL`` are preferences that fall back, and an
    unpinned call consults the dispatch table (op "decode_attention").
    ``block_h`` is the per-call tile demand (raises when illegal);
    ``interpret`` defaults to off-TPU autodetect for explicitly
    requested or table-driven pallas runs. ``k_scale``/``v_scale``
    (``[h, P]``) engage the int8 KV tier's dequantize-at-read on
    either impl; int8 pages WITHOUT scales raise — codes are
    meaningless without their scales, there is no honorable
    fallback."""
    if sm_scale is None:
        import math

        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if impl is not None and impl not in ("jnp", "pallas"):
        raise ValueError(f"unknown decode-attention impl {impl!r}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("decode_attention: k_scale and v_scale come "
                         "as a pair (one of them is missing)")
    if k_scale is None and k_pages.dtype == jnp.int8:
        raise ValueError(
            "decode_attention: int8 pages without k_scale/v_scale — "
            "quantized codes are meaningless without their scales")
    eff, from_table, pref_t = _effective_impl(impl, q, k_pages,
                                              page_table)
    if tile_pref:
        merged = dict(pref_t or ())
        merged.update(dict(tile_pref))
        pref_t = tuple(sorted(merged.items()))
    b, h, d = q.shape
    ok = supported(h, k_pages.shape[1], k_pages.shape[2], d,
                   k_pages.dtype)
    if eff == "pallas" and not ok and impl == "pallas":
        raise ValueError(
            f"decode_attention: impl='pallas' cannot be honored for "
            f"h={h} ps={k_pages.shape[2]} d={d}")
    if eff == "pallas" and ok:
        if interpret is None:
            try:
                interpret = jax.devices()[0].platform != "tpu"
            except RuntimeError:
                interpret = True
        return decode_attention_pallas(
            q, k_pages, v_pages, page_table, lengths, sm_scale,
            k_scale=k_scale, v_scale=v_scale,
            block_h=block_h, interpret=interpret, tile_pref=pref_t)
    # the jnp path is what actually runs from here on: an explicit
    # per-call tile demand cannot be honored on it, whatever
    # preference resolved the impl (a "pallas" setter/table choice
    # that fell back on unsupported geometry included) — per-call
    # raises, preferences fall back
    if block_h is not None:
        raise ValueError("decode_attention: block_h tiles the pallas "
                         "kernel; it cannot be honored on the jnp path")
    return decode_attention_reference(q, k_pages, v_pages, page_table,
                                      lengths, sm_scale,
                                      k_scale=k_scale, v_scale=v_scale)
