"""Fused (flash) attention for TPU.

The TPU-native replacement for the reference's attention kernel zoo —
``fmhalib`` (contrib/csrc/fmha, 6,958 LoC), ``fast_multihead_attn``
(8,010 LoC) and the three megatron softmax kernels (SURVEY §2.6): ONE
blockwise-softmax attention with causal and segment-id (varlen) masking.

On TPU this lowers to the Pallas flash-attention kernel (memory-bound
optimal: no [s, s] score tensor ever touches HBM; fwd and bwd are tiled
VMEM-resident loops with fp32 online-softmax accumulators). Elsewhere
(CPU test mesh) it falls back to the numerically-equivalent dense form.

Layout: [batch, heads, seq, head_dim] (the kernel's native layout).
"""

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _dense_attention(q, k, v, causal, sm_scale, segment_ids):
    """Reference semantics (the flash kernel's mha_reference): fp32
    softmax, masked positions excluded, fully-masked rows → 0."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scores = lax.dot_general(
        q, k, (((3,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32) * sm_scale
    mask = None
    if causal:
        mask = jnp.arange(sk)[None, :] > jnp.arange(sq)[:, None]
        mask = jnp.broadcast_to(mask, scores.shape)
    if segment_ids is not None:
        seg_q, seg_kv = segment_ids
        diff = seg_q[:, None, :, None] != seg_kv[:, None, None, :]
        diff = jnp.broadcast_to(diff, scores.shape)
        mask = diff if mask is None else (mask | diff)
    if mask is not None:
        scores = jnp.where(mask, jnp.finfo(jnp.float32).min, scores)
    m = jnp.max(scores, axis=-1, keepdims=True)
    e = jnp.exp(scores - m)
    if mask is not None:
        e = jnp.where(mask, 0.0, e)
    s = jnp.sum(e, axis=-1, keepdims=True)
    probs = jnp.where(s > 0, e / jnp.where(s > 0, s, 1.0), 0.0)
    return lax.dot_general(
        probs.astype(v.dtype), v, (((3,), (2,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32).astype(q.dtype)


@functools.lru_cache(maxsize=1)
def _tpu_available():
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


def _block(n, cap):
    """Largest power-of-two block ≤ cap dividing n (≥ MIN_BLOCK_SIZE)."""
    b = 1
    while b * 2 <= cap and n % (b * 2) == 0:
        b *= 2
    return b


def flash_supported(sq, sk):
    """Whether ``fused_attention`` will take the Pallas flash path for
    these sequence lengths on the current backend (else the XLA-fused
    dense path). Public so harnesses/labels stay truthful by
    construction."""
    return _tpu_available() and sq % 128 == 0 and sk % 128 == 0


# Which TPU kernel backs fused_attention when both can: "flash" (the
# bundled multi-pass kernel, tuned blocks) or "rows" (the self-authored
# VMEM-row kernel, ops/attention_pallas.py). The default is whichever won
# benchmarks/profile_attention.py's fwd+d(q,k,v) decision row on the
# round's hardware (PERF.md); set_default_impl flips it process-wide.
# When neither a per-call impl nor the setter pins the choice, the
# per-shape dispatch table (apex_tpu.dispatch, op "attention") is
# consulted at trace time; a table miss lands on _DEFAULT_IMPL.
_DEFAULT_IMPL = "flash"
_IMPL_PINNED = False  # True once set_default_impl was called


def set_default_impl(impl):
    """Select the TPU kernel behind ``fused_attention``: "flash" or
    "rows" (shapes the chosen kernel can't handle still fall through
    flash → dense). Pins the choice process-wide — the dispatch table
    is no longer consulted (precedence: per-call > this setter > table
    > built-in)."""
    global _DEFAULT_IMPL, _IMPL_PINNED
    if impl not in ("flash", "rows"):
        raise ValueError(f"unknown attention impl {impl!r}")
    _DEFAULT_IMPL = impl
    _IMPL_PINNED = True


def reset_default_impl():
    """Back to the unpinned built-in default (tests / knob teardown)."""
    global _DEFAULT_IMPL, _IMPL_PINNED
    _DEFAULT_IMPL = "flash"
    _IMPL_PINNED = False


def _effective_impl_params(impl, q, k):
    """``(impl, from_table, tile_params)`` for one call: per-call
    ``impl`` > ``set_default_impl`` > dispatch-table entry for this
    shape bucket > built-in. Table entries are preferences (measured on
    this backend, keyed by shape bucket); unsupported shapes still fall
    through rows → flash → dense downstream. ``from_table`` lets the
    rows branch run a CPU-measured table choice in interpret mode — the
    way it was measured. ``tile_params`` is the entry's tile payload
    (block_q/...), handed to the rows kernel as a PREFERENCE — illegal
    tiles for the real shape fall back to the kernel heuristic there."""
    if impl is not None:
        return impl, False, None
    if _IMPL_PINNED:
        return _DEFAULT_IMPL, False, None
    from apex_tpu import dispatch

    choice, params = dispatch.lookup_params(
        "attention", dtype=q.dtype, b=q.shape[0], h=q.shape[1],
        sq=q.shape[2], sk=k.shape[2], d=q.shape[3])
    if choice:
        return choice, True, params
    # a params-only entry (tile measured for the shipped default impl)
    # still feeds the kernel's tile preference
    return _DEFAULT_IMPL, False, params


def _effective_impl(impl, q, k):
    """``(impl, from_table)`` — the choice half of
    :func:`_effective_impl_params` (kept for its callers/tests)."""
    return _effective_impl_params(impl, q, k)[:2]


def fused_attention(q, k, v, *, causal=False, sm_scale=None,
                    segment_ids=None, force_dense=None, impl=None):
    """Flash attention.

    Args:
      q, k, v: [b, h, s, d].
      causal: apply the lower-triangular mask.
      sm_scale: softmax scale; default 1/sqrt(d).
      segment_ids: optional (seg_q [b, sq], seg_kv [b, sk]) int arrays —
        tokens attend only within equal ids (varlen/packed batches; the
        fmha cu_seqlens capability).
      force_dense: force the XLA-fused dense path (tests / tiny shapes).
      impl: override the kernel choice for this call ("flash" | "rows");
        default is the measured process-wide default (set_default_impl).

    The Pallas paths require seq divisible by 128; other shapes (and
    non-TPU backends) use the XLA dense path.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if impl is not None and impl not in ("flash", "rows"):
        raise ValueError(f"unknown attention impl {impl!r}")
    sq, sk = q.shape[2], k.shape[2]
    # force_dense never consults the table: a consult the caller ignores
    # would still land in the dispatch.snapshot() consult log and
    # mislabel what a dense-baseline row actually ran
    eff_impl, from_table, tile_params = (
        ("flash", False, None) if force_dense
        else _effective_impl_params(impl, q, k))
    if eff_impl == "rows" and not force_dense:
        from apex_tpu.dispatch import tiles
        from apex_tpu.ops import attention_pallas as ap

        # the *default* dispatch caps the rows kernel at the fmha-style
        # moderate-seq envelope (beyond ~2k keys the multi-pass flash
        # kernel's causal skip + bounded unroll win back what the
        # single-pass structure saves); an explicit per-call impl="rows"
        # is honored for every supported shape so A/B rows stay truthful
        seq_ok = impl == "rows" or sk <= 2048
        # off-TPU the kernel can still run in interpret mode when the
        # choice came from a (backend-keyed, CPU-measured) table entry
        # or the pinned-A/B CPU leg asks for it (autotune --smoke) —
        # never silently: a "rows" label over a dense run is label drift
        interp = (not _tpu_available()
                  and (from_table
                       or tiles.env_flag("APEX_PALLAS_INTERPRET")))
        if ((_tpu_available() or interp) and seq_ok
                and ap.supported(sq, sk, q.shape[-1])):
            # table tile params ride as a PREFERENCE tuple (hashable —
            # custom_vjp nondiff arg); the kernel validates per shape
            # and falls back to its heuristic on an illegal tile
            pref = tuple(sorted(tile_params.items())) if tile_params \
                else None
            return ap.fused_attention_rows(q, k, v, causal,
                                           float(sm_scale), segment_ids,
                                           interp, tile_pref=pref)
    use_flash = flash_supported(sq, sk) and not force_dense
    if not use_flash:
        return _dense_attention(q, k, v, causal, sm_scale, segment_ids)

    from jax.experimental.pallas.ops.tpu import flash_attention as fa

    # Tuned on v5e (benchmarks/profile_attention.py, PERF.md): large q
    # blocks (fewer grid steps per head) with 512-wide k blocks beat the
    # kernel defaults ~3x at GPT shapes; block_b>1 doesn't help and big
    # values fail to compile.
    bq = _block(sq, 1024)
    blk = _block(min(sq, sk), 512)
    bs = fa.BlockSizes(
        block_q=bq, block_k_major=blk, block_k=blk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=blk,
        block_k_dkv=blk, block_q_dkv=bq,
        block_k_major_dq=blk, block_k_dq=blk, block_q_dq=bq)
    seg = None
    if segment_ids is not None:
        seg = fa.SegmentIds(q=segment_ids[0].astype(jnp.int32),
                            kv=segment_ids[1].astype(jnp.int32))
    return fa.flash_attention(q, k, v, segment_ids=seg, causal=causal,
                              sm_scale=float(sm_scale), block_sizes=bs)
