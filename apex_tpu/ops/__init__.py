"""apex_tpu.ops — Pallas TPU kernels for the hot ops.

The L0 tier of the TPU build: where the reference ships CUDA kernels
(csrc/, contrib/csrc — SURVEY §2.6), this package ships Pallas kernels /
kernel wrappers with XLA-fusion fallbacks. Ops dispatch on the backend so
the same model code runs on the CPU test mesh and on TPU.
"""

from apex_tpu.ops.attention import fused_attention  # noqa: F401
from apex_tpu.ops.context_parallel import (  # noqa: F401
    ring_attention,
    ulysses_attention,
)
from apex_tpu.ops.decode_attention_pallas import (  # noqa: F401
    decode_attention,
)
from apex_tpu.ops import layer_norm_pallas  # noqa: F401
from apex_tpu.ops import softmax_pallas  # noqa: F401
