"""Context (sequence) parallel attention: ring + all-to-all (Ulysses).

Long-sequence scaling beyond a single chip's HBM: the sequence dimension
is sharded over a mesh axis ("cp") and attention runs SPMD. Two canonical
schemes, both TPU-first (XLA collectives over ICI; no NCCL analog of the
reference required — the reference scales sequence only via Megatron
sequence-parallel scatter/gather around the norms, tensor_parallel/
mappings.py, which apex_tpu also ships):

  * ``ring_attention`` — blockwise online-softmax attention; K/V blocks
    rotate around the ring via ``lax.ppermute`` while each rank's Q stays
    resident. O(s_local²·cp) compute per rank, O(s_local) memory. The
    BACKWARD ring is not hand-written: differentiating through the
    scan+ppermute reverses the permutation (same design as the pipeline
    schedules — schedules.py) and replays blocks in reverse.
  * ``ulysses_attention`` — DeepSpeed-Ulysses-style: ``lax.all_to_all``
    re-shards [seq-sharded, heads full] into [heads-sharded, seq full],
    runs ordinary (flash) attention on whole sequences per head group,
    and all-to-alls back. Needs heads % cp == 0; one pair of all-to-alls
    per call, attention itself is the single-chip kernel (ops.attention).

Numerics: fp32 online-softmax accumulators (same as the flash kernel);
causal masking across ring blocks is exact (diagonal block triangular,
future blocks fully masked). Parity + grad tests vs dense attention on
the gathered sequence: tests/test_context_parallel.py.
"""

import math

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.ops.attention import fused_attention

NEG_INF = -1e30


def ring_attention(q, k, v, axis_name, *, causal=True, sm_scale=None,
                   dropout_p=0.0, dropout_seed=None):
    """Ring attention over sequence shards.

    Args:
      q, k, v: [b, h, s_local, d] — this rank's sequence shard. The global
        sequence is the axis-order concatenation of shards.
      axis_name: mesh axis the sequence is sharded over (inside shard_map).
      causal: apply the global lower-triangular mask.
      sm_scale: softmax scale; default 1/sqrt(d).
      dropout_p / dropout_seed: inverted attention-probability dropout,
        applied INSIDE the ring with the same coordinate-chained hash as
        the rows kernel (attention_pallas._dropout_mscale, keyed on
        GLOBAL (b, h, row, col)) — every rank regenerates its slice of
        one consistent global mask, and dropping the unnormalized block
        probs while accumulating the UNdropped row sums is exactly
        dropout on the normalized probabilities. ``dropout_seed`` must be
        the same traced int32 scalar on every rank.

    Returns [b, h, s_local, d] in q.dtype.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if not 0.0 <= dropout_p < 1.0:
        raise ValueError(f"dropout_p={dropout_p} outside [0, 1)")
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed")
    cp = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s, d = q.shape
    qf = q.astype(jnp.float32) * sm_scale

    # ppermute sends rank i's block to i+1; after r hops this rank holds
    # the block that originated at rank (idx - r) mod cp
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def step(carry, r):
        o, m, l, k_cur, v_cur = carry
        src = (idx - r) % cp

        scores = lax.dot_general(
            qf, k_cur.astype(jnp.float32),
            (((3,), (3,)), ((0, 1), (0, 1))))  # [b, h, s, s]
        if causal:
            tri = (jnp.arange(s)[None, :] > jnp.arange(s)[:, None])
            # src == idx: triangular; src > idx: fully masked (global
            # future); src < idx: fully visible (global past)
            block_mask = jnp.where(
                src == idx, tri,
                jnp.broadcast_to(src > idx, (s, s)))
            scores = jnp.where(block_mask[None, None], NEG_INF, scores)

        blk_max = jnp.max(scores, axis=-1)  # [b, h, s]
        m_new = jnp.maximum(m, blk_max)
        # renormalize the running accumulator to the new max
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        if causal:
            p = jnp.where(block_mask[None, None], 0.0, p)
        l_new = l * alpha + jnp.sum(p, axis=-1)   # UNdropped row sums
        pd = p
        if dropout_p > 0.0:
            from apex_tpu.ops.attention_pallas import _dropout_mscale

            mscale = jax.vmap(lambda ib: jax.vmap(
                lambda ih: _dropout_mscale(
                    dropout_seed, ib, ih, idx * s, s, s, dropout_p, h,
                    col0=src * s))(jnp.arange(h)))(jnp.arange(b))
            pd = p * mscale
        o_new = o * alpha[..., None] + lax.dot_general(
            pd, v_cur.astype(jnp.float32),
            (((3,), (2,)), ((0, 1), (0, 1))))

        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    o0 = jnp.zeros((b, h, s, d), jnp.float32)
    m0 = jnp.full((b, h, s), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(cp))
    l = jnp.where(l > 0, l, 1.0)  # fully-masked rows (none when causal)
    return (o / l[..., None]).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, *, causal=True, sm_scale=None,
                      dropout_p=0.0, dropout_seed=None, segment_ids=None,
                      **attn_kwargs):
    """All-to-all (Ulysses) context-parallel attention.

    Args/returns as ``ring_attention``. Requires ``h % cp == 0``: the
    all-to-all trades the sequence sharding for a head sharding, each rank
    then runs the ordinary fused attention kernel over FULL sequences for
    its h/cp heads, and the reverse all-to-all restores sequence sharding.

    ``segment_ids``: shard-local ``(seg_q [b, s_loc], seg_kv [b, s_loc])``
    (or one array for both) — packed varlen batches, exactly the serving
    prefill input shape (ISSUE 10; reference capability:
    apex/contrib/fmha packed cu_seqlens). The ids ride their own
    re-shard: while q/k/v trade sequence sharding for head sharding
    through the all-to-all, the ids are head-independent, so an
    ``all_gather`` along the same axis (axis-order concatenation —
    identical to the all_to_all's sequence order) rebuilds the GLOBAL
    id row every head group needs; the per-head-group kernel then masks
    cross-segment pairs exactly like the single-chip path. Parity vs a
    per-segment dense reference: tests/test_context_parallel.py.

    ``dropout_p``/``dropout_seed``: inverted attention dropout via the
    VMEM-rows kernel's in-kernel hash (each rank owns DISJOINT global
    heads, so the per-rank mask streams are decorrelated by folding the
    rank into the seed). Requires rows-kernel-supported shapes — the
    materialized fallback at Ulysses-scale sequences is the HBM blow-up
    this scheme exists to avoid, so unsupported shapes raise.
    """
    cp = lax.axis_size(axis_name)
    b, h, s, d = q.shape
    if h % cp != 0:
        raise ValueError(f"ulysses_attention: heads ({h}) not divisible by "
                         f"axis size ({cp})")
    if not 0.0 <= dropout_p < 1.0:
        raise ValueError(f"dropout_p={dropout_p} outside [0, 1)")
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed")

    def scatter_heads(x):
        # [b, h, s_loc, d] -> [b, h/cp, s_glob, d]: split heads across the
        # axis, gather sequence. all_to_all splits dim 1, concats dim 2.
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def gather_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    seg_glob = None
    if segment_ids is not None:
        seg_q, seg_kv = (segment_ids if isinstance(segment_ids,
                                                   (tuple, list))
                         else (segment_ids, segment_ids))
        seg_glob = tuple(
            lax.all_gather(sg.astype(jnp.int32), axis_name, axis=1,
                           tiled=True)
            for sg in (seg_q, seg_kv))

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    if dropout_p > 0.0:
        from apex_tpu.ops import attention_pallas

        # an explicitly-passed default (e.g. force_dense=None) IS its
        # default — only non-default demands are un-honorable
        demands = {k: v for k, v in attn_kwargs.items() if v is not None}
        if demands:
            # per-call knobs are demands, not preferences (CLAUDE.md):
            # the dropout branch runs the rows kernel unconditionally,
            # so an explicit impl=/force_dense= cannot be honored
            raise ValueError(
                f"ulysses_attention: kwargs {sorted(demands)} cannot "
                "be honored with dropout_p > 0 (the dropout branch runs "
                "the rows kernel)")
        s_glob = qh.shape[2]
        if not attention_pallas.supported(s_glob, s_glob, d, dropout=True):
            raise NotImplementedError(
                f"ulysses_attention dropout needs rows-kernel-supported "
                f"shapes (s={s_glob}, d={d}); the materialized fallback "
                "would defeat the scheme's memory purpose")
        # rank folded through the avalanche, not added: seed + rank has
        # additive pre-image collisions (step t, rank r+1 == step t+1,
        # rank r for consecutive caller seeds), replaying one head
        # group's mask stream on another
        from apex_tpu.ops.attention_pallas import _fmix32

        rank_u = lax.axis_index(axis_name).astype(jnp.uint32)
        seed = lax.bitcast_convert_type(
            jnp.asarray(dropout_seed, jnp.int32).astype(jnp.uint32)
            ^ _fmix32(rank_u + jnp.uint32(0x9E3779B9)),
            jnp.int32).reshape(1, 1)
        ctx = attention_pallas.fused_attention_rows(
            qh, kh, vh, causal,
            sm_scale if sm_scale is not None else 1.0 / math.sqrt(d),
            seg_glob, jax.devices()[0].platform == "cpu", None, None,
            float(dropout_p), seed)
    else:
        ctx = fused_attention(qh, kh, vh, causal=causal, sm_scale=sm_scale,
                              segment_ids=seg_glob, **attn_kwargs)
    return gather_heads(ctx)
