"""Pallas TPU row layer-norm kernel (fwd + bwd).

The TPU counterpart of the reference's hand-written LN kernels
(csrc/layer_norm_cuda_kernel.cu:68-260 warp-shuffle Welford;
contrib/csrc/layer_norm/ln_fwd/bwd_kernels.cuh "FastLayerNorm"). One VMEM
pass per row block: fp32 statistics, normalize, affine — fwd saves only
the [rows, 1] (mean, rstd) stat columns (2-D so the blocks satisfy
Mosaic's last-two-dims rule); bwd recomputes x̂ from x and produces dx
plus per-block [nblocks, 1, hidden] (dw, db) partial sums reduced
outside the kernel.

LayerNorm is HBM-bandwidth-bound, so the jnp path (XLA-fused) is already
near the roofline for most shapes (measured — PERF.md §4);
``fused_layer_norm`` dispatches to whichever side the measurement favors.
This kernel exists to (a) prove the claim either way with a real
alternative, (b) serve the very-wide-row regime where XLA's reduction
splitting is weakest, and (c) back ``contrib.layer_norm.FastLayerNorm``
with an actual kernel.

Tested against the jnp reference in Pallas interpret mode on CPU
(tests/test_layer_norm_pallas.py); block sizes sized to VMEM.

Tile geometry is a dispatch axis (the measured-dispatch rule one level
below impl choice): the row block ``br`` resolves per call as

    per-call ``block_rows``  (raises on an illegal tile)
  > ``set_block_rows`` / ``APEX_LN_BLOCK_ROWS``  (preference — an
    illegal tile for this shape falls back per shape)
  > table ``block_rows_pref``  (the dispatch-table ``params`` payload
    the consumer passes down; same fallback semantics)
  > the VMEM-model heuristic (``tiles.ln_row_block`` — UNCHANGED)

with legality judged by the shared model in
``apex_tpu.dispatch.tiles`` (the same model ``check_bench_labels``
check 4 holds committed payloads to).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.dispatch import tiles

# the VMEM budget and working-set counts live in the shared tile model
# (apex_tpu/dispatch/tiles.py) so the sweeper, the checker and this
# lowering can never disagree; these names remain for their users
_VMEM_BUDGET = tiles.LN_VMEM_BUDGET
_FWD_ARRAYS = tiles.LN_FWD_ARRAYS
_BWD_ARRAYS = tiles.LN_BWD_ARRAYS


def _row_block(rows, hidden, n_arrays):
    """The heuristic row block (shared model; 0 → no valid blocking)."""
    cap = max(1, _VMEM_BUDGET // (4 * hidden * n_arrays))
    b = tiles.chain_block(rows, cap)
    return b if b >= 8 else 0


# Process-wide row-block *preference* (tri-state: None = unpinned).
# Like every process-wide setter it falls back per shape; only the
# per-call ``block_rows=`` raises on an un-honorable tile.
_BLOCK_ROWS = None


def set_block_rows(value):
    """Pin the process-wide row-block preference (int), or un-pin with
    None (table params / the heuristic apply again). Shapes the pinned
    tile can't legally block fall back to the heuristic silently."""
    global _BLOCK_ROWS
    tiles.check_setter_value(value, "block_rows")
    _BLOCK_ROWS = value


def _env_block_rows():
    """Trace-time APEX_LN_BLOCK_ROWS (shared parser: tiles.env_int —
    an env knob is a preference, not a per-call raise)."""
    return tiles.env_int("APEX_LN_BLOCK_ROWS")


def _resolve_br(rows, hidden, block_rows, block_rows_pref):
    """The resolved row block for one call, or None when no knob
    resolves — the fwd and bwd heuristics then apply UNCHANGED (they
    size to different working sets; a resolved tile is used by both
    passes and its legality is gated on the bwd — binding — model)."""
    dims = {"rows": rows, "hidden": hidden}
    if block_rows is not None:
        problems = tiles.legal("layer_norm", dims, None,
                               {"block_rows": block_rows})
        if problems:
            raise ValueError("layer_norm_pallas: illegal block_rows: "
                             + "; ".join(problems))
        return block_rows
    for pref in (_BLOCK_ROWS, _env_block_rows(), block_rows_pref):
        if pref is not None and not tiles.legal(
                "layer_norm", dims, None, {"block_rows": pref}):
            return pref
    return None


def _fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps,
                has_w, has_b):
    x = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=1)
    xc = x - mean[:, None]
    var = jnp.mean(xc * xc, axis=1)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd[:, None]
    if has_w:
        y = y * w_ref[...].astype(jnp.float32)[None, :]
    if has_b:
        y = y + b_ref[...].astype(jnp.float32)[None, :]
    y_ref[...] = y.astype(y_ref.dtype)
    # stats are [br, 1] 2-D: a rank-1 (br,) block is lane-dim under
    # Mosaic's last-two-dims rule and only legal when br % 128 == 0 or
    # br == rows; sublane-major [rows, 1] is legal for every br >= 8
    mean_ref[...] = mean[:, None]
    rstd_ref[...] = rstd[:, None]


def _bwd_kernel(x_ref, w_ref, mean_ref, rstd_ref, dy_ref, dx_ref, dw_ref,
                db_ref, *, has_w):
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    mean = mean_ref[...]          # [br, 1] — see _fwd_kernel's note
    rstd = rstd_ref[...]
    xhat = (x - mean) * rstd
    wg = dy * w_ref[...].astype(jnp.float32)[None, :] if has_w else dy
    m1 = jnp.mean(wg, axis=1)
    m2 = jnp.mean(wg * xhat, axis=1)
    dx = (wg - m1[:, None] - xhat * m2[:, None]) * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)
    # per-block affine-grad partials, reduced over blocks by the caller.
    # [nblocks, 1, hidden] with (1, 1, hidden) blocks: a 2-D (1, hidden)
    # block over [nblocks, hidden] puts a bare 1 against the block axis
    # and fails Mosaic's last-two-dims rule on device
    dw_ref[...] = jnp.sum(dy * xhat, axis=0)[None, None, :]
    db_ref[...] = jnp.sum(dy, axis=0)[None, None, :]


def supported(rows, hidden):
    """Whether the kernel handles this shape (else jnp fallback). Gated on
    the backward kernel's (larger) VMEM footprint so a shape accepted here
    never fails to compile mid-training."""
    return hidden % 128 == 0 and _row_block(rows, hidden, _BWD_ARRAYS) != 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def layer_norm(x2d, weight, bias, eps=1e-5, interpret=False,
               block_rows=None, block_rows_pref=None):
    """Row layer-norm over the last dim of ``x2d`` [rows, hidden].

    ``weight``/``bias`` may be None (plain normalization). Statistics and
    affine math in fp32; output in ``x2d.dtype``. Use ``supported`` first;
    unsupported shapes raise. ``interpret=True`` runs the kernel in Pallas
    interpret mode (CPU tests).

    ``block_rows``: per-call row-block demand — raises when the tile is
    illegal for this shape (divisibility / VMEM model, see
    ``apex_tpu.dispatch.tiles``). ``block_rows_pref``: preference form
    (the dispatch-table params consumer passes it) — an illegal tile
    falls back silently; ``set_block_rows``/``APEX_LN_BLOCK_ROWS``
    resolve above it, the built-in heuristic below it.
    """
    y, _ = _fwd(x2d, weight, bias, eps, interpret, block_rows,
                block_rows_pref)
    return y


def _fwd(x2d, weight, bias, eps, interpret, block_rows=None,
         block_rows_pref=None):
    rows, hidden = x2d.shape
    if not supported(rows, hidden):
        raise ValueError(f"layer_norm_pallas: unsupported shape {x2d.shape}")
    br = _resolve_br(rows, hidden, block_rows, block_rows_pref)
    if br is None:
        br = _row_block(rows, hidden, _FWD_ARRAYS)
    has_w = weight is not None
    has_b = bias is not None
    w_in = weight if has_w else jnp.zeros((hidden,), jnp.float32)
    b_in = bias if has_b else jnp.zeros((hidden,), jnp.float32)

    grid = (rows // br,)
    y, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps, has_w=has_w, has_b=has_b),
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, hidden), x2d.dtype),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2d, w_in, b_in)
    return y, (x2d, w_in, mean, rstd, has_w, has_b)


def _fwd_rule(x2d, weight, bias, eps, interpret, block_rows=None,
              block_rows_pref=None):
    y, res = _fwd(x2d, weight, bias, eps, interpret, block_rows,
                  block_rows_pref)
    return y, res


def _bwd_rule(eps, interpret, block_rows, block_rows_pref, res, dy):
    x2d, w_in, mean, rstd, has_w, has_b = res
    rows, hidden = x2d.shape
    br = _resolve_br(rows, hidden, block_rows, block_rows_pref)
    if br is None:
        br = _row_block(rows, hidden, _BWD_ARRAYS)
    grid = (rows // br,)
    dx, dw_part, db_part = pl.pallas_call(
        functools.partial(_bwd_kernel, has_w=has_w),
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, 1, hidden), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, hidden), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, hidden), x2d.dtype),
            jax.ShapeDtypeStruct((rows // br, 1, hidden), jnp.float32),
            jax.ShapeDtypeStruct((rows // br, 1, hidden), jnp.float32),
        ],
        interpret=interpret,
    )(x2d, w_in, mean, rstd, dy)
    dw = jnp.sum(dw_part, axis=(0, 1)) if has_w else None
    db = jnp.sum(db_part, axis=(0, 1)) if has_b else None
    return dx, dw, db


layer_norm.defvjp(_fwd_rule, _bwd_rule)
