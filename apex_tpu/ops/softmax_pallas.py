"""Pallas TPU fused scale + mask + softmax kernel (fwd + bwd).

TPU counterpart of the reference's three megatron softmax kernels
(csrc/megatron/scaled_upper_triang_masked_softmax.{h,cu},
scaled_masked_softmax.{h,cu}, generic_scaled_masked_softmax.{h,cu}): one
VMEM pass per row block computing ``softmax(scale * x + mask)`` in fp32
with masked positions emitted as exactly 0 (fully-masked rows become
all-zero rows, matching the CUDA kernels), output in the input dtype.

Layout: ``x`` is [b, np, sq, sk]; the grid tiles (b, np, sq-blocks) and an
explicit boolean mask of shape [b, 1|np, sq, sk] is broadcast over the
head axis by the BlockSpec index map — the mask is read once per head
from HBM but never materialized at [b, np, sq, sk]. The causal variant
derives its mask from row/col iota in-register (no mask operand at all).

Backward is the softmax VJP on the saved probabilities,
``dx = scale * y * (g - sum(g * y))``; masked positions have y == 0 so no
mask is needed in the backward kernel (also exactly how the reference's
bwd kernels work on the saved softmax results).

The jnp path (transformer/functional/fused_softmax.py) stays the default:
XLA fuses the same chain into one loop, and softmax is HBM-bound. This
kernel (a) proves the "XLA fusion is enough" claim with a real
alternative measured by benchmarks/profile_softmax.py, (b) guarantees the
fusion (no reliance on XLA heuristics) for the dense-attention path, and
(c) gives FusedScaleMaskSoftmax a genuine kernel behind its dispatch
predicate. Tested against the jnp reference in interpret mode
(tests/test_softmax_pallas.py).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.dispatch import tiles

# budget/working-set constants live in the shared tile model
# (apex_tpu/dispatch/tiles.py) — sweeper, checker and lowering agree
_VMEM_BUDGET = tiles.SM_VMEM_BUDGET
_FWD_ARRAYS = tiles.SM_FWD_ARRAYS
_BWD_ARRAYS = tiles.SM_BWD_ARRAYS


def _sq_block(sq, sk, n_arrays):
    """The heuristic sq block (shared model; 0 → unsupported)."""
    cap = max(1, _VMEM_BUDGET // (4 * sk * n_arrays))
    b = tiles.chain_block(sq, cap)
    return b if b >= 8 else 0


# Process-wide row-block preference (tri-state; falls back per shape —
# only the per-call ``block_rows=`` raises on an illegal tile)
_BLOCK_ROWS = None


def set_block_rows(value):
    """Pin the process-wide sq-block preference (int), or un-pin with
    None. Shapes the pinned tile can't block fall back silently."""
    global _BLOCK_ROWS
    tiles.check_setter_value(value, "block_rows")
    _BLOCK_ROWS = value


def _env_block_rows():
    return tiles.env_int("APEX_SOFTMAX_BLOCK_ROWS")


def _resolve_bsq(sq, sk, block_rows, block_rows_pref):
    """Resolved sq block, or None (heuristics apply unchanged):
    per-call (raise) > setter/env (fall back) > table pref (fall back).
    Legality via the shared model, gated on the bwd working set; a
    resolved tile is used by BOTH passes."""
    dims = {"b": 1, "h": 1, "sq": sq, "sk": sk}
    if block_rows is not None:
        problems = tiles.legal("softmax", dims, None,
                               {"block_rows": block_rows})
        if problems:
            raise ValueError("softmax_pallas: illegal block_rows: "
                             + "; ".join(problems))
        return block_rows
    for pref in (_BLOCK_ROWS, _env_block_rows(), block_rows_pref):
        if pref is not None and not tiles.legal(
                "softmax", dims, None, {"block_rows": pref}):
            return pref
    return None


def supported(sq, sk):
    """Whether the kernel handles [.., sq, sk] rows (else jnp fallback).
    Gated on the backward footprint so accepted shapes never fail to
    compile mid-training; sk must be lane-aligned."""
    return sk % 128 == 0 and _sq_block(sq, sk, _BWD_ARRAYS) != 0


def mask_supported(mask, x_shape):
    """Whether ``mask`` has one of the two shapes the kernel's BlockSpec
    broadcast handles ([b, 1, sq, sk] or [b, np, sq, sk]); other
    broadcastable shapes (e.g. key-padding [b, 1, 1, sk]) need the jnp
    fallback."""
    b, np_, sq, sk = x_shape
    return mask.shape in ((b, 1, sq, sk), (b, np_, sq, sk))


def _fwd_kernel(*refs, scale, causal, has_mask, bsq):
    x_ref, y_ref = refs[0], refs[-1]
    x = x_ref[...].astype(jnp.float32) * jnp.float32(scale)
    _, _, rows, sk = x.shape
    masked = None
    if has_mask:
        masked = refs[1][...] != 0
    if causal:
        isq = pl.program_id(2)
        row = isq * bsq + jax.lax.broadcasted_iota(jnp.int32, (rows, sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (rows, sk), 1)
        tri = (col > row)[None, None]
        masked = tri if masked is None else masked | tri
    if masked is not None:
        neg = jnp.asarray(jnp.finfo(jnp.float32).min, jnp.float32)
        x = jnp.where(masked, neg, x)
    e = jnp.exp(x - jnp.max(x, axis=-1, keepdims=True))
    if masked is not None:
        e = jnp.where(masked, 0.0, e)
    s = jnp.sum(e, axis=-1, keepdims=True)
    y = jnp.where(s > 0, e / jnp.where(s > 0, s, 1.0), 0.0)
    y_ref[...] = y.astype(y_ref.dtype)


def _bwd_kernel(y_ref, g_ref, dx_ref, *, scale):
    y = y_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    dot = jnp.sum(y * g, axis=-1, keepdims=True)
    dx_ref[...] = (jnp.float32(scale) * y * (g - dot)).astype(dx_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def scaled_masked_softmax(x, mask, scale=1.0, causal=False, interpret=False,
                          block_rows=None, block_rows_pref=None):
    """``softmax(scale * x [+ causal/explicit mask])`` over the last dim.

    ``x``: [b, np, sq, sk]. ``mask``: None or a boolean/int array of shape
    [b, 1, sq, sk] or [b, np, sq, sk] — nonzero = masked out. The causal
    triangle is generated in-register when ``causal``. Use ``supported``
    first; unsupported shapes raise. ``interpret=True`` runs in Pallas
    interpret mode (CPU tests).

    ``block_rows``: per-call sq-block demand (raises on an illegal tile
    — divisibility/VMEM model, ``apex_tpu.dispatch.tiles``).
    ``block_rows_pref``: preference form (table params) — falls back
    silently; ``set_block_rows``/``APEX_SOFTMAX_BLOCK_ROWS`` resolve
    above it, the heuristic below it.
    """
    y, _ = _fwd(x, mask, scale, causal, interpret, block_rows,
                block_rows_pref)
    return y


def _fwd(x, mask, scale, causal, interpret, block_rows=None,
         block_rows_pref=None):
    b, np_, sq, sk = x.shape
    if not supported(sq, sk):
        raise ValueError(f"softmax_pallas: unsupported shape {x.shape}")
    bsq = _resolve_bsq(sq, sk, block_rows, block_rows_pref)
    if bsq is None:
        bsq = _sq_block(sq, sk, _FWD_ARRAYS)
    has_mask = mask is not None
    grid = (b, np_, sq // bsq)
    blk = (1, 1, bsq, sk)

    in_specs = [pl.BlockSpec(blk, lambda ib, ih, js: (ib, ih, js, 0))]
    ops = [x]
    if has_mask:
        assert mask.shape in ((b, 1, sq, sk), (b, np_, sq, sk)), (
            f"mask shape {mask.shape} does not broadcast to {x.shape}")
        # head-broadcast happens in the index map: a [b, 1, sq, sk] mask is
        # re-read per head from HBM, never materialized per-head
        bcast_h = mask.shape[1] == 1
        mblk = (1, 1, bsq, sk)
        in_specs.append(pl.BlockSpec(
            mblk, (lambda ib, ih, js: (ib, 0, js, 0)) if bcast_h
            else (lambda ib, ih, js: (ib, ih, js, 0))))
        ops.append(mask.astype(jnp.int8))

    y = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          has_mask=has_mask, bsq=bsq),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec(blk, lambda ib, ih, js: (ib, ih, js, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(*ops)
    return y, y


def _fwd_rule(x, mask, scale, causal, interpret, block_rows=None,
              block_rows_pref=None):
    y, res = _fwd(x, mask, scale, causal, interpret, block_rows,
                  block_rows_pref)
    return y, res


def _bwd_rule(scale, causal, interpret, block_rows, block_rows_pref, y,
              g):
    b, np_, sq, sk = y.shape
    bsq = _resolve_bsq(sq, sk, block_rows, block_rows_pref)
    if bsq is None:
        bsq = _sq_block(sq, sk, _BWD_ARRAYS)
    blk = (1, 1, bsq, sk)
    spec = pl.BlockSpec(blk, lambda ib, ih, js: (ib, ih, js, 0))
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale),
        grid=(b, np_, sq // bsq),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(y.shape, y.dtype),
        interpret=interpret,
    )(y, g)
    # mask is non-differentiable (None or boolean)
    return dx, None


scaled_masked_softmax.defvjp(_fwd_rule, _bwd_rule)
