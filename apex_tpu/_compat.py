"""JAX version-compat shims.

This tree targets the current ``jax.shard_map(f, mesh=..., in_specs=...,
out_specs=..., check_vma=...)`` surface. On older jax (e.g. 0.4.x) that
API lives at ``jax.experimental.shard_map.shard_map`` with the
replication-check kwarg still named ``check_rep``; without a shim every
sharded code path — including ``bench.py`` and the 8-virtual-device test
mesh — fails with ``AttributeError: module 'jax' has no attribute
'shard_map'`` before running anything. :func:`install` bridges exactly
that gap and is a no-op wherever ``jax.shard_map`` already exists (the
shim never shadows a real implementation).
"""

import functools

import jax


def install():
    """Idempotently install the handful of current-jax surfaces this
    tree uses that an older jax spells differently. Each shim installs
    only when the real attribute is missing — never shadows one."""
    _install_shard_map()
    _install_axis_size()


def _install_shard_map():
    if hasattr(jax, "shard_map"):
        return
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError:  # neither surface: let call sites raise honestly
        return

    @functools.wraps(_shard_map)
    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kw):
        if check_vma is not None and "check_rep" not in kw:
            kw["check_rep"] = check_vma  # old name of the same knob
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)

    jax.shard_map = shard_map


def _install_axis_size():
    # lax.axis_size(name): the STATIC size of a mapped axis. On old jax
    # the same lookup lives on the trace-time axis env (a psum(1, name)
    # would be traced, breaking static uses like shape arithmetic).
    from jax import lax

    if hasattr(lax, "axis_size"):
        return

    def axis_size(axis_name):
        from jax._src import core

        env = core.get_axis_env()
        if isinstance(axis_name, (tuple, list)):
            size = 1
            for name in axis_name:
                size *= env.axis_size(name)
            return size
        return env.axis_size(axis_name)

    lax.axis_size = axis_size
