"""JAX version-compat shims.

This tree targets the current ``jax.shard_map(f, mesh=..., in_specs=...,
out_specs=..., check_vma=...)`` surface. On older jax (e.g. 0.4.x) that
API lives at ``jax.experimental.shard_map.shard_map`` with the
replication-check kwarg still named ``check_rep``; without a shim every
sharded code path — including ``bench.py`` and the 8-virtual-device test
mesh — fails with ``AttributeError: module 'jax' has no attribute
'shard_map'`` before running anything. :func:`install` bridges exactly
that gap and is a no-op wherever ``jax.shard_map`` already exists (the
shim never shadows a real implementation).

It is also the home of the **XLA analysis normalizers** the cost
accounting layer (``apex_tpu.telemetry.costs``) consults: the
``cost_analysis`` / ``memory_analysis`` surfaces differ by jax version
AND backend — on jax 0.4.37 ``Lowered.cost_analysis()`` returns a flat
dict, ``Compiled.cost_analysis()`` a LIST of per-computation dicts, and
``Compiled.memory_analysis()`` a ``CompiledMemoryStats`` extension
object (attributes, not keys); other versions/backends return None, a
dict, or omit the method entirely. :func:`cost_analysis_dict` and
:func:`memory_analysis_dict` fold every observed variant into one
plain-dict shape (or None — "the backend can't report" is a value here,
never an exception), so the cost block's producers degrade gracefully
instead of version-forking at every call site.
"""

import functools

import jax


def install():
    """Idempotently install the handful of current-jax surfaces this
    tree uses that an older jax spells differently. Each shim installs
    only when the real attribute is missing — never shadows one."""
    _install_shard_map()
    _install_axis_size()


def _install_shard_map():
    if hasattr(jax, "shard_map"):
        return
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError:  # neither surface: let call sites raise honestly
        return

    @functools.wraps(_shard_map)
    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kw):
        if check_vma is not None and "check_rep" not in kw:
            kw["check_rep"] = check_vma  # old name of the same knob
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)

    jax.shard_map = shard_map


def _install_axis_size():
    # lax.axis_size(name): the STATIC size of a mapped axis. On old jax
    # the same lookup lives on the trace-time axis env (a psum(1, name)
    # would be traced, breaking static uses like shape arithmetic).
    from jax import lax

    if hasattr(lax, "axis_size"):
        return

    def axis_size(axis_name):
        from jax._src import core

        env = core.get_axis_env()
        if isinstance(axis_name, (tuple, list)):
            size = 1
            for name in axis_name:
                size *= env.axis_size(name)
            return size
        return env.axis_size(axis_name)

    lax.axis_size = axis_size


# --------------------------------------------------------------------------
# XLA cost/memory analysis normalizers (telemetry.costs feature detection)

# CompiledMemoryStats attribute names → the one key set the cost block
# speaks. Every field is device-side; the host_* twins are ignored.
_MEMORY_FIELDS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)


def cost_analysis_dict(stage):
    """One flat ``{metric: float}`` dict from a ``Lowered`` or
    ``Compiled`` stage's ``cost_analysis()``, or None when the backend
    can't report.

    Observed variants, all folded here (jax 0.4.37 calibration):

    * method absent (old stages, custom wrappers) → None
    * returns None / raises (unimplemented backend) → None
    * ``Lowered.cost_analysis()`` → a flat dict → passed through
    * ``Compiled.cost_analysis()`` → a LIST of per-computation dicts
      (one per partition/computation) → key-wise SUM across the list
      (a multi-computation executable's flops are the total it runs)
    * empty list / list of non-dicts → None
    """
    fn = getattr(stage, "cost_analysis", None)
    if fn is None:
        return None
    try:
        raw = fn()
    except Exception:
        return None
    if isinstance(raw, dict):
        return dict(raw) or None
    if isinstance(raw, (list, tuple)):
        dicts = [d for d in raw if isinstance(d, dict)]
        if not dicts:
            return None
        out = {}
        for d in dicts:
            for k, v in d.items():
                if isinstance(v, (int, float)):
                    out[k] = out.get(k, 0) + v
        return out or None
    return None


def memory_analysis_dict(compiled):
    """One plain dict (``argument/output/temp/alias/generated_code
    _size_in_bytes`` ints) from ``Compiled.memory_analysis()``, or None.

    Folds: method absent → None; returns None / raises → None; a
    ``CompiledMemoryStats`` extension object → attribute read; an
    already-plain dict (some backends) → key filter. Missing individual
    fields degrade to 0 (the stats object always carries the full set
    on backends that report at all)."""
    fn = getattr(compiled, "memory_analysis", None)
    if fn is None:
        return None
    try:
        raw = fn()
    except Exception:
        return None
    if raw is None:
        return None
    out = {}
    for field in _MEMORY_FIELDS:
        v = raw.get(field) if isinstance(raw, dict) \
            else getattr(raw, field, None)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[field] = int(v)
        else:
            out[field] = 0
    if not any(out.values()):
        # a stats object with every field 0 carries no information
        # (e.g. a backend that stubs the surface) — report "can't"
        return None
    return out
