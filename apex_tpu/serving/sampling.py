"""Batched stochastic sampling for the serving decode path (ISSUE 13).

Temperature / top-k / top-p sampling as ARRAY-VALUE ops inside the one
compiled decode program: every per-request parameter (temperature,
top_k, top_p, the threefry key lane, the per-request sample counter)
rides into :func:`sample_tokens` as a ``[B]``-shaped array the engine
re-stages each round — never a static argument — so admitting, evicting
or re-seeding requests changes array VALUES only and the decode step
keeps its one-compile contract (``decode_cache_size()==1``, asserted
with sampling on in tests/test_serving_generation.py).

Determinism is per REQUEST, not per batch: each request carries its own
threefry key (``PRNGKey(seed)``) and every sampled token folds in the
request's own generation index (``fold_in(key, n_generated)``), so the
token stream of a seeded request is identical whatever the batch
composition, slot placement or eviction order around it — the property
the per-slot-RNG determinism test pins.

Greedy exactness: a temperature-0 lane takes the exact
``argmax(logits.astype(f32))`` the pre-sampling decode step computed —
not a limit of the softmax path — so a sampling-enabled engine over
all-greedy requests reproduces the greedy engine token-for-token.

Knob: ``sampling=`` at engine build (per-call bool; a sampling-OFF
engine RAISES at ``submit`` when a request demands stochastic params —
explicit request ≠ preference) > ``set_sampling`` setter >
``APEX_SERVE_SAMPLING`` env preference > built-in OFF. Default OFF per
the measured-dispatch rule: with sampling compiled in, even all-greedy
batches pay the sort/top-p ops, so the decode program only grows them
when asked (the sampling-vs-greedy decode A/B is queued in PERF.md §2
behind ``APEX_SERVE_BENCH=1``).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.dispatch import tiles as _tiles

_SAMPLING = None  # process-wide tri-state preference


def set_sampling(value):
    """Pin the process-wide sampling preference (True/False), or un-pin
    with None (env then default apply). A setter CALL with a non-bool
    raises."""
    global _SAMPLING
    if value is not None and not isinstance(value, bool):
        raise ValueError(
            f"set_sampling wants True/False/None, got {value!r}")
    _SAMPLING = value


def resolve(per_call=None):
    """The effective sampling decision: per-call (the engine validates
    demands at submit — a stochastic request against a sampling-off
    engine raises there) > setter > ``APEX_SERVE_SAMPLING`` env
    (warn-once-and-ignore on unknown values) > built-in OFF."""
    if per_call is not None:
        if not isinstance(per_call, bool):
            raise ValueError(
                f"sampling= wants True/False/None, got {per_call!r}")
        return per_call
    if _SAMPLING is not None:
        return _SAMPLING
    v = _tiles.env_choice("APEX_SERVE_SAMPLING", ("1", "0"))
    if v is not None:
        return v == "1"
    return False


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling controls (the vLLM ``SamplingParams``
    analog — see docs/MIGRATING.md). ``temperature=0`` is EXACT greedy
    (the argmax path, not a softmax limit); ``top_k=0`` / ``top_p=1``
    disable their truncations. ``seed`` keys the request's private
    threefry lane."""
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0

    def validate(self):
        problems = []
        if self.temperature < 0:
            problems.append(f"temperature {self.temperature} < 0")
        if self.top_k < 0:
            problems.append(f"top_k {self.top_k} < 0")
        if not 0.0 < self.top_p <= 1.0:
            problems.append(f"top_p {self.top_p} not in (0, 1]")
        if problems:
            raise ValueError("invalid SamplingParams: "
                             + "; ".join(problems))

    @property
    def greedy(self):
        return self.temperature == 0.0


GREEDY = SamplingParams()


def request_key(seed):
    """The request's private threefry key lane as raw host bytes
    (``uint32[2]``), computed ONCE at submit so the per-round lane
    staging is pure numpy. Determinism hangs off this: the lane is a
    function of the request's seed alone, never of the slot or batch
    it lands in."""
    return np.asarray(jax.random.PRNGKey(int(seed)))


def _lane_buffers(n):
    """Zeroed/off-valued lane arrays for ``n`` lanes: ``(temps,
    top_ks, top_ps, keys, counters)``."""
    return (np.zeros((n,), np.float32), np.zeros((n,), np.int32),
            np.ones((n,), np.float32), np.zeros((n, 2), np.uint32),
            np.zeros((n,), np.int32))


def fill_lane(request, i, temps, top_ks, top_ps, keys):
    """Stage ONE request's sampling params + key into lane ``i`` —
    the single fill both the per-round decode staging and the
    engine's prefill first-token sampling go through, so a request's
    first token can never be drawn under different truncation/key
    semantics than the rest of its stream. The key derives lazily and
    is CACHED on the request (greedy lanes never read theirs — the
    zero lane is fine and costs no dispatch)."""
    p = getattr(request, "sampling", None) or GREEDY
    temps[i] = p.temperature
    top_ks[i] = p.top_k
    top_ps[i] = p.top_p
    key = getattr(request, "rng_key", None)
    if key is None and p.temperature > 0:
        key = request_key(p.seed)
        request.rng_key = key
    if key is not None:
        keys[i] = key


def lane_arrays(slots, num_slots):
    """The per-round ``[B]`` sampling-lane arrays for the decode
    program, rebuilt from the live slots (array VALUES change across
    admit/evict; shapes never): ``(temps, top_ks, top_ps, keys,
    counters)``. The counter is the request's own generation index
    (``len(out_tokens)``) — eviction and re-admission elsewhere cannot
    perturb another request's stream."""
    temps, top_ks, top_ps, keys, counters = _lane_buffers(
        int(num_slots))
    for i, slot in enumerate(slots):
        if slot is None:
            continue
        fill_lane(slot.request, i, temps, top_ks, top_ps, keys)
        counters[i] = len(slot.request.out_tokens)
    return temps, top_ks, top_ps, keys, counters


def batch_lanes(requests):
    """Lane arrays for an explicit request list (the engine's
    first-token sampling over a packed prefill batch): counters stay
    0 — the first token IS generation index 0."""
    temps, top_ks, top_ps, keys, counters = _lane_buffers(
        len(requests))
    for i, req in enumerate(requests):
        fill_lane(req, i, temps, top_ks, top_ps, keys)
    return temps, top_ks, top_ps, keys, counters


def sample_tokens(logits, temps, top_ks, top_ps, keys, counters,
                  active):
    """One sampled token per lane from ``[B, V]`` logits — pure jnp,
    traced INSIDE the decode program (and run eagerly on the prefill
    logits for each request's first token, the existing host-argmax
    idiom).

    temps/top_ps ``[B] f32``, top_ks/counters ``[B] i32``, keys
    ``[B, 2] u32`` (raw threefry lanes), active ``[B] bool``. Lane
    semantics: ``temps[i] == 0`` -> the exact f32 argmax; else logits
    are temperature-scaled, truncated to the top-k set (0 = off) AND
    the top-p nucleus (1 = off; the crossing token is kept, so the set
    is never empty), and the token is drawn by Gumbel-max under
    ``fold_in(keys[i], counters[i])`` — a function of the request's
    own key and generation index only, never of the batch around it.
    Inactive lanes return 0.
    """
    lf = logits.astype(jnp.float32)
    V = lf.shape[-1]
    greedy = jnp.argmax(lf, axis=-1).astype(jnp.int32)

    scaled = lf / jnp.maximum(temps, 1e-6)[:, None]
    sorted_desc = -jnp.sort(-scaled, axis=-1)
    # top-k: the kth largest value is the keep threshold (k=0 -> V)
    k_eff = jnp.where(top_ks > 0, top_ks, V)
    k_idx = jnp.clip(k_eff - 1, 0, V - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)
    keep_k = scaled >= kth
    # top-p nucleus over the sorted probabilities: a sorted position is
    # kept while the mass BEFORE it is under p (the crossing token is
    # kept — the nucleus always holds >= 1 token); the smallest kept
    # sorted value is then the unsorted keep threshold
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    before = jnp.cumsum(probs, axis=-1) - probs
    keep_sorted = before < top_ps[:, None]
    cut_idx = jnp.maximum(jnp.sum(keep_sorted.astype(jnp.int32),
                                  axis=-1) - 1, 0)
    cut = jnp.take_along_axis(sorted_desc, cut_idx[:, None], axis=-1)
    keep_p = scaled >= cut
    masked = jnp.where(keep_k & keep_p, scaled, -jnp.inf)

    def _lane_gumbel(key, ctr):
        return jax.random.gumbel(jax.random.fold_in(key, ctr), (V,),
                                 jnp.float32)

    gumbel = jax.vmap(_lane_gumbel)(keys, counters)
    drawn = jnp.argmax(masked + gumbel, axis=-1).astype(jnp.int32)
    toks = jnp.where(temps <= 0.0, greedy, drawn)
    return jnp.where(active, toks, 0)
