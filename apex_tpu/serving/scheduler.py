"""Continuous-batching scheduler (host-side, stdlib-only).

Runs BETWEEN decode steps: admit queued requests into free decode
slots (allocating their cache pages up front — all-or-nothing, so a
mid-stream request can never run out of pages), evict completed ones
(freeing pages), and materialize the static-shape arrays the jitted
decode step consumes. Only array VALUES change across admit/evict
events — shapes are fixed at construction, so the decode program
compiles exactly once (the ISSUE 10 jaxpr-stability contract).

Admission is strict FIFO with head-of-line blocking: if the oldest
queued request does not fit (no free slot, or the free list cannot
cover its ``prompt + max_new_tokens`` pages), nothing younger is
admitted over it — the no-starvation property
(tests/test_serving.py asserts completion order ⊇ arrival order under
the synthetic trace).

The synthetic traffic trace (:func:`synthetic_trace`) is the
deterministic workload every serving measurement pins: request
arrival ticks, prompt lengths and output lengths from one seeded
stdlib RNG, identified by a content hash (``trace_id``) that rides in
the ledger's serving block. Two ARRIVAL PROCESSES (the ISSUE 11
open-loop load harness, ROADMAP 2e): ``"poisson"`` — exponential
inter-arrivals at the constant offered rate (what the original trace
already drew, now named) — and ``"diurnal"`` — a non-homogeneous
Poisson process whose instantaneous rate swings sinusoidally around
the base rate (the day/night traffic shape heavy-traffic serving is
actually sized against). The process is a per-call argument of the
trace (unknown values raise) and a pinned knob of the measuring
harness (``APEX_SERVE_ARRIVALS``, check 9).

Scheduler POLICY is a dispatch choice, not an architecture constant
(ROADMAP 2e: FIFO vs priority vs chunked prefill as measured
dispatch): :func:`resolve_policy` keeps the CLAUDE.md asymmetry —
per-call unknown policies raise, the ``APEX_SERVE_SCHED`` env
preference warns once and falls back. Today the vocabulary is
``("fifo",)``; the knob exists so the first alternative policy lands
as a pinned A/B row, not a silent default flip.
"""

import dataclasses
import hashlib
import math
import random
from collections import deque
from typing import List, Optional

from apex_tpu.dispatch import tiles as _tiles

ARRIVALS = ("poisson", "diurnal")
POLICIES = ("fifo",)


def resolve_policy(per_call=None):
    """The effective scheduler policy: per-call (raises on unknown —
    an explicit request is a demand) > ``APEX_SERVE_SCHED`` env
    preference (warn-once-and-ignore on unknown) > built-in FIFO."""
    if per_call is not None:
        if per_call not in POLICIES:
            raise ValueError(
                f"unknown scheduler policy {per_call!r} "
                f"(vocabulary: {POLICIES})")
        return per_call
    return _tiles.env_choice("APEX_SERVE_SCHED", POLICIES) or "fifo"


@dataclasses.dataclass
class Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    arrival: float = 0.0          # logical tick the request appears at
    # filled in by the engine/scheduler:
    out_tokens: List[int] = dataclasses.field(default_factory=list)
    enqueue_wall: Optional[float] = None
    finish_wall: Optional[float] = None
    # lifecycle wall stamps (seconds, host clock — the engine threads
    # them through admit/prefill so replay latencies are seconds, not
    # tick counts; apex_tpu.serving.lifecycle derives TTFT/TPOT here)
    admitted_wall: Optional[float] = None
    first_token_wall: Optional[float] = None
    admitted_tick: Optional[int] = None
    finished_tick: Optional[int] = None

    def done(self):
        return len(self.out_tokens) >= self.max_new_tokens


@dataclasses.dataclass
class Slot:
    request: Request
    pages: List[int]
    pos: int = 0                  # context length held in the cache
    next_token: int = 0           # token the next decode step consumes


class ContinuousBatchingScheduler:
    def __init__(self, num_slots, max_pages_per_slot, page_size,
                 allocator, policy=None):
        self.num_slots = int(num_slots)
        self.max_pages = int(max_pages_per_slot)
        self.page_size = int(page_size)
        self.allocator = allocator
        self.policy = resolve_policy(policy)
        self.slots = [None] * self.num_slots
        self.queue = deque()
        self.completed = []

    # ------------------------------------------------------- bookkeeping

    def submit(self, request):
        """Enqueue one request. An impossible request (prompt +
        max_new_tokens over the per-slot page table, i.e. over
        max_seq) raises HERE — before anything is enqueued — so one
        malformed submission can never crash a later scheduler round
        mid-step and take the whole serving loop (and every other
        queued request) down with it."""
        if request.max_new_tokens < 1:
            raise ValueError(
                f"request {request.rid}: max_new_tokens must be >= 1 "
                f"(prefill always samples the first token)")
        need = self._request_pages(request)
        if need > self.max_pages:
            raise ValueError(
                f"request {request.rid}: {need} pages exceed the "
                f"per-slot table ({self.max_pages}) — prompt + "
                f"max_new_tokens over max_seq")
        self.queue.append(request)

    def active_indices(self):
        return [i for i, s in enumerate(self.slots) if s is not None]

    def _request_pages(self, req):
        # deferred: kv_cache imports jax.numpy at module level for the
        # cache arrays, and this module's stdlib-only claim is
        # mechanically checked over the import graph (apexlint APX006)
        from apex_tpu.serving.kv_cache import pages_needed

        return pages_needed(len(req.prompt) + req.max_new_tokens,
                            self.page_size)

    def queue_depth(self):
        return len(self.queue)

    def head_of_line_wait(self, wall_time):
        """Seconds the oldest queued request has been waiting at
        ``wall_time`` (0.0 with an empty queue or unstamped head) —
        the gauge that names head-of-line blocking as a number."""
        if not self.queue:
            return 0.0
        head = self.queue[0].enqueue_wall
        if head is None:
            return 0.0
        return max(0.0, wall_time - head)

    def admit(self, tick, wall_time=None):
        """FIFO admission of every queued request that fits, stopping
        at the first that does not (head-of-line blocking — the
        no-starvation rule). Returns the newly filled slot indices.
        ``wall_time`` (the engine's host clock, one read per round)
        stamps each admission's ``admitted_wall`` — the same wall
        seam as :meth:`evict_done`, so replay latencies are seconds,
        not tick counts."""
        admitted = []
        while self.queue:
            req = self.queue[0]
            free = [i for i, s in enumerate(self.slots) if s is None]
            need = self._request_pages(req)
            # submit() already refused impossible requests; anything
            # queued is admittable once slots/pages free up
            assert need <= self.max_pages, (req.rid, need)
            if not free:
                break
            pages = self.allocator.alloc(("req", req.rid), need)
            if pages is None:
                break
            self.queue.popleft()
            idx = free[0]
            self.slots[idx] = Slot(request=req, pages=pages)
            req.admitted_tick = tick
            if wall_time is not None:
                req.admitted_wall = wall_time
            admitted.append(idx)
        return admitted

    def evict_done(self, tick, wall_time=None):
        """Free slots/pages of completed requests; returns them.
        ``wall_time`` backstops ``finish_wall`` for requests whose
        finishing dispatch did not stamp it (the one wall-clock seam
        shared with :meth:`admit`)."""
        done = []
        for i, slot in enumerate(self.slots):
            if slot is not None and slot.request.done():
                self.allocator.free(("req", slot.request.rid))
                slot.request.finished_tick = tick
                if wall_time is not None \
                        and slot.request.finish_wall is None:
                    slot.request.finish_wall = wall_time
                self.completed.append(slot.request)
                done.append(slot.request)
                self.slots[i] = None
        return done

    # ------------------------------------------- static-shape array views

    def page_table_rows(self):
        """int32 [num_slots, max_pages]; empty slots / unallocated
        tail -> null page 0."""
        rows = [[0] * self.max_pages for _ in range(self.num_slots)]
        for i, slot in enumerate(self.slots):
            if slot is not None:
                for j, p in enumerate(slot.pages):
                    rows[i][j] = p
        return rows

    def decode_inputs(self):
        """(tokens, lengths) int lists for the decode step: length 0
        marks an inactive slot (the step zeros its lane)."""
        tokens = [0] * self.num_slots
        lengths = [0] * self.num_slots
        for i, slot in enumerate(self.slots):
            if slot is not None:
                tokens[i] = int(slot.next_token)
                lengths[i] = slot.pos + 1
        return tokens, lengths


def synthetic_trace(seed=0, n_requests=16, vocab=256, prompt_lo=4,
                    prompt_hi=24, new_lo=4, new_hi=32,
                    mean_interarrival=0.5, arrival="poisson",
                    diurnal_period=32.0, diurnal_depth=0.8):
    """Deterministic request trace: ``(requests, trace_id)``. Arrival
    is in decode-step ticks; the id is a content hash of every
    request's (arrival, prompt, max_new) so a cited serving row names
    exactly the workload it measured.

    ``arrival`` selects the OPEN-LOOP arrival process (unknown values
    raise — a per-call argument is a demand):

    * ``"poisson"`` — exponential inter-arrivals at rate
      ``1/mean_interarrival`` (the process the original trace always
      drew; byte-identical stream and ``tr-`` id for existing seeds).
    * ``"diurnal"`` — non-homogeneous Poisson: the instantaneous rate
      swings sinusoidally around the base rate with period
      ``diurnal_period`` ticks and relative amplitude
      ``diurnal_depth`` in [0, 1) (floored at 5% of base so the
      trough never stalls the trace) — peak-hour bursts and
      night-trough droughts in one seeded, content-hashed trace.
    """
    if arrival not in ARRIVALS:
        raise ValueError(f"unknown arrival process {arrival!r} "
                         f"(vocabulary: {ARRIVALS})")
    rng = random.Random(seed)
    t = 0.0
    reqs = []
    for rid in range(n_requests):
        if mean_interarrival > 0:
            rate = 1.0 / mean_interarrival
            if arrival == "diurnal":
                rate *= 1.0 + diurnal_depth * math.sin(
                    2.0 * math.pi * t / diurnal_period)
                rate = max(rate, 0.05 / mean_interarrival)
            t += rng.expovariate(rate)
        plen = rng.randint(prompt_lo, prompt_hi)
        prompt = [rng.randrange(vocab) for _ in range(plen)]
        reqs.append(Request(
            rid=rid, prompt=prompt,
            max_new_tokens=rng.randint(new_lo, new_hi),
            arrival=round(t, 3)))
    h = hashlib.sha1(repr(
        [(r.arrival, tuple(r.prompt), r.max_new_tokens)
         for r in reqs]).encode()).hexdigest()[:10]
    return reqs, f"tr-{h}"


def offered_load(requests):
    """Offered load of a trace in requests per tick: request count
    over the arrival span (the open-loop intensity a cited slo row
    names next to its arrival process). 0.0 for an empty trace; a
    same-tick burst divides by the 1-tick floor."""
    if not requests:
        return 0.0
    span = max(r.arrival for r in requests)
    return len(requests) / max(span, 1.0)
