"""Continuous-batching scheduler (host-side, stdlib-only).

Runs BETWEEN decode steps: admit queued requests into free decode
slots (allocating their cache pages up front — all-or-nothing, so a
mid-stream request can never run out of pages), evict completed ones
(freeing pages), and materialize the static-shape arrays the jitted
decode step consumes. Only array VALUES change across admit/evict
events — shapes are fixed at construction, so the decode program
compiles exactly once (the ISSUE 10 jaxpr-stability contract).

Admission is strict FIFO with head-of-line blocking: if the oldest
queued request does not fit (no free slot, or the free list cannot
cover its ``prompt + max_new_tokens`` pages), nothing younger is
admitted over it — the no-starvation property
(tests/test_serving.py asserts completion order ⊇ arrival order under
the synthetic trace).

The synthetic traffic trace (:func:`synthetic_trace`) is the
deterministic workload every serving measurement pins: request
arrival ticks, prompt lengths and output lengths from one seeded
stdlib RNG, identified by a content hash (``trace_id``) that rides in
the ledger's serving block. Two ARRIVAL PROCESSES (the ISSUE 11
open-loop load harness, ROADMAP 2e): ``"poisson"`` — exponential
inter-arrivals at the constant offered rate (what the original trace
already drew, now named) — and ``"diurnal"`` — a non-homogeneous
Poisson process whose instantaneous rate swings sinusoidally around
the base rate (the day/night traffic shape heavy-traffic serving is
actually sized against). The process is a per-call argument of the
trace (unknown values raise) and a pinned knob of the measuring
harness (``APEX_SERVE_ARRIVALS``, check 9).

Scheduler POLICY is a dispatch choice, not an architecture constant
(ROADMAP 2e: FIFO vs priority vs chunked prefill as measured
dispatch): :func:`resolve_policy` keeps the CLAUDE.md asymmetry —
per-call unknown policies raise, the ``APEX_SERVE_SCHED`` env
preference warns once and falls back. The vocabulary is ``("fifo",
"priority")`` (ISSUE 13 — the PR 10 remainder): ``priority`` admits
the queued request with the highest EFFECTIVE priority
``request.priority + waiting_ticks / AGING_TICKS`` — the aging term
is the no-starvation rule (any waiter eventually outranks every fixed
priority; completion-of-everything is pinned by test) — with
head-of-line blocking ON THE SELECTED request, so an urgent large
request is never starved by smaller queue-jumpers either. The
priority-vs-fifo tail-latency A/B under the diurnal trace is queued
in PERF.md §2 (defaults stay ``fifo`` per the measured-dispatch
rule).

Prefix-cache hop (ISSUE 13): when the engine passes a
:class:`~apex_tpu.serving.prefix_cache.PrefixCache`, admission looks
the prompt up first — shared full pages enter the slot's table by
REFERENCE (refcounted; only the uncovered remainder allocates), a
matched partial tail page schedules a copy-on-write into the slot's
first private page (``Slot.cow_copies`` — the ENGINE performs device
copies), and a short free list asks the cache to ``reclaim``
unreferenced pages before blocking.
"""

import dataclasses
import hashlib
import math
import random
from collections import deque
from typing import Any, List, Optional, Tuple

from apex_tpu.dispatch import tiles as _tiles
from apex_tpu.resilience import faults as _faults

ARRIVALS = ("poisson", "diurnal")
POLICIES = ("fifo", "priority")
# priority aging: one effective-priority level per this many waiting
# ticks — the no-starvation clock of the priority policy
AGING_TICKS = 8.0


def resolve_policy(per_call=None):
    """The effective scheduler policy: per-call (raises on unknown —
    an explicit request is a demand) > ``APEX_SERVE_SCHED`` env
    preference (warn-once-and-ignore on unknown) > built-in FIFO."""
    if per_call is not None:
        if per_call not in POLICIES:
            raise ValueError(
                f"unknown scheduler policy {per_call!r} "
                f"(vocabulary: {POLICIES})")
        return per_call
    return _tiles.env_choice("APEX_SERVE_SCHED", POLICIES) or "fifo"


@dataclasses.dataclass
class Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    arrival: float = 0.0          # logical tick the request appears at
    # scheduling priority (ISSUE 13, policy "priority": higher admits
    # first, aged by waiting time; ignored under "fifo")
    priority: int = 0
    # per-request sampling controls (apex_tpu.serving.sampling
    # .SamplingParams; None = greedy). Typed loosely: this module is
    # stdlib-only and never imports the jax-backed sampling module —
    # the ENGINE validates the params at submit.
    sampling: Optional[Any] = None
    # the request's private threefry key lane (uint32[2] host bytes,
    # stamped by engine.submit so per-round lane staging is numpy-only)
    rng_key: Optional[Any] = None
    # tick the request actually ENTERED the queue (stamped by
    # submit(tick=...) — the engine passes its round tick): the
    # priority policy's aging base. None falls back to ``arrival``,
    # so bare-scheduler callers keep today's semantics
    queued_tick: Optional[float] = None
    # KV-pressure preemption (ISSUE 15): a preempted request's full
    # known stream (prompt + generated tokens at preemption) — the
    # effective prompt its re-admission replays through the EXISTING
    # packed prefill program. None = never preempted past its first
    # token (re-admission is a plain fresh prefill).
    resume_tokens: Optional[List[int]] = None
    preemptions: int = 0
    # host swap tier (ISSUE 20): the banked device pages of a
    # preempted stream (an engine-owned ``kv_tier.SwappedPages``
    # handle). Typed loosely for the same stdlib-only reason as
    # ``sampling`` — this module never imports the jax-backed
    # kv_tier; the ENGINE banks at preemption (via the ``swap_out``
    # ctor callback) and restores or discards at re-admission.
    swapped: Optional[Any] = None
    shed_tick: Optional[int] = None   # deadline shedder drop point
    # filled in by the engine/scheduler:
    out_tokens: List[int] = dataclasses.field(default_factory=list)
    enqueue_wall: Optional[float] = None
    finish_wall: Optional[float] = None
    # lifecycle wall stamps (seconds, host clock — the engine threads
    # them through admit/prefill so replay latencies are seconds, not
    # tick counts; apex_tpu.serving.lifecycle derives TTFT/TPOT here)
    admitted_wall: Optional[float] = None
    first_token_wall: Optional[float] = None
    admitted_tick: Optional[int] = None
    finished_tick: Optional[int] = None

    def done(self):
        return len(self.out_tokens) >= self.max_new_tokens


@dataclasses.dataclass
class Slot:
    request: Request
    pages: List[int]
    pos: int = 0                  # context length held in the cache
    next_token: int = 0           # token the next decode step consumes
    # the KNOWN token stream this slot must consume before generating
    # anything new: the prompt for a fresh admission, the preempted
    # stream (prompt + generated-so-far) for a resumed one. The decode
    # loop's warmup/seam bookkeeping keys on its length — one rule for
    # fresh, prefix-hit and resumed slots alike (ISSUE 15).
    known: List[int] = dataclasses.field(default_factory=list)
    # prefix-cache bookkeeping (ISSUE 13; all empty/zero when the
    # cache is off or the prompt missed):
    shared_pages: List[int] = dataclasses.field(default_factory=list)
    prefix_hit: int = 0           # prompt tokens covered by the cache
    # (src, dst) page copies the ENGINE must perform before the slot's
    # first write — the copy-on-write of a matched partial tail page
    cow_copies: List[Tuple[int, int]] = dataclasses.field(
        default_factory=list)


class ContinuousBatchingScheduler:
    def __init__(self, num_slots, max_pages_per_slot, page_size,
                 allocator, policy=None, prefix=None, preempt=False,
                 swap_out=None):
        self.num_slots = int(num_slots)
        self.max_pages = int(max_pages_per_slot)
        self.page_size = int(page_size)
        self.allocator = allocator
        self.policy = resolve_policy(policy)
        self.prefix = prefix      # PrefixCache or None (engine-owned)
        # KV-pressure preemption (ISSUE 15): with the flag on,
        # admission reserves PROMPT pages only (overcommit) and
        # :meth:`grow` extends the table mid-stream, preempting the
        # lowest-effective-priority running slot when a grant is
        # refused. Off = the all-or-nothing up-front reservation the
        # scheduler always had (disabled mode behavior-identical).
        self.preempt = bool(preempt)
        # host swap tier (ISSUE 20): ``swap_out(slot) -> handle or
        # None`` banks a victim's live pages device→host BEFORE they
        # are freed. Engine-owned callable (this module stays
        # stdlib-only); None = the tier is off and preemption is
        # vLLM-style recompute, exactly as before.
        self.swap_out = swap_out
        self.slots = [None] * self.num_slots
        self.queue = deque()
        self.completed = []
        self.shed = []            # deadline-shed requests (engine-fed)
        self._preempted = []      # requests preempted since last drain

    # ------------------------------------------------------- bookkeeping

    def submit(self, request, tick=None):
        """Enqueue one request. An impossible request (prompt +
        max_new_tokens over the per-slot page table, i.e. over
        max_seq) raises HERE — before anything is enqueued — so one
        malformed submission can never crash a later scheduler round
        mid-step and take the whole serving loop (and every other
        queued request) down with it. ``tick`` stamps
        ``queued_tick`` — the priority policy ages WAITING time, not
        absolute tick, so a late direct submission gets no spurious
        boost."""
        self.validate(request)
        if tick is not None and request.queued_tick is None:
            request.queued_tick = tick
        self.queue.append(request)

    def validate(self, request):
        """The impossible-request teeth, callable on their own: the
        ENGINE runs them before its admission-control gate (ISSUE 15)
        so a malformed request always raises — a full queue must
        reject load, never mask a programming error as a
        ``Rejected``."""
        if request.max_new_tokens < 1:
            raise ValueError(
                f"request {request.rid}: max_new_tokens must be >= 1 "
                f"(prefill always samples the first token)")
        need = self._request_pages(request)
        if need > self.max_pages:
            raise ValueError(
                f"request {request.rid}: {need} pages exceed the "
                f"per-slot table ({self.max_pages}) — prompt + "
                f"max_new_tokens over max_seq")

    def active_indices(self):
        return [i for i, s in enumerate(self.slots) if s is not None]

    def _request_pages(self, req):
        # deferred: kv_cache imports jax.numpy at module level for the
        # cache arrays, and this module's stdlib-only claim is
        # mechanically checked over the import graph (apexlint APX006)
        from apex_tpu.serving.kv_cache import pages_needed

        return pages_needed(len(req.prompt) + req.max_new_tokens,
                            self.page_size)

    def queue_depth(self):
        return len(self.queue)

    def head_of_line_wait(self, wall_time, tick=None):
        """Seconds the BLOCKING request has been waiting at
        ``wall_time`` (0.0 with an empty queue or an unstamped head)
        — the gauge that names head-of-line blocking as a number.
        Under ``fifo`` that is the oldest queued request; under
        ``priority`` admission blocks on :meth:`_select`'s pick, so
        the gauge follows it (``tick`` feeds the aging term — the
        engine passes its round tick)."""
        if not self.queue:
            return 0.0
        head = self._select(tick if tick is not None else 0)
        if head.enqueue_wall is None:
            return 0.0
        return max(0.0, wall_time - head.enqueue_wall)

    def _select(self, tick):
        """The admission candidate under the active policy: the queue
        head under ``fifo``; under ``priority`` the request with the
        highest EFFECTIVE priority (``priority + waiting_ticks /
        AGING_TICKS`` — the aging term is the no-starvation rule),
        oldest-first on ties. Head-of-line blocking applies to the
        SELECTED request either way."""
        if self.policy == "fifo" or len(self.queue) == 1:
            return self.queue[0]
        best, best_key = None, None
        for pos, r in enumerate(self.queue):
            queued = r.queued_tick if r.queued_tick is not None \
                else r.arrival
            eff = r.priority + max(0.0, tick - queued) / AGING_TICKS
            key = (-eff, pos)     # pos = submit order (FIFO tie-break)
            if best_key is None or key < best_key:
                best, best_key = r, key
        return best

    def _alloc_with_reclaim(self, owner, n, protect=(), tick=None,
                            phase="admit"):
        """Allocator grant with prefix-cache pressure relief: a short
        free list asks the cache to reclaim unreferenced pages first
        (pages with live refs are NEVER freed — the cache refuses;
        ``protect`` additionally fences the cover THIS admission just
        matched, so reclaim can never free-and-rehand the pages its
        own request is about to share), then retries once. The
        ``serve_alloc`` chaos site (ISSUE 15) can script a refusal at
        an exact (tick, phase) without shrinking the pool — the
        preemption path then runs under deterministic page pressure."""
        if _faults.denied("serve_alloc", tick=tick, phase=phase):
            return None
        pages = self.allocator.alloc(owner, n)
        if pages is None and self.prefix is not None:
            shortfall = n - self.allocator.free_count
            if self.prefix.reclaim(shortfall,
                                   protect=protect) >= shortfall:
                pages = self.allocator.alloc(owner, n)
        return pages

    def admit(self, tick, wall_time=None):
        """Admission of every queued request that fits under the
        active policy, stopping at the first selected candidate that
        does not (head-of-line blocking — the no-starvation rule).
        Returns the newly filled slot indices. ``wall_time`` (the
        engine's host clock, one read per round) stamps each
        admission's ``admitted_wall`` — the same wall seam as
        :meth:`evict_done`, so replay latencies are seconds, not tick
        counts. With a prefix cache attached, the prompt's cached
        cover enters the slot by reference (full pages) and
        copy-on-write (partial tail), and only the remainder
        allocates."""
        admitted = []
        while self.queue:
            req = self._select(tick)
            free = [i for i, s in enumerate(self.slots) if s is None]
            need = self._request_pages(req)
            # submit() already refused impossible requests; anything
            # queued is admittable once slots/pages free up
            assert need <= self.max_pages, (req.rid, need)
            if not free:
                break
            known = req.resume_tokens or req.prompt
            shared, covered, tail = [], 0, None
            # a RESUMED request skips the prefix lookup: its effective
            # prompt is the preempted stream, not the prompt the cache
            # chains are keyed by — re-admission replays it through
            # the packed prefill program instead (ISSUE 15)
            if self.prefix is not None and req.resume_tokens is None:
                shared, covered, tail = self.prefix.lookup(req.prompt)
            matched = list(shared) + ([tail[0]] if tail else [])
            # under preemption (overcommit), admission reserves only
            # the KNOWN stream's pages — decode grows the table as
            # positions cross page boundaries (grow()); off, the
            # all-or-nothing full reservation stands
            from apex_tpu.serving.kv_cache import pages_needed

            reserve = pages_needed(len(known), self.page_size) \
                if self.preempt else need
            pages = self._alloc_with_reclaim(("req", req.rid),
                                             reserve - len(shared),
                                             protect=matched, tick=tick)
            if pages is None:
                break
            self.queue.remove(req)
            idx = free[0]
            slot = Slot(request=req, pages=shared + pages,
                        shared_pages=list(shared), prefix_hit=covered,
                        known=list(known))
            if covered:
                # the covered suffix replays through decode: position
                # `covered` is the first token the engine feeds
                slot.pos = covered
                slot.next_token = req.prompt[covered]
                if tail is not None:
                    # COW: the snapshot's content lands in the slot's
                    # first private page (same page index) before any
                    # write can alias another request's stream
                    slot.cow_copies.append((tail[0], pages[0]))
            if shared:
                self.prefix.acquire(shared)
            if self.prefix is not None:
                self.prefix.count(len(req.prompt), covered)
            self.slots[idx] = slot
            req.admitted_tick = tick
            if wall_time is not None:
                req.admitted_wall = wall_time
            admitted.append(idx)
        return admitted

    # -------------------------------------- KV-pressure preemption (15)

    def _select_victim(self, tick):
        """The slot index to preempt under page pressure: the LOWEST
        effective priority among running slots — base ``priority``
        (running requests do not age: aging rewards waiting), youngest
        admission first on ties (the latest arrival has the least sunk
        work to replay — vLLM's recompute-preemption order). A slot
        whose request already FINISHED this round (awaiting next
        round's evict) is never a victim: its pages free at the evict
        anyway, and requeuing it would stamp a preempted event after
        finished — a transition the lifecycle machine forbids. None
        when nothing preemptible is running."""
        best, best_key = None, None
        for i, slot in enumerate(self.slots):
            if slot is None or slot.request.done():
                continue
            r = slot.request
            key = (r.priority,
                   -(r.admitted_tick if r.admitted_tick is not None
                     else tick),
                   -r.rid)
            if best_key is None or key < best_key:
                best, best_key = i, key
        return best

    def requeue_slot(self, i, tick, swap=True):
        """Force running slot *i* back into the queue (preemption
        under page pressure, or round recovery after a wedged
        dispatch): free its private pages, decref its shared prefix
        pages (the cache refuses to free referenced pages — refcounts
        respected), stash the known stream for the re-prefill replay,
        and REQUEUE the request (it keeps its original
        ``queued_tick``, so priority aging preserves its seniority —
        a preempted request cannot be starved). Returns the
        request.

        ``swap=True`` offers the slot to the engine's ``swap_out``
        callback BEFORE its pages are freed (the host swap tier,
        ISSUE 20) — the handle rides on ``req.swapped`` next to
        ``resume_tokens``. The engine passes ``swap=False`` from its
        round-recovery and failover-drain paths, where the device
        cache is exactly what cannot be trusted."""
        slot = self.slots[i]
        req = slot.request
        req.swapped = (self.swap_out(slot)
                       if swap and self.swap_out is not None else None)
        self.allocator.free(("req", req.rid))
        if slot.shared_pages and self.prefix is not None:
            self.prefix.release(slot.shared_pages)
        # the full known stream (prompt + generated) is what
        # re-admission replays; a slot preempted before its first
        # token resumes as a plain fresh prefill
        req.resume_tokens = (list(req.prompt) + list(req.out_tokens)) \
            if req.out_tokens else None
        req.preemptions += 1
        self.slots[i] = None
        self.queue.append(req)
        return req

    def grow(self, i, min_pages, tick):
        """Mid-stream page growth for slot *i* (preemption mode): make
        its table hold >= ``min_pages`` pages, preempting the
        lowest-effective-priority running slot (possibly *i* itself —
        then False is returned and the caller drops the lane) each
        time a grant is refused. Preempted requests land in the
        :meth:`take_preempted` buffer for the engine's lifecycle
        events. Progress is guaranteed by the engine's pool check
        (``num_pages - 1 >= max_pages``): with everything else
        preempted and the prefix cache reclaimed, a lone slot can
        always reach ``max_seq`` pages."""
        slot = self.slots[i]
        while len(slot.pages) < min_pages:
            got = self._alloc_with_reclaim(
                ("req", slot.request.rid), 1, tick=tick, phase="grow")
            if got is not None:
                slot.pages.extend(got)
                continue
            victim = self._select_victim(tick)
            if victim is None:  # defensive: slot i itself is a candidate
                return False
            self._preempted.append(self.requeue_slot(victim, tick))
            if victim == i:
                return False
        return True

    def take_preempted(self):
        """Drain the requests preempted since the last call (the
        engine records their ``preempted``/``resubmitted`` lifecycle
        events and counters from this buffer)."""
        out, self._preempted = self._preempted, []
        return out

    def evict_done(self, tick, wall_time=None):
        """Free slots/pages of completed requests; returns them.
        Private pages return to the free list; shared prefix pages
        only DECREF (the cache refuses to free referenced pages — a
        completed request's shared system prompt stays warm for the
        next arrival). ``wall_time`` backstops ``finish_wall`` for
        requests whose finishing dispatch did not stamp it (the one
        wall-clock seam shared with :meth:`admit`)."""
        done = []
        for i, slot in enumerate(self.slots):
            if slot is not None and slot.request.done():
                self.allocator.free(("req", slot.request.rid))
                if slot.shared_pages and self.prefix is not None:
                    self.prefix.release(slot.shared_pages)
                slot.request.finished_tick = tick
                if wall_time is not None \
                        and slot.request.finish_wall is None:
                    slot.request.finish_wall = wall_time
                self.completed.append(slot.request)
                done.append(slot.request)
                self.slots[i] = None
        return done

    # ------------------------------------------- static-shape array views

    def page_table_rows(self):
        """int32 [num_slots, max_pages]; empty slots / unallocated
        tail -> null page 0."""
        rows = [[0] * self.max_pages for _ in range(self.num_slots)]
        for i, slot in enumerate(self.slots):
            if slot is not None:
                for j, p in enumerate(slot.pages):
                    rows[i][j] = p
        return rows

    def decode_inputs(self):
        """(tokens, lengths) int lists for the decode step: length 0
        marks an inactive slot (the step zeros its lane)."""
        tokens = [0] * self.num_slots
        lengths = [0] * self.num_slots
        for i, slot in enumerate(self.slots):
            if slot is not None:
                tokens[i] = int(slot.next_token)
                lengths[i] = slot.pos + 1
        return tokens, lengths


def synthetic_trace(seed=0, n_requests=16, vocab=256, prompt_lo=4,
                    prompt_hi=24, new_lo=4, new_hi=32,
                    mean_interarrival=0.5, arrival="poisson",
                    diurnal_period=32.0, diurnal_depth=0.8,
                    system_prompt=None):
    """Deterministic request trace: ``(requests, trace_id)``. Arrival
    is in decode-step ticks; the id is a content hash of every
    request's (arrival, prompt, max_new) so a cited serving row names
    exactly the workload it measured.

    ``arrival`` selects the OPEN-LOOP arrival process (unknown values
    raise — a per-call argument is a demand):

    * ``"poisson"`` — exponential inter-arrivals at rate
      ``1/mean_interarrival`` (the process the original trace always
      drew; byte-identical stream and ``tr-`` id for existing seeds).
    * ``"diurnal"`` — non-homogeneous Poisson: the instantaneous rate
      swings sinusoidally around the base rate with period
      ``diurnal_period`` ticks and relative amplitude
      ``diurnal_depth`` in [0, 1) (floored at 5% of base so the
      trough never stalls the trace) — peak-hour bursts and
      night-trough droughts in one seeded, content-hashed trace.

    ``system_prompt`` (ISSUE 13): an optional shared token prefix
    prepended to EVERY request's prompt — the shared-system-prompt
    workload the prefix cache exists for. The content hash covers the
    final (prepended) prompts, so a trace with a system prompt never
    shares a ``tr-`` id with one without.
    """
    if arrival not in ARRIVALS:
        raise ValueError(f"unknown arrival process {arrival!r} "
                         f"(vocabulary: {ARRIVALS})")
    rng = random.Random(seed)
    t = 0.0
    reqs = []
    for rid in range(n_requests):
        if mean_interarrival > 0:
            rate = 1.0 / mean_interarrival
            if arrival == "diurnal":
                rate *= 1.0 + diurnal_depth * math.sin(
                    2.0 * math.pi * t / diurnal_period)
                rate = max(rate, 0.05 / mean_interarrival)
            t += rng.expovariate(rate)
        plen = rng.randint(prompt_lo, prompt_hi)
        prompt = [rng.randrange(vocab) for _ in range(plen)]
        if system_prompt:
            prompt = [int(t) for t in system_prompt] + prompt
        reqs.append(Request(
            rid=rid, prompt=prompt,
            max_new_tokens=rng.randint(new_lo, new_hi),
            arrival=round(t, 3)))
    h = hashlib.sha1(repr(
        [(r.arrival, tuple(r.prompt), r.max_new_tokens)
         for r in reqs]).encode()).hexdigest()[:10]
    return reqs, f"tr-{h}"


def offered_load(requests):
    """Offered load of a trace in requests per tick: request count
    over the arrival span (the open-loop intensity a cited slo row
    names next to its arrival process). 0.0 for an empty trace; a
    same-tick burst divides by the 1-tick floor."""
    if not requests:
        return 0.0
    span = max(r.arrival for r in requests)
    return len(requests) / max(span, 1.0)
