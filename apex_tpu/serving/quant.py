"""int8 weight quantization for the serving decode matmuls.

Decode is bandwidth-bound: every step streams the full weight set out
of HBM for one token per sequence. Storing the matmul weights as int8
with per-output-channel fp32 scales halves (vs bf16) the bytes each
step moves; the matmul runs on the int8 array (XLA fuses the widening
convert into the operand stream — the HBM reads stay int8) and the
scale is applied to the OUTPUT columns, so no dequantized weight copy
is ever materialized.

Knob: ``APEX_SERVE_WEIGHT_QUANT`` ∈ {"1", "0"} (preference; unknown
values warn once and are ignored), ``set_weight_quant(True/False/None)``
the process-wide setter, and the engine's per-call ``weight_quant=``
which RAISES on an un-honorable request (non-float params) — the
CLAUDE.md asymmetry. Default OFF per the measured-dispatch rule: the
bandwidth argument is an expectation, not a measurement, so the
int8-vs-bf16 decode A/B is queued in PERF.md §2 and the default flips
only on a committed device row.
"""

import jax.numpy as jnp

from apex_tpu.dispatch import tiles

_QUANT = None  # process-wide tri-state preference


def set_weight_quant(value):
    """Pin the process-wide weight-quant preference (True/False), or
    un-pin with None (env then default apply). A setter CALL with a
    non-bool raises."""
    global _QUANT
    if value is not None and not isinstance(value, bool):
        raise ValueError(
            f"set_weight_quant wants True/False/None, got {value!r}")
    _QUANT = value


def resolve(per_call=None):
    """The effective weight-quant decision: per-call (validated by the
    caller — the engine raises on un-honorable) > setter > env
    ``APEX_SERVE_WEIGHT_QUANT`` (tiles.env_choice: unknown values
    warn once and are ignored) > built-in OFF."""
    if per_call is not None:
        return bool(per_call)
    if _QUANT is not None:
        return _QUANT
    v = tiles.env_choice("APEX_SERVE_WEIGHT_QUANT", ("1", "0"))
    if v is not None:
        return v == "1"
    return False


def quantizable(w):
    """Whether a weight array can take the int8 path (the per-call
    demand's honorability test)."""
    return hasattr(w, "dtype") and jnp.issubdtype(w.dtype, jnp.floating)


def quantize_weight(w):
    """``(w_q int8 [out, in], scale fp32 [out])`` — symmetric
    per-output-channel quantization of a ``[out, in]`` matmul weight.
    All-zero rows get scale 0 (dequantizes to exact 0)."""
    if not quantizable(w):
        raise ValueError(
            f"cannot int8-quantize dtype {getattr(w, 'dtype', None)}")
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=1)
    scale = amax / 127.0
    inv = jnp.where(scale > 0, 1.0 / jnp.where(scale > 0, scale, 1.0),
                    0.0)
    wq = jnp.clip(jnp.round(wf * inv[:, None]), -127, 127).astype(
        jnp.int8)
    return wq, scale


def qmatmul(x, wq, scale, compute_dtype):
    """``x @ dequant(wq, scale)^T`` without materializing the
    dequantized weight: the int8 operand is widened in-stream and the
    per-channel scale lands on the output columns."""
    from jax import lax

    y = lax.dot_general(
        x.astype(compute_dtype), wq.astype(compute_dtype),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (y * scale.astype(jnp.float32)).astype(compute_dtype)
