"""Content-hashed, refcounted copy-on-write prefix cache (ISSUE 13).

Stdlib-only host-side bookkeeping layered on the existing null-page-0
:class:`~apex_tpu.serving.kv_cache.PageAllocator`: a page of K/V is a
pure function of the token prefix that produced it, so two requests
whose prompts share a page-aligned prefix can share the PAGES — the
shared system prompt of ROADMAP 2c is prefilled once per engine, and
every later request's page table simply points at the cached pages.

Ownership model (the refcount/aliasing invariants
``check_invariants`` extends):

* **Full chain pages** are transferred from the registering request to
  the cache (allocator owner ``("prefix", page)``) and FROZEN: holders
  only ever write at positions past their prompt, which lie beyond a
  full prefix page, so a shared full page is never written. Each page
  carries a refcount = number of live slots whose table includes it;
  eviction/reclaim refuses to free a page with live refs.
* **The partial tail page** (a prompt whose length is not
  page-aligned) IS written by every holder — its free rows are where
  the first generated/suffix K/V land. It is therefore shared by COPY,
  not by reference: registration snapshots it into a cache-owned page
  (the engine performs the device copy — this module is stdlib-only
  index bookkeeping), and every hit schedules a copy-on-write of that
  snapshot into the hitting request's own private page at admission,
  BEFORE any write can alias another request's stream. Tail snapshots
  hold no refs and are reclaimable at any time.

A hit never covers the full prompt: at least the LAST prompt token is
left for the engine to run (its logits produce the request's first
output token — logits are not cached, pages are). The covered suffix
is consumed through the decode program one token per round (decode
attends the cached pages — correct by construction), so no new
compiled program exists for cache-hit warmup.

Reclaim walks chains least-recently-used and frees ref-0 pages from
each chain's TAIL backward (a chain stays prefix-valid — an interior
page is never freed under a live descendant), stopping at the first
referenced page. ``reclaim`` is called by the scheduler when admission
runs short of free pages; pages with live refs are NEVER freed — the
eviction-refusal invariant the churn tests pin.

Knob: engine ``prefix_cache=`` per-call bool (non-bool raises) >
``set_prefix_cache`` setter > ``APEX_SERVE_PREFIX_CACHE`` env
preference > built-in OFF (measured-dispatch rule: the shared-prefill
win is an expectation until the device A/B queued in PERF.md §2 runs).
"""

import hashlib

from apex_tpu.dispatch import tiles as _tiles

_PREFIX = None  # process-wide tri-state preference


def set_prefix_cache(value):
    """Pin the process-wide prefix-cache preference (True/False), or
    un-pin with None. A setter CALL with a non-bool raises."""
    global _PREFIX
    if value is not None and not isinstance(value, bool):
        raise ValueError(
            f"set_prefix_cache wants True/False/None, got {value!r}")
    _PREFIX = value


def resolve(per_call=None):
    """The effective prefix-cache decision: per-call (non-bool raises —
    an explicit request is a demand) > setter >
    ``APEX_SERVE_PREFIX_CACHE`` env (warn-once-and-ignore on unknown)
    > built-in OFF."""
    if per_call is not None:
        if not isinstance(per_call, bool):
            raise ValueError(
                f"prefix_cache= wants True/False/None, got {per_call!r}")
        return per_call
    if _PREFIX is not None:
        return _PREFIX
    v = _tiles.env_choice("APEX_SERVE_PREFIX_CACHE", ("1", "0"))
    if v is not None:
        return v == "1"
    return False


def _page_hash(parent_hash, tokens):
    """Chain hash of one page: sha1 over the parent chain hash + this
    page's token content — a page is addressable only through the
    exact prefix that produced its K/V."""
    h = hashlib.sha1(parent_hash.encode())
    h.update(repr(tuple(int(t) for t in tokens)).encode())
    return h.hexdigest()


ROOT = "prefix-root"


class PrefixCache:
    """Host-side chain store + refcounts over cache pages. The
    allocator passed in is the engine's ONE allocator — cached pages
    live in its accounting (owner ``("prefix", page)``), so the
    existing aliasing/accounting invariants cover them too."""

    def __init__(self, allocator, page_size):
        self.allocator = allocator
        self.page_size = int(page_size)
        # chain hash -> {"page": int, "parent": hash, "ntok": int}
        self.nodes = {}
        # parent chain hash -> tail snapshot {"page": int,
        # "tokens": tuple} (one per prefix; first registrant wins)
        self.tails = {}
        self.refs = {}           # page -> live slot reference count
        self._lru = []           # chain-leaf hashes, oldest first
        # accounting for the ledger's prefix_hit_rate
        self.hit_tokens = 0
        self.lookup_tokens = 0

    # ------------------------------------------------------------ lookup

    def lookup(self, prompt):
        """Longest cached cover of ``prompt``: ``(full_pages, covered,
        tail)`` where ``full_pages`` are shared-by-reference full chain
        pages (covering ``len(full_pages) * page_size`` tokens),
        ``covered`` counts ALL covered tokens and ``tail`` is the
        ``(snapshot_page, ntok)`` copy-on-write source extending the
        cover past the last full page (None when no tail matched).
        Never covers the full prompt — the last token is always left
        for the engine. Does NOT take references or count hit-rate
        stats (``acquire`` / ``count`` do, once admission succeeds —
        a head-of-line-blocked request re-looked-up every round must
        not inflate the rate's denominator)."""
        ps = self.page_size
        full_pages, h, covered = [], ROOT, 0
        while covered + ps < len(prompt):  # strict: keep >= 1 token
            page_tokens = prompt[covered:covered + ps]
            if len(page_tokens) < ps:
                break
            nh = _page_hash(h, page_tokens)
            node = self.nodes.get(nh)
            if node is None:
                break
            full_pages.append(node["page"])
            h, covered = nh, covered + ps
        tail = None
        snap = self.tails.get(h)
        if snap is not None:
            ntok = len(snap["tokens"])
            if 0 < ntok < ps and covered + ntok < len(prompt) \
                    and tuple(prompt[covered:covered + ntok]) \
                    == snap["tokens"]:
                tail = (snap["page"], ntok)
                covered += ntok
        if covered and h != ROOT:
            self._touch(h)
        return full_pages, covered, tail

    def count(self, prompt_tokens, covered):
        """Bank one ADMITTED request's hit-rate sample (the ledger's
        ``prefix_hit_rate`` = hit_tokens / lookup_tokens)."""
        self.lookup_tokens += int(prompt_tokens)
        self.hit_tokens += int(covered)

    def acquire(self, pages):
        """Take one reference per shared full page (admission
        succeeded; the slot's table now includes them)."""
        for p in pages:
            self.refs[p] = self.refs.get(p, 0) + 1

    def release(self, pages):
        """Drop one reference per shared full page (the slot evicted).
        Pages stay cached at ref 0 for future hits; ``reclaim`` frees
        them under pressure."""
        for p in pages:
            n = self.refs.get(p, 0) - 1
            assert n >= 0, f"prefix page {p} released below zero refs"
            self.refs[p] = n

    # ---------------------------------------------------------- register

    def register(self, prompt, pages, owner):
        """Adopt a freshly prefilled prompt's pages into the cache.
        ``pages`` is the request's page list (its prompt-covering
        prefix is what registers); ``owner`` is its allocator owner.
        Genuinely NEW full chain pages are TRANSFERRED to cache
        ownership (the registrant's table still reads them — the
        caller must ``acquire`` the returned pages and release them at
        eviction); chain pages that already exist leave this request's
        private duplicates alone (first registrant wins). The partial
        tail page (if any, and if a snapshot page can be allocated) is
        shared by COPY: the returned ``copies`` are ``(src_page,
        dst_page)`` device copies the ENGINE must perform (this module
        never touches jax). Returns ``(adopted_pages, copies)``."""
        ps = self.page_size
        nfull = len(prompt) // ps
        adopted, copies = [], []
        h = ROOT
        for i in range(nfull):
            page_tokens = prompt[i * ps:(i + 1) * ps]
            nh = _page_hash(h, page_tokens)
            if nh not in self.nodes:
                page = pages[i]
                self.allocator.transfer(owner, ("prefix", page), [page])
                self.nodes[nh] = {"page": page, "parent": h, "ntok": ps}
                self.refs.setdefault(page, 0)
                adopted.append(page)
            h = nh
        tail_tokens = tuple(int(t) for t in prompt[nfull * ps:])
        if tail_tokens and h not in self.tails:
            snap = self.allocator.alloc(("prefix-tail", h), 1)
            if snap is not None:
                self.tails[h] = {"page": snap[0], "tokens": tail_tokens}
                copies.append((pages[nfull], snap[0]))
        if h != ROOT:
            self._touch(h)
        return adopted, copies

    def _touch(self, leaf_hash):
        if leaf_hash in self._lru:
            self._lru.remove(leaf_hash)
        self._lru.append(leaf_hash)

    # ----------------------------------------------------------- reclaim

    def reclaim(self, n_pages, protect=()):
        """Free up to ``n_pages`` cached pages back to the allocator,
        least-recently-used chains first, each chain from its TAIL
        backward, refusing any page with live references (the
        eviction invariant) and any page in ``protect`` — the
        scheduler passes the cover a pending admission just MATCHED,
        so relieving page pressure can never free the very pages (or
        COW tail source) that admission is about to reference.
        Returns the number actually freed."""
        freed = 0
        protect = set(protect)
        # tail snapshots first: they hold no refs by construction
        for h in list(self.tails):
            if freed >= n_pages:
                break
            snap = self.tails[h]
            if snap["page"] in protect:
                continue
            del self.tails[h]
            self.allocator.free(("prefix-tail", h))
            self.refs.pop(snap["page"], None)
            freed += 1
        if freed >= n_pages:
            return freed
        children = {}
        for nh, node in self.nodes.items():
            children.setdefault(node["parent"], []).append(nh)
        for leaf in list(self._lru):
            h = leaf
            while freed < n_pages and h != ROOT and h in self.nodes:
                if children.get(h):
                    break  # interior page under a live descendant
                node = self.nodes[h]
                if self.refs.get(node["page"], 0) > 0:
                    break  # NEVER free a page with live refs
                if node["page"] in protect:
                    break  # matched by the admission in flight
                page, parent = node["page"], node["parent"]
                self.allocator.free(("prefix", page))
                self.refs.pop(page, None)
                del self.nodes[h]
                if parent in children and h in children[parent]:
                    children[parent].remove(h)
                self.tails.pop(h, None)
                freed += 1
                h = parent
            if h != leaf:
                self._lru.remove(leaf)
                if h != ROOT and h in self.nodes:
                    self._touch(h)
            if freed >= n_pages:
                break
        return freed

    def flush(self):
        """Drop EVERY cached chain page and tail snapshot back to the
        allocator — the round-recovery path (ISSUE 15): after a wedged
        dispatch the device cache buffer is rebuilt from zeros, so the
        cached K/V no longer exists and every chain is a dangling
        pointer. Refuses under live references (the engine requeues —
        and thereby releases — every slot first); returns the number
        of pages freed."""
        held = {p: n for p, n in self.refs.items() if n > 0}
        assert not held, (
            f"prefix flush with live references: {held} — requeue the "
            f"holding slots first")
        freed = 0
        for node in self.nodes.values():
            self.allocator.free(("prefix", node["page"]))
            freed += 1
        for h in list(self.tails):
            self.allocator.free(("prefix-tail", h))
            freed += 1
        self.nodes.clear()
        self.tails.clear()
        self.refs.clear()
        self._lru.clear()
        return freed

    # -------------------------------------------------------- invariants

    def cached_pages(self):
        pages = [n["page"] for n in self.nodes.values()]
        pages += [t["page"] for t in self.tails.values()]
        return pages

    def is_shared(self, page):
        """Whether ``page`` is cache-owned (a write to it must COW)."""
        return page in self.refs \
            or any(t["page"] == page for t in self.tails.values())

    def check_invariants(self):
        """Raise AssertionError on refcount/aliasing drift — the
        ISSUE 13 extension of the allocator's own check (which still
        covers the global free/live accounting): every cached page is
        allocator-live under a cache owner, refcounts are non-negative
        and keyed only by cached full pages, chains are
        parent-connected, and no page appears in two nodes."""
        pages = self.cached_pages()
        assert len(pages) == len(set(pages)), (
            f"prefix page aliased across chain nodes: {sorted(pages)}")
        live = set(self.allocator.live_pages())
        for nh, node in self.nodes.items():
            p = node["page"]
            assert p in live, f"cached page {p} not allocator-live"
            assert self.allocator.live_pages(("prefix", p)) == [p], (
                f"cached page {p} not owned by the prefix cache")
            parent = node["parent"]
            assert parent == ROOT or parent in self.nodes, (
                f"chain node {nh} orphaned (parent missing)")
        for h, t in self.tails.items():
            assert t["page"] in live, (
                f"tail snapshot page {t['page']} not allocator-live")
            assert 0 < len(t["tokens"]) < self.page_size
        full = {n["page"] for n in self.nodes.values()}
        for p, n in self.refs.items():
            assert n >= 0, f"negative refcount on page {p}"
            assert p in full, f"refcount on non-cached page {p}"
