"""Request-lifecycle event log + scheduler gauges + the SLO ledger block.

PR 9's serving stack is observable only as one decode-throughput
number; TPU serving comparisons live or die on TAIL latency under load
(PAPERS.md arXiv:2605.25645), which needs a per-request view. This
module is the host-side substrate (ROADMAP item 2e):

* **EventLog** — per-request ``submitted / admitted / prefill_done /
  first_token / finished / evicted`` events (plus the ISSUE 15
  resilience chain: terminal ``rejected``/``shed``, and the
  ``preempted``/``degraded_round`` → ``resubmitted`` suspension cycle
  — see ``_NEXT``) with wall-clock stamps,
  appended by the engine strictly BETWEEN device steps (events are
  plain host dicts; the jitted prefill/decode programs never see
  them, so ``decode_cache_size()==1`` holds with the log on or off),
  plus per-round scheduler/allocator **gauges** (slot occupancy,
  queue depth, KV-page high-water, head-of-line wait).
  ``validate_order`` is the mechanical event-ordering invariant
  surface (tests + ``dryrun_serving`` both assert it).
* **enabled()** — the collection gate, same trace-time discipline as
  ``telemetry.metrics.enabled()``: a Python bool (``APEX_SERVE_EVENTS
  =1`` unless :func:`enable`/:func:`disable` overrode it), branched on
  in host code only. Disabled mode allocates no log and appends
  nothing — behavior-identical serving (tests/test_serving_slo.py
  asserts token-for-token identity and the one-compile contract).
* **slo_block()** — the validated ledger block
  ``{ttft_p50/p99_ms, per_token_p50/p99_ms, goodput_tok_s,
  slo_attainment, arrival_process, offered_load, max_queue_depth,
  kv_page_high_water}`` (schema teeth in ``ledger.validate_record``;
  citation pins policed by ``tools/check_bench_labels.py`` check 9).
  Definitions: TTFT = first-token wall − submit wall; per-token
  (TPOT) = (finish − first token) / (tokens − 1) for requests with
  ≥ 2 tokens; a request ATTAINS its SLO when TTFT and TPOT are both
  under their thresholds (a request too short to have a TPOT is
  judged on TTFT alone); goodput = tokens of attaining requests per
  wall second — the honest line under the raw tokens/s
  (arXiv:2605.25645's framing: throughput that violated its SLO is
  not serving anyone).

Stdlib-only (like ``scheduler``): the ledger's validators and
``tools/window_report.py`` consume these blocks without touching jax.
The SLO thresholds are knobs, not constants (``APEX_SERVE_SLO_TTFT_MS``
/ ``APEX_SERVE_SLO_TPOT_MS``, parsed by :func:`env_ms` with
warn-once-and-ignore preference semantics); the defaults below are
starting points a cited row must PIN, never a committed envelope —
measured dispatch, not asserted dispatch.
"""

import os

# canonical per-request event order — the validate_order invariant.
# The resilience events (ISSUE 15) extend the PR 10 chain: `rejected`
# (admission control refused at submit) and `shed` (the deadline
# shedder dropped a queued request) are terminal; `preempted` (KV
# pressure) and `degraded_round` (a wedged/crashed dispatch round)
# suspend a running request and MUST be followed by `resubmitted`,
# after which the admission cycle may repeat — the once-only events
# (prefill_done / first_token / finished / evicted) still fire at
# most once per request across every cycle. The fleet events
# (ISSUE 19) extend it again: `routed` (the router assigned the
# request to a replica — once, right after `submitted`), and the
# failover cycle — `failover` (the request was pulled off a DEAD
# replica, queued or mid-stream) MUST be followed by `replayed`
# (resubmitted through a survivor), after which the admission cycle
# repeats on the new replica; a request may fail over repeatedly
# (cascading replica deaths), so neither is once-only.
EVENTS = ("submitted", "rejected", "shed", "routed", "admitted",
          "prefill_done", "first_token", "preempted", "swap_failed",
          "degraded_round", "resubmitted", "failover", "replayed",
          "finished", "evicted")
_EVENT_IDX = {e: i for i, e in enumerate(EVENTS)}
# the happy-path chain of an undisturbed request (what dryruns and the
# churn tests assert a complete lifecycle looks like)
CORE_EVENTS = ("submitted", "admitted", "prefill_done", "first_token",
               "finished", "evicted")
# events that may legally appear at most ONCE in a request's chain
_ONCE = frozenset(("submitted", "rejected", "shed", "routed",
                   "prefill_done", "first_token", "finished",
                   "evicted"))
# the per-request transition machine (validate_order): allowed
# successors of each event. "admitted" may be re-entered only through
# "resubmitted" or the failover cycle's "replayed"; conditional arcs
# (finished needs a first token; a re-admitted request skips
# prefill_done/first_token it already has) are resolved in
# validate_order against the seen-set. "failover" may interrupt a
# request anywhere between routing and finishing — a replica dies
# with the request queued (after routed/replayed) or mid-stream
# (after admitted/prefill_done/first_token).
_SUSPEND = ("preempted", "degraded_round")
_NEXT = {
    None: ("submitted",),
    "submitted": ("rejected", "shed", "admitted", "routed"),
    "rejected": (),
    "shed": (),
    "routed": ("admitted", "shed", "failover"),
    "admitted": ("prefill_done", "finished", "swap_failed")
    + _SUSPEND + ("failover",),
    "prefill_done": ("first_token",) + _SUSPEND + ("failover",),
    "first_token": ("finished",) + _SUSPEND + ("failover",),
    # "swap_failed" (ISSUE 20): the host swap tier failed a banked
    # stream — either at preemption (swap-out could not copy the
    # victim's pages: `preempted -> swap_failed -> resubmitted`) or at
    # re-admission (the handle was corrupt or swap-in crashed:
    # `admitted -> swap_failed -> ...`, after which the stream replays
    # by recompute and continues its normal arcs). Falls back to
    # vLLM-style recompute preemption either way — tokens preserved.
    # NOT once-only: a request preempted repeatedly may fail its swap
    # repeatedly. A swap-failed stream always has its once-only
    # prefill_done/first_token already (only a stream with generated
    # tokens is ever banked), so those arcs are not re-entered here.
    "preempted": ("resubmitted", "swap_failed"),
    "swap_failed": ("resubmitted", "finished") + _SUSPEND
    + ("failover",),
    "degraded_round": ("resubmitted",),
    "resubmitted": ("shed", "admitted", "failover"),
    "failover": ("replayed",),
    "replayed": ("admitted", "shed", "failover"),
    "finished": ("evicted",),
    "evicted": (),
}

# starting-point SLO thresholds (interactive-serving shaped); a cited
# slo row pins the RESOLVED values (check 9), so these defaults can
# move without orphaning any label
DEFAULT_SLO_TTFT_MS = 1000.0
DEFAULT_SLO_TPOT_MS = 100.0

# --------------------------------------------------------------------------
# collection gate (trace-time discipline; process-wide preference)

_FORCED = None  # programmatic override; None defers to the env knob


def enabled():
    """True when lifecycle collection is on (``APEX_SERVE_EVENTS=1``,
    unless :func:`enable`/:func:`disable` overrode it). Branch on it in
    host code only — the jitted programs never depend on it."""
    if _FORCED is not None:
        return _FORCED
    from apex_tpu.dispatch import tiles

    return tiles.env_flag("APEX_SERVE_EVENTS")


def enable():
    global _FORCED
    _FORCED = True


def disable():
    global _FORCED
    _FORCED = False


def reset_enabled():
    """Back to the env-var default (test hygiene)."""
    global _FORCED
    _FORCED = None


def env_ms(name, default):
    """Positive-float env preference (SLO thresholds, in ms): the
    parsed value when valid, else ``default`` — an unparseable or
    non-positive value warns ONCE per (knob, value) and is ignored.
    Delegates to ``dispatch.tiles.env_float``: the warn-once
    preference machinery has ONE home (next to ``env_int`` /
    ``env_choice``), so its semantics cannot drift per module."""
    from apex_tpu.dispatch import tiles

    return tiles.env_float(name, default)


# --------------------------------------------------------------------------
# the event log


class EventLog:
    """Append-only per-request lifecycle events + per-round gauges.

    Host-side and allocation-cheap: one dict per event, one per gauge
    sample. The engine owns the append sites (strictly between device
    dispatches); this class owns the ordering invariants and the
    summary aggregation.
    """

    def __init__(self):
        self.events = []          # [{event, rid, tick, wall, seq}]
        self.gauges = []          # [{tick, wall, slots_active, ...}]
        self._by_rid = {}         # rid -> [event dict]

    # ------------------------------------------------------------ events

    def record(self, event, rid, tick=None, wall=None):
        """Append one lifecycle event. Unknown event names raise — the
        vocabulary IS the schema, and a misspelled event would silently
        break every ordering invariant downstream."""
        if event not in _EVENT_IDX:
            raise ValueError(f"unknown lifecycle event {event!r} "
                             f"(vocabulary: {EVENTS})")
        rec = {"event": event, "rid": rid, "tick": tick, "wall": wall,
               "seq": len(self.events)}
        self.events.append(rec)
        self._by_rid.setdefault(rid, []).append(rec)
        return rec

    def request_events(self, rid):
        return list(self._by_rid.get(rid, ()))

    def rids(self):
        return sorted(self._by_rid)

    def validate_order(self, rid=None):
        """Ordering problems (empty list = clean) for one request or
        all of them: events must walk the ``_NEXT`` transition machine
        starting at ``submitted`` — the linear PR 10 chain, plus the
        resilience cycles (a ``preempted``/``degraded_round``
        suspension must be followed by ``resubmitted``, after which
        admission may repeat) and the fleet failover cycle (ISSUE 19:
        ``routed`` at most once right after ``submitted``; a
        ``failover`` anywhere between routing and finishing must be
        followed by ``replayed``, after which admission repeats on
        the surviving replica) — with the once-only events
        (``_ONCE``) never duplicated across cycles, ``finished``
        only after a first token landed, and non-decreasing wall
        stamps and ticks. ``dryrun_serving`` and the churn/chaos
        tests assert it mechanically."""
        problems = []
        rids = [rid] if rid is not None else self.rids()
        for r in rids:
            evs = self._by_rid.get(r, [])
            if not evs:
                problems.append(f"rid {r}: no events")
                continue
            if evs[0]["event"] != "submitted":
                problems.append(
                    f"rid {r}: first event is {evs[0]['event']!r}, "
                    f"not 'submitted'")
            last, last_wall, last_tick = None, None, None
            seen = set()
            for e in evs:
                ev = e["event"]
                if ev in _ONCE and ev in seen:
                    problems.append(
                        f"rid {r}: duplicate event {ev!r}")
                elif last is not None or ev == "submitted":
                    allowed = _NEXT[last]
                    if ev not in allowed:
                        problems.append(
                            f"rid {r}: {ev!r} out of order "
                            f"(after {last!r})")
                    elif ev == "finished" \
                            and "first_token" not in seen:
                        problems.append(
                            f"rid {r}: 'finished' before any "
                            f"'first_token'")
                seen.add(ev)
                last = ev
                w = e.get("wall")
                if w is not None and last_wall is not None \
                        and w < last_wall:
                    problems.append(
                        f"rid {r}: wall clock went backwards at "
                        f"{ev!r}")
                if w is not None:
                    last_wall = w
                t = e.get("tick")
                if t is not None and last_tick is not None \
                        and t < last_tick:
                    problems.append(
                        f"rid {r}: tick went backwards at "
                        f"{ev!r}")
                if t is not None:
                    last_tick = t
        return problems

    # ------------------------------------------------------------ gauges

    def sample_gauges(self, tick, wall, *, slots_active, num_slots,
                      queue_depth, kv_pages_live, kv_pages_total,
                      hol_wait_s, spec_drafted=0, spec_accepted=0,
                      prefix_hit_tokens=0, rejected=0, shed=0,
                      preempted=0, resubmitted=0, degraded_rounds=0):
        """One per-scheduler-round gauge sample (engine calls this at
        the end of each :meth:`ServingEngine.step`). Names mirror the
        registered telemetry metric specs (``telemetry.metrics``), so
        a ``MetricsWriter`` can sink :meth:`gauge_rows` directly. The
        generation counters (ISSUE 13) and the resilience counters
        (ISSUE 15: rejected / shed / preempted / resubmitted requests
        and degraded rounds) are CUMULATIVE as of this round — 0
        whenever the feature is off."""
        self.gauges.append({
            "tick": tick, "wall": wall,
            "serve_slots_active": int(slots_active),
            "serve_num_slots": int(num_slots),
            "serve_queue_depth": int(queue_depth),
            "serve_kv_pages_live": int(kv_pages_live),
            "serve_kv_pages_total": int(kv_pages_total),
            "serve_hol_wait_ms": round(float(hol_wait_s) * 1e3, 4),
            "serve_spec_drafted": int(spec_drafted),
            "serve_spec_accepted": int(spec_accepted),
            "serve_prefix_hit_tokens": int(prefix_hit_tokens),
            "serve_rejected": int(rejected),
            "serve_shed": int(shed),
            "serve_preempted": int(preempted),
            "serve_resubmitted": int(resubmitted),
            "serve_degraded_rounds": int(degraded_rounds),
        })

    def gauge_rows(self, run=None):
        """MetricsWriter-shaped rows (one per sample, ``step`` = tick)."""
        rows = []
        for g in self.gauges:
            row = {"step": g["tick"]}
            if run is not None:
                row["run"] = run
            row.update({k: v for k, v in g.items()
                        if k not in ("tick", "wall")})
            rows.append(row)
        return rows

    def summary(self):
        """Aggregate gauge account: the slo block's occupancy fields."""
        if not self.gauges:
            return {"max_queue_depth": None, "kv_page_high_water": None,
                    "max_slots_active": None, "max_hol_wait_ms": None,
                    "samples": 0}
        return {
            "max_queue_depth": max(g["serve_queue_depth"]
                                   for g in self.gauges),
            "kv_page_high_water": max(g["serve_kv_pages_live"]
                                      for g in self.gauges),
            "max_slots_active": max(g["serve_slots_active"]
                                    for g in self.gauges),
            "max_hol_wait_ms": max(g["serve_hol_wait_ms"]
                                   for g in self.gauges),
            "samples": len(self.gauges),
        }


# --------------------------------------------------------------------------
# per-request latency derivation + the slo block


def request_latencies(requests):
    """Per-request latency rows derived from the wall stamps the
    engine threads through admit/prefill/decode (seconds, host clock):
    ``{rid, ttft_s, tpot_s, n_out}`` — ``ttft_s`` None when either
    stamp is missing, ``tpot_s`` None for requests with < 2 tokens
    (no inter-token interval exists)."""
    rows = []
    for r in requests:
        n_out = len(getattr(r, "out_tokens", ()) or ())
        ttft = None
        if r.enqueue_wall is not None and r.first_token_wall is not None:
            ttft = max(0.0, r.first_token_wall - r.enqueue_wall)
        tpot = None
        if n_out >= 2 and r.first_token_wall is not None \
                and r.finish_wall is not None:
            tpot = max(0.0, (r.finish_wall - r.first_token_wall)
                       / (n_out - 1))
        rows.append({"rid": r.rid, "ttft_s": ttft, "tpot_s": tpot,
                     "n_out": n_out})
    return rows


def percentile(values, q):
    """Nearest-rank percentile of a list (None when empty) — the same
    convention as profile_serving's p50/p99 so the two latency
    surfaces can never disagree on method (at q=50 the index formula
    IS profile_serving's ``vals[n // 2]``)."""
    if not values:
        return None
    vals = sorted(values)
    return vals[min(len(vals) - 1, int(len(vals) * q / 100.0))]


def slo_block(requests, wall_s, *, ttft_ms, tpot_ms, arrival_process,
              offered_load, log=None, resilience=None,
              decode_block_k=1):
    """Assemble the validated ``slo`` ledger block from completed
    requests + the run's wall time (+ the EventLog's gauge summary
    when collection was on — occupancy fields null-degrade without
    it, never vanish). ``resilience`` (ISSUE 15) is the engine's
    ``resilience_rates()`` dict — ``shed_rate`` / ``preempt_rate`` /
    ``degraded_rounds``, each None when its knob is off (degradation,
    never omission; check 9 refuses a non-None rate whose selecting
    knob is unpinned or off). ``decode_block_k`` (ISSUE 17) is the
    engine's multi-token block size — the TTFT/TPOT trade the row
    embodies depends on it, so it rides the block and
    check_bench_labels check 8 refuses a row whose
    ``APEX_SERVE_DECODE_K`` pin disagrees with it."""
    lats = request_latencies(requests)
    ttfts = [x["ttft_s"] * 1e3 for x in lats if x["ttft_s"] is not None]
    tpots = [x["tpot_s"] * 1e3 for x in lats if x["tpot_s"] is not None]

    def _attains(x):
        if x["ttft_s"] is None or x["ttft_s"] * 1e3 > ttft_ms:
            return False
        # a 1-token request has no inter-token interval: TTFT decides
        return x["tpot_s"] is None or x["tpot_s"] * 1e3 <= tpot_ms

    attained = [x for x in lats if _attains(x)]
    good_tokens = sum(x["n_out"] for x in attained)
    summary = log.summary() if log is not None else {}

    def _r(v, nd=2):
        return None if v is None else round(v, nd)

    return {
        "ttft_p50_ms": _r(percentile(ttfts, 50)),
        "ttft_p99_ms": _r(percentile(ttfts, 99)),
        "per_token_p50_ms": _r(percentile(tpots, 50)),
        "per_token_p99_ms": _r(percentile(tpots, 99)),
        "goodput_tok_s": _r(good_tokens / wall_s if wall_s > 0 else None),
        "slo_attainment": _r(len(attained) / len(lats) if lats else None,
                             4),
        "slo_ttft_ms": float(ttft_ms),
        "slo_tpot_ms": float(tpot_ms),
        "arrival_process": arrival_process,
        "offered_load": _r(offered_load, 4),
        "requests": len(lats),
        "max_queue_depth": summary.get("max_queue_depth"),
        "kv_page_high_water": summary.get("kv_page_high_water"),
        "max_hol_wait_ms": summary.get("max_hol_wait_ms"),
        "shed_rate": _r((resilience or {}).get("shed_rate"), 4),
        "preempt_rate": _r((resilience or {}).get("preempt_rate"), 4),
        "degraded_rounds": (resilience or {}).get("degraded_rounds"),
        "decode_block_k": int(decode_block_k),
    }
