"""KV-cache memory hierarchy: int8 KV quantization + host swap tier.

The paged KV cache is the serving batch ceiling — every "at scale"
lever (continuous batching, multi-token blocks, TP sharding) runs out
of road when paged KV fills HBM. This module is the two-layer answer
(ROADMAP item 5, ISSUE 20), both layers default OFF per the
measured-dispatch rule:

* **int8 KV quantization** (``APEX_SERVE_KV_QUANT`` /
  ``ServingEngine(kv_quant=)``): the paged cache stores int8 K/V with
  per-(page, head) bf16 scales — ≈2x effective pages per chip, which
  raises the preemption threshold and the batch ceiling directly.
  Prefill's in-program page scatter quantizes at write
  (:func:`prefill_scatter_quant`); the decode step re-quantizes the
  single written page read-modify-write (:func:`decode_scatter_quant`);
  both attention consumers dequantize at read (the jnp gather
  reference and the Pallas decode kernel, where the scales ride as a
  second scalar-prefetch-indexed operand — see
  ops/decode_attention_pallas.py). Null page 0 stays all-zero through
  the codec: its scale is pinned to 0, and quantizing under a zero
  scale emits int8 zeros (:func:`inv_scale`). Non-finite inputs are
  poisoned to 0 before the amax (the PR 8 block-quant NaN-flush
  precedent — one NaN must not zero a whole page's scale arithmetic).

* **host swap tier** (``APEX_SERVE_KV_SWAP`` / ``engine(kv_swap=)``):
  on KV-pressure preemption the victim's live pages copy
  device→host between dispatches (the DurableCheckpointer staging
  precedent; quantized pages swap in their int8+scale wire format, so
  the quant layer halves swap bytes too) into a :class:`SwappedPages`
  handle stashed next to ``resume_tokens``; re-admission copies the
  pages back into freshly granted device pages and resumes decode
  directly, skipping replay prefill. Whether a resumed stream
  restores by swap-in or by recompute is a per-prompt-length
  dispatch decision (:func:`resolve_kv_restore`, op ``kv_restore``):
  the crossover against the ~65 ms relay dispatch floor is
  shape-dependent, never a constant.

Knob asymmetry (CLAUDE.md): the per-call engine knobs are demands
(``kv_swap=True`` with preemption resolved off raises in the engine
ctor; ``kv_restore="swap"`` with the host tier off raises here); the
env knobs are preferences that fall back per shape. This module is
jax-backed (the codec runs inside the jitted prefill/decode
programs) — the stdlib-only scheduler only ever holds the opaque
:class:`SwappedPages` handle it is handed.
"""

import dataclasses
import hashlib
from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

from apex_tpu import dispatch as _dispatch
from apex_tpu.dispatch import tiles as _tiles

# wire format of the quantized tier: int8 codes + per-(page, head)
# bf16 scales. bf16 is enough for a scale (it is an amax/127, consumed
# in fp32), and it halves the scale arrays' HBM + swap bytes.
CODE_DTYPE = jnp.int8
SCALE_DTYPE = jnp.bfloat16
QMAX = 127.0

SCALE_KEYS = ("k_scale", "v_scale")
RESTORE_CHOICES = ("recompute", "swap")


# ---------------------------------------------------------------------------
# knob resolution (engine per-call args are validated by the ENGINE —
# these resolvers own the env-preference legs)
# ---------------------------------------------------------------------------


def resolve_kv_quant(per_call=None):
    """The effective int8-KV decision: per-call (the engine's
    ``kv_quant=`` demand) > ``APEX_SERVE_KV_QUANT`` env preference
    (tiles.env_choice: unknown values warn once and are ignored) >
    built-in OFF (measured-dispatch rule — the 2x-pages argument is an
    expectation until the PERF.md §2 serving_kv_quant A/B commits)."""
    if per_call is not None:
        return bool(per_call)
    v = _tiles.env_choice("APEX_SERVE_KV_QUANT", ("1", "0"))
    if v is not None:
        return v == "1"
    return False


def resolve_kv_swap(per_call=None):
    """The effective host-swap-tier decision: per-call demand >
    ``APEX_SERVE_KV_SWAP`` env preference > built-in OFF. The
    preemption pairing (swap without preemption is dead weight) is the
    ENGINE ctor's job — it sees whether each side was a demand."""
    if per_call is not None:
        return bool(per_call)
    v = _tiles.env_choice("APEX_SERVE_KV_SWAP", ("1", "0"))
    if v is not None:
        return v == "1"
    return False


def resolve_kv_restore(per_call=None, *, swap_enabled, tokens, dtype,
                       backend=None):
    """The restore path for ONE resumed stream of ``tokens`` known
    tokens: per-call demand (raises when un-honorable — ``"swap"``
    demanded with the host tier off has no honorable answer) >
    ``APEX_SERVE_KV_RESTORE`` env preference > ``kv_restore``
    dispatch-table entry at bucket ``s=tokens`` (the committed
    per-prompt-length crossover) > built-in ``"swap"`` (with the tier
    ON, using the banked pages is the capability the knob bought;
    the table refines the shape-dependent crossover). With the tier
    off every preference falls back to ``"recompute"`` — the
    replay-prefill path preemption always had."""
    if per_call is not None:
        if per_call not in RESTORE_CHOICES:
            raise ValueError(
                f"unknown kv_restore {per_call!r} "
                f"(vocabulary: {RESTORE_CHOICES})")
        if per_call == "swap" and not swap_enabled:
            raise ValueError(
                "kv_restore='swap' demanded but the host swap tier is "
                "off (enable kv_swap=/APEX_SERVE_KV_SWAP=1) — no "
                "honorable way to restore from pages that were never "
                "banked")
        return per_call
    if not swap_enabled:
        return "recompute"
    v = _tiles.env_choice("APEX_SERVE_KV_RESTORE", RESTORE_CHOICES)
    if v is not None:
        return v
    choice = _dispatch.lookup("kv_restore", dtype, backend=backend,
                              s=max(1, int(tokens)))
    if choice is not None:
        return choice
    return "swap"


# ---------------------------------------------------------------------------
# the int8 codec (pure jnp — runs inside the jitted programs)
# ---------------------------------------------------------------------------


def is_quantized(cache):
    """Whether a cache dict carries the int8 tier's scale leaves."""
    return "k_scale" in cache


def finite(x):
    """Non-finite poisoning (the PR 8 NaN-flush precedent): NaN/Inf
    inputs become 0 BEFORE any amax, so one poisoned activation can
    neither NaN a page scale nor saturate it to Inf."""
    return jnp.where(jnp.isfinite(x), x, jnp.zeros_like(x))


def inv_scale(scale):
    """Guarded fp32 reciprocal of a scale array: 0 where the scale is
    0 (the null page / an all-zero page), so quantizing under a dead
    scale emits exact int8 zeros instead of NaN codes."""
    s = scale.astype(jnp.float32)
    return jnp.where(s > 0, 1.0 / jnp.where(s > 0, s, 1.0),
                     jnp.zeros_like(s))


def quantize(x, scale):
    """int8 codes of ``x`` under per-leading-dims ``scale`` (broadcast
    over the trailing ``(page_size, head_dim)`` dims)."""
    inv = inv_scale(scale)[..., None, None]
    q = jnp.round(finite(x).astype(jnp.float32) * inv)
    return jnp.clip(q, -QMAX, QMAX).astype(CODE_DTYPE)


def dequantize(q, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize` (per-leading-dims scale broadcast
    over the trailing two dims)."""
    return (q.astype(jnp.float32)
            * scale.astype(jnp.float32)[..., None, None]).astype(dtype)


def init_scales(num_layers, num_heads, num_pages):
    """Zeroed per-(page, head) scale leaves ``{"k_scale", "v_scale"}``
    of ``[layers, h, num_pages]`` — the page axis sits at axis 2 like
    the code arrays', so the engine's page-copy/gather/scatter helpers
    treat every cache leaf uniformly, and the head axis at axis 1
    means the TP ``cache_shardings`` head split covers the scales
    too."""
    shape = (num_layers, num_heads, num_pages)
    return {k: jnp.zeros(shape, SCALE_DTYPE) for k in SCALE_KEYS}


def prefill_scatter_quant(cache, layer, part, val, dest_page, dest_off,
                          keep_scale):
    """Quantize-at-write page scatter for the packed prefill program
    (the quant-tier replacement of the plain
    ``cache[part].at[layer, :, dest_page, dest_off, :].set(...)``).

    ``val`` is the layer's fresh K or V rows ``[s, h, d]``;
    ``dest_page``/``dest_off`` the packed rows' page/offset ``[s]``;
    ``keep_scale`` ``[num_pages]`` is 1 for pages whose existing
    content (and scale) is still live — a verify pass re-covering a
    partially filled page — and 0 for pages freshly granted to this
    prefill, whose stale codes and scale are dead. Functional
    recipe (no data-dependent shapes, so the one-compile contract
    holds): scatter-max the fresh rows' amax into a per-(head, page)
    scale floor, grow each destination page's surviving scale to
    cover it, re-quantize the whole layer under the grown scales
    (ratio 1 for untouched pages — bit-identical codes; ratio 0 for
    fresh pages and the null page — stale garbage zeroed), then
    quantize and scatter the fresh rows. Page 0's scale is pinned to
    0, so padded rows (which the packer routes to page 0) quantize to
    exact zeros — the null page stays all-zero through the codec."""
    q = cache[part]                      # [L, h, P, ps, d] int8
    sc = cache[part + "_scale"]          # [L, h, P] bf16
    h, num_pages = q.shape[1], q.shape[2]
    vf = finite(val.astype(jnp.float32))                 # [s, h, d]
    row_amax = jnp.max(jnp.abs(vf), axis=-1)             # [s, h]
    amax_pages = jnp.zeros((h, num_pages), jnp.float32)
    amax_pages = amax_pages.at[:, dest_page].max(row_amax.T)
    old = sc[layer].astype(jnp.float32) * keep_scale[None, :]
    new_scale = jnp.maximum(old, amax_pages / QMAX)
    new_scale = new_scale.at[:, 0].set(0.0)              # null page pin
    ratio = jnp.where(new_scale > 0,
                      old / jnp.where(new_scale > 0, new_scale, 1.0),
                      jnp.zeros_like(new_scale))
    requant = jnp.clip(jnp.round(q[layer].astype(jnp.float32)
                                 * ratio[:, :, None, None]),
                       -QMAX, QMAX)
    dest_scale = new_scale[:, dest_page]                 # [h, s]
    rows = jnp.round(vf * inv_scale(dest_scale).T[:, :, None])
    rows = jnp.clip(rows, -QMAX, QMAX)                   # [s, h, d]
    updated = requant.at[:, dest_page, dest_off, :].set(
        rows.transpose(1, 0, 2))
    cache[part] = q.at[layer].set(updated.astype(CODE_DTYPE))
    cache[part + "_scale"] = sc.at[layer].set(
        new_scale.astype(SCALE_DTYPE))
    return cache


def decode_scatter_quant(cache, layer, part, val, write_page, write_off):
    """Quantize-at-write for the decode step's single-row scatter: a
    per-page read-modify-write (gather the B written pages — a
    ``[h, B, ps, d]`` transient, cheap — dequantize, zero the rows at
    and beyond the write offset (a freshly granted page arrives with
    ``write_off == 0``, so its stale garbage dies here without any
    alloc-time zeroing), insert the new row, re-derive the page scale
    from the page's live content, re-quantize, scatter back).
    ``val`` is ``[B, h, d]``; ``write_page``/``write_off`` ``[B]``
    with inactive lanes routed to page 0 — whose re-derived scale is
    forced to 0, so page 0 is re-written with exact zeros."""
    q = cache[part]                      # [L, h, P, ps, d] int8
    sc = cache[part + "_scale"]          # [L, h, P] bf16
    ps = q.shape[3]
    pages_q = q[layer][:, write_page]                    # [h, B, ps, d]
    pscale = sc[layer][:, write_page]                    # [h, B]
    pf = dequantize(pages_q, pscale)                     # [h, B, ps, d]
    row_ids = jnp.arange(ps)[None, None, :, None]
    pf = jnp.where(row_ids < write_off[None, :, None, None], pf,
                   jnp.zeros_like(pf))
    vf = finite(val.astype(jnp.float32)).transpose(1, 0, 2)  # [h, B, d]
    pf = pf.at[:, jnp.arange(vf.shape[1]), write_off, :].set(vf)
    amax = jnp.max(jnp.abs(pf), axis=(-2, -1))           # [h, B]
    new_scale = jnp.where(write_page[None, :] == 0,
                          jnp.zeros_like(amax), amax / QMAX)
    pq = jnp.clip(jnp.round(pf * inv_scale(new_scale)[..., None, None]),
                  -QMAX, QMAX).astype(CODE_DTYPE)
    cache[part] = q.at[layer, :, write_page].set(
        pq.transpose(1, 0, 2, 3))
    cache[part + "_scale"] = sc.at[layer, :, write_page].set(
        new_scale.astype(SCALE_DTYPE).T)
    return cache


# ---------------------------------------------------------------------------
# the host swap tier
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SwappedPages:
    """Host-side copy of one preempted stream's live pages, in wire
    format (bf16 pages plain; int8 codes + bf16 scales under the quant
    tier — the quant layer halves swap bytes too). ``leaves`` maps
    each cache leaf name to a numpy array whose page axis (axis 2) is
    padded to the engine's ``max_pages`` with null-page content, so
    the device gather/scatter programs compile exactly once. The
    sha1 seals the banked bytes: a corrupt handle (the ``serve_swap``
    chaos site's damage mode) is detected at swap-in and the stream
    falls back to recompute — degraded restore latency, never a
    corrupted token stream."""

    leaves: Dict[str, Any]
    page_count: int           # live pages banked (≤ the padded axis)
    tokens: int               # known-stream length the pages cover
    quant: bool
    checksum: Optional[str] = None

    def nbytes(self):
        return int(sum(a.nbytes for a in self.leaves.values()))

    def _digest(self):
        h = hashlib.sha1()
        h.update(repr((self.page_count, self.tokens,
                       self.quant)).encode())
        for name in sorted(self.leaves):
            arr = np.ascontiguousarray(self.leaves[name])
            h.update(name.encode())
            h.update(str(arr.dtype).encode())
            h.update(repr(arr.shape).encode())
            h.update(arr.tobytes())
        return h.hexdigest()

    def seal(self):
        self.checksum = self._digest()
        return self

    def intact(self):
        """Whether the banked bytes still match the seal."""
        return self.checksum is not None \
            and self.checksum == self._digest()


@dataclasses.dataclass
class KVTierStats:
    """Host-side counters of the swap tier's economics — the source of
    the serving ledger block's ``swap_rate`` /
    ``swapped_pages_high_water`` fields and window_report's
    KV-economics line. ``None``-when-disabled is the ENGINE's account
    (degradation, never omission); these counters just count."""

    swap_outs: int = 0
    swap_out_failures: int = 0
    swap_ins: int = 0
    swap_in_failures: int = 0
    restores_swap: int = 0
    restores_recompute: int = 0
    swapped_pages_live: int = 0
    swapped_pages_high_water: int = 0
    swapped_bytes_live: int = 0
    swapped_bytes_high_water: int = 0

    def banked(self, handle):
        self.swap_outs += 1
        self.swapped_pages_live += handle.page_count
        self.swapped_bytes_live += handle.nbytes()
        self.swapped_pages_high_water = max(
            self.swapped_pages_high_water, self.swapped_pages_live)
        self.swapped_bytes_high_water = max(
            self.swapped_bytes_high_water, self.swapped_bytes_live)

    def released(self, handle):
        self.swapped_pages_live -= handle.page_count
        self.swapped_bytes_live -= handle.nbytes()
