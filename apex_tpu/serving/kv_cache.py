"""Paged KV cache: block-granular allocation as index arithmetic.

Device side: per layer, K and V live as ``[h, num_pages, page_size,
head_dim]`` arrays stacked over layers into ``[layers, h, num_pages,
page_size, head_dim]`` — the page axis is a plain array axis, so
"allocating" a page to a sequence is writing its index into that
sequence's page-table row and "freeing" it is forgetting the index.
No reshape, no growing array, no recompile: the decode step's operand
shapes are fixed for the life of the engine, whatever the scheduler
does between steps (the ISSUE 10 jaxpr-stability contract, asserted
by tests/test_serving.py).

The head axis leads the page axis because the decode-attention
kernel's BlockSpec tiles heads (``block_h``) while the page block's
trailing ``(page_size, head_dim)`` dims span their full array axes —
Mosaic's last-two-dims rule is then satisfied for every legal head
block (see ops/decode_attention_pallas.py).

Host side: :class:`PageAllocator` — an explicit free list over pages
``1..num_pages-1``. Page 0 is RESERVED as the null page: padded
page-table tails and padded prefill tokens point at it, so a garbage
index can never alias a live sequence's data (the kernel skips those
positions by context length; the null page absorbs the writes).
"""

import jax.numpy as jnp


def init_cache(num_layers, num_heads, num_pages, page_size, head_dim,
               dtype=jnp.bfloat16, kv_quant=False):
    """Zeroed cache dict ``{"k", "v"}`` of
    ``[layers, h, num_pages, page_size, head_dim]`` arrays.

    ``kv_quant=True`` (the int8 KV tier, ISSUE 20) stores the code
    arrays as int8 and adds per-(page, head) bf16 scale leaves
    ``{"k_scale", "v_scale"}`` of ``[layers, h, num_pages]`` — pages
    at axis 2 and heads at axis 1 exactly like the code arrays, so
    page-copy helpers and the TP ``cache_shardings`` treat every leaf
    uniformly. Zero scales make the all-zero init exact: a zero scale
    dequantizes (and quantizes) to exact zeros, which is also what
    pins null page 0 dead through the codec."""
    shape = (num_layers, num_heads, num_pages, page_size, head_dim)
    if kv_quant:
        from apex_tpu.serving import kv_tier

        cache = {"k": jnp.zeros(shape, kv_tier.CODE_DTYPE),
                 "v": jnp.zeros(shape, kv_tier.CODE_DTYPE)}
        cache.update(kv_tier.init_scales(num_layers, num_heads,
                                         num_pages))
        return cache
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def pages_needed(tokens, page_size):
    """Pages to hold ``tokens`` positions at this page size."""
    return -(-int(tokens) // int(page_size))


class PageAllocator:
    """Explicit-free-list page allocator (host-side, stdlib-only).

    Pages ``1..num_pages-1`` are allocatable; page 0 is the reserved
    null page (module docstring). Allocation is all-or-nothing per
    request: :meth:`alloc` returns the page list or None when the free
    list is short — the scheduler then leaves the request queued
    (admission control, never a partial grant).
    """

    def __init__(self, num_pages):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is reserved)")
        self.num_pages = int(num_pages)
        # LIFO free list: recently freed pages are re-used first (their
        # cache lines are the warmest)
        self._free = list(range(1, self.num_pages))
        self._owned = {}  # owner id -> list of page indices

    @property
    def free_count(self):
        return len(self._free)

    def live_pages(self, owner=None):
        if owner is not None:
            return list(self._owned.get(owner, ()))
        return [p for pages in self._owned.values() for p in pages]

    def alloc(self, owner, n):
        """Allocate ``n`` pages to ``owner`` (appending to any it
        already holds); returns the new page list or None when the
        free list cannot cover the request (state unchanged)."""
        n = int(n)
        if n == 0:
            return []  # no phantom empty ownership entry either
        if len(self._free) < n:
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._owned.setdefault(owner, []).extend(pages)
        return pages

    def free(self, owner):
        """Return all of ``owner``'s pages to the free list."""
        for p in self._owned.pop(owner, ()):
            self._free.append(p)

    def transfer(self, owner_from, owner_to, pages):
        """Move specific ``pages`` between owners — the prefix-cache
        adoption hop (ISSUE 13: a registering request's prompt pages
        become cache-owned without round-tripping the free list, so
        their K/V content is never up for reallocation mid-transfer).
        Accounting only; the live set is unchanged. Raises when
        ``owner_from`` does not own every page (state unchanged)."""
        have = self._owned.get(owner_from, [])
        missing = [p for p in pages if p not in have]
        if missing:
            raise ValueError(
                f"pages {missing} are not owned by {owner_from!r}")
        for p in pages:
            have.remove(p)
            self._owned.setdefault(owner_to, []).append(p)
        if not have:
            self._owned.pop(owner_from, None)

    def check_invariants(self):
        """Raise AssertionError on aliasing or accounting drift — the
        test surface for the paged-allocator invariants (ISSUE 10):
        no page owned twice, no page both free and owned, page 0 never
        handed out, free + live == allocatable."""
        live = self.live_pages()
        assert len(live) == len(set(live)), (
            f"page aliasing across live owners: {sorted(live)}")
        assert 0 not in live and 0 not in self._free, (
            "null page 0 escaped the reservation")
        overlap = set(live) & set(self._free)
        assert not overlap, f"pages both free and owned: {overlap}"
        assert len(live) + len(self._free) == self.num_pages - 1, (
            f"accounting drift: {len(live)} live + "
            f"{len(self._free)} free != {self.num_pages - 1}")
