"""Fleet-scale serving: a multi-replica router over real engines (ISSUE 19).

"Millions of users" is a router problem, not a single-engine problem
(ROADMAP item 4): PR 15 built the per-engine half of fault tolerance —
classified dispatch failures, requeue, token-parity replay — but
nothing survived the loss of a whole replica. This module is the fleet
layer: one :class:`Router` drives N real :class:`ServingEngine`
replicas under one shared ``synthetic_trace``, with replica-level
health, failover, and admission composition, using the
concurrency-limits framing of PAPERS.md arXiv:2011.03641 for the
per-replica in-flight caps.

Four cooperating pieces:

* **Routing policies** (``policy=`` > ``APEX_ROUTE_POLICY``, vocabulary
  ``round_robin`` | ``least_loaded`` | ``prefix_affinity``; the
  CLAUDE.md asymmetry — per-call unknown policies raise, the env
  preference warns once and falls back): ``round_robin`` cycles
  routable replicas; ``least_loaded`` picks the smallest queued +
  in-flight count; ``prefix_affinity`` routes by the SAME sha1 chain
  hash the prefix cache keys pages on
  (:func:`~apex_tpu.serving.prefix_cache._page_hash` over the prompt's
  first page), rendezvous-hashed over the live replica set — so
  fleet-wide prefix hit-rate becomes a measurable function of routing
  policy (requests sharing a system prompt land on the same replica
  and prefill it once per REPLICA instead of once per round-robin
  stripe). Default ``round_robin`` per the measured-dispatch rule: the
  CPU-mesh measurement (PERF.md §2) quantifies the hit-rate delta the
  affinity policy buys, and the end-to-end goodput A/B that could flip
  the default is queued behind the ``serving_router`` device rung.
* **Per-replica health state machine** ``healthy → degraded → dead →
  draining → rejoined`` (:data:`_HEALTH_NEXT`; :func:`validate_health`
  is the mechanical invariant surface), fed by the engine's classified
  :class:`~apex_tpu.serving.resilience.DispatchFailure` verdicts — a
  failure escaping a replica's round (or a degraded round its own
  watchdog recovered) marks it ``degraded``; ``breaker_failures``
  CONSECUTIVE failures trip the circuit breaker to ``dead``. A dead
  replica's re-admission is bounded and paced by the PR 4
  :class:`~apex_tpu.resilience.RetryPolicy` state machine (clocked in
  router rounds, never wall sleeps — a host sleep would stall every
  healthy replica): after the paced wait the router marks it
  ``draining`` and drives a fabricated PROBE request through the real
  engine; a completed probe rejoins the replica, a failed one returns
  it to ``dead`` until the probe budget exhausts.
* **Failover** — the zero-loss invariant: when a replica dies
  mid-trace (chaos-killed or breaker-tripped),
  :meth:`ServingEngine.drain_for_failover` requeues its in-flight
  requests exactly like KV-pressure preemption does (pages freed,
  prefix refcounts respected, the known stream stashed in
  ``resume_tokens``) and hands them — plus its still-queued requests —
  back to the router, which REPLAYS them through surviving replicas
  via the existing prefill-replay path. Greedy decode is deterministic
  and the replicas share params, so the replayed stream is
  token-for-token the unkilled single-engine run's (pinned by
  tests/test_router_chaos.py and ``dryrun_router``); an accepted
  request is NEVER dropped — failover replays bypass admission (the
  fleet already accepted that load), and requests orphaned by a total
  outage park in the router until a replica rejoins.
* **Admission composition** (arXiv:2011.03641 concurrency limits):
  ``replica_inflight`` caps each replica's queued + in-flight count
  (the router skips a full replica and tries the next candidate) and
  ``fleet_admit`` caps the fleet total — the structured
  :class:`~apex_tpu.serving.resilience.Rejected` composes with
  distinct reasons (``fleet_full`` ≠ ``replica_full`` ≠ the engine's
  own ``queue_full``), so a fleet-level shed is never mistaken for one
  hot replica. Both are per-call demands (garbage raises; 0 = off).
  :class:`AutoscalePolicy` adds the first scale-out story: replicas
  beyond ``min_replicas`` start parked and join only after fleet load
  has held above ``high_water`` for ``lag_rounds`` consecutive rounds
  — the static-N vs lagged-scale-out A/B under the diurnal trace
  (``benchmarks/profile_router.py``; the device A/B is queued in
  PERF.md §2).

Chaos surface: the ``router_kill`` / ``router_wedge`` / ``router_slow``
fault sites (``apex_tpu.resilience.faults``) fire inside each
replica's round closure — an injected raise/hang lands exactly where a
dying replica's dispatch would — so tests/test_router_chaos.py drives
every failover path through real engines.

Lifecycle: the router rebinds every replica's event log to ONE fleet
:class:`~apex_tpu.serving.lifecycle.EventLog` (gated on
``lifecycle.enabled()`` like the engine) and extends the per-request
chain with ``routed`` (assignment to a replica), ``failover`` (pulled
off a dead replica) and ``replayed`` (resubmitted through a survivor);
``validate_order`` covers the full failover cycle. Replica engine
ticks are fast-forwarded to the router round on unpark/probe-start so
the one fleet log keeps per-request tick monotonicity.

Stdlib-only (like ``scheduler``/``lifecycle``/``prefix_cache``): the
router is host logic over engines it is handed — it never imports jax,
and ``ledger.validate_record``'s ``router`` block teeth plus
``tools/window_report.py``'s FLEET section consume its output without
touching one.
"""

import dataclasses
import hashlib
import math
import time
from typing import Any, List, Optional

from apex_tpu import resilience as res_mod
from apex_tpu.dispatch import tiles as _tiles
from apex_tpu.resilience import faults as _faults
from apex_tpu.serving import lifecycle
from apex_tpu.serving import resilience as serve_res
from apex_tpu.serving.prefix_cache import ROOT, _page_hash
from apex_tpu.serving.scheduler import Request

ROUTE_POLICIES = ("round_robin", "least_loaded", "prefix_affinity")

# health vocabulary + transition machine (validate_health walks it)
HEALTHY, DEGRADED, DEAD = "healthy", "degraded", "dead"
DRAINING, REJOINED = "draining", "rejoined"
HEALTH_STATES = (HEALTHY, DEGRADED, DEAD, DRAINING, REJOINED)
_HEALTH_NEXT = {
    HEALTHY: (DEGRADED,),
    DEGRADED: (HEALTHY, DEAD),
    DEAD: (DRAINING,),
    DRAINING: (DEAD, REJOINED),
    REJOINED: (HEALTHY, DEGRADED),
}

# circuit breaker + re-admission probe defaults (constructor demands
# override; the cited row pins what its harness resolved)
ROUTE_BREAKER_FAILURES = 3
ROUTE_PROBE_ATTEMPTS = 3
ROUTE_PROBE_WAIT_ROUNDS = 4
ROUTE_PROBE_ROUNDS = 16     # rounds a probe may run before it counts
#                             as a failed re-admission attempt
_PROBE_RID_BASE = 8_000_000  # fabricated probe rids (serve_burst's
#                              storm uses 9_000_000 — disjoint ranges)


def resolve_route_policy(per_call=None):
    """The effective routing policy: per-call (raises on unknown — an
    explicit request is a demand) > ``APEX_ROUTE_POLICY`` env
    preference (warn-once-and-ignore on unknown) > built-in
    ``round_robin`` (the neutral baseline; the prefix-affinity
    hit-rate delta is measured in PERF.md §2 and the goodput A/B that
    could flip this default is queued there)."""
    if per_call is not None:
        if per_call not in ROUTE_POLICIES:
            raise ValueError(
                f"unknown routing policy {per_call!r} "
                f"(vocabulary: {ROUTE_POLICIES})")
        return per_call
    return _tiles.env_choice("APEX_ROUTE_POLICY", ROUTE_POLICIES) \
        or "round_robin"


def resolve_route_replicas(per_call=None):
    """The fleet replica count a harness builds: per-call (a positive
    int — anything else raises) > ``APEX_ROUTE_REPLICAS`` env
    preference (``tiles.env_int``: garbage warns once and is ignored)
    > built-in 2 (the smallest fleet with a failover survivor). A
    cited ``router`` row pins the RESOLVED value
    (tools/check_bench_labels.py check 12)."""
    if per_call is not None:
        if isinstance(per_call, bool) or not isinstance(per_call, int) \
                or per_call < 1:
            raise ValueError(
                f"replicas= wants a positive int, got {per_call!r}")
        return per_call
    return _tiles.env_int("APEX_ROUTE_REPLICAS") or 2


def validate_health(history):
    """Ordering problems (empty list = clean) of one replica's health
    history: it must start ``healthy`` and walk :data:`_HEALTH_NEXT` —
    the mechanical invariant surface the chaos tests and
    ``dryrun_router`` assert, mirroring ``lifecycle.validate_order``."""
    problems = []
    if not history:
        return ["empty health history"]
    if history[0] != HEALTHY:
        problems.append(f"history starts at {history[0]!r}, "
                        f"not 'healthy'")
    for prev, cur in zip(history, history[1:]):
        if cur not in _HEALTH_NEXT.get(prev, ()):
            problems.append(f"{prev!r} -> {cur!r} is not a legal "
                            f"health transition")
    return problems


@dataclasses.dataclass
class Replica:
    """One engine under the router: health state + history, breaker
    and probe bookkeeping, and the per-replica routing account."""
    name: str
    engine: Any
    index: int = 0
    state: str = HEALTHY
    history: List[str] = dataclasses.field(
        default_factory=lambda: [HEALTHY])
    consecutive_failures: int = 0
    last_verdict: Optional[str] = None
    parked: bool = False          # autoscale: built but not yet live
    routed: int = 0               # requests assigned here
    # re-admission probe state (armed at death)
    retry: Any = None             # RetryPolicy
    probe_attempts_left: int = 0
    probe_wait_rounds: int = 0
    probe: Any = None             # the in-flight probe Request
    probe_rounds: int = 0
    _degraded_seen: int = 0       # engine degraded_rounds high-water

    def set_state(self, state):
        if state not in _HEALTH_NEXT.get(self.state, ()):
            raise RuntimeError(
                f"replica {self.name}: illegal health transition "
                f"{self.state!r} -> {state!r}")
        self.state = state
        self.history.append(state)

    def routable(self):
        return not self.parked and self.state in (HEALTHY, DEGRADED,
                                                  REJOINED)

    def inflight(self):
        """Queued + in-flight count — the concurrency-limit quantity
        (arXiv:2011.03641) ``least_loaded`` and both admission caps
        meter."""
        sch = self.engine.scheduler
        return sch.queue_depth() + len(sch.active_indices())


@dataclasses.dataclass
class AutoscalePolicy:
    """Lagged scale-out (the first autoscaling story): replicas beyond
    ``min_replicas`` start parked and one is unparked each time fleet
    load (in-flight over live slot capacity) has held above
    ``high_water`` for ``lag_rounds`` CONSECUTIVE router rounds — the
    reaction lag the static-N vs scale-out A/B measures under the
    diurnal trace. Scale-in is deliberately absent: the first A/B
    isolates scale-OUT lag."""
    min_replicas: int
    high_water: float = 0.75
    lag_rounds: int = 8

    def __post_init__(self):
        if isinstance(self.min_replicas, bool) \
                or not isinstance(self.min_replicas, int) \
                or self.min_replicas < 1:
            raise ValueError(
                f"min_replicas wants a positive int, got "
                f"{self.min_replicas!r}")
        if not 0.0 < float(self.high_water) <= 1.0:
            raise ValueError(
                f"high_water wants a fraction in (0, 1], got "
                f"{self.high_water!r}")
        if isinstance(self.lag_rounds, bool) \
                or not isinstance(self.lag_rounds, int) \
                or self.lag_rounds < 1:
            raise ValueError(
                f"lag_rounds wants a positive int, got "
                f"{self.lag_rounds!r}")


class Router:
    """N real ServingEngine replicas under one routing policy, with
    replica health, circuit-breaking, failover replay and composed
    admission (module docstring). Constructor arguments are per-call
    DEMANDS (garbage raises); only the policy falls back through its
    env preference."""

    def __init__(self, engines, *, policy=None, fleet_admit=0,
                 replica_inflight=0, breaker_failures=None,
                 probe_attempts=None, probe_wait_rounds=None,
                 step_timeout_s=None, autoscale=None, names=None):
        if not engines:
            raise ValueError("Router wants at least one engine")
        self.policy = resolve_route_policy(policy)
        for k, v in (("fleet_admit", fleet_admit),
                     ("replica_inflight", replica_inflight)):
            if isinstance(v, bool) or not isinstance(v, int) or v < 0:
                raise ValueError(
                    f"{k}= wants a non-negative int (0 = off), "
                    f"got {v!r}")
        self.fleet_admit = fleet_admit
        self.replica_inflight = replica_inflight
        self.breaker_failures = int(
            breaker_failures if breaker_failures is not None
            else ROUTE_BREAKER_FAILURES)
        if self.breaker_failures < 1:
            raise ValueError("breaker_failures wants >= 1")
        self.probe_attempts = int(
            probe_attempts if probe_attempts is not None
            else ROUTE_PROBE_ATTEMPTS)
        self.probe_wait_rounds = int(
            probe_wait_rounds if probe_wait_rounds is not None
            else ROUTE_PROBE_WAIT_ROUNDS)
        self.step_timeout_s = step_timeout_s
        self.probe_rounds_cap = ROUTE_PROBE_ROUNDS
        # replicas must be interchangeable for replay parity and the
        # affinity hash: same prefill bucket, same page geometry. The
        # deferred-fetch overlapped round holds placeholder tokens a
        # failover drain would replay as values — same incompatibility
        # as preemption (engine docstring), so a router over an
        # overlapped engine raises.
        e0 = engines[0]
        for e in engines:
            if e.prefill_len != e0.prefill_len \
                    or e.page_size != e0.page_size:
                raise ValueError(
                    "Router replicas must share prefill_len/page_size "
                    "(failover replays and the affinity hash assume "
                    "interchangeable replicas)")
            if getattr(e, "overlap", False):
                raise ValueError(
                    "Router cannot drive an overlapped engine: the "
                    "deferred-fetch round holds placeholder tokens a "
                    "failover drain would replay as values")
        self.page_size = e0.page_size
        self.replicas = [
            Replica(name=(names[i] if names else f"r{i}"), engine=e,
                    index=i)
            for i, e in enumerate(engines)]
        if autoscale is not None:
            if not isinstance(autoscale, AutoscalePolicy):
                raise ValueError(
                    f"autoscale= wants an AutoscalePolicy or None, "
                    f"got {autoscale!r}")
            for r in self.replicas[autoscale.min_replicas:]:
                r.parked = True
        self.autoscale = autoscale
        self._over_water = 0      # consecutive rounds above high_water
        # ONE fleet event log: every replica's lifecycle events land in
        # it, so validate_order sees the full cross-replica chain
        # (rebinding happens right after engine construction — the
        # per-engine logs it replaces are empty)
        self.events = lifecycle.EventLog() if lifecycle.enabled() \
            else None
        for r in self.replicas:
            r.engine.events = self.events
        self.tick = 0
        self._rr = 0              # round-robin cursor
        self._probe_seq = 0
        self.rejected = []        # [(request, Rejected)] at the router
        self._orphans = []        # accepted requests with no live home
        self.gauges = []          # MetricsWriter-shaped fleet samples
        self.stats = {"routed": 0, "failovers": 0, "replayed": 0,
                      "rejected_fleet": 0, "rejected_replica": 0,
                      "deaths": 0, "probes": 0, "rejoins": 0,
                      "scale_outs": 0}

    # --------------------------------------------------------- routing

    def _chain_hash(self, prompt):
        """The prompt's first-page chain hash — the SAME sha1 chain the
        prefix cache keys its pages on, so affinity routing and cache
        hits agree on what "same prefix" means."""
        return _page_hash(ROOT, list(prompt[:self.page_size]))

    def _candidates(self, request):
        """Routable replicas in policy order for *request* (empty when
        the whole fleet is down). ``prefix_affinity`` rendezvous-hashes
        the prompt's chain hash over replica names — stable under
        membership change: a dead replica's keys move, everyone else's
        stay put."""
        routable = [r for r in self.replicas if r.routable()]
        if not routable:
            return []
        if self.policy == "least_loaded":
            return sorted(routable, key=lambda r: (r.inflight(),
                                                   r.index))
        if self.policy == "prefix_affinity":
            chain = self._chain_hash(request.prompt)
            return sorted(
                routable, reverse=True,
                key=lambda r: hashlib.sha1(
                    (chain + r.name).encode()).hexdigest())
        start = self._rr % len(routable)
        self._rr += 1
        return routable[start:] + routable[:start]

    def _record(self, event, rid, wall=None):
        if self.events is not None:
            self.events.record(
                event, rid, tick=self.tick,
                wall=time.perf_counter() if wall is None else wall)

    def fleet_inflight(self):
        return len(self._orphans) + sum(r.inflight()
                                        for r in self.replicas)

    def submit(self, request):
        """Route one request: fleet admission, then the policy's
        candidate order with per-replica concurrency caps — the first
        replica with room takes it (its engine's own admission bound
        still applies underneath). Returns None when routed, else a
        structured ``Rejected`` whose reason names WHICH limit refused:
        ``fleet_full`` (the fleet cap), ``replica_full`` (every
        routable replica at its cap or bound), ``no_replica`` (the
        whole fleet is down/parked). Malformed requests raise before
        anything is recorded — a full fleet rejects load, it never
        masks a programming error."""
        self.replicas[0].engine.validate_request(request)
        slots = sum(r.engine.num_slots for r in self.replicas
                    if r.routable()) or 1
        if self.fleet_admit \
                and self.fleet_inflight() >= self.fleet_admit:
            rej = serve_res.Rejected(
                "fleet_full",
                max(1, -(-self.fleet_inflight() // slots)))
            self.stats["rejected_fleet"] += 1
            self.rejected.append((request, rej))
            wall = time.perf_counter()
            self._record("submitted", request.rid, wall)
            self._record("rejected", request.rid, wall)
            return rej
        order = self._candidates(request)
        reason = "no_replica"
        for r in order:
            reason = "replica_full"
            if self.replica_inflight \
                    and r.inflight() >= self.replica_inflight:
                continue
            if r.engine.submit(request, quiet=True) is None:
                r.routed += 1
                self.stats["routed"] += 1
                wall = time.perf_counter()
                self._record("submitted", request.rid, wall)
                self._record("routed", request.rid, wall)
                return None
            # the engine's own admission bound refused — next candidate
        rej = serve_res.Rejected(
            reason, max(1, -(-self.fleet_inflight() // slots)))
        self.stats["rejected_replica"] += 1
        self.rejected.append((request, rej))
        wall = time.perf_counter()
        self._record("submitted", request.rid, wall)
        self._record("rejected", request.rid, wall)
        return rej

    # ------------------------------------------------ failover + replay

    def _replay(self, requests):
        """Resubmit failed-over requests through survivors. Replays
        BYPASS admission (``replay=True`` — the fleet already accepted
        this load; dropping it at requeue would break the zero-loss
        invariant) and keep their original ``enqueue_wall`` (failover
        must not hide queue latency). With no routable survivor the
        requests park in ``_orphans`` and retry when one rejoins."""
        for req in requests:
            order = self._candidates(req)
            if not order:
                self._orphans.append(req)
                continue
            order[0].engine.submit(req, quiet=True, replay=True)
            self.stats["replayed"] += 1
            self._record("replayed", req.rid)

    def _kill(self, r):
        """Breaker trip: mark *r* dead, drain its queued + in-flight
        requests (the engine frees pages / sets ``resume_tokens`` /
        rebuilds its cache so a later rejoin starts clean), replay
        them through survivors, and arm the RetryPolicy-paced probe
        schedule."""
        r.set_state(DEAD)
        self.stats["deaths"] += 1
        drained = r.engine.drain_for_failover(self.tick)
        self.stats["failovers"] += len(drained)
        wall = time.perf_counter()
        for req in drained:
            self._record("failover", req.rid, wall)
        r.retry = res_mod.RetryPolicy(
            attempts=self.probe_attempts,
            retry_wait_s=self.probe_wait_rounds)
        r.probe_attempts_left = self.probe_attempts
        r.probe_wait_rounds = max(1, int(math.ceil(r.retry.pop_wait())))
        r.probe = None
        self._replay(drained)

    def _note_failure(self, r, verdict):
        """One classified replica failure: health to ``degraded``,
        breaker to ``dead`` at ``breaker_failures`` consecutive."""
        r.last_verdict = verdict
        r.consecutive_failures += 1
        if r.state in (HEALTHY, REJOINED):
            r.set_state(DEGRADED)
        if r.state == DEGRADED \
                and r.consecutive_failures >= self.breaker_failures:
            self._kill(r)

    # ------------------------------------------------------- the round

    def _drive(self, r, phase):
        """One replica round under the chaos sites + optional watchdog.
        Returns the classified verdict on failure, None on a clean
        return. The ``router_kill`` / ``router_wedge`` / ``router_slow``
        sites fire inside the round closure — an injected raise or
        hang lands exactly where a dying replica's dispatch would."""
        def call():
            _faults.fire("router_kill", tick=self.tick, replica=r.name)
            _faults.fire("router_wedge", tick=self.tick, replica=r.name)
            _faults.fire("router_slow", tick=self.tick, replica=r.name)
            return r.engine.step()

        try:
            if self.step_timeout_s:
                serve_res.guarded_dispatch(call, self.step_timeout_s,
                                           phase)
            else:
                call()
        except serve_res.DispatchFailure as f:
            return f.verdict
        except RuntimeError:
            # a replica died loudly: the router_kill site, or the
            # engine's own SERVE_ROUND_ATTEMPTS budget exhausting —
            # the engine's last classified verdict names the cause
            return r.engine.resilience.last_verdict \
                or res_mod.classify_subprocess(1)
        return None

    def _step_live(self, r):
        verdict = self._drive(r, "router")
        if verdict is not None:
            self._note_failure(r, verdict)
            return
        # a round the engine's OWN watchdog degraded-and-recovered is
        # still a classified failure signal for the breaker
        d = r.engine.resilience.degraded_rounds
        if d > r._degraded_seen:
            r._degraded_seen = d
            self._note_failure(r, r.engine.resilience.last_verdict)
            return
        r.consecutive_failures = 0
        if r.state in (DEGRADED, REJOINED):
            r.set_state(HEALTHY)

    def _tick_dead(self, r):
        if r.probe_attempts_left <= 0:
            return                # probe budget exhausted: stays dead
        r.probe_wait_rounds -= 1
        if r.probe_wait_rounds > 0:
            return
        # paced wait over: start a re-admission probe through the REAL
        # engine (a bare empty round proves nothing — the probe must
        # prefill and decode). Engine tick fast-forwards to the router
        # round so the fleet event log keeps tick monotonicity.
        r.set_state(DRAINING)
        r.probe_attempts_left -= 1
        self.stats["probes"] += 1
        r.engine.tick = self.tick
        self._probe_seq += 1
        probe = Request(rid=_PROBE_RID_BASE + self._probe_seq,
                        prompt=[1, 2, 3], max_new_tokens=2,
                        arrival=float(self.tick))
        r.probe, r.probe_rounds = probe, 0
        self._record("submitted", probe.rid)
        r.engine.submit(probe, quiet=True, replay=True)

    def _probe_failed(self, r):
        r.set_state(DEAD)
        r.probe = None
        r.probe_wait_rounds = max(1, int(math.ceil(r.retry.pop_wait())))

    def _step_probe(self, r):
        verdict = self._drive(r, "router_probe")
        if verdict is not None:
            r.last_verdict = verdict
            self._probe_failed(r)
            return
        r.probe_rounds += 1
        if r.probe.done():
            r.set_state(REJOINED)
            self.stats["rejoins"] += 1
            r.consecutive_failures = 0
            r.probe = None
        elif r.probe_rounds >= self.probe_rounds_cap:
            # a probe that cannot finish is a failed re-admission
            self._probe_failed(r)

    def _autoscale_tick(self):
        if self.autoscale is None:
            return
        live = [r for r in self.replicas if r.routable()]
        cap = sum(r.engine.num_slots for r in live)
        load = (self.fleet_inflight() / cap) if cap else 1.0
        if load > self.autoscale.high_water:
            self._over_water += 1
        else:
            self._over_water = 0
        if self._over_water >= self.autoscale.lag_rounds:
            parked = [r for r in self.replicas if r.parked]
            if parked:
                r = parked[0]
                r.parked = False
                # tick fast-forward: the unparked engine's events must
                # not stamp ticks behind the requests it will serve
                r.engine.tick = self.tick
                self.stats["scale_outs"] += 1
            self._over_water = 0

    def step(self):
        """One fleet round: autoscale decision, then every live
        replica steps (failures classified into the health machine,
        breaker trips drain-and-replay), dead replicas pace their
        probe schedule, draining replicas drive their probe, and
        orphans retry. Returns the router tick just driven."""
        now = self.tick
        self._autoscale_tick()
        for r in self.replicas:
            if r.parked:
                continue
            if r.state == DEAD:
                self._tick_dead(r)
            elif r.state == DRAINING:
                self._step_probe(r)
            else:
                self._step_live(r)
        if self._orphans and any(r.routable() for r in self.replicas):
            orphans, self._orphans = self._orphans, []
            self._replay(orphans)
        self._sample_gauges()
        self.tick += 1
        return now

    def _sample_gauges(self):
        self.gauges.append({
            "step": self.tick,
            "serve_routed": self.stats["routed"],
            "serve_failovers": self.stats["failovers"],
            "serve_replayed": self.stats["replayed"],
        })

    def gauge_rows(self, run=None):
        """MetricsWriter-shaped fleet gauge rows (one per router round;
        names registered in ``telemetry.metrics``)."""
        if run is None:
            return [dict(g) for g in self.gauges]
        return [dict(g, run=run) for g in self.gauges]

    # ------------------------------------------------------- the trace

    def completed(self):
        """Every completed request across the fleet (probe requests
        excluded — they are router fabrications, not trace load)."""
        out = []
        for r in self.replicas:
            for req in r.engine.scheduler.completed:
                if req.rid < _PROBE_RID_BASE:
                    out.append(req)
        return out

    def run_trace(self, requests, max_ticks=10000):
        """Replay a synthetic trace through the fleet to completion:
        requests are routed when their arrival tick is due; a trace
        request SETTLES by completing on any replica, being shed by
        one, or being rejected at the router. Returns the completed
        Request list. The drain guard raises rather than spinning —
        zero-loss means every ACCEPTED request settles, and a fleet
        that cannot drain must fail loudly."""
        pending = sorted(requests, key=lambda r: (r.arrival, r.rid))
        n_total = len(pending)
        trace_ids = {id(r) for r in requests}
        cursors = {}

        def _settled():
            n = 0
            lists = [("rej", self.rejected)]
            for r in self.replicas:
                lists.append((f"c{r.index}", r.engine.scheduler.completed))
                lists.append((f"s{r.index}", r.engine.scheduler.shed))
                lists.append((f"r{r.index}", r.engine.rejected))
            for key, lst in lists:
                seen = cursors.get(key, 0)
                for item in lst[seen:]:
                    req = item[0] if isinstance(item, tuple) else item
                    if id(req) in trace_ids:
                        n += 1
                cursors[key] = len(lst)
            return n

        settled = 0
        while settled < n_total or pending:
            settled += _settled()
            if settled >= n_total and not pending:
                break
            if self.tick >= max_ticks:
                raise RuntimeError(
                    f"fleet trace did not drain in {max_ticks} rounds "
                    f"({settled}/{n_total} settled, "
                    f"{len(self._orphans)} orphaned)")
            due = [r for r in pending if r.arrival <= self.tick]
            pending = [r for r in pending if r.arrival > self.tick]
            for req in due:
                self.submit(req)
            self.step()
        return self.completed()


# --------------------------------------------------------------------------
# the validated `router` ledger block


def router_block(router, completed, wall_s, *, trace_id,
                 arrival_process, prefix_hit_rate_by_policy=None):
    """Assemble the validated ``router`` ledger block (the fleet
    generalization of ``lifecycle.slo_block``; schema teeth in
    ``ledger.validate_record``, citation pins in
    tools/check_bench_labels.py check 12) from a drained fleet:

    * ``fleet_goodput_tok_s`` — completed tokens per wall second
      across every replica (rejected/shed load excluded by
      construction — they never generated).
    * ``util_spread`` — max minus min per-replica share of generated
      tokens (0.0 = perfectly even; 1.0 = one replica did everything).
    * ``ttft_p99_ms`` / ``tpot_p99_ms`` — CROSS-replica tails over the
      completed set (``lifecycle.request_latencies`` semantics, so the
      fleet tails can never disagree with the slo block on method).
    * ``failovers`` / ``replayed_requests`` — requests pulled off dead
      replicas and resubmitted through survivors.
    * ``prefix_hit_rate_by_policy`` — per-policy fleet hit rates under
      the shared trace (the harness's policy sweep; None outside it).
    """
    lats = lifecycle.request_latencies(completed)
    ttfts = [x["ttft_s"] * 1e3 for x in lats if x["ttft_s"] is not None]
    tpots = [x["tpot_s"] * 1e3 for x in lats if x["tpot_s"] is not None]
    tokens = [r.engine.tokens_generated for r in router.replicas]
    total = sum(tokens)
    shares = [t / total for t in tokens] if total else []
    spread = (max(shares) - min(shares)) if shares else 0.0

    def _r(v, nd=2):
        return None if v is None else round(v, nd)

    good_tokens = sum(x["n_out"] for x in lats)
    return {
        "route_policy": router.policy,
        "replicas": len(router.replicas),
        "fleet_goodput_tok_s": _r(good_tokens / wall_s
                                  if wall_s > 0 else None),
        "util_spread": _r(spread, 4),
        "ttft_p99_ms": _r(lifecycle.percentile(ttfts, 99)),
        "tpot_p99_ms": _r(lifecycle.percentile(tpots, 99)),
        "failovers": router.stats["failovers"],
        "replayed_requests": router.stats["replayed"],
        "requests": router.stats["routed"],
        "completed": len(lats),
        "rejected_fleet": router.stats["rejected_fleet"],
        "rejected_replica": router.stats["rejected_replica"],
        "prefix_hit_rate_by_policy": prefix_hit_rate_by_policy,
        "trace_id": trace_id,
        "arrival_process": arrival_process,
    }
