"""Serving resilience: admission control, load shedding, KV-pressure
preemption, and the per-round dispatch watchdog (ISSUE 15).

The collection pipeline got a full failure story in PR 4 (one
classifier + retry state machine + deterministic fault injection); the
``ServingEngine`` had none — a KV-page exhaustion, a wedged device
dispatch on the flaky axon relay, or a sustained overload either
crashed the serving loop or deadlocked it. This module is the
host-side substrate of the four recovery layers the engine wires in
(production continuous-batching systems treat all four as first-class
— PAPERS.md arXiv:2605.25645's scheduler design; the vLLM
preemption/recompute map in docs/MIGRATING.md):

* **admission control** (``APEX_SERVE_ADMIT=N``): a bounded submit
  queue. ``ServingEngine.submit`` returns a structured
  :class:`Rejected` (reason + a retry-after estimate in scheduler
  ticks) instead of enqueueing when the queue is full — explicit
  reject at the front door, never an exception escaping the loop and
  never an unbounded queue OOMing the host under a burst.
* **deadline shedding** (``APEX_SERVE_SHED=1``): the engine drops
  queued requests whose SLO attainment is already IMPOSSIBLE — a
  request that has waited past the TTFT threshold cannot attain
  whatever happens next (TTFT >= waiting time), so serving it would
  burn decode rounds on a lost cause while attainable requests queue
  behind it. Conservative by construction: only provably-lost
  requests shed.
* **KV-pressure preemption** (``APEX_SERVE_PREEMPT=1``): admission
  reserves PROMPT pages only (overcommit — vLLM's model) and decode
  grows the page table as positions cross page boundaries; a refused
  mid-stream grant preempts the lowest-effective-priority running
  request instead of crashing or head-of-line-deadlocking — its pages
  are freed (prefix-cache refcounts respected), its prompt+generated
  tokens are requeued, and re-admission replays them through the
  EXISTING packed prefill program (token-for-token parity with the
  never-preempted stream — greedy decode is deterministic and the
  replayed K/V is the same computation the decode path wrote).
* **dispatch watchdog + round recovery** (``APEX_SERVE_RECOVER=1``):
  every device dispatch runs under :func:`guarded_dispatch` — a
  worker-thread timeout (default
  ``resilience.SERVE_DISPATCH_TIMEOUT_S``, the §6 envelope's serving
  entry) that converts a hung or crashing round into a
  :class:`DispatchFailure` carrying the resilience classifier's
  verdict (timeout = ``wedged``, exception = ``degraded_relay``). The
  engine then requeues every in-flight request, stamps
  ``degraded_round`` lifecycle events, rebuilds the device cache
  (the wedged dispatch may have consumed the donated buffer) and
  continues — bounded by ``SERVE_ROUND_ATTEMPTS`` consecutive
  failures with ``RetryPolicy`` pacing between them, so a dead
  device still kills the engine loudly instead of spinning.

Knob asymmetry (the CLAUDE.md rule): per-call engine arguments are
demands — garbage values raise, and ``preempt=True`` raises when the
page pool cannot guarantee a lone request's progress
(``num_pages - 1 < max_pages``: even with everything else preempted
the request could wedge) — while the env knobs are preferences that
fall back per shape. All four default OFF with disabled mode
token-for-token identical (tests/test_serving_chaos.py pins it), per
the measured-dispatch rule: the overload A/B (shed-vs-tail under the
diurnal trace) is queued in PERF.md §2 behind the
``serving_resilience`` rung.

Stdlib-only (like ``scheduler``/``lifecycle``): the watchdog is a
plain thread join; the jitted programs are untouched — the engine's
one-compile contract (``decode_cache_size()==1``,
``prefill_cache_size()<=1``) holds under every enabled combination.
"""

import dataclasses
import threading
from typing import Optional

from apex_tpu import resilience as _res
from apex_tpu.dispatch import tiles as _tiles


@dataclasses.dataclass(frozen=True)
class Rejected:
    """The structured admission refusal ``ServingEngine.submit``
    returns under admission control: never an exception (a full queue
    is load, not a programming error), never a silent drop (the
    caller holds the reason and a pacing hint). ``retry_after_ticks``
    is a crude drain estimate — queued-ahead over slot count — a
    client-side retry loop can multiply, not a promise."""
    reason: str
    retry_after_ticks: int


class DispatchFailure(Exception):
    """One failed serving dispatch under the watchdog: ``phase`` names
    the program (``prefill`` | ``decode`` | ``verify``), ``verdict``
    is the resilience classifier's word for it (``wedged`` for a
    timeout, ``degraded_relay`` for a crash), ``detail`` the
    underlying evidence."""

    def __init__(self, phase, verdict, detail):
        super().__init__(f"{phase} dispatch {verdict}: {detail}")
        self.phase = phase
        self.verdict = verdict
        self.detail = detail


def guarded_dispatch(fn, timeout_s, phase):
    """Run one device dispatch (call + fetch, no engine-state
    mutation) under the serving watchdog: *fn* executes on a worker
    thread and its result is adopted only on a clean in-budget return
    — a late result from a timed-out round can never overwrite the
    engine's recovered state. Raises :class:`DispatchFailure` with
    the classifier verdict on timeout or crash."""
    box = {}

    def _run():
        try:
            box["result"] = fn()
        except BaseException as e:  # classified, not swallowed
            box["error"] = e

    t = threading.Thread(target=_run, daemon=True,
                         name=f"serve-{phase}-dispatch")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise DispatchFailure(
            phase, _res.classify_subprocess(None, timed_out=True),
            f"no fetch within the {timeout_s}s round budget "
            f"(resilience.SERVE_DISPATCH_TIMEOUT_S envelope)")
    if "error" in box:
        err = box["error"]
        raise DispatchFailure(
            phase, _res.classify_subprocess(1),
            f"{type(err).__name__}: {err}") from err
    return box["result"]


# --------------------------------------------------------------------------
# knob resolution (per-call demands raise; env preferences fall back)


def resolve_admit(per_call=None):
    """The effective submit-queue bound: per-call int (>= 1 = bound,
    0/False = explicit off; anything else raises — a demand) >
    ``APEX_SERVE_ADMIT`` env preference (``tiles.env_nonneg_int``:
    garbage warns once and is ignored; 0 is the explicit off-pin) >
    built-in OFF (0: the unbounded queue serving always had)."""
    if per_call is not None:
        if per_call is False:
            return 0
        if not isinstance(per_call, int) or isinstance(per_call, bool) \
                or per_call < 0:
            raise ValueError(
                f"admit= wants a non-negative int (0 = off) or None, "
                f"got {per_call!r}")
        return per_call
    v = _tiles.env_nonneg_int("APEX_SERVE_ADMIT")
    return 0 if v is None else v


def _resolve_flag(per_call, env, name):
    if per_call is not None:
        if not isinstance(per_call, bool):
            raise ValueError(
                f"{name}= wants True/False/None, got {per_call!r}")
        return per_call
    v = _tiles.env_choice(env, ("1", "0"))
    if v is not None:
        return v == "1"
    return False


def resolve_shed(per_call=None):
    """Deadline shedding on/off: per-call bool (non-bool raises) >
    ``APEX_SERVE_SHED`` > built-in OFF."""
    return _resolve_flag(per_call, "APEX_SERVE_SHED", "shed")


def resolve_preempt(per_call=None):
    """KV-pressure preemption on/off: per-call bool (non-bool raises)
    > ``APEX_SERVE_PREEMPT`` > built-in OFF. The ENGINE additionally
    judges the progress guarantee (a lone request must be able to
    reach ``max_seq`` pages): a per-call True over a too-small pool
    raises there; the env preference falls back per shape."""
    return _resolve_flag(per_call, "APEX_SERVE_PREEMPT", "preempt")


def resolve_recover(per_call=None):
    """Dispatch watchdog + round recovery on/off: per-call bool
    (non-bool raises) > ``APEX_SERVE_RECOVER`` > built-in OFF."""
    return _resolve_flag(per_call, "APEX_SERVE_RECOVER", "recover")


@dataclasses.dataclass
class ResilienceStats:
    """Engine-lifetime counters of the four layers, and the rate
    surface the ``slo`` ledger block carries (None-when-disabled —
    degradation, never omission; check 9 refuses a non-None rate
    whose selecting knob is unpinned or off)."""
    rejected: int = 0
    shed: int = 0
    preempted: int = 0
    resubmitted: int = 0
    degraded_rounds: int = 0
    submit_attempts: int = 0
    admissions: int = 0
    # the last failed round's classifier verdict (round recovery)
    last_verdict: Optional[str] = None

    def rates(self, *, shed_on, preempt_on, recover_on):
        return {
            "shed_rate": (self.shed / self.submit_attempts
                          if self.submit_attempts else 0.0)
            if shed_on else None,
            "preempt_rate": (self.preempted / self.admissions
                             if self.admissions else 0.0)
            if preempt_on else None,
            "degraded_rounds": self.degraded_rounds
            if recover_on else None,
        }
