"""ServingEngine: cache + compiled steps + scheduler in one object.

The host/device shape follows the concurrency-paper discipline
(PAPERS.md arXiv:2011.03641): ALL host work — admission, eviction,
page accounting, array staging — happens between device dispatches,
and the device programs themselves are compiled exactly once each
(prefill at one packed bucket shape, decode at the slot shape), so
the steady-state loop is dispatch → host bookkeeping → dispatch with
no recompiles on the critical path. Scheduler events change array
VALUES only; ``decode_cache_size()`` / ``prefill_cache_size()``
expose the jit cache sizes so tests (and ``dryrun_serving``) can
assert the contract mechanically — ONE prefill + ONE decode program,
with sampling, speculative decode and the prefix cache all enabled.

Generation subsystem (ISSUE 13), three cooperating layers:

* **sampling** (``serving.sampling``) — per-request temperature /
  top-k / top-p with private threefry lanes. Enabled at engine build
  (``sampling=`` > ``set_sampling`` > ``APEX_SERVE_SAMPLING``); the
  per-request params ride the decode program as ``[B]`` ARRAYS
  restaged each round, so admit/evict/re-seed never recompiles.
  Temperature-0 lanes take the exact greedy argmax.
* **speculative decode** (``serving.speculative``) — self-drafting
  n-gram drafts of up to K tokens (``spec_decode=`` >
  ``APEX_SPEC_DECODE``), verified in ONE dispatch of the SAME packed
  varlen prefill program: the slot's full sequence + draft is one
  segment, already-cached context positions route their K/V writes to
  the null spare row (the cache keeps its decode-written values
  bit-exact), and the flat logits-gather (``prefill_requests *
  (K + 1)`` indices — the generalized ``last_idx``) reads the verify
  chain. Acceptance/rollback is pure page/length arithmetic
  (``speculative.accept``); rejected positions' K/V are never read
  (length-masked) and get overwritten as the sequence advances.
* **prefix cache** (``serving.prefix_cache``) — content-hashed
  refcounted page sharing (``prefix_cache=`` >
  ``APEX_SERVE_PREFIX_CACHE``): the scheduler admits cache hits by
  reference + admission-time copy-on-write of the partial tail page
  (:meth:`_copy_page` — a tiny donated jitted page copy, dispatched
  only at admission/registration, never on the per-token path; the
  VERIFY path adds no program — the prefill program serves it); the
  covered suffix replays through the decode program (which attends
  the shared pages — correct by construction), so a shared system
  prompt is PREFILLED ONCE per engine.

All three default OFF per the measured-dispatch rule — the device
A/Bs are queued in PERF.md §2 behind ``APEX_SERVE_BENCH=1``;
correctness (greedy parity, per-request determinism, refcount/COW
invariants, two-program stability) is pinned on CPU by
tests/test_serving_generation.py.

Knob resolution at engine build (the CLAUDE.md asymmetry):

* ``weight_quant=`` per-call True RAISES when the params cannot take
  the int8 path; None defers to ``quant.set_weight_quant`` /
  ``APEX_SERVE_WEIGHT_QUANT`` (preferences), default OFF.
* ``sampling=`` / ``prefix_cache=`` per-call non-bools RAISE; a
  stochastic request submitted to a sampling-OFF engine RAISES at
  ``submit`` (explicit request ≠ preference); None defers to
  setter/env.
* ``spec_decode=`` per-call RAISES on an un-honorable draft length
  (< 1, or deeper than the prefill bucket); the env preference falls
  back per shape.
* ``decode_impl=`` / ``decode_block_h=`` ride per-call into the
  decode-attention family on every step (raising semantics live
  there); None defers to the family's setter/env/table resolution.
* ``policy=`` per-call unknown policies RAISE
  (``scheduler.resolve_policy``); None defers to ``APEX_SERVE_SCHED``
  (vocabulary ``fifo`` | ``priority``).

Host/device overlap (ISSUE 14, ``overlap=`` > ``APEX_SERVE_OVERLAP``,
knob home :mod:`apex_tpu.overlap`): the serial round serializes
dispatch → fetch → host bookkeeping → next round's planning, leaving
the device idle for the whole host slice ``profile_serving`` measures
into ``costs.overlap_bound``. The overlapped step DEFERS the decode
fetch one round: round t's decode is dispatched and the engine
returns; round t+1 runs the scheduler's admit/evict/prefix-cache
planning FIRST — while the device executes — and syncs only at the
result fetch, where round t's token values land. The contract making
this exact (token-for-token parity with the serial engine, pinned by
test): scheduler state transitions are COUNT functions — ``done()``
is ``len(out_tokens) >= max_new_tokens``, positions advance by one
per decode lane — so round t+1's planning never needs round t's token
VALUES, only its counts, which are advanced at dispatch time with
placeholder tokens the fetch later fills in. Token values are
consumed only where the serial engine consumes them (the next decode
round's input staging, after the fetch). Speculative decode breaks
the contract (acceptance length is a value function): per-call
``overlap=True`` with ``spec_decode`` RAISES; the env preference
falls back to the serial step. Lifecycle events keep their canonical
per-request order (``validate_order`` stays green): finished events
are recorded at the fetch that produced the token, and evicted events
are recorded after that fetch. ``decode_cache_size()==1`` is
untouched — the overlapped mode dispatches the SAME compiled
programs, only the host schedule moves. ``flush()`` resolves an
in-flight round for callers that stop stepping (``run_trace`` flushes
for you); until then the newest token per live request is a
placeholder.

Serving resilience (ISSUE 15, :mod:`apex_tpu.serving.resilience`) —
four default-OFF layers, disabled mode token-for-token identical:

* **admission control** (``admit=`` > ``APEX_SERVE_ADMIT``): a full
  submit queue returns a structured ``Rejected(reason,
  retry_after_ticks)`` instead of enqueueing — overload is load, not
  an exception, and the queue is bounded.
* **deadline shedding** (``shed=`` > ``APEX_SERVE_SHED``): queued
  requests whose TTFT SLO is already blown (waited past the
  threshold — attainment impossible) are dropped with a ``shed``
  lifecycle event before admission.
* **KV-pressure preemption** (``preempt=`` > ``APEX_SERVE_PREEMPT``):
  admission reserves PROMPT pages only and decode grows the table
  mid-stream; a refused grant preempts the lowest-effective-priority
  running slot (pages freed, prefix refcounts respected, stream
  requeued) and re-admission REPLAYS the preempted stream through
  the same packed prefill program (``_replay_prefill`` — no third
  program, token-for-token parity with the never-preempted stream).
  Per-call True raises when the pool cannot guarantee a lone
  survivor's progress; the env preference falls back.
* **dispatch watchdog + round recovery** (``recover=`` >
  ``APEX_SERVE_RECOVER``): every dispatch runs under the
  ``guarded_dispatch`` timeout (``resilience.
  SERVE_DISPATCH_TIMEOUT_S``); a wedged/crashed round requeues every
  in-flight request with ``degraded_round`` events, rebuilds the
  cache, and continues — bounded by ``SERVE_ROUND_ATTEMPTS``
  consecutive failures with ``RetryPolicy`` pacing.

Preemption/recovery demand the serial round (the deferred-fetch
step's placeholder tokens must never reach a requeued stream): the
pairing with ``overlap=`` follows the spec-decode precedent — two
demands raise, a demand drops the other side's env preference,
env-vs-env falls back to serial. The ``serve_*`` chaos sites
(``apex_tpu.resilience.faults``) fire inside the dispatch closures,
so ``tests/test_serving_chaos.py`` drives every recovery path through
the real engine.

Multi-token decode blocks (ISSUE 17, ``decode_k=`` >
``APEX_SERVE_DECODE_K``, default K=1 per the measured-dispatch rule —
the ``serving_multitok`` A/B is queued in PERF.md §2): ONE dispatch
runs K decode steps in a ``lax.scan`` (:func:`model.decode_block`),
amortizing the ~65 ms per-dispatch relay floor across K tokens. K is
a STATIC program constant — at most a second decode compile-cache
key; the per-lane step budgets, in-block warmup feed and sampling
counters ride as VALUES, so ``decode_cache_size()==1`` holds per
engine whatever the scheduler does. All host-side decisions — admit /
evict / shed / preempt / sampling-lane restage — coarsen to every-K
block boundaries; a lane finishing mid-block rides the rest of the
block as masked ballast (null-page writes, outputs discarded), a
preemption victim requeues with its mid-block partial tokens and
replays through the ordinary ``resume_tokens`` path, and the guarded
dispatch watchdog naturally treats the whole K-block as its unit.
Token-for-token parity with the K=1 engine is pinned by
tests/test_serving_multitok.py under every layer combination.
Speculative decode COMPETES for the same amortization (both batch
multiple tokens per dispatch) and its verify arithmetic assumes one
pending token per round, so the pairing follows the established
asymmetry: two per-call demands raise, a demand drops the other
side's env preference, env-vs-env falls back to K=1.

Observability (ISSUE 11): when ``lifecycle.enabled()`` the engine
keeps a request-lifecycle :class:`~apex_tpu.serving.lifecycle.EventLog`
(``self.events``) — submitted/admitted/prefill_done/first_token/
finished/evicted events plus per-round scheduler gauges (now incl.
cumulative draft/accept/prefix-hit counts) — appended strictly
BETWEEN device dispatches, so the jitted programs (and
``decode_cache_size()==1``) are untouched either way; disabled mode
allocates no log and is behavior-identical. ``device_dispatch_s``
accumulates the wall time spent inside device round trips (prefill +
decode fetch), so a harness can attribute the host slice of the
serving loop (``costs.overlap_bound`` — the ROADMAP 4c gap).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import resilience as res_mod
from apex_tpu.resilience import faults as faults_mod
from apex_tpu.serving import kv_tier as kv_tier_mod
from apex_tpu.serving import lifecycle
from apex_tpu.serving import model as smodel
from apex_tpu.serving import prefix_cache as prefix_mod
from apex_tpu.serving import quant as quant_mod
from apex_tpu.serving import resilience as serve_res
from apex_tpu.serving import sampling as sampling_mod
from apex_tpu.serving import speculative as spec_mod
from apex_tpu.serving import tp as tp_mod
from apex_tpu.serving.kv_cache import (PageAllocator, init_cache,
                                       pages_needed)
from apex_tpu.serving.scheduler import ContinuousBatchingScheduler, Request


def detokenize(tokens):
    """Toy detokenizer for dryruns/smokes: token id -> letter."""
    return "".join(chr(97 + int(t) % 26) for t in tokens)


class ServingEngine:
    def __init__(self, cfg, params=None, *, num_slots=4, page_size=16,
                 num_pages=64, max_seq=None, prefill_len=64,
                 prefill_requests=None, weight_quant=None, tp=None,
                 decode_impl=None, decode_block_h=None, interpret=None,
                 policy=None, sampling=None, spec_decode=None,
                 decode_k=None, prefix_cache=None, overlap=None,
                 admit=None,
                 shed=None, preempt=None, recover=None,
                 kv_quant=None, kv_swap=None, kv_restore=None,
                 shed_ttft_ms=None, dispatch_timeout_s=None,
                 round_attempts=None, round_retry_wait_s=None, seed=0):
        smodel.check_serving_config(cfg)
        self.cfg = cfg
        self.num_slots = int(num_slots)
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.max_seq = int(max_seq or cfg.max_position_embeddings)
        if self.max_seq > cfg.max_position_embeddings:
            raise ValueError("max_seq exceeds the position table")
        self.max_pages = -(-self.max_seq // self.page_size)
        self.prefill_len = int(prefill_len)
        self.prefill_requests = int(prefill_requests or num_slots)
        self.params = params if params is not None \
            else smodel.init_gpt_params(cfg, seed)

        # weight quant: per-call demand raises on un-honorable;
        # env/setter preferences fall back (quant.resolve)
        if weight_quant is True:
            for name, w in (("word_embeddings",
                             self.params["word_embeddings"]),):
                if not quant_mod.quantizable(w):
                    raise ValueError(
                        f"weight_quant=True cannot be honored: {name} "
                        f"has dtype {w.dtype}")
        self.weight_quant = quant_mod.resolve(weight_quant)
        # tensor-parallel serving (ISSUE 18, `tp=` > APEX_SERVE_TP,
        # default tp=1 — the serving_tp A/B is queued in PERF.md §2;
        # the capability exception for the >HBM config is argued
        # there too). tp x weight_quant COMPOSES (ISSUE 20 satellite,
        # formerly a two-demand raise): the int8 decode records shard
        # along the same Megatron split as their float weights
        # (tp.qparams_shardings — per-out-channel scales ride the
        # column split, replicate across the row split), device_put
        # below with the params.
        self.tp = tp_mod.resolve_serve_tp(
            tp, n_heads=cfg.num_attention_heads)
        self.qparams = smodel.quantize_decode_params(
            self.params, cfg) if self.weight_quant else None
        self.decode_impl = decode_impl
        self.decode_block_h = decode_block_h
        self.interpret = interpret

        # generation knobs (ISSUE 13): sampling / speculative decode /
        # prefix cache, each defaulting OFF (measured-dispatch rule)
        self.sampling = sampling_mod.resolve(sampling)
        k = spec_mod.resolve_k(spec_decode)
        if spec_decode is not None and k and k + 1 > self.prefill_len:
            raise ValueError(
                f"spec_decode={k} cannot be honored: the verify window "
                f"(K+1 = {k + 1} tokens) exceeds "
                f"prefill_len={self.prefill_len}")
        if k and k + 1 > self.prefill_len:
            k = 0  # env preference: falls back per shape
        self.spec_k = k
        self.spec_stats = spec_mod.SpecStats() if self.spec_k else None
        # host/device overlap (ISSUE 14): the deferred-fetch contract
        # cannot run under speculation (value-dependent counts — see
        # the module docstring). Knob asymmetry across the pair: an
        # explicit overlap=True DEMAND against an env-PREFERENCE spec
        # drops the preference (speculation falls back to plain decode
        # — token-identical, so the demand IS honorable); against a
        # per-call spec_decode= DEMAND it raises (two demands, no
        # honorable order); the APEX_SERVE_OVERLAP preference falls
        # back to the serial step either way.
        from apex_tpu import overlap as overlap_mod

        if overlap is True and self.spec_k and spec_decode is None:
            self.spec_k = 0
            self.spec_stats = None
        # multi-token decode blocks (ISSUE 17): K decode steps per
        # device dispatch — ONE lax.scan program, K a static compile
        # key — amortizing the per-dispatch relay floor. Default K=1
        # per the measured-dispatch rule (the serving_multitok A/B is
        # queued in PERF.md §2). Speculative decode competes for the
        # same amortization (both batch multiple tokens per dispatch)
        # and its verify/rollback arithmetic assumes ONE pending token
        # per decode round, so the pairing follows the established
        # asymmetry: two per-call demands raise, a demand drops the
        # other side's env preference, env-vs-env falls back to K=1
        # (the committed measurement backs the spec layer; the K-block
        # row is still queued).
        dk = smodel.resolve_decode_k(decode_k)
        if dk > 1 and self.spec_k:
            if decode_k is not None and spec_decode is not None:
                raise ValueError(
                    f"decode_k={dk} cannot be honored with "
                    f"spec_decode={self.spec_k}: the verify rollback "
                    f"assumes one pending token per decode round "
                    f"(two demands, no honorable order)")
            if decode_k is not None:
                # explicit K-block demand drops the env draft pref
                self.spec_k = 0
                self.spec_stats = None
            else:
                dk = 1  # APEX_SERVE_DECODE_K preference falls back
        self.decode_k = dk
        # serving resilience (ISSUE 15): four default-OFF layers.
        # Preemption and round recovery need the serial round (the
        # deferred-fetch step's placeholder tokens must never reach a
        # requeued stream), so the pairing follows the spec-decode
        # precedent: two per-call demands raise, a demand drops the
        # other side's env preference, env-vs-env falls back to the
        # serial step. Admission control and shedding are queue-side
        # and compose with every schedule.
        if overlap is True and (preempt is True or recover is True):
            raise ValueError(
                "overlap=True cannot be honored with preempt=True/"
                "recover=True: the deferred-fetch round holds "
                "placeholder tokens a preempted/requeued stream would "
                "replay as values (two demands, no honorable order)")
        self.preempt = serve_res.resolve_preempt(preempt)
        self.recover = serve_res.resolve_recover(recover)
        if overlap is True:
            # env resilience preferences drop before the explicit
            # overlap demand (preference semantics, never a raise)
            if preempt is None:
                self.preempt = False
            if recover is None:
                self.recover = False
        if self.preempt and self.num_pages - 1 < self.max_pages:
            # the progress guarantee of overcommit admission: with
            # everything else preempted, a lone request must still be
            # able to grow to max_seq pages — otherwise preemption
            # trades a head-of-line block for a genuine livelock
            if preempt is True:
                raise ValueError(
                    f"preempt=True cannot be honored: the page pool "
                    f"({self.num_pages - 1} allocatable) cannot cover "
                    f"one request's max_seq table ({self.max_pages} "
                    f"pages) — a lone preemption survivor could wedge")
            self.preempt = False  # env preference: falls back per shape
        # KV-cache memory hierarchy (ISSUE 20, serving.kv_tier): int8
        # KV quantization + host swap tier, both default OFF per the
        # measured-dispatch rule (the serving_kv_quant/serving_kv_swap
        # device A/Bs are queued in PERF.md §2). The swap tier banks
        # pages AT preemption, so kv_swap pairs with preempt by the
        # established asymmetry: kv_swap=True demanded with preemption
        # resolved off raises (nothing is ever preempted, so nothing
        # is ever banked); the APEX_SERVE_KV_SWAP preference falls
        # back off. Overlap pairing rides preempt's (a swap engine is
        # a preempting engine, which is already serial-only).
        self.kv_quant = kv_tier_mod.resolve_kv_quant(kv_quant)
        self.kv_swap = kv_tier_mod.resolve_kv_swap(kv_swap)
        if self.kv_swap and not self.preempt:
            if kv_swap is True:
                raise ValueError(
                    "kv_swap=True cannot be honored without "
                    "KV-pressure preemption (preempt=True / "
                    "APEX_SERVE_PREEMPT=1): the host tier banks pages "
                    "AT preemption — with it off nothing is ever "
                    "swapped")
            self.kv_swap = False  # env preference falls back
        if kv_restore is not None:
            # validate the per-call demand at BUILD: an unknown
            # vocabulary word or "swap" against a swap-less engine
            # raises here, not at the first preemption mid-serve
            kv_tier_mod.resolve_kv_restore(
                kv_restore, swap_enabled=self.kv_swap, tokens=1,
                dtype="bfloat16")
        self.kv_restore = kv_restore
        self.kv_stats = kv_tier_mod.KVTierStats() if self.kv_swap \
            else None
        # rids whose swap-OUT failed since the last preemption drain —
        # the drain stamps their classified ``swap_failed`` between
        # ``preempted`` and ``resubmitted``
        self._swap_failed_rids = set()
        self.admit_limit = serve_res.resolve_admit(admit)
        self.shed = serve_res.resolve_shed(shed)
        if shed_ttft_ms is not None:
            if not isinstance(shed_ttft_ms, (int, float)) \
                    or isinstance(shed_ttft_ms, bool) or shed_ttft_ms <= 0:
                raise ValueError(
                    f"shed_ttft_ms= wants a positive number, got "
                    f"{shed_ttft_ms!r}")
            self.shed_ttft_ms = float(shed_ttft_ms)
        else:
            self.shed_ttft_ms = lifecycle.env_ms(
                "APEX_SERVE_SLO_TTFT_MS", lifecycle.DEFAULT_SLO_TTFT_MS)
        self.dispatch_timeout_s = float(
            dispatch_timeout_s if dispatch_timeout_s is not None
            else res_mod.SERVE_DISPATCH_TIMEOUT_S)
        self.round_attempts = int(
            round_attempts if round_attempts is not None
            else res_mod.SERVE_ROUND_ATTEMPTS)
        # RetryPolicy pacing between failed rounds (the §6 serving
        # envelope); explicit args so the bench-attempt env knobs
        # never leak into the serving loop
        self._round_retry = res_mod.RetryPolicy(
            attempts=self.round_attempts,
            retry_wait_s=round_retry_wait_s
            if round_retry_wait_s is not None
            else res_mod.SERVE_ROUND_RETRY_WAIT_S)
        self._round_failures = 0   # consecutive; reset on any clean round
        self.resilience = serve_res.ResilienceStats()
        self.rejected = []         # [(request, Rejected)] at submit
        self.overlap = overlap_mod.resolve_serve_overlap(
            overlap, spec_k=self.spec_k)
        if self.overlap and (self.preempt or self.recover):
            # the APEX_SERVE_OVERLAP preference falls back to serial
            # when a resilience layer is engaged (same fall-back the
            # spec-decode pairing takes)
            self.overlap = False
        self._pending = None  # in-flight decode round (overlap mode)
        self.prefix_enabled = prefix_mod.resolve(prefix_cache)
        self.prefix = prefix_mod.PrefixCache(
            PageAllocator(num_pages), self.page_size) \
            if self.prefix_enabled else None
        # width of the flat logits gather per packed request: the
        # verify chain needs K+1 rows; plain prefill reads row r*w
        self._gather_w = self.spec_k + 1

        self._cache_dtype = smodel.compute_dtype(cfg)
        # tp > 1: params + paged KV cache are device_put over the tp
        # mesh; the jitted programs below are UNTOUCHED — GSPMD
        # partitions them from these committed input shardings
        # (qkv/h_to_4h column-split on whole heads, attn-dense/
        # 4h_to_h row-split, cache on its leading head axis), so the
        # one-compile contract holds on the mesh and every host-side
        # layer composes unchanged (serving/tp.py docstring).
        self.mesh = tp_mod.mesh_for(self.tp) if self.tp > 1 else None
        if self.mesh is not None:
            self.params = jax.device_put(
                self.params,
                tp_mod.param_shardings(self.params, self.mesh))
            if self.qparams is not None:
                self.qparams = jax.device_put(
                    self.qparams,
                    tp_mod.qparams_shardings(self.qparams, self.mesh))
        self.cache = self._fresh_cache()
        self.allocator = self.prefix.allocator if self.prefix \
            is not None else PageAllocator(num_pages)
        self.scheduler = ContinuousBatchingScheduler(
            num_slots, self.max_pages, page_size, self.allocator,
            policy=policy, prefix=self.prefix, preempt=self.preempt,
            swap_out=self._swap_out_slot if self.kv_swap else None)
        # lifecycle observability (gated, host-side only): None when
        # collection is off — disabled mode appends nothing and reads
        # no extra clocks beyond the per-round stamps below
        self.events = lifecycle.EventLog() if lifecycle.enabled() \
            else None

        # the quantized prefill takes ONE extra operand — the
        # keep_scale row staged per dispatch (_packed_call); the plain
        # program keeps its exact pre-tier signature, so the disabled
        # mode's jaxpr is byte-identical to the pre-ISSUE-20 engine
        if self.kv_quant:
            def _prefill(cache, ids, positions, seg, token_rows,
                         page_table, last_idx, keep_scale):
                return smodel.prefill(self.params, cache, ids,
                                      positions, seg, token_rows,
                                      page_table, last_idx, keep_scale,
                                      cfg=cfg)
        else:
            def _prefill(cache, ids, positions, seg, token_rows,
                         page_table, last_idx):
                return smodel.prefill(self.params, cache, ids,
                                      positions, seg, token_rows,
                                      page_table, last_idx, cfg=cfg)

        # the decode program: at K=1 the single-step program is built
        # byte-identical to the pre-block engine; at K>1 the ONE
        # lax.scan K-block program replaces it (K is static — at most
        # a second compile-cache key; the per-lane budgets/warmup
        # arrays are VALUES, so the one-compile contract holds)
        if self.decode_k > 1 and self.sampling:
            def _decode(cache, tokens, lengths, page_table, steps,
                        warm_tokens, warm_steps, temps, top_ks,
                        top_ps, keys, counters):
                return smodel.decode_block(
                    self.params, cache, tokens, lengths, page_table,
                    steps, warm_tokens, warm_steps,
                    lanes=(temps, top_ks, top_ps, keys, counters),
                    k=self.decode_k, cfg=cfg, qparams=self.qparams,
                    decode_impl=self.decode_impl,
                    decode_block_h=self.decode_block_h,
                    interpret=self.interpret)
        elif self.decode_k > 1:
            def _decode(cache, tokens, lengths, page_table, steps,
                        warm_tokens, warm_steps):
                return smodel.decode_block(
                    self.params, cache, tokens, lengths, page_table,
                    steps, warm_tokens, warm_steps,
                    k=self.decode_k, cfg=cfg, qparams=self.qparams,
                    decode_impl=self.decode_impl,
                    decode_block_h=self.decode_block_h,
                    interpret=self.interpret)
        elif self.sampling:
            def _decode(cache, tokens, lengths, page_table, temps,
                        top_ks, top_ps, keys, counters):
                cache, _, logits = smodel.decode_step(
                    self.params, cache, tokens, lengths, page_table,
                    cfg=cfg, qparams=self.qparams,
                    decode_impl=self.decode_impl,
                    decode_block_h=self.decode_block_h,
                    interpret=self.interpret)
                toks = sampling_mod.sample_tokens(
                    logits, temps, top_ks, top_ps, keys, counters,
                    lengths > 0)
                return cache, toks, logits
        else:
            def _decode(cache, tokens, lengths, page_table):
                return smodel.decode_step(
                    self.params, cache, tokens, lengths, page_table,
                    cfg=cfg, qparams=self.qparams,
                    decode_impl=self.decode_impl,
                    decode_block_h=self.decode_block_h,
                    interpret=self.interpret)

        def _copy(cache, src, dst):
            # one K/V page src -> dst across all layers/heads; src/dst
            # are traced scalars, so every COW/snapshot hop reuses ONE
            # compiled copy and the donated cache updates in place —
            # an eager .at[].set here would materialize the ENTIRE
            # cache per copied page. Iterates every cache leaf: the
            # int8 tier's [L, h, P] scale planes carry their page axis
            # at axis 2 exactly like the code arrays, so a COW copy
            # moves a page's codes AND its scale in the same hop.
            for part in cache:
                page = jax.lax.dynamic_index_in_dim(
                    cache[part], src, axis=2, keepdims=False)
                cache[part] = cache[part].at[:, :, dst].set(page)
            return cache

        def _swap_gather(cache, page_idx):
            # host swap tier (ISSUE 20), device half of swap-OUT: one
            # victim's pages gathered along every leaf's page axis at
            # a [max_pages] index row PADDED with null page 0 (zero
            # codes, zero scale), so this program compiles exactly
            # once whatever the victim's live page count — the
            # one-compile contract holds; the host device_get of the
            # result is the staging copy, never a third serving
            # program
            return {name: jnp.take(cache[name], page_idx, axis=2)
                    for name in cache}

        def _swap_scatter(cache, page_idx, leaves):
            # device half of swap-IN: the banked leaves scatter back
            # at the freshly granted pages; the padded tail entries
            # re-write null page 0 with its own zero content — benign,
            # and the program compiles exactly once
            for name in cache:
                cache[name] = cache[name].at[:, :, page_idx].set(
                    leaves[name])
            return cache

        # donate the cache: the scatter-updated pages stay in place
        self._prefill_fn = jax.jit(_prefill, donate_argnums=(0,))
        self._decode_fn = jax.jit(_decode, donate_argnums=(0,))
        # the prefix cache's page-copy hop (admission/registration
        # only — never on the per-token path; the TWO serving
        # programs above stay the jaxpr-stability surfaces)
        self._copy_fn = jax.jit(_copy, donate_argnums=(0,))
        # swap-tier staging hops (preemption/re-admission only — same
        # auxiliary-program precedent as _copy_fn)
        self._swap_gather_fn = jax.jit(_swap_gather)
        self._swap_scatter_fn = jax.jit(_swap_scatter,
                                        donate_argnums=(0,))
        self.tick = 0
        self.decode_steps = 0
        self.verify_calls = 0
        self.prefill_batches = 0
        self.tokens_generated = 0
        # wall seconds spent inside device round trips (prefill +
        # decode fetch): run wall minus this is the HOST slice of the
        # serving loop — the overlap_bound input
        self.device_dispatch_s = 0.0
        # wall seconds inside swap-tier staging copies (device_get at
        # swap-out + scatter at swap-in) — the host-copy clock the
        # kv_restore crossover sweep measures against the replay
        # dispatch it saves
        self.swap_copy_s = 0.0

    # ---------------------------------------------------------- plumbing

    def _place_cache(self, cache):
        """Commit a (re)built KV cache to the tp mesh sharding — the
        ONE placement home, so the round-recovery rebuild cannot
        re-enter the jit caches with a drifted sharding (which would
        break ``decode_cache_size()==1``). tp=1: identity."""
        if self.mesh is None:
            return cache
        return jax.device_put(
            cache, tp_mod.cache_shardings(cache, self.mesh))

    def _fresh_cache(self):
        """Build + place a zeroed cache — the ONE construction home
        (ctor, round recovery, failover drain), so a rebuild can never
        drop the int8 tier's scale leaves or drift the dtype (either
        would re-enter the jit caches as a second program)."""
        return self._place_cache(init_cache(
            self.cfg.num_layers, self.cfg.num_attention_heads,
            self.num_pages, self.page_size, self.cfg.head_dim,
            self._cache_dtype, kv_quant=self.kv_quant))

    def decode_cache_size(self):
        """jit-cache entry count of the decode step — the
        jaxpr-stability assertion surface (must stay 1 whatever the
        scheduler admits or evicts)."""
        return self._decode_fn._cache_size()

    def prefill_cache_size(self):
        """jit-cache entry count of the packed prefill program — with
        speculative decode on, admission prefills AND verify batches
        dispatch THIS one program (the no-third-program proof next to
        :meth:`decode_cache_size`)."""
        return self._prefill_fn._cache_size()

    def generation_stats(self):
        """The ledger-facing generation account (None-when-disabled,
        the degradation-not-omission rule): speculative acceptance
        rate + mean draft length, prefix-cache hit rate."""
        st = self.spec_stats
        pf = self.prefix
        return {
            "spec_acceptance_rate":
                st.acceptance_rate() if st is not None else None,
            "draft_len":
                st.mean_draft_len() if st is not None else None,
            "prefix_hit_rate":
                (pf.hit_tokens / pf.lookup_tokens)
                if pf is not None and pf.lookup_tokens else None,
        }

    def resilience_rates(self):
        """The ledger-facing resilience account (ISSUE 15), shaped for
        ``lifecycle.slo_block(resilience=)``: shed / preempt rates and
        the degraded-round count, each None when its layer is off —
        degradation, never omission (check 9 teeth)."""
        return self.resilience.rates(
            shed_on=self.shed, preempt_on=self.preempt,
            recover_on=self.recover)

    def kv_tier_rates(self):
        """The ledger-facing KV-tier account (ISSUE 20): ``kv_quant``
        (True with the int8 tier on, None off), ``swap_rate`` (banked
        swap-outs over preemptions) and ``swapped_pages_high_water``,
        the swap fields None when the host tier is off — degradation,
        never omission (the check 8 teeth)."""
        quant = True if self.kv_quant else None
        st = self.kv_stats
        if st is None:
            return {"kv_quant": quant, "swap_rate": None,
                    "swapped_pages_high_water": None}
        preempted = self.resilience.preempted
        return {
            "kv_quant": quant,
            "swap_rate": (st.swap_outs / preempted) if preempted
            else 0.0,
            "swapped_pages_high_water": st.swapped_pages_high_water,
        }

    def _dispatch(self, phase, fn):
        """One device dispatch (call + fetch, no engine-state writes
        inside) under the resilience layer: the ``serve_*`` chaos
        sites fire inside the dispatched closure (so an injected hang
        blocks exactly where the live relay wedges), and with
        ``recover`` on the whole closure runs under the
        :func:`~apex_tpu.serving.resilience.guarded_dispatch`
        watchdog — a timeout or crash surfaces as a classified
        :class:`~apex_tpu.serving.resilience.DispatchFailure` the
        round-recovery path catches. Without the knob the failure
        propagates (and a watchdog-less engine dies with it — the A/B
        the chaos suite pins). The ``verify`` phase dispatches the
        SAME compiled program as admission prefill, so it shares the
        ``serve_prefill`` chaos site — but keeps its own failure
        label, so a degraded round's verdict names the dispatch that
        actually wedged."""
        site = "serve_prefill" if phase == "verify" \
            else f"serve_{phase}"

        def call():
            faults_mod.fire(site, tick=self.tick,
                            step=self.decode_steps,
                            call=self.prefill_batches)
            return fn()

        if not self.recover:
            return call()
        return serve_res.guarded_dispatch(
            call, self.dispatch_timeout_s, phase)

    def validate_request(self, request):
        """The front-door teeth, shared with the fleet router: the
        scheduler validates the page budget (max_seq); the engine
        additionally owns the packed prefill bucket, so the
        prompt-vs-prefill_len bound — which would otherwise crash
        _run_prefill mid-round AFTER admission had already filled a
        slot and allocated pages — is checked at the same front door.
        Sampling demands are validated here too: stochastic params
        against a sampling-OFF engine raise (an explicit request is a
        demand, not a preference); a validated stochastic request also
        gets its per-request sampling key stamped here, so the lane
        key exists from the first admission onward."""
        self.scheduler.validate(request)
        if len(request.prompt) > self.prefill_len:
            raise ValueError(
                f"request {request.rid}: prompt ({len(request.prompt)} "
                f"tokens) exceeds prefill_len={self.prefill_len}")
        sp = getattr(request, "sampling", None)
        if sp is not None:
            sp.validate()
            if not sp.greedy and not self.sampling:
                raise ValueError(
                    f"request {request.rid} demands stochastic "
                    f"sampling (temperature={sp.temperature}) but the "
                    f"engine was built without sampling "
                    f"(sampling=True / APEX_SERVE_SAMPLING=1)")
            if request.rng_key is None:
                request.rng_key = sampling_mod.request_key(sp.seed)

    def submit(self, request, *, quiet=False, replay=False):
        """Enqueue one request; impossible requests raise HERE, before
        anything is enqueued or allocated (``validate_request`` — the
        teeth run FIRST: a full queue rejects load, it must never mask
        a malformed request as a Rejected).

        Under admission control (ISSUE 15, ``admit=`` /
        ``APEX_SERVE_ADMIT``) a FULL queue is load, not a programming
        error: submit returns a structured
        :class:`~apex_tpu.serving.resilience.Rejected` (reason +
        retry-after estimate in ticks) instead of enqueueing — an
        exception never escapes the serving loop for overload, and
        the queue can never grow without bound. Returns None when the
        request was enqueued.

        The fleet router's hooks (ISSUE 19): ``quiet=True`` skips the
        engine's submitted/rejected lifecycle events — the router owns
        the request's front-of-chain events on the ONE fleet log, and
        a failover resubmission must not stamp a second ``submitted``.
        ``replay=True`` (implies the router path) additionally
        bypasses the admission bound and keeps an already-stamped
        ``enqueue_wall``: a failover replay is load the fleet ALREADY
        accepted — dropping it at requeue would break the zero-loss
        invariant, and re-stamping its wall would hide the latency the
        dead replica cost it."""
        self.resilience.submit_attempts += 1
        self.validate_request(request)
        if not replay and self.admit_limit \
                and self.scheduler.queue_depth() >= self.admit_limit:
            # explicit reject at the front door: nothing enqueued,
            # nothing allocated. The retry-after estimate is the
            # queued-ahead count over the slot drain width — a pacing
            # hint, not a promise.
            rej = serve_res.Rejected(
                "queue_full",
                max(1, -(-self.scheduler.queue_depth()
                         // self.num_slots)))
            self.resilience.rejected += 1
            self.rejected.append((request, rej))
            if self.events is not None and not quiet:
                wall = time.perf_counter()
                self.events.record("submitted", request.rid,
                                   tick=self.tick, wall=wall)
                self.events.record("rejected", request.rid,
                                   tick=self.tick, wall=wall)
            return rej
        if not (replay and request.enqueue_wall is not None):
            request.enqueue_wall = time.perf_counter()
        self.scheduler.submit(request, tick=self.tick)
        if self.events is not None and not quiet:
            self.events.record("submitted", request.rid, tick=self.tick,
                               wall=request.enqueue_wall)
        return None

    # -------------------------------------------------- page-level hops

    def _copy_page(self, src, dst):
        """Device copy of one K/V page (the prefix cache's COW hop and
        tail-snapshot registration): one tiny donated jitted helper,
        compiled once for any (src, dst) pair, dispatched BETWEEN the
        serving programs' steps — the prefill/decode jaxpr-stability
        surfaces are untouched and the copy moves one page, not the
        cache."""
        self.cache = self._copy_fn(self.cache, jnp.int32(src),
                                   jnp.int32(dst))

    def _assert_writable(self, slot, first_pos, last_pos):
        """Design guard: after admission-time COW, no write of any
        slot may land on a cache-shared page. Cheap host check; a
        failure here is a prefix-cache invariant bug, not a runtime
        condition."""
        if self.prefix is None:
            return
        ps = self.page_size
        for j in range(first_pos // ps, last_pos // ps + 1):
            if j < len(slot.pages):
                assert not self.prefix.is_shared(slot.pages[j]), (
                    f"rid {slot.request.rid}: write at positions "
                    f"[{first_pos}, {last_pos}] would hit shared page "
                    f"{slot.pages[j]} (COW failed)")

    # ------------------------------------------- host swap tier hops

    def _swap_out_slot(self, slot):
        """Bank a preemption victim's live pages device→host (the
        scheduler's ``swap_out`` callback, fired inside
        ``requeue_slot`` BEFORE the pages are freed). Returns a sealed
        :class:`~apex_tpu.serving.kv_tier.SwappedPages` handle, or
        None when there is nothing worth banking (no generated tokens
        — re-admission is a plain fresh prefill) or the copy failed
        (the ``serve_swap`` chaos site: the stream falls back to
        recompute preemption, classified ``swap_failed`` at the
        drain — tokens preserved either way). The banked extent is
        every page covering positions ``0..pos-1`` — including
        previously shared prefix pages' CONTENT (their refs release
        exactly as before; restore writes private pages, never
        aliases). The copy is host staging between dispatches
        (device_get of the one-compile gather) — never a third
        serving program."""
        req = slot.request
        t = slot.pos
        if not req.out_tokens or t < 1:
            return None
        n = pages_needed(t, self.page_size)
        try:
            faults_mod.fire("serve_swap", phase="swap_out",
                            tick=self.tick, rid=req.rid)
            idx = np.zeros((self.max_pages,), np.int32)
            idx[:n] = slot.pages[:n]
            t0 = time.perf_counter()
            gathered = self._swap_gather_fn(self.cache,
                                            jnp.asarray(idx))
            leaves = {name: np.asarray(jax.device_get(arr))
                      for name, arr in gathered.items()}
            self.swap_copy_s += time.perf_counter() - t0
        except Exception:
            self.kv_stats.swap_out_failures += 1
            self._swap_failed_rids.add(req.rid)
            return None
        handle = kv_tier_mod.SwappedPages(
            leaves=leaves, page_count=n, tokens=t,
            quant=self.kv_quant).seal()
        self.kv_stats.banked(handle)
        return handle

    def _swap_in_slot(self, si, handle):
        """Copy one banked stream's pages back into the slot's freshly
        granted device pages (host→device staging between dispatches —
        every restore reuses the one-compile scatter). True on
        success: the slot resumes decode directly past the banked
        content, skipping the replay dispatch entirely. False when the
        ``serve_swap`` chaos site fired or the handle no longer
        matches its seal (classified ``swap_failed``) — the caller
        replays by recompute; the integrity check runs BEFORE the
        scatter, so corrupt bytes never reach the device."""
        sch = self.scheduler
        slot = sch.slots[si]
        req = slot.request
        try:
            faults_mod.fire("serve_swap", phase="swap_in",
                            tick=self.tick, rid=req.rid)
            if faults_mod.corrupt("serve_swap", phase="swap_in",
                                  tick=self.tick, rid=req.rid):
                # scripted host rot: flip one banked byte in place —
                # the seal below must catch it
                name = sorted(handle.leaves)[0]
                handle.leaves[name].view(np.uint8).ravel()[0] ^= 0xFF
            if not handle.intact():
                raise RuntimeError(
                    f"rid {req.rid}: swapped pages failed their "
                    f"checksum — banked bytes rotted on the host")
            n = handle.page_count
            dst = np.zeros((self.max_pages,), np.int32)
            dst[:n] = slot.pages[:n]
            t0 = time.perf_counter()
            leaves = {name: jnp.asarray(arr)
                      for name, arr in handle.leaves.items()}
            self.cache = self._swap_scatter_fn(
                self.cache, jnp.asarray(dst), leaves)
            self.swap_copy_s += time.perf_counter() - t0
        except Exception:
            self.kv_stats.swap_in_failures += 1
            self.kv_stats.released(handle)
            req.swapped = None
            if self.events is not None:
                self.events.record("swap_failed", req.rid,
                                   tick=self.tick,
                                   wall=time.perf_counter())
            return False
        self.kv_stats.swap_ins += 1
        self.kv_stats.released(handle)
        req.swapped = None
        # resume exactly where the banked content ends: pos positions
        # are valid, the next known token feeds the first decode step
        # (for a stream banked mid-warmup this lands back inside the
        # warmup window — the decode loop's known-token bookkeeping
        # carries it the rest of the way, same as replay overflow)
        slot.pos = handle.tokens
        slot.next_token = int(req.resume_tokens[handle.tokens])
        return True

    def _restore_resumed(self, resumed):
        """Route each re-admitted preempted stream down its resolved
        restore path (ISSUE 20, dispatch op ``kv_restore`` keyed on
        the resumed stream's token length): ``"swap"`` scatters the
        banked pages back and resumes decode directly; ``"recompute"``
        — or any swap failure/corruption — falls back to the
        replay-prefill the preemption layer always had. Returns the
        slots still needing the replay dispatch."""
        sch = self.scheduler
        replay = []
        for si in resumed:
            req = sch.slots[si].request
            handle = getattr(req, "swapped", None)
            if handle is not None:
                choice = kv_tier_mod.resolve_kv_restore(
                    self.kv_restore, swap_enabled=self.kv_swap,
                    tokens=len(req.resume_tokens),
                    dtype=self._cache_dtype)
                if choice == "swap" and self._swap_in_slot(si, handle):
                    self.kv_stats.restores_swap += 1
                    continue
                if req.swapped is not None:
                    # recompute resolved: release the handle — the
                    # replay recomputes these pages (a failed swap-in
                    # already released it)
                    self.kv_stats.released(handle)
                    req.swapped = None
            if self.kv_stats is not None:
                self.kv_stats.restores_recompute += 1
            replay.append(si)
        return replay

    # ----------------------------------------------------------- prefill

    def _sample_first_tokens(self, logits_rows, slot_indices):
        """First-token selection off prefill logits ``[R, vocab]`` for
        the admitted slots — the SAME lane semantics as the decode
        program's in-graph sampling (counter 0, the request's own
        key), run eagerly between dispatches."""
        sch = self.scheduler
        if not self.sampling:
            return np.asarray(jnp.argmax(
                logits_rows.astype(jnp.float32), axis=-1))
        temps, top_ks, top_ps, keys, counters = \
            sampling_mod.batch_lanes(
                [sch.slots[si].request for si in slot_indices])
        toks = sampling_mod.sample_tokens(
            logits_rows, jnp.asarray(temps), jnp.asarray(top_ks),
            jnp.asarray(top_ps), jnp.asarray(keys),
            jnp.asarray(counters),
            jnp.ones((len(slot_indices),), bool))
        return np.asarray(toks)

    def _pack_greedy(self, items, sizes):
        """Greedy bucket split shared by admission prefill and the
        speculative verify: a batch closes when the next packed
        sequence would overflow the [prefill_len] bucket or the
        per-batch request cap — further items start another dispatch
        of the SAME compiled program."""
        S, R = self.prefill_len, self.prefill_requests
        batches, cur, used = [], [], 0
        for item, n in zip(items, sizes):
            if cur and (used + n > S or len(cur) >= R):
                batches.append(cur)
                cur, used = [], 0
            cur.append(item)
            used += n
        if cur:
            batches.append(cur)
        return batches

    def _packed_call(self, rows, phase="prefill"):
        """ONE dispatch of the packed prefill program for pre-split
        ``rows = [(slot_idx, fed_tokens, write_from, gather_pos)]`` —
        the single assembly both admission prefill and speculative
        verify go through, so the packing contract (segment ids 1..R,
        padding -> the all-null spare row, positions below
        ``write_from`` routing their K/V writes to that spare row,
        within-sequence ``gather_pos`` filling the flat logits gather
        at stride ``_gather_w``) cannot drift between the two callers.
        Returns ``(logits, t0)`` — the caller fetches what it needs
        and closes the ``device_dispatch_s`` timing seam."""
        S, R, W = self.prefill_len, self.prefill_requests, self._gather_w
        ids = np.zeros((S,), np.int32)
        positions = np.zeros((S,), np.int32)
        seg = np.zeros((S,), np.int32)
        token_rows = np.full((S,), self.num_slots, np.int32)
        gather_idx = np.zeros((R * W,), np.int32)
        pt = np.zeros((self.num_slots + 1, self.max_pages), np.int32)
        pt[:self.num_slots] = self.scheduler.page_table_rows()
        cursor = 0
        for r, (si, fed, write_from, gathers) in enumerate(rows):
            n = len(fed)
            ids[cursor:cursor + n] = fed
            positions[cursor:cursor + n] = np.arange(n)
            seg[cursor:cursor + n] = r + 1
            token_rows[cursor + write_from:cursor + n] = si
            for j, gp in enumerate(gathers):
                gather_idx[r * W + j] = cursor + gp
            cursor += n
        keep = None
        if self.kv_quant:
            # keep_scale row (kv_tier.prefill_scatter_quant): 1 for
            # pages whose existing int8 content must survive this
            # dispatch's scale growth, 0 for pages this dispatch fully
            # rewrites (fresh pages — stale codes there must NOT pin
            # the scale). A row writing from write_from>0 (verify
            # replay) keeps the partially-valid page holding position
            # write_from-1 and zeroes only the pages past it.
            keep = np.ones((self.num_pages,), np.float32)
            for si, fed, write_from, _ in rows:
                pages = self.scheduler.slots[si].pages
                first = (0 if write_from == 0
                         else (write_from - 1) // self.page_size + 1)
                for j in range(first,
                               (len(fed) - 1) // self.page_size + 1):
                    if j < len(pages):
                        keep[pages[j]] = 0.0
        t0 = time.perf_counter()

        def call():
            args = [self.cache, jnp.asarray(ids),
                    jnp.asarray(positions), jnp.asarray(seg),
                    jnp.asarray(token_rows), jnp.asarray(pt),
                    jnp.asarray(gather_idx)]
            if keep is not None:
                args.append(jnp.asarray(keep))
            cache, logits = self._prefill_fn(*args)
            if self.recover:
                # fetch INSIDE the watchdog: the sync on the gathered
                # logits is where a wedged round actually blocks
                logits = np.asarray(logits)
            return cache, logits

        # state adopted only after a clean return: a timed-out round's
        # late result can never overwrite the recovered engine
        self.cache, logits = self._dispatch(phase, call)
        return logits, t0

    def _replay_prefill(self, resumed):
        """Re-admission replay of preempted/requeued slots (ISSUE 15)
        through the SAME packed prefill program: each slot's known
        stream (minus the still-pending last token) is one segment
        writing its fresh pages — the re-prefilled K/V is the same
        computation the decode path originally wrote, so the resumed
        greedy stream is token-for-token the never-preempted stream.
        No token is sampled and no first-token seam fires (the stream
        is already known; the gathered logits row is fixed-shape
        dispatch ballast). A stream longer than the prefill bucket
        replays its overflow through the decode warmup path (the
        ``slot.known`` bookkeeping), one token per round."""
        sch = self.scheduler
        items = []
        for si in resumed:
            slot = sch.slots[si]
            fed = slot.request.resume_tokens[:-1][:self.prefill_len]
            items.append((si, fed))
        for batch in self._pack_greedy(items,
                                       [len(f) for _, f in items]):
            rows = []
            for si, fed in batch:
                self._assert_writable(sch.slots[si], 0, len(fed) - 1)
                rows.append((si, fed, 0, [len(fed) - 1]))
            logits, t0 = self._packed_call(rows)
            self.prefill_batches += 1
            _ = np.asarray(logits[:1, :1])  # close the dispatch seam
            wall = time.perf_counter()
            self.device_dispatch_s += wall - t0
            for si, fed in batch:
                slot = sch.slots[si]
                slot.pos = len(fed)
                slot.next_token = int(slot.known[len(fed)])

    def _run_prefill(self, slot_indices):
        """Pack the newly admitted slots' prompts into [prefill_len]
        batches and fill the cache (every prompt position writes its
        slot's pages; the one logits gather per request reads the last
        prompt token). Sets each slot's first decode token, and
        registers fresh prompts with the prefix cache. Resumed slots
        (a preempted stream re-admitted, ISSUE 15) replay through
        :meth:`_replay_prefill` first — same compiled program, no
        sampling."""
        sch = self.scheduler
        resumed = [si for si in slot_indices
                   if sch.slots[si].request.resume_tokens]
        slot_indices = [si for si in slot_indices if si not in resumed]
        if resumed:
            # swap tier (ISSUE 20): streams with banked pages restore
            # by host->device copy and skip the replay dispatch; the
            # rest (recompute-resolved, swap-failed, never banked)
            # replay as before
            replay = (self._restore_resumed(resumed) if self.kv_swap
                      else resumed)
            if replay:
                self._replay_prefill(replay)
        if not slot_indices:
            return resumed
        for si in slot_indices:
            n = len(sch.slots[si].request.prompt)
            if n > self.prefill_len:
                raise ValueError(
                    f"prompt of request "
                    f"{sch.slots[si].request.rid} ({n} tokens) exceeds "
                    f"prefill_len={self.prefill_len}")
        batches = self._pack_greedy(
            slot_indices,
            [len(sch.slots[si].request.prompt) for si in slot_indices])
        for batch in batches:
            rows = [(si, sch.slots[si].request.prompt, 0,
                     [len(sch.slots[si].request.prompt) - 1])
                    for si in batch]
            logits, t0 = self._packed_call(rows)
            self.prefill_batches += 1
            # rows r*W hold each request's last-prompt-token logits
            sel = logits[np.arange(len(batch)) * self._gather_w]
            next_toks = self._sample_first_tokens(sel, batch)
            wall = time.perf_counter()
            self.device_dispatch_s += wall - t0
            for r, si in enumerate(batch):
                slot = sch.slots[si]
                slot.pos = len(slot.request.prompt)
                tok = int(next_toks[r])
                slot.request.out_tokens.append(tok)
                slot.next_token = tok
                self.tokens_generated += 1
                # prefill always samples the request's FIRST token —
                # this dispatch's fetch wall IS the TTFT stamp
                if slot.request.first_token_wall is None:
                    slot.request.first_token_wall = wall
                if slot.request.done():
                    slot.request.finish_wall = wall
                if self.events is not None:
                    rid = slot.request.rid
                    self.events.record("prefill_done", rid,
                                       tick=self.tick, wall=wall)
                    self.events.record("first_token", rid,
                                       tick=self.tick, wall=wall)
                    if slot.request.done():
                        self.events.record("finished", rid,
                                           tick=self.tick, wall=wall)
                # register the fresh prompt's pages with the prefix
                # cache (between dispatches; tail snapshots copy here)
                if self.prefix is not None:
                    adopted, copies = self.prefix.register(
                        slot.request.prompt, slot.pages,
                        ("req", slot.request.rid))
                    if adopted:
                        self.prefix.acquire(adopted)
                        slot.shared_pages.extend(adopted)
                    for src, dst in copies:
                        self._copy_page(src, dst)
        return resumed + slot_indices

    # ------------------------------------------------------- speculative

    def _propose_drafts(self, active):
        """Draft proposals for this round: ``[(slot_idx, draft)]`` for
        every greedy slot past its prompt whose n-gram draft exists,
        fits the remaining token budget AND the verify window fits
        the prefill bucket. Sampled (stochastic) slots never draft —
        speculation is a greedy-path optimization."""
        sch = self.scheduler
        out = []
        for i in active:
            slot = sch.slots[i]
            req = slot.request
            # known covers the prompt AND a resumed stream's warmup
            # (ISSUE 15): a slot still consuming known tokens never
            # drafts — the verify arithmetic assumes pos is past them
            if req.done() or slot.pos < len(slot.known):
                continue
            sp = getattr(req, "sampling", None)
            if sp is not None and not sp.greedy:
                continue
            remaining = req.max_new_tokens - len(req.out_tokens)
            k = min(self.spec_k, remaining - 1,
                    self.prefill_len - slot.pos - 1,
                    self.max_seq - slot.pos - 1)
            if k < 1:
                continue
            draft = spec_mod.propose(req.prompt + req.out_tokens, k)
            if draft:
                out.append((i, draft))
        return out

    def _run_verify(self, drafts):
        """Verify drafted slots in dispatches of the SAME packed
        prefill program: each slot's full sequence (prompt + generated
        + draft) is one segment — context positions write the null
        spare row (the cache keeps its decode-written K/V bit-exact),
        pending+draft positions write the slot's pages, and the flat
        gather reads the K+1 verify logits per slot. Acceptance and
        rollback are pure length/index arithmetic
        (``speculative.accept``); a slot gains 1..K+1 tokens."""
        sch = self.scheduler
        W = self._gather_w
        batches = self._pack_greedy(
            drafts,
            [sch.slots[i].pos + 1 + len(d) for i, d in drafts])
        verified = []
        for batch in batches:
            rows = []
            for i, draft in batch:
                slot = sch.slots[i]
                req = slot.request
                fed = req.prompt + req.out_tokens + draft
                pos = slot.pos
                assert len(fed) == pos + 1 + len(draft), (
                    len(fed), pos, len(draft))
                # context positions -> the all-null spare row (their
                # decode-written K/V must survive bit-exact); only the
                # pending token + draft positions write real pages
                self._assert_writable(slot, pos, len(fed) - 1)
                rows.append((i, fed, pos,
                             list(range(pos, pos + len(draft) + 1))))
            logits, t0 = self._packed_call(rows, phase="verify")
            self.verify_calls += 1
            greedy = np.asarray(jnp.argmax(
                logits.astype(jnp.float32), axis=-1))
            wall = time.perf_counter()
            self.device_dispatch_s += wall - t0
            for r, (i, draft) in enumerate(batch):
                slot = sch.slots[i]
                req = slot.request
                chain = [int(t) for t in
                         greedy[r * W:r * W + len(draft) + 1]]
                added = spec_mod.accept(draft, chain)
                # _propose_drafts capped k <= remaining - 1, so the
                # round can never overshoot the token budget — named
                # here so the stats line below stays honest by
                # construction (it counts only produced tokens)
                assert len(added) <= req.max_new_tokens \
                    - len(req.out_tokens), (req.rid, added)
                self.spec_stats.record(len(draft), len(added) - 1)
                req.out_tokens.extend(added)
                slot.pos = len(req.prompt) + len(req.out_tokens) - 1
                slot.next_token = req.out_tokens[-1]
                self.tokens_generated += len(added)
                if req.done():
                    req.finish_wall = wall
                    if self.events is not None:
                        self.events.record("finished", req.rid,
                                           tick=self.tick, wall=wall)
                verified.append(i)
        return verified

    # ------------------------------------------------------------- steps

    def _lane_budget(self, slot):
        """``(warmup steps remaining, this block's step budget)`` for
        one live lane: warmup steps consume KNOWN tokens (a prefix-hit
        covered suffix or a resumed stream's replay overflow, outputs
        discarded), then emit steps count toward the request's
        remaining new tokens. The budget caps at ``decode_k`` and at
        the lane's own finish — a lane never decodes past its last
        token inside a block, so block writes stay within the
        request's admitted ``prompt + max_new_tokens`` page span."""
        req = slot.request
        warm = max(0, len(slot.known) - 1 - slot.pos)
        rem = req.max_new_tokens - len(req.out_tokens)
        return warm, min(self.decode_k, warm + rem)

    def _block_hi(self, slot):
        """Highest cache position this block writes for a live lane —
        the page-growth span (at K=1 this is exactly ``slot.pos``)."""
        return slot.pos + self._lane_budget(slot)[1] - 1

    def _stage_block(self, decode_lanes):
        """Per-lane staging of one K-block dispatch (ISSUE 17):
        returns ``(steps, steps_dev, warm_tokens, warm_steps)`` where
        ``steps`` maps lane -> host bookkeeping step count,
        ``steps_dev [B]`` is the device step budget (0 for
        done-ballast lanes: the whole block treats them as inactive —
        null-page writes, outputs discarded), ``warm_tokens [K, B]``
        is the in-block warmup feed and ``warm_steps [B]`` how many
        leading steps consume it. All VALUES — the compiled block
        never specializes on them (the one-compile contract)."""
        sch = self.scheduler
        k = self.decode_k
        steps = {}
        steps_dev = np.zeros(self.num_slots, np.int32)
        warm_tokens = np.zeros((k, self.num_slots), np.int32)
        warm_steps = np.zeros(self.num_slots, np.int32)
        for i in decode_lanes:
            slot = sch.slots[i]
            if slot.request.done():
                steps[i] = 1  # ballast: one count step, no device step
                continue
            warm, budget = self._lane_budget(slot)
            steps[i] = budget
            steps_dev[i] = budget
            w = min(warm, budget)
            warm_steps[i] = w
            for j in range(w):
                warm_tokens[j, i] = int(slot.known[slot.pos + j + 1])
        return steps, steps_dev, warm_tokens, warm_steps

    def _dispatch_decode(self, assert_lanes, zero_length_lanes=()):
        """Stage + dispatch ONE decode block for the current slots —
        the SHARED assembly of the serial and overlapped rounds, so
        their token-for-token parity is structural (one staging path)
        rather than maintained across twin code. At K=1 the staged
        program is the single decode step, byte-identical to the
        pre-block engine; at K>1 it is the ``decode_block`` scan with
        the per-lane budget/warmup arrays staged as values.
        ``zero_length_lanes`` are this round's verify-satisfied lanes
        (serial speculative path — K=1 only, the pairing rule).
        Returns ``(next_toks, t0, steps)`` with the fetch left to the
        caller (the serial round fetches immediately; the overlapped
        round defers it); ``steps`` maps lane -> how many of the
        block's scan steps that lane's bookkeeping consumes."""
        sch = self.scheduler
        tokens, lengths = sch.decode_inputs()
        for i in zero_length_lanes:
            lengths[i] = 0  # this round's tokens came via verify
        pt = np.asarray(sch.page_table_rows(), np.int32)
        if self.decode_k > 1:
            steps, steps_dev, warm_tokens, warm_steps = \
                self._stage_block(assert_lanes)
            for i in assert_lanes:
                if steps_dev[i]:
                    self._assert_writable(
                        sch.slots[i], sch.slots[i].pos,
                        sch.slots[i].pos + int(steps_dev[i]) - 1)
        else:
            steps = {i: 1 for i in assert_lanes}
            for i in assert_lanes:
                self._assert_writable(sch.slots[i], sch.slots[i].pos,
                                      sch.slots[i].pos)
        args = [self.cache, jnp.asarray(tokens, dtype=jnp.int32),
                jnp.asarray(lengths, dtype=jnp.int32),
                jnp.asarray(pt)]
        if self.decode_k > 1:
            args += [jnp.asarray(steps_dev), jnp.asarray(warm_tokens),
                     jnp.asarray(warm_steps)]
        if self.sampling:
            temps, top_ks, top_ps, keys, counters = \
                sampling_mod.lane_arrays(sch.slots, self.num_slots)
            args += [jnp.asarray(temps), jnp.asarray(top_ks),
                     jnp.asarray(top_ps), jnp.asarray(keys),
                     jnp.asarray(counters)]
        t0 = time.perf_counter()

        def call():
            cache, toks, _ = self._decode_fn(*args)
            if self.recover:
                # fetch INSIDE the watchdog — the token sync is where
                # a wedged decode round actually blocks
                toks = np.asarray(toks)
            return cache, toks

        # state adopted only after a clean return (a timed-out
        # round's late result never overwrites the recovered engine)
        self.cache, next_toks = self._dispatch("decode", call)
        return next_toks, t0, steps

    def _sample_gauges(self, tick):
        """One gauge sample per scheduler round, AFTER the round's
        device work (occupancy as the next round will see it) — shared
        by the serial and overlapped rounds."""
        if self.events is None:
            return
        sch = self.scheduler
        wall = time.perf_counter()
        st, pf = self.spec_stats, self.prefix
        self.events.sample_gauges(
            tick=tick, wall=wall,
            slots_active=len(sch.active_indices()),
            num_slots=self.num_slots,
            queue_depth=sch.queue_depth(),
            kv_pages_live=(self.allocator.num_pages - 1
                           - self.allocator.free_count),
            kv_pages_total=self.allocator.num_pages,
            hol_wait_s=sch.head_of_line_wait(wall, tick=tick),
            spec_drafted=st.drafted if st is not None else 0,
            spec_accepted=st.accepted if st is not None else 0,
            prefix_hit_tokens=pf.hit_tokens if pf is not None else 0,
            rejected=self.resilience.rejected,
            shed=self.resilience.shed,
            preempted=self.resilience.preempted,
            resubmitted=self.resilience.resubmitted,
            degraded_rounds=self.resilience.degraded_rounds)

    def step(self, arrivals=None):
        """One scheduler round: enqueue due arrivals, evict, admit (+
        prefill + prefix-hit COW), speculative verify, decode every
        remaining active slot. Returns a dict of what happened (the
        dryrun/trace-replay surface). In overlap mode
        (``overlap=`` / ``APEX_SERVE_OVERLAP``) the round is the
        deferred-fetch pipelined variant — same schedule, same tokens
        (see the module docstring); the serial body is untouched."""
        if self.overlap:
            return self._step_overlap(arrivals)
        return self._step_serial(arrivals)

    def _fire_burst(self, tick):
        """Chaos: the ``serve_burst`` site (ISSUE 15) — fabricate and
        submit a scripted request storm through the REAL submit path,
        so admission control's structured rejections (and the shedder
        behind them) are exercised by an actual overload, not a
        mocked queue."""
        spec = faults_mod.burst("serve_burst", tick=tick)
        if not spec:
            return
        base = int(spec.get("rid_base", 9_000_000))
        plen = int(spec.get("prompt_len", 4))
        for j in range(int(spec.get("count", 8))):
            self.submit(Request(
                rid=base + j, prompt=[1 + (j % 7)] * plen,
                max_new_tokens=int(spec.get("max_new", 4)),
                arrival=float(tick)))

    def _shed_queue(self, tick, wall):
        """The deadline shedder (ISSUE 15): drop queued requests whose
        SLO attainment is already IMPOSSIBLE — one that has waited
        past the TTFT threshold cannot attain whatever happens next
        (its TTFT is at least its wait), so decoding it would burn
        rounds on a lost cause while attainable requests queue behind
        it. Conservative by construction: a request with a first
        token already (a requeued preemption victim mid-stream) has
        its TTFT fixed and is never shed."""
        sch = self.scheduler
        dropped = []
        for req in list(sch.queue):
            if req.first_token_wall is not None \
                    or req.enqueue_wall is None:
                continue
            if (wall - req.enqueue_wall) * 1e3 > self.shed_ttft_ms:
                sch.queue.remove(req)
                req.shed_tick = tick
                sch.shed.append(req)
                self.resilience.shed += 1
                dropped.append(req)
                if self.events is not None:
                    self.events.record("shed", req.rid, tick=tick,
                                       wall=wall)
        return dropped

    def _drain_preempted(self, tick):
        """Record lifecycle events + counters for requests the
        scheduler preempted since the last drain (page-pressure
        growth, :meth:`ContinuousBatchingScheduler.grow`)."""
        preempted = self.scheduler.take_preempted()
        for req in preempted:
            self.resilience.preempted += 1
            self.resilience.resubmitted += 1
            if self.events is not None:
                wall = time.perf_counter()
                self.events.record("preempted", req.rid, tick=tick,
                                   wall=wall)
                if req.rid in self._swap_failed_rids:
                    # swap-out raised/hung at requeue (serve_swap chaos
                    # site): the stream still resubmits — it just
                    # replays by recompute instead of restoring banked
                    # pages. Classified, never silent (ISSUE 20).
                    self.events.record("swap_failed", req.rid,
                                       tick=tick, wall=wall)
                self.events.record("resubmitted", req.rid, tick=tick,
                                   wall=wall)
            self._swap_failed_rids.discard(req.rid)
        return preempted

    def _ensure_pages(self, lanes_pos, tick):
        """Mid-stream page growth (preemption mode): make every
        lane's table cover its highest write position this round,
        preempting the lowest-effective-priority slot when a grant is
        refused. Returns the lanes still alive — a lane preempted to
        make room (possibly by its own growth) drops out of the
        round."""
        sch = self.scheduler
        alive = []
        for i, hi in lanes_pos:
            if sch.slots[i] is None:
                continue  # preempted by an earlier lane's growth
            if sch.grow(i, hi // self.page_size + 1, tick):
                alive.append(i)
        self._drain_preempted(tick)
        return [i for i in alive if sch.slots[i] is not None]

    def _step_serial(self, arrivals=None):
        now = self.tick
        self._fire_burst(now)
        if arrivals:
            for req in arrivals:
                self.submit(req)
        try:
            result = self._round_serial(now)
        except serve_res.DispatchFailure as failure:
            # only the guarded (recover=on) dispatch raises this —
            # without the watchdog the raw failure propagates and the
            # engine dies with it (the A/B the chaos suite pins)
            return self._recover_round(now, failure)
        self._round_failures = 0
        return result

    def _round_serial(self, now):
        sch = self.scheduler
        wall = time.perf_counter()
        evicted = sch.evict_done(now, wall)
        shed = self._shed_queue(now, wall) if self.shed else []
        admitted = sch.admit(now, wall)
        if self.events is not None:
            for r in evicted:
                self.events.record("evicted", r.rid, tick=now, wall=wall)
            for i in admitted:
                self.events.record("admitted", sch.slots[i].request.rid,
                                   tick=now, wall=wall)
        self.resilience.admissions += len(admitted)
        # prefix-cache hits skip the packed prefill: their COW copies
        # run here (between dispatches) and their covered suffix
        # replays through the decode program below
        to_prefill = []
        for i in admitted:
            slot = sch.slots[i]
            if slot.prefix_hit:
                for src, dst in slot.cow_copies:
                    self._copy_page(src, dst)
                slot.cow_copies = []
            else:
                to_prefill.append(i)
        prefilled = self._run_prefill(to_prefill) if to_prefill else []
        active = sch.active_indices()
        verified = []
        if self.spec_k and active:
            drafts = self._propose_drafts(active)
            if self.preempt and drafts:
                # the verify window writes pos..pos+|draft| — grow the
                # tables first (a grown-out lane drops its draft)
                alive = set(self._ensure_pages(
                    [(i, sch.slots[i].pos + len(d)) for i, d in drafts],
                    now))
                drafts = [(i, d) for i, d in drafts if i in alive]
            if drafts:
                verified = self._run_verify(drafts)
            active = sch.active_indices()  # growth may have preempted
        decode_lanes = [i for i in active if i not in verified]
        if self.preempt and decode_lanes:
            # the decode step writes each lane's pending position —
            # grow under pressure, preempting the lowest-priority slot
            # on a refused grant instead of crashing the round. DONE
            # lanes (finished at this round's prefill, riding the
            # dispatch as ballast) are skipped: their write lands on
            # the absorbing null page and their output is discarded —
            # growing (let alone preempting a live stream) for them
            # would spend pages on a dead write
            grown = set(self._ensure_pages(
                [(i, self._block_hi(sch.slots[i])) for i in decode_lanes
                 if not sch.slots[i].request.done()], now))
            decode_lanes = [i for i in decode_lanes
                            if sch.slots[i] is not None
                            and (sch.slots[i].request.done()
                                 or i in grown)]
        decoded = 0
        if decode_lanes:
            next_toks, t0, steps = self._dispatch_decode(
                decode_lanes, zero_length_lanes=verified)
            plan, decoded = self._advance_counts(decode_lanes, steps)
            next_toks = np.asarray(next_toks)
            wall2 = time.perf_counter()
            self.device_dispatch_s += wall2 - t0
            self._fill_plan(plan, next_toks, wall2, now)
        self._sample_gauges(now)
        # a slot whose LAST token was just produced frees at the next
        # round's evict — one round of slack, never a starved queue
        self.tick += 1
        return {"tick": now, "evicted": [r.rid for r in evicted],
                "admitted": admitted, "prefilled": prefilled,
                "verified": verified, "decoded_slots": decoded,
                "shed": [r.rid for r in shed]}

    def _recover_round(self, now, failure):
        """Round recovery (ISSUE 15): a dispatch the watchdog timed
        out or caught crashing does NOT kill the engine — every
        in-flight request is requeued (pages freed, known stream
        stashed for the prefill replay), a ``degraded_round``
        lifecycle event is stamped per request with the classifier's
        verdict on the engine, the device cache is rebuilt (the
        wedged dispatch may have consumed the donated buffer — and a
        timed-out round's LATE result is never adopted, so a zeroed
        cache is the only sound state) and the prefix cache is
        flushed (its chains pointed into the abandoned buffer). The
        next rounds re-admit and replay; ``SERVE_ROUND_ATTEMPTS``
        consecutive failures exhaust the budget and raise — bounded
        recovery, a dead device still fails loudly."""
        sch = self.scheduler
        self._round_failures += 1
        self.resilience.degraded_rounds += 1
        self.resilience.last_verdict = failure.verdict
        # requeue every UNFINISHED active slot: whatever the failed
        # program was, the cache buffer's contents are no longer
        # trustworthy. A request that already finished this round
        # needs no further compute — it stays seated for the next
        # round's evict (requeuing it would stamp degraded_round
        # after finished, which the lifecycle machine forbids, and
        # replay a completed stream for nothing).
        requeued = []
        for i in sch.active_indices():
            if not sch.slots[i].request.done():
                # swap=False: the failed round's cache contents are
                # exactly what we no longer trust — banking them would
                # restore poison. (Handles banked BEFORE the failure
                # survive: host bytes are independent of the rebuilt
                # device buffer, so those streams still swap in.)
                requeued.append(sch.requeue_slot(i, now, swap=False))
        if self.prefix is not None:
            # finished slots keep their seats (evicted next round),
            # but the cache flush below refuses live references —
            # release theirs now and clear the list so the later
            # evict cannot double-release. Their page-table entries
            # still name the freed indices, but a done slot only
            # READS them as discarded ballast — never writes.
            for i in sch.active_indices():
                slot = sch.slots[i]
                if slot.shared_pages:
                    self.prefix.release(slot.shared_pages)
                    slot.shared_pages = []
            self.prefix.flush()
        self.cache = self._fresh_cache()
        if self.events is not None:
            wall = time.perf_counter()
            for req in requeued:
                self.events.record("degraded_round", req.rid, tick=now,
                                   wall=wall)
                self.events.record("resubmitted", req.rid, tick=now,
                                   wall=wall)
        self.resilience.resubmitted += len(requeued)
        if self._round_failures >= self.round_attempts:
            raise RuntimeError(
                f"serving round failed {self._round_failures} "
                f"consecutive times (last: {failure}) — the "
                f"SERVE_ROUND_ATTEMPTS budget is exhausted; the "
                f"device/relay is {failure.verdict}") from failure
        # RetryPolicy pacing before re-driving the round (the §6
        # relay-flap backoff; chaos tests pin the wait to 0)
        wait = self._round_retry.pop_wait()
        if wait:
            time.sleep(wait)
        self._sample_gauges(now)
        self.tick += 1
        return {"tick": now, "evicted": [], "admitted": [],
                "prefilled": [], "verified": [], "decoded_slots": 0,
                "shed": [],
                "degraded": {"phase": failure.phase,
                             "verdict": failure.verdict,
                             "detail": failure.detail,
                             "requeued": [r.rid for r in requeued]}}

    def drain_for_failover(self, tick):
        """Evacuate this replica for the fleet router's failover
        (ISSUE 19): every unsettled request — queued AND in-flight —
        leaves the engine in replayable form, and the engine is left
        in the same clean state ``_recover_round`` rebuilds, so a
        later re-admission probe starts from a sound cache. In-flight
        slots requeue exactly like KV-pressure preemption (pages
        freed, prefix refcounts respected, the known stream stashed in
        ``resume_tokens`` for the prefill replay); finished-but-not-
        evicted slots settle here (their streams are complete — only
        their pages are reclaimed); the prefix cache is flushed (its
        chains point into the abandoned buffer) and the device cache
        rebuilt. Returns the drained requests in replay order
        (in-flight first — they hold the oldest streams), each ready
        for ``submit(..., replay=True)`` on a survivor. The router
        owns the ``failover``/``replayed`` lifecycle events; nothing
        is stamped here."""
        sch = self.scheduler
        wall = time.perf_counter()
        # finished streams settle (complete output, nothing to replay);
        # pages + prefix refs reclaim through the normal evict path
        for r in sch.evict_done(tick, wall):
            if self.events is not None:
                self.events.record("evicted", r.rid, tick=tick,
                                   wall=wall)
        queued = list(sch.queue)
        sch.queue.clear()
        # swap=False: the drained requests replay on a DIFFERENT
        # replica — a host-banked handle from this process cannot
        # restore into the survivor's cache, so bank nothing and
        # release any handle still riding a drained request below
        inflight = [sch.requeue_slot(i, tick, swap=False)
                    for i in sch.active_indices()]
        sch.queue.clear()  # requeue_slot re-appended them — the router
        #                    owns where these requests go next
        if self.prefix is not None:
            self.prefix.flush()
        self.cache = self._fresh_cache()
        self._round_failures = 0
        drained = inflight + queued
        for req in drained:
            handle = getattr(req, "swapped", None)
            if handle is not None:
                self.kv_stats.released(handle)
                req.swapped = None
        return drained

    # ------------- shared round bookkeeping (ISSUEs 14/17 one seam)

    def _advance_counts(self, decode_lanes, steps):
        """Post-dispatch COUNT bookkeeping of one decode block — the
        ONE round-bookkeeping seam shared by the serial and overlapped
        rounds (ISSUE 17 satellite: formerly twin code), walking the
        block's (step, lane) grid with a placeholder where each token
        VALUE lands (``_fill_plan`` fills it — immediately after the
        fetch on the serial round, at the deferred fetch on the
        overlapped one). ``steps`` maps lane -> how many of the
        block's K scan steps that lane's bookkeeping consumes (1
        everywhere at K=1). Every transition here is a count function
        — the overlapped round-t+1 planner never observes round-t
        token values early. Plan entries hold the slot/request REFS
        (eviction between dispatch and fetch detaches the slot, the
        refs stay valid). Returns ``(plan, decoded)``."""
        sch = self.scheduler
        plan = []
        decoded = 0
        for j in range(self.decode_k):
            for i in decode_lanes:
                if j >= steps.get(i, 0):
                    continue
                slot = sch.slots[i]
                req = slot.request
                k_len = len(slot.known)
                consumed_pos = slot.pos
                slot.pos += 1
                if consumed_pos < k_len - 1:
                    # warmup: the consumed token was a KNOWN token
                    # (prefix-hit covered suffix or a resumed stream's
                    # replay overflow) with more to come — the next one
                    # is fed (host-side here at K=1; the staged
                    # ``warm_tokens`` row inside the block at K>1) and
                    # the lane's output is discarded
                    slot.next_token = int(slot.known[consumed_pos + 1])
                    decoded += 1
                    continue
                if not req.done():
                    req.out_tokens.append(None)  # value lands at fill
                    self.tokens_generated += 1
                    plan.append({
                        "lane": i, "step": j, "slot": slot, "req": req,
                        "out_idx": len(req.out_tokens) - 1,
                        # the slot's FIRST output token: its warmup
                        # ended this step — the prefill-done /
                        # first-token seam of the cached path. A
                        # resumed stream's warmup end is NOT a first
                        # token (its seam fired in an earlier cycle —
                        # the wall guard keeps the chain single-shot)
                        "first": (consumed_pos == k_len - 1
                                  and req.first_token_wall is None),
                        "done": req.done(),
                    })
                decoded += 1
        # decode_steps counts DISPATCHES — the ~65 ms relay unit the
        # K-block amortizes; tokens-per-dispatch is the economics ratio
        self.decode_steps += 1
        return plan, decoded

    def _fill_plan(self, plan, next_toks, wall, tick):
        """The VALUE half of the round-bookkeeping seam: fill the
        block's placeholder tokens and stamp the walls / lifecycle
        events the counts deferred. ``next_toks`` is ``[B]`` from the
        single-step program or ``[K, B]`` from the K-block; entries
        index it by (step, lane). A lane with several emits in one
        block fills in step order, so its ``next_token`` (the NEXT
        block's feed) is the last step's token."""
        toks = np.asarray(next_toks)
        if toks.ndim == 1:
            toks = toks[None]
        for e in plan:
            tok = int(toks[e["step"], e["lane"]])
            e["req"].out_tokens[e["out_idx"]] = tok
            e["slot"].next_token = tok
            rid = e["req"].rid
            if e["first"]:
                if e["req"].first_token_wall is None:
                    e["req"].first_token_wall = wall
                if self.events is not None:
                    self.events.record("prefill_done", rid,
                                       tick=tick, wall=wall)
                    self.events.record("first_token", rid,
                                       tick=tick, wall=wall)
            if e["done"]:
                if e["req"].finish_wall is None:
                    e["req"].finish_wall = wall
                if self.events is not None:
                    self.events.record("finished", rid,
                                       tick=tick, wall=wall)

    # ----------------------------------- overlapped round (ISSUE 14)

    def _resolve_pending(self):
        """The sync point of the overlapped round: fetch the in-flight
        decode block's tokens and hand them to ``_fill_plan`` (stamped
        with the dispatching round's tick — the round the serial
        engine would have recorded them at)."""
        p = self._pending
        if p is None:
            return
        self._pending = None
        next_toks = np.asarray(p["next_toks"])   # blocks until ready
        wall = time.perf_counter()
        # planning time between dispatch and this fetch ran INSIDE the
        # device window — counting it as dispatch wall is the measured
        # claim (run wall minus this = the host slice overlap removed)
        self.device_dispatch_s += wall - p["t0"]
        self._fill_plan(p["plan"], next_toks, wall, p["tick"])

    def flush(self):
        """Resolve the in-flight decode round (overlap mode): fill the
        placeholder tokens and land their lifecycle events. A no-op on
        the serial engine or with nothing in flight; ``run_trace``
        calls it for you — direct ``step()`` drivers call it before
        reading ``out_tokens``."""
        self._resolve_pending()

    def _step_overlap(self, arrivals=None):
        """The deferred-fetch pipelined round: PLAN round t+1 (evict/
        admit/prefix-COW — count state only) while the device executes
        round t, sync at the fetch, then prefill + dispatch round
        t+1's decode and return with IT in flight. Same admissions,
        evictions and tokens per round as the serial engine (pinned by
        test); only the host schedule moves."""
        sch = self.scheduler
        now = self.tick
        if arrivals:
            for req in arrivals:
                self.submit(req)
        wall = time.perf_counter()
        # ---- the overlap window: host planning under the in-flight
        # decode. wall_time=None on evict: finish_wall belongs to the
        # fetch that produced the finishing token (_resolve_pending).
        evicted = sch.evict_done(now, None)
        # the deadline shedder composes with the overlapped schedule:
        # it touches QUEUED requests only (no placeholder tokens exist
        # before admission), so the count-function contract holds
        shed = self._shed_queue(now, wall) if self.shed else []
        admitted = sch.admit(now, wall)
        if self.events is not None:
            for i in admitted:
                self.events.record("admitted", sch.slots[i].request.rid,
                                   tick=now, wall=wall)
        to_prefill = []
        for i in admitted:
            slot = sch.slots[i]
            if slot.prefix_hit:
                # COW copies are device work: they queue behind the
                # in-flight decode and run before any dependent read
                for src, dst in slot.cow_copies:
                    self._copy_page(src, dst)
                slot.cow_copies = []
            else:
                to_prefill.append(i)
        # ---- sync point: round t's values land (finished /
        # first-token events), then the evictions planned above are
        # RECORDED — after the finished events they must follow
        self._resolve_pending()
        for r in evicted:
            if r.finish_wall is None:
                r.finish_wall = wall  # the evict_done backstop seam
        if self.events is not None and evicted:
            wall_e = time.perf_counter()
            for r in evicted:
                self.events.record("evicted", r.rid, tick=now,
                                   wall=wall_e)
        prefilled = self._run_prefill(to_prefill) if to_prefill else []
        decode_lanes = sch.active_indices()
        decoded = 0
        if decode_lanes:
            next_toks, t0, steps = self._dispatch_decode(decode_lanes)
            # NO fetch: the round returns with the decode in flight;
            # counts advance now so the next round can plan
            plan, decoded = self._advance_counts(decode_lanes, steps)
            self._pending = {"next_toks": next_toks, "plan": plan,
                             "t0": t0, "tick": now}
        self._sample_gauges(now)
        self.tick += 1
        return {"tick": now, "evicted": [r.rid for r in evicted],
                "admitted": admitted, "prefilled": prefilled,
                "verified": [], "decoded_slots": decoded,
                "shed": [r.rid for r in shed]}

    def run_trace(self, requests, max_ticks=10000):
        """Replay a synthetic trace to completion: requests are
        submitted when their arrival tick is due; returns the
        completed Request list (latency fields filled). Flushes the
        overlapped engine's in-flight round before returning, so the
        completed list never holds a placeholder token. A trace
        request SETTLES by completing, being shed (deadline shedder)
        or being rejected at submit (admission control) — the
        resilience layers drop load, they never hang the drain
        (rejected/shed requests are in ``self.rejected`` /
        ``scheduler.shed``, not the completed list)."""
        pending = sorted(requests, key=lambda r: (r.arrival, r.rid))
        n_total = len(pending)
        trace_set = {id(r) for r in requests}
        # incremental settle counter: the three lists only ever grow,
        # so each tick scans NEW entries only (the replay loop is the
        # measured host slice — a full rescan per tick would inflate
        # every serving row's host_ms). Counting trace requests only:
        # chaos bursts (serve_burst) complete/reject through the same
        # lists but must not inflate the trace's account.
        settled = 0
        cursors = [0, 0, 0]

        def _drain_settled():
            nonlocal settled
            lists = (self.scheduler.completed, self.rejected,
                     self.scheduler.shed)
            for k, lst in enumerate(lists):
                for idx in range(cursors[k], len(lst)):
                    item = lst[idx]
                    r = item[0] if k == 1 else item
                    if id(r) in trace_set:
                        settled += 1
                cursors[k] = len(lst)
            return settled

        while _drain_settled() < n_total:
            if self.tick >= max_ticks:
                raise RuntimeError(
                    f"trace did not drain in {max_ticks} ticks "
                    f"({settled}/{n_total} settled)")
            due = [r for r in pending if r.arrival <= self.tick]
            pending = [r for r in pending if r.arrival > self.tick]
            self.step(arrivals=due)
        self.flush()
        return list(self.scheduler.completed)
