"""ServingEngine: cache + compiled steps + scheduler in one object.

The host/device shape follows the concurrency-paper discipline
(PAPERS.md arXiv:2011.03641): ALL host work — admission, eviction,
page accounting, array staging — happens between device dispatches,
and the device programs themselves are compiled exactly once each
(prefill at one packed bucket shape, decode at the slot shape), so
the steady-state loop is dispatch → host bookkeeping → dispatch with
no recompiles on the critical path. Scheduler events change array
VALUES only; ``decode_cache_size()`` exposes the jit cache size so
tests (and ``dryrun_serving``) can assert the contract mechanically.

Knob resolution at engine build (the CLAUDE.md asymmetry):

* ``weight_quant=`` per-call True RAISES when the params cannot take
  the int8 path; None defers to ``quant.set_weight_quant`` /
  ``APEX_SERVE_WEIGHT_QUANT`` (preferences), default OFF.
* ``decode_impl=`` / ``decode_block_h=`` ride per-call into the
  decode-attention family on every step (raising semantics live
  there); None defers to the family's setter/env/table resolution.
* ``policy=`` per-call unknown policies RAISE
  (``scheduler.resolve_policy``); None defers to ``APEX_SERVE_SCHED``.

Observability (ISSUE 11): when ``lifecycle.enabled()`` the engine
keeps a request-lifecycle :class:`~apex_tpu.serving.lifecycle.EventLog`
(``self.events``) — submitted/admitted/prefill_done/first_token/
finished/evicted events plus per-round scheduler gauges — appended
strictly BETWEEN device dispatches, so the jitted programs (and
``decode_cache_size()==1``) are untouched either way; disabled mode
allocates no log and is behavior-identical. ``device_dispatch_s``
accumulates the wall time spent inside device round trips (prefill +
decode fetch), so a harness can attribute the host slice of the
serving loop (``costs.overlap_bound`` — the ROADMAP 4c gap).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.serving import lifecycle
from apex_tpu.serving import model as smodel
from apex_tpu.serving import quant as quant_mod
from apex_tpu.serving.kv_cache import PageAllocator, init_cache
from apex_tpu.serving.scheduler import ContinuousBatchingScheduler


def detokenize(tokens):
    """Toy detokenizer for dryruns/smokes: token id -> letter."""
    return "".join(chr(97 + int(t) % 26) for t in tokens)


class ServingEngine:
    def __init__(self, cfg, params=None, *, num_slots=4, page_size=16,
                 num_pages=64, max_seq=None, prefill_len=64,
                 prefill_requests=None, weight_quant=None,
                 decode_impl=None, decode_block_h=None, interpret=None,
                 policy=None, seed=0):
        smodel.check_serving_config(cfg)
        self.cfg = cfg
        self.num_slots = int(num_slots)
        self.page_size = int(page_size)
        self.max_seq = int(max_seq or cfg.max_position_embeddings)
        if self.max_seq > cfg.max_position_embeddings:
            raise ValueError("max_seq exceeds the position table")
        self.max_pages = -(-self.max_seq // self.page_size)
        self.prefill_len = int(prefill_len)
        self.prefill_requests = int(prefill_requests or num_slots)
        self.params = params if params is not None \
            else smodel.init_gpt_params(cfg, seed)

        # weight quant: per-call demand raises on un-honorable;
        # env/setter preferences fall back (quant.resolve)
        if weight_quant is True:
            for name, w in (("word_embeddings",
                             self.params["word_embeddings"]),):
                if not quant_mod.quantizable(w):
                    raise ValueError(
                        f"weight_quant=True cannot be honored: {name} "
                        f"has dtype {w.dtype}")
        self.weight_quant = quant_mod.resolve(weight_quant)
        self.qparams = smodel.quantize_decode_params(
            self.params, cfg) if self.weight_quant else None
        self.decode_impl = decode_impl
        self.decode_block_h = decode_block_h
        self.interpret = interpret

        self.cache = init_cache(
            cfg.num_layers, cfg.num_attention_heads, num_pages,
            page_size, cfg.head_dim, smodel.compute_dtype(cfg))
        self.allocator = PageAllocator(num_pages)
        self.scheduler = ContinuousBatchingScheduler(
            num_slots, self.max_pages, page_size, self.allocator,
            policy=policy)
        # lifecycle observability (gated, host-side only): None when
        # collection is off — disabled mode appends nothing and reads
        # no extra clocks beyond the per-round stamps below
        self.events = lifecycle.EventLog() if lifecycle.enabled() \
            else None

        def _prefill(cache, ids, positions, seg, token_rows,
                     page_table, last_idx):
            return smodel.prefill(self.params, cache, ids, positions,
                                  seg, token_rows, page_table,
                                  last_idx, cfg=cfg)

        def _decode(cache, tokens, lengths, page_table):
            return smodel.decode_step(
                self.params, cache, tokens, lengths, page_table,
                cfg=cfg, qparams=self.qparams,
                decode_impl=self.decode_impl,
                decode_block_h=self.decode_block_h,
                interpret=self.interpret)

        # donate the cache: the scatter-updated pages stay in place
        self._prefill_fn = jax.jit(_prefill, donate_argnums=(0,))
        self._decode_fn = jax.jit(_decode, donate_argnums=(0,))
        self.tick = 0
        self.decode_steps = 0
        self.tokens_generated = 0
        # wall seconds spent inside device round trips (prefill +
        # decode dispatch-to-fetch): run wall minus this is the HOST
        # slice of the serving loop — the overlap_bound input
        self.device_dispatch_s = 0.0

    # ---------------------------------------------------------- plumbing

    def decode_cache_size(self):
        """jit-cache entry count of the decode step — the
        jaxpr-stability assertion surface (must stay 1 whatever the
        scheduler admits or evicts)."""
        return self._decode_fn._cache_size()

    def submit(self, request):
        """Enqueue one request; impossible requests raise HERE, before
        anything is enqueued or allocated. The scheduler validates the
        page budget (max_seq); the engine additionally owns the packed
        prefill bucket, so the prompt-vs-prefill_len bound — which
        would otherwise crash _run_prefill mid-round AFTER admission
        had already filled a slot and allocated pages — is checked at
        the same front door."""
        if len(request.prompt) > self.prefill_len:
            raise ValueError(
                f"request {request.rid}: prompt ({len(request.prompt)} "
                f"tokens) exceeds prefill_len={self.prefill_len}")
        request.enqueue_wall = time.perf_counter()
        self.scheduler.submit(request)
        if self.events is not None:
            self.events.record("submitted", request.rid, tick=self.tick,
                               wall=request.enqueue_wall)

    # ----------------------------------------------------------- prefill

    def _run_prefill(self, slot_indices):
        """Pack the newly admitted slots' prompts into [prefill_len]
        batches (segment ids 1..R per batch; padding 0 -> null page
        row) and fill the cache. Greedy packing: a batch closes when
        the next prompt would overflow the bucket or the per-batch
        request cap — further admissions start a new packed dispatch
        of the SAME compiled program. Sets each slot's first decode
        token."""
        sch = self.scheduler
        S, R = self.prefill_len, self.prefill_requests
        batches, cur, used = [], [], 0
        for si in slot_indices:
            n = len(sch.slots[si].request.prompt)
            if n > S:
                raise ValueError(
                    f"prompt of request "
                    f"{sch.slots[si].request.rid} ({n} tokens) exceeds "
                    f"prefill_len={S}")
            if cur and (used + n > S or len(cur) >= R):
                batches.append(cur)
                cur, used = [], 0
            cur.append(si)
            used += n
        if cur:
            batches.append(cur)
        # page table rows [num_slots + 1, max_pages]: the spare row is
        # the padding tokens' all-null destination
        pt = np.zeros((self.num_slots + 1, self.max_pages), np.int32)
        pt[:self.num_slots] = sch.page_table_rows()
        wall = None
        for batch in batches:
            ids = np.zeros((S,), np.int32)
            positions = np.zeros((S,), np.int32)
            seg = np.zeros((S,), np.int32)
            token_rows = np.full((S,), self.num_slots, np.int32)
            last_idx = np.zeros((R,), np.int32)
            cursor = 0
            for r, si in enumerate(batch):
                prompt = sch.slots[si].request.prompt
                n = len(prompt)
                ids[cursor:cursor + n] = prompt
                positions[cursor:cursor + n] = np.arange(n)
                seg[cursor:cursor + n] = r + 1
                token_rows[cursor:cursor + n] = si
                last_idx[r] = cursor + n - 1
                cursor += n
            t0 = time.perf_counter()
            self.cache, logits = self._prefill_fn(
                self.cache, jnp.asarray(ids), jnp.asarray(positions),
                jnp.asarray(seg), jnp.asarray(token_rows),
                jnp.asarray(pt), jnp.asarray(last_idx))
            next_toks = np.asarray(
                jnp.argmax(logits.astype(jnp.float32), axis=-1))
            wall = time.perf_counter()
            self.device_dispatch_s += wall - t0
            for r, si in enumerate(batch):
                slot = sch.slots[si]
                slot.pos = len(slot.request.prompt)
                tok = int(next_toks[r])
                slot.request.out_tokens.append(tok)
                slot.next_token = tok
                self.tokens_generated += 1
                # prefill always samples the request's FIRST token —
                # this dispatch's fetch wall IS the TTFT stamp
                if slot.request.first_token_wall is None:
                    slot.request.first_token_wall = wall
                if slot.request.done():
                    slot.request.finish_wall = wall
                if self.events is not None:
                    rid = slot.request.rid
                    self.events.record("prefill_done", rid,
                                       tick=self.tick, wall=wall)
                    self.events.record("first_token", rid,
                                       tick=self.tick, wall=wall)
                    if slot.request.done():
                        self.events.record("finished", rid,
                                           tick=self.tick, wall=wall)
        return slot_indices

    # ------------------------------------------------------------- steps

    def step(self, arrivals=None):
        """One scheduler round: enqueue due arrivals, evict, admit (+
        prefill), decode every active slot. Returns a dict of what
        happened (the dryrun/trace-replay surface)."""
        sch = self.scheduler
        now = self.tick
        if arrivals:
            for req in arrivals:
                self.submit(req)
        wall = time.perf_counter()
        evicted = sch.evict_done(now, wall)
        admitted = sch.admit(now, wall)
        if self.events is not None:
            for r in evicted:
                self.events.record("evicted", r.rid, tick=now, wall=wall)
            for i in admitted:
                self.events.record("admitted", sch.slots[i].request.rid,
                                   tick=now, wall=wall)
        prefilled = self._run_prefill(admitted) if admitted else []
        active = sch.active_indices()
        decoded = 0
        if active:
            tokens, lengths = sch.decode_inputs()
            pt = np.asarray(sch.page_table_rows(), np.int32)
            t0 = time.perf_counter()
            self.cache, next_toks, _ = self._decode_fn(
                self.cache, jnp.asarray(tokens, dtype=jnp.int32),
                jnp.asarray(lengths, dtype=jnp.int32), jnp.asarray(pt))
            next_toks = np.asarray(next_toks)
            wall2 = time.perf_counter()
            self.device_dispatch_s += wall2 - t0
            for i in active:
                slot = sch.slots[i]
                slot.pos += 1
                if not slot.request.done():
                    tok = int(next_toks[i])
                    slot.request.out_tokens.append(tok)
                    slot.next_token = tok
                    self.tokens_generated += 1
                    if slot.request.done():
                        slot.request.finish_wall = wall2
                        if self.events is not None:
                            self.events.record("finished",
                                               slot.request.rid,
                                               tick=now, wall=wall2)
                decoded += 1
            self.decode_steps += 1
        if self.events is not None:
            # one gauge sample per scheduler round, AFTER the round's
            # device work (occupancy as the next round will see it)
            wall3 = time.perf_counter()
            self.events.sample_gauges(
                tick=now, wall=wall3,
                slots_active=len(sch.active_indices()),
                num_slots=self.num_slots,
                queue_depth=sch.queue_depth(),
                kv_pages_live=(self.allocator.num_pages - 1
                               - self.allocator.free_count),
                kv_pages_total=self.allocator.num_pages,
                hol_wait_s=sch.head_of_line_wait(wall3))
        # a slot whose LAST token was just produced frees at the next
        # round's evict — one round of slack, never a starved queue
        self.tick += 1
        return {"tick": now, "evicted": [r.rid for r in evicted],
                "admitted": admitted, "prefilled": prefilled,
                "decoded_slots": decoded}

    def run_trace(self, requests, max_ticks=10000):
        """Replay a synthetic trace to completion: requests are
        submitted when their arrival tick is due; returns the
        completed Request list (latency fields filled)."""
        pending = sorted(requests, key=lambda r: (r.arrival, r.rid))
        n_total = len(pending)
        while len(self.scheduler.completed) < n_total:
            if self.tick >= max_ticks:
                raise RuntimeError(
                    f"trace did not drain in {max_ticks} ticks "
                    f"({len(self.scheduler.completed)}/{n_total} done)")
            due = [r for r in pending if r.arrival <= self.tick]
            pending = [r for r in pending if r.arrival > self.tick]
            self.step(arrivals=due)
        return list(self.scheduler.completed)
