"""Pure prefill / decode-step functions over the GPTModel param tree.

The serving forward consumes the EXACT parameter tree
``GPTModel.init`` produces (standalone_transformer_lm.py — flagship
model; weights move from training to serving with no conversion), and
mirrors its numerics op-for-op: fp32 layer-norm statistics
(normalization/fused_layer_norm.py jnp path), ``x @ W^T`` matmuls with
fp32 accumulation cast back to the compute dtype
(tensor_parallel/layers.py ``_mm``), the per-head ``[q|k|v]``
interleaving of the fused qkv projection, approximate-gelu MLP, and
tied logits against the word table (``parallel_lm_logits``). Parity
with ``GPTModel.apply`` is asserted in tests/test_serving.py — the
serving stack's numbers are the training stack's numbers.

Two jitted programs (built once per engine — the ISSUE 10
jaxpr-stability contract):

* :func:`prefill` — one packed varlen prompt batch ``[S_pack]`` with
  segment ids (exactly the fmha-style packed shape the CP satellite
  opens up): causal + segment-masked attention via ``fused_attention``,
  every token's K/V scattered into its request's cache pages (pure
  index arithmetic — page/offset computed from the page table), and
  the next-token logits gathered at each request's last prompt token.
* :func:`decode_step` — one token per active slot over the paged
  cache: append K/V at ``length-1``, attend through the dispatched
  decode-attention family (ops/decode_attention_pallas.py), greedy
  next token. Decode matmuls optionally run int8-quantized weights
  (``apex_tpu.serving.quant`` — knob-gated, default OFF).

Serving constraints (validated by :func:`check_serving_config`): no
dropout, no query-key layer scaling (its coeff is a training-range
trick; minimal.py disables it for the same uniformity reason), single
chip (tp=1 param shapes), no MoE/sequence/context parallelism.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from apex_tpu.dispatch import tiles as _tiles
from apex_tpu.serving import kv_tier as kv_tier_mod
from apex_tpu.serving import quant as quant_mod
from apex_tpu.serving import sampling as sampling_mod


def check_serving_config(cfg):
    """Raise on TransformerConfig options the serving forward does not
    model (explicit refusal beats silent numeric drift)."""
    problems = []
    if cfg.hidden_dropout or cfg.attention_dropout:
        problems.append("dropout > 0 (serving is deterministic)")
    if cfg.apply_query_key_layer_scaling:
        problems.append("apply_query_key_layer_scaling (training-range "
                        "trick; set False like minimal.py)")
    if cfg.num_moe_experts:
        problems.append("MoE")
    if cfg.sequence_parallel or cfg.context_parallel_axis:
        problems.append("sequence/context parallelism (single-chip "
                        "serving engine)")
    if problems:
        raise ValueError("serving does not support: "
                         + "; ".join(problems))


def compute_dtype(cfg):
    return jnp.bfloat16 if cfg.bf16 else (
        jnp.float16 if cfg.fp16 else jnp.float32)


def init_gpt_params(cfg, seed=0):
    """GPTModel.init on a 1-device TENSOR_AXIS mesh (the lax.axis_size
    calls inside the model need the axis bound) — the serving param
    source when no trained checkpoint is supplied."""
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.transformer.parallel_state import TENSOR_AXIS
    from apex_tpu.transformer.testing import GPTModel

    model = GPTModel(cfg)
    mesh = Mesh(np.asarray(jax.devices()[:1]), (TENSOR_AXIS,))
    b, s = 1, min(8, cfg.max_position_embeddings)
    ids = jnp.zeros((b, s), jnp.int32)
    pos = jnp.zeros((b, s), jnp.int32)

    def init(ids, pos):
        return model.init(jax.random.PRNGKey(seed), ids, pos,
                          None)["params"]

    return jax.jit(jax.shard_map(
        init, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=False))(ids, pos)


def _mm(x, w, dtype):
    """x @ w^T, fp32 accumulation (the layers.py `_mm` idiom)."""
    return lax.dot_general(
        x.astype(dtype), w.astype(dtype),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dtype)


def _layer_norm(x, p, eps):
    """fp32-stats LN (fused_layer_norm's jnp path, op-for-op)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    y = y * p["weight"].astype(jnp.float32) \
        + p["bias"].astype(jnp.float32)
    return y.astype(x.dtype)


def _split_qkv(qkv, n_heads, hd):
    """[rows, 3*proj] -> (q, k, v) each [rows, n_heads, hd] with the
    per-head [q|k|v] interleaving of ParallelAttention's fused
    projection (reshape to [rows, np, 3*hd], split on the last axis)."""
    rows = qkv.shape[0]
    qkv = qkv.reshape(rows, n_heads, 3 * hd)
    return (qkv[..., :hd], qkv[..., hd:2 * hd], qkv[..., 2 * hd:])


def quantize_decode_params(params, cfg):
    """The decode-side weight records: each matmul weight becomes
    ``{"wq", "scale"}`` (int8 + per-channel fp32); biases and norms
    stay full precision, and the word table keeps its float copy for
    the embedding GATHER (only the logits MATMUL runs the int8 copy —
    the gather reads one row per token, the matmul reads them all)."""
    qp = {"layers": [], "word_logits": None}
    for i in range(cfg.num_layers):
        lp = params["transformer"][f"layer_{i}"]
        rec = {}
        for name, sub in (("qkv", lp["self_attention"]["query_key_value"]),
                          ("dense", lp["self_attention"]["dense"]),
                          ("h4", lp["mlp"]["dense_h_to_4h"]),
                          ("4h", lp["mlp"]["dense_4h_to_h"])):
            wq, scale = quant_mod.quantize_weight(sub["weight"])
            rec[name] = {"wq": wq, "scale": scale}
        qp["layers"].append(rec)
    wq, scale = quant_mod.quantize_weight(params["word_embeddings"])
    qp["word_logits"] = {"wq": wq, "scale": scale}
    return qp


def _wmat(x, full_w, qrec, dtype):
    """One decode matmul: the int8 record when quantization resolved
    ON (qrec non-None), else the full-precision weight."""
    if qrec is not None:
        return quant_mod.qmatmul(x, qrec["wq"], qrec["scale"], dtype)
    return _mm(x, full_w, dtype)


def _trunk_layer(x, lp, qr, cfg, attn):
    """ONE transformer layer of the serving trunk — shared verbatim by
    prefill and decode so the two paths cannot drift numerically (the
    decode-vs-prefill parity the acceptance pins is a property of this
    function, applied twice). ``qr`` is the layer's int8 record dict
    ({} = full precision — ``_wmat`` with qrec None IS ``_mm``);
    ``attn(q, k, v)`` owns everything path-specific: the cache scatter
    for this layer's k/v and the attention itself, returning the
    ``[rows, n_heads*head_dim]`` context."""
    dtype = x.dtype
    ln1 = _layer_norm(x, lp["input_layernorm"], cfg.layernorm_epsilon)
    sa = lp["self_attention"]
    qkv = _wmat(ln1, sa["query_key_value"]["weight"], qr.get("qkv"),
                dtype) + sa["query_key_value"]["bias"].astype(dtype)
    q, k, v = _split_qkv(qkv, cfg.num_attention_heads, cfg.head_dim)
    ctx = attn(q, k, v)
    attn_out = _wmat(ctx, sa["dense"]["weight"], qr.get("dense"),
                     dtype) + sa["dense"]["bias"].astype(dtype)
    x = x + attn_out
    ln2 = _layer_norm(x, lp["post_attention_layernorm"],
                      cfg.layernorm_epsilon)
    mlp = lp["mlp"]
    inter = _wmat(ln2, mlp["dense_h_to_4h"]["weight"], qr.get("h4"),
                  dtype) + mlp["dense_h_to_4h"]["bias"].astype(dtype)
    inter = jax.nn.gelu(inter, approximate=True)
    out = _wmat(inter, mlp["dense_4h_to_h"]["weight"], qr.get("4h"),
                dtype) + mlp["dense_4h_to_h"]["bias"].astype(dtype)
    return x + out


# --------------------------------------------------------------- prefill

def prefill(params, cache, ids, positions, seg, token_rows, page_table,
            last_idx, keep_scale=None, *, cfg):
    """One packed prompt batch through the trunk, filling the cache.

    ids/positions/seg/token_rows: ``[S_pack]`` — token values, their
    within-request positions, segment ids (0 = padding, 1..R real),
    and each token's row into ``page_table`` (padding rows point at
    the all-null spare row). page_table: ``[R_rows, max_pages]``.
    last_idx: ``[G]`` flat pack indices to gather logits at (inactive
    entries 0 — callers mask). Plain prefill gathers one index per
    request (its last prompt token); the SPECULATIVE VERIFY dispatch
    of this same program (ISSUE 13) gathers K+1 indices per request —
    the pending-token + draft positions whose greedy chain decides
    acceptance. Returns ``(cache, logits [G, vocab])``.

    keep_scale: ``[num_pages]`` float (1 = the page already holds live
    rows whose scale must survive, 0 = fresh or null) — required by
    and only consumed on the int8 KV tier (``kv_tier.is_quantized``),
    where the scatter routes through the quantize-at-write codec.
    """
    dtype = compute_dtype(cfg)
    hd, n_heads = cfg.head_dim, cfg.num_attention_heads
    ps = cache["k"].shape[3]
    S = ids.shape[0]

    word = params["word_embeddings"]
    x = jnp.take(word, ids, axis=0) \
        + jnp.take(params["embedding"]["position_embeddings"],
                   positions, axis=0)
    x = x.astype(dtype)

    dest_page = jnp.take_along_axis(
        token_rows_to_pages(page_table, token_rows),
        (positions // ps)[:, None], axis=1)[:, 0]
    dest_off = positions % ps

    quant = kv_tier_mod.is_quantized(cache)
    if quant and keep_scale is None:
        raise ValueError(
            "prefill on a quantized cache needs the keep_scale row — "
            "requantizing without it would zero surviving pages")

    from apex_tpu.ops import fused_attention

    seg2 = seg.astype(jnp.int32)[None, :]
    for i in range(cfg.num_layers):
        def attn(q, k, v, i=i):
            # scatter this layer's K/V into the paged cache: values
            # are [S, H, d] as produced (mixed basic/advanced indexing
            # puts the gathered token axis FIRST) at (page, offset) —
            # index arithmetic only (the int8 tier routes the same
            # scatter through the quantize-at-write codec) — then
            # packed causal+segment attention over the full bucket
            nonlocal cache
            if quant:
                cache = kv_tier_mod.prefill_scatter_quant(
                    cache, i, "k", k, dest_page, dest_off, keep_scale)
                cache = kv_tier_mod.prefill_scatter_quant(
                    cache, i, "v", v, dest_page, dest_off, keep_scale)
            else:
                cache["k"] = cache["k"].at[
                    i, :, dest_page, dest_off, :].set(
                    k.astype(cache["k"].dtype))
                cache["v"] = cache["v"].at[
                    i, :, dest_page, dest_off, :].set(
                    v.astype(cache["v"].dtype))
            ctx = fused_attention(
                q.transpose(1, 0, 2)[None],
                k.transpose(1, 0, 2)[None],
                v.transpose(1, 0, 2)[None], causal=True,
                sm_scale=1.0 / math.sqrt(hd),
                segment_ids=(seg2, seg2))
            return ctx[0].transpose(1, 0, 2).reshape(S, n_heads * hd)

        x = _trunk_layer(x, params["transformer"][f"layer_{i}"], {},
                         cfg, attn)

    x = _layer_norm(x, params["transformer"]["final_layernorm"],
                    cfg.layernorm_epsilon)
    x_last = jnp.take(x, last_idx, axis=0)
    logits = _mm(x_last, word, dtype)
    return cache, logits


def token_rows_to_pages(page_table, token_rows):
    """[S, max_pages] per-token page-table rows (a gather; split out
    so the scatter line above stays readable)."""
    return jnp.take(page_table, token_rows, axis=0)


# ---------------------------------------------------------------- decode

def decode_step(params, cache, tokens, lengths, page_table, *, cfg,
                qparams=None, decode_impl=None, decode_block_h=None,
                interpret=None):
    """One greedy decode step for every slot (q_len = 1).

    tokens/lengths: ``[B]`` — the token to process and the context
    length INCLUDING it (0 = inactive slot: its writes land on the
    null page, its logits/next token are zeros). page_table:
    ``[B, max_pages]``. Returns ``(cache, next_tokens [B],
    logits [B, vocab])``.

    ``qparams`` (from :func:`quantize_decode_params`) switches the
    decode matmuls to the int8 records; ``decode_impl`` /
    ``decode_block_h`` ride per-call into the decode-attention family
    (None = the family's own knob/table resolution).
    """
    from apex_tpu.ops import decode_attention_pallas as dap

    dtype = compute_dtype(cfg)
    hd, n_heads = cfg.head_dim, cfg.num_attention_heads
    ps = cache["k"].shape[3]
    B = tokens.shape[0]

    active = lengths > 0
    positions = jnp.maximum(lengths - 1, 0)
    write_page = jnp.where(
        active,
        jnp.take_along_axis(page_table, (positions // ps)[:, None],
                            axis=1)[:, 0],
        0)
    write_off = jnp.where(active, positions % ps, 0)

    word = params["word_embeddings"]
    x = jnp.take(word, tokens, axis=0) \
        + jnp.take(params["embedding"]["position_embeddings"],
                   positions, axis=0)
    x = x.astype(dtype)

    ql = qparams["layers"] if qparams is not None else None
    quant = kv_tier_mod.is_quantized(cache)
    for i in range(cfg.num_layers):
        def attn(q, k, v, i=i):
            # append this step's k/v at (page, offset) — the int8 tier
            # rewrites the touched pages through the per-page RMW
            # codec — then paged decode attention through the
            # dispatched fifth family (quantized pages ride with their
            # per-(page, head) scale planes)
            nonlocal cache
            if quant:
                cache = kv_tier_mod.decode_scatter_quant(
                    cache, i, "k", k, write_page, write_off)
                cache = kv_tier_mod.decode_scatter_quant(
                    cache, i, "v", v, write_page, write_off)
            else:
                cache["k"] = cache["k"].at[
                    i, :, write_page, write_off, :].set(
                    k.astype(cache["k"].dtype))  # [B, H, d] values
                cache["v"] = cache["v"].at[
                    i, :, write_page, write_off, :].set(
                    v.astype(cache["v"].dtype))
            ctx = dap.decode_attention(
                q.astype(dtype), cache["k"][i], cache["v"][i],
                page_table, lengths, sm_scale=1.0 / math.sqrt(hd),
                k_scale=cache["k_scale"][i] if quant else None,
                v_scale=cache["v_scale"][i] if quant else None,
                impl=decode_impl, block_h=decode_block_h,
                interpret=interpret)
            return ctx.reshape(B, n_heads * hd).astype(dtype)

        x = _trunk_layer(x, params["transformer"][f"layer_{i}"],
                         ql[i] if ql is not None else {}, cfg, attn)

    x = _layer_norm(x, params["transformer"]["final_layernorm"],
                    cfg.layernorm_epsilon)
    logits = _wmat(x, word,
                   qparams["word_logits"] if qparams is not None
                   else None, dtype)
    next_tokens = jnp.where(
        active, jnp.argmax(logits.astype(jnp.float32), axis=-1)
        .astype(jnp.int32), 0)
    return cache, next_tokens, logits


# ---------------------------------------------- multi-token decode block


def resolve_decode_k(per_call=None):
    """Knob resolution for the multi-token decode block (ISSUE 17),
    per the CLAUDE.md asymmetry: the per-call ``decode_k=`` argument
    is a DEMAND — a bool, non-int or K < 1 raises; the
    ``APEX_SERVE_DECODE_K`` env value is a PREFERENCE through the
    one-home positive-int parser (garbage warns once and falls back).
    Default K=1 per the measured-dispatch rule — the single-step
    program stays the dispatched one until the ``serving_multitok``
    device A/B (PERF.md §2) lands."""
    if per_call is not None:
        if isinstance(per_call, bool) or not isinstance(per_call, int) \
                or per_call < 1:
            raise ValueError(
                f"decode_k= wants an int >= 1, got {per_call!r}")
        return per_call
    return _tiles.env_int("APEX_SERVE_DECODE_K") or 1


def decode_block(params, cache, tokens, lengths, page_table,
                 steps_budget, warm_tokens, warm_steps, lanes=None, *,
                 k, cfg, qparams=None, decode_impl=None,
                 decode_block_h=None, interpret=None):
    """K decode steps in ONE dispatch (ISSUE 17): a ``lax.scan`` over
    :func:`decode_step` with in-program per-slot stop detection, so a
    single device round trip amortizes the relay's per-dispatch floor
    across up to K tokens per slot.

    ``k`` is a STATIC program constant — at most a second
    compile-cache key next to the K=1 single-step program; every
    per-round quantity below is an array VALUE, so scheduler events
    (admit/evict/shed/preempt between blocks) never recompile. Per
    scanned step ``j`` (0-based):

    * a lane is LIVE while ``j < steps_budget[i]`` (its host-computed
      budget: warmup steps left + remaining token budget, capped at
      K) and its staged length is non-zero. A finished/empty lane's
      length is masked to 0 for the step, which routes its K/V write
      to the null page 0 and emits the pad token 0 — exactly
      :func:`decode_step`'s inactive-slot contract — and its length
      does not advance.
    * warmup steps (``j < warm_steps[i]`` — a prefix-hit prompt or a
      resumed stream's replay overflow) feed the next KNOWN token
      (``warm_tokens[j, i]``) as the following step's input instead
      of the model's emission; the emitted token is discarded
      host-side, mirroring the K=1 warmup loop.
    * sampling lanes (``lanes`` = the engine's staged ``(temps,
      top_ks, top_ps, keys, counters)`` arrays) fold the generation
      index INSIDE the scan: the draw for generation index g always
      uses ``fold_in(key, g)`` whatever K or the batch composition —
      per-step counters are ``counters + max(0, j - warm_steps)``, so
      a seeded request's stream is pinned identical to the K=1
      engine's (the per-slot-RNG determinism test, now under K).

    tokens/lengths: ``[B]`` staged exactly as for :func:`decode_step`;
    steps_budget/warm_steps: ``[B]`` int32; warm_tokens: ``[K, B]``
    int32. Returns ``(cache, toks [K, B], logits [K, B, vocab])`` —
    row j holds step j's emissions (warmup/dead rows are discarded or
    pad by construction).
    """
    def body(carry, xs):
        cache, tok, lens = carry
        j, warm_j = xs
        live = (j < steps_budget) & (lens > 0)
        step_lens = jnp.where(live, lens, 0)
        cache, emitted, logits = decode_step(
            params, cache, tok, step_lens, page_table, cfg=cfg,
            qparams=qparams, decode_impl=decode_impl,
            decode_block_h=decode_block_h, interpret=interpret)
        if lanes is not None:
            temps, top_ks, top_ps, keys, counters = lanes
            ctr = counters + jnp.maximum(j - warm_steps, 0)
            emitted = sampling_mod.sample_tokens(
                logits, temps, top_ks, top_ps, keys, ctr, live)
        emitted = emitted.astype(jnp.int32)
        nxt = jnp.where(j < warm_steps, warm_j, emitted)
        lens = jnp.where(live, lens + 1, lens)
        return (cache, nxt, lens), (emitted, logits)

    xs = (jnp.arange(k, dtype=jnp.int32), warm_tokens)
    (cache, _, _), (toks, logits) = lax.scan(
        body, (cache, tokens, lengths), xs)
    return cache, toks, logits
