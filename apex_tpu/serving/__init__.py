"""apex_tpu.serving — the inference serving stack (ISSUE 10).

The repo's first decode path: prefill/decode split over a static-shape
PAGED KV cache, a host-side continuous-batching scheduler, the
decode-attention Pallas kernel as the fifth dispatch family, and int8
weight quantization for the decode matmuls behind
``APEX_SERVE_WEIGHT_QUANT``. Grounded in PAPERS.md "Fine-Tuning and
Serving Gemma 4 31B on Cloud TPU" (arXiv:2605.25645 — the
prefill/decode + KV-cache design) with the host/device overlap
discipline of "Exploring the limits of Concurrency in ML Training on
Google TPUs" (arXiv:2011.03641).

Layering:

* ``kv_cache``   — the paged cache arrays + the host-side block
                   allocator (explicit free list; page 0 reserved null)
* ``model``      — pure jitted prefill / decode-step functions over
                   the GPTModel param tree (weights shared with
                   training — no conversion step)
* ``quant``      — int8 per-channel weight quantization for the
                   decode matmuls (knob-gated, default OFF)
* ``scheduler``  — stdlib-only continuous batching: admit/evict
                   between decode steps against a synthetic trace
                   (seeded Poisson/diurnal arrival processes; policy
                   knob ``APEX_SERVE_SCHED`` — ``fifo`` | aged
                   ``priority``)
* ``sampling``   — batched temperature/top-k/top-p with per-request
                   threefry lanes as array-value ops inside the one
                   decode program (``APEX_SERVE_SAMPLING``; ISSUE 13)
* ``speculative``— stdlib-only self-drafting n-gram speculation:
                   drafts verified through the SAME packed prefill
                   program, rollback as index arithmetic
                   (``APEX_SPEC_DECODE``)
* ``prefix_cache``— stdlib-only content-hashed refcounted
                   copy-on-write page sharing over the allocator
                   (``APEX_SERVE_PREFIX_CACHE``)
* ``lifecycle``  — stdlib-only request-lifecycle event log, scheduler
                   gauges, and the validated ``slo`` ledger block
                   (gated on ``APEX_SERVE_EVENTS`` /
                   ``lifecycle.enable()`` — disabled mode is
                   behavior-identical; ISSUE 11)
* ``resilience`` — stdlib-only serving failure story (ISSUE 15):
                   admission control's structured ``Rejected``,
                   deadline shedding, KV-pressure preemption
                   plumbing, and the per-round dispatch watchdog
                   (``APEX_SERVE_ADMIT`` / ``APEX_SERVE_SHED`` /
                   ``APEX_SERVE_PREEMPT`` / ``APEX_SERVE_RECOVER``,
                   all default OFF)
* ``engine``     — the glue: one ServingEngine owning cache, params,
                   compiled steps and the scheduler loop
* ``router``     — stdlib-only fleet layer (ISSUE 19): N real engines
                   under one routing policy (``APEX_ROUTE_POLICY`` —
                   ``round_robin`` | ``least_loaded`` |
                   ``prefix_affinity``), per-replica health + circuit
                   breaker, failover with requeue-and-replay through
                   survivors, composed fleet/replica admission, and
                   the validated ``router`` ledger block
"""

from apex_tpu.serving import lifecycle  # noqa: F401
from apex_tpu.serving import resilience  # noqa: F401
from apex_tpu.serving import speculative  # noqa: F401
from apex_tpu.serving.resilience import (  # noqa: F401
    DispatchFailure,
    Rejected,
)
from apex_tpu.serving.kv_cache import (  # noqa: F401
    PageAllocator,
    init_cache,
)
from apex_tpu.serving.prefix_cache import PrefixCache  # noqa: F401
from apex_tpu.serving.sampling import SamplingParams  # noqa: F401
from apex_tpu.serving.scheduler import (  # noqa: F401
    ContinuousBatchingScheduler,
    Request,
    offered_load,
    resolve_policy,
    synthetic_trace,
)
from apex_tpu.serving.engine import ServingEngine, detokenize  # noqa: F401
from apex_tpu.serving.router import (  # noqa: F401
    AutoscalePolicy,
    Router,
    router_block,
)
