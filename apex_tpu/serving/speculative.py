"""Self-drafting speculative decode: host-side draft + accept logic.

Stdlib-only (like ``scheduler``/``lifecycle``): this module owns the
n-gram draft proposal and the accept/rollback ARITHMETIC; the verify
dispatch itself is the engine's existing packed-varlen prefill program
(``model.prefill`` — the verify batch IS the packed segment-id shape,
so no third compiled program exists; tests assert the jit cache sizes
stay at one prefill + one decode with speculation on).

The draft has NO second model (ROADMAP 2b): the most recent earlier
occurrence of the sequence's trailing n-gram proposes the tokens that
followed it — free to compute, surprisingly effective on the looping
continuations greedy decode produces, and zero new device state. A
verify round feeds the slot's FULL sequence (prompt + generated +
draft) as one segment of the packed prefill bucket: segment-masked
causal attention over the segment is exactly full-context attention,
the already-cached context positions route their K/V writes to the
null spare row (the cache keeps its decode-written values bit-exact),
and only the pending-token + draft positions write real pages.
Acceptance then takes the longest draft prefix matching the verify
logits' greedy chain plus ONE bonus token; ROLLBACK is pure index
arithmetic — rejected positions' K/V stay in the pages as garbage
beyond the new length, never read (decode attention masks by context
length) and overwritten when the sequence advances (the same
null-page-0 discipline the allocator already guarantees).

Knob (the CLAUDE.md asymmetry): per-call ``spec_decode=K`` at engine
build RAISES when un-honorable (K < 1, or K+1 deeper than the prefill
bucket); the ``APEX_SPEC_DECODE`` env is a preference — 0/unset is
off, garbage warns once and is ignored. Default OFF per the
measured-dispatch rule (the verify-vs-decode device A/B is queued in
PERF.md §2 behind ``APEX_SERVE_BENCH=1``); correctness — speculative
output ≡ non-speculative greedy token-for-token — is pinned on CPU by
tests/test_serving_generation.py.
"""

NGRAM = 2  # trailing n-gram the draft matches (the self-draft context)


def resolve_k(per_call=None):
    """The effective draft length K: per-call (raises on un-honorable
    — an explicit request is a demand) > ``APEX_SPEC_DECODE`` env
    preference (``tiles.env_nonneg_int``: 0/unset = off — 0 is the
    legal explicit off-pin profile_serving stamps; garbage warns once
    and is ignored) > built-in OFF (0)."""
    if per_call is not None:
        if isinstance(per_call, bool) or not isinstance(per_call, int) \
                or per_call < 1:
            raise ValueError(
                f"spec_decode= wants a draft length >= 1 or None, "
                f"got {per_call!r}")
        return per_call
    from apex_tpu.dispatch import tiles as _tiles

    return _tiles.env_nonneg_int("APEX_SPEC_DECODE") or 0


def propose(history, k, ngram=NGRAM):
    """Up to ``k`` draft tokens for a sequence ending in ``history``
    (prompt + generated so far, oldest first): the tokens that
    followed an earlier occurrence of the trailing ``ngram`` —
    preferring the most recent occurrence with a FULL ``k``-token
    continuation (an occurrence at the very end of history can only
    contribute a truncated draft; on a period-1 loop the one-back
    match would cap every draft at a single token), falling back to
    the longest continuation found. An empty list when no earlier
    occurrence exists (the engine then runs a plain decode round — a
    draft is an optimization, never a requirement)."""
    n = len(history)
    if k < 1 or n < ngram + 1:
        return []
    tail = list(history[-ngram:])
    best = []
    for i in range(n - ngram - 1, -1, -1):
        if list(history[i:i + ngram]) == tail:
            cont = list(history[i + ngram:i + ngram + k])
            if len(cont) == k:
                return cont
            if len(cont) > len(best):
                best = cont
    return best


def accept(draft, greedy):
    """Accept/rollback arithmetic for one verified slot: ``draft`` is
    the proposed tokens d_1..d_k; ``greedy`` is the verify program's
    argmax chain g_0..g_k where ``g_j`` is the model's token AFTER
    consuming position j of the verify window (g_0 follows the pending
    token). Returns the tokens the round PRODUCES: the longest draft
    prefix matching the greedy chain plus the one bonus token — between
    1 (all rejected: the bonus is g_0, exactly the plain decode round's
    token) and ``len(draft) + 1`` tokens, always the same stream plain
    greedy decode would emit one token at a time."""
    out = []
    a = 0
    while a < len(draft) and draft[a] == greedy[a]:
        out.append(draft[a])
        a += 1
    out.append(greedy[a])  # the bonus token (g_a exists: len == k+1)
    return out


class SpecStats:
    """Per-engine speculation counters -> the ledger's
    ``spec_acceptance_rate`` / ``draft_len`` fields (None-when-off at
    the profile_serving seam)."""

    def __init__(self):
        self.rounds = 0          # verified slots (one per verify lane)
        self.drafted = 0         # draft tokens proposed
        self.accepted = 0        # draft tokens accepted
        self.bonus = 0           # bonus tokens (1 per verified slot)

    def record(self, drafted, accepted):
        self.rounds += 1
        self.drafted += int(drafted)
        self.accepted += int(accepted)
        self.bonus += 1

    def acceptance_rate(self):
        """Accepted fraction of drafted tokens (None before any
        draft)."""
        if not self.drafted:
            return None
        return self.accepted / self.drafted

    def mean_draft_len(self):
        if not self.rounds:
            return None
        return self.drafted / self.rounds
