"""Tensor-parallel serving shardings (ISSUE 18): the capability rung.

Reference surface: apex/transformer/tensor_parallel/layers.py:256
(ColumnParallelLinear) and apex/transformer/tensor_parallel/layers.py:452
(RowParallelLinear) — Megatron's column/row split, re-expressed as
GSPMD shardings instead of hand-written collectives. The serving
engine's two jitted programs are UNTOUCHED: the ONLY change at
``ServingEngine(tp=)`` > 1 is that the params and the paged KV cache
are ``device_put`` with :class:`~jax.sharding.NamedSharding` over a
``tp`` mesh, and GSPMD partitions the SAME prefill/decode jaxprs from
those committed input shardings. Host-side scheduling, page
accounting, sampling lanes and the one-compile contract
(``decode_cache_size()==1`` / ``prefill_cache_size()<=1``) are
mesh-invariant by construction — the mesh is a build-time constant
and every per-round input keeps its shape and sharding.

The split (Megatron pairing, whole heads per shard — demands a
``num_attention_heads % tp == 0`` config):

* ``query_key_value`` ``[3h, h]`` — COLUMN-parallel on the fused
  output dim. The per-head ``[q|k|v]`` interleaving
  (:func:`model._split_qkv` reshapes to ``[rows, np, 3*hd]``) makes a
  contiguous block of ``3h/tp`` rows exactly ``n_heads/tp`` whole
  heads, so attention stays head-local. Bias follows the output dim.
* ``self_attention.dense`` ``[h, h]`` — ROW-parallel on the input
  dim (the per-head context it consumes); the psum GSPMD inserts is
  Megatron's RowParallel all-reduce. Bias replicated (added once,
  after the reduction).
* ``mlp.dense_h_to_4h`` ``[4h, h]`` — column-parallel (+ bias);
  ``mlp.dense_4h_to_h`` ``[h, 4h]`` — row-parallel (bias replicated).
* Embeddings, layernorms, everything else — replicated. The logits
  matmul against the replicated word table is vocab-unsharded (the
  v5e HBM pressure is the 48-layer trunk, not the 50304-row table).
* KV cache ``[layers, heads, pages, page_size, head_dim]`` — sharded
  on its LEADING HEAD axis (axis 1): the paged layout leads with
  heads for exactly this, so each chip holds its own heads' pages
  and the decode gather never crosses chips.

Knob home (the CLAUDE.md asymmetry): per-call ``ServingEngine(tp=)``
is a DEMAND — un-honorable values (non-int, tp < 1, tp > visible
devices, ``n_heads % tp != 0``) raise here; the ``APEX_SERVE_TP`` env
preference rides the one-home :func:`tiles.env_int` parser and falls
back to tp=1 per shape. Default tp=1 (single-chip engine,
byte-identical to the pre-TP build) per the measured-dispatch rule —
the ``serving_tp`` A/B is queued in PERF.md §2; the capability
exception (the committed ~22B :func:`zero3.capability_config` whose
costs block PROVES peak_hbm > v5e HBM) is argued in PERF.md per the
CLAUDE.md capability-default rule.
"""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.dispatch import tiles as _tiles
from apex_tpu.transformer.parallel_state import TENSOR_AXIS


def resolve_serve_tp(tp=None, *, n_heads, n_devices=None):
    """Resolve the serving tensor-parallel width.

    Per-call ``tp=`` is a demand: raises on non-positive-int values,
    on ``tp`` exceeding the visible device count, and on a head count
    the whole-heads split cannot honor. ``None`` defers to the
    ``APEX_SERVE_TP`` env preference (one-home
    :func:`tiles.env_int`), which falls back to 1 when un-honorable
    — preference semantics, never a raise."""
    if n_devices is None:
        n_devices = len(jax.devices())
    if tp is not None:
        if isinstance(tp, bool) or not isinstance(tp, int) or tp < 1:
            raise ValueError(
                f"tp= wants a positive int, got {tp!r}")
        if tp > n_devices:
            raise ValueError(
                f"tp={tp} cannot be honored: only {n_devices} "
                f"device(s) visible")
        if n_heads % tp:
            raise ValueError(
                f"tp={tp} cannot be honored: num_attention_heads="
                f"{n_heads} does not split into whole heads per chip")
        return tp
    v = _tiles.env_int("APEX_SERVE_TP")
    if v is None or v == 1:
        return 1
    if v > n_devices or n_heads % v:
        return 1  # env preference: falls back per shape
    return v


def mesh_for(tp):
    """One-axis ``(TENSOR_AXIS,)`` mesh over the first ``tp`` visible
    devices — the build-time constant every sharding below names."""
    return Mesh(np.asarray(jax.devices()[:tp]), (TENSOR_AXIS,))


def _param_spec(path, leaf):
    """PartitionSpec for one serving-param leaf, by tree path (the
    module-docstring split table)."""
    keys = {getattr(k, "key", None) for k in path}
    col = ("query_key_value" in keys or "dense_h_to_4h" in keys)
    row = (("dense" in keys and "self_attention" in keys)
           or "dense_4h_to_h" in keys)
    if col:
        return P(TENSOR_AXIS, None) if leaf.ndim == 2 \
            else P(TENSOR_AXIS)
    if row and leaf.ndim == 2:
        return P(None, TENSOR_AXIS)
    return P()  # row-parallel bias, embeddings, norms: replicated


def param_shardings(params, mesh):
    """NamedSharding tree matching ``params`` (the serving GPT tree of
    :func:`model.init_gpt_params`) for ``device_put``."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, _param_spec(path, leaf)),
        params)


def cache_shardings(cache, mesh):
    """NamedSharding tree for the paged KV cache: every array sharded
    on its leading head axis, ``P(None, TENSOR_AXIS)``."""
    s = NamedSharding(mesh, P(None, TENSOR_AXIS))
    return jax.tree.map(lambda _: s, cache)


def qparams_shardings(qparams, mesh):
    """NamedSharding tree for the int8 decode records
    (:func:`model.quantize_decode_params`) — the tp x weight_quant
    composition (ISSUE 20 satellite). Each record's ``wq`` is
    ``[out, in]`` and its ``scale`` is per-OUT-channel, so the specs
    follow the float split table exactly:

    * column-parallel records (``qkv``, ``h4``): ``wq``
      ``P(TENSOR_AXIS, None)`` — the out dim is the sharded fused
      output (whole heads per shard for qkv, 4h/tp rows for h4;
      both divide because ``n_heads % tp == 0`` forces ``h % tp ==
      0``) — and ``scale`` ``P(TENSOR_AXIS)`` rides the same dim.
    * row-parallel records (``dense``, ``4h``): ``wq``
      ``P(None, TENSOR_AXIS)`` on the in dim; ``scale`` replicated
      ``P()`` (it lands on the UNSHARDED output columns after the
      GSPMD psum, exactly like the row-parallel float bias).
    * ``word_logits``: replicated — the float word table is
      replicated and the logits matmul vocab-unsharded (module
      docstring), so its int8 copy keeps that layout.
    """
    col_wq = NamedSharding(mesh, P(TENSOR_AXIS, None))
    col_sc = NamedSharding(mesh, P(TENSOR_AXIS))
    row_wq = NamedSharding(mesh, P(None, TENSOR_AXIS))
    rep = NamedSharding(mesh, P())
    spec = {"layers": [
        {"qkv": {"wq": col_wq, "scale": col_sc},
         "h4": {"wq": col_wq, "scale": col_sc},
         "dense": {"wq": row_wq, "scale": rep},
         "4h": {"wq": row_wq, "scale": rep}}
        for _ in qparams["layers"]],
        "word_logits": {"wq": rep, "scale": rep}}
    return spec
