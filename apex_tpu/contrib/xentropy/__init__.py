"""apex_tpu.contrib.xentropy (reference: apex/contrib/xentropy)."""

from apex_tpu.contrib.xentropy.softmax_xentropy import (  # noqa: F401
    SoftmaxCrossEntropyLoss,
    softmax_cross_entropy_loss,
)
