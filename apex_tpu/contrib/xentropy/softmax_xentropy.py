"""Fused softmax + cross-entropy with label smoothing.

Capability port of apex/contrib/xentropy/softmax_xentropy.py:6-45 over
``xentropy_cuda`` (770 LoC CUDA). The kernel fuses softmax, CE loss, and
label smoothing in one pass, saving (max, logsumexp) instead of the full
softmax for backward, and writes the gradient in place.

TPU version: one ``jax.custom_vjp``. Forward keeps only (logits, max-free
logsumexp, target) residuals — the same memory saving the CUDA kernel
targets (no [N, V] softmax materialized between fwd and bwd); backward
recomputes ``softmax = exp(logits − lse)`` fused into the grad expression,
which XLA fuses into a single pass.
"""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def softmax_cross_entropy_loss(logits, labels, smoothing=0.0,
                               half_to_float=False):
    """Per-row loss (reference: SoftmaxCrossEntropyLoss.forward :14-32).

    logits [N, V] (fp16/bf16/fp32), labels [N] int; ``half_to_float``
    returns fp32 loss from half inputs (kernel flag).
    """
    loss, _ = _fwd(logits, labels, smoothing, half_to_float)
    return loss


def _fwd(logits, labels, smoothing, half_to_float):
    x = logits.astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    lse = jnp.squeeze(m, -1) + jnp.log(
        jnp.sum(jnp.exp(x - m), axis=-1))
    picked = jnp.take_along_axis(x, labels[:, None], axis=-1)[:, 0]
    nll = lse - picked
    if smoothing > 0:
        # label smoothing: (1-eps)*nll + eps*mean_k(lse - x_k)
        mean_all = lse - jnp.mean(x, axis=-1)
        loss = (1.0 - smoothing) * nll + smoothing * mean_all
    else:
        loss = nll
    if not half_to_float:
        loss = loss.astype(logits.dtype)
    return loss, (logits, lse, labels)


def _bwd(smoothing, half_to_float, res, g):
    logits, lse, labels = res
    x = logits.astype(jnp.float32)
    softmax = jnp.exp(x - lse[:, None])
    v = x.shape[-1]
    one_hot = jax.nn.one_hot(labels, v, dtype=jnp.float32)
    if smoothing > 0:
        target = (1.0 - smoothing) * one_hot + smoothing / v
    else:
        target = one_hot
    grad = (softmax - target) * g.astype(jnp.float32)[:, None]
    return grad.astype(logits.dtype), None


softmax_cross_entropy_loss.defvjp(_fwd, _bwd)


class SoftmaxCrossEntropyLoss:
    """Class surface of the reference autograd Function (reference:
    softmax_xentropy.py:6)."""

    @staticmethod
    def apply(logits, labels, smoothing=0.0, padding_idx=0,
              half_to_float=False):
        """``padding_idx`` rows (label == padding_idx is NOT masked in the
        reference either — the arg exists but the kernel only uses it to
        skip grad of ignored rows when labels==padding_idx in some
        downstream forks; we mirror the upstream no-op)."""
        return softmax_cross_entropy_loss(logits, labels, smoothing,
                                          half_to_float)
