"""cudnn-frontend group batch norm (stats reduced across a device group).

Capability port of apex/contrib/cudnn_gbn/batch_norm.py:9-150 over
``cudnn_gbn_lib`` (682 LoC) + ``peer_memory_cuda``. Same capability as
contrib.groupbn with a cleaner surface: a GroupBatchNorm2d whose training
statistics are averaged over ``group_size`` ranks. On TPU this is the
identical psum-over-subgroups BN; the peer-memory fwd/bwd buffer pools the
reference threads through are replaced by the collective itself.
"""

from apex_tpu.contrib.groupbn.batch_norm import BatchNorm2d_NHWC


def GroupBatchNorm2d(num_features, group_size=1, axis_name=None,
                     momentum=0.9, eps=1e-5, **kwargs):
    """Factory mirroring the reference ctor (cudnn_gbn/batch_norm.py:44:
    num_features, group_size, momentum, eps). Returns the TPU group-BN
    module (flax modules are frozen dataclasses, so the arg adaptation is
    a factory rather than a subclass __init__)."""
    return BatchNorm2d_NHWC(num_features=num_features, bn_group=group_size,
                            axis_name=axis_name, momentum=momentum, eps=eps,
                            **kwargs)
