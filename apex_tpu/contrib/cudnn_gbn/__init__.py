"""apex_tpu.contrib.cudnn_gbn (reference: apex/contrib/cudnn_gbn)."""

from apex_tpu.contrib.cudnn_gbn.batch_norm import GroupBatchNorm2d  # noqa: F401
