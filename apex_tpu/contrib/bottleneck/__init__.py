"""apex_tpu.contrib.bottleneck (reference: apex/contrib/bottleneck)."""

from apex_tpu.contrib.bottleneck.bottleneck import (  # noqa: F401
    Bottleneck,
    FrozenBatchNorm2d,
    SpatialBottleneck,
)
from apex_tpu.contrib.bottleneck.halo_exchangers import (  # noqa: F401
    HaloExchanger,
    HaloExchangerAllGather,
    HaloExchangerNoComm,
    HaloExchangerPeer,
    HaloExchangerSendRecv,
    HaloPadder,
)
