"""Halo exchangers for spatial (H-split) parallelism.

Capability port of apex/contrib/bottleneck/halo_exchangers.py:11-170. The
reference offers four transports for trading one-row halos between
H-adjacent ranks: NoComm (edge zeros), AllGather (whole-tensor gather,
slice), SendRecv (NCCL p2p), Peer (CUDA-IPC push). On TPU every variant is
a ``lax.ppermute`` shift along the spatial mesh axis — the ICI neighbor
exchange IS the send/recv — so the subclasses differ only in fidelity
notes; all are numerically identical to SendRecv. The class family is kept
so reference call sites (and the transport-selection config) port 1:1.

All methods run inside ``shard_map`` over ``axis_name``.
"""

import jax.numpy as jnp
from jax import lax


class HaloExchanger:
    """Base (reference: halo_exchangers.py:11-25). ``ranks`` become the
    mesh axis; ``rank_in_group`` is ``lax.axis_index`` at trace time."""

    def __init__(self, axis_name="spatial", world_size=None):
        self.axis_name = axis_name
        self.world_size = world_size

    def _shift(self, x, direction):
        """direction +1: rank r → r+1 (receives from r-1), -1: reverse.
        Non-wrapping: edge ranks receive zeros (the reference zeroes
        out-of-image halos)."""
        n = self.world_size or lax.axis_size(self.axis_name)
        if direction > 0:
            perm = [(i, i + 1) for i in range(n - 1)]
        else:
            perm = [(i + 1, i) for i in range(n - 1)]
        return lax.ppermute(x, self.axis_name, perm)

    def left_right_halo_exchange(self, left_output_halo, right_output_halo,
                                 left_input_halo=None,
                                 right_input_halo=None):
        """Send my left edge to the left neighbor and right edge to the
        right neighbor; receive their facing edges (reference signature
        :30-37). Returns (left_input_halo, right_input_halo)."""
        # my right_output goes to rank+1's left_input
        left_in = self._shift(right_output_halo, +1)
        right_in = self._shift(left_output_halo, -1)
        return left_in, right_in


class HaloExchangerNoComm(HaloExchanger):
    """Zeros instead of communication (reference :26-36) — for measuring
    comm overhead."""

    def left_right_halo_exchange(self, left_output_halo, right_output_halo,
                                 left_input_halo=None,
                                 right_input_halo=None):
        return (jnp.zeros_like(right_output_halo),
                jnp.zeros_like(left_output_halo))


class HaloExchangerAllGather(HaloExchanger):
    """All-gather transport (reference :37-68): gather every rank's halo
    pair, slice the neighbors'. Same result; more bytes on the wire —
    kept for parity with the reference's transport matrix."""

    def left_right_halo_exchange(self, left_output_halo, right_output_halo,
                                 left_input_halo=None,
                                 right_input_halo=None):
        n = self.world_size or lax.axis_size(self.axis_name)
        idx = lax.axis_index(self.axis_name)
        both = jnp.stack([left_output_halo, right_output_halo])
        allh = lax.all_gather(both, self.axis_name)  # [n, 2, ...]
        left_in = jnp.where(
            idx > 0, allh[jnp.maximum(idx - 1, 0), 1],
            jnp.zeros_like(right_output_halo))
        right_in = jnp.where(
            idx < n - 1, allh[jnp.minimum(idx + 1, n - 1), 0],
            jnp.zeros_like(left_output_halo))
        return left_in, right_in


class HaloExchangerSendRecv(HaloExchanger):
    """NCCL p2p transport (reference :69-89) — the ppermute base IS
    send/recv on TPU."""


class HaloExchangerPeer(HaloExchanger):
    """CUDA-IPC peer-push transport (reference :90-117). On TPU direct
    neighbor ICI transfer is what ppermute lowers to; the peer_pool and
    numSM arguments are accepted no-ops."""

    def __init__(self, axis_name="spatial", world_size=None, peer_pool=None,
                 explicit_nhwc=False, numSM=1):
        super().__init__(axis_name, world_size)
        self.peer_pool = peer_pool
        self.explicit_nhwc = explicit_nhwc
        self.numSM = numSM


class HaloPadder:
    """Pad a spatial shard with neighbor halo rows/cols in one shot
    (reference: halo_exchangers.py:118-165 — allocates the padded
    buffer on side streams and fills the edges from the exchanger).
    Functional here: returns a new array of the padded shape.

    ``y`` is the UNPADDED per-rank shard; the result has ``2*half_halo``
    extra rows (H_split) or cols filled from the neighbors, zeros at the
    outer edges. ``explicit_nhwc`` selects the layout exactly as in the
    reference: True → NHWC (H at dim 1), False → NCHW (H at dim 2) —
    but this codebase is NHWC throughout (see bottleneck.py), so the
    default here is True, a documented divergence from the reference's
    False. ``wait()`` is a no-op — no side streams to synchronize."""

    def __init__(self, halo_ex):
        self.halo_ex = halo_ex

    def __call__(self, y, half_halo, explicit_nhwc=True, H_split=True):
        hh = half_halo
        if explicit_nhwc:
            axis = 1 if H_split else 2    # N H W C
        else:
            axis = 2 if H_split else 3    # N C H W

        def take(arr, start, size):
            idx = [slice(None)] * arr.ndim
            idx[axis] = slice(start, start + size)
            return arr[tuple(idx)]

        n = y.shape[axis]
        top_out = take(y, 0, hh)          # first rows → previous rank
        bot_out = take(y, n - hh, hh)     # last rows → next rank
        left_in, right_in = self.halo_ex.left_right_halo_exchange(
            top_out, bot_out)
        return jnp.concatenate([left_in, y, right_in], axis=axis)

    def wait(self):
        pass
