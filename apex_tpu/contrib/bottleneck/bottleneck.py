"""ResNet bottleneck block + spatially-parallel (H-split) variant.

Capability port of apex/contrib/bottleneck/bottleneck.py:30-780 over
``fast_bottleneck`` (4,073 LoC cudnn-frontend fusion) and ``nccl_p2p``.

* ``Bottleneck``: conv1x1-BN-ReLU → conv3x3-BN-ReLU → conv1x1-BN →
  (+residual, optionally downsampled) → ReLU, NHWC. The cudnn fusion graph
  is XLA's standard conv+epilogue fusion on TPU.
* ``FrozenBatchNorm2d``: BN with fixed affine stats folded to scale/bias
  (the reference jit-scripts this; XLA folds it into the conv).
* ``SpatialBottleneck``: the SAME block with activations H-split across a
  mesh axis. The 3x3 conv needs one halo row from each H-neighbor —
  exchanged with a HaloExchanger (ppermute over ICI), concatenated, then
  cropped after the conv. This is the reference's spatial parallelism
  (bottleneck.py:265-780) and the seed pattern for ring attention.

Layout NHWC throughout (TPU-native; the reference's fast path is also
NHWC-only).
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax import lax

from apex_tpu.contrib.bottleneck.halo_exchangers import (
    HaloExchanger,
    HaloExchangerSendRecv,
)


class FrozenBatchNorm2d(nn.Module):
    """BatchNorm2d where affine params + running stats are constants
    (reference: bottleneck.py:30-72; get_scale_bias folding :44-53).

    The four tensors live in the non-trainable "batch_stats" collection —
    the flax analog of the reference's requires_grad=False buffers — so
    optimizers over the "params" collection never touch them and no
    gradients flow into them."""

    n: int

    @nn.compact
    def __call__(self, x):
        weight = self.variable("batch_stats", "weight",
                               lambda: jnp.ones((self.n,))).value
        bias = self.variable("batch_stats", "bias",
                             lambda: jnp.zeros((self.n,))).value
        running_mean = self.variable("batch_stats", "running_mean",
                                     lambda: jnp.zeros((self.n,))).value
        running_var = self.variable("batch_stats", "running_var",
                                    lambda: jnp.ones((self.n,))).value
        scale = weight * lax.rsqrt(running_var + 1e-5)
        b = bias - running_mean * scale
        return x * scale.astype(x.dtype) + b.astype(x.dtype)

    def get_scale_bias(self, variables):
        p = variables["batch_stats"]
        scale = p["weight"] * lax.rsqrt(p["running_var"] + 1e-5)
        bias = p["bias"] - p["running_mean"] * scale
        return scale, bias


def _conv_nhwc(x, w, stride, padding):
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32).astype(x.dtype)


class Bottleneck(nn.Module):
    """Reference: bottleneck.py:134-263 (ctor args :142-150). Frozen-BN
    variant of the ResNet bottleneck used by detection nets; the BN is
    folded to scale/bias (use_cudnn path) and everything fuses."""

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    groups: int = 1
    dilation: int = 1
    norm_func: Any = FrozenBatchNorm2d
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        return self._forward(x, None)

    def _forward(self, x, _conv3x3):
        # shared body; called from exactly one @nn.compact method
        assert self.groups == 1, "only groups=1 is supported (as reference)"
        c_in, c_b, c_out = (self.in_channels, self.bottleneck_channels,
                            self.out_channels)
        init = nn.initializers.variance_scaling(2.0, "fan_out",
                                                "truncated_normal")
        w1 = self.param("conv1", init, (1, 1, c_in, c_b), self.param_dtype)
        w2 = self.param("conv2", init, (3, 3, c_b, c_b), self.param_dtype)
        w3 = self.param("conv3", init, (1, 1, c_b, c_out), self.param_dtype)

        bn1 = self.norm_func(c_b, name="bn1")
        bn2 = self.norm_func(c_b, name="bn2")
        bn3 = self.norm_func(c_out, name="bn3")

        # stride placement: torchvision-style stride on the 3x3
        # (reference stride_1x1 option covers the legacy placement)
        out = nn.relu(bn1(_conv_nhwc(x, w1, 1, ((0, 0), (0, 0)))))
        if _conv3x3 is None:
            d = self.dilation
            out = nn.relu(bn2(_conv_nhwc(
                out, w2, self.stride, ((d, d), (d, d)))))
        else:
            out = nn.relu(bn2(_conv3x3(out, w2)))
        out = bn3(_conv_nhwc(out, w3, 1, ((0, 0), (0, 0))))

        if self.stride != 1 or c_in != c_out:
            wd = self.param("downsample", init, (1, 1, c_in, c_out),
                            self.param_dtype)
            bnd = self.norm_func(c_out, name="bn_downsample")
            identity = bnd(_conv_nhwc(x, wd, self.stride, ((0, 0), (0, 0))))
        else:
            identity = x
        return nn.relu(out + identity)


class SpatialBottleneck(Bottleneck):
    """H-split spatially-parallel bottleneck (reference:
    bottleneck.py:265-780, SpatialBottleneckFunction).

    Input x is this rank's H-shard [N, H/n, W, C] inside shard_map over
    ``spatial_axis``. The 3x3 conv exchanges one halo row with each
    neighbor via ``halo_ex`` (default: ppermute send/recv); edge ranks get
    zero halos = the zero padding the unsplit conv would see.
    """

    spatial_axis: str = "spatial"
    spatial_group_size: Optional[int] = None
    halo_ex: Optional[HaloExchanger] = None

    @nn.compact
    def __call__(self, x):
        assert self.stride == 1, (
            "H-split with stride≠1 needs cross-shard output realignment "
            "(reference restricts spatial segments to stride-1 3x3s too)")
        assert self.dilation == 1, (
            "H-split halo width is hardcoded for dilation=1; dilation>1 "
            "needs a dilation-row halo")
        halo_ex = self.halo_ex or HaloExchangerSendRecv(
            self.spatial_axis, self.spatial_group_size)

        def conv3x3_with_halo(h, w2):
            top_out = h[:, :1]       # my first row → up neighbor
            bot_out = h[:, -1:]      # my last row → down neighbor
            top_in, bot_in = halo_ex.left_right_halo_exchange(
                top_out, bot_out)
            h = jnp.concatenate([top_in, h, bot_in], axis=1)
            # halo rows replace one row of zero padding in H
            return _conv_nhwc(h, w2, 1, ((0, 0), (1, 1)))

        return self._forward(x, conv3x3_with_halo)
