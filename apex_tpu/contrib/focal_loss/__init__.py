"""apex_tpu.contrib.focal_loss (reference: apex/contrib/focal_loss)."""

from apex_tpu.contrib.focal_loss.focal_loss import (  # noqa: F401
    FocalLoss,
    focal_loss,
)
