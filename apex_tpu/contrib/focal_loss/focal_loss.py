"""Fused focal loss for detection (RetinaNet/EfficientDet-style).

Capability port of apex/contrib/focal_loss/focal_loss.py:6-61 over
``focal_loss_cuda`` (337 LoC). The CUDA kernel fuses sigmoid, the focal
modulation, label smoothing, normalization by num_positives, and stashes
the partial gradient; here the whole expression is one XLA fusion and the
gradient is recomputed in backward (cheaper than stashing on TPU — it
re-fuses with the cotangent multiply).

Semantics (matching the kernel): one-vs-all sigmoid focal loss over
``cls_output`` [..., num_classes_padded]; ``cls_targets_at_level`` holds
class indices with -2 = ignore (zero loss), -1 = pure negative (background:
all-classes-negative); classes ≥ num_real_classes are padding and excluded;
the summed loss is normalized by ``num_positives_sum``.
"""

import jax
import jax.numpy as jnp


def _focal_loss(cls_output, cls_targets, num_positives_sum,
                num_real_classes, alpha, gamma, label_smoothing):
    # alpha/gamma/label_smoothing are Python floats (hyperparams, static
    # under the caller's jit — same contract as the CUDA kernel's scalars)
    x = cls_output.astype(jnp.float32)
    num_classes = x.shape[-1]
    t = cls_targets

    # one-hot positives; -1 (negative) and -2 (ignore) produce all-zeros
    y = jax.nn.one_hot(t, num_classes, dtype=jnp.float32)
    if label_smoothing > 0:
        y = y * (1.0 - label_smoothing) + 0.5 * label_smoothing

    p = jax.nn.sigmoid(x)
    # focal BCE per element: FL = -alpha_t (1-p_t)^gamma log(p_t)
    p_t = p * y + (1.0 - p) * (1.0 - y)
    alpha_t = alpha * y + (1.0 - alpha) * (1.0 - y)
    # numerically-stable log(p_t) via logsigmoid
    log_p_t = (jax.nn.log_sigmoid(x) * y
               + jax.nn.log_sigmoid(-x) * (1.0 - y))
    per_elem = -alpha_t * jnp.power(1.0 - p_t, gamma) * log_p_t

    # mask: ignore anchors (t == -2) contribute nothing; padded classes off
    anchor_mask = (t != -2).astype(jnp.float32)[..., None]
    class_mask = (jnp.arange(num_classes) < num_real_classes).astype(
        jnp.float32)
    per_elem = per_elem * anchor_mask * class_mask

    return jnp.sum(per_elem) / num_positives_sum.astype(jnp.float32)


class FocalLoss:
    """Class surface of the reference autograd Function (focal_loss.py:6)."""

    @staticmethod
    def apply(cls_output, cls_targets_at_level, num_positives_sum,
              num_real_classes, alpha, gamma, label_smoothing=0.0):
        return _focal_loss(cls_output, cls_targets_at_level,
                           num_positives_sum, num_real_classes, alpha,
                           gamma, label_smoothing)


def focal_loss(cls_output, cls_targets_at_level, num_positive_sum,
               num_real_classes, alpha, gamma, label_smoothing=0.0):
    """Fused focal loss function (reference: focal_loss.py:42-61)."""
    return FocalLoss.apply(cls_output, cls_targets_at_level,
                           num_positive_sum, num_real_classes, alpha, gamma,
                           label_smoothing)
