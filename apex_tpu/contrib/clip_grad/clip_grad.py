"""Fused global-norm gradient clipping.

Capability port of apex/contrib/clip_grad/clip_grad.py:15-76 — a drop-in
``clip_grad_norm_`` built on ``multi_tensor_l2norm`` + ``multi_tensor_scale``.
On TPU the two fused kernels are one XLA reduction over the flattened grads
plus one fused scale; being functional, it returns (clipped_grads,
total_norm) instead of mutating.
"""

import jax
import jax.numpy as jnp


def clip_grad_norm_(grads, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """Returns (clipped grads pytree, total_norm). Semantics of
    torch.nn.utils.clip_grad_norm_ as reproduced by the reference
    (clip_grad.py:27-76): no-op scale when total_norm <= max_norm;
    optional error on non-finite norm."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return grads, jnp.asarray(0.0, jnp.float32)
    norm_type = float(norm_type)
    if norm_type == float("inf"):
        total_norm = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(g)).astype(jnp.float32) for g in leaves]))
    else:
        # the multi_tensor_l2norm path (one fused reduction)
        total_norm = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g).astype(jnp.float32) ** norm_type)
             for g in leaves])) ** (1.0 / norm_type)
    if error_if_nonfinite:
        # host-level check only meaningful outside jit (the reference's
        # eager RuntimeError, clip_grad.py:49-58)
        import numpy as np

        tn = np.asarray(total_norm)
        if tn.shape == () and not np.isfinite(tn):
            raise RuntimeError(
                f"The total norm of order {norm_type} for gradients is "
                "non-finite, so it cannot be clipped.")
    # multi_tensor_scale analog; clamp coefficient at 1 (clip only)
    clip_coef = jnp.minimum(max_norm / (total_norm + 1e-6), 1.0)
    clipped = jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * clip_coef).astype(g.dtype), grads)
    return clipped, total_norm
