"""RNN-T transducer joint and loss.

Capability port of apex/contrib/transducer/transducer.py:5-200 over
``transducer_joint_cuda`` + ``transducer_loss_cuda`` (1,952 LoC).

* joint: out[b,t,u] = f[b,t] + g[b,u] with don't-care regions (t ≥ f_len,
  u ≥ g_len) masked, optional fused ReLU/dropout, optional packed output
  (the CUDA tiling/opt knobs are accepted no-ops — XLA fuses the
  broadcast-add chain).
* loss: the alpha recurrence α[t,u] = logaddexp(α[t-1,u] + blank(t-1,u),
  α[t,u-1] + y(t,u-1)) is T sequential steps of a log-semiring linear
  recurrence in u, computed with ``lax.associative_scan`` (log-depth per
  row — TPU-friendly, unlike the per-cell wavefront the CUDA kernel
  threads). Backward comes from autodiff through the scan; like the
  reference's ``fuse_softmax_backward`` the softmax+loss backward is one
  fused XLA pass.
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.utils import train_dropout
from jax import lax

_NEG = -1e30


def transducer_joint(f, g, f_len, g_len, pack_output=False, relu=False,
                     dropout=False, batch_offset=None, packed_batch=0,
                     dropout_prob=0.0, rng=None):
    """f [B,T,H] + g [B,U,H] → [B,T,U,H] (reference: TransducerJointFunc
    :158-186). Don't-care cells are zeroed (the kernel leaves them
    uninitialized; zero is the defined analog). With ``pack_output``,
    returns [packed_batch, H] with rows laid out like
    batch_offset = cumsum(f_len * g_len)."""
    B, T, H = f.shape
    U = g.shape[1]
    out = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        out = jnp.maximum(out, 0)
    if dropout and dropout_prob > 0.0:
        if rng is None:
            raise ValueError("dropout requires an rng key")
        out = train_dropout(rng, out, dropout_prob)
    mask = ((jnp.arange(T)[None, :, None] < f_len[:, None, None])
            & (jnp.arange(U)[None, None, :] < g_len[:, None, None]))
    out = jnp.where(mask[..., None], out, 0.0)
    if not pack_output:
        return out
    if batch_offset is None or packed_batch == 0:
        raise Exception("Please specify batch_offset and packed_batch when "
                        "packing is enabled")
    # packed row index of (b, t, u): start[b] + t * g_len[b] + u
    start = batch_offset - f_len * g_len  # cumsum is inclusive
    idx = (start[:, None, None] + jnp.arange(T)[None, :, None]
           * g_len[:, None, None] + jnp.arange(U)[None, None, :])
    idx = jnp.where(mask, idx, packed_batch)  # OOB rows dropped
    packed = jnp.zeros((packed_batch + 1, H), out.dtype)
    packed = packed.at[idx.reshape(-1)].add(
        out.reshape(-1, H), mode="drop")
    return packed[:packed_batch]


def _log_linrec(b, c):
    """x[u] = logaddexp(b[u], x[u-1] + c[u]) with x[-1] = -inf, via
    associative scan over the log semiring."""
    def op(l, r):
        cl, bl = l
        cr, br = r
        return cl + cr, jnp.logaddexp(br, bl + cr)

    _, x = lax.associative_scan(op, (c, b), axis=-1)
    return x


def transducer_loss(x, label, f_len, y_len, blank_idx=0, packed_input=False,
                    batch_offset=None, max_f_len=None, debug_list=None):
    """Per-batch RNN-T negative log likelihood (reference: TransducerLoss
    :68-156). x: [B, T, U, V] joint logits (U = max label len + 1);
    label: [B, U-1]; f_len: time lengths; y_len: label lengths."""
    assert not packed_input, (
        "packed_input: unpack with transducer joint's layout before the "
        "loss (TPU build computes on the dense [B,T,U,V] form)")
    B, T, U, V = x.shape
    lp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
    lb = lp[..., blank_idx]  # [B, T, U]
    # ly[b, t, u] = lp[b, t, u, label[b, u]] for u < U-1
    lab = jnp.minimum(label, V - 1)
    ly = jnp.take_along_axis(
        lp[:, :, :U - 1, :], lab[:, None, :, None], axis=-1)[..., 0]
    # pad u-transitions so emitting at u = U-1 is impossible
    ly = jnp.concatenate(
        [ly, jnp.full((B, T, 1), _NEG, jnp.float32)], axis=2)
    # forbid emitting beyond y_len
    u_ids = jnp.arange(U)[None, None, :]
    ly = jnp.where(u_ids < y_len[:, None, None], ly, _NEG)

    # α row at t=0: prefix sums of ly[0] (only label emissions move u)
    alpha0 = jnp.concatenate(
        [jnp.zeros((B, 1), jnp.float32),
         jnp.cumsum(ly[:, 0, :-1], axis=-1)], axis=-1)

    def step(alpha_prev, inputs):
        lb_prev, ly_t = inputs  # [B, U] each
        a = alpha_prev + lb_prev  # arrive via blank from t-1
        c = jnp.concatenate(
            [jnp.full((B, 1), _NEG, jnp.float32), ly_t[:, :-1]], axis=-1)
        alpha_t = _log_linrec(a, c)
        return alpha_t, alpha_t

    _, alphas = lax.scan(
        step, alpha0,
        (lb.transpose(1, 0, 2)[:-1], ly.transpose(1, 0, 2)[1:]))
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, U]

    # loss = -(α[f_len-1, y_len] + blank(f_len-1, y_len))
    t_last = jnp.maximum(f_len - 1, 0)
    a_last = alphas[t_last, jnp.arange(B), y_len]
    lb_last = lb[jnp.arange(B), t_last, y_len]
    return -(a_last + lb_last)


class TransducerJoint:
    """Module surface (reference: transducer.py:5-66)."""

    def __init__(self, pack_output=False, relu=False, dropout=False, opt=1,
                 fwd_tile_size=4, dropout_prob=0, probe_mask=False):
        self.pack_output = pack_output
        self.relu = relu
        self.dropout = dropout
        self.dropout_prob = dropout_prob
        self.opt = opt  # tiling knob — no-op under XLA
        self.fwd_tile_size = fwd_tile_size
        self.mask_probe = [] if (relu or dropout) and probe_mask else None
        self.training = True

    def __call__(self, f, g, f_len, g_len, batch_offset=None,
                 packed_batch=0, rng=None):
        dropout = self.dropout and self.training
        return transducer_joint(f, g, f_len, g_len, self.pack_output,
                                self.relu, dropout, batch_offset,
                                packed_batch, self.dropout_prob, rng)

    forward = __call__


class TransducerLoss:
    """Module surface (reference: transducer.py:68-126)."""

    def __init__(self, fuse_softmax_backward=True, opt=1,
                 packed_input=False):
        self.fuse_softmax_backward = fuse_softmax_backward  # XLA fuses
        self.opt = opt
        self.packed_input = packed_input

    def __call__(self, x, label, f_len, y_len, blank_idx=0,
                 batch_offset=None, max_f_len=None, debug_list=None):
        return transducer_loss(x, label, f_len, y_len, blank_idx,
                               self.packed_input, batch_offset, max_f_len,
                               debug_list)

    forward = __call__
