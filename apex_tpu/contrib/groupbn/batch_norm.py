"""NHWC group batch norm with bn+add+relu fusion (MLPerf ResNet).

Capability port of apex/contrib/groupbn/batch_norm.py:7-160 over ``bnp``
(5,094 LoC CUDA + CUDA-IPC peer memory). The reference's machinery —
peer-memory buffers, magic tokens, occupancy knobs — exists to all-reduce
BN statistics between a small group of GPUs faster than NCCL; on TPU the
statistics reduction is a ``lax.psum`` over a mesh-axis subgroup and every
tuning knob disappears (accepted for API parity, documented no-ops).

The bn_group semantics: stats are averaged over groups of ``bn_group``
adjacent data-parallel ranks (reference: group construction in
``BatchNorm2d_NHWC.__init__``). Here the constructor takes the mesh
``axis_name`` (default "dp"); ``bn_group>1`` inside shard_map reduces over
``axis_index_groups`` partitioning that axis into blocks of bn_group.
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax import lax


def _group_indices(world, bn_group):
    assert world % bn_group == 0
    return [list(range(i, i + bn_group))
            for i in range(0, world, bn_group)]


class BatchNorm2d_NHWC(nn.Module):
    """NHWC BN with optional fused residual-add + ReLU (reference module
    batch_norm.py:7; fuse_relu/bn_addrelu paths :53-160).

    __call__(x, z=None): ``z`` is the residual to add before ReLU (the
    bn_addrelu fusion). Training mode reduces Welford moments over the
    bn_group; eval uses running stats.
    """

    num_features: int
    fuse_relu: bool = False
    bn_group: int = 1
    axis_name: Optional[str] = None  # e.g. "dp" inside shard_map
    momentum: float = 0.9
    eps: float = 1e-5
    param_dtype: Any = jnp.float32
    # cuda-side tuning knobs, accepted for parity (no-ops on TPU):
    max_cta_per_sm: int = 2
    cta_launch_margin: int = 12
    multi_stream: bool = False

    @nn.compact
    def __call__(self, x, z=None, use_running_average=False):
        c = self.num_features
        scale = self.param("weight", nn.initializers.ones, (c,),
                           self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (c,),
                          self.param_dtype)
        ra_mean = self.variable("batch_stats", "running_mean",
                                lambda: jnp.zeros((c,), jnp.float32))
        ra_var = self.variable("batch_stats", "running_var",
                               lambda: jnp.ones((c,), jnp.float32))

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32)
            # single-pass moments over N,H,W (the Welford kernel's output)
            mean = jnp.mean(xf, axis=(0, 1, 2))
            mean_sq = jnp.mean(jnp.square(xf), axis=(0, 1, 2))
            if self.axis_name is not None and self.bn_group > 1:
                world = lax.axis_size(self.axis_name)
                groups = (None if self.bn_group >= world
                          else _group_indices(world, self.bn_group))
                mean = lax.pmean(mean, self.axis_name,
                                 axis_index_groups=groups)
                mean_sq = lax.pmean(mean_sq, self.axis_name,
                                    axis_index_groups=groups)
            var = mean_sq - jnp.square(mean)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var

        inv = lax.rsqrt(var + self.eps)
        y = (x.astype(jnp.float32) - mean) * inv * scale.astype(jnp.float32) \
            + bias.astype(jnp.float32)
        y = y.astype(x.dtype)
        if z is not None:
            y = y + z.astype(y.dtype)  # bn_addrelu fusion input
        if self.fuse_relu or z is not None:
            y = jnp.maximum(y, 0)
        return y
