"""apex_tpu.contrib — production-hardened extras (L6).

Capability port of apex/contrib (the MLPerf toolbox, SURVEY.md §2.7). Each
feature is an opt-in submodule, imported lazily like the reference's
per-extension feature gates (setup.py flags become plain imports — there is
nothing to compile; the "native" side is XLA/Pallas).
"""


def __getattr__(name):
    import importlib

    if name in ("xentropy", "clip_grad", "focal_loss", "index_mul_2d",
                "conv_bias_relu", "layer_norm", "groupbn", "cudnn_gbn",
                "optimizers", "sparsity", "multihead_attn", "fmha",
                "transducer", "bottleneck", "peer_memory"):
        return importlib.import_module(f"apex_tpu.contrib.{name}")
    raise AttributeError(f"module 'apex_tpu.contrib' has no attribute {name!r}")
