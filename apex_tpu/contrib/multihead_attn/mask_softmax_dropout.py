"""Fused mask + softmax + dropout building block.

Capability port of apex/contrib/multihead_attn/mask_softmax_dropout_func.py
(:6-96, over ``fast_multihead_attn.mask_softmax_dropout_*`` CUDA kernels).
The reference exposes the attention-probability sub-step of the fast MHA
path as its own autograd Function so models can fuse just the
mask/softmax/dropout portion; the backward recomputes from the stashed
softmax results. Under XLA the fusion and the recompute policy are the
compiler's job — the port is the numerics: additive or boolean padding
mask, fp32 softmax, train-time dropout with inverted scaling.
"""

import jax
import jax.numpy as jnp

from apex_tpu.transformer.functional.fused_softmax import (
    scaled_masked_softmax,
)
from apex_tpu.utils import train_dropout


def mask_softmax_dropout(is_training, heads, inputs, pad_mask=None,
                         mask_additive=False, dropout_prob=0.0,
                         dropout_rng=None):
    """Returns dropout(softmax(mask(inputs))).

    ``inputs``: [b*heads, sq, sk] attention scores (the reference's
    shape, mask_softmax_dropout_func.py:8). ``pad_mask``: [b, 1, sq, sk]
    or broadcastable; additive (added to the scores) when
    ``mask_additive``, else boolean True == masked (reference: the
    byte-mask fill path). fp32 softmax, output in the input dtype.
    """
    dtype = inputs.dtype
    b_heads, sq, sk = inputs.shape
    mask = pad_mask
    if mask is not None and mask.ndim == 4:
        # [b, 1 or heads, sq, sk] → per-(batch·head) rows
        mask = jnp.broadcast_to(
            mask, (b_heads // heads, heads, sq, sk)
        ).reshape(b_heads, sq, sk)
    if mask is not None and mask_additive:
        x = inputs.astype(jnp.float32) + mask.astype(jnp.float32)
        probs = jax.nn.softmax(x, axis=-1).astype(dtype)
    else:
        # boolean path: shared fp32 masked softmax — fully-masked rows
        # emit zeros, the reference kernels' semantics (and the repo's
        # FusedScaleMaskSoftmax's, functional/fused_softmax.py:30-51)
        probs = scaled_masked_softmax(inputs, mask)
    if is_training and dropout_prob > 0.0:
        if dropout_rng is None:
            raise ValueError(
                "mask_softmax_dropout: dropout_rng is required when "
                "training with dropout_prob > 0")
        probs = train_dropout(dropout_rng, probs, dropout_prob,
                              zero=jnp.zeros((), dtype))
    return probs


class MaskSoftmaxDropout:
    """Class-shaped surface mirroring the reference autograd Function's
    ``apply(is_training, heads, inputs, pad_mask, mask_additive,
    dropout_prob)`` calling convention; JAX AD replaces the hand-written
    backward (which recomputes through the stashed softmax)."""

    @staticmethod
    def apply(is_training, heads, inputs, pad_mask, mask_additive,
              dropout_prob, dropout_rng=None):
        return mask_softmax_dropout(is_training, heads, inputs, pad_mask,
                                    mask_additive, dropout_prob,
                                    dropout_rng)

    def __call__(self, *args, **kwargs):
        return self.apply(*args, **kwargs)
