"""Fast encoder-decoder multi-head attention.

Capability port of apex/contrib/multihead_attn/encdec_multihead_attn.py:21-
200 and encdec autograd fns (q from the decoder stream, packed kv from the
encoder stream). Same TPU design notes as self_multihead_attn.
"""

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from apex_tpu.contrib.multihead_attn.self_multihead_attn import _attn_core


class EncdecMultiheadAttn(nn.Module):
    """Reference ctor: encdec_multihead_attn.py:27-48."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, query, key, value=None, key_padding_mask=None,
                 need_weights=False, attn_mask=None, is_training=True):
        """``key`` is the encoder output; ``value`` must equal key
        (the reference asserts inputs are the same stream and packs kv)."""
        assert value is None or value is key, (
            "EncdecMultiheadAttn packs kv from one stream; pass value=None "
            "or the same tensor as key (reference asserts the same)")
        e, h = self.embed_dim, self.num_heads
        assert e % h == 0
        scaling = (e // h) ** -0.5

        x = query
        residual = query
        if self.include_norm_add:
            x = nn.LayerNorm(epsilon=1e-5, name="lyr_nrm",
                             param_dtype=self.param_dtype)(x)

        q = nn.DenseGeneral(e, use_bias=self.bias, name="q_proj",
                            param_dtype=self.param_dtype,
                            kernel_init=nn.initializers.xavier_uniform())(x)
        kv = nn.DenseGeneral(2 * e, use_bias=self.bias, name="kv_proj",
                             param_dtype=self.param_dtype,
                             kernel_init=nn.initializers.xavier_uniform())(
            key)
        k, v = jnp.split(kv, 2, axis=-1)

        drop = nn.Dropout(rate=self.dropout)
        ctx = _attn_core(q, k, v, scaling, h, key_padding_mask, attn_mask,
                         False, self.dropout, not is_training, drop,
                         fast=self.impl == "fast")
        out = nn.DenseGeneral(e, use_bias=self.bias, name="out_proj",
                              param_dtype=self.param_dtype,
                              kernel_init=nn.initializers.xavier_uniform())(
            ctx)
        if self.include_norm_add:
            out = nn.Dropout(rate=self.dropout)(
                out, deterministic=not is_training) + residual
        return out, None
