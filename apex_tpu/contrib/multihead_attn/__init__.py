"""apex_tpu.contrib.multihead_attn (reference: apex/contrib/multihead_attn)."""

from apex_tpu.contrib.multihead_attn.self_multihead_attn import (  # noqa: F401
    SelfMultiheadAttn,
    jit_dropout_add,
)
from apex_tpu.contrib.multihead_attn.encdec_multihead_attn import (  # noqa: F401
    EncdecMultiheadAttn,
)
from apex_tpu.contrib.multihead_attn.mask_softmax_dropout import (  # noqa: F401
    MaskSoftmaxDropout,
    mask_softmax_dropout,
)
