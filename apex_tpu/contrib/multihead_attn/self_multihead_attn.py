"""Fast self multi-head attention.

Capability port of apex/contrib/multihead_attn/self_multihead_attn.py:21-240
and its autograd functions (self_multihead_attn_func.py,
fast_self_multihead_attn_func.py, fast_self_multihead_attn_norm_add_func.py)
over ``fast_multihead_attn`` (8,010 LoC CUDA).

The CUDA "fast" path removes transposes/copies, fuses mask+softmax+dropout,
and batches the GEMMs via cublasLt strided-batch; the "norm_add" variants
prepend a fused LayerNorm and append the residual add. On TPU the
elementwise fusions are XLA's job; ``impl="fast"`` additionally routes the
unmasked/no-dropout case through ``ops.fused_attention`` (one Pallas flash
kernel on TPU — no materialized scores), while ``impl="default"`` always
runs the unfused composition with materialized [b*h, sq, sk] scores
(fp32-accumulated — the reference "default" autograd-function semantics).
``include_norm_add`` composes the same LN → attn → dropout → +residual
chain the fused kernel hardcodes.

Layout: [seq, batch, embed] (torch MHA convention, as the reference).
"""

import math
from typing import Any

import jax.numpy as jnp
from flax import linen as nn
from jax import lax


def _attn_core(q, k, v, scaling, heads, key_padding_mask, attn_mask,
               mask_additive, dropout, deterministic, dropout_module,
               fast=True):
    """Batched [b*h, s, d] attention with fp32-accumulated GEMMs and fp32
    softmax (the CUDA kernels' internal accumulation)."""
    sq, b, e = q.shape
    sk = k.shape[0]
    d = e // heads

    if (fast and attn_mask is None and key_padding_mask is None
            and (dropout == 0.0 or deterministic)):
        # the genuinely fast path: flash attention (one Pallas kernel on
        # TPU — no materialized [b*h, sq, sk] scores), the TPU analog of
        # what fast_multihead_attn's fused CUDA path buys
        from apex_tpu.ops import fused_attention

        def to_bhsd(x):
            return x.reshape(x.shape[0], b, heads, d).transpose(1, 2, 0, 3)

        ctx = fused_attention(to_bhsd(q), to_bhsd(k), to_bhsd(v),
                              sm_scale=scaling)
        return ctx.transpose(2, 0, 1, 3).reshape(sq, b, e)

    def split_heads(x):
        # [s, b, e] → [b*h, s, d]
        return (x.reshape(x.shape[0], b * heads, d)
                .transpose(1, 0, 2))

    qb, kb, vb = split_heads(q * scaling), split_heads(k), split_heads(v)
    scores = lax.dot_general(qb, kb, (((2,), (2,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)

    if attn_mask is not None:
        if mask_additive:
            scores = scores + attn_mask.astype(scores.dtype)
        else:
            scores = jnp.where(attn_mask.astype(bool), -jnp.inf, scores)
    if key_padding_mask is not None:
        # [b, sk] True = pad → mask every head/query of that batch
        kp = key_padding_mask.astype(bool)[:, None, None, :]
        kp = jnp.broadcast_to(kp, (b, heads, sq, sk)).reshape(
            b * heads, sq, sk)
        scores = jnp.where(kp, -jnp.inf, scores)

    probs = nn.softmax(scores, axis=-1)
    # fully-masked rows → 0 (matches the CUDA kernel's masked softmax)
    probs = jnp.where(jnp.isnan(probs), 0.0, probs)
    probs = dropout_module(probs.astype(q.dtype),
                           deterministic=deterministic)

    ctx = lax.dot_general(probs, vb, (((2,), (1,)), ((0,), (0,))),
                          preferred_element_type=jnp.float32).astype(q.dtype)
    return ctx.transpose(1, 0, 2).reshape(sq, b, e)


def jit_dropout_add(x, residual, prob, is_training, rng=None):
    """residual + dropout(x) (reference:
    self_multihead_attn.py:14-18, a torchscripted fusion — XLA fuses the
    chain without annotation)."""
    if is_training and prob > 0.0:
        from apex_tpu.utils import train_dropout
        if rng is None:
            raise ValueError("jit_dropout_add: rng required in training")
        x = train_dropout(rng, x, prob)
    return residual + x


class SelfMultiheadAttn(nn.Module):
    """Reference ctor: self_multihead_attn.py:27-50 (embed_dim, num_heads,
    dropout, bias, include_norm_add, impl, separate_qkv_params,
    mask_additive)."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"  # "fast": flash kernel for unmasked/no-dropout
    # attention; "default": always the materialized-scores composition
    separate_qkv_params: bool = False
    mask_additive: bool = False
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, query, key=None, value=None, key_padding_mask=None,
                 need_weights=False, attn_mask=None, is_training=True):
        """forward(query, key, value, key_padding_mask, need_weights,
        attn_mask, is_training) (reference :150-240). key/value args are
        accepted-and-ignored for self attention parity."""
        e, h = self.embed_dim, self.num_heads
        assert e % h == 0
        scaling = (e // h) ** -0.5
        dense = lambda n, feats: nn.DenseGeneral(  # noqa: E731
            feats, use_bias=self.bias, name=n, param_dtype=self.param_dtype,
            kernel_init=nn.initializers.xavier_uniform())

        x = query
        residual = query
        if self.include_norm_add:
            x = nn.LayerNorm(epsilon=1e-5, name="lyr_nrm",
                             param_dtype=self.param_dtype)(x)

        if self.separate_qkv_params:
            q = dense("q_proj", e)(x)
            k = dense("k_proj", e)(x)
            v = dense("v_proj", e)(x)
        else:
            qkv = dense("in_proj", 3 * e)(x)
            q, k, v = jnp.split(qkv, 3, axis=-1)

        drop = nn.Dropout(rate=self.dropout)
        ctx = _attn_core(q, k, v, scaling, h, key_padding_mask, attn_mask,
                         self.mask_additive, self.dropout,
                         not is_training, drop, fast=self.impl == "fast")
        out = nn.DenseGeneral(e, use_bias=self.bias, name="out_proj",
                              param_dtype=self.param_dtype,
                              kernel_init=nn.initializers.xavier_uniform())(
            ctx)
        if self.include_norm_add:
            out = nn.Dropout(rate=self.dropout)(
                out, deterministic=not is_training) + residual
        if need_weights:
            return out, None  # reference fast path never returns weights
        return out, None
