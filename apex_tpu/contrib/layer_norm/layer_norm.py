"""High-performance layer norm for hidden sizes 768–12288.

Capability port of apex/contrib/layer_norm/layer_norm.py:8-60 over
``fast_layer_norm`` (2,231 LoC CUDA: one-pass vectorized row norm). The
TPU counterpart of that kernel is ``apex_tpu.ops.layer_norm_pallas`` — a
hand-written Pallas row kernel (fp32 stats, per-block affine-grad
partials) — which this surface selects by default, falling back to the
XLA-fused jnp path for shapes the kernel doesn't cover. PERF.md §4 records
the head-to-head timing on TPU.
"""

from apex_tpu.normalization.fused_layer_norm import FusedLayerNorm as _Fused


def FastLayerNorm(hidden_size, eps=1e-5, **kwargs):
    """Factory mirroring the reference ctor (layer_norm.py:41-60). Returns
    a FusedLayerNorm module (flax modules are frozen dataclasses, so the
    ctor adaptation is a factory rather than an __init__ override) with the
    Pallas row kernel enabled."""
    kwargs.setdefault("use_pallas", True)
    return _Fused(normalized_shape=hidden_size, eps=eps, **kwargs)
