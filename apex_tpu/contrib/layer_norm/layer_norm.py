"""High-performance layer norm for hidden sizes 768–12288.

Capability port of apex/contrib/layer_norm/layer_norm.py:8-60 over
``fast_layer_norm`` (2,231 LoC CUDA: one-pass vectorized row norm). On TPU
the one-pass row norm is the same Pallas/XLA kernel behind
apex_tpu.normalization.FusedLayerNorm — this is the contrib-surface alias,
mirroring how the reference ships two generations of LN kernels with
different ctor conventions (hidden_size instead of normalized_shape).
"""

from apex_tpu.normalization.fused_layer_norm import FusedLayerNorm as _Fused


def FastLayerNorm(hidden_size, eps=1e-5, **kwargs):
    """Factory mirroring the reference ctor (layer_norm.py:41-60). Returns
    a FusedLayerNorm module (flax modules are frozen dataclasses, so the
    ctor adaptation is a factory rather than an __init__ override)."""
    return _Fused(normalized_shape=hidden_size, eps=eps, **kwargs)
