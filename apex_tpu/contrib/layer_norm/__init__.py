"""apex_tpu.contrib.layer_norm (reference: apex/contrib/layer_norm)."""

from apex_tpu.contrib.layer_norm.layer_norm import FastLayerNorm  # noqa: F401
