"""apex_tpu.contrib.sparsity (reference: apex/contrib/sparsity)."""

from apex_tpu.contrib.sparsity.asp import ASP  # noqa: F401
from apex_tpu.contrib.sparsity.sparse_masklib import (  # noqa: F401
    compute_valid_2d_patterns,
    create_mask,
    m4n2_1d,
    m4n2_2d_best,
    m4n2_2d_greedy,
    mn_1d_best,
    mn_2d_best,
    mn_2d_greedy,
)
from apex_tpu.contrib.sparsity.permutation_search import (  # noqa: F401
    accelerated_search_for_good_permutation,
    efficacy,
    exhaustive_search,
    magnitude_after_pruning_rows,
    progressive_channel_swap,
    sum_after_2_to_4,
)
