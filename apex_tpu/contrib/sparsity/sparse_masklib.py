"""2:4 structured-sparsity mask computation.

Capability port of apex/contrib/sparsity/sparse_masklib.py (the
``create_mask`` dispatch + m4n2 pattern family at :145). The semantics:
partition each weight row into groups of ``m`` consecutive elements and
keep the ``n`` largest-magnitude entries per group (n:m sparsity; m4n2 =
2-of-4, the pattern NVIDIA sparse tensor cores require).

TPU note: MXUs don't execute 2:4 sparse matmuls, but the *capability* —
training with hardware-friendly structured masks (for export to
GPU-serving, or for FLOP reduction via mask-aware kernels) — ports
directly; the mask math is pure tensor ops and jit-safe.
"""

import jax
import jax.numpy as jnp


def _unstructured_mask(w, density):
    """Keep exactly round(size*density) entries. Selection is by index
    (argsort of |w|), not a >=threshold compare — a threshold keeps every
    tie at the cutoff (a constant tensor would come out fully dense)."""
    k = max(1, int(round(w.size * density)))
    flat = jnp.abs(w).reshape(-1)
    _, idx = jax.lax.top_k(flat, k)
    mask = jnp.zeros(flat.shape, w.dtype).at[idx].set(1)
    return mask.reshape(w.shape)


def _nm_mask(w, n, m):
    """Keep the n largest-|w| of every m consecutive elements along the
    last dim (reference: mn_1d_best / m4n2_1d, sparse_masklib.py:98-148)."""
    orig_shape = w.shape
    assert orig_shape[-1] % m == 0, (
        f"last dim {orig_shape[-1]} not divisible by group size {m}")
    groups = jnp.abs(w).reshape(-1, m)
    # rank within each group; keep the top-n
    order = jnp.argsort(groups, axis=-1)  # ascending
    ranks = jnp.argsort(order, axis=-1)
    mask = (ranks >= (m - n)).astype(w.dtype)
    return mask.reshape(orig_shape)


def create_mask(tensor, pattern="m4n2_1d", density=0.5):
    """Reference: sparse_masklib.py:145 ``create_mask(tensor, pattern)``.

    Supported patterns: "m4n2_1d" (and the general "mMnN_1d" family),
    "unstructured".
    """
    if pattern == "unstructured":
        return _unstructured_mask(tensor, density)
    if pattern.startswith("m") and "_1d" in pattern:
        body = pattern[: pattern.index("_1d")]  # e.g. "m4n2"
        m_str, n_str = body[1:].split("n")
        return _nm_mask(tensor, int(n_str), int(m_str))
    raise ValueError(f"unsupported sparsity pattern: {pattern}")


# named pattern entry points (reference: sparse_masklib.py:90-143 —
# `mn_1d_best` searches the best n-of-m column mask per group, and the
# m4n2_* wrappers pin (m, n); the 2d variants apply the same selection
# to 4x4 blocks on magnitude-transposed views)
def mn_1d_best(matrix, m, n):
    """Best n:m 1D mask (reference: sparse_masklib.py:90-104). The jnp
    top-k selection in `_nm_mask` IS the best-per-group choice."""
    return _nm_mask(matrix, n, m)


def m4n2_1d(mat, density=None):
    """Reference: sparse_masklib.py:106-107."""
    del density  # fixed by the pattern, kept for the reference signature
    return mn_1d_best(mat, 4, 2)
