"""2:4 structured-sparsity mask computation.

Capability port of apex/contrib/sparsity/sparse_masklib.py (the
``create_mask`` dispatch + m4n2 pattern family at :145). The semantics:
partition each weight row into groups of ``m`` consecutive elements and
keep the ``n`` largest-magnitude entries per group (n:m sparsity; m4n2 =
2-of-4, the pattern NVIDIA sparse tensor cores require).

TPU note: MXUs don't execute 2:4 sparse matmuls, but the *capability* —
training with hardware-friendly structured masks (for export to
GPU-serving, or for FLOP reduction via mask-aware kernels) — ports
directly; the mask math is pure tensor ops and jit-safe.
"""

import functools
import itertools

import numpy as np

import jax
import jax.numpy as jnp


def fill(x):
    """Fraction of nonzero entries (reference: sparse_masklib.py:9-10 —
    the density diagnostic ASP logs)."""
    return float(jnp.count_nonzero(x)) / x.size


def _unstructured_mask(w, density):
    """Keep exactly round(size*density) entries. Selection is by index
    (argsort of |w|), not a >=threshold compare — a threshold keeps every
    tie at the cutoff (a constant tensor would come out fully dense)."""
    k = max(1, int(round(w.size * density)))
    flat = jnp.abs(w).reshape(-1)
    _, idx = jax.lax.top_k(flat, k)
    mask = jnp.zeros(flat.shape, w.dtype).at[idx].set(1)
    return mask.reshape(w.shape)


def _nm_mask(w, n, m):
    """Keep the n largest-|w| of every m consecutive elements along the
    last dim (reference: mn_1d_best / m4n2_1d, sparse_masklib.py:98-148)."""
    orig_shape = w.shape
    assert orig_shape[-1] % m == 0, (
        f"last dim {orig_shape[-1]} not divisible by group size {m}")
    groups = jnp.abs(w).reshape(-1, m)
    # rank within each group; keep the top-n
    order = jnp.argsort(groups, axis=-1)  # ascending
    ranks = jnp.argsort(order, axis=-1)
    mask = (ranks >= (m - n)).astype(w.dtype)
    return mask.reshape(orig_shape)


def create_mask(tensor, pattern="m4n2_1d", density=0.5):
    """Reference: sparse_masklib.py:145 ``create_mask(tensor, pattern)``.

    Supported patterns: "m4n2_1d" (and the general "mMnN_1d" family),
    "m4n2_2d_best" / "m4n2_2d_greedy" (and their mMnN families),
    "unstructured".
    """
    if pattern == "unstructured":
        return _unstructured_mask(tensor, density)
    if pattern.startswith("m") and "_1d" in pattern:
        body = pattern[: pattern.index("_1d")]  # e.g. "m4n2"
        m_str, n_str = body[1:].split("n")
        return _nm_mask(tensor, int(n_str), int(m_str))
    if pattern.startswith("m") and "_2d_" in pattern:
        body = pattern[: pattern.index("_2d_")]
        m_str, n_str = body[1:].split("n")
        if pattern.endswith("_2d_best"):
            fn = mn_2d_best
        elif pattern.endswith("_2d_greedy"):
            fn = mn_2d_greedy
        else:
            raise ValueError(f"unsupported sparsity pattern: {pattern}")
        m_, n_ = int(m_str), int(n_str)
        shape = tensor.shape
        # reshape to 2D per the reference's rules (sparse_masklib.py:
        # 150-183): 1d -> [1, d]; 3d (batch, in, out) -> [b*in, out];
        # 4d convs -> channels-minor [h*w*out, in], permuted back
        if tensor.ndim == 1:
            return fn(tensor.reshape(1, -1), m_, n_).reshape(shape)
        if tensor.ndim == 2:
            return fn(tensor, m_, n_)
        if tensor.ndim == 3:
            return fn(tensor.reshape(-1, shape[-1]), m_, n_).reshape(shape)
        if tensor.ndim == 4:
            t = tensor.transpose(2, 3, 0, 1).reshape(-1, shape[1])
            mask = fn(t, m_, n_)
            return mask.reshape(shape[2], shape[3], shape[0],
                                shape[1]).transpose(2, 3, 0, 1)
        raise ValueError(
            f"unsupported tensor rank {tensor.ndim} for 2d pruning")
    raise ValueError(f"unsupported sparsity pattern: {pattern}")


# named pattern entry points (reference: sparse_masklib.py:90-143 —
# `mn_1d_best` searches the best n-of-m column mask per group, and the
# m4n2_* wrappers pin (m, n); the 2d variants apply the same selection
# to 4x4 blocks on magnitude-transposed views)
def mn_1d_best(matrix, m, n):
    """Best n:m 1D mask (reference: sparse_masklib.py:90-104). The jnp
    top-k selection in `_nm_mask` IS the best-per-group choice."""
    return _nm_mask(matrix, n, m)


def m4n2_1d(mat, density=None):
    """Reference: sparse_masklib.py:106-107."""
    del density  # fixed by the pattern, kept for the reference signature
    return mn_1d_best(mat, 4, 2)


# ---------------------------------------------------------------------------
# 2D n:m pruning (reference: sparse_masklib.py:53-141). A weight tensor
# masked "2d" is n:m sparse along BOTH rows and columns of every mxm
# block, so its TRANSPOSE is also n:m sparse — the property that
# accelerates DGRAD on sparse tensor cores. The reference drives a
# host loop (greedy) or a cuda pattern-matmul (best); both are realized
# here as one batched jnp program over all blocks at once.
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _valid_2d_patterns_cached(m, n):
    row = np.zeros(m)
    row[:n] = 1
    rows = sorted(set(itertools.permutations(row.tolist())))
    valid = []
    for combo in itertools.product(range(len(rows)), repeat=m):
        p = np.asarray([rows[i] for i in combo])
        if (p.sum(0) <= n).all():
            valid.append(p)
    out = np.stack(valid).astype(np.float32)
    out.flags.writeable = False  # shared cache: callers get a copy
    return out


def compute_valid_2d_patterns(m, n):
    """All mxm 0/1 patterns with exactly n ones per row and <= n per
    column (reference: sparse_masklib.py:103-118; with m rows of n ones
    the column bound makes every column exactly n). Returns a host
    ndarray [num_patterns, m, m] — 90 patterns for m=4, n=2. A fresh
    copy each call: the cached array must not be mutable through the
    public boundary."""
    return _valid_2d_patterns_cached(m, n).copy()


def _blocks(matrix, m):
    """[R, C] -> [R/m * C/m, m, m] row-major blocks (+ inverse info)."""
    R, C = matrix.shape
    assert R % m == 0 and C % m == 0, (
        f"2d pruning needs shapes divisible by {m}, got {matrix.shape}")
    b = matrix.reshape(R // m, m, C // m, m).transpose(0, 2, 1, 3)
    return b.reshape(-1, m, m)


def _unblocks(blocks, R, C, m):
    return blocks.reshape(R // m, C // m, m, m).transpose(0, 2, 1, 3) \
        .reshape(R, C)


def mn_2d_best(matrix, m, n):
    """Exhaustive best 2D n:m mask (reference: sparse_masklib.py:121-138):
    for every mxm block pick the valid pattern maximizing the kept
    magnitude — one [blocks, m*m] x [m*m, patterns] matmul. Trailing
    rows/cols beyond the last full block stay unmasked, the same ragged
    contract as :func:`mn_2d_greedy`."""
    R, C = matrix.shape
    Rf, Cf = (R // m) * m, (C // m) * m
    patterns = jnp.asarray(_valid_2d_patterns_cached(m, n))   # [P, m, m]
    blocks = jnp.abs(_blocks(matrix[:Rf, :Cf], m)).reshape(-1, m * m)
    scores = blocks @ patterns.reshape(-1, m * m).T           # [B, P]
    best = jnp.argmax(scores, axis=-1)
    chosen = jnp.take(patterns.reshape(-1, m * m), best, axis=0)
    full = jnp.ones((R, C), matrix.dtype)
    return full.at[:Rf, :Cf].set(
        _unblocks(chosen.reshape(-1, m, m), Rf, Cf, m).astype(matrix.dtype))


def m4n2_2d_best(mat, density=None):
    """Reference: sparse_masklib.py:139-140."""
    del density
    return mn_2d_best(mat, 4, 2)


def mn_2d_greedy(matrix, m, n):
    """Greedy 2D n:m mask (reference: sparse_masklib.py:68-96): per
    block, admit entries in descending |w| order while their row and
    column budgets (n each) last. The reference's per-block host loop
    becomes one lax.scan over the m*m magnitude ranks, batched over all
    blocks. Trailing rows/cols beyond the last full block stay unmasked
    (reference behavior). NB (also true of the reference): greedy
    admission can strand a row/column below n entries — only the row
    and column UPPER bound n is guaranteed; ``mn_2d_best`` gives the
    exact-n property."""
    R, C = matrix.shape
    Rf, Cf = (R // m) * m, (C // m) * m
    sub = matrix[:Rf, :Cf]
    blocks = jnp.abs(_blocks(sub, m)).reshape(-1, m * m)     # [B, m*m]
    order = jnp.argsort(-blocks, axis=-1)                     # desc
    rows = order // m                                         # [B, m*m]
    cols = order % m

    def step(carry, idx):
        mask, rcnt, ccnt = carry
        r = jnp.take_along_axis(rows, idx[:, None], 1)[:, 0]  # [B]
        c = jnp.take_along_axis(cols, idx[:, None], 1)[:, 0]
        r1 = jax.nn.one_hot(r, m, dtype=jnp.int32)            # [B, m]
        c1 = jax.nn.one_hot(c, m, dtype=jnp.int32)
        take = ((jnp.sum(rcnt * r1, -1) < n)
                & (jnp.sum(ccnt * c1, -1) < n))               # [B]
        t = take.astype(jnp.int32)
        mask = mask + (r1[:, :, None] * c1[:, None, :]) * t[:, None, None]
        return (mask, rcnt + r1 * t[:, None], ccnt + c1 * t[:, None]), None

    B = blocks.shape[0]
    init = (jnp.zeros((B, m, m), jnp.int32),
            jnp.zeros((B, m), jnp.int32), jnp.zeros((B, m), jnp.int32))
    idxs = jnp.broadcast_to(jnp.arange(m * m)[:, None], (m * m, B))
    (mask, _, _), _ = jax.lax.scan(step, init, idxs)
    full = jnp.ones((R, C), matrix.dtype)
    return full.at[:Rf, :Cf].set(
        _unblocks(mask, Rf, Cf, m).astype(matrix.dtype))


def m4n2_2d_greedy(mat, density=None):
    """Reference: sparse_masklib.py:98-99."""
    del density
    return mn_2d_greedy(mat, 4, 2)
