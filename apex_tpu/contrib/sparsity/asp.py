"""ASP — automatic sparsity.

Capability port of apex/contrib/sparsity/asp.py:28-260: compute 2:4 masks
for eligible weights, then keep applying them after every optimizer step so
the network trains within the sparse support ("prune once, retrain").

The torch version monkey-patches ``optimizer.step``; the functional analog
wraps the optimizer transform: ``ASP.prune_trained_model``-equivalent is

    asp = ASP()
    asp.init_model_for_pruning(params)       # choose eligible weights
    asp.compute_sparse_masks(params)         # snapshot masks
    params = asp.apply_masks(params)         # prune
    tx = asp.wrap_optimizer(tx)              # re-mask after every update

``wrap_optimizer`` masks the UPDATES for masked weights, so a jitted train
loop stays sparse without host sync — observably identical to the
reference's step patch (weights outside the mask stay exactly zero).

The channel-permutation accuracy search (permutation_lib.py, CUDA-
accelerated) is out of scope here; ``allow_permutation`` is accepted and
must be False.
"""

import jax
import jax.numpy as jnp

from apex_tpu.contrib.sparsity.sparse_masklib import create_mask


def _default_allowed(path, leaf):
    """Eligible: ≥2-D float weights whose dims divide the group (the
    reference targets Linear/Conv weights with in-features %4 == 0,
    asp.py:87-110)."""
    if not jnp.issubdtype(leaf.dtype, jnp.floating):
        return False
    if leaf.ndim < 2:
        return False
    return leaf.shape[-1] % 4 == 0


class ASP:
    """Reference: asp.py:28 (classmethod-style singleton there; instances
    here — tests want isolation)."""

    def __init__(self):
        self.masks = None
        self._eligible = None
        self.pattern = "m4n2_1d"

    def init_model_for_pruning(self, params, mask_calculator="m4n2_1d",
                               verbosity=2, whitelist=None,
                               allowed_layer_names=None,
                               disallowed_layer_names=(),
                               allow_recompute_mask=False,
                               custom_layer_dict=None,
                               allow_permutation=False):
        """Reference: asp.py:60-150. ``whitelist``/layer-name filters
        operate on pytree path strings here."""
        assert not allow_permutation, (
            "channel-permutation search is not implemented in the TPU "
            "build (reference: permutation_lib.py)")
        self.pattern = mask_calculator

        def eligible(path, leaf):
            name = jax.tree_util.keystr(path)
            if allowed_layer_names is not None and not any(
                    a in name for a in allowed_layer_names):
                return False
            if any(d in name for d in disallowed_layer_names):
                return False
            return _default_allowed(path, leaf)

        self._eligible = jax.tree_util.tree_map_with_path(eligible, params)
        return self._eligible

    def compute_sparse_masks(self, params):
        """Reference: asp.py:152-200 — snapshot masks from current
        magnitudes."""
        assert self._eligible is not None, \
            "call init_model_for_pruning first"
        self.masks = jax.tree_util.tree_map(
            lambda ok, p: create_mask(p, self.pattern) if ok
            else jnp.ones_like(p),
            self._eligible, params)
        return self.masks

    def apply_masks(self, params):
        """Prune: w *= mask (reference: asp.py:176-184)."""
        assert self.masks is not None
        return jax.tree_util.tree_map(lambda p, m: p * m, params,
                                      self.masks)

    def wrap_optimizer(self, tx):
        """Mask updates so pruned weights stay zero — the functional form
        of the reference's patched ``optimizer.step`` (asp.py:214-240)."""
        assert self.masks is not None
        masks = self.masks

        def init(params):
            return tx.init(params)

        def update(grads, state, params=None):
            updates, state = tx.update(grads, state, params)
            updates = jax.tree_util.tree_map(
                lambda u, m: u * m.astype(u.dtype), updates, masks)
            return updates, state

        import optax

        return optax.GradientTransformation(init, update)

    # reference convenience (asp.py:242-260)
    def prune_trained_model(self, params, tx):
        self.init_model_for_pruning(params)
        self.compute_sparse_masks(params)
        return self.apply_masks(params), self.wrap_optimizer(tx)
