"""ASP — automatic sparsity.

Capability port of apex/contrib/sparsity/asp.py:28-260: compute 2:4 masks
for eligible weights, then keep applying them after every optimizer step so
the network trains within the sparse support ("prune once, retrain").

The torch version monkey-patches ``optimizer.step``; the functional analog
wraps the optimizer transform: ``ASP.prune_trained_model``-equivalent is

    asp = ASP()
    asp.init_model_for_pruning(params)       # choose eligible weights
    asp.compute_sparse_masks(params)         # snapshot masks
    params = asp.apply_masks(params)         # prune
    tx = asp.wrap_optimizer(tx)              # re-mask after every update

``wrap_optimizer`` masks the UPDATES for masked weights, so a jitted train
loop stays sparse without host sync — observably identical to the
reference's step patch (weights outside the mask stay exactly zero).

With ``allow_permutation=True`` the channel-permutation accuracy search
(reference: permutation_lib.py:42 + permutation_search_kernels/, ported in
``permutation_search.py``) runs per eligible weight: masks are computed in
the permuted column domain — where 2:4 groups align with the best
grouping found — and scattered back, so training proceeds in the original
layout while keeping the permuted-optimal magnitude. ``self.permutations``
stores each weight's column permutation for export to a physically
permuted 2:4 layout (the reference instead rewires the torch graph;
a functional pytree has no graph to rewire).
"""

import numpy as np

import jax
import jax.numpy as jnp

from apex_tpu.contrib.sparsity.sparse_masklib import create_mask


def _default_allowed(path, leaf):
    """Eligible: ≥2-D float weights whose dims divide the group (the
    reference targets Linear/Conv weights with in-features %4 == 0,
    asp.py:87-110)."""
    if not jnp.issubdtype(leaf.dtype, jnp.floating):
        return False
    if leaf.ndim < 2:
        return False
    return leaf.shape[-1] % 4 == 0


class ASP:
    """Reference: asp.py:28 (classmethod-style singleton there; instances
    here — tests want isolation)."""

    def __init__(self):
        self.masks = None
        self._eligible = None
        self.pattern = "m4n2_1d"
        self.permutations = None
        self._allow_permutation = False
        self._search_options = None

    def init_model_for_pruning(self, params, mask_calculator="m4n2_1d",
                               verbosity=2, whitelist=None,
                               allowed_layer_names=None,
                               disallowed_layer_names=(),
                               allow_recompute_mask=False,
                               custom_layer_dict=None,
                               allow_permutation=False,
                               permutation_search_options=None):
        """Reference: asp.py:60-150. ``whitelist``/layer-name filters
        operate on pytree path strings here. ``allow_permutation`` enables
        the channel-permutation search during ``compute_sparse_masks``."""
        if allow_permutation and mask_calculator != "m4n2_1d":
            # the search kernels score top-2-of-4 groups specifically
            # (reference kernels are likewise m=4-only); any other pattern
            # would be optimized against the wrong objective
            raise ValueError(
                "allow_permutation=True requires mask_calculator='m4n2_1d' "
                f"(got {mask_calculator!r})")
        self._allow_permutation = allow_permutation
        self._search_options = permutation_search_options
        self.pattern = mask_calculator

        def eligible(path, leaf):
            name = jax.tree_util.keystr(path)
            if allowed_layer_names is not None and not any(
                    a in name for a in allowed_layer_names):
                return False
            if any(d in name for d in disallowed_layer_names):
                return False
            return _default_allowed(path, leaf)

        self._eligible = jax.tree_util.tree_map_with_path(eligible, params)
        return self._eligible

    def compute_sparse_masks(self, params):
        """Reference: asp.py:152-200 — snapshot masks from current
        magnitudes (optionally in each weight's best permuted column
        domain, reference permutation_lib.py)."""
        assert self._eligible is not None, \
            "call init_model_for_pruning first"

        self.permutations = {} if self._allow_permutation else None

        def make_mask(path, ok, p):
            if not ok:
                return jnp.ones_like(p)
            if not self._allow_permutation:
                return create_mask(p, self.pattern)
            return self._permuted_mask(jax.tree_util.keystr(path), p)

        self.masks = jax.tree_util.tree_map_with_path(
            make_mask, self._eligible, params)
        return self.masks

    def _permuted_mask(self, name, p):
        """Search a column permutation, mask in the permuted domain, and
        scatter the mask back to the original layout (recorded in
        ``self.permutations[name]`` for physical-layout export)."""
        from apex_tpu.contrib.sparsity.permutation_search import (
            accelerated_search_for_good_permutation)

        mat = np.asarray(p.astype(jnp.float32)).reshape(-1, p.shape[-1])
        perm = accelerated_search_for_good_permutation(
            mat, self._search_options)
        self.permutations[name] = np.asarray(perm)
        permuted = jnp.take(p, jnp.asarray(perm), axis=-1)
        mask_p = create_mask(permuted, self.pattern)
        inv = np.argsort(perm)
        return jnp.take(mask_p, jnp.asarray(inv), axis=-1)

    def apply_masks(self, params):
        """Prune: w *= mask (reference: asp.py:176-184)."""
        assert self.masks is not None
        return jax.tree_util.tree_map(lambda p, m: p * m, params,
                                      self.masks)

    def wrap_optimizer(self, tx):
        """Mask updates so pruned weights stay zero — the functional form
        of the reference's patched ``optimizer.step`` (asp.py:214-240)."""
        assert self.masks is not None
        masks = self.masks

        def init(params):
            return tx.init(params)

        def update(grads, state, params=None):
            updates, state = tx.update(grads, state, params)
            updates = jax.tree_util.tree_map(
                lambda u, m: u * m.astype(u.dtype), updates, masks)
            return updates, state

        import optax

        return optax.GradientTransformation(init, update)

    # reference convenience (asp.py:242-260)
    def prune_trained_model(self, params, tx):
        self.init_model_for_pruning(params)
        self.compute_sparse_masks(params)
        return self.apply_masks(params), self.wrap_optimizer(tx)
