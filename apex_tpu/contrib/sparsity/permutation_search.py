"""Channel-permutation search for 2:4 sparsity.

Capability port of apex/contrib/sparsity/permutation_lib.py:42 +
permutation_search_kernels/ (exhaustive_search.py, channel_swap.py,
permutation_utilities.py; CUDA kernels under CUDA_kernels/). Permuting the
grouped (input-channel) axis before applying an n:m mask changes WHICH
weights share a group, so a good permutation preserves far more magnitude
than the naive layout — the accuracy-preserving half of ASP.

TPU-first design: the reference farms per-stripe-group scoring out to CUDA
kernels (build_permute_map / sum_after_2_to_4) driven by a greedy host
loop. Here the same split is: ONE jitted batched scoring program
(gather all stripe-pairs → apply all 35 canonical permutations → top-2-of-4
magnitude sums, reduced over rows on the VPU) and a small greedy host loop
over its [pairs] result. No per-pair kernel launches, no Python over rows.

Layout convention: ``matrix`` is [rows, cols] with the GROUPED axis last
(cols), matching ``sparse_masklib.create_mask``. For flax kernels
[in, out] pass ``kernel.T`` if the grouped axis is the input dim.
"""

import functools
import itertools

import numpy as np

import jax
import jax.numpy as jnp


GROUP = 4  # m in 2:4 — the kernels are specialized to m=4 like the CUDA ones
KEEP = 2   # n


def sum_after_2_to_4(matrix):
    """Total |w| kept if 2:4 were applied along the last axis (reference:
    permutation_utilities.py sum_after_2_to_4 — CUDA kernel / per-row loop;
    here one vectorized top-2-of-4 reduction)."""
    m = jnp.abs(jnp.asarray(matrix, jnp.float32))
    g = m.reshape(*m.shape[:-1], m.shape[-1] // GROUP, GROUP)
    s = jnp.sort(g, axis=-1)
    return jnp.sum(s[..., KEEP:])


def magnitude_after_pruning_rows(matrix, rate=0.5):
    """Unstructured per-row pruning magnitude — the optimality bound used
    for efficacy (reference: permutation_utilities.py
    magnitude_after_pruning_rows)."""
    m = jnp.abs(jnp.asarray(matrix, jnp.float32))
    k = int(m.shape[-1] * (1.0 - rate))
    s = jnp.sort(m, axis=-1)
    return jnp.sum(s[..., m.shape[-1] - k:])


def efficacy(optimal_lost, base_lost, cur_lost):
    """How much of the naive→optimal gap a permutation recovers
    (reference: permutation_utilities.py efficacy)."""
    if base_lost == optimal_lost:
        return 1.0
    return (base_lost - cur_lost) / (base_lost - optimal_lost)


@functools.lru_cache(maxsize=None)
def _pair_permutations():
    """The 35 canonical permutations of 8 columns into two sorted groups of
    4 (group order and in-group order don't affect 2:4, so the canonical
    form — sorted groups, group containing column 0 first — enumerates each
    distinct grouping once; reference: exhaustive_search.py
    generate_unique_combinations / predict_unique_combinations(8,4)=35)."""
    perms = []
    cols = range(8)
    for ga in itertools.combinations(cols, GROUP):
        if 0 not in ga:
            continue
        gb = tuple(c for c in cols if c not in ga)
        perms.append(ga + gb)
    return np.asarray(perms, np.int32)  # [35, 8]


@jax.jit
def _score_all_pairs(mat_stripes, pairs):
    """Best-permutation improvement for every stripe pair.

    mat_stripes: [R, S, 4]; pairs: [P, 2] int32.
    Returns (improvement [P] fp32, best_perm_idx [P] int32) where
    improvement is (best permuted kept-magnitude) − (unpermuted kept
    magnitude) for the pair's 8 columns.
    """
    perms = jnp.asarray(_pair_permutations())  # [35, 8]

    def _kept(x):
        g = x.reshape(*x.shape[:-1], 2, GROUP)
        s = jnp.sort(g, axis=-1)
        # sum over rows (axis 0) and the two groups; keep pair/perm axes
        return jnp.sum(s[..., KEEP:], axis=(0, -1, -2))

    # [R, Pc, 8] — the pair's two stripes side by side
    sub = jnp.concatenate(
        [mat_stripes[:, pairs[:, 0]], mat_stripes[:, pairs[:, 1]]], axis=-1)
    sub = jnp.abs(sub.astype(jnp.float32))
    base = _kept(sub)                    # [Pc]
    permuted = sub[:, :, perms]          # [R, Pc, 35, 8]
    kept = _kept(permuted)               # [Pc, 35]
    best = jnp.argmax(kept, axis=-1)
    return jnp.max(kept, axis=-1) - base, best.astype(jnp.int32)


def _score_pairs_chunked(mat_stripes, pairs, chunk=2048):
    """Host-side chunking over pairs to bound the [R, Pc, 35, 8] tile.
    Chunks are padded to a fixed grid of sizes so the jitted scorer
    compiles O(log) distinct shapes, not one per touched-set size."""
    outs_i, outs_b = [], []
    for lo in range(0, len(pairs), chunk):
        part = pairs[lo:lo + chunk]
        n = len(part)
        padded = 1 << (n - 1).bit_length() if n > 1 else 1
        if padded != n:
            part = np.concatenate(
                [part, np.zeros((padded - n, 2), part.dtype)])
        imp, best = _score_all_pairs(mat_stripes, jnp.asarray(part))
        outs_i.append(np.asarray(imp)[:n])
        outs_b.append(np.asarray(best)[:n])
    return np.concatenate(outs_i), np.concatenate(outs_b)


def exhaustive_search(matrix, stripe_group_size=8, escape_attempts=100,
                      seed=0, threshold=1e-4):
    """Greedy stripe-pair permutation search (reference:
    exhaustive_search.py Exhaustive_Search: build_stripe_map scores every
    stripe group, use_stripe_map greedily applies the best disjoint ones,
    repeating until no positive improvement, with random perturbations to
    escape local minima).

    Only the reference's default window (stripe_group_size=8 → pairs of
    4-column stripes, 35 canonical permutations each) is implemented; the
    wider windows exist in the reference to feed the same greedy loop
    bigger local moves and change results marginally.

    Returns (permuted_matrix, permutation, improvement) with
    ``permuted_matrix == matrix[:, permutation]``.
    """
    assert stripe_group_size == 8, (
        "TPU build implements the default stripe_group_size=8 (pair) window")
    mat = np.asarray(matrix, np.float32)
    R, C = mat.shape
    assert C % GROUP == 0
    S = C // GROUP
    rng = np.random.RandomState(seed)
    perms35 = _pair_permutations()

    perm = np.arange(C)
    all_pairs = np.asarray(list(itertools.combinations(range(S), 2)),
                           np.int32)
    if len(all_pairs) == 0:
        return mat, perm, 0.0

    cur = mat.copy()
    base_kept = float(sum_after_2_to_4(cur))
    best_kept = base_kept
    best_perm = perm.copy()
    escapes_left = escape_attempts

    imp, bidx = _score_pairs_chunked(cur.reshape(R, S, GROUP), all_pairs)

    while True:
        # greedy pass: apply best disjoint positive pairs (use_stripe_map)
        order = np.argsort(-imp)
        used = set()
        applied = False
        for pi in order:
            if imp[pi] <= threshold:
                break
            a, b = all_pairs[pi]
            if a in used or b in used:
                continue
            cols = np.concatenate([np.arange(a * GROUP, a * GROUP + GROUP),
                                   np.arange(b * GROUP, b * GROUP + GROUP)])
            p8 = perms35[bidx[pi]]
            cur[:, cols] = cur[:, cols[p8]]
            perm[cols] = perm[cols[p8]]
            used.update((int(a), int(b)))
            applied = True

        if applied:
            kept = float(sum_after_2_to_4(cur))
            if kept > best_kept:
                best_kept = kept
                best_perm = perm.copy()
            # rescore only pairs touching modified stripes (reference:
            # build_stripe_map's used_stripes fast path)
            touched = np.asarray(
                [i for i, (a, b) in enumerate(all_pairs)
                 if a in used or b in used], np.int32)
            t_imp, t_bidx = _score_pairs_chunked(
                cur.reshape(R, S, GROUP), all_pairs[touched])
            imp[touched] = t_imp
            bidx[touched] = t_bidx
            continue

        # converged: random two-channel cross-stripe swap to escape
        # (reference: use_stripe_map's sm_perturbation path)
        if escapes_left <= 0:
            break
        escapes_left -= 1
        src = rng.randint(C)
        dst = rng.randint(C)
        if src // GROUP == dst // GROUP:
            continue
        cur[:, [src, dst]] = cur[:, [dst, src]]
        perm[[src, dst]] = perm[[dst, src]]
        touched = np.asarray(
            [i for i, (a, b) in enumerate(all_pairs)
             if a in (src // GROUP, dst // GROUP)
             or b in (src // GROUP, dst // GROUP)], np.int32)
        t_imp, t_bidx = _score_pairs_chunked(
            cur.reshape(R, S, GROUP), all_pairs[touched])
        imp[touched] = t_imp
        bidx[touched] = t_bidx

    return (np.asarray(matrix, np.float32)[:, best_perm], best_perm,
            best_kept - base_kept)


def progressive_channel_swap(matrix, max_attempts=1000,
                             improvement_threshold=1e-9, seed=0):
    """Random greedy channel swaps (reference:
    call_permutation_search_kernels.py 'progressive channel swap' strategy;
    bounded by attempts instead of wall-clock so results are
    deterministic). Returns (permuted_matrix, permutation, improvement)."""
    mat = np.asarray(matrix, np.float32)
    R, C = mat.shape
    S = C // GROUP
    rng = np.random.RandomState(seed)
    perm = np.arange(C)
    cur = mat.copy()
    base = float(sum_after_2_to_4(cur))

    def stripe_kept(sidx):
        g = np.abs(cur[:, sidx * GROUP:(sidx + 1) * GROUP])
        return float(np.sum(np.sort(g, axis=-1)[:, KEEP:]))

    kept_per_stripe = np.asarray([stripe_kept(s) for s in range(S)])

    for _ in range(max_attempts):
        src, dst = rng.randint(C), rng.randint(C)
        sa, sb = src // GROUP, dst // GROUP
        if sa == sb:
            continue
        # evaluate only the two affected stripes, without a matrix copy
        cur[:, [src, dst]] = cur[:, [dst, src]]
        new_a, new_b = stripe_kept(sa), stripe_kept(sb)
        gain = (new_a + new_b) - (kept_per_stripe[sa] + kept_per_stripe[sb])
        if gain > improvement_threshold:
            perm[[src, dst]] = perm[[dst, src]]
            kept_per_stripe[sa], kept_per_stripe[sb] = new_a, new_b
        else:
            cur[:, [src, dst]] = cur[:, [dst, src]]  # revert

    return (np.asarray(matrix, np.float32)[:, perm], perm,
            float(sum_after_2_to_4(cur)) - base)


def accelerated_search_for_good_permutation(matrix, options=None):
    """Strategy dispatch (reference:
    call_permutation_search_kernels.py accelerated_search_for_good_
    permutation). Returns the permutation sequence."""
    options = dict(options or {})
    strategy = options.setdefault("strategy", "exhaustive")
    if strategy == "exhaustive":
        _, perm, _ = exhaustive_search(
            matrix,
            stripe_group_size=options.get("stripe_group_size", 8),
            escape_attempts=options.get("escape_attempts", 100),
            seed=options.get("seed", 0))
        return perm
    if strategy == "progressive channel swap":
        _, perm, _ = progressive_channel_swap(
            matrix,
            max_attempts=options.get("max_attempts", 1000),
            improvement_threshold=options.get("improvement_threshold", 1e-9),
            seed=options.get("seed", 0))
        return perm
    raise ValueError(f"unknown permutation search strategy: {strategy}")
