"""Fused Conv + Bias [+ Mask] [+ ReLU] ops.

Capability port of apex/contrib/conv_bias_relu/conv_bias_relu.py:12-104
over ``fused_conv_bias_relu`` (1,639 LoC cudnn-frontend). The cudnn fusion
graph (conv → bias-add → [mask-mul] → relu) is exactly what XLA emits as a
conv + fused epilogue on TPU, so each "op" is the straight expression; the
half-precision contract (``custom_fwd(cast_inputs=torch.half)``) becomes an
explicit cast to the amp compute dtype.

Layout: NHWC (TPU-native; the cudnn path also runs channels-last).
Weights are [Kh, Kw, Cin, Cout] (jax conv convention).
"""

import jax.numpy as jnp
from jax import lax

from apex_tpu.amp import policy as _policy


def _conv(x, w, padding, stride):
    dt = _policy.compute_dtype(x.dtype)
    pad = ((padding, padding), (padding, padding)) \
        if isinstance(padding, int) else padding
    strides = (stride, stride) if isinstance(stride, int) else stride
    return lax.conv_general_dilated(
        x.astype(dt), w.astype(dt), window_strides=strides, padding=pad,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32).astype(dt)


class _OpSurface:
    """Mirrors torch.autograd.Function.apply-style call surface."""

    @classmethod
    def apply(cls, *args):
        return cls.forward(*args)


class ConvBiasReLU(_OpSurface):
    """y = relu(conv(x, w) + b) (reference: ConvBiasReLU_ :12-32)."""

    @staticmethod
    def forward(x, weight, bias, padding, stride):
        y = _conv(x, weight, padding, stride)
        return jnp.maximum(y + bias.reshape(1, 1, 1, -1).astype(y.dtype), 0)


class ConvBias(_OpSurface):
    """y = conv(x, w) + b (reference: ConvBias_ :58-77)."""

    @staticmethod
    def forward(x, weight, bias, padding, stride):
        y = _conv(x, weight, padding, stride)
        return y + bias.reshape(1, 1, 1, -1).astype(y.dtype)


class ConvBiasMaskReLU(_OpSurface):
    """y = relu((conv(x, w) + b) * mask) (reference: ConvBiasMaskReLU_
    :34-56)."""

    @staticmethod
    def forward(x, weight, bias, mask, padding, stride):
        y = _conv(x, weight, padding, stride)
        y = (y + bias.reshape(1, 1, 1, -1).astype(y.dtype)) \
            * mask.astype(y.dtype)
        return jnp.maximum(y, 0)


class ConvFrozenScaleBiasReLU(_OpSurface):
    """y = relu(conv(x, w) * scale + b) — frozen-BN folding (reference:
    ConvFrozenScaleBiasReLU_ :79-104)."""

    @staticmethod
    def forward(x, weight, scale, bias, padding, stride):
        y = _conv(x, weight, padding, stride)
        return jnp.maximum(
            y * scale.reshape(1, 1, 1, -1).astype(y.dtype)
            + bias.reshape(1, 1, 1, -1).astype(y.dtype), 0)
