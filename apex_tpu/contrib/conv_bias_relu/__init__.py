"""apex_tpu.contrib.conv_bias_relu (reference: apex/contrib/conv_bias_relu)."""

from apex_tpu.contrib.conv_bias_relu.conv_bias_relu import (  # noqa: F401
    ConvBias,
    ConvBiasMaskReLU,
    ConvBiasReLU,
    ConvFrozenScaleBiasReLU,
)
