"""apex_tpu.contrib.peer_memory (reference: apex/contrib/peer_memory)."""

from apex_tpu.contrib.peer_memory.peer_memory import PeerMemoryPool  # noqa: F401
from apex_tpu.contrib.peer_memory.peer_halo_exchanger_1d import (  # noqa: F401
    PeerHaloExchanger1d,
)
