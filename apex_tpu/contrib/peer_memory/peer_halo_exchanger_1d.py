"""1-D halo exchange over "peer memory" (ICI neighbor transfer).

Capability port of apex/contrib/peer_memory/peer_halo_exchanger_1d.py:5-90.
The reference pushes halo rows directly into neighbors' mapped buffers with
signal flags; on TPU the neighbor push is ``lax.ppermute`` (see
contrib.bottleneck.halo_exchangers for the design note). This class keeps
the reference's "pad with halo rows in place" calling convention:
``y`` arrives WITH 2*half_halo padding rows already allocated and the
exchange fills them from the neighbors.
"""

import jax.numpy as jnp

from apex_tpu.contrib.bottleneck.halo_exchangers import HaloExchangerSendRecv


class PeerHaloExchanger1d:
    """Reference ctor: (ranks, rank_in_group, peer_pool, half_halo)."""

    def __init__(self, ranks=None, rank_in_group=None, peer_pool=None,
                 half_halo=1, axis_name="spatial"):
        self.peer_group_size = len(ranks) if ranks is not None else None
        self.half_halo = half_halo
        self.peer_pool = peer_pool
        self._ex = HaloExchangerSendRecv(axis_name, self.peer_group_size)

    def __call__(self, y, H_split=True, explicit_nhwc=False, numSM=1,
                 diagnostics=False):
        """y: NHWC [N, Hs, W, C] (H_split) or [N, H, Ws, C] with
        2*half_halo padding rows/cols; returns y with the padding filled
        from neighbors (functional: returns the new array)."""
        hh = self.half_halo
        axis = 1 if H_split else 2

        def take(arr, start, size):
            idx = [slice(None)] * arr.ndim
            idx[axis] = slice(start, start + size)
            return arr[tuple(idx)]

        H = y.shape[axis] - 2 * hh
        low_out = take(y, hh, hh)          # first interior rows → up
        high_out = take(y, H, hh)          # last interior rows → down
        low_in, high_in = self._ex.left_right_halo_exchange(low_out,
                                                            high_out)
        pieces = [low_in, take(y, hh, H), high_in]
        return jnp.concatenate(pieces, axis=axis)
