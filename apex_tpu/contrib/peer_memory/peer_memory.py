"""Peer-memory pool — CUDA-IPC buffer compat surface.

Capability port of apex/contrib/peer_memory/peer_memory.py:5-80 over
``peer_memory_cuda`` (709 LoC). The reference mmaps raw CUDA allocations
into sibling processes so halo pushes bypass NCCL. On TPU there is no
process-addressable peer memory: direct neighbor transfers over ICI are
what ``lax.ppermute`` compiles to, which is strictly the same capability
(the kernel-bypass fast path) with no buffer management at all.

The pool is therefore a thin allocator of ordinary device arrays that
keeps the reference's call surface (allocate_peer_tensors) so ported code
runs; the "peer" aspect is realized by the collectives that consume these
buffers (see PeerHaloExchanger1d).
"""

import jax.numpy as jnp
import numpy as np


class PeerMemoryPool:
    """Reference ctor: peer_memory.py:8 (static_size, dynamic_size,
    peer_ranks)."""

    def __init__(self, static_size=0, dynamic_size=0, peer_ranks=None):
        self.static_size = static_size
        self.dynamic_size = dynamic_size
        self.peer_ranks = peer_ranks
        self._dynamic_allocated = 0

    def __del__(self):
        pass

    def reset(self):
        """Reference: reset dynamic offset (peer_memory.py:40)."""
        self._dynamic_allocated = 0

    def allocate_peer_tensors(self, shape, dtype, channels_last,
                              dynamic):
        """Returns one zeroed buffer per peer rank (reference returns a
        list of mapped peer tensors, peer_memory.py:50-80)."""
        n = len(self.peer_ranks) if self.peer_ranks is not None else 1
        size = int(np.prod(shape))
        if dynamic:
            self._dynamic_allocated += size * jnp.dtype(dtype).itemsize
        return [jnp.zeros(tuple(shape), dtype) for _ in range(n)]
