"""Peer-memory pool — arena accounting over ICI neighbor transfer.

Behavioral port of apex/contrib/peer_memory/peer_memory.py:1-90 (backed
there by ``peer_memory_cuda``, 709 LoC). The reference carves fp16/fp32/
int32 views out of one raw CUDA allocation whose pointer is IPC-mapped
into every sibling process, so halo pushes write straight into a
neighbor's HBM. On TPU there is no process-addressable peer memory: the
kernel-bypass neighbor push is what ``lax.ppermute`` compiles to (direct
ICI DMA), and XLA owns all device allocation under jit.

What this class keeps from the reference is everything that is *not* the
CUDA mapping — the arena bookkeeping that ported callers depend on:

* a static region (signal flags, long-lived buffers) and a dynamic
  region (per-iteration halo staging), each rounded up to the 256-byte
  alignment (reference :23-25);
* per-allocation offset bump with 256-byte alignment and exhaustion
  asserts carrying the reference's messages (:50-63) — including the
  reference's exact edge semantics: the bound check is strict ``<`` (an
  allocation that exactly fills a region trips the assert) and the
  offset is bumped *before* the assert (a failed static allocation is
  not rewound; ``reset()`` rewinds only the dynamic region);
* ``reset()`` rewinding only the dynamic offset (:45-46);
* peer-rank group validation (:19-21);
* the fp16 / fp32 / int32 dtype whitelist (:51-89), extended with
  bfloat16 — the dtype halo buffers actually carry on TPU.

``allocate_peer_tensors`` returns one zeroed device array per peer rank
(the reference returns mapped views of each peer's arena); the "peer"
transfer itself is realized by the collectives that consume the buffers
(see PeerHaloExchanger1d and contrib.bottleneck.halo_exchangers).
"""

import jax
import jax.numpy as jnp
import numpy as np

_SUPPORTED = tuple(
    jnp.dtype(s) for s in (jnp.float16, jnp.float32, jnp.int32,
                           jnp.bfloat16))


def _align_up(nbytes, alignment):
    return ((nbytes + alignment - 1) // alignment) * alignment


class PeerMemoryPool:
    """Reference ctor: peer_memory.py:7 (static_size, dynamic_size,
    peer_ranks). Sizes are in bytes, as in the reference."""

    alignment = 256

    def __init__(self, static_size, dynamic_size, peer_ranks=None,
                 rank=None, peer_group_size=None):
        # sizes are required, as in the reference — a 0-byte region
        # rejects every allocation (the strict-< bound), so an unsized
        # pool would be a silent footgun rather than a compat surface
        self.static_size = _align_up(static_size, self.alignment)
        self.dynamic_size = _align_up(dynamic_size, self.alignment)
        if peer_ranks is not None:
            # reference peer_memory.py:19-21 — peers must sit in this
            # rank's node-local group; the reference derives the group
            # size from the node's device count, so do the same when
            # the caller doesn't pass one
            if peer_group_size is None:
                peer_group_size = jax.local_device_count()
            if rank is None:
                # reference: torch.distributed.get_rank(); the global
                # device-rank of this process's first local device
                rank = jax.process_index() * jax.local_device_count()
            base = (rank // peer_group_size) * peer_group_size
            for pr in peer_ranks:
                if not base <= pr < base + peer_group_size:
                    raise AssertionError(
                        "%d :: peer_rank %d not on same node (ranks=[%d,%d])"
                        % (rank, pr, base, base + peer_group_size - 1))
        self.peer_ranks = peer_ranks
        self.static_offset = 0
        self.dynamic_offset = 0

    def __del__(self):
        pass  # reference frees the raw CUDA arena; XLA owns ours

    def reset(self):
        """Rewind the dynamic region only (reference peer_memory.py:45)."""
        self.dynamic_offset = 0

    def allocate_peer_tensors(self, shape, dtype, channels_last, dynamic):
        """Carve one buffer per peer rank out of the arena.

        Mirrors reference peer_memory.py:48-89: align the region offset
        to 256, bump it by the buffer's byte size, assert on exhaustion.
        ``channels_last`` is accepted for call compatibility (layout is
        XLA's concern on TPU).
        """
        dt = jnp.dtype(dtype)
        if dt not in _SUPPORTED:
            raise AssertionError("dtype %s not supported" % (dtype,))
        nbytes = int(np.prod(shape)) * dt.itemsize
        if dynamic:
            start = _align_up(self.dynamic_offset, self.alignment)
            self.dynamic_offset = start + nbytes
            assert self.dynamic_offset < self.dynamic_size, \
                "Dynamic peer memory pool exhausted"
        else:
            start = _align_up(self.static_offset, self.alignment)
            self.static_offset = start + nbytes
            assert self.static_offset < self.static_size, \
                "Static peer memory pool exhausted"
        n = len(self.peer_ranks) if self.peer_ranks is not None else 1
        return [jnp.zeros(tuple(shape), dt) for _ in range(n)]
