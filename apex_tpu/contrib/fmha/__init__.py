"""apex_tpu.contrib.fmha (reference: apex/contrib/fmha)."""

from apex_tpu.contrib.fmha.fmha import FMHA, FMHAFun, fmha_varlen  # noqa: F401
