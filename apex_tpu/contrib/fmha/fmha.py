"""Fused multi-head attention for variable-length sequences (MLPerf BERT).

Capability port of apex/contrib/fmha/fmha.py:33-90 over ``fmhalib``
(6,958 LoC CUDA: fused QKV attention for seq ≤ 512, varlen batches packed
as [total_tokens, 3, h, d] + cu_seqlens prefix offsets).

TPU design: varlen packing exists to avoid padding waste on GPUs; on TPU
the same effect comes from segment-id masking — the packed token stream
stays packed, and attention is computed blockwise with a segment mask so
tokens only attend within their own sequence. Both training and eval
route through fused kernels at lane-aligned totals: eval through
``apex_tpu.ops.fused_attention``, dropout training through the VMEM-row
kernel's in-kernel counter-hash dropout (replayed exactly in backward,
mirroring fmhalib's Philox-offset replay — reference fmha.py:33-61), so
the [total, total] probability matrix never reaches HBM in either mode.
The dense computation below survives only as the odd-shape fallback.
"""

import os

import jax
import jax.numpy as jnp

from apex_tpu.utils import train_dropout
import numpy as np
from flax import linen as nn
from jax import lax

def dropout_impl():
    """Dropout-training kernel preference, read at TRACE time (the
    APX001 rule — the import-time read this replaced froze the knob
    before a test or autotune subprocess could vary it): "fused"
    (in-kernel hash dropout — the default, on the memory-capability
    argument documented at the call site) or "dense" (materialized
    probs + jax.random dropout — the escape hatch while the device
    speed A/B is queued). An invalid value still raises, at first
    use: the escape hatch is an explicit request, not a preference."""
    impl = os.environ.get("APEX_FMHA_DROPOUT", "fused")
    if impl not in ("fused", "dense"):
        raise ValueError(f"APEX_FMHA_DROPOUT={impl!r} "
                         "(expected 'fused' or 'dense')")
    return impl


def _segment_ids_from_cu_seqlens(cu_seqlens, total):
    """[total] segment id per packed token; cu_seqlens [b+1] prefix sums.
    Tokens at/past cu_seqlens[-1] (padding) get id == num_seqs, which the
    caller must treat as invalid."""
    # token i belongs to segment = #(cu_seqlens[1:] <= i)
    return jnp.sum(jnp.arange(total)[:, None]
                   >= cu_seqlens[None, 1:], axis=-1)


def fmha_varlen(qkv, cu_seqlens, p_dropout=0.0, max_s=512,
                is_training=True, zero_tensors=False, rng=None):
    """Packed varlen attention (reference: FMHAFun.forward fmha.py:33-47).

    qkv: [total, 3, h, d]; cu_seqlens: [b+1] int32. Returns [total, h, d].
    """
    total, three, h, d = qkv.shape
    assert three == 3
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # [total, h, d]

    seg = _segment_ids_from_cu_seqlens(cu_seqlens, total)
    num_seqs = cu_seqlens.shape[0] - 1
    valid = seg < num_seqs  # tokens at/past cu_seqlens[-1] are padding
    # padding gets a sentinel id no real token shares → fully masked rows
    seg = jnp.where(valid, seg, num_seqs + 1)

    if p_dropout == 0.0 or not is_training:
        # flash path: packed stream as one [1, h, total, d] sequence with
        # segment-id masking (TPU Pallas kernel; dense fallback elsewhere)
        from apex_tpu.ops import fused_attention

        ctx = fused_attention(
            q.transpose(1, 0, 2)[None], k.transpose(1, 0, 2)[None],
            v.transpose(1, 0, 2)[None],
            sm_scale=1.0 / np.sqrt(d),
            segment_ids=(seg[None], seg[None]))
        return ctx[0].transpose(1, 0, 2).astype(qkv.dtype)

    from apex_tpu.ops import attention_pallas

    if rng is None:
        raise ValueError("dropout requires an rng key")
    if (dropout_impl() == "fused"
            and attention_pallas.supported(total, total, d, dropout=True)):
        # fused dropout-training path: probability dropout happens INSIDE
        # the VMEM-row kernel (counter-hash mask, replayed in backward),
        # so the [total, total] attention matrix never reaches HBM — the
        # capability fmhalib's Philox-offset replay provides on GPU
        # (reference apex/contrib/fmha/fmha.py:33-61). The default is the
        # memory-capability argument (at MLPerf packing the dense probs
        # are the HBM blow-up fmhalib exists to avoid); the device speed
        # A/B (profile_attention.py dropout rows) is queued — PERF.md §7.
        # The dense path below remains as the odd-shape fallback and the
        # APEX_FMHA_DROPOUT=dense escape hatch.
        seed = jax.random.randint(rng, (1, 1), -2**31, 2**31 - 1, jnp.int32)
        interpret = jax.devices()[0].platform == "cpu"
        ctx = attention_pallas.fused_attention_rows(
            q.transpose(1, 0, 2)[None], k.transpose(1, 0, 2)[None],
            v.transpose(1, 0, 2)[None], False, 1.0 / np.sqrt(d),
            (seg[None], seg[None]), interpret, None, None,
            float(p_dropout), seed)
        return ctx[0].transpose(1, 0, 2).astype(qkv.dtype)

    same_seg = (seg[:, None] == seg[None, :]) & valid[:, None] \
        & valid[None, :]

    scale = 1.0 / np.sqrt(d)
    # [h, total, total] scores, fp32 accumulation on the MXU
    scores = lax.dot_general(
        (q * scale).transpose(1, 0, 2), k.transpose(1, 0, 2),
        (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32)
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, jnp.float32)
    scores = jnp.where(same_seg[None], scores, neg)
    probs = jax.nn.softmax(scores, axis=-1)
    probs = jnp.where(same_seg[None], probs, 0.0).astype(qkv.dtype)

    if is_training and p_dropout > 0.0:
        if rng is None:
            raise ValueError("dropout requires an rng key")
        probs = train_dropout(rng, probs, p_dropout)

    ctx = lax.dot_general(probs, v.transpose(1, 0, 2),
                          (((2,), (1,)), ((0,), (0,))),
                          preferred_element_type=jnp.float32)
    return ctx.transpose(1, 0, 2).astype(qkv.dtype)  # [total, h, d]


class FMHAFun:
    """apply-surface of the reference autograd Function (fmha.py:33)."""

    @staticmethod
    def apply(qkv, cu_seqlens, p_dropout, max_s, is_training, zero_tensors,
              rng=None):
        return fmha_varlen(qkv, cu_seqlens, p_dropout, max_s, is_training,
                           zero_tensors, rng)


class FMHA(nn.Module):
    """Module surface (reference: fmha.py:63-90; config carries
    num_attention_heads / hidden_size / attention_probs_dropout_prob)."""

    num_attention_heads: int
    hidden_size: int
    attention_probs_dropout_prob: float = 0.0

    @nn.compact
    def __call__(self, qkv, cu_seqlens, max_s, is_training=True,
                 zero_tensors=False):
        h = self.num_attention_heads
        d = self.hidden_size // h
        assert d * h == self.hidden_size, "Invalid hidden size/num_heads"
        rng = (self.make_rng("dropout")
               if is_training and self.attention_probs_dropout_prob > 0
               else None)
        ctx = fmha_varlen(qkv.reshape(-1, 3, h, d), cu_seqlens,
                          self.attention_probs_dropout_prob, max_s,
                          is_training, zero_tensors, rng)
        return ctx.reshape(-1, self.hidden_size)
