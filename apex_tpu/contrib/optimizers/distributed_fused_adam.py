"""DistributedFusedAdam — ZeRO-2 sharded Adam.

Capability port of apex/contrib/optimizers/distributed_fused_adam.py:76
(1,426 LoC Python + 2,448 LoC CUDA): params flattened into a contiguous
buffer, optimizer state + reduced gradients sharded over the data-parallel
ranks, gradient sync by reduce-scatter overlapped with backward, updated
shards re-assembled by all-gather.

TPU-native shape — the whole algorithm is three collectives around flat
math, inside ``shard_map`` over the dp axis:

    flat grads ──psum_scatter──► my grad shard        (ZeRO grad sync)
    my (m, v, master) shard ──adam──► my update shard (1/N state memory)
    my update shard ──all_gather──► full flat update  (ZeRO param sync)

The reference's overlap machinery (dwu_num_blocks/chunks double-buffering,
side streams, pipeline hooks) is XLA's latency-hiding scheduler's job and
the knobs are accepted as documented no-ops. The "distributed×redundant
process grid" (dwu_group_size) maps to ``axis_index_groups`` if sub-axis
sharding is ever needed; default shards over the full axis.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from apex_tpu.optimizers._fused import (
    get_meta,
    zero_ef_residuals,
    zero_gather_updates,
    zero_grad_shard,
    zero_master_shard,
    zero_padded_total,
)
from apex_tpu.optimizers.fused_adam import _adam_flat
from apex_tpu.parallel import collectives


class DistAdamState(NamedTuple):
    count: jnp.ndarray
    m: jnp.ndarray       # [padded_total / num_shards] fp32, THIS rank's shard
    v: jnp.ndarray
    master: jnp.ndarray  # fp32 master copy of this rank's param shard
    # error-feedback residuals of the quantized collective hops
    # (apex_tpu.parallel.collectives; None — an empty pytree slot, so
    # the state stays leaf-identical to the 4-field layout — whenever
    # compression is off)
    g_residual: jnp.ndarray = None   # grad reduce-scatter send error
    u_residual: jnp.ndarray = None   # update all-gather send error


def distributed_fused_adam(learning_rate=1e-3, betas=(0.9, 0.999), eps=1e-8,
                           weight_decay=0.0, adam_w_mode=True,
                           bias_correction=True, max_grad_norm=0.0, *,
                           num_shards, axis_name="dp", grad_average=True,
                           grad_compress=None, hier_allreduce=None):
    """optax-style ZeRO-2 Adam for use INSIDE shard_map over ``axis_name``
    (a mesh-axis name, or an (inner, outer) pair for the staged
    hierarchical collectives).

    ``num_shards`` must equal the mesh axis size (static — shard shapes
    depend on it). Gradients passed to ``update`` are the LOCAL grads;
    the transform performs the cross-replica reduction itself (do NOT
    pre-pmean them — that is this optimizer's job, like the reference DDP
    interplay, distributed_fused_adam.py:76-120).

    ``grad_compress``/``hier_allreduce`` are the per-call knob forms
    (raise on un-honorable requests); None consults the process-wide
    ``collectives`` setters / ``APEX_GRAD_COMPRESS`` /
    ``APEX_HIER_ALLREDUCE``. Resolution happens ONCE, here — the state
    layout (error-feedback residual slots) must agree between ``init``
    and every ``update``.
    """
    beta1, beta2 = betas
    scheme = collectives.resolve_compress(grad_compress)
    hier = collectives.resolve_hier(hier_allreduce,
                                    collectives.axes_tuple(axis_name))
    _compress = scheme if scheme is not None else False

    def init(params):
        leaves = jax.tree_util.tree_leaves(params)
        meta = get_meta(leaves)
        master = zero_master_shard(meta, leaves, num_shards, axis_name)
        shard = master.shape[0]
        g_res = u_res = None
        if scheme is not None:
            g_res, u_res = zero_ef_residuals(meta.total, num_shards,
                                             axis_name, hier)
        return DistAdamState(
            count=jnp.zeros((), jnp.int32),
            m=jnp.zeros((shard,), jnp.float32),
            v=jnp.zeros((shard,), jnp.float32),
            master=master,
            g_residual=g_res,
            u_residual=u_res,
        )

    def update(grads, state, params=None):
        assert params is not None
        leaves_g, treedef = jax.tree_util.tree_flatten(grads)
        leaves_p = jax.tree_util.tree_leaves(params)
        meta = get_meta(leaves_p)

        # ZeRO grad sync: reduce-scatter (sum) → my shard
        g_shard, g_res = zero_grad_shard(
            meta, leaves_g, num_shards, axis_name, compress=_compress,
            hierarchical=hier, residual=state.g_residual)
        if grad_average:
            g_shard = g_shard / num_shards

        # global grad-norm clip on the reduced grads (reference:
        # max_grad_norm handling in distributed_fused_adam.py step)
        if max_grad_norm is not None and max_grad_norm > 0:
            gnorm = jnp.sqrt(lax.psum(jnp.sum(g_shard * g_shard),
                                      axis_name))
            g_shard = g_shard / jnp.maximum(gnorm / max_grad_norm, 1.0)

        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) \
            else learning_rate
        upd_shard, m, v = _adam_flat(
            g_shard, state.master, state.m, state.v, count, lr, beta1,
            beta2, eps, weight_decay, adam_w_mode, bias_correction)
        master = state.master + upd_shard

        # ZeRO param sync: all-gather updated shards → full flat update
        upd_leaves, u_res = zero_gather_updates(
            meta, upd_shard, axis_name, [x.dtype for x in leaves_p],
            compress=_compress, hierarchical=hier,
            residual=state.u_residual)
        updates = jax.tree_util.tree_unflatten(treedef, upd_leaves)
        return updates, DistAdamState(count=count, m=m, v=v, master=master,
                                      g_residual=g_res, u_residual=u_res)

    return optax.GradientTransformation(init, update)


class DistributedFusedAdam:
    """Reference class surface (distributed_fused_adam.py:76). Accepts the
    CUDA overlap/tuning kwargs as documented no-ops."""

    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, eps_inside_sqrt=False,
                 weight_decay=0.0, max_grad_norm=0.0, amsgrad=False,
                 flat_mt=False, overlap_reductions=True,
                 compute_L2_grad_norm=False, distributed_weight_update=0,
                 dwu_group_size=0, dwu_num_blocks=4, dwu_num_rs_pg=1,
                 dwu_num_ar_pg=4, dwu_num_ag_pg=0, dwu_num_chunks=4,
                 revert_method=1, full_pipeline=True, e5m2_allgather=False,
                 *, num_shards, axis_name="dp", grad_compress=None,
                 hier_allreduce=None):
        assert not amsgrad, "amsgrad is not supported (as in the reference)"
        self.params = params
        self.tx = distributed_fused_adam(
            learning_rate=lr, betas=betas, eps=eps,
            weight_decay=weight_decay, bias_correction=bias_correction,
            adam_w_mode=False, max_grad_norm=max_grad_norm,
            num_shards=num_shards, axis_name=axis_name,
            grad_compress=grad_compress, hier_allreduce=hier_allreduce)
        self.state = None

    def init_params(self, params=None):
        """Reference pre-registration hook (distributed_fused_adam.py:
        509-534: builds state buckets for ``params`` — a subset is
        accepted, unknown params silently skipped). The functional port
        has nothing to pre-register: state covers the constructor's
        params and is created lazily by ``step()`` INSIDE the traced
        region (creating it here, outside, would either fail on the
        unbound dp axis or cache leaked tracers). Accepts and ignores
        ``params`` like the reference's default path and returns the
        current state (None before the first step)."""
        del params
        return self.state

    def init(self):
        self.state = self.tx.init(self.params)
        return self.state

    def step(self, grads):
        if self.state is None:
            self.init()
        updates, self.state = self.tx.update(grads, self.state, self.params)
        self.params = jax.tree_util.tree_map(
            lambda p, u: p + u.astype(p.dtype), self.params, updates)
        return self.params
