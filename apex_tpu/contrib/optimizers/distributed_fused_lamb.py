"""DistributedFusedLAMB — ZeRO-sharded LAMB (MLPerf BERT).

Capability port of apex/contrib/optimizers/distributed_fused_lamb.py:16
(986 LoC + CUDA): sharded LAMB with overlapped reductions, fused L2 norm,
optional compressed all-gather, ``full_ar`` vs reduce-scatter modes,
``clip_after_ar`` grad clipping placement.

TPU design mirrors distributed_fused_adam with LAMB's two extra global
reductions, both cheap on ICI:

  * global grad norm: local shard sum-of-squares → psum (the fused
    multi_tensor_l2norm + allreduce of the reference);
  * per-tensor trust ratios: segment-sum of the SHARDED flat buffers with
    the matching seg-id slice → psum — per-tensor norms come out exact
    even for tensors spanning shard boundaries, with no per-tensor
    bookkeeping (the reference needs a dedicated L2-norm kernel over
    block-partitioned buffers for this).

e5m2 compressed allgather: bf16 gather is the TPU analog knob
(``allgather_in_fp32=False``).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from apex_tpu.optimizers._fused import (
    get_meta,
    zero_ef_residuals,
    zero_gather_updates,
    zero_grad_shard,
    zero_master_shard,
    zero_padded_total,
)
from apex_tpu.parallel import collectives


class DistLambState(NamedTuple):
    count: jnp.ndarray
    m: jnp.ndarray
    v: jnp.ndarray
    master: jnp.ndarray
    # error-feedback residuals (see DistAdamState): None slots when
    # compression is off, so the 4-field leaf layout is preserved
    g_residual: jnp.ndarray = None
    u_residual: jnp.ndarray = None


def distributed_fused_lamb(learning_rate=1e-3, betas=(0.9, 0.999), eps=1e-6,
                           weight_decay=0.01, bias_correction=True,
                           adam_w_mode=True, grad_averaging=True,
                           max_grad_norm=1.0, use_nvlamb=False,
                           clip_after_ar=True, allgather_in_fp32=True, *,
                           num_shards, axis_name="dp", grad_compress=None,
                           hier_allreduce=None):
    """optax-style ZeRO LAMB for use INSIDE shard_map over ``axis_name``
    (name or (inner, outer) pair). Takes LOCAL grads; reduction is
    internal (see distributed_fused_adam — same per-call-raises /
    preference-falls-back knob contract, resolved once here so init
    and update agree on the residual slots)."""
    beta1, beta2 = betas
    scheme = collectives.resolve_compress(grad_compress)
    hier = collectives.resolve_hier(hier_allreduce,
                                    collectives.axes_tuple(axis_name))
    _compress = scheme if scheme is not None else False

    def init(params):
        leaves = jax.tree_util.tree_leaves(params)
        meta = get_meta(leaves)
        master = zero_master_shard(meta, leaves, num_shards, axis_name)
        shard = master.shape[0]
        g_res = u_res = None
        if scheme is not None:
            g_res, u_res = zero_ef_residuals(meta.total, num_shards,
                                             axis_name, hier)
        return DistLambState(
            count=jnp.zeros((), jnp.int32),
            m=jnp.zeros((shard,), jnp.float32),
            v=jnp.zeros((shard,), jnp.float32),
            master=master,
            g_residual=g_res,
            u_residual=u_res,
        )

    def update(grads, state, params=None):
        assert params is not None
        leaves_g, treedef = jax.tree_util.tree_flatten(grads)
        leaves_p = jax.tree_util.tree_leaves(params)
        meta = get_meta(leaves_p)
        P = zero_padded_total(meta.total, num_shards)
        shard = P // num_shards
        idx = collectives.axes_index(axis_name)

        g_shard, g_res = zero_grad_shard(
            meta, leaves_g, num_shards, axis_name, compress=_compress,
            hierarchical=hier, residual=state.g_residual)
        # cross-rank averaging is unconditional (grad_averaging only
        # selects LAMB's beta3, as in the reference)
        g_shard = g_shard / num_shards

        # sharded seg ids for per-tensor reductions (padding → segment N)
        seg_full = jnp.concatenate(
            [meta.seg_ids,
             jnp.full((P - meta.total,), meta.num_tensors, jnp.int32)])
        seg_shard = lax.dynamic_slice_in_dim(seg_full, idx * shard, shard)

        def psum_segments(vals):
            local = jax.ops.segment_sum(vals, seg_shard,
                                        num_segments=meta.num_tensors + 1)
            return lax.psum(local, axis_name)[:meta.num_tensors]

        # global grad-norm clip (clip_after_ar=True: on reduced grads —
        # reference distributed_fused_lamb.py "clip after allreduce")
        gnorm_sq = lax.psum(jnp.sum(g_shard * g_shard), axis_name)
        global_norm = jnp.sqrt(gnorm_sq)
        if max_grad_norm is not None and max_grad_norm > 0:
            clip = jnp.maximum(global_norm / max_grad_norm, 1.0)
            g_shard = g_shard / clip

        count = state.count + 1
        t = count.astype(jnp.float32)
        lr = learning_rate(count) if callable(learning_rate) \
            else learning_rate
        p = state.master
        beta3 = 1.0 - beta1 if grad_averaging else 1.0
        g_eff = g_shard if adam_w_mode else g_shard + weight_decay * p
        m = beta1 * state.m + beta3 * g_eff
        v = beta2 * state.v + (1.0 - beta2) * g_eff * g_eff
        if bias_correction:
            bc1 = 1.0 - beta1 ** t
            bc2 = 1.0 - beta2 ** t
        else:
            bc1 = bc2 = 1.0
        upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
        if adam_w_mode:
            upd = upd + weight_decay * p

        # exact per-tensor trust ratios from sharded buffers
        w_norm = jnp.sqrt(psum_segments(p * p))
        u_norm = jnp.sqrt(psum_segments(upd * upd))
        ratio = jnp.where((w_norm > 0) & (u_norm > 0),
                          w_norm / (u_norm + 1e-38), 1.0)
        if weight_decay == 0.0 and not use_nvlamb:
            ratio = jnp.ones_like(ratio)
        ratio_flat = jnp.concatenate(
            [ratio, jnp.ones((1,), jnp.float32)])[seg_shard]
        upd_shard = -lr * ratio_flat * upd
        master = p + upd_shard

        gather_dtype = jnp.float32 if allgather_in_fp32 else jnp.bfloat16
        upd_leaves, u_res = zero_gather_updates(
            meta, upd_shard, axis_name, [x.dtype for x in leaves_p],
            gather_dtype, compress=_compress, hierarchical=hier,
            residual=state.u_residual)
        updates = jax.tree_util.tree_unflatten(treedef, upd_leaves)
        return updates, DistLambState(count=count, m=m, v=v, master=master,
                                      g_residual=g_res, u_residual=u_res)

    return optax.GradientTransformation(init, update)


class DistributedFusedLAMB:
    """Reference class surface (distributed_fused_lamb.py:16); CUDA
    overlap/compression knobs accepted as documented no-ops."""

    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-6, eps_inside_sqrt=False,
                 weight_decay=0.01, max_grad_norm=1.0, adam_w_mode=True,
                 use_nvlamb=False, step_supports_amp_scaling=True,
                 overlap_reductions=True, dwu_group_size=0,
                 dwu_num_blocks=4, dwu_num_chunks=4, dwu_num_rs_pg=1,
                 dwu_num_ar_pg=4, dwu_num_ag_pg=0, fused_norm=False,
                 e5m2_allgather=False, verbose=False, clip_after_ar=True,
                 full_ar=False, set_param_views_to_flat_buffer=False,
                 skip_allgather=False, fuse_scale=False,
                 param_order=None, nccl_allgather_channels=0, *,
                 num_shards, axis_name="dp", grad_compress=None,
                 hier_allreduce=None):
        self.params = params
        self.tx = distributed_fused_lamb(
            learning_rate=lr, betas=betas, eps=eps,
            weight_decay=weight_decay, bias_correction=bias_correction,
            adam_w_mode=adam_w_mode, max_grad_norm=max_grad_norm,
            use_nvlamb=use_nvlamb, clip_after_ar=clip_after_ar,
            allgather_in_fp32=not e5m2_allgather, num_shards=num_shards,
            axis_name=axis_name, grad_compress=grad_compress,
            hier_allreduce=hier_allreduce)
        self.state = None

    def init(self):
        self.state = self.tx.init(self.params)
        return self.state

    def step(self, grads):
        if self.state is None:
            self.init()
        updates, self.state = self.tx.update(grads, self.state, self.params)
        self.params = jax.tree_util.tree_map(
            lambda p, u: p + u.astype(p.dtype), self.params, updates)
        return self.params
