"""apex_tpu.contrib.optimizers (reference: apex/contrib/optimizers).

ZeRO-sharded optimizers (DistributedFusedAdam/LAMB) plus the deprecated
earlier-generation fused optimizers kept for compat (reference:
contrib/optimizers/fused_*.py — aliases of the main tier here, exactly as
the reference kept old kernels behind the same names).
"""

from apex_tpu.contrib.optimizers.distributed_fused_adam import (  # noqa: F401
    DistributedFusedAdam,
    distributed_fused_adam,
)
from apex_tpu.contrib.optimizers.distributed_fused_lamb import (  # noqa: F401
    DistributedFusedLAMB,
    distributed_fused_lamb,
)

# deprecated compat aliases (reference: contrib/optimizers/fused_adam.py etc.)
from apex_tpu.optimizers.fused_adam import FusedAdam  # noqa: F401
from apex_tpu.optimizers.fused_lamb import FusedLAMB  # noqa: F401
from apex_tpu.optimizers.fused_sgd import FusedSGD  # noqa: F401
from apex_tpu.fp16_utils.fp16_optimizer import FP16_Optimizer  # noqa: F401
