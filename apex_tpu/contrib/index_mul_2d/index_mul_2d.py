"""Fused gather-multiply (point-cloud workloads).

Capability port of apex/contrib/index_mul_2d/index_mul_2d.py:5-120 over
``fused_index_mul_2d`` (617 LoC CUDA): ``out = in1[idx1] * in2`` with a
fused backward whose grad_in1 is a scatter-add (the CUDA kernel uses
atomics; XLA lowers the same to a sorted segment-sum on TPU).

Only dim-0 indexing of 2-D tensors, no broadcast — the kernel's contract.
The custom_vjp exists to pin the backward to gather/scatter-add (vs XLA
differentiating through take) and to keep grad_in1 accumulation fp32 for
fp16 inputs like ``half_scale_forward`` does.
"""

import jax
import jax.numpy as jnp


@jax.custom_vjp
def index_mul_2d(in1, in2, idx1):
    """out[i, :] = in1[idx1[i], :] * in2[i, :] (reference:
    IndexMul2d_.forward :12-49)."""
    assert in1.ndim == 2 and in2.ndim == 2, \
        "in1 and in2 must be 2-dimension tensor."
    assert idx1.ndim == 1, "idx1 must be 1-dimension tensor."
    assert in2.shape[0] == idx1.shape[0]
    return jnp.take(in1, idx1, axis=0) * in2


def _fwd(in1, in2, idx1):
    return index_mul_2d(in1, in2, idx1), (in1, in2, idx1)


def _bwd(res, grad_out):
    in1, in2, idx1 = res
    g = grad_out.astype(jnp.float32)
    gathered = jnp.take(in1, idx1, axis=0).astype(jnp.float32)
    grad_in2 = (gathered * g).astype(in2.dtype)
    # scatter-add in fp32 (the kernel's atomicAdd on a zeroed buffer)
    contrib = g * in2.astype(jnp.float32)
    grad_in1 = jnp.zeros(in1.shape, jnp.float32).at[idx1].add(contrib)
    return grad_in1.astype(in1.dtype), grad_in2, None


index_mul_2d.defvjp(_fwd, _bwd)
