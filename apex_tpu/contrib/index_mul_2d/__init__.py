"""apex_tpu.contrib.index_mul_2d (reference: apex/contrib/index_mul_2d)."""

from apex_tpu.contrib.index_mul_2d.index_mul_2d import index_mul_2d  # noqa: F401
