"""mlp.MLP — whole-MLP fused forward/backward.

Capability port of apex.mlp (reference: apex/mlp/mlp.py:12-87; CUDA
csrc/mlp_cuda.cu — chained cublas GEMMs with fused bias/activation
epilogues in one autograd Function). Under XLA the layer chain compiles to
exactly that (GEMM + fused epilogue per layer), so the module is the API:
``mlp_sizes`` like the reference, activation ∈ {none, relu, sigmoid}.
"""

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from apex_tpu.amp import policy as _policy

_ACTS = {
    "none": lambda x: x,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
}


def mlp_function(x, weights, biases, activation="relu"):
    """Functional N-layer MLP (reference: mlp.py:12-40 MlpFunction).

    ``weights[i]``: [out_i, in_i] (torch layout); activation applied to all
    layers except the last (matching mlp_cuda.forward).
    """
    if activation not in _ACTS:
        raise TypeError(f"activation must be relu or none or sigmoid, got {activation}")
    act = _ACTS[activation]
    dt = _policy.compute_dtype(x.dtype)
    h = x.astype(dt)
    n = len(weights)
    for i, (w, b) in enumerate(zip(weights, biases)):
        h = jax.lax.dot_general(
            h, w.astype(dt), (((h.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dt)
        if b is not None:
            h = h + b.astype(dt)
        if i < n - 1:
            h = act(h)
    return h


class MLP(nn.Module):
    """Module surface of apex.mlp.MLP (reference: mlp.py:43-87).

    ``mlp_sizes``: e.g. [in, hidden1, hidden2, out].
    """

    mlp_sizes: Sequence[int]
    bias: bool = True
    relu: bool = True  # legacy flag (reference kept it alongside activation)
    activation: str = "relu"
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        activation = self.activation if self.relu else "none"
        weights, biases = [], []
        for i in range(len(self.mlp_sizes) - 1):
            fan_in, fan_out = self.mlp_sizes[i], self.mlp_sizes[i + 1]
            w = self.param(f"weight_{i}", nn.initializers.lecun_normal(),
                           (fan_out, fan_in), self.param_dtype)
            weights.append(w)
            if self.bias:
                biases.append(self.param(f"bias_{i}", nn.initializers.zeros,
                                         (fan_out,), self.param_dtype))
            else:
                biases.append(None)
        return mlp_function(x, weights, biases, activation)
