"""apex_tpu.amp — automatic mixed precision for TPU.

Public surface mirrors apex.amp (reference: apex/amp/__init__.py:1-4):
``initialize``, ``scale_loss``, ``state_dict``/``load_state_dict``,
``register_{half,float,promote}_function`` — re-designed functionally:
dtype policies instead of monkey-patching, pytree scaler state instead of
stateful LossScaler objects.

ADR — amp legacy glue not ported (reference apex/amp/{opt,compat,
rnn_compat}.py, 536 LoC): those modules exist to patch Variable/Tensor
API splits of pre-1.0 torch (compat.py), to wrap the deprecated
``amp.half_function(torch.nn.RNN)`` eager-RNN internals (rnn_compat.py),
and to provide the ``OptimWrapper`` plumbing (opt.py) that upstream
itself deprecates in favor of ``amp.initialize``. None of these has a
JAX analog to patch — tracing makes namespace shims meaningless — and
the supported reference surface (``initialize``-based) is fully covered
here. Deliberately omitted, not deferred. The deprecated ``amp.init()``
handle ENTRY itself (amp.py:68) IS provided — ``init`` returns an
AmpHandle/NoOpHandle over the functional machinery (handle.py) — it is
only the monkey-patch registry behind it that has no analog.
"""

from apex_tpu.amp.frontend import (
    initialize,
    state_dict,
    load_state_dict,
    opt_levels,
    Properties,
    build_policy,
)
from apex_tpu.amp._amp_state import master_params
from apex_tpu.amp.scaler import LossScaler, LossScalerState
from apex_tpu.amp.amp_optimizer import AmpOptimizer, AmpOptState
from apex_tpu.amp.handle import (scale_loss, value_and_scaled_grad,
                                 disable_casts, AmpHandle, NoOpHandle,
                                 init)
from apex_tpu.amp.policy import (
    Policy,
    autocast,
    current_policy,
    compute_dtype,
    half_function,
    float_function,
    promote_function,
    register_half_function,
    register_float_function,
    register_promote_function,
    cast_for_op,
    lookup_cast,
    FP16_FUNCS,
    FP32_FUNCS,
    CASTS,
    SEQUENCE_CASTS,
    BANNED_FUNCS,
)
from apex_tpu.amp import _amp_state

__all__ = [
    "initialize", "state_dict", "load_state_dict", "opt_levels", "Properties",
    "build_policy", "LossScaler", "LossScalerState", "AmpOptimizer",
    "AmpOptState", "scale_loss", "value_and_scaled_grad", "disable_casts",
    "AmpHandle", "NoOpHandle", "init", "master_params",
    "Policy", "autocast", "current_policy", "compute_dtype", "half_function",
    "float_function", "promote_function", "register_half_function",
    "register_float_function", "register_promote_function", "cast_for_op",
    "lookup_cast",
]
