"""apex_tpu.amp — automatic mixed precision for TPU.

Public surface mirrors apex.amp (reference: apex/amp/__init__.py:1-4):
``initialize``, ``scale_loss``, ``state_dict``/``load_state_dict``,
``register_{half,float,promote}_function`` — re-designed functionally:
dtype policies instead of monkey-patching, pytree scaler state instead of
stateful LossScaler objects.
"""

from apex_tpu.amp.frontend import (
    initialize,
    state_dict,
    load_state_dict,
    opt_levels,
    Properties,
    build_policy,
)
from apex_tpu.amp.scaler import LossScaler, LossScalerState
from apex_tpu.amp.amp_optimizer import AmpOptimizer, AmpOptState
from apex_tpu.amp.handle import scale_loss, value_and_scaled_grad, disable_casts
from apex_tpu.amp.policy import (
    Policy,
    autocast,
    current_policy,
    compute_dtype,
    half_function,
    float_function,
    promote_function,
    register_half_function,
    register_float_function,
    register_promote_function,
    cast_for_op,
    lookup_cast,
    FP16_FUNCS,
    FP32_FUNCS,
    CASTS,
    SEQUENCE_CASTS,
    BANNED_FUNCS,
)
from apex_tpu.amp import _amp_state

__all__ = [
    "initialize", "state_dict", "load_state_dict", "opt_levels", "Properties",
    "build_policy", "LossScaler", "LossScalerState", "AmpOptimizer",
    "AmpOptState", "scale_loss", "value_and_scaled_grad", "disable_casts",
    "Policy", "autocast", "current_policy", "compute_dtype", "half_function",
    "float_function", "promote_function", "register_half_function",
    "register_float_function", "register_promote_function", "cast_for_op",
    "lookup_cast",
]
