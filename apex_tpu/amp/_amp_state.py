"""Module-level amp state (reference: apex/amp/_amp_state.py:18-69).

Holds the currently-selected Properties/Policy and verbosity. Unlike the
reference, no tensors live here — all numerical state is a pytree owned by
the caller (AmpOptState) so jit/pjit stay pure.
"""

import sys


class AmpState:
    def __init__(self):
        self.hard_override = False
        self.allow_incoming_model_not_fp32 = False
        self.verbosity = 1
        self.opt_properties = None
        self.policy = None
        self.loss_scalers = []
        self.optimizers = []


_amp_state = AmpState()
this = sys.modules[__name__]


def __getattr__(name):
    return getattr(_amp_state, name)


def warn_or_err(msg):
    if _amp_state.hard_override:
        print("Warning: " + msg)
    else:
        raise RuntimeError(msg)


def maybe_print(msg, verbosity=None, rank0=True):
    """Rank-0 gated print (reference: _amp_state.py:40-51)."""
    import jax

    v = verbosity if verbosity is not None else _amp_state.verbosity
    if v == 0:
        return
    try:
        if rank0 and jax.process_index() != 0:
            return
    except Exception:
        pass
    print(msg)
