"""Module-level amp state (reference: apex/amp/_amp_state.py:18-69).

Holds the currently-selected Properties/Policy and verbosity. Unlike the
reference, no tensors live here — all numerical state is a pytree owned by
the caller (AmpOptState) so jit/pjit stay pure.
"""

import sys


class AmpState:
    def __init__(self):
        self.hard_override = False
        self.allow_incoming_model_not_fp32 = False
        self.verbosity = 1
        self.opt_properties = None
        self.policy = None
        self.loss_scalers = []
        self.optimizers = []


_amp_state = AmpState()
this = sys.modules[__name__]


def __getattr__(name):
    return getattr(_amp_state, name)


def warn_or_err(msg):
    if _amp_state.hard_override:
        print("Warning: " + msg)
    else:
        raise RuntimeError(msg)


def maybe_print(msg, verbosity=None, rank0=True):
    """Rank-0 gated print (reference: _amp_state.py:40-51)."""
    import jax

    v = verbosity if verbosity is not None else _amp_state.verbosity
    if v == 0:
        return
    try:
        if rank0 and jax.process_index() != 0:
            return
    except Exception:
        pass
    print(msg)


def master_params(state, params=None):
    """The list of param leaves an optimizer steps (reference:
    _amp_state.py:60-69 iterates the optimizer's param groups — fp32
    masters under O2, the model params themselves under O1). Here the
    masters live in the ``AmpOptState`` pytree; when the opt level keeps
    no masters, pass the model ``params`` (the O1 caller owns them).
    Returns a list (a real pytree container — an iterator would be one
    opaque leaf to jax.tree_util). NB the functional clipping pattern
    clips GRADIENTS, not params: ``clip_grad_norm_(grads, max_norm)``
    (contrib/clip_grad); use master_params for norms/inspection of what
    the optimizer will step."""
    import jax

    masters = getattr(state, "master_params", None)
    if masters is None:
        masters = params
    if masters is None:
        # validate EAGERLY (a plain function returning a generator):
        # deferring this to first iteration would surface the misuse
        # deep inside the consumer, or never
        raise ValueError(
            "master_params: this opt level keeps no fp32 masters — pass "
            "the model params (master_params(state, params)); yielding "
            "nothing would silently no-op gradient clipping")
    return jax.tree_util.tree_leaves(masters)
