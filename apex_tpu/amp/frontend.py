"""amp frontend: opt-level presets, the ``Properties`` option struct, and
``initialize``.

Port of the semantics of apex/amp/frontend.py: ``Properties`` with
consistency checks in ``__setattr__`` (frontend.py:7-97), the O0–O3 presets
(:102-186), and ``initialize`` (:195). The TPU-native difference: instead of
mutating models/optimizers in place, ``initialize`` returns a cast parameter
pytree and an ``AmpOptimizer`` wrapper (functional master-weight + loss-scale
+ skip-step semantics, replacing _initialize.py/_process_optimizer.py's
monkey-patching).

TPU note on "fp16": the half dtype is configurable (``half_dtype``). bf16 is
the MXU-native choice and needs no loss scaling in practice, but fp16 +
dynamic scaling is kept available for numerical-parity runs with the
reference; O-level presets use bf16 by default.
"""

import jax
import jax.numpy as jnp

from apex_tpu.amp import _amp_state
from apex_tpu.amp.policy import Policy
from apex_tpu.amp.scaler import LossScaler
from apex_tpu.amp.amp_optimizer import AmpOptimizer


class Properties(object):
    """Option struct with mutual-consistency logic in ``__setattr__``
    (reference: apex/amp/frontend.py:7-97)."""

    def __init__(self):
        self.options = {
            "enabled": False,
            "opt_level": None,
            "cast_model_type": None,
            "patch_torch_functions": False,
            "keep_batchnorm_fp32": None,
            "master_weights": None,
            "loss_scale": 1.0,
            "half_dtype": jnp.bfloat16,  # TPU extension: which half type
            # reference kwarg parity (frontend.py:203); advisory here —
            # functional models return outputs directly
            "cast_model_outputs": None,
        }

    def _update_options_dict(self, new_options):
        for k, v in new_options.items():
            if k in self.options:
                self.options[k] = v
            else:
                raise ValueError(f"Tried to set unexpected option {k}")

    def __getattr__(self, name):
        if "options" in self.__dict__:
            options = self.__dict__["options"]
            if name in options:
                return options[name]
        raise AttributeError(f"'Properties' object has no attribute '{name}'")

    def __setattr__(self, name, value):
        if "options" in self.__dict__:
            if name not in self.options:
                raise AttributeError(f"Tried to set unexpected option {name}")
            # consistency checks mirroring frontend.py:33-93
            if name == "cast_model_type":
                if self.opt_level == "O1" and value is not None:
                    if value is not False and value != jnp.float32:
                        raise RuntimeError(
                            "O1 inserts casts around functions rather than "
                            "casting the model."
                        )
                self.options[name] = value
            elif name == "patch_torch_functions":
                if self.opt_level != "O1" and value:
                    raise RuntimeError(
                        "Currently, patch_torch_functions=True should only be "
                        "set by selecting opt_level='O1'."
                    )
                self.options[name] = value
            elif name == "keep_batchnorm_fp32":
                if self.opt_level == "O1" and value is not None:
                    raise RuntimeError(
                        "With opt_level O1, batchnorm functions are "
                        "automatically patched to run in fp32, so "
                        "keep_batchnorm_fp32 should be None."
                    )
                if value == "False":
                    self.options[name] = False
                elif value == "True":
                    self.options[name] = True
                else:
                    assert value in (True, False, None), (
                        "keep_batchnorm_fp32 must be a boolean, the string "
                        f"'True' or 'False', or None, found {value}"
                    )
                    self.options[name] = value
            elif name == "master_weights":
                if self.opt_level == "O1" and value is not None:
                    raise RuntimeError(
                        "It doesn't make sense to use master_weights with O1."
                    )
                self.options[name] = value
            elif name == "loss_scale":
                if value == "dynamic":
                    self.options[name] = value
                else:
                    self.options[name] = float(value)
            else:
                self.options[name] = value
        else:
            super().__setattr__(name, value)


class O3:
    """Pure half. 'Speed of light' ceiling (frontend.py:102-122)."""

    brief = "O3: Pure half-precision (speed-of-light ceiling)."

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O3"
        properties.cast_model_type = "half"
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = False
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


class O2:
    """Half model + fp32 master weights + dynamic scaling (frontend.py:124)."""

    brief = "O2: half casting of the model, with FP32 master weights."

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O2"
        properties.cast_model_type = "half"
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = True
        properties.master_weights = True
        properties.loss_scale = "dynamic"
        return properties


class O1:
    """Policy-driven op casting (the patch-engine analog), dynamic scaling
    (frontend.py:147)."""

    brief = "O1: insert automatic casts around safe ops (dtype policy)."

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O1"
        properties.cast_model_type = None
        properties.patch_torch_functions = True
        properties.keep_batchnorm_fp32 = None
        properties.master_weights = None
        properties.loss_scale = "dynamic"
        return properties


class O0:
    """Pure fp32 baseline (frontend.py:169)."""

    brief = "O0: Pure FP32 training."

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O0"
        properties.cast_model_type = jnp.float32
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = None
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


opt_levels = {"O3": O3(), "O2": O2(), "O1": O1(), "O0": O0()}


def _default_bn_predicate(path):
    """Heuristic BN detection over a flax param path (keep_batchnorm_fp32)."""
    joined = "/".join(str(p) for p in path).lower()
    return any(tag in joined for tag in ("batchnorm", "batch_norm", "bn_", "/bn", "batchstats", "batch_stats"))


def _cast_params(params, dtype, keep_bn_fp32, bn_predicate):
    """convert_network analog (reference: apex/fp16_utils/fp16util.py via
    _initialize.py:176-182): cast floating params, keeping BN params fp32."""

    def cast(path, leaf):
        if not (hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating)):
            return leaf
        if keep_bn_fp32 and bn_predicate(path):
            return leaf.astype(jnp.float32)
        return leaf.astype(dtype)

    return jax.tree_util.tree_map_with_path(cast, params)


def build_policy(properties):
    """Map a Properties struct to the functional dtype Policy.

    ``cast_model_type`` overrides the model dtype when set to a concrete
    dtype (reference allows e.g. cast_model_type=torch.float16 with any
    opt_level, frontend.py:195-210).
    """
    half = properties.half_dtype
    cmt = properties.cast_model_type
    if cmt not in (None, "half", False):
        half = jnp.dtype(cmt)
        if half == jnp.float32:
            return Policy()
        return Policy(param_dtype=half, compute_dtype=half,
                      output_dtype=jnp.float32,
                      keep_batchnorm_fp32=properties.keep_batchnorm_fp32
                      in (True, None),
                      )
    if properties.opt_level == "O3":
        return Policy(param_dtype=half, compute_dtype=half, output_dtype=half,
                      keep_batchnorm_fp32=False)
    if properties.opt_level == "O2":
        return Policy(param_dtype=half, compute_dtype=half, output_dtype=jnp.float32,
                      keep_batchnorm_fp32=bool(properties.keep_batchnorm_fp32))
    if properties.opt_level == "O1":
        return Policy(param_dtype=jnp.float32, compute_dtype=half,
                      output_dtype=jnp.float32, keep_batchnorm_fp32=True)
    return Policy()  # O0


def initialize(
    params,
    optimizer=None,
    opt_level="O1",
    cast_model_type=None,
    patch_torch_functions=None,
    keep_batchnorm_fp32=None,
    master_weights=None,
    loss_scale=None,
    num_losses=1,
    min_loss_scale=None,
    max_loss_scale=2.0 ** 24,
    half_dtype=None,
    bn_predicate=_default_bn_predicate,
    verbosity=1,
    cast_model_outputs=None,
):
    """Functional ``amp.initialize`` (reference: apex/amp/frontend.py:195-358).

    Args:
      params: parameter pytree (the "model") — returned cast per the policy.
      optimizer: an optax ``GradientTransformation`` (or list of them) to wrap
        with master-weight + loss-scale + skip-step semantics, or None.
      opt_level / overrides: as the reference; ``half_dtype`` selects
        bf16 (default) or fp16.
      num_losses / min_loss_scale / max_loss_scale: per-loss scalers
        (frontend.py:195-210).
      cast_model_outputs: accepted for reference-kwarg parity
        (frontend.py:203 — the patched forward casts outputs to this
        dtype). Functional models return values directly; wrap the model
        output yourself or rely on loss computation in fp32 (the policy's
        FP32 list covers losses). A non-None value is recorded on the
        Properties for introspection.

    Returns (cast_params, amp_optimizer) — or just cast_params if no
    optimizer given. Policy + properties are recorded in amp._amp_state.
    """
    if opt_level not in opt_levels:
        raise RuntimeError(f"Unexpected optimization level {opt_level}.")
    properties = opt_levels[opt_level](Properties())
    _amp_state.maybe_print(
        f"Selected optimization level {opt_level}: {opt_levels[opt_level].brief}",
        verbosity, True,
    )
    for name, value in (
        ("cast_model_type", cast_model_type),
        ("patch_torch_functions", patch_torch_functions),
        ("keep_batchnorm_fp32", keep_batchnorm_fp32),
        ("master_weights", master_weights),
        ("loss_scale", loss_scale),
        ("half_dtype", half_dtype),
        ("cast_model_outputs", cast_model_outputs),
    ):
        if value is not None:
            setattr(properties, name, value)

    policy = build_policy(properties)
    _amp_state.opt_properties = properties
    _amp_state.policy = policy
    _amp_state.verbosity = verbosity

    cast_params = params
    if policy.param_dtype != jnp.dtype(jnp.float32):
        cast_params = _cast_params(
            params, policy.param_dtype, policy.keep_batchnorm_fp32, bn_predicate
        )

    if optimizer is None:
        return cast_params

    scaler = LossScaler(
        loss_scale=properties.loss_scale,
        min_loss_scale=min_loss_scale,
        max_loss_scale=max_loss_scale,
    )
    # NB: optax.GradientTransformation is itself a NamedTuple — check for the
    # transform interface before treating the argument as a sequence.
    def _is_tx(o):
        return hasattr(o, "init") and hasattr(o, "update")

    single = _is_tx(optimizer)
    optimizers = [optimizer] if single else list(optimizer)
    wrapped = [
        AmpOptimizer(
            tx,
            scaler=scaler,
            num_losses=num_losses,
            master_weights=bool(properties.master_weights),
            param_dtype=policy.param_dtype,
        )
        for tx in optimizers
    ]
    _amp_state.loss_scalers = [scaler] * num_losses
    _amp_state.optimizers = wrapped
    return cast_params, (wrapped[0] if single else wrapped)


def state_dict(amp_opt_states=None, destination=None):
    """Persist per-scaler loss_scale + unskipped (frontend.py:361-370)."""
    states = amp_opt_states if amp_opt_states is not None else []
    out = {}
    i = 0
    for opt_state in states:
        for s in opt_state.scalers:
            out[f"loss_scaler{i}"] = {
                "loss_scale": jax.device_get(s.loss_scale).item(),
                "unskipped": jax.device_get(s.unskipped).item(),
            }
            i += 1
    return out


def load_state_dict(state_dict_in, amp_opt_states):
    """Restore per-scaler state (frontend.py:373-400). Returns new opt states."""
    import warnings

    n_saved = len(state_dict_in)
    n_here = sum(len(s.scalers) for s in amp_opt_states)
    if n_saved != n_here:
        warnings.warn(
            f"Loading state_dict containing {n_saved} loss_scalers into an "
            f"amp setup with {n_here} loss_scalers."
        )
    flat = [state_dict_in[k] for k in sorted(state_dict_in, key=lambda k: int(k.replace("loss_scaler", "")))]
    out = []
    i = 0
    for opt_state in amp_opt_states:
        new_scalers = []
        for s in opt_state.scalers:
            if i < len(flat):
                s = s.replace(
                    loss_scale=jnp.asarray(flat[i]["loss_scale"], jnp.float32),
                    unskipped=jnp.asarray(flat[i]["unskipped"], jnp.int32),
                )
            new_scalers.append(s)
            i += 1
        out.append(opt_state.replace(scalers=tuple(new_scalers)))
    return out
