"""Dynamic loss scaling as a pure pytree state machine.

Port of the semantics of apex.amp.scaler.LossScaler (reference:
apex/amp/scaler.py:33-217): static or dynamic scaling, init 2**16, x2 every
2000 unskipped steps, /2 on overflow, clamped to [min_loss_scale,
max_loss_scale]. The CUDA overflow sentinel (GPU-side ``_overflow_buf``,
scaler.py:105-117) becomes an on-device ``jnp.isfinite`` reduction fused into
the unscale, so a jitted train step never syncs the host to decide whether to
skip — the skip itself is a ``jnp.where`` select (the observable behaviour of
apex's one-shot patched ``skip_step``, apex/amp/handle.py:128-154).
"""

import dataclasses

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class LossScalerState:
    """The mutable part of a LossScaler, as a jit-safe pytree.

    ``loss_scale`` + ``unskipped`` are exactly the fields apex persists in
    ``amp.state_dict()`` (reference: apex/amp/frontend.py:361-370).
    """

    loss_scale: jnp.ndarray  # f32 scalar
    unskipped: jnp.ndarray  # i32 scalar — steps since last overflow
    overflow: jnp.ndarray  # bool scalar — last-step overflow flag


@dataclasses.dataclass(frozen=True)
class LossScaler:
    """Static config + pure transition functions.

    Reference ctor semantics: apex/amp/scaler.py:38-55. ``loss_scale`` is
    either a number (static) or "dynamic".
    """

    loss_scale: object = "dynamic"
    init_scale: float = 2.0 ** 16
    scale_factor: float = 2.0
    scale_window: int = 2000
    min_loss_scale: float = None
    max_loss_scale: float = 2.0 ** 24
    # overflow shrink multiplier; None → 1/scale_factor (apex always halves,
    # torch GradScaler exposes it independently as backoff_factor)
    backoff_factor: float = None

    @property
    def dynamic(self):
        return self.loss_scale == "dynamic"

    def init(self):
        scale = self.init_scale if self.dynamic else float(self.loss_scale)
        return LossScalerState(
            loss_scale=jnp.asarray(scale, jnp.float32),
            unskipped=jnp.asarray(0, jnp.int32),
            overflow=jnp.asarray(False),
        )

    # -- forward: loss scaling (apex/amp/handle.py:113 yields loss*scale) --
    def scale(self, loss, state):
        return loss.astype(jnp.float32) * state.loss_scale

    # -- backward: fused unscale + overflow detect (apex/amp/scaler.py:94-189) --
    def unscale(self, grads, state):
        """Returns (unscaled fp32 grads, found_inf). One fused pass; the
        isfinite reduction replaces amp_C's noop_flag.

        The unscaled result stays fp32 — apex unscales *into* fp32 master
        grads (_process_optimizer.py:161); casting back to fp16 here would
        flush small unscaled values to zero.
        """
        inv = 1.0 / state.loss_scale
        leaves = jax.tree_util.tree_leaves(grads)
        finite = jnp.array(True)
        for g in leaves:
            finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))
        unscaled = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) * inv, grads
        )
        return unscaled, ~finite

    def update(self, state, found_inf):
        """Scale-update state machine (apex/amp/scaler.py:197-217).

        On overflow: scale = max(scale*0.5, min_loss_scale), unskipped = 0.
        Else: unskipped += 1; at scale_window: scale = min(scale*2,
        max_loss_scale), unskipped = 0. Static scaling only tracks overflow.
        """
        if not self.dynamic:
            return LossScalerState(
                loss_scale=state.loss_scale,
                unskipped=state.unskipped,
                overflow=found_inf,
            )
        min_scale = self.min_loss_scale if self.min_loss_scale is not None else 0.0
        backoff = (self.backoff_factor if self.backoff_factor is not None
                   else 1.0 / self.scale_factor)
        shrunk = jnp.maximum(state.loss_scale * backoff, min_scale)
        unskipped = jnp.where(found_inf, 0, state.unskipped + 1)
        grow = unskipped == self.scale_window
        grown = jnp.minimum(state.loss_scale * self.scale_factor, self.max_loss_scale)
        new_scale = jnp.where(found_inf, shrunk, jnp.where(grow, grown, state.loss_scale))
        new_unskipped = jnp.where(grow, 0, unskipped)
        return LossScalerState(
            loss_scale=new_scale.astype(jnp.float32),
            unskipped=new_unskipped.astype(jnp.int32),
            overflow=found_inf,
        )

    def unscale_and_update(self, grads, state):
        """Convenience: unscale, update scale state, and report skip.

        Returns (grads, new_state, should_skip). Mirrors the scale_loss
        context-exit sequence (apex/amp/handle.py:118-154).
        """
        grads, found_inf = self.unscale(grads, state)
        new_state = self.update(state, found_inf)
        return grads, new_state, found_inf

    # -- telemetry provider (apex_tpu.telemetry.metrics) --
    @staticmethod
    def metrics(state):
        """The scaler's in-step telemetry scalars, as traced values.

        Pure and ungated — always returns the dict; the process-wide
        telemetry switch lives in the caller's ``telemetry.collect`` /
        ``telemetry.enabled()`` trace-time branch (the same explicit-
        request-vs-preference asymmetry as the kernel knobs)."""
        return {
            "loss_scale": state.loss_scale,
            "overflow": state.overflow,
            "unskipped": state.unskipped,
        }

    # -- persistence: apex/amp/frontend.py:361-400 --
    @staticmethod
    def state_dict(state):
        return {
            "loss_scale": state.loss_scale,
            "unskipped": state.unskipped,
        }

    @staticmethod
    def load_state_dict(state, d):
        return LossScalerState(
            loss_scale=jnp.asarray(d["loss_scale"], jnp.float32),
            unskipped=jnp.asarray(d["unskipped"], jnp.int32),
            overflow=state.overflow,
        )
