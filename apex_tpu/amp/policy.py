"""Dtype-policy interpreter — the TPU-native replacement for amp O1's
monkey-patch engine.

The reference patches torch namespaces with cast wrappers driven by
whitelist/blacklist tables (reference: apex/amp/amp.py:68-177,
apex/amp/lists/*). Under JAX, tracing makes namespace patching both fragile
and unnecessary: the same capability is a *policy* — (param_dtype,
compute_dtype, output_dtype) — consulted by apex_tpu layers, plus explicit
cast combinators (``half_function`` / ``float_function`` /
``promote_function``, reference: apex/amp/amp.py:30-42) for user functions.

A thread-local policy stack (``autocast``) plays the role of amp's global
"handle is active" state (apex/amp/_amp_state.py).
"""

import contextlib
import functools
import threading

import jax
import jax.numpy as jnp

# ---------------------------------------------------------------------------
# Cast lists — port of apex/amp/lists/{functional,torch,tensor}_overrides.py.
# Names are abstract op categories rather than torch symbols; apex_tpu layers
# look themselves up here so tests can assert the table drives behaviour.
# ---------------------------------------------------------------------------

# FP16 (half) list: matmul-class ops where reduced precision is safe and the
# MXU wants bf16 (reference: lists/functional_overrides.py:18-27,
# lists/torch_overrides.py:7-28).
FP16_FUNCS = {
    "conv1d", "conv2d", "conv3d", "conv_transpose1d", "conv_transpose2d",
    "conv_transpose3d", "conv_tbc", "linear", "matmul", "mm", "bmm", "addmm",
    "addbmm", "baddbmm", "dot", "einsum", "prelu", "mv", "dot_general",
}

# FP32 list: reductions / transcendentals / losses / norms
# (reference: lists/functional_overrides.py:29-69, torch_overrides.py:29-60).
FP32_FUNCS = {
    "softmax", "log_softmax", "gelu", "tanh", "sigmoid", "erf", "erfinv",
    "exp", "expm1", "log", "log10", "log2", "log1p", "cosh", "sinh", "acos",
    "asin", "atan", "reciprocal", "rsqrt", "pow", "norm", "prod", "sum",
    "cumsum", "cumprod", "mean", "var", "std", "renorm", "dist",
    "layer_norm", "group_norm", "batch_norm", "instance_norm",
    "nll_loss", "cross_entropy", "l1_loss", "mse_loss", "smooth_l1_loss",
    "kl_div", "poisson_nll_loss", "cosine_embedding_loss", "hinge_embedding_loss",
    "margin_ranking_loss", "multilabel_margin_loss", "soft_margin_loss",
    "triplet_margin_loss", "multi_margin_loss", "softmin", "softplus",
}

# Promote table: binary ops where the widest input dtype wins
# (reference: lists/torch_overrides.py CASTS).
CASTS = {
    "add", "addcdiv", "addcmul", "atan2", "cross", "bilinear", "div", "mul",
    "dot_product", "equal", "ge", "gt", "le", "lt", "ne", "sub", "true_divide",
}

# Sequence promotes: ops over tensor sequences (torch.cat/stack analog).
SEQUENCE_CASTS = {"cat", "stack", "concatenate"}

# Banned in half precision with a remediation message
# (reference: lists/functional_overrides.py:70 — binary_cross_entropy).
BANNED_FUNCS = {
    "binary_cross_entropy": (
        "apex_tpu.amp does not work out-of-the-box with binary_cross_entropy "
        "on half inputs. Use a sigmoid-fused cross entropy "
        "(optax.sigmoid_binary_cross_entropy) on fp32 logits, or decorate "
        "your loss with @amp.float_function."
    )
}


class Policy:
    """(param, compute, output) dtype triple + BN handling.

    The functional core of an O-level; built by ``amp.frontend.opt_levels``.
    """

    def __init__(
        self,
        param_dtype=jnp.float32,
        compute_dtype=jnp.float32,
        output_dtype=jnp.float32,
        keep_batchnorm_fp32=True,
        cast_inputs=None,
        enabled=True,
    ):
        self.param_dtype = jnp.dtype(param_dtype)
        self.compute_dtype = jnp.dtype(compute_dtype)
        self.output_dtype = jnp.dtype(output_dtype)
        self.keep_batchnorm_fp32 = keep_batchnorm_fp32
        self.cast_inputs = cast_inputs
        self.enabled = enabled

    def cast_to_compute(self, tree):
        return _cast_floating(tree, self.compute_dtype)

    def cast_to_param(self, tree):
        return _cast_floating(tree, self.param_dtype)

    def cast_to_output(self, tree):
        return _cast_floating(tree, self.output_dtype)

    def __repr__(self):
        return (
            f"Policy(param={self.param_dtype.name}, compute={self.compute_dtype.name}, "
            f"output={self.output_dtype.name}, keep_bn_fp32={self.keep_batchnorm_fp32})"
        )


def _is_floating(x):
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


def _cast_floating(tree, dtype):
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if _is_floating(x) else x, tree
    )


# ---------------------------------------------------------------------------
# Thread-local active-policy stack (the _amp_state analog)
# ---------------------------------------------------------------------------

_local = threading.local()


def _stack():
    if not hasattr(_local, "stack"):
        _local.stack = []
    return _local.stack


def current_policy():
    """The innermost active policy, or None outside any autocast region."""
    s = _stack()
    return s[-1] if s else None


def compute_dtype(default=jnp.float32):
    """Dtype apex_tpu layers should compute matmul-class ops in."""
    p = current_policy()
    if p is None or not p.enabled:
        return jnp.dtype(default)
    return p.compute_dtype


@contextlib.contextmanager
def autocast(policy=None, enabled=True, dtype=jnp.bfloat16):
    """Activate a policy for the dynamic extent (the O1 region analog).

    With no explicit policy, builds one computing matmuls in ``dtype``
    (bf16 — the MXU-native half type — by default; fp16 for parity runs).
    """
    if policy is None:
        policy = Policy(
            param_dtype=jnp.float32,
            compute_dtype=dtype,
            output_dtype=jnp.float32,
            enabled=enabled,
        )
    _stack().append(policy)
    try:
        yield policy
    finally:
        _stack().pop()


@contextlib.contextmanager
def disable_casts():
    """Reference: apex/amp/handle.py:163-167 — run a region in fp32 (used
    around optimizer steps in O1)."""
    p = current_policy()
    disabled = Policy(enabled=False) if p is None else Policy(
        param_dtype=p.param_dtype,
        compute_dtype=jnp.float32,
        output_dtype=p.output_dtype,
        keep_batchnorm_fp32=p.keep_batchnorm_fp32,
        enabled=False,
    )
    _stack().append(disabled)
    try:
        yield
    finally:
        _stack().pop()


# ---------------------------------------------------------------------------
# Cast combinators — apex/amp/wrap.py semantics, functional
# ---------------------------------------------------------------------------

def _widest_dtype(args):
    dtypes = [a.dtype for a in jax.tree_util.tree_leaves(args) if _is_floating(a)]
    if not dtypes:
        return None
    return functools.reduce(jnp.promote_types, dtypes)


def half_function(fn):
    """Run ``fn`` with floating inputs cast to the active compute dtype
    (reference: amp.half_function, apex/amp/amp.py:30; wrapper
    wrap.make_cast_wrapper, apex/amp/wrap.py:10-29)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        p = current_policy()
        if p is None or not p.enabled:
            return fn(*args, **kwargs)
        args, kwargs = _cast_floating((args, kwargs), p.compute_dtype)
        return fn(*args, **kwargs)

    wrapper.__amp_wrapped__ = "half"
    return wrapper


def float_function(fn):
    """Run ``fn`` in fp32 regardless of the active policy
    (reference: amp.float_function, apex/amp/amp.py:34)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        p = current_policy()
        if p is None or not p.enabled:
            return fn(*args, **kwargs)
        args, kwargs = _cast_floating((args, kwargs), jnp.float32)
        return fn(*args, **kwargs)

    wrapper.__amp_wrapped__ = "float"
    return wrapper


def promote_function(fn):
    """Cast all floating inputs to the widest participating dtype
    (reference: amp.promote_function, apex/amp/amp.py:38; wrap.promote,
    apex/amp/wrap.py:65)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        p = current_policy()
        if p is None or not p.enabled:
            return fn(*args, **kwargs)
        widest = _widest_dtype((args, kwargs))
        if widest is not None:
            args, kwargs = _cast_floating((args, kwargs), widest)
        return fn(*args, **kwargs)

    wrapper.__amp_wrapped__ = "promote"
    return wrapper


# user registries (reference: apex/amp/amp.py:46-64)
_user_registries = {"half": [], "float": [], "promote": []}


def register_half_function(module, name):
    setattr(module, name, half_function(getattr(module, name)))
    _user_registries["half"].append((module, name))


def register_float_function(module, name):
    setattr(module, name, float_function(getattr(module, name)))
    _user_registries["float"].append((module, name))


def register_promote_function(module, name):
    setattr(module, name, promote_function(getattr(module, name)))
    _user_registries["promote"].append((module, name))


def lookup_cast(op_name):
    """Which cast class an abstract op belongs to — the table-dispatch the
    patch engine performed at import time (apex/amp/amp.py:80-177)."""
    if op_name in BANNED_FUNCS:
        raise NotImplementedError(BANNED_FUNCS[op_name])
    if op_name in FP16_FUNCS:
        return "half"
    if op_name in FP32_FUNCS:
        return "float"
    if op_name in CASTS:
        return "promote"
    if op_name in SEQUENCE_CASTS:
        return "sequence_promote"
    return None


def cast_for_op(op_name, *args):
    """Cast ``args`` the way the O1 patch engine would for ``op_name``."""
    p = current_policy()
    if p is None or not p.enabled:
        return args
    kind = lookup_cast(op_name)
    if kind == "half":
        return _cast_floating(args, p.compute_dtype)
    if kind == "float":
        return _cast_floating(args, jnp.float32)
    if kind in ("promote", "sequence_promote"):
        widest = _widest_dtype(args)
        return _cast_floating(args, widest) if widest is not None else args
    return args
