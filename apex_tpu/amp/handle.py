"""Loss-scaling helpers — the functional analog of apex.amp.handle
(reference: apex/amp/handle.py:17-167).

The reference's ``with amp.scale_loss(loss, optimizer) as scaled:`` context
exists to interleave with eager autograd. Under JAX the backward is a
transform, so the same capability is a *grad-transformer*:

    value_and_scaled_grad(loss_fn, optimizer) returns a function computing
    (loss, unscaled_grads, found_inf) with scaling applied inside —
    everything the context manager + hooks achieved, in one jit-safe call.

``scale_loss`` itself is still provided for step-by-step parity use.
"""

import contextlib

import jax
import jax.numpy as jnp


def scale_loss(loss, amp_optimizer, state, loss_id=0):
    """Return the scaled loss (reference: handle.py:113 ``loss.float()*scale``)."""
    return amp_optimizer.scale_loss(loss, state, loss_id=loss_id)


def value_and_scaled_grad(loss_fn, amp_optimizer, loss_id=0, has_aux=False):
    """Build a jit-safe (loss, grads) function with loss scaling inside.

    ``loss_fn(params, *args)`` → scalar loss (optionally (loss, aux)).
    Returned fn: ``f(params, amp_state, *args)`` →
    ((loss, aux?), unscaled_fp32_grads, found_inf).

    Covers the whole scale→backward→unscale→overflow-check sequence of the
    reference's context exit (handle.py:118-154) minus the scale-state
    update, which `AmpOptimizer.apply_gradients` performs.
    """

    def scaled_loss_fn(params, amp_state, *args):
        out = loss_fn(params, *args)
        loss = out[0] if has_aux else out
        scaled = amp_optimizer.scale_loss(loss, amp_state, loss_id=loss_id)
        return scaled, (out[1] if has_aux else None, loss)

    grad_fn = jax.grad(scaled_loss_fn, has_aux=True)

    def f(params, amp_state, *args):
        grads, (aux, loss) = grad_fn(params, amp_state, *args)
        unscaled, found_inf = amp_optimizer.unscale(grads, amp_state, loss_id=loss_id)
        if has_aux:
            return (loss, aux), unscaled, found_inf
        return loss, unscaled, found_inf

    return f


@contextlib.contextmanager
def disable_casts():
    """Reference: handle.py:163-167."""
    from apex_tpu.amp import policy as _policy

    with _policy.disable_casts():
        yield


class AmpHandle:
    """Legacy handle object (reference: apex/amp/handle.py:22-160,
    returned by the deprecated ``amp.init()``). The reference handle
    owns the loss scaler and a cache of casted weights; here it wraps
    an ``(amp_optimizer, state)`` pair and exposes the same control
    surface. The ``scale_loss`` context yields the scaled loss; the
    caller differentiates it and passes the grads through
    ``amp_optimizer.apply_gradients`` as usual — single-controller JAX
    has no backward() side effect to hook.
    """

    def __init__(self, amp_optimizer=None, state=None, enable_caching=True,
                 verbose=False):
        self._amp_optimizer = amp_optimizer
        self._state = state
        self._cache = {}
        self._enable_caching = enable_caching
        self._verbose = verbose
        self._is_active = True

    def is_active(self):
        """Reference: handle.py:179 — a method, not a property."""
        return self._is_active

    @property
    def has_cache(self):
        return self._enable_caching

    @property
    def cache(self):
        return self._cache

    def remove_cache(self, param):
        if self._enable_caching and param in self._cache:
            del self._cache[param]

    @property
    def verbose(self):
        return self._verbose

    @property
    def state(self):
        return self._state

    def update_state(self, state):
        """Thread the latest AmpOptState into the handle. Dynamic loss
        scaling mutates the scale inside the state the caller threads
        through ``apply_gradients``; a handle holding the construction-
        time state would scale by a stale factor."""
        self._state = state
        return state

    @contextlib.contextmanager
    def scale_loss(self, loss, optimizer=None, loss_id=0, state=None):
        """Reference: handle.py:84-157. Yields loss * current scale.

        ``optimizer`` may carry the amp optimizer when the handle was
        built bare (the reference amp.init() pattern passes it per
        call); ``state`` overrides the handle's threaded state for this
        call."""
        if not self._is_active:
            yield loss
            return
        amp_opt = self._amp_optimizer
        if amp_opt is None and optimizer is not None and hasattr(
                optimizer, "scale_loss"):
            amp_opt = optimizer
        if amp_opt is None:
            raise RuntimeError(
                "AmpHandle has no amp optimizer: construct it as "
                "AmpHandle(amp_optimizer, state) or pass the wrapped "
                "optimizer to scale_loss — silently skipping loss "
                "scaling would underflow fp16 gradients")
        use_state = state if state is not None else self._state
        if use_state is None:
            raise RuntimeError(
                "AmpHandle has no amp state: pass state= or call "
                "update_state() with the state threaded through "
                "apply_gradients")
        yield scale_loss(loss, amp_opt, use_state, loss_id=loss_id)

    def wrap_optimizer(self, optimizer, num_loss=1):
        """Reference: handle.py:66-72 — here amp.initialize already
        returns the wrapped optimizer; passthrough for ported code."""
        return optimizer

    def _clear_cache(self):
        self._cache.clear()

    @contextlib.contextmanager
    def _disable_casts(self):
        """Reference: handle.py:183 — casts are policy-driven here."""
        from apex_tpu.amp import policy as _policy
        with _policy.disable_casts():
            yield

    def _deactivate(self):
        self._is_active = False


class NoOpHandle:
    """Reference: apex/amp/handle.py:250-281 — the disabled-amp handle."""

    has_cache = False
    verbose = False

    def is_active(self):
        return False

    @contextlib.contextmanager
    def scale_loss(self, loss, optimizer=None, loss_id=0, state=None):
        del optimizer, loss_id, state  # same surface as AmpHandle
        yield loss

    def wrap_optimizer(self, optimizer, num_loss=1):
        return optimizer

    @contextlib.contextmanager
    def _disable_casts(self):
        yield

    def _clear_cache(self):
        pass

    def _deactivate(self):
        pass


def init(enabled=True, loss_scale="dynamic", enable_caching=True,
         verbose=False, allow_banned=False):
    """Deprecated amp entry (reference: apex/amp/amp.py:68-96 — returns
    a handle; the modern path is ``amp.initialize``). Returns a
    NoOpHandle when disabled, else a bare AmpHandle: thread the wrapped
    optimizer/state in via ``AmpHandle.update_state`` /
    ``scale_loss(optimizer=...)`` (the reference's monkey-patch
    registry has no JAX analog — casts are policy-driven, see
    amp/policy.py). ``loss_scale``/``allow_banned`` are accepted for
    the reference signature; the scale lives in the optimizer state."""
    del allow_banned
    if loss_scale != "dynamic":
        import warnings
        warnings.warn(
            "amp.init(loss_scale=...) has no effect here: the loss scale "
            "lives in the optimizer state produced by amp.initialize "
            "(configure it there via LossScaler(loss_scale=...))",
            stacklevel=2)
    if not enabled:
        return NoOpHandle()
    return AmpHandle(enable_caching=enable_caching, verbose=verbose)
