"""Loss-scaling helpers — the functional analog of apex.amp.handle
(reference: apex/amp/handle.py:17-167).

The reference's ``with amp.scale_loss(loss, optimizer) as scaled:`` context
exists to interleave with eager autograd. Under JAX the backward is a
transform, so the same capability is a *grad-transformer*:

    value_and_scaled_grad(loss_fn, optimizer) returns a function computing
    (loss, unscaled_grads, found_inf) with scaling applied inside —
    everything the context manager + hooks achieved, in one jit-safe call.

``scale_loss`` itself is still provided for step-by-step parity use.
"""

import contextlib

import jax
import jax.numpy as jnp


def scale_loss(loss, amp_optimizer, state, loss_id=0):
    """Return the scaled loss (reference: handle.py:113 ``loss.float()*scale``)."""
    return amp_optimizer.scale_loss(loss, state, loss_id=loss_id)


def value_and_scaled_grad(loss_fn, amp_optimizer, loss_id=0, has_aux=False):
    """Build a jit-safe (loss, grads) function with loss scaling inside.

    ``loss_fn(params, *args)`` → scalar loss (optionally (loss, aux)).
    Returned fn: ``f(params, amp_state, *args)`` →
    ((loss, aux?), unscaled_fp32_grads, found_inf).

    Covers the whole scale→backward→unscale→overflow-check sequence of the
    reference's context exit (handle.py:118-154) minus the scale-state
    update, which `AmpOptimizer.apply_gradients` performs.
    """

    def scaled_loss_fn(params, amp_state, *args):
        out = loss_fn(params, *args)
        loss = out[0] if has_aux else out
        scaled = amp_optimizer.scale_loss(loss, amp_state, loss_id=loss_id)
        return scaled, (out[1] if has_aux else None, loss)

    grad_fn = jax.grad(scaled_loss_fn, has_aux=True)

    def f(params, amp_state, *args):
        grads, (aux, loss) = grad_fn(params, amp_state, *args)
        unscaled, found_inf = amp_optimizer.unscale(grads, amp_state, loss_id=loss_id)
        if has_aux:
            return (loss, aux), unscaled, found_inf
        return loss, unscaled, found_inf

    return f


@contextlib.contextmanager
def disable_casts():
    """Reference: handle.py:163-167."""
    from apex_tpu.amp import policy as _policy

    with _policy.disable_casts():
        yield
