"""AmpOptimizer — functional master-weight + loss-scale + skip-step wrapper.

This is the TPU-native re-design of apex's optimizer mutation
(reference: apex/amp/_process_optimizer.py:321 — ``_amp_stash`` injection,
lazy fp32 master copies, unscale-into-master backward hooks, patched
``step``/``zero_grad``) plus the scale_loss context's overflow handling
(apex/amp/handle.py:17-154). Instead of hooks, everything is one pure
``apply_gradients`` transition safe under ``jax.jit``:

    grads (wrt scaled loss, half) ──unscale──► fp32 ──tx.update──► master
    params'──cast──► model params', with the whole update select-gated on
    overflow (apex's one-shot ``skip_step``).

The fused unscale + isfinite is the multi_tensor_scale analog; the master →
model copy after step is _process_optimizer.py:353-364.
"""

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct

from apex_tpu.amp.scaler import LossScaler, LossScalerState


@struct.dataclass
class AmpOptState:
    inner: Any  # wrapped optax state
    master_params: Any  # fp32 master copies (None when master_weights=False)
    scalers: Tuple[LossScalerState, ...]  # one per loss (num_losses)


def _where_tree(pred, a, b):
    return jax.tree_util.tree_map(lambda x, y: jnp.where(pred, x, y), a, b)


@dataclasses.dataclass(frozen=True)
class AmpOptimizer:
    """Wraps an optax GradientTransformation with amp semantics.

    Usable directly, or via ``amp.initialize``. All methods are pure.
    """

    tx: optax.GradientTransformation
    scaler: LossScaler = LossScaler(loss_scale="dynamic")
    num_losses: int = 1
    master_weights: bool = False
    param_dtype: Any = jnp.float32

    def init(self, params):
        if self.master_weights:
            master = jax.tree_util.tree_map(
                lambda p: p.astype(jnp.float32)
                if jnp.issubdtype(p.dtype, jnp.floating) else p,
                params,
            )
        else:
            master = None
        inner = self.tx.init(master if master is not None else params)
        scalers = tuple(self.scaler.init() for _ in range(self.num_losses))
        return AmpOptState(inner=inner, master_params=master, scalers=scalers)

    # -- loss scaling (apex/amp/handle.py:113) --
    def scale_loss(self, loss, state, loss_id=0):
        return self.scaler.scale(loss, state.scalers[loss_id])

    def unscale(self, grads, state, loss_id=0):
        """Returns (unscaled fp32 grads, found_inf). Grad accumulation across
        calls is the caller's sum — the axpby stash path collapses to ``+``."""
        grads, found_inf = self.scaler.unscale(grads, state.scalers[loss_id])
        grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
        return grads, found_inf

    def update_scaler(self, state, found_inf, loss_id=0):
        """Advance ONE loss's dynamic-scale state without stepping.

        The reference updates each loss's scaler on its own
        ``scale_loss`` context exit (handle.py:118-154); when several
        backward passes share one ``apply_gradients`` (which only
        advances ``loss_id``'s scaler), the other losses' scalers must be
        advanced with this — otherwise an overflowing loss can never back
        its scale off."""
        new_sstate = self.scaler.update(state.scalers[loss_id], found_inf)
        scalers = tuple(new_sstate if i == loss_id else s
                        for i, s in enumerate(state.scalers))
        return state.replace(scalers=scalers)

    def apply_gradients(self, grads, state, params, loss_id=0,
                        grads_already_unscaled=False, found_inf=None,
                        scaler_found_inf=None):
        """One optimizer step with amp semantics.

        Args:
          grads: gradient pytree wrt the *scaled* loss (unless
            ``grads_already_unscaled``).
          state: AmpOptState. params: current (model-dtype) params.
          found_inf: the skip-step predicate (may OR several losses'
            flags when their backward passes share this step).
          scaler_found_inf: the flag that advances ``loss_id``'s dynamic
            scale — defaults to ``found_inf``; pass the loss's OWN
            overflow flag when ``found_inf`` is a combined one, so
            another loss's overflow never backs this loss's scale off.
        Returns (new_params, new_state, info dict with 'overflow' and
        'loss_scale').
        """
        sstate = state.scalers[loss_id]
        if grads_already_unscaled:
            assert found_inf is not None
            fp32_grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads)
        else:
            fp32_grads, found_inf = self.scaler.unscale(grads, sstate)
            fp32_grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), fp32_grads)
        new_sstate = self.scaler.update(
            sstate,
            found_inf if scaler_found_inf is None else scaler_found_inf)

        opt_params = state.master_params if self.master_weights else params
        updates, new_inner = self.tx.update(fp32_grads, state.inner, opt_params)
        stepped = optax.apply_updates(opt_params, updates)

        # skip-step select (handle.py:128-154): on overflow keep everything
        new_inner = _where_tree(found_inf, state.inner, new_inner)
        stepped = _where_tree(found_inf, opt_params, stepped)

        if self.master_weights:
            new_master = stepped
            # master→model copy (multi_tensor_scale copy,
            # _process_optimizer.py:353-364)
            new_params = jax.tree_util.tree_map(
                lambda m, p: m.astype(p.dtype), new_master, params
            )
        else:
            new_master = None
            new_params = jax.tree_util.tree_map(
                lambda s, p: s.astype(p.dtype), stepped, params
            )

        scalers = tuple(
            new_sstate if i == loss_id else s for i, s in enumerate(state.scalers)
        )
        new_state = AmpOptState(inner=new_inner, master_params=new_master,
                                scalers=scalers)
        info = {"overflow": found_inf, "loss_scale": new_sstate.loss_scale}
        return new_params, new_state, info

    # -- optax GradientTransformation interface so AmpOptimizer drops into
    # flax TrainState etc. (update == apply_gradients minus the param cast) --
    def update(self, grads, state, params=None):
        new_params, new_state, _ = self.apply_gradients(grads, state, params)
        updates = jax.tree_util.tree_map(
            lambda n, p: (n.astype(jnp.float32) - p.astype(jnp.float32)).astype(p.dtype),
            new_params, params,
        )
        return updates, new_state
