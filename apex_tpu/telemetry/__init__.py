"""apex_tpu.telemetry — observability layer: metrics, tracing, run ledger.

Three parts, all built around one rule: **disabled is free**. The repo's
measurement discipline (PERF.md §0) pins every headline number to a
committed method; an observability layer that perturbed the measured
program would invalidate the pins it exists to protect.

* ``metrics`` — registry + JSONL sink for in-step training scalars
  (loss-scale trajectory, overflow/skip events, grad-norm stats,
  tokens/s). Scalars are collected INSIDE the jitted step as auxiliary
  outputs stacked by the training scan and fetched with the existing
  1-element-sync pattern — never via host callbacks (on this backend
  they dial the relay). The enabled/disabled switch is a Python
  (trace-time) bool: with telemetry off the instrumented step traces to
  a byte-identical jaxpr (asserted by tests/test_telemetry.py).
* ``tracing`` — the single implementation of the PERF.md §0 timing rules
  (K-scan chaining, traced-eps feedback, 1-element sync, dispatch-
  overhead calibration). ``benchmarks/_timing.py`` re-exports it; the
  profile harnesses share :class:`~apex_tpu.telemetry.tracing.Tracer`
  so every emitted number carries its calibration metadata.
* ``ledger`` — every bench/profile invocation appends one structured
  record (git SHA, APEX_* knob pins, dispatch overhead, K, relay stamp,
  platform, span rows) to ``benchmarks/ledger.jsonl``. PERF.md table
  captions cite records as ``ledger:<id>``; ``tools/check_bench_labels.py``
  (tier-1) cross-checks captions against records.

Env knobs: ``APEX_TELEMETRY=1`` turns in-step metric collection on;
``APEX_TELEMETRY_PATH`` points the metrics JSONL sink;
``APEX_TELEMETRY_LEDGER`` overrides the ledger path (smoke-mode runs
skip the ledger write unless it is set).
"""

from apex_tpu.telemetry import ledger, metrics  # noqa: F401 (jax-free)
from apex_tpu.telemetry.metrics import (  # noqa: F401
    MetricsWriter,
    collect,
    disable,
    enable,
    enabled,
    read_metrics,
    register,
    reset_enabled,
)


def __getattr__(name):
    # tracing imports jax at module import time; keep it lazy so the
    # jax-free parts (ledger, metrics registry) stay importable before a
    # harness has decided its backend (the _smoke.py ordering contract).
    if name == "tracing":
        import importlib

        return importlib.import_module("apex_tpu.telemetry.tracing")
    raise AttributeError(f"module 'apex_tpu.telemetry' has no attribute {name!r}")
