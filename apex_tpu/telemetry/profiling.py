"""Budgeted on-device profiler capture (``APEX_PROFILE_CAPTURE=1``).

Static cost accounting (``telemetry.costs``) says what a program
*should* cost; only a device trace says where its time actually went.
But a profiler trace perturbs the traced run and the relay can wedge
mid-capture — so a capture must NEVER ride the scored attempt. The
contract, enforced by bench.py's watchdog:

* the watchdog runs the capture as a SEPARATE subprocess
  (``APEX_PROFILE_INNER=1``) after the scored attempts complete, under
  the resilience timeout envelope (:func:`timeout_s` — a wedged
  capture costs a bounded slice of the window, never the window);
* the capture child re-runs the measured program's warm scan, then
  traces K' post-warmup steps (one more scan dispatch) inside
  ``jax.profiler.trace`` — nothing it produces is a measurement, and
  its ledger record says so (harness ``bench_profile``, no ``value``);
* the artifact directory + a content hash are stamped into the ledger
  (:func:`artifact_block`), so a PERF.md attribution claim can name
  the exact trace it read — tamper-evidently, like every other stamp;
* a capture is REFUSED outright under ``APEX_FAULT_PLAN`` (like the
  collection shells and the scored artifacts: an injected run's trace
  must not land next to real ones).

Feature detection: ``jax.profiler.trace`` is absent or non-functional
on some backends — :func:`trace` degrades to a no-op context and the
artifact block reports zero files (a "can't report" value, never a
crash). Knobs: ``APEX_PROFILE_CAPTURE=1`` arms the watchdog hook;
``APEX_PROFILE_DIR`` overrides the artifact root (default
``benchmarks/profiles/``, git-ignored); ``APEX_PROFILE_TIMEOUT``
overrides the subprocess budget.
"""

import contextlib
import hashlib
import os
import time

from apex_tpu.telemetry.ledger import repo_root

DEFAULT_TIMEOUT_S = 900  # matches the resilience wedge cap: a capture
#                          is upside, never worth more than a capped
#                          attempt's budget


def requested():
    """True when the operator armed the watchdog's capture hook."""
    from apex_tpu.dispatch.tiles import env_flag

    return env_flag("APEX_PROFILE_CAPTURE")


def capture_active():
    """True inside the capture CHILD (``APEX_PROFILE_INNER=1`` — set
    only by the watchdog hook; the scored inner attempts never see
    it)."""
    from apex_tpu.dispatch.tiles import env_flag

    return env_flag("APEX_PROFILE_INNER")


def refusal():
    """Reason string when a capture must be refused, else None. Mirrors
    the collection shells' APEX_FAULT_PLAN gate: profiler artifacts are
    refused under injection like every other scored artifact."""
    try:
        from apex_tpu.resilience import faults

        if faults.active():
            return ("APEX_FAULT_PLAN is set (fault injection is "
                    "test-only; a profiler artifact must never be "
                    "captured under injection)")
    except Exception:
        pass
    return None


def timeout_s():
    """The capture subprocess budget (the resilience timeout envelope:
    ``APEX_PROFILE_TIMEOUT`` override, :data:`DEFAULT_TIMEOUT_S`
    default)."""
    from apex_tpu.dispatch.tiles import env_int

    return env_int("APEX_PROFILE_TIMEOUT") or DEFAULT_TIMEOUT_S


def profile_root():
    return os.environ.get("APEX_PROFILE_DIR") or os.path.join(
        repo_root(), "benchmarks", "profiles")


def new_capture_dir(label="capture"):
    """A fresh artifact directory under the profile root; created
    eagerly so the trace has somewhere to land."""
    d = os.path.join(profile_root(),
                     f"{label}-{time.strftime('%Y%m%d-%H%M%S')}-"
                     f"{os.getpid()}")
    os.makedirs(d, exist_ok=True)
    return d


@contextlib.contextmanager
def trace(outdir):
    """``jax.profiler.trace`` with feature detection: yields True when
    a real trace is active, False when the surface is absent/broken
    (the body still runs — a capture child that can't trace still
    exercises the program and reports an empty artifact block)."""
    cm = None
    try:
        import jax.profiler

        cm = jax.profiler.trace(outdir)
        cm.__enter__()
    except Exception:
        cm = None
    try:
        yield cm is not None
    finally:
        if cm is not None:
            try:
                cm.__exit__(None, None, None)
            except Exception:
                pass


def artifact_block(outdir):
    """The ledger stamp for one capture: ``{dir, files, bytes,
    sha256}``. The hash covers every file's relative path + content in
    sorted order, so a trace edited (or truncated) after the fact no
    longer matches its stamped record — same tamper-evidence rule as
    the record ids themselves. Never raises; an unreadable dir reports
    zero files."""
    files, total = [], 0
    h = hashlib.sha256()
    try:
        for root, _, names in sorted(os.walk(outdir)):
            for name in sorted(names):
                p = os.path.join(root, name)
                rel = os.path.relpath(p, outdir)
                # chunked read: device traces run to hundreds of MB and
                # the 1-core collection host hashes them while the
                # window is still open — never hold a whole artifact.
                # Feed a COPY and commit on success, so a file whose
                # read fails midway contributes nothing to the digest
                # (same all-or-nothing rule as the whole-read it
                # replaces).
                trial = h.copy()
                trial.update(rel.encode())
                nbytes = 0
                try:
                    with open(p, "rb") as f:
                        while True:
                            chunk = f.read(1 << 20)
                            if not chunk:
                                break
                            trial.update(chunk)
                            nbytes += len(chunk)
                except OSError:
                    continue
                h = trial
                files.append(rel)
                total += nbytes
    except OSError:
        pass
    return {"dir": outdir, "files": len(files), "bytes": total,
            "sha256": h.hexdigest() if files else None}


def validate_block(block):
    """Schema problems for a ``profile`` artifact block (ledger
    teeth, like the compile_cache/cost blocks)."""
    if not isinstance(block, dict):
        return ["profile is not a dict"]
    problems = []
    if not isinstance(block.get("dir"), str):
        problems.append("profile.dir is not a string")
    for k in ("files", "bytes"):
        v = block.get(k)
        if not (isinstance(v, int) and not isinstance(v, bool)
                and v >= 0):
            problems.append(f"profile.{k} is not a non-negative int")
    sha = block.get("sha256")
    if sha is not None and not (isinstance(sha, str) and len(sha) == 64):
        problems.append("profile.sha256 is not a sha256 hex digest")
    if block.get("files") and sha is None:
        problems.append("profile has files but no content hash")
    return problems
