"""Run ledger: one structured JSONL record per bench/profile invocation.

Every measurement harness appends a record — git SHA, APEX_* knob pins,
measured dispatch overhead, scan length K, relay-degradation stamp,
platform, per-span rows — to ``benchmarks/ledger.jsonl``. PERF.md table
captions cite records as ``ledger:<id>`` and
``tools/check_bench_labels.py`` (run in the tier-1 suite, like
``check_api_parity.py``) cross-checks the captions against the records,
so label drift of the kind that shipped the §10 "68–75 ms" caption over
an 82.6 ms log is mechanically detectable instead of a prose audit.

Record ids are content hashes (``lg-`` + sha1 of the canonical record
sans ``id``), so a record edited after the fact no longer matches its
own id — the checker flags that too.

Writes are best-effort and NEVER raise: bench.py's one-JSON-line
contract must survive a read-only checkout. Smoke-mode runs
(``APEX_BENCH_SMOKE=1``) skip the write unless ``APEX_TELEMETRY_LEDGER``
explicitly points somewhere — CPU sanity numbers do not belong in the
measurement ledger.
"""

import hashlib
import json
import os
import time

REQUIRED_FIELDS = ("id", "ts", "harness", "git_sha", "platform", "knobs",
                   "dispatch_overhead_ms", "k", "relay")


def repo_root():
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_path():
    return os.path.join(repo_root(), "benchmarks", "ledger.jsonl")


def ledger_path():
    return os.environ.get("APEX_TELEMETRY_LEDGER") or default_path()


def knob_pins(env=None):
    """Every ``APEX_*`` env var, sorted — the process-wide knob pins.
    Per-call knobs (e.g. bench.py's ``config`` dict) ride in ``extra``."""
    env = os.environ if env is None else env
    return {k: env[k] for k in sorted(env) if k.startswith("APEX_")}


# Harness-infrastructure knobs that legitimately differ between the run
# that SAVED a checkpoint and the run that RESUMES it (paths, attempt
# counters, retry budgets) — everything else an APEX_* pin names shapes
# the measured program, and a resumed timing row whose pins drifted
# from the checkpoint's is mixing two configs under one label. Shared
# by bench.py's resume provenance and check_bench_labels check 5 so the
# two can never disagree about what counts as drift.
INFRA_KNOB_PREFIXES = (
    "APEX_CKPT_", "APEX_BENCH_ATTEMPT", "APEX_BENCH_TIMEOUT",
    "APEX_BENCH_RETRY_WAIT", "APEX_BENCH_INNER", "APEX_BENCH_BASELINE",
    "APEX_TELEMETRY_LEDGER", "APEX_TELEMETRY_PATH",
    "APEX_COMPILE_CACHE", "APEX_WARM_ONLY", "APEX_WARM_TIMEOUT",
    "APEX_PROBE_", "APEX_FAULT_PLAN", "APEX_COLLECT_MANIFEST",
    "APEX_PROFILE_", "APEX_COST_ANALYSIS", "APEX_SERVE_BENCH",
    "APEX_FLIGHT_",  # flight recorder / supervisor (ISSUE 16): where
                     # beats land + reap thresholds — never the program
)


def measurement_pins(knobs=None):
    """The subset of ``knobs`` (default: the live environment) that
    shapes the measured program — infra knobs stripped. This is what a
    checkpoint saves and what resume-provenance pin-matching compares."""
    knobs = knob_pins() if knobs is None else knobs
    return {k: v for k, v in knobs.items()
            if not any(k.startswith(p) for p in INFRA_KNOB_PREFIXES)}


def pin_drift(saved, now):
    """Measurement-pin drift between a checkpoint's saved pins and a
    run's knobs: ``{knob: [saved, now]}`` for every measurement knob
    that differs, BOTH sides filtered through
    :func:`measurement_pins`. The ONE implementation shared by the
    provenance producer (``checkpoint.resume_provenance``) and the
    citation checker (``check_bench_labels`` check 5) — two copies of
    this comparison could disagree about what counts as drift."""
    saved = measurement_pins(saved or {})
    now = measurement_pins(now or {})
    return {k: [saved.get(k), now.get(k)]
            for k in sorted(set(saved) | set(now))
            if saved.get(k) != now.get(k)}


def git_sha():
    """HEAD commit of the repo (None when git is unavailable)."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=repo_root(), timeout=10,
            capture_output=True, text=True)
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else None
    except (OSError, subprocess.SubprocessError):
        return None


def record_id(rec):
    """Deterministic short id: sha1 over the canonical record sans id."""
    body = json.dumps({k: v for k, v in rec.items() if k != "id"},
                      sort_keys=True)
    return "lg-" + hashlib.sha1(body.encode()).hexdigest()[:10]


def make_record(harness, platform, dispatch_overhead_ms, k, relay=None,
                knobs=None, git=None, ts=None, extra=None):
    """Build (but do not write) a ledger record with its content id.

    ``relay`` is the degradation stamp: ``{"degraded": bool|None,
    "kind": str|None}`` — None/None when the harness has no detector
    (most profile harnesses; bench.py fills in its MFU-envelope
    verdict)."""
    rec = {
        "ts": round(time.time(), 3) if ts is None else ts,
        "harness": harness,
        "git_sha": git_sha() if git is None else git,
        "platform": platform,
        "knobs": knob_pins() if knobs is None else dict(knobs),
        "dispatch_overhead_ms": dispatch_overhead_ms,
        "k": k,
        "relay": ({"degraded": None, "kind": None} if relay is None
                  else dict(relay)),
    }
    if extra:
        rec.update(extra)
    if os.environ.get("APEX_FAULT_PLAN"):
        # any record produced under fault injection (the test-only
        # APEX_FAULT_PLAN — apex_tpu.resilience.faults) is stamped with
        # the plan hash BEFORE the content id is computed, so the stamp
        # is tamper-evident: an injected run can never masquerade as a
        # measurement (tools/check_bench_labels.py refuses citations of
        # stamped records in tier-1). An ACTIVE-but-unresolvable plan
        # (bad path, malformed JSON) still stamps — a sentinel, never a
        # silent omission that would let the record pass as clean.
        try:
            from apex_tpu.resilience import faults as _faults

            fp = _faults.plan_hash() or "fp-unresolvable"
        except Exception:
            fp = "fp-unresolvable"
        rec["fault_plan"] = fp
    rec["id"] = record_id(rec)
    return rec


def append_record(harness, platform, dispatch_overhead_ms, k, relay=None,
                  knobs=None, extra=None, path=None):
    """Append one record; returns its id, or None when the write was
    skipped (smoke mode without an explicit path) or failed (never
    raises — see module docstring)."""
    try:
        if path is None:
            from apex_tpu.dispatch.tiles import env_flag

            if (env_flag("APEX_BENCH_SMOKE")
                    and not os.environ.get("APEX_TELEMETRY_LEDGER")):
                return None
            path = ledger_path()
        rec = make_record(harness, platform, dispatch_overhead_ms, k,
                          relay=relay, knobs=knobs, extra=extra)
        with open(path, "a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
        return rec["id"]
    except Exception:
        return None


def read_ledger(path=None):
    """Parse a ledger file into a list of records. Raises ValueError
    (with the line number) on an unparseable OR non-object line — a
    corrupt/truncated ledger is a finding, not something to skip past
    silently, and a line truncated down to a bare JSON scalar (``42``,
    ``"harness"``) must fail here with its line number instead of
    crashing a consumer with an AttributeError later."""
    path = path or ledger_path()
    records = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise ValueError(f"{path}:{lineno}: unparseable ledger "
                                 f"line ({e})") from None
            if not isinstance(rec, dict):
                raise ValueError(
                    f"{path}:{lineno}: ledger line is not a JSON object "
                    f"(truncated line? parsed as {type(rec).__name__})")
            records.append(rec)
    return records


# the slo ledger block's schema (apex_tpu.serving.lifecycle builds it;
# this module owns the validation teeth, like the serving block above,
# so the stdlib-only validators never import the serving package)
SLO_FIELDS = ("ttft_p50_ms", "ttft_p99_ms", "per_token_p50_ms",
              "per_token_p99_ms", "goodput_tok_s", "slo_attainment",
              "slo_ttft_ms", "slo_tpot_ms", "arrival_process",
              "offered_load", "max_queue_depth", "kv_page_high_water",
              # resilience economics (ISSUE 15): None-when-disabled —
              # present always, so a disabled layer reads as explicit
              # degradation, never omission (check 9 refuses non-None
              # rates whose selecting knob is unpinned or off)
              "shed_rate", "preempt_rate", "degraded_rounds",
              # multi-token decode blocks (ISSUE 17): the K the row ran
              # at — a REQUIRED positive int (every engine has a block
              # size; K=1 is the single-step program, not an absence)
              "decode_block_k")
_SLO_NUMERIC = ("ttft_p50_ms", "ttft_p99_ms", "per_token_p50_ms",
                "per_token_p99_ms", "goodput_tok_s", "slo_ttft_ms",
                "slo_tpot_ms", "offered_load")
_SLO_COUNTS = ("max_queue_depth", "kv_page_high_water",
               "degraded_rounds")
_SLO_RATES = ("slo_attainment", "shed_rate", "preempt_rate")


def _validate_slo(slo):
    if not isinstance(slo, dict):
        return ["not a dict"]
    problems = []
    for field in SLO_FIELDS:
        if field not in slo:
            problems.append(f"missing field {field!r}")
    for field in _SLO_NUMERIC:
        v = slo.get(field)
        if v is not None and (not isinstance(v, (int, float))
                              or isinstance(v, bool) or v < 0):
            problems.append(f"{field} is not a non-negative number")
    for field in _SLO_COUNTS:
        v = slo.get(field)
        if v is not None and (not isinstance(v, int)
                              or isinstance(v, bool) or v < 0):
            problems.append(f"{field} is not a non-negative int")
    for field in _SLO_RATES:
        att = slo.get(field)
        if att is not None and (not isinstance(att, (int, float))
                                or isinstance(att, bool)
                                or not 0.0 <= att <= 1.0):
            problems.append(f"{field} is not in [0, 1]")
    for lo, hi in (("ttft_p50_ms", "ttft_p99_ms"),
                   ("per_token_p50_ms", "per_token_p99_ms")):
        a, b = slo.get(lo), slo.get(hi)
        if isinstance(a, (int, float)) and isinstance(b, (int, float)) \
                and not isinstance(a, bool) and not isinstance(b, bool) \
                and a > b:
            problems.append(f"{lo} exceeds {hi}")
    ap = slo.get("arrival_process")
    if "arrival_process" in slo and not (isinstance(ap, str) and ap):
        problems.append("arrival_process is not a non-empty string")
    dk = slo.get("decode_block_k")
    if "decode_block_k" in slo and (not isinstance(dk, int)
                                    or isinstance(dk, bool) or dk < 1):
        problems.append("decode_block_k is not a positive int")
    return problems


# the router ledger block's schema (apex_tpu.serving.router builds it;
# this module owns the validation teeth — same division as the slo
# block, so the stdlib-only validators never import the serving
# package). The policy vocabulary is duplicated from
# router.ROUTE_POLICIES on purpose (no serving import here);
# tests/test_router.py asserts the two tuples stay identical.
ROUTER_POLICY_VOCAB = ("round_robin", "least_loaded", "prefix_affinity")
ROUTER_FIELDS = ("route_policy", "replicas", "fleet_goodput_tok_s",
                 "util_spread", "ttft_p99_ms", "tpot_p99_ms",
                 "failovers", "replayed_requests", "requests",
                 "completed", "rejected_fleet", "rejected_replica",
                 "prefix_hit_rate_by_policy", "trace_id",
                 "arrival_process")
_ROUTER_NUMERIC = ("fleet_goodput_tok_s", "ttft_p99_ms", "tpot_p99_ms")
_ROUTER_COUNTS = ("failovers", "replayed_requests", "requests",
                  "completed", "rejected_fleet", "rejected_replica")


def _validate_router(rt):
    if not isinstance(rt, dict):
        return ["not a dict"]
    problems = []
    for field in ROUTER_FIELDS:
        if field not in rt:
            problems.append(f"missing field {field!r}")
    pol = rt.get("route_policy")
    if "route_policy" in rt and pol not in ROUTER_POLICY_VOCAB:
        problems.append(
            f"route_policy {pol!r} is not in {ROUTER_POLICY_VOCAB}")
    n = rt.get("replicas")
    if "replicas" in rt and (not isinstance(n, int)
                             or isinstance(n, bool) or n < 1):
        problems.append("replicas is not a positive int")
    for field in _ROUTER_NUMERIC:
        v = rt.get(field)
        if v is not None and (not isinstance(v, (int, float))
                              or isinstance(v, bool) or v < 0):
            problems.append(f"{field} is not a non-negative number")
    for field in _ROUTER_COUNTS:
        v = rt.get(field)
        if v is not None and (not isinstance(v, int)
                              or isinstance(v, bool) or v < 0):
            problems.append(f"{field} is not a non-negative int")
    sp = rt.get("util_spread")
    if sp is not None and (not isinstance(sp, (int, float))
                           or isinstance(sp, bool)
                           or not 0.0 <= sp <= 1.0):
        problems.append("util_spread is not in [0, 1]")
    hr = rt.get("prefix_hit_rate_by_policy")
    if hr is not None:
        # the policy sweep's proof surface: per-policy fleet hit rates
        # under the SAME trace — a malformed one could claim an
        # affinity win no sweep produced
        if not isinstance(hr, dict):
            problems.append("prefix_hit_rate_by_policy is not a dict")
        else:
            for k, v in hr.items():
                if k not in ROUTER_POLICY_VOCAB:
                    problems.append(
                        f"prefix_hit_rate_by_policy key {k!r} is not "
                        f"in {ROUTER_POLICY_VOCAB}")
                if not isinstance(v, (int, float)) \
                        or isinstance(v, bool) or not 0.0 <= v <= 1.0:
                    problems.append(
                        f"prefix_hit_rate_by_policy[{k!r}] is not in "
                        f"[0, 1]")
    for field in ("trace_id", "arrival_process"):
        v = rt.get(field)
        if field in rt and not (isinstance(v, str) and v):
            problems.append(f"{field} is not a non-empty string")
    return problems


def validate_record(rec):
    """Schema problems for one record (empty list = clean)."""
    problems = []
    for field in REQUIRED_FIELDS:
        if field not in rec:
            problems.append(f"missing field {field!r}")
    if not isinstance(rec.get("knobs", {}), dict):
        problems.append("knobs is not a dict")
    relay = rec.get("relay")
    if relay is not None and not isinstance(relay, dict):
        problems.append("relay is not a dict")
    oh = rec.get("dispatch_overhead_ms")
    if oh is not None and not isinstance(oh, (int, float)):
        problems.append("dispatch_overhead_ms is not numeric")
    if "k" in rec and rec["k"] is not None \
            and not isinstance(rec["k"], int):
        problems.append("k is not an int")
    cc = rec.get("compile_cache")
    if cc is not None:
        # the warm-start telemetry block (apex_tpu.compile_cache): a
        # malformed one could silently claim a number was compile-free
        if not isinstance(cc, dict):
            problems.append("compile_cache is not a dict")
        else:
            if not isinstance(cc.get("enabled"), bool):
                problems.append("compile_cache.enabled is not a bool")
            for field in ("hits", "misses"):
                v = cc.get(field)
                if not (isinstance(v, int) and not isinstance(v, bool)
                        and v >= 0):
                    problems.append(
                        f"compile_cache.{field} is not a non-negative int")
            if cc.get("dir") is not None \
                    and not isinstance(cc["dir"], str):
                problems.append("compile_cache.dir is not a string")
            age = cc.get("warm_age_s")
            if age is not None and not (isinstance(age, (int, float))
                                        and not isinstance(age, bool)
                                        and age >= 0):
                problems.append(
                    "compile_cache.warm_age_s is not a non-negative number")
    ck = rec.get("checkpoint")
    if ck is not None:
        # the durability telemetry block (apex_tpu.checkpoint
        # DurableCheckpointer.snapshot): a malformed one could silently
        # claim a window's state was banked when it was not
        if not isinstance(ck, dict):
            problems.append("checkpoint is not a dict")
        else:
            for field in ("saves", "queue_depth"):
                v = ck.get(field)
                if not (isinstance(v, int) and not isinstance(v, bool)
                        and v >= 0):
                    problems.append(
                        f"checkpoint.{field} is not a non-negative int")
            if ck.get("commit_ms") is not None and not isinstance(
                    ck["commit_ms"], (int, float)):
                problems.append("checkpoint.commit_ms is not numeric")
            if ck.get("last_step") is not None and not (
                    isinstance(ck["last_step"], int)
                    and not isinstance(ck["last_step"], bool)):
                problems.append("checkpoint.last_step is not an int")
    prof = rec.get("profile")
    if prof is not None:
        # the profiler artifact stamp (telemetry.profiling): a capture
        # whose hash/extent fields are malformed could pass off an
        # edited trace as the one the record captured
        from apex_tpu.telemetry import profiling as _profiling

        problems += _profiling.validate_block(prof)
    cost = rec.get("cost")
    if cost is not None:
        # the attribution block (apex_tpu.telemetry.costs): a malformed
        # one could silently mis-attribute a headline gap (wrong floor,
        # wrong MFU bound) — same teeth as the compile_cache block
        from apex_tpu.telemetry import costs as _costs

        problems += [f"cost: {p}" for p in _costs.validate(cost)]
    sv = rec.get("serving")
    if sv is not None:
        # the serving-bench block (benchmarks/profile_serving.py,
        # ISSUE 10): a malformed one could claim a tokens/s or latency
        # figure no trace produced — same teeth as the cost block
        if not isinstance(sv, dict):
            problems.append("serving is not a dict")
        else:
            for field in ("tokens_per_s", "p50_ms", "p99_ms"):
                v = sv.get(field)
                if v is not None and not (isinstance(v, (int, float))
                                          and not isinstance(v, bool)
                                          and v >= 0):
                    problems.append(
                        f"serving.{field} is not a non-negative number")
            p50, p99 = sv.get("p50_ms"), sv.get("p99_ms")
            if isinstance(p50, (int, float)) \
                    and isinstance(p99, (int, float)) and p50 > p99:
                problems.append("serving.p50_ms exceeds serving.p99_ms")
            if not (isinstance(sv.get("trace_id"), str)
                    and sv["trace_id"].startswith("tr-")):
                problems.append(
                    "serving.trace_id is not a trace hash (tr-...)")
            kp = sv.get("kv_pages")
            if not (isinstance(kp, int) and not isinstance(kp, bool)
                    and kp > 0):
                problems.append("serving.kv_pages is not a positive int")
            # generation fields (ISSUE 13): None-when-disabled is the
            # legal degradation; a present value must be a sane number
            # — a malformed rate could claim a speculation win no
            # verify chain produced. Absent fields are legacy rows.
            for field in ("spec_acceptance_rate", "prefix_hit_rate"):
                v = sv.get(field)
                if v is not None and (not isinstance(v, (int, float))
                                      or isinstance(v, bool)
                                      or not 0.0 <= v <= 1.0):
                    problems.append(
                        f"serving.{field} is not in [0, 1]")
            dl = sv.get("draft_len")
            if dl is not None and (not isinstance(dl, (int, float))
                                   or isinstance(dl, bool) or dl < 0):
                problems.append(
                    "serving.draft_len is not a non-negative number")
            # KV-tier fields (ISSUE 20): None-when-disabled like the
            # generation rates — a malformed swap_rate could claim a
            # restore economy no preemption churn produced
            kq = sv.get("kv_quant")
            if kq is not None and not isinstance(kq, bool):
                problems.append("serving.kv_quant is not a bool")
            sr = sv.get("swap_rate")
            if sr is not None and (not isinstance(sr, (int, float))
                                   or isinstance(sr, bool)
                                   or not 0.0 <= sr <= 1.0):
                problems.append("serving.swap_rate is not in [0, 1]")
            hw = sv.get("swapped_pages_high_water")
            if hw is not None and (not isinstance(hw, int)
                                   or isinstance(hw, bool) or hw < 0):
                problems.append(
                    "serving.swapped_pages_high_water is not a "
                    "non-negative int")
    slo = rec.get("slo")
    if slo is not None:
        # the SLO block (apex_tpu.serving.lifecycle.slo_block, ISSUE
        # 11): per-request tail latency + goodput under a named
        # arrival process. Malformed, it could claim an SLO attainment
        # no trace produced — same teeth as the serving block. Fields
        # may be null (a trace with no >=2-token request has no TPOT
        # percentile) but must be PRESENT: degradation, not omission.
        problems += [f"slo: {p}" for p in _validate_slo(slo)]
    rt = rec.get("router")
    if rt is not None:
        # the fleet block (apex_tpu.serving.router.router_block, ISSUE
        # 19): fleet goodput, utilization spread, cross-replica tails,
        # and the failover/replay account. Malformed, it could claim a
        # zero-loss failover or a prefix-affinity hit-rate delta no
        # fleet produced — same teeth as the slo block.
        problems += [f"router: {p}" for p in _validate_router(rt)]
    fr = rec.get("flight_reap")
    if fr is not None:
        # the supervisor's reap stamp (apex_tpu.resilience.flight_watch,
        # ISSUE 16): a malformed one could claim a rung was reaped for
        # heartbeat silence when it actually ran out its cap (or vice
        # versa) — the window account would mis-bill the reclaimed
        # minutes. Verdict/reason vocabularies come from the resilience
        # classifier so the two can never drift.
        from apex_tpu import resilience as _resilience

        if not isinstance(fr, dict):
            problems.append("flight_reap is not a dict")
        else:
            if not (isinstance(fr.get("row"), str) and fr["row"]):
                problems.append(
                    "flight_reap.row does not name the reaped row")
            if fr.get("verdict") not in _resilience.INFLIGHT_VERDICTS:
                problems.append(
                    f"flight_reap.verdict {fr.get('verdict')!r} is not a "
                    f"classified in-flight verdict "
                    f"{_resilience.INFLIGHT_VERDICTS}")
            if fr.get("reason") not in ("silence", "cap", "signal"):
                problems.append(
                    f"flight_reap.reason {fr.get('reason')!r} is not one "
                    f"of ('silence', 'cap', 'signal')")
            for field in ("silence_s", "timeout_s", "elapsed_s"):
                v = fr.get(field)
                if not (isinstance(v, (int, float))
                        and not isinstance(v, bool) and v >= 0):
                    problems.append(
                        f"flight_reap.{field} is not a non-negative "
                        f"number")
            nb = fr.get("beats")
            if not (isinstance(nb, int) and not isinstance(nb, bool)
                    and nb >= 0):
                problems.append(
                    "flight_reap.beats is not a non-negative int")
            age = fr.get("age_s")
            if age is not None and (not isinstance(age, (int, float))
                                    or isinstance(age, bool) or age < 0):
                problems.append(
                    "flight_reap.age_s is not a non-negative number "
                    "or null")
            lp = fr.get("last_phase")
            if lp is not None and not isinstance(lp, str):
                problems.append(
                    "flight_reap.last_phase is not a string or null")
    rf = rec.get("resumed_from")
    if rf is not None:
        # resume provenance (bench.py --resume / profile_gpt): rides
        # INSIDE the content-hashed id; check_bench_labels check 5
        # pin-matches citations of resumed records
        if not isinstance(rf, dict):
            problems.append("resumed_from is not a dict")
        else:
            if not (isinstance(rf.get("ckpt"), str)
                    and rf["ckpt"].startswith("ck-")):
                problems.append(
                    "resumed_from.ckpt is not a checkpoint id (ck-...)")
            if not (isinstance(rf.get("step"), int)
                    and not isinstance(rf.get("step"), bool)):
                problems.append("resumed_from.step is not an int")
            if not isinstance(rf.get("pins"), dict):
                problems.append("resumed_from.pins is not a dict")
    if "id" in rec and all(f in rec for f in REQUIRED_FIELDS):
        want = record_id(rec)
        if rec["id"] != want:
            problems.append(
                f"id {rec['id']!r} does not match record content "
                f"(expected {want!r}) — record edited after the fact?")
    return problems


# ------------------------------------------------------- inspection CLI
# ``python -m apex_tpu.telemetry.ledger status|tail|show <id>`` — until
# now the only ledger reader was the checker; a window operator (or the
# window-economics report) should not need a JSON one-liner to ask
# "what did this round record". Read-only; never writes the ledger.


def _summary_line(rec):
    """One human line per record: id, harness, platform, ts, verdict-ish
    marks (relay stamp / fault stamp / value / span count)."""
    import datetime

    ts = rec.get("ts")
    when = "?"
    if isinstance(ts, (int, float)):
        when = datetime.datetime.fromtimestamp(ts).strftime(
            "%Y-%m-%d %H:%M:%S")
    marks = []
    relay = rec.get("relay") or {}
    if isinstance(relay, dict) and relay.get("degraded"):
        marks.append(f"degraded:{relay.get('kind')}")
    if rec.get("fault_plan"):
        marks.append(f"INJECTED:{rec['fault_plan']}")
    if rec.get("value") is not None:
        marks.append(f"value={rec['value']}")
    if rec.get("mfu") is not None:
        marks.append(f"mfu={rec['mfu']}")
    spans = rec.get("spans")
    if isinstance(spans, list):
        marks.append(f"{len(spans)} span(s)")
    slo = rec.get("slo")
    if isinstance(slo, dict):
        att = slo.get("slo_attainment")
        # malformed attainment (a validator FINDING) must not crash
        # the summary that would surface it
        marks.append(f"slo={att:.0%}"
                     if isinstance(att, (int, float))
                     and not isinstance(att, bool) else "slo")
    cost = rec.get("cost")
    if isinstance(cost, dict) and cost.get("peak_hbm_bytes"):
        marks.append(f"peak_hbm={cost['peak_hbm_bytes'] / 2 ** 20:.0f}MiB")
    fr = rec.get("flight_reap")
    if isinstance(fr, dict):
        marks.append(f"reaped:{fr.get('row', '?')}"
                     f"({fr.get('reason', '?')}/{fr.get('verdict', '?')})")
    return (f"{rec.get('id', '?'):14s} {when}  "
            f"{str(rec.get('harness', '?')):22s} "
            f"{str(rec.get('platform', '?')):4s} "
            f"{' '.join(marks)}").rstrip()


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.telemetry.ledger",
        description="Inspect the run ledger (read-only).")
    ap.add_argument("--ledger", default=None,
                    help="ledger path (default: APEX_TELEMETRY_LEDGER "
                         "or benchmarks/ledger.jsonl)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status", help="record counts + schema findings")
    tail = sub.add_parser("tail", help="last N record summaries")
    tail.add_argument("n", nargs="?", type=int, default=10)
    show = sub.add_parser("show", help="pretty-print one record")
    show.add_argument("id", help="record id (lg-...)")
    args = ap.parse_args(argv)

    path = args.ledger or ledger_path()
    try:
        records = read_ledger(path)
    except FileNotFoundError:
        print(f"no ledger at {path}")
        return 1
    except ValueError as e:
        print(f"CORRUPT: {e}")
        return 1

    if args.cmd == "status":
        by_harness, problems, injected = {}, 0, 0
        for rec in records:
            h = rec.get("harness", "?")
            by_harness[h] = by_harness.get(h, 0) + 1
            if validate_record(rec):
                problems += 1
            if rec.get("fault_plan"):
                injected += 1
        print(f"{path}: {len(records)} record(s)")
        for h in sorted(by_harness):
            print(f"  {h:24s} {by_harness[h]}")
        print(f"  schema findings: {problems}; fault-injected: {injected}")
        # serving/slo account (ISSUE 11): a window operator asking
        # "what did serving bank" gets the tail-latency story, not
        # just a row count
        sv_rows = [r for r in records
                   if isinstance(r.get("serving"), dict)]
        slo_rows = [r for r in records if isinstance(r.get("slo"), dict)]
        if sv_rows or slo_rows:
            print(f"  serving: {len(sv_rows)} row(s), "
                  f"{len(slo_rows)} with slo block")
            for r in slo_rows:
                s = r["slo"]
                att = s.get("slo_attainment")
                # a malformed attainment is a schema FINDING above —
                # the status line that reports it must not crash on it
                att_s = (format(att, ".0%")
                         if isinstance(att, (int, float))
                         and not isinstance(att, bool) else "?")
                sv = r.get("serving")  # may be malformed: a finding,
                tid = (sv.get("trace_id", "?")  # never a crash here
                       if isinstance(sv, dict) else "?")
                print(f"    {r.get('id', '?')} "
                      f"{s.get('arrival_process', '?')} "
                      f"offered={s.get('offered_load')} req/tick "
                      f"attainment={att_s} "
                      f"goodput={s.get('goodput_tok_s')} tok/s "
                      f"ttft_p99={s.get('ttft_p99_ms')}ms [{tid}]")
        # newest flight heartbeat (ISSUE 16): when a flight dir is
        # armed the ledger status also answers "is anything alive
        # RIGHT NOW" — newest beat's phase + age
        from apex_tpu.telemetry import flight as _flight

        if _flight.enabled():
            print(f"  {_flight.status_line()}")
        return 1 if problems else 0
    if args.cmd == "tail":
        # n<=0 prints nothing (records[-0:] would be the WHOLE ledger)
        for rec in records[-args.n:] if args.n > 0 else []:
            print(_summary_line(rec))
        return 0
    # show <id>
    for rec in records:
        if rec.get("id") == args.id:
            print(json.dumps(rec, indent=2, sort_keys=True))
            problems = validate_record(rec)
            for p in problems:
                print(f"FINDING: {p}")
            return 1 if problems else 0
    print(f"no record {args.id!r} in {path}")
    return 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
