"""Static cost/memory/communication accounting — the attribution layer.

The repo can measure (PR 1), dispatch (PR 3/5) and survive the relay
(PR 4), but a number like "38.7% MFU at b=8" carries no attribution:
is the gap to the 0.45 goal compute-bound, HBM-bound, or tunnel-bound,
and which slice owns it? This module derives, for every AOT-lowered
bench/harness program, a validated **cost block** from XLA's own
analyses — no measurement, no device time, no change to the measured
program (the analyses read the lowered/compiled artifact; PR-1's
disabled-is-free invariant holds trivially: the traced jaxpr is
byte-identical whether or not anyone asks XLA to count its flops).

The block (:func:`build`; schema policed by :func:`validate`, wired
into ``ledger.validate_record``)::

    {"source": "compiled"|"lowered"|"eval_shape"|None,
                                            # what surface reported —
                                            # "eval_shape" marks a pure
                                            # shape-walk lower bound (the
                                            # ISSUE 18 capability rung:
                                            # nothing compiled, arg bytes
                                            # only)
     "steps": K,                            # scan length (metadata —
                                            # XLA counts the body ONCE)
     "xla_flops_per_step":   ...,  # XLA-counted flops (real HLO work)
     "model_flops_per_step": ...,  # the 6·N·tokens an MFU claim uses
     "hbm_bytes_per_step":   ...,  # bytes moved ("bytes accessed")
     "peak_hbm_bytes":       ...,  # arg+out+temp+code − alias
     "memory": {...},              # the raw memory_analysis fields
     "comm_bytes_per_axis": {...}, # collective payload per mesh axis
     "peak_flops": ..., "hbm_bytes_per_s": ...,   # roofline constants
     "compute_floor_ms": ..., "bandwidth_floor_ms": ...,
     "step_floor_ms": ...,         # max(compute, bandwidth) floor
     "mfu_bound": ...}             # model flops at the floor ÷ peak

plus two OPTIONAL stamps (present only where they say something —
legacy blocks stay valid without them, malformed is a finding):
``comm_compression`` (the quantized-collectives claim, PR 8) and
``overlap_bound`` (:func:`overlap_bound` — compute floor vs measured
comm+host time, the ROADMAP 4d gap ``window_report`` prints).

Every field degrades to None where the backend can't report (the
``_compat`` normalizers fold the per-version/backend shape differences:
absent method, None return, flat dict, list-of-dicts, extension
object) — a cost block is *always* stampable, never a crash.

Comm accounting (:func:`comm_from_jaxpr`) counts collective payload
bytes per mesh axis from the jaxpr — psum/pmean/all_gather/
reduce_scatter/ppermute/all_to_all operand bytes, scan bodies
multiplied by their trip count. "Payload" = per-participant operand
bytes, NOT wire bytes (a ring all-reduce moves ~2(n−1)/n× payload);
the number is the telemetry prerequisite for quantized-collective
work (ROADMAP item 3), where payload shrinkage is exactly the claim.

Predicted peak HBM drives the §6 starvation economics BEFORE a row
burns window time: :func:`starvation` flags a program whose predicted
peak exceeds the chip (hard infeasible) or the operator-set
``APEX_STARVE_HBM_BYTES`` threshold (the relay's observed large-HBM
starvation mode sits between the b=8 and b=16 working sets; the
threshold is a knob, not an asserted constant, until a window measures
it — measured dispatch, not asserted dispatch).

Stdlib-only at import (like ``ledger``): jax and ``_compat`` load
lazily inside the capture functions, so the ledger's validators and
``tools/window_report.py`` never touch a backend.
"""

import os

# ------------------------------------------------- chip roofline envelope
# The ONE home of the v5e constants the harnesses previously inlined
# (bench.py / profile_*.py `peak_flops = 197e12`): an MFU claim and its
# cost block must divide by the same peak.
V5E_PEAK_BF16_FLOPS = 197e12
V5E_HBM_BYTES_PER_S = 819e9       # v5e HBM bandwidth
V5E_HBM_CAPACITY_BYTES = 16 * 2 ** 30
# Inter-chip interconnect ENVELOPE (ROADMAP 4d: the training comm_ms
# input of overlap_bound). Datasheet-derived — v5e carries 1600 Gbps of
# ICI per chip — and HONESTLY AN ENVELOPE, not a measurement: the
# single-chip window has no second chip to move bytes to, so every
# comm_ms stamped from it is a best-case lower bound on collective time
# (payload ÷ peak ICI, no ring factor, no launch latency) until the
# pod-slice window measures the real curve (PERF.md §2, the same
# measured-not-asserted ladder the roofline constants climbed).
V5E_ICI_BYTES_PER_S_ENVELOPE = 200e9

_NUMERIC_FIELDS = (
    "xla_flops_per_step", "model_flops_per_step", "hbm_bytes_per_step",
    "peak_hbm_bytes", "peak_flops", "hbm_bytes_per_s",
    "compute_floor_ms", "bandwidth_floor_ms", "step_floor_ms",
    "mfu_bound",
)
FIELDS = ("source", "steps", "memory", "comm_bytes_per_axis") \
    + _NUMERIC_FIELDS

_MEMORY_KEYS = ("argument_size_in_bytes", "output_size_in_bytes",
                "temp_size_in_bytes", "alias_size_in_bytes",
                "generated_code_size_in_bytes")

# collective primitives counted by comm_from_jaxpr; pmean/pmax/pmin
# lower to (or are) reductions over the same axes as psum
_COLLECTIVES = ("psum", "pmean", "pmax", "pmin", "all_gather",
                "all_to_all", "ppermute", "reduce_scatter",
                "psum_scatter")


def peak_flops_for(platform):
    """The bf16 roofline peak an MFU on this platform divides by (None
    when the repo has no committed envelope — CPU smoke numbers carry
    no MFU, same rule as bench.py)."""
    return V5E_PEAK_BF16_FLOPS if platform == "tpu" else None


def hbm_bw_for(platform):
    return V5E_HBM_BYTES_PER_S if platform == "tpu" else None


def hbm_capacity_for(platform):
    return V5E_HBM_CAPACITY_BYTES if platform == "tpu" else None


def ici_bw_for(platform):
    """The ICI bandwidth ENVELOPE an overlap_bound ``comm_ms`` divides
    by (None off-TPU — a CPU smoke's collective bytes carry no
    interconnect claim, same rule as :func:`peak_flops_for`)."""
    return V5E_ICI_BYTES_PER_S_ENVELOPE if platform == "tpu" else None


def wire_bytes(comm, axis_sizes):
    """The per-axis payload that actually MOVES: drop size-1 axes (a
    single-participant collective is traced but free on the wire —
    counting it would overstate every degenerate topology). Axes not
    named in ``axis_sizes`` are kept (unknown means "assume it
    moves"). The ONE home of the claim-shaping filter every harness
    applies before :func:`comm_ms_from_axis_bytes` — five private
    copies of the idiom could silently disagree about what counts as
    wire payload."""
    if not isinstance(comm, dict):
        return comm
    sizes = axis_sizes or {}
    return {ax: v for ax, v in comm.items() if sizes.get(ax, 2) > 1}


def comm_ms_from_axis_bytes(comm, platform):
    """Predicted per-step collective milliseconds from a
    :func:`comm_from_jaxpr` per-axis payload dict over the measured-
    interconnect envelope — the TRAINING ``comm_ms`` input of
    :func:`overlap_bound` (ROADMAP 4d: bench/profile_gpt records get
    the same gap attribution serving records already carry).

    Returns 0.0 for a traced-but-collective-free program (an empty
    dict is a real answer: nothing to hide), and None when ``comm``
    is None (untraced — no claim) or the platform has no committed
    envelope. Payload over peak-ICI is an ENVELOPE lower bound (see
    ``V5E_ICI_BYTES_PER_S_ENVELOPE``); the stamp is still honest —
    a gap it names can only be larger on the real wire."""
    if not isinstance(comm, dict):
        return None
    bw = ici_bw_for(platform)
    if bw is None:
        return None
    total = 0.0
    for v in comm.values():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            total += float(v)
    return round(total / bw * 1e3, 6)


def requested():
    """Tri-state ``APEX_COST_ANALYSIS``: True ("1"), False ("0"), or
    None (unset — the caller's default applies). A process-wide
    preference, never a raise (CLAUDE.md knob asymmetry; same parsing
    as ``compile_cache.requested``)."""
    v = os.environ.get("APEX_COST_ANALYSIS")
    if v == "1":
        return True
    if v == "0":
        return False
    return None


def enabled(default=True):
    """Whether to run the XLA captures. Real runs default ON; smoke
    callers pass ``default=False`` (a CPU sanity run should not pay
    extra host traces for numbers nobody cites — mirroring the
    ledger's and compile cache's smoke rule). Disabled still stamps
    the all-None block: degradation, never omission."""
    r = requested()
    return bool(default) if r is None else r


def null_block():
    """The all-None degradation: the backend (or the escape hatch)
    reported nothing, and the record says so explicitly instead of
    omitting the block."""
    block = {k: None for k in FIELDS}
    return block


def comm_compression_block(snapshot, uncompressed=None):
    """The comm-compression stamp for a cost block:
    ``{scheme, hierarchical, block, uncompressed_bytes_per_axis}``.
    ``snapshot`` is ``parallel.collectives.snapshot()`` (the resolved
    process-wide knobs the measured program traced under);
    ``uncompressed`` the per-axis byte counts of the program's
    uncompressed twin (traced under ``collectives.disabled()``), so a
    record claiming a payload cut carries BOTH sides of the claim.
    Returns None when nothing is compressed (the block is only stamped
    where it says something — old records stay valid without it)."""
    if not snapshot.get("scheme") and not snapshot.get("hierarchical"):
        return None
    out = {"scheme": snapshot.get("scheme"),
           "hierarchical": bool(snapshot.get("hierarchical")),
           "block": snapshot.get("block")}
    if isinstance(uncompressed, dict):
        out["uncompressed_bytes_per_axis"] = {
            str(k): float(v) for k, v in sorted(uncompressed.items())}
    return out


def overlap_bound(compute_floor_ms, host_ms=None, comm_ms=None):
    """The overlap upper bound (ROADMAP 4d seed): compute floor vs the
    comm+host time a perfectly overlapped schedule would hide behind
    it. ``host_ms`` is MEASURED non-device wall per step (e.g. the
    serving loop's scheduler/staging slice — run wall minus device
    dispatch time, per decode round); ``comm_ms`` a per-step
    collective-time estimate where a caller has one. Returns None
    when neither is known (the stamp only exists where it says
    something); fields null-degrade individually::

        {"compute_floor_ms": ...,  # the block's roofline floor
         "host_ms": ..., "comm_ms": ...,
         "comm_host_ms": ...,      # what overlap could hide
         "hideable_ms": ...,       # min(floor, comm+host) — the win
         "bound_step_ms": ...}     # max(floor, comm+host) — the best
                                   # fully-overlapped step

    ``bound_step_ms − compute_floor_ms`` is the gap every future
    overlap/scheduler PR is chasing; ``window_report`` prints it as a
    column so the gap has a name before anyone claims to have closed
    it."""
    if host_ms is None and comm_ms is None:
        return None
    comm_host = (host_ms or 0.0) + (comm_ms or 0.0)
    out = {
        "compute_floor_ms": None if compute_floor_ms is None
        else round(float(compute_floor_ms), 6),
        "host_ms": None if host_ms is None else round(float(host_ms), 6),
        "comm_ms": None if comm_ms is None else round(float(comm_ms), 6),
        "comm_host_ms": round(float(comm_host), 6),
        "hideable_ms": None, "bound_step_ms": None,
    }
    if compute_floor_ms is not None:
        out["hideable_ms"] = round(min(float(compute_floor_ms),
                                       comm_host), 6)
        out["bound_step_ms"] = round(max(float(compute_floor_ms),
                                         comm_host), 6)
    return out


def attach_overlap(block, host_ms=None, comm_ms=None):
    """Return ``block`` with an ``overlap_bound`` stamp derived from
    its own ``compute_floor_ms`` (None-degrading: a null-degraded
    block still carries the measured comm+host side). The sub-block
    is OPTIONAL in the schema — legacy cost blocks stay valid without
    it — but malformed is a finding (:func:`validate`)."""
    ob = overlap_bound(
        (block or {}).get("compute_floor_ms"), host_ms=host_ms,
        comm_ms=comm_ms)
    if ob is None:
        return block
    out = dict(block or null_block())
    out["overlap_bound"] = ob
    return out


_OVERLAP_FIELDS = ("compute_floor_ms", "host_ms", "comm_ms",
                   "comm_host_ms", "hideable_ms", "bound_step_ms")


def build(xla_flops=None, hbm_bytes=None, memory=None, comm=None,
          steps=None, model_flops_per_step=None, platform=None,
          source=None, comm_compression=None, host_ms=None,
          comm_ms=None):
    """Assemble a validated cost block from XLA's reported numbers.

    ``xla_flops`` / ``hbm_bytes`` are the analyses' reported counts,
    which are PER-STEP already for a K-step ``lax.scan`` program: XLA
    counts a loop body ONCE, not × trip count (calibrated on this
    container's jax 0.4.37, Lowered and Compiled both — a 16-step scan
    of a 2·64³-flop matmul reports 524,290 flops, one body plus loop
    overhead; asserted by tests/test_costs.py so a jax that changes the
    counting fails loudly instead of silently re-breaking attribution).
    ``steps`` is metadata — the scan length of the analyzed program,
    NOT a divisor. ``memory`` is the normalized memory_analysis dict;
    ``comm`` the per-axis payload dict (per step — the caller divides
    its whole-program jaxpr walk by the scan length, since
    ``comm_from_jaxpr`` DOES multiply bodies by trip count). Floors and
    the MFU bound are derived where the inputs allow, None elsewhere."""
    block = null_block()
    block["source"] = source
    steps = int(steps) if steps else None
    block["steps"] = steps
    if xla_flops is not None:
        block["xla_flops_per_step"] = float(xla_flops)
    if hbm_bytes is not None:
        block["hbm_bytes_per_step"] = float(hbm_bytes)
    if model_flops_per_step is not None:
        block["model_flops_per_step"] = float(model_flops_per_step)
    if isinstance(memory, dict):
        block["memory"] = {k: memory.get(k) for k in _MEMORY_KEYS}
        block["peak_hbm_bytes"] = max(0, (
            (memory.get("argument_size_in_bytes") or 0)
            + (memory.get("output_size_in_bytes") or 0)
            + (memory.get("temp_size_in_bytes") or 0)
            + (memory.get("generated_code_size_in_bytes") or 0)
            - (memory.get("alias_size_in_bytes") or 0)))
    if isinstance(comm, dict):
        block["comm_bytes_per_axis"] = {str(k): float(v)
                                        for k, v in sorted(comm.items())}
    if isinstance(comm_compression, dict):
        # the quantized/hierarchical-collectives stamp
        # (comm_compression_block): which knobs shaped the traced
        # payload, and what the uncompressed twin would have moved
        block["comm_compression"] = comm_compression
    peak = peak_flops_for(platform)
    bw = hbm_bw_for(platform)
    block["peak_flops"] = peak
    block["hbm_bytes_per_s"] = bw
    if peak and block["xla_flops_per_step"] is not None:
        block["compute_floor_ms"] = round(
            block["xla_flops_per_step"] / peak * 1e3, 6)
    if bw and block["hbm_bytes_per_step"] is not None:
        block["bandwidth_floor_ms"] = round(
            block["hbm_bytes_per_step"] / bw * 1e3, 6)
    floors = [f for f in (block["compute_floor_ms"],
                          block["bandwidth_floor_ms"]) if f is not None]
    if floors:
        block["step_floor_ms"] = max(floors)
        mf = block["model_flops_per_step"] or block["xla_flops_per_step"]
        if mf and peak and block["step_floor_ms"] > 0:
            block["mfu_bound"] = round(
                mf / (block["step_floor_ms"] / 1e3) / peak, 4)
    ob = overlap_bound(block["compute_floor_ms"], host_ms=host_ms,
                       comm_ms=comm_ms)
    if ob is not None:
        # the overlap upper bound (ROADMAP 4d): stamped only when a
        # caller measured a comm/host side — optional, never omitted
        # silently once known
        block["overlap_bound"] = ob
    return block


def capture(lowered=None, compiled=None, steps=1, comm=None,
            model_flops_per_step=None, platform=None,
            comm_compression=None, host_ms=None, comm_ms=None):
    """The capture path: feature-detected ``cost_analysis`` /
    ``memory_analysis`` off an AOT stage pair, folded into one block.

    ``compiled`` is preferred (its analyses see the optimized
    executable, and only it carries memory_analysis); ``lowered``
    degrades to flops/bytes only. Never raises; with the escape hatch
    thrown (or no stage at all) returns the all-None block."""
    if not enabled() or (lowered is None and compiled is None):
        return build(comm=comm, steps=steps,
                     model_flops_per_step=model_flops_per_step,
                     platform=platform, source=None,
                     comm_compression=comm_compression,
                     host_ms=host_ms, comm_ms=comm_ms)
    try:
        from apex_tpu import _compat
    except Exception:
        return null_block()
    ca = ma = None
    source = None
    if compiled is not None:
        ca = _compat.cost_analysis_dict(compiled)
        ma = _compat.memory_analysis_dict(compiled)
        if ca is not None or ma is not None:
            source = "compiled"
    if ca is None and lowered is not None:
        ca = _compat.cost_analysis_dict(lowered)
        if ca is not None and source is None:
            source = "lowered"
    return build(
        xla_flops=ca.get("flops") if ca else None,
        hbm_bytes=ca.get("bytes accessed") if ca else None,
        memory=ma, comm=comm, steps=steps,
        model_flops_per_step=model_flops_per_step, platform=platform,
        source=source, comm_compression=comm_compression,
        host_ms=host_ms, comm_ms=comm_ms)


# --------------------------------------------------------- comm accounting

def _aval_bytes(var):
    aval = getattr(var, "aval", None)
    size = getattr(aval, "size", None)
    dtype = getattr(aval, "dtype", None)
    if size is None or dtype is None:
        return 0
    return int(size) * int(getattr(dtype, "itemsize", 0) or 0)


def _eqn_axes(params):
    axes = params.get("axes", params.get("axis_name"))
    if axes is None:
        return ()
    if isinstance(axes, (tuple, list)):
        return tuple(a for a in axes if isinstance(a, (str, int)))
    return (axes,)


def comm_from_jaxpr(jaxpr):
    """Per-mesh-axis collective payload bytes in a (Closed)Jaxpr.

    Walks every equation, recursing into sub-jaxprs (pjit/shard_map
    bodies, cond branches) and multiplying scan/while bodies by their
    static trip count where known (a microbatch loop's collectives
    happen once per microbatch per step). Payload = summed operand
    array bytes, attributed to EACH named axis of the eqn (a
    two-axis psum moves the payload on both meshes). Returns
    ``{axis_name: bytes}`` — empty dict = traced, no collectives;
    never raises (a jaxpr shape this walker doesn't know contributes
    nothing rather than crashing a harness)."""
    totals = {}

    def visit(jxp, mult):
        eqns = getattr(jxp, "eqns", None)
        if eqns is None:  # ClosedJaxpr
            inner = getattr(jxp, "jaxpr", None)
            if inner is None:
                return
            return visit(inner, mult)
        for eqn in eqns:
            name = getattr(eqn.primitive, "name", "")
            if name in _COLLECTIVES:
                nbytes = sum(_aval_bytes(v) for v in eqn.invars) * mult
                for ax in _eqn_axes(eqn.params):
                    ax = str(ax)
                    totals[ax] = totals.get(ax, 0) + nbytes
            # trip-count multiplier for loop bodies
            inner_mult = mult
            if name == "scan":
                length = eqn.params.get("length")
                if isinstance(length, int) and length > 0:
                    inner_mult = mult * length
            for p in eqn.params.values():
                for sub in _sub_jaxprs(p):
                    visit(sub, inner_mult)

    def _sub_jaxprs(p):
        if hasattr(p, "eqns") or hasattr(p, "jaxpr"):
            yield p
        elif isinstance(p, (tuple, list)):
            for item in p:
                if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                    yield item

    try:
        visit(jaxpr, 1)
    except Exception:
        return {}
    return {k: int(v) for k, v in totals.items()}


# -------------------------------------------- collective scheduling

# the backward-compute primitives a collective can hide behind: matmul
# and convolution carry the step's MXU work (elementwise tails are
# bandwidth noise a psum cannot meaningfully overlap)
_COMPUTE_PRIMS = ("dot_general", "conv_general_dilated")


def collective_schedule(jaxpr, axes=None):
    """The jaxpr-level overlap verdict (ROADMAP 4b, the ISSUE 14 proof
    surface): walk every equation IN ORDER (recursing into
    pjit/shard_map/custom-vjp/scan sub-jaxprs at their position, the
    same traversal as :func:`comm_from_jaxpr`) and judge whether the
    collectives interleave with remaining compute or form one terminal
    block::

        {"verdict": "interleaved" | "terminal" | "no-collectives",
         "collectives": n,            # counted collective eqns
         "compute": n,                # dot_general/conv eqn count
         "compute_after_first_collective": n}

    ``axes`` restricts WHICH collectives are judged (an iterable of
    mesh-axis names — e.g. the dp axes of a grad sync): a real
    training program carries forward collectives too (tp psums in the
    parallel CE, pp ppermutes — traced even over size-1 axes), and
    those interleave with backward compute by construction, which
    would drown the grad-sync schedule the claim is about. With
    ``axes=None`` every collective counts (the profile_comm dp-only
    shape needs no filter).

    ``interleaved`` iff at least one compute equation appears AFTER
    the first counted collective — the bucket-interleaved schedule
    (``overlap.bucketed``) emits each bucket's psum as its cotangents
    complete, so later-bucket collectives precede earlier-layer
    backward matmuls; the historical terminal reduction emits every
    collective after the last backward matmul. Equation order is the
    claim surface: XLA's latency-hiding scheduler may still recover
    overlap from a terminal block, but only the interleaved jaxpr
    GUARANTEES the operands are ready early — which is why the verdict
    (not a hope about the scheduler) is what tests pin. Never raises;
    an unwalkable jaxpr returns the no-collectives verdict with zero
    counts (same degradation rule as :func:`comm_from_jaxpr`)."""
    axes = None if axes is None else {str(a) for a in axes}
    order = []

    def visit(jxp):
        eqns = getattr(jxp, "eqns", None)
        if eqns is None:  # ClosedJaxpr
            inner = getattr(jxp, "jaxpr", None)
            if inner is None:
                return
            return visit(inner)
        for eqn in eqns:
            name = getattr(eqn.primitive, "name", "")
            if name in _COLLECTIVES:
                eqn_axes = {str(a) for a in _eqn_axes(eqn.params)}
                if axes is None or (eqn_axes & axes):
                    order.append("coll")
            elif name in _COMPUTE_PRIMS:
                order.append("comp")
            for p in eqn.params.values():
                if hasattr(p, "eqns") or hasattr(p, "jaxpr"):
                    visit(p)
                elif isinstance(p, (tuple, list)):
                    for item in p:
                        if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                            visit(item)

    try:
        visit(jaxpr)
    except Exception:
        order = []
    n_coll = order.count("coll")
    n_comp = order.count("comp")
    out = {"verdict": "no-collectives", "collectives": n_coll,
           "compute": n_comp, "compute_after_first_collective": 0}
    if not n_coll:
        return out
    first_coll = order.index("coll")
    after = order[first_coll + 1:].count("comp")
    out["compute_after_first_collective"] = after
    out["verdict"] = "interleaved" if after else "terminal"
    return out


# --------------------------------------------------- starvation economics

def starve_threshold():
    """Operator-set predicted-peak-HBM starvation threshold in bytes
    (``APEX_STARVE_HBM_BYTES``; None = no committed threshold yet —
    the §6 mode's boundary is unmeasured, so nothing is flagged by
    default: measured dispatch, not asserted dispatch)."""
    from apex_tpu.dispatch.tiles import env_int

    return env_int("APEX_STARVE_HBM_BYTES")


def starvation(peak_hbm_bytes, platform=None):
    """Pre-flight verdict for a program's predicted peak HBM:
    ``"exceeds-hbm"`` (hard infeasible on the chip),
    ``"starvation-risk"`` (above the operator-set §6 threshold), or
    None (no flag / nothing to judge)."""
    if not isinstance(peak_hbm_bytes, (int, float)) or peak_hbm_bytes <= 0:
        return None
    cap = hbm_capacity_for(platform)
    if cap and peak_hbm_bytes > cap:
        return "exceeds-hbm"
    thresh = starve_threshold()
    if thresh and peak_hbm_bytes > thresh:
        return "starvation-risk"
    return None


# -------------------------------------------------------------- validation

def validate(block):
    """Schema problems for one cost block (empty list = clean). Fed by
    ``ledger.validate_record`` for every record carrying ``cost`` —
    a malformed block could silently mis-attribute a headline gap."""
    problems = []
    if not isinstance(block, dict):
        return ["cost is not a dict"]
    for field in FIELDS:
        if field not in block:
            problems.append(f"missing field {field!r}")
    for field in _NUMERIC_FIELDS:
        v = block.get(field)
        if v is not None and (not isinstance(v, (int, float))
                              or isinstance(v, bool) or v < 0):
            problems.append(f"{field} is not a non-negative number")
    src = block.get("source")
    if src is not None and src not in ("compiled", "lowered",
                                       "eval_shape"):
        problems.append(f"source {src!r} not in "
                        f"('compiled', 'lowered', 'eval_shape')")
    steps = block.get("steps")
    if steps is not None and (not isinstance(steps, int)
                              or isinstance(steps, bool) or steps <= 0):
        problems.append("steps is not a positive int")
    mem = block.get("memory")
    if mem is not None:
        if not isinstance(mem, dict):
            problems.append("memory is not a dict")
        else:
            for k in _MEMORY_KEYS:
                v = mem.get(k)
                if v is not None and (not isinstance(v, int)
                                      or isinstance(v, bool) or v < 0):
                    problems.append(
                        f"memory.{k} is not a non-negative int")
    comm = block.get("comm_bytes_per_axis")
    if comm is not None:
        if not isinstance(comm, dict):
            problems.append("comm_bytes_per_axis is not a dict")
        else:
            for k, v in comm.items():
                if not isinstance(k, str) or not isinstance(
                        v, (int, float)) or isinstance(v, bool) or v < 0:
                    problems.append(
                        f"comm_bytes_per_axis[{k!r}] is not a "
                        f"non-negative number")
    ob = block.get("overlap_bound")
    if ob is not None:
        # the overlap-bound stamp (ROADMAP 4d) — OPTIONAL (legacy
        # blocks carry none), but malformed is a finding: a broken
        # stamp could name a fake overlap gap for the next PR to
        # "close"
        if not isinstance(ob, dict):
            problems.append("overlap_bound is not a dict")
        else:
            for field in _OVERLAP_FIELDS:
                if field not in ob:
                    problems.append(
                        f"overlap_bound missing field {field!r}")
                v = ob.get(field)
                if v is not None and (not isinstance(v, (int, float))
                                      or isinstance(v, bool) or v < 0):
                    problems.append(
                        f"overlap_bound.{field} is not a non-negative "
                        f"number")
    cc = block.get("comm_compression")
    if cc is not None:
        # the quantized/hierarchical-collectives stamp — OPTIONAL
        # (legacy blocks carry none), but malformed is a finding: a
        # broken stamp could pass off a compressed row as uncompressed
        if not isinstance(cc, dict):
            problems.append("comm_compression is not a dict")
        else:
            scheme = cc.get("scheme")
            if scheme is not None and not isinstance(scheme, str):
                problems.append("comm_compression.scheme is not a "
                                "string or null")
            if not isinstance(cc.get("hierarchical"), bool):
                problems.append("comm_compression.hierarchical is not "
                                "a bool")
            blk = cc.get("block")
            if blk is not None and (not isinstance(blk, int)
                                    or isinstance(blk, bool) or blk <= 0):
                problems.append("comm_compression.block is not a "
                                "positive int")
            unc = cc.get("uncompressed_bytes_per_axis")
            if unc is not None:
                if not isinstance(unc, dict):
                    problems.append("comm_compression."
                                    "uncompressed_bytes_per_axis is "
                                    "not a dict")
                else:
                    for k, v in unc.items():
                        if not isinstance(k, str) or not isinstance(
                                v, (int, float)) or isinstance(v, bool) \
                                or v < 0:
                            problems.append(
                                f"comm_compression."
                                f"uncompressed_bytes_per_axis[{k!r}] "
                                f"is not a non-negative number")
    return problems
