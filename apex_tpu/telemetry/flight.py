"""apex_tpu.telemetry.flight — the flight recorder (ISSUE 16).

A crash-safe, append-only per-process heartbeat stream: every process
that can hold the device appends one JSON line per phase transition
(``proc_start``, ``backend_init``, ``compile_start``, ``compile_done``,
``dispatch``, ``fetch``, ``attempt_start``, ``attempt_done``,
``flush``) to ``$APEX_FLIGHT_DIR/flight-<pid>.jsonl``. Each beat
carries a wall stamp (``ts`` — for human timelines), a monotonic stamp
(``mono`` — CLOCK_MONOTONIC is system-wide, so a supervisor in another
process can age a child's beats against its own clock without trusting
wall time), the phase, pid, the harness/row label, and the watchdog's
attempt index.

Gated on ``APEX_FLIGHT_DIR`` per the ``metrics.enabled()`` precedent:
unset means :func:`beat` returns after ONE env lookup — zero cost,
behavior-identical (beats are host-side file appends; they never touch
a traced program, so the disabled-mode jaxpr identity holds by
construction and is asserted in tests/test_flight.py). Writes never
raise: a full disk or an unwritable dir degrades to a missing beat,
never a crashed harness — the recorder must not be able to kill the
flight it records.

Consumers: ``apex_tpu.resilience.flight_watch`` (heartbeat-driven
early reap of silent children), ``resilience.classify_inflight``
(advancing | slow | silent), ``tools/window_report.py`` (exact
per-attempt minute attribution), and the ``status`` surfaces
(``python -m apex_tpu.telemetry.flight status``,
``python -m apex_tpu.telemetry.ledger status``,
``probe_and_collect.sh --status``).

Stdlib-only at module level (the supervisor imports this relay-proof);
the chaos hook imports :mod:`apex_tpu.resilience.faults` lazily inside
:func:`beat` — the ``heartbeat`` fault site is how the chaos suite
scripts a slow-but-beating run (hang N seconds per beat: wall time
stretches, beats keep arriving, the supervisor must NOT reap early).
"""

import json
import os
import time

# the phase vocabulary — window_report and the tests pin against this
PHASES = (
    "proc_start", "backend_init", "compile_start", "compile_done",
    "dispatch", "fetch", "attempt_start", "attempt_done", "flush",
)


def flight_dir():
    """The armed flight directory, or None when the recorder is off."""
    return os.environ.get("APEX_FLIGHT_DIR") or None


def enabled():
    return flight_dir() is not None


def beat(phase, label=None, attempt=None, **extra):
    """Append one heartbeat; returns the record, or None when disabled
    or the write failed (never raises).

    ``label`` defaults to ``APEX_FLIGHT_ROW`` (set by the flight_watch
    supervisor so every beat names the collection row it serves);
    ``attempt`` defaults to ``APEX_BENCH_ATTEMPT`` (set by bench.py's
    watchdog on each inner attempt). The beat is written BEFORE the
    ``heartbeat`` chaos hook fires, so a scripted per-beat hang slows
    the flight without silencing it.
    """
    d = os.environ.get("APEX_FLIGHT_DIR")
    if not d:
        return None
    try:
        rec = {
            "ts": round(time.time(), 3),
            "mono": round(time.monotonic(), 3),
            "phase": phase,
            "pid": os.getpid(),
        }
        lbl = label if label is not None \
            else os.environ.get("APEX_FLIGHT_ROW")
        if lbl is not None:
            rec["label"] = lbl
        if attempt is None:
            raw = os.environ.get("APEX_BENCH_ATTEMPT")
            if raw:
                try:
                    attempt = int(raw)
                except ValueError:
                    attempt = None
        if attempt is not None:
            rec["attempt"] = attempt
        if extra:
            rec.update(extra)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, "flight-%d.jsonl" % os.getpid())
        with open(path, "a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
            f.flush()
        from apex_tpu.resilience import faults

        faults.fire("heartbeat", phase=phase)
        return rec
    except Exception:
        return None


def read_beats(d=None):
    """Every heartbeat under ``d`` (default: the armed dir), all
    ``flight-*.jsonl`` files merged, sorted by monotonic stamp.
    Unparseable lines are skipped — a torn final line (the writer was
    reaped mid-append) must not hide the beats before it."""
    d = d or flight_dir()
    beats = []
    if not d or not os.path.isdir(d):
        return beats
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return beats
    for name in names:
        if not (name.startswith("flight-") and name.endswith(".jsonl")):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict):
                        beats.append(rec)
        except OSError:
            continue
    beats.sort(key=lambda b: b["mono"]
               if isinstance(b.get("mono"), (int, float))
               else float("-inf"))
    return beats


def newest_beat(d=None):
    beats = read_beats(d)
    return beats[-1] if beats else None


def status_line(d=None, now=None):
    """One human line: the newest heartbeat's phase + age — 'is the
    window alive right now' without tailing raw logs."""
    d = d or flight_dir()
    if not d:
        return "flight: disabled (APEX_FLIGHT_DIR unset)"
    b = newest_beat(d)
    if b is None:
        return "flight: no heartbeats under %s" % d
    now = time.time() if now is None else now
    ts = b.get("ts")
    age = ("%.1fs ago" % max(0.0, now - ts)
           if isinstance(ts, (int, float)) else "age ?")
    parts = ["flight: %s (%s)" % (b.get("phase", "?"), age),
             "row=%s" % (b.get("label") or "?")]
    if b.get("attempt") is not None:
        parts.append("attempt=%s" % b["attempt"])
    parts.append("pid=%s" % b.get("pid", "?"))
    return " ".join(parts) + " [%s]" % d


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.telemetry.flight",
        description="Inspect the flight recorder (read-only).")
    sub = ap.add_subparsers(dest="cmd", required=True)
    st = sub.add_parser(
        "status", help="newest heartbeat's phase + age")
    st.add_argument("--dir", default=None,
                    help="flight dir (default: APEX_FLIGHT_DIR)")
    args = ap.parse_args(argv)
    print(status_line(args.dir))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
