"""In-step training metrics: named-scalar registry + JSONL sink.

Collection contract (the zero-cost rule): the jitted train step gates
every metric computation on :func:`enabled` — a Python bool read at
TRACE time, never a traced value — and threads the scalars out as
auxiliary outputs of the step (stacked across iterations by the
training ``lax.scan``). Disabled, the gates short-circuit to ``None``
(an empty pytree) before any jnp op is built, so the step traces to a
byte-identical jaxpr and a pinned measurement is never perturbed;
tests/test_telemetry.py asserts this. Enabled, the host fetches the
stacked scalars AFTER the timed region with the same 1-element-sync-
then-fetch pattern as the measured value — zero host callbacks (on the
axon-tunneled backend a callback dials the relay mid-program).

Providers stay pure and ungated: ``LossScaler.metrics(state)``
(amp/scaler.py) and ``optimizers.grad_norm_stats(grads)`` always return
their scalar dicts; the telemetry gate lives in the caller's
:func:`collect` / in-step ``if telemetry.enabled():`` branch. That
mirrors the repo's explicit-request-vs-preference asymmetry: the
providers honor the request verbatim, the process-wide switch is a
preference.
"""

import dataclasses
import json
import os

import numpy as np

from apex_tpu.telemetry import ledger as _ledger

# --------------------------------------------------------------------------
# enabled/disabled switch (trace-time; process-wide preference)

_FORCED = None  # programmatic override; None defers to the env knob


def enabled():
    """True when in-step metric collection is on (``APEX_TELEMETRY=1``,
    unless :func:`enable`/:func:`disable` overrode it). Read at trace
    time only — branch on it in Python, never inside traced code."""
    if _FORCED is not None:
        return _FORCED
    from apex_tpu.dispatch.tiles import env_flag

    return env_flag("APEX_TELEMETRY")


def enable():
    global _FORCED
    _FORCED = True


def disable():
    global _FORCED
    _FORCED = False


def reset_enabled():
    """Back to the env-var default (test hygiene)."""
    global _FORCED
    _FORCED = None


# --------------------------------------------------------------------------
# registry


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    name: str
    unit: str = ""
    description: str = ""


_REGISTRY = {}


def register(name, unit="", description=""):
    """Register a named metric; idempotent for an identical spec,
    ValueError on a conflicting re-registration (two harnesses silently
    disagreeing about what a name means is exactly the label drift this
    subsystem exists to prevent)."""
    spec_ = MetricSpec(name, unit, description)
    prev = _REGISTRY.get(name)
    if prev is not None and prev != spec_:
        raise ValueError(
            f"metric {name!r} already registered as {prev}, conflicting "
            f"re-registration {spec_}")
    _REGISTRY[name] = spec_
    return spec_


def spec(name):
    return _REGISTRY.get(name)


def registered():
    return dict(_REGISTRY)


# The core training-step scalars every instrumented harness shares.
register("loss", unit="nats", description="unscaled mean per-token loss")
register("loss_scale", unit="", description="dynamic loss scale (amp)")
register("overflow", unit="bool",
         description="loss-scale skip event (non-finite grads this step)")
register("unskipped", unit="steps",
         description="steps since the last overflow (scaler window)")
register("grad_norm", unit="", description="global L2 norm of the grads")
register("grad_max", unit="", description="max |g| over the grad pytree")
register("tokens_per_sec", unit="tokens/s",
         description="host-derived throughput for the run")

# Serving-loop gauges (apex_tpu.serving.lifecycle.EventLog.sample_gauges
# — one sample per scheduler round, ISSUE 11): registered here so the
# registry stays the ONE schema and EventLog.gauge_rows() can sink
# through a strict MetricsWriter without auto-registration.
register("serve_slots_active", unit="slots",
         description="decode slots holding a live request this round")
register("serve_num_slots", unit="slots",
         description="decode slot capacity of the engine")
register("serve_queue_depth", unit="requests",
         description="requests waiting for admission this round")
register("serve_kv_pages_live", unit="pages",
         description="KV cache pages allocated to live requests")
register("serve_kv_pages_total", unit="pages",
         description="KV cache page capacity (incl. reserved null page)")
register("serve_hol_wait_ms", unit="ms",
         description="age of the head-of-line queued request")
register("serve_spec_drafted", unit="tokens",
         description="cumulative speculative draft tokens proposed "
                     "(ISSUE 13; 0 with spec decode off)")
register("serve_spec_accepted", unit="tokens",
         description="cumulative speculative draft tokens accepted "
                     "by the verify program")
register("serve_prefix_hit_tokens", unit="tokens",
         description="cumulative prompt tokens served from the "
                     "prefix cache (0 with the cache off)")
register("serve_rejected", unit="requests",
         description="cumulative submits refused by admission control "
                     "(ISSUE 15; 0 with APEX_SERVE_ADMIT off)")
register("serve_shed", unit="requests",
         description="cumulative queued requests dropped by the "
                     "deadline shedder (SLO attainment impossible)")
register("serve_preempted", unit="requests",
         description="cumulative KV-pressure preemptions (pages freed, "
                     "stream requeued for prefill replay)")
register("serve_resubmitted", unit="requests",
         description="cumulative requeues back into the admission "
                     "queue (preemption + degraded-round recovery)")
register("serve_degraded_rounds", unit="rounds",
         description="cumulative serving rounds lost to a timed-out "
                     "or crashed device dispatch (watchdog recovery)")

# Fleet-router gauges (apex_tpu.serving.router.Router.gauge_rows — one
# sample per router round, ISSUE 19): 0/absent without a router.
register("serve_routed", unit="requests",
         description="cumulative requests the fleet router assigned "
                     "to a replica (ISSUE 19; absent without a router)")
register("serve_failovers", unit="requests",
         description="cumulative requests pulled off a dead replica "
                     "(queued + in-flight) for requeue-and-replay")
register("serve_replayed", unit="requests",
         description="cumulative failed-over requests resubmitted "
                     "through a surviving replica (prefill replay)")


# --------------------------------------------------------------------------
# in-step collection


def collect(metrics, **scalars):
    """Merge named scalars into the step's metrics dict.

    Disabled (trace-time), the input passes through untouched — ``None``
    stays ``None``, so an uninstrumented and a disabled-instrumented
    step build identical jaxprs. Callers must gate any *computation* of
    a scalar on :func:`enabled` themselves; ``collect`` only gates the
    carry."""
    if not enabled():
        return metrics
    out = {} if metrics is None else dict(metrics)
    out.update(scalars)
    return out


# --------------------------------------------------------------------------
# JSONL sink


def metrics_path():
    """``APEX_TELEMETRY_PATH`` or ``benchmarks/telemetry_metrics.jsonl``."""
    return (os.environ.get("APEX_TELEMETRY_PATH")
            or os.path.join(_ledger.repo_root(), "benchmarks",
                            "telemetry_metrics.jsonl"))


class MetricsWriter:
    """Append-only JSONL sink for fetched (host-side numpy) metrics.

    One row per training step: ``{"run": <ledger id or None>, "step": i,
    "<name>": <float>, ...}``. ``strict=True`` refuses unregistered
    names (the registry is the schema); the default auto-registers them
    so an exploratory harness can't lose data to bookkeeping."""

    def __init__(self, path=None, strict=False):
        self.path = path or metrics_path()
        self.strict = strict

    def _check(self, names):
        for n in names:
            if spec(n) is None:
                if self.strict:
                    raise KeyError(f"metric {n!r} not registered")
                register(n)

    def append(self, record):
        """Append one pre-built row (a plain JSON-able dict)."""
        self._check(k for k in record if k not in ("run", "step"))
        with open(self.path, "a") as f:
            f.write(json.dumps(record, sort_keys=True) + "\n")

    def append_steps(self, stacked, run=None, start_step=0):
        """Write the scan-stacked in-step scalars: ``stacked`` maps
        metric name -> array of shape [k] (scalars and shape-[1] arrays
        broadcast to every row). Mismatched [k] lengths raise ValueError
        up front — a half-written run would read as a complete one.
        Returns the number of rows written."""
        if not stacked:
            return 0
        arrays = {k: np.asarray(v) for k, v in stacked.items()}
        lengths = {a.shape[0] for a in arrays.values()
                   if a.ndim and a.shape[0] != 1}
        if len(lengths) > 1:
            raise ValueError(
                f"mismatched metric lengths {sorted(lengths)}: "
                f"{ {n: a.shape for n, a in arrays.items()} }")
        k = lengths.pop() if lengths else 1
        self._check(arrays)
        rows = []
        for i in range(k):
            row = {"step": start_step + i}
            if run is not None:
                row["run"] = run
            for name, a in arrays.items():
                row[name] = float(a[i] if a.ndim and a.shape[0] == k
                                  else a[0] if a.ndim else a)
            rows.append(row)
        with open(self.path, "a") as f:
            for row in rows:
                f.write(json.dumps(row, sort_keys=True) + "\n")
        return len(rows)


def read_metrics(path=None):
    """Read a metrics JSONL file back as a list of row dicts."""
    path = path or metrics_path()
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows
