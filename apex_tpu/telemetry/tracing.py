"""Calibrated span/timer API — ONE implementation of the PERF.md §0 rules.

Three facts (measured; the calibration experiments are in PERF.md §0)
shape every benchmark in this tree:

  1. each jit dispatch pays ~30-70 ms of relay latency — so measured
     programs run K chained iterations inside ONE ``lax.scan`` dispatch;
  2. ``block_until_ready`` resolves before device execution completes —
     so synchronization is a 1-element device fetch (:func:`sync`);
  3. a literal-0 feedback chaining the scan carry is constant-folded,
     letting XLA hoist the loop-invariant body out of the scan — so the
     chain factor ``eps`` is a TRACED runtime scalar (0.0 to warm,
     1e-30 when timing, which also defeats same-args result caching).

Before this module, those rules lived as a convention each
``benchmarks/profile_*.py`` hand-rolled around ``_timing.py``'s
primitives — and the emitted numbers carried their calibration only as
prose. :class:`Tracer` owns the scan length K and the measured
per-dispatch overhead for a run; every :class:`Span` it emits carries
that calibration metadata, and :meth:`Tracer.flush_ledger` writes the
whole run (spans + knob pins + git SHA + platform) as one
``benchmarks/ledger.jsonl`` record. ``benchmarks/_timing.py`` re-exports
the primitives, so existing call sites keep working unchanged.
"""

import dataclasses
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.telemetry import flight


def sync(x):
    """Wait for device execution by fetching one element."""
    leaf = jax.tree_util.tree_leaves(x)[0]
    return np.asarray(jnp.ravel(leaf)[:1])


def _overhead_program(k):
    """The jitted calibration scan — module-level so the warm path
    (benchmarks/warm_cache.py via bench.py's APEX_WARM_ONLY mode) can
    AOT-compile the EXACT program measure_dispatch_overhead will
    dispatch: same function, same HLO, same persistent-cache key."""
    def run(c, eps):
        def body(c, _):
            return c + eps, ()
        c, _ = lax.scan(body, c, jnp.arange(k))
        return c

    return jax.jit(run)


def measure_dispatch_overhead(k):
    """Fixed per-dispatch tunnel latency: best-of-3 trivial k-iter scans."""
    f = _overhead_program(k)
    sync(f(jnp.float32(0.0), jnp.float32(0.0)))
    best = float("inf")
    for i in range(3):
        t0 = time.perf_counter()
        sync(f(jnp.float32(0.0), jnp.float32(1e-30 * (i + 1))))
        best = min(best, time.perf_counter() - t0)
    return best


def bench_k(smoke, default=128):
    """Scan length for kernel-level microbenches (env ``APEX_BENCH_K``).

    The relay's ±30 ms dispatch-overhead variance divides by K, so sub-ms
    kernel rows need K >> 32 to resolve (~±0.25 ms at the 128 default);
    scan length does not grow the compiled program. Step-level harnesses
    (profile_gpt etc.) keep their own smaller fixed K — their rows are
    10–100 ms, where K=16–32 noise is already <5%.
    """
    from apex_tpu.dispatch.tiles import env_int

    return 2 if smoke else (env_int("APEX_BENCH_K") or default)


@dataclasses.dataclass
class Span:
    """One measured row and the calibration it was taken under.

    ``seconds`` is the per-iteration time with the dispatch overhead
    already subtracted (None when the row failed to run — ``error``
    holds the reason, so a window's failures reach the ledger too)."""

    name: str
    seconds: float  # per-iteration, overhead-subtracted; None on error
    total_s: float  # raw wall time of the timed dispatch
    k: int
    overhead_s: float
    method: str = "scan-chain"  # the PERF.md §0 protocol
    flops_per_iter: float = None
    error: str = None
    extra: dict = dataclasses.field(default_factory=dict)

    @property
    def ms(self):
        return None if self.seconds is None else self.seconds * 1e3

    def tflops(self):
        if self.seconds is None or not self.flops_per_iter:
            return None
        return self.flops_per_iter / self.seconds / 1e12

    def mfu(self, peak_flops):
        if self.seconds is None or not self.flops_per_iter or not peak_flops:
            return None
        return self.flops_per_iter / self.seconds / peak_flops

    def format_row(self, peak_flops=None, width=28, ms_prec=2):
        """The harness table row (name, ms, optional TF/s + MFU)."""
        if self.seconds is None and self.error is None \
                and self.extra.get("warm_only"):
            w = self.extra.get("warm", {})
            return (f"{self.name:{width}s} warmed "
                    f"(compile {w.get('seconds', '?')}s, "
                    f"cached={w.get('cached')})")
        if self.seconds is None:
            return f"{self.name:{width}s} FAILED: {self.error}"
        extra = ""
        if self.flops_per_iter and peak_flops:
            extra = (f"  {self.tflops():6.1f} TF/s"
                     f"  MFU={self.mfu(peak_flops) * 100:5.1f}%")
        return f"{self.name:{width}s} {self.ms:8.{ms_prec}f} ms{extra}"

    def as_record(self):
        rec = {"name": self.name,
               "ms": None if self.ms is None else round(self.ms, 4),
               "k": self.k,
               "dispatch_overhead_ms": round(self.overhead_s * 1e3, 2),
               "method": self.method}
        if self.error is not None:
            rec["error"] = self.error
        rec.update(self.extra)
        return rec


class Tracer:
    """Calibrated timing context for one harness run.

    Calibrates the per-dispatch overhead once (``overhead=`` injects a
    pre-measured value — e.g. bench.py measures before compiling), then
    times rows via :meth:`scan_time` / :meth:`time_call`; spans
    accumulate for :meth:`flush_ledger`.
    """

    def __init__(self, k, overhead=None, peak_flops=None):
        self.k = int(k)
        if overhead is not None:
            self.overhead = float(overhead)
        else:
            from apex_tpu import compile_cache

            if compile_cache.warm_only():
                # compile-only contract: never execute the calibration
                # dispatches (4 timed relay round-trips) in a warm pass
                # — the measurement would go unused (nothing is timed,
                # flush_ledger is skipped). AOT-warm its cache key
                # instead, so the scored run's calibration compile is
                # also a cache read.
                try:
                    sds = jax.ShapeDtypeStruct((), jnp.float32)
                    compile_cache.warm(_overhead_program(self.k),
                                       (sds, sds))
                except Exception:
                    pass
                self.overhead = 0.0
            else:
                self.overhead = measure_dispatch_overhead(self.k)
        self.peak_flops = peak_flops
        self.spans = []
        # the run-level attribution block (apex_tpu.telemetry.costs):
        # set by the first capture_cost=True row (or set_cost); flushed
        # with every ledger record — null-degraded when nothing captured
        self.cost = None

    @property
    def overhead_ms(self):
        return self.overhead * 1e3

    def _capture_cost(self, call, args, flops_per_iter, compiled=None,
                      comm=None, comm_compression=None, host_ms=None,
                      comm_ms=None):
        """Attribution block for one measured program (cost_analysis /
        memory_analysis via apex_tpu.telemetry.costs): ``compiled`` is
        the free-harvest path (the warm mode already paid for the AOT
        object); otherwise one extra host-side ``call.lower`` trace,
        compiled only where that is a persistent-cache read — never a
        second cold compile through the relay. Never raises; the first
        captured block becomes the run-level ``self.cost``."""
        from apex_tpu import compile_cache
        from apex_tpu.telemetry import costs

        platform = jax.devices()[0].platform
        lowered = None
        try:
            if compiled is None and hasattr(call, "lower"):
                lowered = call.lower(*args)
                if compile_cache.enabled():
                    compiled = lowered.compile()
        except Exception:
            pass
        block = costs.capture(lowered=lowered, compiled=compiled,
                              steps=self.k,
                              model_flops_per_step=flops_per_iter,
                              platform=platform, comm=comm,
                              comm_compression=comm_compression,
                              host_ms=host_ms, comm_ms=comm_ms)
        if self.cost is None:
            self.cost = block
        return block

    def time_call(self, name, call, warm_args, timed_args,
                  flops_per_iter=None, extra=None, on_fail="raise",
                  sync_out=sync, capture_cost=False, comm=None,
                  comm_compression=None, host_ms=None, comm_ms=None):
        """Warm (compile + drain) with ``warm_args``, then time one
        dispatch of ``call(*timed_args)``; per-iteration time = (wall -
        overhead) / K. The two argument tuples must differ in a traced
        value (the eps chain) or the relay may serve a cached result.
        ``on_fail="span"`` records a failed row instead of raising (the
        sweep-harness pattern: one unlowered config must not kill the
        window's remaining rows).

        Under ``APEX_WARM_ONLY=1`` (the warm-start path,
        ``apex_tpu.compile_cache``) the row is only AOT-COMPILED —
        ``call.lower(*warm_args).compile()`` populates the persistent
        cache without executing or timing anything; the returned Span
        has ``seconds=None`` and a ``warm`` extra. Non-jitted callables
        fall back to one executed warm dispatch."""
        from apex_tpu import compile_cache

        if compile_cache.warm_only():
            try:
                warm_cost = None
                # flight beats (ISSUE 16): host-side appends, no trace
                # interaction — the supervisor sees "compiling" live
                flight.beat("compile_start", span=name)
                if hasattr(call, "lower"):
                    info, compiled = compile_cache.warm(call, warm_args)
                    if capture_cost:
                        # free harvest: the warm already paid for the
                        # Compiled object (bench's warm path does the
                        # same — predicted peak HBM before any dispatch)
                        warm_cost = self._capture_cost(
                            call, warm_args, flops_per_iter,
                            compiled=compiled, comm=comm,
                            comm_compression=comm_compression,
                            host_ms=host_ms, comm_ms=comm_ms)
                else:
                    sync_out(call(*warm_args))
                    info = {"executed": True}
                flight.beat("compile_done", span=name)
                span = Span(name, None, None, self.k, self.overhead,
                            flops_per_iter=flops_per_iter,
                            extra=dict(extra or {}, warm_only=True,
                                       warm=info,
                                       **({"cost": warm_cost}
                                          if warm_cost else {})))
            except Exception as e:
                if on_fail != "span":
                    raise
                span = Span(name, None, None, self.k, self.overhead,
                            flops_per_iter=flops_per_iter,
                            error=f"{type(e).__name__}: {str(e)[:100]}",
                            extra=dict(extra or {}, warm_only=True))
            self.spans.append(span)
            return span
        # flight beats (ISSUE 16) bracket the phases a supervisor needs
        # to tell "compiling" from "dispatched, waiting on the fetch":
        # host-side file appends outside the timed region (the dispatch
        # beat lands BEFORE t0), never touching the traced program
        flight.beat("compile_start", span=name)
        try:
            sync_out(call(*warm_args))
        except Exception as e:
            if on_fail != "span":
                raise
            span = Span(name, None, None, self.k, self.overhead,
                        flops_per_iter=flops_per_iter,
                        error=f"{type(e).__name__}: {str(e)[:100]}",
                        extra=dict(extra or {}))
            self.spans.append(span)
            return span
        flight.beat("compile_done", span=name)
        flight.beat("dispatch", span=name)
        t0 = time.perf_counter()
        sync_out(call(*timed_args))
        total = time.perf_counter() - t0
        flight.beat("fetch", span=name)
        span_extra = dict(extra or {})
        if capture_cost:
            # AFTER the timed region: the lower/compile are host work
            # that must never straddle t0 (the calibration-flap class)
            span_extra["cost"] = self._capture_cost(
                call, warm_args, flops_per_iter, comm=comm,
                comm_compression=comm_compression, host_ms=host_ms,
                comm_ms=comm_ms)
        span = Span(name, (total - self.overhead) / self.k, total, self.k,
                    self.overhead, flops_per_iter=flops_per_iter,
                    extra=span_extra)
        self.spans.append(span)
        return span

    def scan_time(self, name, make_body, carry0, ops, wrap=None,
                  flops_per_iter=None, extra=None, on_fail="raise",
                  capture_cost=False, comm=None, comm_compression=None,
                  host_ms=None, comm_ms=None):
        """The §0 protocol in one call. ``make_body(eps, *ops)`` returns
        ``body(carry, t) -> (carry, metric)``; ``ops`` (big arrays) are
        jit ARGUMENTS — closure-captured constants would be inlined into
        the HLO payload and overflow the remote-compile tunnel. ``wrap``
        maps the run function before jit (e.g. a shard_map)."""
        k = self.k

        def run(carry0, eps, *ops):
            body = make_body(eps, *ops)
            return lax.scan(body, carry0, jnp.arange(k))

        f = jax.jit(run if wrap is None else wrap(run))
        return self.time_call(
            name, f, (carry0, jnp.float32(0.0)) + tuple(ops),
            (carry0, jnp.float32(1e-30)) + tuple(ops),
            flops_per_iter=flops_per_iter, extra=extra, on_fail=on_fail,
            capture_cost=capture_cost, comm=comm,
            comm_compression=comm_compression, host_ms=host_ms,
            comm_ms=comm_ms)

    def flush_ledger(self, harness, platform=None, relay=None, extra=None,
                     path=None):
        """Append this run (calibration + every span) as one ledger
        record; returns the record id (None when the write was skipped
        or failed — see ledger.append_record). Warm-only runs
        (``APEX_WARM_ONLY=1``) write nothing: a compile pass is not a
        measurement and must not look like one in the ledger. Every
        written record is stamped with the compile-cache telemetry
        block, so a PERF.md row can prove whether its numbers were
        taken compile-free."""
        from apex_tpu import compile_cache, dispatch
        from apex_tpu.telemetry import ledger

        if compile_cache.warm_only():
            return None
        flight.beat("flush", harness=harness)
        if platform is None:
            platform = jax.devices()[0].platform
        from apex_tpu.telemetry import costs

        payload = {"spans": [s.as_record() for s in self.spans],
                   "compile_cache": compile_cache.snapshot(),
                   "dispatch": dispatch.snapshot(),
                   # every Tracer record carries a validated cost block:
                   # the first capture_cost=True row's, or the explicit
                   # all-None degradation (never a silent omission)
                   "cost": self.cost if self.cost is not None
                   else costs.null_block()}
        payload.update(extra or {})
        return ledger.append_record(
            harness=harness, platform=platform,
            dispatch_overhead_ms=round(self.overhead_ms, 2), k=self.k,
            relay=relay, extra=payload, path=path)
