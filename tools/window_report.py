#!/usr/bin/env python
"""Window economics: one per-round timeline from the collection artifacts.

Rounds 4-5 got exactly ONE 50-minute relay window and no record of where
its minutes went — the §6 ordering lessons (bench-first, small-HBM-first,
warm-before-measure) were reconstructed from prose afterwards. This tool
aggregates the round's durable artifacts into one account:

* the **run ledger** (``benchmarks/ledger.jsonl``) — per-record verdicts,
  compile-cache hit/miss totals (the warm-start proof-of-work), cost-block
  coverage, the measured-MFU vs MFU-bound attribution gap, the
  ``overlap_bound`` column (compute floor vs comm+host — ROADMAP 4d),
  and the SERVING ECONOMICS section (ISSUE 11): per-trace SLO
  attainment, goodput vs the decode-scan throughput line, and
  queue/KV-page occupancy from the ``serving``/``slo`` blocks;
* a **raw log directory** (e.g. ``benchmarks/device_logs_r05``) — every
  harness log's dated backend-init banner(s) anchor the timeline: starts,
  attempt counts, per-log verdicts (via the shared resilience classifier)
  and the minutes each slot consumed before the next program started;
* the **collection manifest** (``manifest.json``) — rows cashed vs owed;
* the **probe state** — the last stamped probe verdict.

Runnable today against the committed round-5 artifacts::

    python tools/window_report.py --logs benchmarks/device_logs_r05

Exit status 0 when the report was produced (an empty round is a report,
not an error); 1 only on unreadable inputs. ``--json`` appends ONE
machine-readable JSON line (the driver-interface idiom) after the text.
"""

import argparse
import datetime
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from apex_tpu import resilience  # noqa: E402
from apex_tpu.telemetry import ledger as ledger_mod  # noqa: E402

# the dated backend-init banner every harness log opens with — the one
# wall-clock anchor the raw logs carry
BANNER_RE = re.compile(
    r"^WARNING:(\d{4}-\d{2}-\d{2} \d{2}:\d{2}:\d{2}),\d+:"
    r"jax\._src\.xla_bridge")
ROW_RE = re.compile(r"\d+\.\d+ ms")


def parse_log(path):
    """One log's timeline entry: banner timestamps (each = one backend
    init, i.e. one attempt/process), measured-row count, and the
    verdict of its last JSON line (the shared classifier) or a
    table/no-output heuristic for Tracer harnesses."""
    with open(path, errors="replace") as f:
        text = f.read()
    starts = [datetime.datetime.strptime(m.group(1), "%Y-%m-%d %H:%M:%S")
              for m in map(BANNER_RE.match, text.splitlines()) if m]
    # a measured table row, NOT the Tracer header ("... dispatch
    # overhead 75.8 ms subtracted)") every harness prints before its
    # first row — a run that wedged right after calibration must read
    # no-output, not "table"
    rows = sum(1 for line in text.splitlines()
               if ROW_RE.search(line) and "dispatch overhead" not in line)
    _, rec = resilience.last_json(text)
    if rec is not None:
        verdict = resilience.classify(rec)
    elif rows:
        # a table-printing harness: rows landed (exit status is not in
        # the log, so this is the optimistic read the manifest's
        # probe-state gate exists to police)
        verdict = "table"
    else:
        # banner only: the §10b wedge signature (fresh compile hung in
        # the remote-compile helper)
        verdict = "no-output"
    return {
        "name": os.path.basename(path),
        "starts": starts,
        "attempts": max(1, len(starts)) if (starts or text.strip()) else 0,
        "rows": rows,
        "verdict": verdict,
        "value": (rec or {}).get("value"),
        "mfu": (rec or {}).get("mfu"),
    }


def logs_timeline(logs_dir):
    """Sorted per-log timeline + slot minutes: each log's slot runs from
    its first banner to the NEXT log's first banner (the raw logs carry
    start anchors, not end anchors — the gap IS where the minutes
    went). The last slot's cost is unknowable from the logs alone."""
    entries = []
    for name in sorted(os.listdir(logs_dir)):
        if not name.endswith(".log"):
            continue
        entries.append(parse_log(os.path.join(logs_dir, name)))
    timed = sorted((e for e in entries if e["starts"]),
                   key=lambda e: e["starts"][0])
    for i, e in enumerate(timed):
        if i + 1 < len(timed):
            dt = timed[i + 1]["starts"][0] - e["starts"][0]
            e["slot_minutes"] = round(dt.total_seconds() / 60.0, 1)
        else:
            e["slot_minutes"] = None
    return entries, timed


def ledger_summary(records):
    """Aggregate the ledger's side of the account: per-harness counts,
    platform split, compile-cache totals, cost-block coverage, and the
    measured-vs-bound attribution rows."""
    by_harness = {}
    platforms = {}
    cc_hits = cc_misses = cc_records = 0
    cost_present = cost_reporting = 0
    injected = 0
    attribution = []
    comm_rows = []
    serving_rows = []
    overlap_rows = []
    for rec in records:
        by_harness[rec.get("harness", "?")] = \
            by_harness.get(rec.get("harness", "?"), 0) + 1
        platforms[rec.get("platform", "?")] = \
            platforms.get(rec.get("platform", "?"), 0) + 1
        if rec.get("fault_plan"):
            injected += 1
        cc = rec.get("compile_cache")
        if isinstance(cc, dict):
            cc_records += 1
            cc_hits += cc.get("hits") or 0
            cc_misses += cc.get("misses") or 0
        cost = rec.get("cost")
        if isinstance(cost, dict):
            cost_present += 1
            if cost.get("source"):
                cost_reporting += 1
            mfu = rec.get("mfu")
            bound = cost.get("mfu_bound")
            if mfu is not None and bound is not None:
                attribution.append({
                    "id": rec.get("id"), "harness": rec.get("harness"),
                    "mfu": mfu, "mfu_bound": bound,
                    "step_floor_ms": cost.get("step_floor_ms"),
                    "peak_hbm_bytes": cost.get("peak_hbm_bytes"),
                })
            # the comm column: per-axis collective payload from the
            # cost block, compressed-vs-uncompressed where the record
            # carries the collectives stamp — comm gets attributed the
            # same way flops do (ROADMAP item 3)
            comm = cost.get("comm_bytes_per_axis")
            if isinstance(comm, dict) and comm:
                stamp = cost.get("comm_compression") \
                    if isinstance(cost.get("comm_compression"), dict) \
                    else {}
                comm_rows.append({
                    "id": rec.get("id"), "harness": rec.get("harness"),
                    "bytes_per_axis": comm,
                    "scheme": stamp.get("scheme"),
                    "hierarchical": stamp.get("hierarchical"),
                    "uncompressed_bytes_per_axis":
                        stamp.get("uncompressed_bytes_per_axis"),
                })
            # the overlap column (ROADMAP 4d, costs.overlap_bound):
            # compute floor vs measured comm+host — the gap every
            # future overlap/scheduler PR is chasing, named per record
            ob = cost.get("overlap_bound")
            if isinstance(ob, dict):
                row = dict(ob, id=rec.get("id"),
                           harness=rec.get("harness"))
                # the ISSUE 14 columns: which overlap schedules the
                # record claims it measured under, and the jaxpr-level
                # collective-schedule verdict (interleaved/terminal)
                cs = rec.get("collective_schedule")
                if isinstance(cs, dict):
                    row["schedule_verdict"] = cs.get("verdict")
                claim = rec.get("overlap")
                if isinstance(claim, dict):
                    row["claim"] = claim
                overlap_rows.append(row)
        # serving economics (ISSUE 11): per-trace SLO attainment,
        # goodput vs decode-throughput gap, occupancy high-waters —
        # one row per record carrying a serving and/or slo block
        sv = rec.get("serving")
        slo = rec.get("slo")
        if isinstance(sv, dict) or isinstance(slo, dict):
            sv = sv if isinstance(sv, dict) else {}
            slo = slo if isinstance(slo, dict) else None
            serving_rows.append({
                "id": rec.get("id"), "harness": rec.get("harness"),
                "trace_id": sv.get("trace_id"),
                "tokens_per_s": sv.get("tokens_per_s"),
                "scan_tokens_per_s": sv.get("scan_tokens_per_s"),
                "kv_pages": sv.get("kv_pages"),
                # generation economics (ISSUE 13): None-when-disabled
                "spec_acceptance_rate": sv.get("spec_acceptance_rate"),
                "draft_len": sv.get("draft_len"),
                "prefix_hit_rate": sv.get("prefix_hit_rate"),
                "slo": slo,
            })
    ts = [r["ts"] for r in records
          if isinstance(r.get("ts"), (int, float))]
    return {
        "records": len(records),
        "by_harness": by_harness,
        "platforms": platforms,
        "span": ([_fmt_ts(min(ts)), _fmt_ts(max(ts))] if ts else None),
        "compile_cache": {"records": cc_records, "hits": cc_hits,
                          "misses": cc_misses},
        "cost_blocks": {"present": cost_present,
                        "reporting": cost_reporting},
        "injected": injected,
        "attribution": attribution,
        "comm": comm_rows,
        "overlap": overlap_rows,
        "serving": serving_rows,
    }


def _fmt_ts(ts):
    return datetime.datetime.fromtimestamp(ts).strftime(
        "%Y-%m-%d %H:%M:%S")


def manifest_summary(path):
    try:
        from apex_tpu.resilience import manifest as manifest_mod

        data = manifest_mod.load(path)
        rows = data.get("rows", {}) if isinstance(data, dict) else {}
        cashed = sorted(manifest_mod.cashed_rows(path))
        owed = [r for r in manifest_mod.PASS_ROWS if r not in cashed]
        return {"cashed": cashed, "owed": owed,
                "verdicts": {name: (entry or {}).get("verdict")
                             for name, entry in sorted(rows.items())}}
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def probe_summary(path):
    try:
        with open(path) as f:
            state = json.load(f)
        if not isinstance(state, dict):
            return {"error": "probe state is not a JSON object"}
        out = {"verdict": state.get("verdict"), "rc": state.get("rc"),
               "detail": state.get("detail")}
        if isinstance(state.get("ts"), (int, float)):
            out["at"] = _fmt_ts(state["ts"])
        return out
    except FileNotFoundError:
        return None
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def build_report(ledger_path=None, logs_dir=None, manifest_path=None,
                 probe_state=None):
    report = {}
    if ledger_path and os.path.exists(ledger_path):
        report["ledger"] = ledger_summary(ledger_mod.read_ledger(
            ledger_path))
    if logs_dir:
        entries, timed = logs_timeline(logs_dir)
        window = None
        if timed:
            t0 = timed[0]["starts"][0]
            t1 = max(e["starts"][-1] for e in timed)
            window = {
                "start": t0.strftime("%Y-%m-%d %H:%M:%S"),
                "last_activity": t1.strftime("%Y-%m-%d %H:%M:%S"),
                "minutes": round((t1 - t0).total_seconds() / 60.0, 1),
            }
        report["logs"] = {
            "dir": logs_dir,
            "window": window,
            "timeline": [{k: (v if k != "starts" else
                              [s.strftime("%H:%M:%S") for s in v])
                          for k, v in e.items()}
                         for e in (timed or entries)],
            "unanchored": [e["name"] for e in entries
                           if not e["starts"]],
        }
    if manifest_path:
        report["manifest"] = manifest_summary(manifest_path)
    if probe_state:
        report["probe"] = probe_summary(probe_state)
    return report


def print_report(report, out=None):
    out = out or sys.stdout  # resolved at call time, not import time
    p = lambda s="": print(s, file=out)  # noqa: E731
    led = report.get("ledger")
    if led:
        p(f"ledger: {led['records']} record(s)"
          + (f", {led['injected']} fault-injected" if led["injected"]
             else ""))
        if led["span"]:
            p(f"  span: {led['span'][0]} .. {led['span'][1]}")
        plat = ", ".join(f"{k}={v}" for k, v in
                         sorted(led["platforms"].items()))
        p(f"  platforms: {plat}")
        for h in sorted(led["by_harness"]):
            p(f"  {h:24s} {led['by_harness'][h]}")
        cc = led["compile_cache"]
        p(f"  compile cache: {cc['hits']} hit(s) / {cc['misses']} "
          f"miss(es) across {cc['records']} stamped record(s)")
        cb = led["cost_blocks"]
        p(f"  cost blocks: {cb['present']} present, {cb['reporting']} "
          f"with XLA numbers")
        for a in led["attribution"]:
            gap = (f", gap {a['mfu_bound'] - a['mfu']:.3f}"
                   if a["mfu_bound"] >= a["mfu"] else " (ABOVE bound — "
                   "check the model)")
            p(f"  attribution {a['id']} ({a['harness']}): measured MFU "
              f"{a['mfu']:.3f} vs bound {a['mfu_bound']:.3f}{gap}")
        for c in led.get("comm", []):
            axes = " ".join(f"{k}={int(v)}B" for k, v in
                            sorted(c["bytes_per_axis"].items()))
            line = (f"  comm {c['id']} ({c['harness']}): {axes}")
            if c.get("scheme") or c.get("hierarchical"):
                unc = c.get("uncompressed_bytes_per_axis") or {}
                unc_s = " ".join(f"{k}={int(v)}B" for k, v in
                                 sorted(unc.items()))
                line += (f" [scheme={c['scheme']}"
                         f" hier={bool(c['hierarchical'])}"
                         + (f" uncompressed: {unc_s}" if unc_s else "")
                         + "]")
            p(line)
        for o in led.get("overlap", []):
            def _ms(v):
                return "?" if v is None else f"{v:g} ms"
            line = (f"  overlap {o['id']} ({o['harness']}): compute "
                    f"floor {_ms(o.get('compute_floor_ms'))} vs "
                    f"comm+host {_ms(o.get('comm_host_ms'))}")
            if o.get("hideable_ms") is not None:
                line += (f" -> hideable {_ms(o['hideable_ms'])}, best "
                         f"overlapped step {_ms(o.get('bound_step_ms'))}")
            if o.get("schedule_verdict"):
                line += f" [schedule={o['schedule_verdict']}]"
            claim = o.get("claim")
            if isinstance(claim, dict):
                bits = " ".join(f"{k}={v}" for k, v in
                                sorted(claim.items()) if v is not None)
                if bits:
                    line += f" [{bits}]"
            p(line)
        if led.get("serving"):
            p("  serving economics:")
            for s in led["serving"]:
                tps = s.get("tokens_per_s")
                scan = s.get("scan_tokens_per_s")
                line = (f"    {s['id']} ({s['harness']}) "
                        f"[{s.get('trace_id') or '?'}]: "
                        f"{'?' if tps is None else format(tps, 'g')} "
                        f"tok/s replay")
                if scan:
                    line += f" vs {scan:g} tok/s decode-scan upper line"
                p(line)
                # generation economics (ISSUE 13): the speculation and
                # prefix-sharing levers, printed only when measured —
                # None-when-disabled never renders a phantom rate
                gen = []
                if s.get("spec_acceptance_rate") is not None:
                    gen.append(
                        f"spec acceptance="
                        f"{s['spec_acceptance_rate']:.0%}"
                        + (f" (draft len {s['draft_len']:g})"
                           if s.get("draft_len") is not None else ""))
                if s.get("prefix_hit_rate") is not None:
                    gen.append(
                        f"prefix hit={s['prefix_hit_rate']:.0%}")
                if gen:
                    p(f"      generation: {', '.join(gen)}")
                slo = s.get("slo")
                if slo:
                    att = slo.get("slo_attainment")
                    good = slo.get("goodput_tok_s")
                    gap = None
                    if good is not None and scan:
                        gap = 1.0 - good / scan
                    # resilience economics (ISSUE 15): shed / preempt
                    # rates + degraded-round count next to attainment
                    # — None-when-disabled never renders a phantom
                    res = []
                    if slo.get("shed_rate") is not None:
                        res.append(f"shed={slo['shed_rate']:.0%}")
                    if slo.get("preempt_rate") is not None:
                        res.append(
                            f"preempt={slo['preempt_rate']:.0%}")
                    if slo.get("degraded_rounds") is not None:
                        res.append(
                            f"degraded_rounds="
                            f"{slo['degraded_rounds']}")
                    p(f"      slo: arrival={slo.get('arrival_process')} "
                      f"offered={slo.get('offered_load')} req/tick, "
                      f"attainment="
                      f"{'?' if att is None else format(att, '.0%')} "
                      f"(ttft<={slo.get('slo_ttft_ms')}ms "
                      f"tpot<={slo.get('slo_tpot_ms')}ms), goodput "
                      f"{'?' if good is None else format(good, 'g')} "
                      f"tok/s"
                      + ("" if gap is None else
                         f" ({gap:.0%} under the scan line)")
                      + (f" [{', '.join(res)}]" if res else ""))
                    p(f"      tails: ttft p50/p99 "
                      f"{slo.get('ttft_p50_ms')}/"
                      f"{slo.get('ttft_p99_ms')} ms, per-token p50/p99 "
                      f"{slo.get('per_token_p50_ms')}/"
                      f"{slo.get('per_token_p99_ms')} ms; max queue "
                      f"{slo.get('max_queue_depth')}, kv high-water "
                      f"{slo.get('kv_page_high_water')}"
                      + (f"/{s['kv_pages']} pages"
                         if s.get("kv_pages") else ""))
    logs = report.get("logs")
    if logs:
        p(f"logs: {logs['dir']}")
        w = logs["window"]
        if w:
            p(f"  window: {w['start']} .. {w['last_activity']} "
              f"({w['minutes']} min of anchored activity)")
        for e in logs["timeline"]:
            starts = e.get("starts") or []
            slot = (f"{e['slot_minutes']:5.1f} min"
                    if e.get("slot_minutes") is not None else "  end   ")
            extra = ""
            if e.get("value") is not None:
                extra = f" value={e['value']}"
                if e.get("mfu") is not None:
                    extra += f" mfu={e['mfu']}"
            elif e.get("rows"):
                extra = f" {e['rows']} row(s)"
            p(f"  {starts[0] if starts else '--:--:--'}  "
              f"{e['name']:26s} {slot}  {e['attempts']} attempt(s)  "
              f"{e['verdict']}{extra}")
        if logs["unanchored"]:
            p(f"  unanchored (no dated banner): "
              f"{', '.join(logs['unanchored'])}")
    man = report.get("manifest")
    if man:
        if "error" in man:
            p(f"manifest: unreadable ({man['error']})")
        else:
            p(f"manifest: {len(man['cashed'])} cashed / "
              f"{len(man['owed'])} owed")
            if man["cashed"]:
                p(f"  cashed: {', '.join(man['cashed'])}")
            if man["owed"]:
                p(f"  owed:   {', '.join(man['owed'])}")
    probe = report.get("probe")
    if probe is not None:
        if "error" in probe:
            p(f"probe: unreadable ({probe['error']})")
        else:
            p(f"probe: last verdict {probe.get('verdict')} "
              f"at {probe.get('at', '?')} ({probe.get('detail', '')})")
    if not report:
        p("nothing to report (no readable inputs)")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ledger",
                    default=os.path.join(REPO, "benchmarks",
                                         "ledger.jsonl"))
    ap.add_argument("--logs", default=None,
                    help="raw harness log directory "
                         "(e.g. benchmarks/device_logs_r05)")
    ap.add_argument("--manifest", default=None,
                    help="collection manifest.json (cashed/owed rows)")
    ap.add_argument("--probe-state", default=None,
                    help="probe state file (last stamped verdict)")
    ap.add_argument("--json", action="store_true",
                    help="append one machine-readable JSON line")
    args = ap.parse_args(argv)

    try:
        report = build_report(ledger_path=args.ledger, logs_dir=args.logs,
                              manifest_path=args.manifest,
                              probe_state=args.probe_state)
    except (OSError, ValueError) as e:
        print(f"FAIL: {e}")
        return 1
    print_report(report)
    if args.json:
        print(json.dumps(report, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
