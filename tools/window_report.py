#!/usr/bin/env python
"""Window economics: one per-round timeline from the collection artifacts.

Rounds 4-5 got exactly ONE 50-minute relay window and no record of where
its minutes went — the §6 ordering lessons (bench-first, small-HBM-first,
warm-before-measure) were reconstructed from prose afterwards. This tool
aggregates the round's durable artifacts into one account:

* the **run ledger** (``benchmarks/ledger.jsonl``) — per-record verdicts,
  compile-cache hit/miss totals (the warm-start proof-of-work), cost-block
  coverage, the measured-MFU vs MFU-bound attribution gap, the
  ``overlap_bound`` column (compute floor vs comm+host — ROADMAP 4d),
  and the SERVING ECONOMICS section (ISSUE 11): per-trace SLO
  attainment, goodput vs the decode-scan throughput line, and
  queue/KV-page occupancy from the ``serving``/``slo`` blocks;
* the **flight recorder** (``apex_tpu.telemetry.flight``, ISSUE 16) —
  when a round carries heartbeat streams (``--flight``), they are the
  PRIMARY timeline: exact per-process compile / dispatch->fetch minute
  attribution from phase beats (monotonic deltas, not banner
  inference), per-row totals, and the supervisor's reap account
  (``flight_reap`` ledger records: minutes reclaimed from
  heartbeat-silent wedges). The raw-log banner timeline below stays as
  the fallback for rounds that predate the recorder;
* a **raw log directory** (e.g. ``benchmarks/device_logs_r05``) — every
  harness log's dated backend-init banner(s) anchor the fallback
  timeline: starts, attempt counts, per-log verdicts (via the shared
  resilience classifier) and the minutes each slot consumed before the
  next program started;
* the **collection manifest** (``manifest.json``) — rows cashed vs owed;
* the **probe state** — the last stamped probe verdict.

``--watch`` turns the report into a live status loop: newest heartbeat
(phase + age), recent beats, probe verdict and the manifest account,
re-rendered every ``--interval`` seconds.

Runnable today against the committed round-5 artifacts::

    python tools/window_report.py --logs benchmarks/device_logs_r05

Exit status 0 when the report was produced (an empty round is a report,
not an error); 1 only on unreadable inputs. ``--json`` appends ONE
machine-readable JSON line (the driver-interface idiom) after the text.
"""

import argparse
import datetime
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from apex_tpu import resilience  # noqa: E402
from apex_tpu.telemetry import flight as flight_mod  # noqa: E402
from apex_tpu.telemetry import ledger as ledger_mod  # noqa: E402

# the dated backend-init banner every harness log opens with — the one
# wall-clock anchor the raw logs carry
BANNER_RE = re.compile(
    r"^WARNING:(\d{4}-\d{2}-\d{2} \d{2}:\d{2}:\d{2}),\d+:"
    r"jax\._src\.xla_bridge")
ROW_RE = re.compile(r"\d+\.\d+ ms")


def parse_log(path):
    """One log's timeline entry: banner timestamps (each = one backend
    init, i.e. one attempt/process), measured-row count, and the
    verdict of its last JSON line (the shared classifier) or a
    table/no-output heuristic for Tracer harnesses."""
    with open(path, errors="replace") as f:
        text = f.read()
    starts = [datetime.datetime.strptime(m.group(1), "%Y-%m-%d %H:%M:%S")
              for m in map(BANNER_RE.match, text.splitlines()) if m]
    # a measured table row, NOT the Tracer header ("... dispatch
    # overhead 75.8 ms subtracted)") every harness prints before its
    # first row — a run that wedged right after calibration must read
    # no-output, not "table"
    rows = sum(1 for line in text.splitlines()
               if ROW_RE.search(line) and "dispatch overhead" not in line)
    _, rec = resilience.last_json(text)
    if rec is not None:
        verdict = resilience.classify(rec)
    elif rows:
        # a table-printing harness: rows landed (exit status is not in
        # the log, so this is the optimistic read the manifest's
        # probe-state gate exists to police)
        verdict = "table"
    else:
        # banner only: the §10b wedge signature (fresh compile hung in
        # the remote-compile helper)
        verdict = "no-output"
    return {
        "name": os.path.basename(path),
        "starts": starts,
        "attempts": max(1, len(starts)) if (starts or text.strip()) else 0,
        "rows": rows,
        "verdict": verdict,
        "value": (rec or {}).get("value"),
        "mfu": (rec or {}).get("mfu"),
    }


def logs_timeline(logs_dir):
    """Sorted per-log timeline + slot minutes: each log's slot runs from
    its first banner to the NEXT log's first banner (the raw logs carry
    start anchors, not end anchors — the gap IS where the minutes
    went). The last slot's cost is unknowable from the logs alone."""
    entries = []
    for name in sorted(os.listdir(logs_dir)):
        if not name.endswith(".log"):
            continue
        entries.append(parse_log(os.path.join(logs_dir, name)))
    timed = sorted((e for e in entries if e["starts"]),
                   key=lambda e: e["starts"][0])
    for i, e in enumerate(timed):
        if i + 1 < len(timed):
            dt = timed[i + 1]["starts"][0] - e["starts"][0]
            e["slot_minutes"] = round(dt.total_seconds() / 60.0, 1)
        else:
            e["slot_minutes"] = None
    return entries, timed


def ledger_summary(records):
    """Aggregate the ledger's side of the account: per-harness counts,
    platform split, compile-cache totals, cost-block coverage, and the
    measured-vs-bound attribution rows."""
    by_harness = {}
    platforms = {}
    cc_hits = cc_misses = cc_records = 0
    cost_present = cost_reporting = 0
    injected = 0
    attribution = []
    comm_rows = []
    serving_rows = []
    overlap_rows = []
    router_rows = []
    for rec in records:
        by_harness[rec.get("harness", "?")] = \
            by_harness.get(rec.get("harness", "?"), 0) + 1
        platforms[rec.get("platform", "?")] = \
            platforms.get(rec.get("platform", "?"), 0) + 1
        if rec.get("fault_plan"):
            injected += 1
        cc = rec.get("compile_cache")
        if isinstance(cc, dict):
            cc_records += 1
            cc_hits += cc.get("hits") or 0
            cc_misses += cc.get("misses") or 0
        cost = rec.get("cost")
        if isinstance(cost, dict):
            cost_present += 1
            if cost.get("source"):
                cost_reporting += 1
            mfu = rec.get("mfu")
            bound = cost.get("mfu_bound")
            if mfu is not None and bound is not None:
                attribution.append({
                    "id": rec.get("id"), "harness": rec.get("harness"),
                    "mfu": mfu, "mfu_bound": bound,
                    "step_floor_ms": cost.get("step_floor_ms"),
                    "peak_hbm_bytes": cost.get("peak_hbm_bytes"),
                })
            # the comm column: per-axis collective payload from the
            # cost block, compressed-vs-uncompressed where the record
            # carries the collectives stamp — comm gets attributed the
            # same way flops do (ROADMAP item 3)
            comm = cost.get("comm_bytes_per_axis")
            if isinstance(comm, dict) and comm:
                stamp = cost.get("comm_compression") \
                    if isinstance(cost.get("comm_compression"), dict) \
                    else {}
                comm_rows.append({
                    "id": rec.get("id"), "harness": rec.get("harness"),
                    "bytes_per_axis": comm,
                    "scheme": stamp.get("scheme"),
                    "hierarchical": stamp.get("hierarchical"),
                    "uncompressed_bytes_per_axis":
                        stamp.get("uncompressed_bytes_per_axis"),
                })
            # the overlap column (ROADMAP 4d, costs.overlap_bound):
            # compute floor vs measured comm+host — the gap every
            # future overlap/scheduler PR is chasing, named per record
            ob = cost.get("overlap_bound")
            if isinstance(ob, dict):
                row = dict(ob, id=rec.get("id"),
                           harness=rec.get("harness"))
                # the ISSUE 14 columns: which overlap schedules the
                # record claims it measured under, and the jaxpr-level
                # collective-schedule verdict (interleaved/terminal)
                cs = rec.get("collective_schedule")
                if isinstance(cs, dict):
                    row["schedule_verdict"] = cs.get("verdict")
                claim = rec.get("overlap")
                if isinstance(claim, dict):
                    row["claim"] = claim
                overlap_rows.append(row)
        # serving economics (ISSUE 11): per-trace SLO attainment,
        # goodput vs decode-throughput gap, occupancy high-waters —
        # one row per record carrying a serving and/or slo block
        sv = rec.get("serving")
        slo = rec.get("slo")
        if isinstance(sv, dict) or isinstance(slo, dict):
            sv = sv if isinstance(sv, dict) else {}
            slo = slo if isinstance(slo, dict) else None
            serving_rows.append({
                "id": rec.get("id"), "harness": rec.get("harness"),
                "trace_id": sv.get("trace_id"),
                "tokens_per_s": sv.get("tokens_per_s"),
                "scan_tokens_per_s": sv.get("scan_tokens_per_s"),
                "kv_pages": sv.get("kv_pages"),
                # dispatch economics (ISSUE 17): decode_steps counts
                # DISPATCHES — tokens/dispatch is the K-block
                # amortization of the per-dispatch relay floor
                "decode_steps": sv.get("decode_steps"),
                "tokens_generated": sv.get("tokens_generated"),
                # generation economics (ISSUE 13): None-when-disabled
                "spec_acceptance_rate": sv.get("spec_acceptance_rate"),
                "draft_len": sv.get("draft_len"),
                "prefix_hit_rate": sv.get("prefix_hit_rate"),
                # KV-tier economics (ISSUE 20): None-when-disabled
                "kv_quant": sv.get("kv_quant"),
                "swap_rate": sv.get("swap_rate"),
                "swapped_pages_high_water":
                    sv.get("swapped_pages_high_water"),
                "slo": slo,
            })
        # fleet economics (ISSUE 19): the router block — utilization
        # spread, failover/replay account, per-policy prefix hit rates
        # — one row per record carrying it
        rt = rec.get("router")
        if isinstance(rt, dict):
            router_rows.append(dict(rt, id=rec.get("id"),
                                    harness=rec.get("harness")))
    ts = [r["ts"] for r in records
          if isinstance(r.get("ts"), (int, float))]
    return {
        "records": len(records),
        "by_harness": by_harness,
        "platforms": platforms,
        "span": ([_fmt_ts(min(ts)), _fmt_ts(max(ts))] if ts else None),
        "compile_cache": {"records": cc_records, "hits": cc_hits,
                          "misses": cc_misses},
        "cost_blocks": {"present": cost_present,
                        "reporting": cost_reporting},
        "injected": injected,
        "attribution": attribution,
        "comm": comm_rows,
        "overlap": overlap_rows,
        "serving": serving_rows,
        "router": router_rows,
    }


def _fmt_ts(ts):
    return datetime.datetime.fromtimestamp(ts).strftime(
        "%Y-%m-%d %H:%M:%S")


def manifest_summary(path):
    try:
        from apex_tpu.resilience import manifest as manifest_mod

        data = manifest_mod.load(path)
        rows = data.get("rows", {}) if isinstance(data, dict) else {}
        cashed = sorted(manifest_mod.cashed_rows(path))
        owed = [r for r in manifest_mod.PASS_ROWS if r not in cashed]
        return {"cashed": cashed, "owed": owed,
                "verdicts": {name: (entry or {}).get("verdict")
                             for name, entry in sorted(rows.items())}}
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def probe_summary(path):
    try:
        with open(path) as f:
            state = json.load(f)
        if not isinstance(state, dict):
            return {"error": "probe state is not a JSON object"}
        out = {"verdict": state.get("verdict"), "rc": state.get("rc"),
               "detail": state.get("detail")}
        if isinstance(state.get("ts"), (int, float)):
            out["at"] = _fmt_ts(state["ts"])
        return out
    except FileNotFoundError:
        return None
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def _flight_process(beats):
    """One process's beat stream -> exact minute attribution. Durations
    are MONOTONIC deltas between phase beats of the same pid (the §0
    concern — wall clocks can step — does not apply to mono stamps),
    so compile and dispatch->fetch minutes are measured, not inferred
    from banner gaps."""
    beats = [b for b in beats
             if isinstance(b.get("mono"), (int, float))
             and not isinstance(b.get("mono"), bool)]
    if not beats:
        return None
    compile_s = measure_s = 0.0
    pend_compile = pend_dispatch = None
    attempts = 0
    phases = {}
    label = None
    for b in beats:
        ph = b.get("phase")
        phases[ph] = phases.get(ph, 0) + 1
        if b.get("label"):
            label = b["label"]
        if ph == "compile_start":
            pend_compile = b["mono"]
        elif ph == "compile_done" and pend_compile is not None:
            compile_s += max(0.0, b["mono"] - pend_compile)
            pend_compile = None
        elif ph == "dispatch":
            pend_dispatch = b["mono"]
        elif ph == "fetch" and pend_dispatch is not None:
            measure_s += max(0.0, b["mono"] - pend_dispatch)
            pend_dispatch = None
        elif ph == "attempt_start":
            attempts += 1
    ts = [b.get("ts") for b in beats
          if isinstance(b.get("ts"), (int, float))]
    return {
        "pid": beats[0].get("pid"),
        "label": label,
        "start": _fmt_ts(min(ts)) if ts else None,
        "last_beat": _fmt_ts(max(ts)) if ts else None,
        "minutes": round((beats[-1]["mono"] - beats[0]["mono"]) / 60.0, 2),
        "beats": len(beats),
        "last_phase": beats[-1].get("phase"),
        "compile_open": pend_compile is not None,  # died mid-compile
        "compile_minutes": round(compile_s / 60.0, 2),
        "measure_minutes": round(measure_s / 60.0, 2),
        "attempts": attempts,
        "phases": phases,
    }


def flight_summary(flight_dir, records=()):
    """The PRIMARY timeline (ISSUE 16): per-process phase accounts from
    the heartbeat streams, per-row totals, and the supervisor's reap
    account from ``flight_reap`` ledger records (minutes a silent wedge
    would have burnt vs what it actually got)."""
    all_beats = flight_mod.read_beats(flight_dir)
    by_pid = {}
    for b in all_beats:
        by_pid.setdefault(b.get("pid"), []).append(b)
    procs = [p for p in (_flight_process(bs) for bs in by_pid.values())
             if p is not None]
    procs.sort(key=lambda p: (p["start"] or "", p["pid"] or 0))
    by_label = {}
    for pr in procs:
        row = by_label.setdefault(pr["label"] or "?", {
            "processes": 0, "minutes": 0.0, "compile_minutes": 0.0,
            "measure_minutes": 0.0})
        row["processes"] += 1
        for k in ("minutes", "compile_minutes", "measure_minutes"):
            row[k] = round(row[k] + pr[k], 2)
    reaps = []
    reclaimed = 0.0
    for rec in records:
        fr = rec.get("flight_reap")
        if not isinstance(fr, dict):
            continue
        saved_s = max(0.0, (fr.get("timeout_s") or 0)
                      - (fr.get("elapsed_s") or 0))
        reaps.append({
            "id": rec.get("id"), "row": fr.get("row"),
            "reason": fr.get("reason"), "verdict": fr.get("verdict"),
            "elapsed_s": fr.get("elapsed_s"),
            "timeout_s": fr.get("timeout_s"),
            "last_phase": fr.get("last_phase"),
            "reclaimed_minutes": round(saved_s / 60.0, 1),
        })
        reclaimed += saved_s
    ts = [b.get("ts") for b in all_beats
          if isinstance(b.get("ts"), (int, float))]
    window = None
    if ts:
        window = {"start": _fmt_ts(min(ts)),
                  "last_activity": _fmt_ts(max(ts)),
                  "minutes": round((max(ts) - min(ts)) / 60.0, 1)}
    return {
        "dir": flight_dir,
        "window": window,
        "processes": procs,
        "by_label": by_label,
        "reaps": reaps,
        "reclaimed_minutes": round(reclaimed / 60.0, 1),
    }


def build_report(ledger_path=None, logs_dir=None, manifest_path=None,
                 probe_state=None, flight_dir=None):
    report = {}
    records = []
    if ledger_path and os.path.exists(ledger_path):
        records = ledger_mod.read_ledger(ledger_path)
        report["ledger"] = ledger_summary(records)
    if flight_dir and os.path.isdir(flight_dir):
        fl = flight_summary(flight_dir, records)
        if fl["processes"] or fl["reaps"]:
            report["flight"] = fl
    if logs_dir:
        entries, timed = logs_timeline(logs_dir)
        window = None
        if timed:
            t0 = timed[0]["starts"][0]
            t1 = max(e["starts"][-1] for e in timed)
            window = {
                "start": t0.strftime("%Y-%m-%d %H:%M:%S"),
                "last_activity": t1.strftime("%Y-%m-%d %H:%M:%S"),
                "minutes": round((t1 - t0).total_seconds() / 60.0, 1),
            }
        report["logs"] = {
            "dir": logs_dir,
            "window": window,
            "timeline": [{k: (v if k != "starts" else
                              [s.strftime("%H:%M:%S") for s in v])
                          for k, v in e.items()}
                         for e in (timed or entries)],
            "unanchored": [e["name"] for e in entries
                           if not e["starts"]],
        }
    if manifest_path:
        report["manifest"] = manifest_summary(manifest_path)
    if probe_state:
        report["probe"] = probe_summary(probe_state)
    return report


def print_report(report, out=None):
    out = out or sys.stdout  # resolved at call time, not import time
    p = lambda s="": print(s, file=out)  # noqa: E731
    led = report.get("ledger")
    if led:
        p(f"ledger: {led['records']} record(s)"
          + (f", {led['injected']} fault-injected" if led["injected"]
             else ""))
        if led["span"]:
            p(f"  span: {led['span'][0]} .. {led['span'][1]}")
        plat = ", ".join(f"{k}={v}" for k, v in
                         sorted(led["platforms"].items()))
        p(f"  platforms: {plat}")
        for h in sorted(led["by_harness"]):
            p(f"  {h:24s} {led['by_harness'][h]}")
        cc = led["compile_cache"]
        p(f"  compile cache: {cc['hits']} hit(s) / {cc['misses']} "
          f"miss(es) across {cc['records']} stamped record(s)")
        cb = led["cost_blocks"]
        p(f"  cost blocks: {cb['present']} present, {cb['reporting']} "
          f"with XLA numbers")
        for a in led["attribution"]:
            gap = (f", gap {a['mfu_bound'] - a['mfu']:.3f}"
                   if a["mfu_bound"] >= a["mfu"] else " (ABOVE bound — "
                   "check the model)")
            p(f"  attribution {a['id']} ({a['harness']}): measured MFU "
              f"{a['mfu']:.3f} vs bound {a['mfu_bound']:.3f}{gap}")
        for c in led.get("comm", []):
            axes = " ".join(f"{k}={int(v)}B" for k, v in
                            sorted(c["bytes_per_axis"].items()))
            line = (f"  comm {c['id']} ({c['harness']}): {axes}")
            if c.get("scheme") or c.get("hierarchical"):
                unc = c.get("uncompressed_bytes_per_axis") or {}
                unc_s = " ".join(f"{k}={int(v)}B" for k, v in
                                 sorted(unc.items()))
                line += (f" [scheme={c['scheme']}"
                         f" hier={bool(c['hierarchical'])}"
                         + (f" uncompressed: {unc_s}" if unc_s else "")
                         + "]")
            p(line)
        for o in led.get("overlap", []):
            def _ms(v):
                return "?" if v is None else f"{v:g} ms"
            line = (f"  overlap {o['id']} ({o['harness']}): compute "
                    f"floor {_ms(o.get('compute_floor_ms'))} vs "
                    f"comm+host {_ms(o.get('comm_host_ms'))}")
            if o.get("hideable_ms") is not None:
                line += (f" -> hideable {_ms(o['hideable_ms'])}, best "
                         f"overlapped step {_ms(o.get('bound_step_ms'))}")
            if o.get("schedule_verdict"):
                line += f" [schedule={o['schedule_verdict']}]"
            claim = o.get("claim")
            if isinstance(claim, dict):
                bits = " ".join(f"{k}={v}" for k, v in
                                sorted(claim.items()) if v is not None)
                if bits:
                    line += f" [{bits}]"
            p(line)
        if led.get("serving"):
            p("  serving economics:")
            for s in led["serving"]:
                tps = s.get("tokens_per_s")
                scan = s.get("scan_tokens_per_s")
                line = (f"    {s['id']} ({s['harness']}) "
                        f"[{s.get('trace_id') or '?'}]: "
                        f"{'?' if tps is None else format(tps, 'g')} "
                        f"tok/s replay")
                if scan:
                    line += f" vs {scan:g} tok/s decode-scan upper line"
                p(line)
                # dispatch economics (ISSUE 17): how many tokens each
                # ~65 ms relay dispatch bought — the K-block lever;
                # the slo block's decode_block_k names the program K
                # the trade was measured at
                toks = s.get("tokens_generated")
                steps = s.get("decode_steps")
                dk = (s.get("slo") or {}).get("decode_block_k") \
                    if isinstance(s.get("slo"), dict) else None
                if toks is not None and steps:
                    per = toks / steps
                    p(f"      dispatch economics: {per:.2f} "
                      f"tokens/dispatch ({toks} tok / {steps} "
                      f"decode dispatches"
                      + ("" if dk is None else
                         f", decode_block_k={dk}") + ")")
                # generation economics (ISSUE 13): the speculation and
                # prefix-sharing levers, printed only when measured —
                # None-when-disabled never renders a phantom rate
                gen = []
                if s.get("spec_acceptance_rate") is not None:
                    gen.append(
                        f"spec acceptance="
                        f"{s['spec_acceptance_rate']:.0%}"
                        + (f" (draft len {s['draft_len']:g})"
                           if s.get("draft_len") is not None else ""))
                if s.get("prefix_hit_rate") is not None:
                    gen.append(
                        f"prefix hit={s['prefix_hit_rate']:.0%}")
                if gen:
                    p(f"      generation: {', '.join(gen)}")
                # KV-tier economics (ISSUE 20): codec + swap/restore
                # levers, printed only when measured
                kv = []
                if s.get("kv_quant") is not None:
                    kv.append("kv=int8")
                if s.get("swap_rate") is not None:
                    kv.append(f"swap rate={s['swap_rate']:.0%}"
                              + (f" (pages hw "
                                 f"{s['swapped_pages_high_water']})"
                                 if s.get("swapped_pages_high_water")
                                 is not None else ""))
                if kv:
                    p(f"      kv tier: {', '.join(kv)}")
                slo = s.get("slo")
                if slo:
                    att = slo.get("slo_attainment")
                    good = slo.get("goodput_tok_s")
                    gap = None
                    if good is not None and scan:
                        gap = 1.0 - good / scan
                    # resilience economics (ISSUE 15): shed / preempt
                    # rates + degraded-round count next to attainment
                    # — None-when-disabled never renders a phantom
                    res = []
                    if slo.get("shed_rate") is not None:
                        res.append(f"shed={slo['shed_rate']:.0%}")
                    if slo.get("preempt_rate") is not None:
                        res.append(
                            f"preempt={slo['preempt_rate']:.0%}")
                    if slo.get("degraded_rounds") is not None:
                        res.append(
                            f"degraded_rounds="
                            f"{slo['degraded_rounds']}")
                    p(f"      slo: arrival={slo.get('arrival_process')} "
                      f"offered={slo.get('offered_load')} req/tick, "
                      f"attainment="
                      f"{'?' if att is None else format(att, '.0%')} "
                      f"(ttft<={slo.get('slo_ttft_ms')}ms "
                      f"tpot<={slo.get('slo_tpot_ms')}ms), goodput "
                      f"{'?' if good is None else format(good, 'g')} "
                      f"tok/s"
                      + ("" if gap is None else
                         f" ({gap:.0%} under the scan line)")
                      + (f" [{', '.join(res)}]" if res else ""))
                    p(f"      tails: ttft p50/p99 "
                      f"{slo.get('ttft_p50_ms')}/"
                      f"{slo.get('ttft_p99_ms')} ms, per-token p50/p99 "
                      f"{slo.get('per_token_p50_ms')}/"
                      f"{slo.get('per_token_p99_ms')} ms; max queue "
                      f"{slo.get('max_queue_depth')}, kv high-water "
                      f"{slo.get('kv_page_high_water')}"
                      + (f"/{s['kv_pages']} pages"
                         if s.get("kv_pages") else ""))
        if led.get("router"):
            # FLEET (ISSUE 19): the router block next to the per-engine
            # serving economics — fleet goodput, how evenly the
            # replicas shared the load, the failover/replay account,
            # and what each routing policy bought in prefix hits
            p("  fleet:")
            for rt in led["router"]:
                good = rt.get("fleet_goodput_tok_s")
                sp = rt.get("util_spread")
                p(f"    {rt['id']} ({rt['harness']}) "
                  f"[{rt.get('trace_id') or '?'}]: "
                  f"policy={rt.get('route_policy')} "
                  f"replicas={rt.get('replicas')}, fleet goodput "
                  f"{'?' if good is None else format(good, 'g')} tok/s, "
                  f"util spread "
                  f"{'?' if sp is None else format(sp, '.1%')}")
                p(f"      failover: {rt.get('failovers')} failed over, "
                  f"{rt.get('replayed_requests')} replayed "
                  f"({rt.get('requests')} routed, "
                  f"{rt.get('completed')} completed; rejected "
                  f"fleet={rt.get('rejected_fleet')} "
                  f"replica={rt.get('rejected_replica')})")
                p(f"      tails (cross-replica): ttft p99 "
                  f"{rt.get('ttft_p99_ms')} ms, tpot p99 "
                  f"{rt.get('tpot_p99_ms')} ms")
                hr = rt.get("prefix_hit_rate_by_policy")
                if isinstance(hr, dict) and hr:
                    bits = ", ".join(
                        f"{k}={v:.0%}" for k, v in sorted(hr.items()))
                    p(f"      prefix hit-rate by policy: {bits}")
    fl = report.get("flight")
    if fl:
        p(f"flight: {fl['dir']} (primary timeline — exact phase "
          f"minutes from heartbeats)")
        w = fl["window"]
        if w:
            p(f"  window: {w['start']} .. {w['last_activity']} "
              f"({w['minutes']} min of recorded activity)")
        for pr in fl["processes"]:
            start = (pr["start"] or "?").split(" ")[-1]
            extra = ""
            if pr["attempts"]:
                extra += f"  {pr['attempts']} attempt(s)"
            if pr["compile_open"]:
                extra += "  DIED MID-COMPILE"
            p(f"  {start}  {str(pr['label'] or '?'):26s} "
              f"{pr['minutes']:6.1f} min  compile {pr['compile_minutes']:g}"
              f" min  dispatch->fetch {pr['measure_minutes']:g} min  "
              f"last={pr['last_phase']} pid={pr['pid']}{extra}")
        if fl["by_label"]:
            p("  per-row totals:")
            for name in sorted(fl["by_label"]):
                row = fl["by_label"][name]
                p(f"    {name:26s} {row['minutes']:6.1f} min across "
                  f"{row['processes']} process(es)  (compile "
                  f"{row['compile_minutes']:g}, dispatch->fetch "
                  f"{row['measure_minutes']:g})")
        for r in fl["reaps"]:
            p(f"  reap {r['id']} row={r['row']}: {r['reason']} "
              f"(verdict={r['verdict']}) after {r['elapsed_s']}s of a "
              f"{r['timeout_s']}s cap — reclaimed "
              f"{r['reclaimed_minutes']} min (last phase "
              f"{r['last_phase']})")
        if fl["reaps"]:
            p(f"  reclaimed by early reap: {fl['reclaimed_minutes']} min")
    logs = report.get("logs")
    if logs:
        fallback = " (fallback timeline — banner inference)" \
            if report.get("flight") else ""
        p(f"logs: {logs['dir']}{fallback}")
        w = logs["window"]
        if w:
            p(f"  window: {w['start']} .. {w['last_activity']} "
              f"({w['minutes']} min of anchored activity)")
        for e in logs["timeline"]:
            starts = e.get("starts") or []
            slot = (f"{e['slot_minutes']:5.1f} min"
                    if e.get("slot_minutes") is not None else "  end   ")
            extra = ""
            if e.get("value") is not None:
                extra = f" value={e['value']}"
                if e.get("mfu") is not None:
                    extra += f" mfu={e['mfu']}"
            elif e.get("rows"):
                extra = f" {e['rows']} row(s)"
            p(f"  {starts[0] if starts else '--:--:--'}  "
              f"{e['name']:26s} {slot}  {e['attempts']} attempt(s)  "
              f"{e['verdict']}{extra}")
        if logs["unanchored"]:
            p(f"  unanchored (no dated banner): "
              f"{', '.join(logs['unanchored'])}")
    man = report.get("manifest")
    if man:
        if "error" in man:
            p(f"manifest: unreadable ({man['error']})")
        else:
            p(f"manifest: {len(man['cashed'])} cashed / "
              f"{len(man['owed'])} owed")
            if man["cashed"]:
                p(f"  cashed: {', '.join(man['cashed'])}")
            if man["owed"]:
                p(f"  owed:   {', '.join(man['owed'])}")
    probe = report.get("probe")
    if probe is not None:
        if "error" in probe:
            p(f"probe: unreadable ({probe['error']})")
        else:
            p(f"probe: last verdict {probe.get('verdict')} "
              f"at {probe.get('at', '?')} ({probe.get('detail', '')})")
    if not report:
        p("nothing to report (no readable inputs)")


def watch_once(flight_dir, manifest_path=None, probe_state=None,
               out=None):
    """One frame of the live status view: newest heartbeat (phase +
    age), the last few beats, probe verdict, manifest account."""
    out = out or sys.stdout
    p = lambda s="": print(s, file=out)  # noqa: E731
    p(flight_mod.status_line(flight_dir))
    beats = flight_mod.read_beats(flight_dir)
    for b in beats[-5:]:
        ts = b.get("ts")
        when = (_fmt_ts(ts).split(" ")[-1]
                if isinstance(ts, (int, float))
                and not isinstance(ts, bool) else "?")
        bits = [f"  {when}  {str(b.get('phase', '?')):14s} "
                f"pid={b.get('pid', '?')}"]
        if b.get("label"):
            bits.append(f"row={b['label']}")
        if b.get("attempt") is not None:
            bits.append(f"attempt={b['attempt']}")
        p(" ".join(bits))
    if probe_state:
        probe = probe_summary(probe_state)
        if probe is None:
            p("probe: no state file yet")
        elif "error" in probe:
            p(f"probe: unreadable ({probe['error']})")
        else:
            p(f"probe: last verdict {probe.get('verdict')} "
              f"at {probe.get('at', '?')} ({probe.get('detail', '')})")
    if manifest_path:
        man = manifest_summary(manifest_path)
        if "error" in man:
            p(f"manifest: unreadable ({man['error']})")
        else:
            p(f"manifest: {len(man['cashed'])} cashed / "
              f"{len(man['owed'])} owed"
              + (f" (owed: {', '.join(man['owed'])})"
                 if man["owed"] else ""))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ledger",
                    default=os.path.join(REPO, "benchmarks",
                                         "ledger.jsonl"))
    ap.add_argument("--logs", default=None,
                    help="raw harness log directory "
                         "(e.g. benchmarks/device_logs_r05)")
    ap.add_argument("--manifest", default=None,
                    help="collection manifest.json (cashed/owed rows)")
    ap.add_argument("--probe-state", default=None,
                    help="probe state file (last stamped verdict)")
    ap.add_argument("--flight", default=None,
                    help="flight-recorder heartbeat dir (ISSUE 16) — "
                         "the primary timeline when present "
                         "(default: APEX_FLIGHT_DIR)")
    ap.add_argument("--watch", action="store_true",
                    help="live status loop: newest heartbeat + probe + "
                         "manifest, re-rendered every --interval s")
    ap.add_argument("--interval", type=float, default=10.0,
                    help="seconds between --watch frames")
    ap.add_argument("--iterations", type=int, default=0,
                    help="stop --watch after N frames (0 = until ^C)")
    ap.add_argument("--json", action="store_true",
                    help="append one machine-readable JSON line")
    args = ap.parse_args(argv)

    flight_dir = args.flight or os.environ.get("APEX_FLIGHT_DIR")
    if args.watch:
        if not flight_dir:
            print("FAIL: --watch needs a flight dir "
                  "(--flight or APEX_FLIGHT_DIR)")
            return 1
        import time as _time

        n = 0
        try:
            while True:
                watch_once(flight_dir, manifest_path=args.manifest,
                           probe_state=args.probe_state)
                n += 1
                if args.iterations and n >= args.iterations:
                    return 0
                _time.sleep(max(0.1, args.interval))
                print()
        except KeyboardInterrupt:
            return 0

    try:
        report = build_report(ledger_path=args.ledger, logs_dir=args.logs,
                              manifest_path=args.manifest,
                              probe_state=args.probe_state,
                              flight_dir=flight_dir)
    except (OSError, ValueError) as e:
        print(f"FAIL: {e}")
        return 1
    print_report(report)
    if args.json:
        print(json.dumps(report, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
