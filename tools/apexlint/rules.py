"""The six invariant rules. Each is a function
``(repo, config, report, reference_root=None) -> [Finding]`` walking
already-parsed ASTs; nothing here imports repo code (see core.py).
"""

import ast
import os
import re

from tools.apexlint.core import Finding

APEX_NAME_RE = re.compile(r"APEX_[A-Z0-9_]+")


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------

def _is_environ(node, ctx):
    """True for ``os.environ`` (any os alias, or direct import)."""
    if isinstance(node, ast.Attribute) and node.attr == "environ" \
            and isinstance(node.value, ast.Name) \
            and node.value.id in ctx.os_aliases:
        return True
    if isinstance(node, ast.Name):
        return any(alias == node.id and orig == "environ"
                   for alias, orig in ctx.direct_env_names)
    return False


def _is_getenv_call(node, ctx):
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "getenv" \
            and isinstance(f.value, ast.Name) and f.value.id in ctx.os_aliases:
        return True
    if isinstance(f, ast.Name):
        return any(alias == f.id and orig == "getenv"
                   for alias, orig in ctx.direct_env_names)
    return False


def _literal_str(node, ctx):
    """Resolve a node to a string: literal constant, or a module-level
    ``NAME = "..."`` constant (the faults.py ``ENV`` pattern)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return ctx.str_constants.get(node.id)
    return None


def iter_env_reads(ctx):
    """Yield ``(node, name_or_None)`` for every os.environ/os.getenv
    READ in the file: ``environ.get/getenv calls``, ``environ[k]``
    loads, ``k in environ`` tests, ``environ.setdefault``. Writes
    (``environ[k] = v``, ``pop``) are not reads."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            if _is_getenv_call(node, ctx):
                arg = node.args[0] if node.args else None
                yield node, _literal_str(arg, ctx)
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("get", "setdefault") \
                    and _is_environ(node.func.value, ctx):
                arg = node.args[0] if node.args else None
                yield node, _literal_str(arg, ctx)
        elif isinstance(node, ast.Subscript) \
                and isinstance(getattr(node, "ctx", None), ast.Load) \
                and _is_environ(node.value, ctx):
            yield node, _literal_str(node.slice, ctx)
        elif isinstance(node, ast.Compare) \
                and any(isinstance(op, (ast.In, ast.NotIn))
                        for op in node.ops) \
                and any(_is_environ(c, ctx) for c in node.comparators):
            yield node, _literal_str(node.left, ctx)


def iter_env_writes(ctx):
    """Yield ``(node, name_or_None)`` for env WRITES: subscript
    stores, ``pop``, and the subprocess-env idiom
    ``dict(os.environ, APEX_X="1")``."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) \
                        and _is_environ(t.value, ctx):
                    yield t, _literal_str(t.slice, ctx)
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "pop" \
                    and _is_environ(f.value, ctx) and node.args:
                yield node, _literal_str(node.args[0], ctx)
            elif isinstance(f, ast.Name) and f.id == "dict" \
                    and node.args and _is_environ(node.args[0], ctx):
                for kw in node.keywords:
                    if kw.arg:
                        yield node, kw.arg


def iter_helper_reads(ctx, helper_names):
    """Yield ``(node, name)`` for ``env_int("APEX_X")``-style calls to
    the one-home parsers (any receiver: ``tiles.env_int`` or a direct
    import)."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        fname = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if fname in helper_names and node.args:
            name = _literal_str(node.args[0], ctx)
            if name:
                yield node, name


# ---------------------------------------------------------------------------
# APX001 — no import-time env reads in apex_tpu/
# ---------------------------------------------------------------------------

def apx001(repo, config, report, reference_root=None):
    findings = []
    for ctx in repo.ctxs(config.SCOPE_PKG):
        import_time = _import_time_nodes(ctx.tree)
        reads = list(iter_env_reads(ctx))
        # the one-home parsers count too: env_flag(...) at module level
        # is the same frozen-at-import knob, just better dressed
        reads += list(iter_helper_reads(ctx, config.ENV_HELPERS))
        for node, name in reads:
            if id(node) in import_time:
                what = name or "os.environ"
                findings.append(Finding(
                    "APX001", ctx.path, node.lineno,
                    f"import-time env read ({what}) — knobs are read at "
                    "TRACE time; move inside a function (PERF.md §0 / "
                    "ISSUE 5)"))
    return findings


def _import_time_nodes(tree):
    """ids of nodes evaluated at import: everything except function
    bodies (decorators and argument defaults DO run at import)."""
    ids = set()

    def mark(node):
        ids.add(id(node))
        for child in ast.iter_child_nodes(node):
            mark(child)

    def visit(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.decorator_list:
                mark(d)
            for default in (node.args.defaults + node.args.kw_defaults):
                if default is not None:
                    mark(default)
            return  # body is call-time
        if isinstance(node, ast.Lambda):
            return  # body is call-time
        ids.add(id(node))
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(tree)
    return ids


# ---------------------------------------------------------------------------
# APX002 — APEX_* raw reads outside the one-home parsers / allowlist
# ---------------------------------------------------------------------------

def _reader_entry(path, knob, config):
    """Index of the DESIGNATED_READERS entry covering this (file,
    knob) read, or None — the ONE matcher shared by allowlisting and
    stale-entry accounting (two copies could desynchronize)."""
    for i, (entry_path, spec, _reason) in enumerate(
            config.DESIGNATED_READERS):
        if path != entry_path:
            continue
        if spec.endswith("*"):
            if knob.startswith(spec[:-1]):
                return i
        elif knob == spec:
            return i
    return None


def apx002(repo, config, report, reference_root=None):
    findings = []
    hit_entries = set()
    for ctx in repo.ctxs(config.SCOPE_NONTEST):
        for node, name in iter_env_reads(ctx):
            if not name or not name.startswith("APEX_"):
                continue
            entry = _reader_entry(ctx.path, name, config)
            if entry is not None:
                hit_entries.add(entry)
                continue
            findings.append(Finding(
                "APX002", ctx.path, node.lineno,
                f"raw env read of {name} outside its designated reader "
                "— parse through dispatch.tiles.env_int/env_choice/"
                "env_float/env_flag, or add a DESIGNATED_READERS entry "
                "naming this file the knob's one home"))
    # allowlist hygiene: an entry no raw read matches is rot (the
    # check_api_parity stale-allowlist pattern). Only judged for files
    # present in the scanned tree — fixture trees carry a subset; a
    # DELETED file's entries are caught by the tier-1 test asserting
    # every configured path exists in the real repo.
    for i, (p, spec, _r) in enumerate(config.DESIGNATED_READERS):
        if i not in hit_entries and repo.exists(p):
            findings.append(Finding(
                "APX002", "tools/apexlint (config)", 0,
                f"stale DESIGNATED_READERS entry ({p}, {spec}) — no raw "
                "read matches it; prune"))
    return findings


# ---------------------------------------------------------------------------
# APX003 — knob registry: code uses == docs table + infra coverage
# ---------------------------------------------------------------------------

def _infra_prefixes(repo, config):
    """``ledger.INFRA_KNOB_PREFIXES`` read via AST, never import."""
    ctx = repo.ctx(config.LEDGER_PY)
    if ctx is None:
        return None
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name)
                and t.id == "INFRA_KNOB_PREFIXES" for t in node.targets):
            if isinstance(node.value, (ast.Tuple, ast.List)):
                return tuple(
                    e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str))
    return None


def _documented_knobs(repo, config):
    """Knob names from the docs/API.md table between the apexlint
    markers — the machine-checkable shape: every knob fully spelled
    inside backticks in each row's first cell."""
    if not repo.exists(config.API_MD):
        return None, 0
    text = repo.read_text(config.API_MD)
    begin = text.find(config.KNOB_TABLE_BEGIN)
    end = text.find(config.KNOB_TABLE_END)
    if begin < 0 or end < 0 or end < begin:
        return None, 0
    table = text[begin:end]
    line0 = text[:begin].count("\n") + 1
    knobs = {}
    for i, line in enumerate(table.splitlines()):
        if not line.lstrip().startswith("|"):
            continue
        cells = re.split(r"(?<!\\)\|", line)  # \| is a literal pipe
        first_cell = cells[1] if len(cells) >= 3 else ""
        for span in re.findall(r"`([^`]+)`", first_cell):
            for m in APEX_NAME_RE.finditer(span):
                knobs.setdefault(m.group(0), line0 + i)
    return knobs, line0


def apx003(repo, config, report, reference_root=None):
    findings = []
    prefixes = _infra_prefixes(repo, config)
    if prefixes is None:
        findings.append(Finding(
            "APX003", config.LEDGER_PY, 1,
            "could not extract INFRA_KNOB_PREFIXES (literal tuple "
            "expected)"))
        prefixes = ()
    documented, _ = _documented_knobs(repo, config)
    if documented is None:
        findings.append(Finding(
            "APX003", config.API_MD, 1,
            f"knob table markers missing ({config.KNOB_TABLE_BEGIN} … "
            f"{config.KNOB_TABLE_END}) — the table must be "
            "machine-checkable"))
        documented = {}

    used = {}  # name -> first (path, line)
    helper_names = config.ENV_HELPERS
    for ctx in repo.ctxs(config.SCOPE_NONTEST):
        for it in (iter_env_reads(ctx), iter_env_writes(ctx),
                   iter_helper_reads(ctx, helper_names)):
            for node, name in it:
                if name and name.startswith("APEX_"):
                    used.setdefault(name, (ctx.path,
                                           getattr(node, "lineno", 1)))
    for shell in config.SHELLS:
        if not repo.exists(shell):
            continue
        for i, line in enumerate(repo.read_text(shell).splitlines(),
                                 start=1):
            if line.lstrip().startswith("#"):
                # a comment naming a knob is prose, not a use — else a
                # stale mention would mask the no-op-row direction
                continue
            for m in APEX_NAME_RE.finditer(line):
                used.setdefault(m.group(0), (shell, i))

    for name in sorted(set(used) - set(documented)):
        if any(name.startswith(p) for p in prefixes):
            continue  # infra-covered (ledger.INFRA_KNOB_PREFIXES)
        path, line = used[name]
        findings.append(Finding(
            "APX003", path, line,
            f"knob {name} is read/set in code but absent from the "
            f"docs/API.md knob table (document it or drop the read)"))
    for name in sorted(set(documented) - set(used)):
        findings.append(Finding(
            "APX003", config.API_MD, documented[name],
            f"knob {name} is documented but never read or set anywhere "
            "in non-test code — a no-op knob row (the PR 4 audit class)"))
    for p in prefixes:
        if not any(u == p or u.startswith(p) for u in used):
            findings.append(Finding(
                "APX003", config.LEDGER_PY, 1,
                f"stale INFRA_KNOB_PREFIXES entry {p!r}: no used knob "
                "matches it"))
    return findings


# ---------------------------------------------------------------------------
# APX004 — timing hygiene in benchmarks/
# ---------------------------------------------------------------------------

_TIME_ATTRS = {"time", "perf_counter", "perf_counter_ns", "monotonic",
               "monotonic_ns"}


def apx004(repo, config, report, reference_root=None):
    findings = []
    for ctx in repo.ctxs(config.SCOPE_BENCH):
        time_aliases = {"time"}
        direct = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        time_aliases.add(a.asname or "time")
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name in _TIME_ATTRS:
                        direct.add(a.asname or a.name)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            what = None
            if isinstance(f, ast.Attribute):
                if f.attr in _TIME_ATTRS and isinstance(f.value, ast.Name) \
                        and f.value.id in time_aliases:
                    what = f"time.{f.attr}()"
                elif f.attr == "block_until_ready":
                    what = "block_until_ready"
            elif isinstance(f, ast.Name) and f.id in direct:
                what = f"{f.id}()"
            if what:
                findings.append(Finding(
                    "APX004", ctx.path, node.lineno,
                    f"naked {what} in benchmarks/ — the PERF.md §0 "
                    "timing rules have ONE implementation "
                    "(apex_tpu.telemetry.tracing); use Tracer/Span, or "
                    "pragma with the reason this is not a measured row"))
    # monotonic-home extension (ISSUE 16): outside benchmarks/ (the
    # stricter full scan above), ``time.monotonic``/``monotonic_ns``
    # may only be called from the flight-recorder homes
    # (config.MONOTONIC_HOMES) — the beat stamp and the supervisor's
    # aging clock are a cross-process contract (CLOCK_MONOTONIC is
    # system-wide), and a third clock site could silently age beats
    # against a different rule than classify_inflight applies.
    mono_attrs = {"monotonic", "monotonic_ns"}
    homes = set(getattr(config, "MONOTONIC_HOMES", ()))
    for ctx in repo.ctxs(config.SCOPE_NONTEST):
        if ctx.path in homes:
            continue
        if any(ctx.path == p or ctx.path.startswith(p + "/")
               for p in config.SCOPE_BENCH):
            continue  # already covered by the full _TIME_ATTRS scan
        time_aliases = {"time"}
        direct = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        time_aliases.add(a.asname or "time")
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name in mono_attrs:
                        direct.add(a.asname or a.name)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            what = None
            if isinstance(f, ast.Attribute):
                if f.attr in mono_attrs and isinstance(f.value, ast.Name) \
                        and f.value.id in time_aliases:
                    what = f"time.{f.attr}()"
            elif isinstance(f, ast.Name) and f.id in direct:
                what = f"{f.id}()"
            if what:
                findings.append(Finding(
                    "APX004", ctx.path, node.lineno,
                    f"{what} outside the flight/tracing monotonic homes "
                    f"({', '.join(sorted(homes))}) — beat stamps and "
                    "their aging share ONE clock contract (ISSUE 16); "
                    "emit a flight.beat / use the resilience classifier, "
                    "or pragma with the reason this clock is not aging "
                    "heartbeats"))
    return findings


# ---------------------------------------------------------------------------
# APX005 — reference citations resolve (file exists, line in range)
# ---------------------------------------------------------------------------

_CITE_RE = re.compile(
    r"(?<![\w/])([A-Za-z0-9_][\w./-]*\.(?:py|cu|cpp|cuh|h|cc))"
    r":(\d+)(?:\s*[-–]\s*(\d+))?")


class _RefIndex:
    def __init__(self, ref_root):
        self.root = ref_root
        self.paths = []
        for dirpath, dirnames, filenames in os.walk(ref_root):
            dirnames[:] = [d for d in dirnames if d != ".git"]
            for f in filenames:
                self.paths.append(os.path.relpath(
                    os.path.join(dirpath, f), ref_root))
        self._nlines = {}

    def candidates(self, cited):
        cands = [p for p in self.paths
                 if p == cited or p.endswith("/" + cited)]
        if not cands and "/" not in cited:
            cands = [p for p in self.paths
                     if os.path.basename(p) == cited]
        return cands

    def nlines(self, rel):
        if rel not in self._nlines:
            try:
                with open(os.path.join(self.root, rel), "rb") as fh:
                    self._nlines[rel] = fh.read().count(b"\n") + 1
            except OSError:
                self._nlines[rel] = 0
        return self._nlines[rel]


def _docstrings(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            if (node.body and isinstance(node.body[0], ast.Expr)
                    and isinstance(node.body[0].value, ast.Constant)
                    and isinstance(node.body[0].value.value, str)):
                yield node.body[0].value


def apx005(repo, config, report, reference_root=None):
    ref_root = reference_root or config.REFERENCE_ROOT
    if not os.path.isdir(ref_root):
        report.notes.append(
            f"APX005 skipped: reference tree not found at {ref_root}")
        return []
    index = _RefIndex(ref_root)
    repo_suffixes = None  # lazily-built set for repo self-citations
    findings = []
    for ctx in repo.ctxs(config.SCOPE_CITED):
        for doc in _docstrings(ctx.tree):
            text = doc.value
            if "reference" not in text.lower():
                continue
            for m in _CITE_RE.finditer(text):
                cited, a, b = m.group(1), int(m.group(2)), m.group(3)
                line_in_doc = text.count("\n", 0, m.start())
                at = doc.lineno + line_in_doc
                cands = index.candidates(cited)
                if not cands:
                    if repo_suffixes is None:
                        repo_suffixes = repo.walk_py(
                            ("apex_tpu", "benchmarks", "tools", "tests"))
                    if any(p == cited or p.endswith("/" + cited)
                           or os.path.basename(p) == cited
                           for p in repo_suffixes):
                        continue  # repo self-citation, not a reference one
                    findings.append(Finding(
                        "APX005", ctx.path, at,
                        f"citation {m.group(0)!r} does not resolve under "
                        f"{ref_root}"))
                    continue
                end = int(b) if b else a
                if not any(index.nlines(c) >= end for c in cands):
                    best = max(index.nlines(c) for c in cands)
                    findings.append(Finding(
                        "APX005", ctx.path, at,
                        f"citation {m.group(0)!r}: line out of range "
                        f"(resolved file has {best} lines)"))
    return findings


# ---------------------------------------------------------------------------
# APX006 — stdlib-only claims hold, transitively over the import graph
# ---------------------------------------------------------------------------

def _module_rel(repo, dotted):
    """apex_tpu.x.y -> repo-relative file, resolving pkg __init__."""
    base = dotted.replace(".", "/")
    for cand in (base + ".py", base + "/__init__.py"):
        if repo.exists(cand):
            return cand
    return None


def _module_level_imports(ctx):
    """(dotted_module, lineno) for every import executed at import time
    (module body, incl. try/if blocks; ``if TYPE_CHECKING`` skipped).
    Relative imports are resolved against the module's own package so
    ``from .kv_cache import x`` cannot slip past the walk."""
    pkg_parts = ctx.path[:-3].replace("/", ".").split(".")
    if pkg_parts[-1] == "__init__":
        pkg_parts = pkg_parts[:-1]      # the package itself
    else:
        pkg_parts = pkg_parts[:-1]      # the containing package
    out = []

    def visit(body):
        for node in body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    out.append((a.name, node.lineno))
            elif isinstance(node, ast.ImportFrom):
                if node.level == 0:
                    base = [node.module] if node.module else None
                else:
                    up = node.level - 1
                    anchor = pkg_parts[:len(pkg_parts) - up] if up else \
                        list(pkg_parts)
                    if not anchor:
                        continue  # escapes the tree — nothing to walk
                    base = anchor + ([node.module] if node.module else [])
                if base is None:
                    continue
                mod = ".".join(base)
                for a in node.names:
                    out.append((f"{mod}.{a.name}", node.lineno))
            elif isinstance(node, ast.If):
                test = ast.dump(node.test)
                if "TYPE_CHECKING" in test:
                    continue
                visit(node.body)
                visit(node.orelse)
            elif isinstance(node, ast.Try):
                visit(node.body)
                for h in node.handlers:
                    visit(h.body)
                visit(node.orelse)
                visit(node.finalbody)
    visit(ctx.tree.body)
    return out


def apx006(repo, config, report, reference_root=None):
    findings = []
    claimed = []
    for spec in config.STDLIB_ONLY_CLAIMED:
        # absent paths are skipped: fixture trees carry a subset, and
        # deletion rot is caught by the tier-1 config-paths-exist test
        if spec.endswith("/"):
            if repo.exists(spec.rstrip("/")):
                claimed.extend(repo.walk_py((spec.rstrip("/"),)))
        elif repo.exists(spec):
            claimed.append(spec)

    def offenders(rel, seen):
        """(dotted, via_chain) for every denylisted module-level import
        reachable from ``rel`` over explicit in-package imports. The
        documented parent-package exception applies: importing
        apex_tpu.x.y executes apex_tpu/__init__ (~3s, noted in the
        resilience docstring) but only explicitly-imported TARGET
        modules are walked."""
        if rel in seen:
            return []
        seen.add(rel)
        ctx = repo.ctx(rel)
        if ctx is None:
            return []
        out = []
        for dotted, lineno in _module_level_imports(ctx):
            top = dotted.split(".")[0]
            if top in config.STDLIB_DENYLIST:
                out.append((top, f"{rel}:{lineno}"))
            elif top == "apex_tpu":
                target = _module_rel(repo, dotted)
                if target is None and "." in dotted:
                    # "from apex_tpu.mod import name" where name is a
                    # def — resolve the module instead
                    target = _module_rel(repo, dotted.rsplit(".", 1)[0])
                if target and target != "apex_tpu/__init__.py":
                    for top2, via in offenders(target, seen):
                        out.append((top2, f"{rel}:{lineno} -> {via}"))
        return out

    for rel in claimed:
        ctx = repo.ctx(rel)
        if ctx is None:
            continue
        # one finding per offending import chain, anchored at the
        # claimed module's own import line so a fix has an address
        for top, via in offenders(rel, set()):
            line = int(via.split(" -> ")[0].rsplit(":", 1)[1])
            findings.append(Finding(
                "APX006", rel, line,
                f"stdlib-only module reaches a module-level import of "
                f"{top} (via {via})"))
    return findings


RULES = {
    "APX001": apx001,
    "APX002": apx002,
    "APX003": apx003,
    "APX004": apx004,
    "APX005": apx005,
    "APX006": apx006,
}
