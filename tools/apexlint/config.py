"""Rule scopes and allowlists. Every entry here is itself policed:
a DESIGNATED_READERS row no raw read matches, or a
STDLIB_ONLY_CLAIMED path that does not exist, is a finding (the
check_api_parity stale-allowlist discipline — config rot must not
accumulate silently).
"""

# ---------------------------------------------------------------------------
# scopes (repo-relative; dirs are walked recursively, .py only)
# ---------------------------------------------------------------------------

SCOPE_PKG = ("apex_tpu",)
SCOPE_BENCH = ("benchmarks",)
# "outside tests": the shipped package, the harnesses, and the tools —
# examples/ are reference-ported torch demos, out of knob scope
SCOPE_NONTEST = ("apex_tpu", "benchmarks", "tools",
                 "bench.py", "__graft_entry__.py")
# citation-bearing docstrings (APX005) live everywhere code does
SCOPE_CITED = ("apex_tpu", "benchmarks", "tools",
               "bench.py", "__graft_entry__.py")

SHELLS = ("benchmarks/run_all_tpu.sh", "benchmarks/probe_and_collect.sh")
# APX004 monotonic-home extension (ISSUE 16): the only non-benchmark
# files allowed to call time.monotonic/monotonic_ns — the beat stamp,
# its one other emitter, and the supervisor that ages beats
MONOTONIC_HOMES = (
    "apex_tpu/telemetry/flight.py",
    "apex_tpu/telemetry/tracing.py",
    "apex_tpu/resilience/flight_watch.py",
)
API_MD = "docs/API.md"
LEDGER_PY = "apex_tpu/telemetry/ledger.py"
KNOB_TABLE_BEGIN = "<!-- apexlint: knob-table begin -->"
KNOB_TABLE_END = "<!-- apexlint: knob-table end -->"
REFERENCE_ROOT = "/root/reference"

# the one-home env parsers (dispatch/tiles.py + the lifecycle delegate)
# — a knob read THROUGH these is never a raw read, wherever it happens
ENV_HELPERS = frozenset(
    {"env_int", "env_nonneg_int", "env_choice", "env_float",
     "env_flag", "env_ms"})

# ---------------------------------------------------------------------------
# APX002 — designated readers: (file, knob-or-prefix*, why this file is
# the knob's one home). Raw reads anywhere else are findings.
# ---------------------------------------------------------------------------

DESIGNATED_READERS = (
    # knob owners inside the package: semantics the typed helpers can't
    # express (paths, tri-states, present-vs-absent checks)
    ("apex_tpu/dispatch/__init__.py", "APEX_DISPATCH",
     "the dispatch gate itself: present-but-off semantics"),
    ("apex_tpu/dispatch/__init__.py", "APEX_DISPATCH_TABLE",
     "table-path override; path, not a typed value"),
    ("apex_tpu/compile_cache/__init__.py", "APEX_COMPILE_CACHE",
     "tri-state hard-on/off/unset-follows-harness"),
    ("apex_tpu/compile_cache/__init__.py", "APEX_COMPILE_CACHE_DIR",
     "cache dir path"),
    ("apex_tpu/checkpoint.py", "APEX_CKPT_*",
     "durability knobs: retention 0 is legal (env_int is positive-only) "
     "and queue/async resolve once at ctor time"),
    ("apex_tpu/telemetry/ledger.py", "APEX_TELEMETRY_LEDGER",
     "ledger path override — the write-site home"),
    ("apex_tpu/telemetry/ledger.py", "APEX_FAULT_PLAN",
     "tamper-evident stamp: present-vs-absent, value hashed into ids"),
    ("apex_tpu/telemetry/metrics.py", "APEX_TELEMETRY_PATH",
     "metrics sink path"),
    ("apex_tpu/telemetry/profiling.py", "APEX_PROFILE_DIR",
     "profile artifact root path"),
    ("apex_tpu/resilience/faults.py", "APEX_FAULT_PLAN",
     "the injection engine: reads the plan json/path itself"),
    ("apex_tpu/parallel/multiproc.py", "APEX_TPU_COORDINATOR",
     "multi-process launcher wiring (addresses, not typed knobs)"),
    ("apex_tpu/parallel/multiproc.py", "APEX_TPU_NUM_PROCESSES",
     "launcher wiring"),
    ("apex_tpu/parallel/multiproc.py", "APEX_TPU_PROCESS_ID",
     "launcher wiring"),
    ("apex_tpu/parallel/collectives.py", "APEX_GRAD_COMPRESS",
     "present-but-empty/off is an explicit off-pin that also blocks "
     "the table consult (PR 8) — richer than env_choice"),
    ("apex_tpu/parallel/collectives.py", "APEX_HIER_ALLREDUCE",
     "presence-sensitive tri-state with warn-once on non-1/0 (PR 8)"),
    ("apex_tpu/contrib/fmha/fmha.py", "APEX_FMHA_DROPOUT",
     "validated raise at first use: the escape hatch is an explicit "
     "request, not a preference"),
    ("apex_tpu/resilience/__init__.py", "APEX_BENCH_*",
     "the §6 timeout-envelope home; zero is a legal value here (chaos "
     "pins RETRY_WAIT=0) which the positive-only env_int cannot "
     "express"),
    ("apex_tpu/resilience/probe.py", "APEX_PROBE_STATE",
     "CLI state-path default (path, not a typed value)"),
    ("apex_tpu/resilience/manifest.py", "APEX_PROBE_STATE",
     "CLI --probe-state default (probe_and_collect.sh exports it per "
     "round)"),
    ("apex_tpu/telemetry/costs.py", "APEX_COST_ANALYSIS",
     "tri-state hard-on/hard-off/unset-follows-harness"),
    ("apex_tpu/optimizers/fused_lamb.py", "APEX_LAMB_IMPL",
     "validated raise on unknown values (committed semantics, "
     "test-pinned; predates env_choice)"),
    ("apex_tpu/transformer/pipeline_parallel/schedules.py",
     "APEX_PP_IMPL",
     "merged with per-call impl= then validated with a raise — a "
     "typo'd knob must not pass silently"),
    # harness-side owners: bench.py / the profile drivers are the
    # arming + label-pinning sites the records are stamped from
    ("benchmarks/_knobs.py", "APEX_REMAT",
     "the documented one-home resolver for the step-harness pins "
     "(validated raise)"),
    ("benchmarks/_knobs.py", "APEX_ATTN_IMPL",
     "one-home resolver; set_default_impl validates with a raise"),
    ("benchmarks/_knobs.py", "APEX_LN_PALLAS",
     "one-home resolver; tri-state 1/0/unset"),
    ("benchmarks/_knobs.py", "APEX_FUSED_LM_HEAD",
     "one-home resolver; tri-state 1/0/unset"),
    ("bench.py", "APEX_CKPT_DIR",
     "durability arming path, consumed host-side before any trace "
     "(checkpoint.py owns the other APEX_CKPT_* semantics)"),
    ("bench.py", "APEX_BENCH_BASELINE",
     "baseline-store path redirect (the chaos-test hook)"),
    ("bench.py", "APEX_ATTN_IMPL",
     "label pin: the scored line stamps the raw pin it ran under "
     "(_knobs.apply_dispatch_knobs already validated it)"),
    ("bench.py", "APEX_LN_PALLAS",
     "label pin (tri-state mirror of _knobs)"),
    ("benchmarks/profile_gpt.py", "APEX_CKPT_DIR",
     "durability arming path (same pattern as bench.py)"),
    ("benchmarks/profile_serving.py", "APEX_DECODE_ATTN_*",
     "pin-riding: reads the incoming pin to stamp the RESOLVED "
     "values back into the env and the record's knobs (check 8)"),
    ("benchmarks/warm_cache.py", "APEX_COLLECT_MANIFEST",
     "manifest-path handoff from probe_and_collect.sh"),
    # flight recorder + supervisor (ISSUE 16)
    ("apex_tpu/telemetry/flight.py", "APEX_FLIGHT_*",
     "the recorder itself: dir path + row label, read per-beat (unset "
     "= disabled is the whole zero-cost contract — a typed helper "
     "would be a second home)"),
    ("apex_tpu/telemetry/flight.py", "APEX_BENCH_ATTEMPT",
     "beats auto-stamp the watchdog's attempt index; raw int parse "
     "because a beat must NEVER raise on a malformed value"),
    ("apex_tpu/resilience/flight_watch.py", "APEX_FLIGHT_*",
     "supervisor clock thresholds: zero and fractional seconds are "
     "legal (chaos tests pin seconds-scale silence), which the "
     "positive-int helpers cannot express; plus the pool-restore "
     "marker handoff from run_all_tpu.sh"),
    ("tools/window_report.py", "APEX_FLIGHT_DIR",
     "CLI --flight default (probe_and_collect.sh exports it per "
     "round) — path, not a typed value"),
)

# ---------------------------------------------------------------------------
# APX006 — modules whose docstrings claim stdlib-only (module-level
# imports; jax in function bodies is the documented lazy pattern)
# ---------------------------------------------------------------------------

STDLIB_ONLY_CLAIMED = (
    "apex_tpu/resilience/",
    "apex_tpu/telemetry/flight.py",
    "apex_tpu/dispatch/tiles.py",
    "apex_tpu/dispatch/__init__.py",
    "apex_tpu/serving/scheduler.py",
    "apex_tpu/serving/lifecycle.py",
    "apex_tpu/serving/speculative.py",
    "apex_tpu/serving/prefix_cache.py",
    "apex_tpu/serving/router.py",
    "apex_tpu/compile_cache/__init__.py",
    "apex_tpu/telemetry/ledger.py",
    "apex_tpu/telemetry/costs.py",
)

STDLIB_DENYLIST = frozenset({
    "jax", "jaxlib", "numpy", "np", "flax", "optax", "orbax",
    "ml_dtypes", "chex", "torch", "scipy", "pandas", "absl",
})
