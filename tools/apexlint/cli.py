"""CLI: ``python -m tools.apexlint [--json] [--rule APXnnn]``.

Exit codes follow the checker convention (tools/check_bench_labels.py):
0 clean, 1 findings, 2 crash-as-finding — a linter that dies must
surface as a loud failure, never a silent pass.
"""

import argparse
import json
import os
import sys


def main(argv=None):
    from tools.apexlint.core import run

    ap = argparse.ArgumentParser(
        prog="python -m tools.apexlint",
        description="AST-level invariant checker for the repo's own "
                    "rules (APX001-APX006; see tools/apexlint).")
    ap.add_argument("--root", default=None,
                    help="repo root (default: the tree this tool "
                         "lives in)")
    ap.add_argument("--rule", action="append", metavar="APXnnn",
                    help="run only these rules (repeatable)")
    ap.add_argument("--reference", default=None,
                    help="reference tree for APX005 (default "
                         "/root/reference; absent = rule skipped)")
    ap.add_argument("--json", action="store_true",
                    help="one machine-readable line (findings per "
                         "rule, pragma account) for window_report/CI "
                         "trending")
    ap.add_argument("--verbose", action="store_true",
                    help="also list every pragma with its hit count")
    args = ap.parse_args(argv)

    from tools.apexlint.rules import RULES

    unknown = sorted(set(args.rule or ()) - set(RULES) - {"APX000"})
    if unknown:
        # an explicit request names rules that exist — a typo'd filter
        # must not select zero rules and report a green gate
        ap.error(f"unknown rule id(s): {' '.join(unknown)} "
                 f"(known: APX000 {' '.join(sorted(RULES))})")

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    report = run(root, rules=args.rule, reference_root=args.reference)
    if args.json:
        print(json.dumps(report.as_json(), sort_keys=True))
    else:
        print(report.render(verbose=args.verbose))
    return 0 if report.ok else 1


def cli():
    try:
        sys.exit(main())
    except SystemExit:
        raise
    except Exception as e:  # crash-as-finding: rc 2, message, no
        # traceback — tier-1 and the shells see a loud structured
        # failure either way. Under --json the stdout contract stays
        # one parseable line; otherwise the crash goes to stderr.
        msg = f"CRASH: apexlint error: {type(e).__name__}: {e}"
        if "--json" in sys.argv[1:]:
            print(json.dumps({"ok": False, "crash": msg}))
        else:
            print(msg, file=sys.stderr)
        sys.exit(2)
