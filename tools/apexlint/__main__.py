from tools.apexlint.cli import cli

cli()
