"""Framework: file model, pragma accounting, report rendering.

The linter is static all the way down: files are parsed with ``ast``,
facts about the repo (knob prefixes, the docs knob table) are extracted
from source text, and nothing under ``apex_tpu/`` is ever imported —
the collection shells run this gate before arming, where a jax import
could dial the wedged relay (CLAUDE.md environment facts).
"""

import ast
import os
import re

PRAGMA_RE = re.compile(
    r"#\s*apexlint:\s*(disable|disable-file)\s*=\s*"
    r"(APX\d{3}(?:\s*,\s*APX\d{3})*)"          # rule list
    r"(?:\s*(?:—|–|--|-)\s*(.*?))?\s*$"  # — reason
)
# a line that tries to be a pragma but fails the strict shape above
PRAGMA_ATTEMPT_RE = re.compile(r"#\s*apexlint\s*:")


class Finding:
    """One violation: ``rule`` id, repo-relative ``path``, 1-based
    ``line``, human message. ``suppressed`` is set by pragma matching
    (a suppressed finding is counted, never fails the run)."""

    def __init__(self, rule, path, line, msg):
        self.rule = rule
        self.path = path
        self.line = line
        self.msg = msg
        self.suppressed = False

    def render(self):
        return f"{self.path}:{self.line}: {self.rule}: {self.msg}"

    def sort_key(self):
        return (self.path, self.line, self.rule, self.msg)


class Pragma:
    """One ``# apexlint: disable[-file]=`` comment. ``hits`` counts the
    findings it suppressed — a pragma that suppresses nothing is
    reported as unused (rot, like a stale allowlist entry)."""

    def __init__(self, path, line, rules, reason, file_level):
        self.path = path
        self.line = line
        self.rules = rules
        self.reason = reason
        self.file_level = file_level
        self.hits = 0


class FileCtx:
    """One parsed source file: AST, raw lines, pragmas, and the
    os-alias map rules need to recognize ``os.environ`` spelled as
    ``_os.environ`` or ``from os import environ``."""

    def __init__(self, relpath, source, known_rules):
        self.path = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self.pragmas = []
        self.pragma_findings = []  # APX000
        self._scan_pragmas(known_rules)
        self.os_aliases, self.direct_env_names = self._scan_os_imports()
        # module-top-level NAME = "literal" str constants, for resolving
        # os.environ.get(ENV) where ENV is a module constant
        self.str_constants = {
            t.id: n.value.value
            for n in self.tree.body if isinstance(n, ast.Assign)
            and isinstance(n.value, ast.Constant)
            and isinstance(n.value.value, str)
            for t in n.targets if isinstance(t, ast.Name)
        }

    def _scan_pragmas(self, known_rules):
        for i, raw in enumerate(self.lines, start=1):
            if "apexlint" not in raw:
                continue
            m = PRAGMA_RE.search(raw)
            if not m:
                if PRAGMA_ATTEMPT_RE.search(raw):
                    self.pragma_findings.append(Finding(
                        "APX000", self.path, i,
                        "malformed apexlint pragma (want '# apexlint: "
                        "disable=APXnnn — <reason>')"))
                continue
            kind, rule_list, reason = m.groups()
            rules = tuple(r.strip() for r in rule_list.split(","))
            unknown = [r for r in rules if r not in known_rules]
            if unknown:
                self.pragma_findings.append(Finding(
                    "APX000", self.path, i,
                    f"pragma names unknown rule(s) {' '.join(unknown)}"))
                continue
            if not (reason or "").strip():
                self.pragma_findings.append(Finding(
                    "APX000", self.path, i,
                    "pragma without a reason — every suppression states "
                    "why (ISSUE 12 acceptance)"))
                continue
            self.pragmas.append(Pragma(
                self.path, i, rules, reason.strip(),
                file_level=(kind == "disable-file")))

    def _scan_os_imports(self):
        aliases, direct = set(), set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "os":
                        aliases.add(a.asname or "os")
            elif isinstance(node, ast.ImportFrom) and node.module == "os":
                for a in node.names:
                    if a.name in ("environ", "getenv"):
                        direct.add((a.asname or a.name, a.name))
        return aliases, direct

    def suppress(self, finding):
        """Apply this file's pragmas to one finding; True if eaten."""
        for p in self.pragmas:
            if finding.rule not in p.rules:
                continue
            if p.file_level:
                p.hits += 1
                return True
            if p.line == finding.line:
                p.hits += 1
                return True
            # a standalone comment-line pragma covers the first
            # statement after its comment block (the pragma may open a
            # multi-line comment explaining the reason)
            if (p.line < finding.line
                    and self.lines[p.line - 1].lstrip().startswith("#")
                    and all(self.lines[i].lstrip().startswith("#")
                            or not self.lines[i].strip()
                            for i in range(p.line, finding.line - 1))):
                p.hits += 1
                return True
        return False


class Repo:
    """Lazily-parsed view of the tree rooted at ``root``. Rules pull
    files by scope; parse failures surface as findings, not crashes
    (a file the linter cannot read is a file the gate cannot vouch
    for)."""

    EXCLUDE_DIRS = {"__pycache__", ".git", ".compile_cache", "reference"}
    # the linter does not lint itself (its config spells every knob and
    # rule pattern as literals); fixtures are linted only by the tests
    EXCLUDE_PREFIXES = ("tools/apexlint/", "tests/fixtures/")

    def __init__(self, root, known_rules):
        self.root = os.path.abspath(root)
        self.known_rules = known_rules
        self._cache = {}
        self.parse_findings = []

    def abspath(self, rel):
        return os.path.join(self.root, rel)

    def exists(self, rel):
        return os.path.exists(self.abspath(rel))

    def read_text(self, rel):
        with open(self.abspath(rel), encoding="utf-8") as fh:
            return fh.read()

    def ctx(self, rel):
        if rel not in self._cache:
            try:
                self._cache[rel] = FileCtx(rel, self.read_text(rel),
                                           self.known_rules)
            except (SyntaxError, UnicodeDecodeError, OSError) as e:
                self.parse_findings.append(Finding(
                    "APX000", rel, getattr(e, "lineno", 1) or 1,
                    f"unparseable file: {type(e).__name__}: {e}"))
                self._cache[rel] = None
        return self._cache[rel]

    def walk_py(self, tops):
        """Yield repo-relative .py paths under the given top dirs/files,
        sorted, excluding the linter itself and test fixtures."""
        out = []
        for top in tops:
            top_abs = self.abspath(top)
            if os.path.isfile(top_abs):
                out.append(top)
                continue
            for dirpath, dirnames, filenames in os.walk(top_abs):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in self.EXCLUDE_DIRS)
                for f in sorted(filenames):
                    if not f.endswith(".py"):
                        continue
                    rel = os.path.relpath(os.path.join(dirpath, f),
                                          self.root)
                    if rel.startswith(self.EXCLUDE_PREFIXES):
                        continue
                    out.append(rel)
        return [p for p in out if self.exists(p)]

    def ctxs(self, tops):
        for rel in self.walk_py(tops):
            c = self.ctx(rel)
            if c is not None:
                yield c


class Report:
    """Outcome of one run: findings (split live/suppressed), pragma
    accounting, and the render/JSON surfaces the CLI prints."""

    def __init__(self, rule_ids):
        self.rule_ids = list(rule_ids)
        self.findings = []       # unsuppressed — these fail the gate
        self.suppressed = []
        self.pragmas = []
        self.notes = []

    @property
    def ok(self):
        return not self.findings

    def counts(self, items):
        c = {r: 0 for r in self.rule_ids}
        for f in items:
            c[f.rule] = c.get(f.rule, 0) + 1
        return {r: n for r, n in c.items() if n}

    def unused_pragmas(self):
        return [p for p in self.pragmas if p.hits == 0]

    def as_json(self):
        return {
            "ok": self.ok,
            "findings": self.counts(self.findings),
            "total": len(self.findings),
            "suppressed": self.counts(self.suppressed),
            "pragmas": len(self.pragmas),
            "unused_pragmas": len(self.unused_pragmas()),
            # skip notes ride the machine line too: an "ok" with
            # "APX005 skipped: no reference tree" must be
            # distinguishable from an ok that validated citations
            "notes": list(self.notes),
        }

    def render(self, verbose=False):
        lines = []
        for f in sorted(self.findings, key=Finding.sort_key):
            lines.append(f.render())
        if verbose or not self.findings:
            for n in self.notes:
                lines.append(f"note: {n}")
        # pragma account — suppressions are visible debt, never silent
        if self.pragmas:
            lines.append(
                f"pragmas: {len(self.pragmas)} "
                f"({len(self.suppressed)} finding(s) suppressed"
                + (f", {len(self.unused_pragmas())} UNUSED"
                   if self.unused_pragmas() else "") + ")")
            if verbose:
                for p in sorted(self.pragmas,
                                key=lambda p: (p.path, p.line)):
                    kind = "file" if p.file_level else "line"
                    lines.append(
                        f"  {p.path}:{p.line} [{kind}] "
                        f"{','.join(p.rules)} hits={p.hits} — {p.reason}")
        for p in self.unused_pragmas():
            lines.append(f"note: UNUSED pragma {p.path}:{p.line} "
                         f"({','.join(p.rules)}) — prune it")
        if self.findings:
            lines.append(f"FAIL: {len(self.findings)} finding(s)")
        else:
            lines.append("OK: apexlint clean")
        return "\n".join(lines)


def run(root, rules=None, reference_root=None):
    """Run the rule set over the tree at ``root``; returns a Report.

    ``rules`` filters by id (default: all). ``reference_root``
    overrides the APX005 resolution tree (default
    ``config.REFERENCE_ROOT``; absent tree = rule skipped with a
    note, like check_api_parity)."""
    from tools.apexlint import config
    from tools.apexlint.rules import RULES

    selected = {rid: fn for rid, fn in RULES.items()
                if rules is None or rid in rules}
    repo = Repo(root, known_rules=set(RULES))
    report = Report(sorted(set(RULES) | {"APX000"}))

    raw = []
    for rid, fn in sorted(selected.items()):
        raw.extend(fn(repo, config, report,
                      reference_root=reference_root))
    raw.extend(repo.parse_findings)

    # pragma application + accounting (APX000 findings are about the
    # pragmas themselves and cannot be suppressed by one)
    seen_files = set()
    for f in raw:
        ctx = repo._cache.get(f.path)
        if ctx is not None and ctx.suppress(f):
            report.suppressed.append(f)
        else:
            report.findings.append(f)
    for ctx in repo._cache.values():
        if ctx is None or ctx.path in seen_files:
            continue
        seen_files.add(ctx.path)
        report.pragmas.extend(ctx.pragmas)
        # pragma hygiene (APX000) rides along for every scanned file,
        # rule filter or not: a reasonless pragma must never pass just
        # because the run was narrowed
        report.findings.extend(ctx.pragma_findings)
    return report
