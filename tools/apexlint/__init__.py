"""apexlint — AST-level invariant checker for the repo's own rules.

Eleven PRs of conventions (CLAUDE.md / PERF.md §0) are load-bearing but
were enforced only by scattered per-feature tests, and each had already
been violated at least once before a human caught it (the round-3
``APEX_LN_PALLAS`` label-drift bug, the round-4 no-op-knob audit
findings, the round-5 import-time ``APEX_XENT_ROW_BLOCK`` read). This
package mechanizes them as one tier-1 gate — the measured-not-asserted
discipline the kernels get, applied to the code itself.

Rules (each grounded in an already-committed convention):

========  ==========================================================
APX001    no import-time ``os.environ``/``os.getenv`` in
          ``apex_tpu/`` — env knobs are read at TRACE time (the
          round-5 ``APEX_XENT_ROW_BLOCK`` class)
APX002    ``APEX_*`` reads outside tests go through the
          ``dispatch.tiles.env_int/env_choice/env_float/env_flag``
          one-home parsers, or the knob's designated-reader
          allowlist entry (``config.DESIGNATED_READERS``)
APX003    knob registry cross-check — the set of ``APEX_*`` names
          used anywhere in non-test code (python env ops + the
          collection shells) must exactly equal the docs/API.md
          knob table plus ``ledger.INFRA_KNOB_PREFIXES`` coverage
          (the round-4 no-op-knob audit, whole-namespace)
APX004    timing hygiene — no naked ``time.time()`` /
          ``perf_counter()`` / ``block_until_ready`` in
          ``benchmarks/``: the PERF.md §0 timing rules have ONE
          implementation (``apex_tpu.telemetry.tracing``)
APX005    citation resolver — every ``reference …py:line``
          docstring citation resolves against ``/root/reference``
          (file exists, line in range): ``check_api_parity``
          upgraded from presence to validity
APX006    stdlib-only enforcement — modules that claim it
          (``config.STDLIB_ONLY_CLAIMED``) must not import
          jax/numpy at module level, checked transitively over the
          in-package import graph
APX000    pragma hygiene — every ``# apexlint: disable=`` pragma
          names known rules AND states a reason
========  ==========================================================

Suppression is inline and itself accounted for (counted, reported,
and surfaced in ``--json``)::

    something_flagged()  # apexlint: disable=APX004 — why this is ok
    # apexlint: disable=APX002 — reason          (on the line above)
    # apexlint: disable-file=APX004 — whole-file reason

Run as a tier-1 test (tests/test_apexlint.py) and as a CLI::

    python -m tools.apexlint [--json] [--rule APXnnn] [--root DIR]

Exit status follows the checker convention (check_bench_labels):
0 clean, 1 findings, 2 crash-as-finding (a linter that dies must not
pass silently). Stdlib-only and import-free: every fact it needs from
the repo (INFRA_KNOB_PREFIXES, the knob table, the import graph) is
read via ``ast``/text, never by importing ``apex_tpu`` — so the
collection shells can run it relay-proof, without a jax backend.
"""

from tools.apexlint.core import Report, run  # noqa: F401
from tools.apexlint.cli import main  # noqa: F401
