#!/usr/bin/env python
"""Cross-check PERF.md bench-table captions against benchmarks/ledger.jsonl.

The repo's measurement rule is "pin the label to what was measured"
(CLAUDE.md); rounds 1-2 shipped wrong headline numbers and the round-5
§10 caption said "dispatch overhead 68-75 ms" over a log that recorded
82.6 ms — label drift that only a prose audit caught. This tool makes
that class of drift mechanical. It runs in the tier-1 suite
(tests/test_bench_labels.py), like tools/check_api_parity.py.

Checks:

1. **Ledger schema** — every ledger line parses; every record carries
   the required fields (apex_tpu.telemetry.ledger.REQUIRED_FIELDS);
   ids are unique AND match their record's content hash (an id is a
   sha1 over the canonical record, so a record edited after the fact
   no longer matches its own id). Records carrying the warm-start
   telemetry block (``compile_cache: {enabled, dir, hits, misses,
   warm_age_s}`` — apex_tpu.compile_cache) must carry it well-formed:
   a malformed block could silently claim a number was compile-free.
2. **Caption cross-check** — every ``ledger:<id>`` citation in PERF.md
   must resolve to a ledger record, and any "dispatch overhead X ms"
   (or "X-Y ms" range) stated in the citing paragraph must agree with
   AT LEAST ONE cited record's ``dispatch_overhead_ms``: a single value
   within ±0.15 ms (captions round to 0.1), a range must bracket it.
   (At-least-one, not all: an A/B paragraph legitimately cites two
   records with two different overheads.)
3. **Dispatch table** (``apex_tpu/dispatch/table.jsonl``) — every
   entry parses and carries the required fields, its op/choice is in
   the vocabulary, its ``ledger`` id resolves to a record, and every
   knob in its ``pins`` matches the cited record's recorded ``knobs``
   (a table entry claiming APEX_ATTN_IMPL=rows over a record measured
   without the pin is the same label-drift class as a wrong caption —
   runtime lookups skip a corrupt line and fall back, but here it is a
   finding).
4. **Tile params payloads** — every entry carrying a ``params``
   payload (the per-shape tile geometry from
   ``benchmarks/autotune_tiles.py``) must be LEGAL under the shared
   tile model (``apex_tpu.dispatch.tiles``: VMEM working set +
   (8, 128)-divisibility at the entry's bucket dims — a committed tile
   must lower), cite a resolving, un-injected ``params.ledger``
   record, and carry ``params.pins`` matching that record's knobs.
   Runtime consults skip a malformed payload and fall back to the
   kernel heuristic; here it is a finding, so corruption cannot
   persist in the committed table.
5. **Resume provenance** — a cited record carrying ``resumed_from``
   (bench.py ``--resume`` / profile_gpt: the run restored a
   checkpointed TrainState and continued) must pin-match: the
   measurement pins saved in the checkpoint
   (``resumed_from.pins``, filtered by
   ``ledger.measurement_pins``) must equal the restored run's own
   recorded ``knobs`` — a resumed timing row whose pins drifted is
   mixing two configs under one label. And any paragraph making a
   COLD-start claim ("cold start", "cold compile", "cold cache")
   must not cite a resumed record at all: a run that restored state
   is not a cold start, whatever its compile-cache counters say.
   The same pin-match applies to dispatch-table entries citing
   resumed records.
6. **MFU/cost arithmetic** — a cited record that reports an ``mfu``
   AND carries a cost block with ``model_flops_per_step`` /
   ``peak_flops`` (plus ``value`` and ``config.batch``/``config.s``)
   must be arithmetically consistent:
   ``mfu == model_flops_per_step * value / (batch * s * peak_flops)``
   within rounding tolerance. A headline MFU that disagrees with its
   own record's flops accounting is the §10 label-drift class wearing
   an attribution costume. Records without the block (legacy, or
   null-degraded backends) are skipped — no block, no claim to check.
   Applies to PERF.md citations AND dispatch-table-cited records.
7. **Comm-compression pin-match** — a cited record whose cost block
   (run-level or any span's) carries a ``comm_compression`` stamp
   claiming the payload was compressed (``scheme`` non-null) or
   hierarchically staged must PIN the selecting knob in its recorded
   ``knobs`` (``APEX_GRAD_COMPRESS``/``APEX_HIER_ALLREDUCE`` — the
   quantized collectives of ``apex_tpu.parallel.collectives``): a row
   measured with compression engaged through a process-wide setter
   alone carries no pin the label can be checked against — the same
   drift class as an unpinned A/B. Applies to PERF.md citations AND
   dispatch-table-cited records.
8. **Serving pin-match** — a cited record carrying a ``serving``
   block (``benchmarks/profile_serving.py``: {tokens_per_s, p50_ms,
   p99_ms, trace_id, kv_pages}) must PIN both serving dispatch knobs
   in its recorded ``knobs``: ``APEX_SERVE_WEIGHT_QUANT`` and
   ``APEX_DECODE_ATTN_IMPL``. The decode step's program is shaped by
   both (int8 vs full-precision matmuls; pallas vs jnp gather
   attention), and a serving row engaged through a process-wide
   setter alone carries no pin the label can be checked against —
   same teeth as checks 6-7. The harness stamps the RESOLVED values
   into its environment before the ledger write, so an unpinned run
   cannot produce a citable serving row. Generation fields (ISSUE
   13): a block with a non-None ``spec_acceptance_rate`` /
   ``prefix_hit_rate`` was measured with speculative decode / the
   prefix cache ENGAGED and must pin ``APEX_SPEC_DECODE`` /
   ``APEX_SERVE_PREFIX_CACHE`` at a non-off value — a rate under an
   off (or missing) pin names a program the label did not run.
9. **SLO pin-match** — a cited record carrying an ``slo`` block
   (``apex_tpu.serving.lifecycle.slo_block``: TTFT/per-token
   percentiles, goodput, SLO attainment under a named arrival
   process) must PIN the knobs that shaped the claim in its recorded
   ``knobs``: the SLO thresholds (``APEX_SERVE_SLO_TTFT_MS`` /
   ``APEX_SERVE_SLO_TPOT_MS`` — attainment and goodput are FUNCTIONS
   of the thresholds), the arrival process (``APEX_SERVE_ARRIVALS``
   — offered load means nothing without it), and the scheduler
   policy (``APEX_SERVE_SCHED`` — the dispatch choice every
   tail-latency number depends on). And the block's own
   ``arrival_process`` / ``slo_ttft_ms`` / ``slo_tpot_ms`` fields
   must AGREE with the pinned values — a block claiming a diurnal
   trace under a poisson pin (or a 1000 ms attainment under a
   500 ms pin) is the same label-drift class as a wrong caption.
   Resilience teeth (ISSUE 15, the check-8 generation pattern): a
   block whose ``shed_rate`` / ``preempt_rate`` / ``degraded_rounds``
   is non-None was measured with the deadline shedder / KV-pressure
   preemption / the dispatch watchdog ENGAGED and must pin
   ``APEX_SERVE_SHED`` / ``APEX_SERVE_PREEMPT`` /
   ``APEX_SERVE_RECOVER`` at a non-off value — a rate under an off
   (or missing) pin names an engine the label did not run.
10. **Overlap pin-match** — a cited record whose cost block (run-level
    or any span's) carries an ``overlap_bound`` with a non-null
    ``host_ms``/``comm_ms`` alongside an ``overlap`` claim block
    (``benchmarks/profile_overlap.py`` / ``profile_serving.py``:
    ``{grad, buckets, prefetch, serve}`` — which overlap schedules
    the measured program ran under, ISSUE 14) must PIN the claimed
    knobs in its recorded ``knobs`` at the claimed values
    (``APEX_OVERLAP_GRAD`` / ``APEX_OVERLAP_BUCKETS`` /
    ``APEX_PREFETCH`` / ``APEX_SERVE_OVERLAP``), and — the other
    direction — a non-off pin of any of those knobs on such a record
    must appear in the claim block: a host-slice number measured
    under the pipelined engine but labeled serial (or vice versa) is
    the same drift class as checks 7-9. Records with an
    overlap_bound but no claim block (the pre-ISSUE-14 serving rows)
    predate the knobs and are skipped. Applies to PERF.md citations
    AND dispatch-table-cited records.
11. **Parallel pin-match** — ZeRO-3 and tp-serving rows (ISSUE 18).
    A cited record carrying a ``parallel`` claim block
    (``benchmarks/profile_comm.py`` / ``profile_serving.py``:
    ``{zero_stage, tp}`` — whether the measured program ran with
    params dp-sharded behind the gather-on-use hop, and at what
    serving tensor-parallel width) must PIN the selecting knobs
    (``APEX_ZERO_STAGE`` / ``APEX_SERVE_TP``) in its recorded
    ``knobs`` at the claimed values, and — the other direction — an
    ENGAGED pin (``APEX_ZERO_STAGE`` past ``0``, ``APEX_SERVE_TP``
    past ``1``) must appear in the claim block even when the record
    carries no claim at all: a throughput number measured over the
    sharded program but labeled unsharded (or vice versa) is the
    same drift class as checks 7-10, and unlike check 10 there is no
    measurement gate — the pins reshape EVERY number in the record.
    Applies to PERF.md citations AND dispatch-table-cited records.
12. **Router pin-match** — fleet rows (ISSUE 19). A cited record
    carrying a ``router`` block (``benchmarks/profile_router.py`` /
    ``apex_tpu.serving.router.router_block``: fleet goodput,
    utilization spread, cross-replica tails, failover/replay counts,
    per-policy prefix hit rates) must PIN both fleet knobs in its
    recorded ``knobs`` (``APEX_ROUTE_POLICY`` /
    ``APEX_ROUTE_REPLICAS``), and the block's own ``route_policy`` /
    ``replicas`` fields must AGREE with the pinned values — a block
    claiming a prefix-affinity hit rate under a round-robin pin (or
    a 3-replica spread under a 2-replica pin) names a fleet the
    label did not run. The other direction: an engaged fleet pin on
    a record with NO router block is a finding — a routed fleet ran
    that the label does not name (the check-11 no-measurement-gate
    pattern: the pins reshape every number in the record). Applies
    to PERF.md citations AND dispatch-table-cited records.

New PERF.md table rows must cite their ledger record id in the caption
(``ledger:<id>``) — uncited legacy paragraphs are not flagged, but they
get no drift protection either.

Usage: python tools/check_bench_labels.py [--perf PATH] [--ledger PATH]
                                          [--table PATH] [--verbose]
Exit status: 0 when clean, 1 on any finding.
"""

import argparse
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from apex_tpu import dispatch as dispatch_mod  # noqa: E402
from apex_tpu.telemetry import ledger as ledger_mod  # noqa: E402

CITE_RE = re.compile(r"ledger:(lg-[0-9a-f]{10})")
# "dispatch overhead 82.6 ms" / "dispatch overhead 68-75 ms subtracted";
# both hyphen and en-dash spell the (drift-prone) range form
OVERHEAD_RE = re.compile(
    r"dispatch overhead\s+([0-9]+(?:\.[0-9]+)?)"
    r"(?:\s*[–-]\s*([0-9]+(?:\.[0-9]+)?))?\s*ms")
TOL_MS = 0.15  # captions round to 0.1 ms
# check 5: a paragraph claiming a cold start must not cite a record
# that restored checkpointed state (both hyphen and space spellings)
COLD_RE = re.compile(r"\bcold[- ](?:start|compile|cache)", re.IGNORECASE)


def resume_problems(rec, rid):
    """Check-5 pin-match for one cited record carrying resume
    provenance; [] when clean or not resumed. The comparison is the
    SAME filter the provenance was stamped with
    (``ledger.measurement_pins``), so infra knobs (paths, attempt
    counters) can never count as drift while measurement knobs always
    do."""
    rf = rec.get("resumed_from")
    if rf is None:
        return []
    if not isinstance(rf, dict) or not isinstance(rf.get("pins"), dict):
        return [f"record {rid} carries malformed resume provenance"]
    problems = []
    # the ONE drift comparison (ledger.pin_drift, shared with the
    # provenance producer) — both sides measurement-filtered
    drift = ledger_mod.pin_drift(rf["pins"], rec.get("knobs"))
    if drift:
        detail = ", ".join(f"{k}: ckpt={s!r} run={n!r}"
                           for k, (s, n) in sorted(drift.items()))
        problems.append(
            f"record {rid} resumed from checkpoint {rf.get('ckpt')} "
            f"under DIFFERENT measurement pins ({detail}) — the row "
            f"mixes two configs under one label")
    return problems


def mfu_problems(rec, rid):
    """Check-6 arithmetic for one cited record; [] when clean or when
    the record carries no checkable (mfu, cost) pair. The recomputation
    uses ONLY fields inside the content-hashed record — value, config
    batch/s, and the cost block's model flops + peak — so a drifted MFU
    cannot be repaired by editing any one of them without breaking the
    record's own id."""
    mfu = rec.get("mfu")
    cost = rec.get("cost")
    if mfu is None or not isinstance(cost, dict):
        return []
    model_flops = cost.get("model_flops_per_step")
    peak = cost.get("peak_flops")
    value = rec.get("value")
    cfg = rec.get("config") if isinstance(rec.get("config"), dict) else {}
    b, s = cfg.get("batch"), cfg.get("s")
    inputs = (model_flops, peak, value, b, s)
    if any(not isinstance(x, (int, float)) or isinstance(x, bool)
           or x <= 0 for x in inputs):
        return []  # null-degraded block / legacy record: nothing to check
    expect = model_flops * value / (b * s * peak)
    # mfu rounds to 4 decimals, value to 0.1 — tolerate both roundings
    tol = max(5e-4, 0.002 * expect)
    if abs(mfu - expect) > tol:
        return [f"record {rid} reports mfu={mfu} but its cost block's "
                f"flops imply {expect:.4f} "
                f"(model_flops_per_step={model_flops:g}, value={value:g} "
                f"tok/s, tokens={b * s}, peak={peak:g}) — MFU/cost "
                f"arithmetic drift"]
    return []


def comm_compress_problems(rec, rid):
    """Check-7 pin-match for one cited record; [] when clean or when no
    cost block carries a compression claim. The stamp's scheme /
    hierarchical flags come from ``collectives.snapshot()`` at capture
    time, so a setter-engaged compression that never pinned its env
    knob is caught here — the record claims a compressed payload its
    pins do not select."""
    blocks = [rec.get("cost")]
    for s in rec.get("spans") or []:
        if isinstance(s, dict):
            blocks.append(s.get("cost"))
    knobs = rec.get("knobs") if isinstance(rec.get("knobs"), dict) else {}
    problems = set()
    for b in blocks:
        cc = b.get("comm_compression") if isinstance(b, dict) else None
        if not isinstance(cc, dict):
            continue
        scheme = cc.get("scheme")
        if scheme and knobs.get("APEX_GRAD_COMPRESS") != scheme:
            problems.add(
                f"record {rid} was measured with compressed collectives "
                f"(comm_compression.scheme={scheme!r}) but does not pin "
                f"APEX_GRAD_COMPRESS={scheme!r} in its knobs "
                f"(recorded: {knobs.get('APEX_GRAD_COMPRESS')!r}) — an "
                f"unpinned compressed row cannot be cited")
        if cc.get("hierarchical") \
                and knobs.get("APEX_HIER_ALLREDUCE") != "1":
            problems.add(
                f"record {rid} was measured with hierarchical "
                f"collectives (comm_compression.hierarchical=true) but "
                f"does not pin APEX_HIER_ALLREDUCE=1 in its knobs "
                f"(recorded: {knobs.get('APEX_HIER_ALLREDUCE')!r})")
    return sorted(problems)


def serving_problems(rec, rid):
    """Check-8 pin-match for one cited record; [] when clean or when
    the record carries no serving block. Both serving dispatch knobs
    must be PRESENT in the record's knobs — the resolved value is what
    the label pins; absence means the choice came from a setter or a
    default the citation cannot be audited against. Generation teeth
    (ISSUE 13): a block whose ``spec_acceptance_rate`` is non-None was
    measured with speculative decode ENGAGED, so it must pin
    ``APEX_SPEC_DECODE`` (and its pin must not be the off value 0 —
    an acceptance rate under a spec-off pin names a program the label
    did not run); same for ``prefix_hit_rate`` and
    ``APEX_SERVE_PREFIX_CACHE``. Multi-token teeth (ISSUE 17): a
    serving row must pin ``APEX_SERVE_DECODE_K`` (the block size is a
    different compiled program — an unpinned K cannot be audited), and
    when the record's slo block carries ``decode_block_k`` the pin and
    the field must agree BOTH directions (a pin naming a K the engine
    did not run, or an engine K the label does not name, both fail).
    KV-tier teeth (ISSUE 20): the two cache knobs
    ``APEX_SERVE_KV_QUANT`` / ``APEX_SERVE_KV_SWAP`` must be pinned
    (the codec and the restore path are different programs), a
    non-None ``kv_quant``/``swap_rate`` field demands its knob pinned
    ON, and a knob pinned ON demands its field non-None — both
    directions, so neither the label nor the block can claim a tier
    the other did not run."""
    sv = rec.get("serving")
    if not isinstance(sv, dict):
        return []
    knobs = rec.get("knobs") if isinstance(rec.get("knobs"), dict) else {}
    problems = []
    for knob in ("APEX_SERVE_WEIGHT_QUANT", "APEX_DECODE_ATTN_IMPL",
                 "APEX_SERVE_DECODE_K", "APEX_SERVE_KV_QUANT",
                 "APEX_SERVE_KV_SWAP"):
        if knob not in knobs:
            problems.append(
                f"record {rid} carries a serving block but does not pin "
                f"{knob} in its knobs — an unpinned serving row cannot "
                f"be cited")
    for field, knob in (("kv_quant", "APEX_SERVE_KV_QUANT"),
                        ("swap_rate", "APEX_SERVE_KV_SWAP")):
        pin = knobs.get(knob)
        if sv.get(field) is not None and str(pin) == "0":
            problems.append(
                f"record {rid} carries serving.{field}={sv[field]!r} "
                f"but pins {knob}={pin!r} (off) — the block and the "
                f"label name different cache tiers")
        if str(pin) == "1" and field in sv and sv.get(field) is None:
            problems.append(
                f"record {rid} pins {knob}=1 but its "
                f"serving.{field} is null — a tier the label claims "
                f"left no account in the block")
    for field, knob, off in (
            ("spec_acceptance_rate", "APEX_SPEC_DECODE", "0"),
            ("prefix_hit_rate", "APEX_SERVE_PREFIX_CACHE", "0")):
        if sv.get(field) is None:
            continue
        pin = knobs.get(knob)
        if pin is None:
            problems.append(
                f"record {rid} carries serving.{field}="
                f"{sv[field]!r} but does not pin {knob} in its knobs "
                f"— an unpinned speculative/prefix row cannot be cited")
        elif str(pin) == off:
            problems.append(
                f"record {rid} carries serving.{field}={sv[field]!r} "
                f"but pins {knob}={pin!r} (off) — the block and the "
                f"label name different programs")
    slo = rec.get("slo")
    dk = slo.get("decode_block_k") if isinstance(slo, dict) else None
    pin = knobs.get("APEX_SERVE_DECODE_K")
    if dk is not None and pin is not None:
        try:
            pinned = float(pin)
        except (TypeError, ValueError):
            problems.append(
                f"record {rid} pins APEX_SERVE_DECODE_K={pin!r}, which "
                f"is not a number")
            pinned = None
        if pinned is not None and isinstance(dk, (int, float)) \
                and not isinstance(dk, bool) \
                and abs(pinned - dk) > 1e-6:
            problems.append(
                f"record {rid} slo.decode_block_k={dk!r} disagrees "
                f"with its pinned APEX_SERVE_DECODE_K={pin!r} — the "
                f"block and the label name different decode programs")
    return problems


def slo_pin_problems(rec, rid):
    """Check-9 pin-match for one cited record; [] when clean or when
    the record carries no slo block. Presence teeth first (an
    unpinned slo row cannot be audited at all), then agreement teeth
    (the pinned value must be what the block claims — the knob and
    the block ride the same content-hashed record, so neither can be
    edited to fit the other without breaking the id)."""
    slo = rec.get("slo")
    if not isinstance(slo, dict):
        return []
    knobs = rec.get("knobs") if isinstance(rec.get("knobs"), dict) else {}
    problems = []
    for knob in ("APEX_SERVE_SLO_TTFT_MS", "APEX_SERVE_SLO_TPOT_MS",
                 "APEX_SERVE_ARRIVALS", "APEX_SERVE_SCHED"):
        if knob not in knobs:
            problems.append(
                f"record {rid} carries an slo block but does not pin "
                f"{knob} in its knobs — an unpinned slo row cannot be "
                f"cited")
    arr = knobs.get("APEX_SERVE_ARRIVALS")
    ap = slo.get("arrival_process")
    if arr is not None and ap is not None and ap != arr:
        problems.append(
            f"record {rid} slo.arrival_process={ap!r} disagrees with "
            f"its pinned APEX_SERVE_ARRIVALS={arr!r} — the block and "
            f"the label name different workloads")
    for knob, field in (("APEX_SERVE_SLO_TTFT_MS", "slo_ttft_ms"),
                        ("APEX_SERVE_SLO_TPOT_MS", "slo_tpot_ms")):
        pin, val = knobs.get(knob), slo.get(field)
        if pin is None or not isinstance(val, (int, float)) \
                or isinstance(val, bool):
            continue
        try:
            pinned = float(pin)
        except (TypeError, ValueError):
            # a corrupt knob value (list, dict, unparseable string) is
            # a FINDING, never a checker crash
            problems.append(
                f"record {rid} pins {knob}={pin!r}, which is not a "
                f"number")
            continue
        if abs(pinned - val) > 1e-6:
            problems.append(
                f"record {rid} slo.{field}={val:g} disagrees with its "
                f"pinned {knob}={pinned:g} — the attainment was judged "
                f"against a threshold the label does not name")
    # resilience teeth (ISSUE 15): a non-None rate/count names an
    # ENGAGED layer — its selecting knob must be pinned non-off (the
    # check-8 generation-field pattern)
    for field, knob in (("shed_rate", "APEX_SERVE_SHED"),
                        ("preempt_rate", "APEX_SERVE_PREEMPT"),
                        ("degraded_rounds", "APEX_SERVE_RECOVER")):
        if slo.get(field) is None:
            continue
        pin = knobs.get(knob)
        if pin is None:
            problems.append(
                f"record {rid} carries slo.{field}={slo[field]!r} but "
                f"does not pin {knob} in its knobs — an unpinned "
                f"resilience row cannot be cited")
        elif str(pin) == "0":
            problems.append(
                f"record {rid} carries slo.{field}={slo[field]!r} but "
                f"pins {knob}={pin!r} (off) — the block and the label "
                f"name different engines")
    return problems


# check 10: the overlap claim fields and the knobs that select them
# (the "off" value is what an engaged claim must not pin — and what an
# omitted claim field must not be pinned past; APEX_OVERLAP_BUCKETS
# has NO off value, so any pinned count at all is "engaged")
_OVERLAP_CLAIM_KNOBS = (
    ("grad", "APEX_OVERLAP_GRAD", "off"),
    ("buckets", "APEX_OVERLAP_BUCKETS", None),
    ("prefetch", "APEX_PREFETCH", "0"),
    ("serve", "APEX_SERVE_OVERLAP", "0"),
)


def overlap_problems(rec, rid):
    """Check-10 pin-match for one cited record; [] when clean, when no
    cost block carries a non-null overlap_bound host/comm side, or
    when the record carries no ``overlap`` claim block (the
    pre-ISSUE-14 rows predate the knobs — no claim, no teeth). Both
    directions: every non-None claim field must be pinned at the
    claimed value, and every non-off pin of an overlap knob must
    appear in the claim — a measured host/comm slice is a FUNCTION of
    the overlap schedules, so an unpinned or contradicted claim names
    a program the label did not run."""
    blocks = [rec.get("cost")]
    for s in rec.get("spans") or []:
        if isinstance(s, dict):
            blocks.append(s.get("cost"))
            extra = s.get("extra")
            if isinstance(extra, dict):
                blocks.append(extra.get("cost"))
    has_ob = False
    for b in blocks:
        ob = b.get("overlap_bound") if isinstance(b, dict) else None
        if isinstance(ob, dict) and (ob.get("host_ms") is not None
                                     or ob.get("comm_ms") is not None):
            has_ob = True
            break
    claim = rec.get("overlap")
    if not has_ob or not isinstance(claim, dict):
        return []
    knobs = rec.get("knobs") if isinstance(rec.get("knobs"), dict) else {}
    problems = []
    for field, knob, off in _OVERLAP_CLAIM_KNOBS:
        val = claim.get(field)
        pin = knobs.get(knob)
        if val is not None:
            if pin is None:
                problems.append(
                    f"record {rid} claims overlap.{field}={val!r} but "
                    f"does not pin {knob} in its knobs — an unpinned "
                    f"overlap row cannot be cited")
            elif str(pin) != str(val):
                problems.append(
                    f"record {rid} claims overlap.{field}={val!r} but "
                    f"pins {knob}={pin!r} — the claim and the label "
                    f"name different schedules")
        elif pin is not None and (off is None or str(pin) != off):
            problems.append(
                f"record {rid} pins {knob}={pin!r} (engaged) but its "
                f"overlap claim omits {field!r} — the measured "
                f"host/comm slice ran a schedule the claim does not "
                f"name")
    return problems


# check 11: the parallel claim fields (ISSUE 18 — ZeRO-3 parameter
# sharding and tp-serving) and the knobs that select them; the "off"
# value is the default program the claim-less rows ran
_PARALLEL_CLAIM_KNOBS = (
    ("zero_stage", "APEX_ZERO_STAGE", "0"),
    ("tp", "APEX_SERVE_TP", "1"),
)


def parallel_problems(rec, rid):
    """Check-11 pin-match for one cited record; [] when clean. Both
    directions, with NO measurement gate (unlike check 10): a
    non-None ``parallel`` claim field must be pinned at the claimed
    value, and an engaged pin (``APEX_ZERO_STAGE`` past 0,
    ``APEX_SERVE_TP`` past 1) must be claimed — even on a record
    with no ``parallel`` block at all, because the pins reshape
    every number in the record (a sharded program cited under an
    unsharded label is the checks-7-10 drift class)."""
    claim = rec.get("parallel")
    claim = claim if isinstance(claim, dict) else {}
    knobs = rec.get("knobs") if isinstance(rec.get("knobs"), dict) else {}
    problems = []
    for field, knob, off in _PARALLEL_CLAIM_KNOBS:
        val = claim.get(field)
        pin = knobs.get(knob)
        if val is not None:
            if pin is None:
                problems.append(
                    f"record {rid} claims parallel.{field}={val!r} "
                    f"but does not pin {knob} in its knobs — an "
                    f"unpinned zero3/tp row cannot be cited")
            elif str(pin) != str(val):
                problems.append(
                    f"record {rid} claims parallel.{field}={val!r} "
                    f"but pins {knob}={pin!r} — the claim and the "
                    f"label name different programs")
        elif pin is not None and str(pin) != off:
            problems.append(
                f"record {rid} pins {knob}={pin!r} (engaged) but its "
                f"parallel claim omits {field!r} — a sharded program "
                f"ran that the label does not name")
    return problems


# check 12: the router block fields and the fleet knobs that pin them
_ROUTER_CLAIM_KNOBS = (
    ("route_policy", "APEX_ROUTE_POLICY"),
    ("replicas", "APEX_ROUTE_REPLICAS"),
)


def router_problems(rec, rid):
    """Check-12 pin-match for one cited record; [] when clean. Both
    directions, with NO measurement gate (the check-11 pattern): a
    record carrying a ``router`` block must pin both fleet knobs and
    the block's ``route_policy``/``replicas`` must agree with them;
    an engaged fleet pin on a record WITHOUT a router block is a
    finding — a routed fleet ran that the label does not name."""
    rt = rec.get("router")
    knobs = rec.get("knobs") if isinstance(rec.get("knobs"), dict) else {}
    problems = []
    if isinstance(rt, dict):
        for field, knob in _ROUTER_CLAIM_KNOBS:
            val = rt.get(field)
            pin = knobs.get(knob)
            if pin is None:
                problems.append(
                    f"record {rid} carries a router block but does "
                    f"not pin {knob} in its knobs — an unpinned fleet "
                    f"row cannot be cited")
            elif val is not None and str(pin) != str(val):
                problems.append(
                    f"record {rid} router.{field}={val!r} disagrees "
                    f"with its pinned {knob}={pin!r} — the block and "
                    f"the label name different fleets")
    else:
        for field, knob in _ROUTER_CLAIM_KNOBS:
            if knobs.get(knob) is not None:
                problems.append(
                    f"record {rid} pins {knob}={knobs[knob]!r} "
                    f"(engaged) but carries no router block — a "
                    f"routed fleet ran that the label does not name")
    return problems


def _paragraphs(text):
    """(start_lineno, paragraph_text) blocks of consecutive non-blank
    lines — the unit a caption and its numbers share."""
    out, block, start = [], [], None
    for lineno, line in enumerate(text.splitlines(), 1):
        if line.strip():
            if not block:
                start = lineno
            block.append(line)
        elif block:
            out.append((start, "\n".join(block)))
            block = []
    if block:
        out.append((start, "\n".join(block)))
    return out


def check_ledger(records):
    problems = []
    seen = {}
    for i, rec in enumerate(records, 1):
        for p in ledger_mod.validate_record(rec):
            problems.append(f"ledger record {i} ({rec.get('id', '?')}): {p}")
        rid = rec.get("id")
        if rid is not None:
            if rid in seen:
                problems.append(
                    f"ledger record {i}: duplicate id {rid!r} "
                    f"(first at record {seen[rid]})")
            else:
                seen[rid] = i
    return problems


def check_captions(perf_text, perf_path, records):
    by_id = {r.get("id"): r for r in records}
    problems = []
    cited = 0
    for lineno, para in _paragraphs(perf_text):
        ids = CITE_RE.findall(para)
        if not ids:
            continue
        cited += len(ids)
        overheads = {}  # rid -> measured dispatch_overhead_ms
        for rid in ids:
            rec = by_id.get(rid)
            if rec is None:
                problems.append(
                    f"{perf_path}:{lineno}: citation ledger:{rid} has no "
                    f"ledger record")
                continue
            if rec.get("fault_plan"):
                # fault-injected records (APEX_FAULT_PLAN chaos runs —
                # apex_tpu.resilience.faults) are test artifacts: a
                # PERF.md caption must never cite one as a measurement
                problems.append(
                    f"{perf_path}:{lineno}: citation ledger:{rid} is a "
                    f"FAULT-INJECTED record (fault_plan="
                    f"{rec['fault_plan']}) — injected runs are not "
                    f"measurements")
            # check 5: resume provenance — pin-match + cold-start gate
            for p in resume_problems(rec, rid):
                problems.append(f"{perf_path}:{lineno}: {p}")
            # check 6: MFU/cost-block arithmetic consistency
            for p in mfu_problems(rec, rid):
                problems.append(f"{perf_path}:{lineno}: {p}")
            # check 7: comm-compression pin-match
            for p in comm_compress_problems(rec, rid):
                problems.append(f"{perf_path}:{lineno}: {p}")
            # check 8: serving-block pin-match
            for p in serving_problems(rec, rid):
                problems.append(f"{perf_path}:{lineno}: {p}")
            # check 9: slo-block pin-match + threshold/arrival agreement
            for p in slo_pin_problems(rec, rid):
                problems.append(f"{perf_path}:{lineno}: {p}")
            # check 10: overlap-schedule pin-match (both directions)
            for p in overlap_problems(rec, rid):
                problems.append(f"{perf_path}:{lineno}: {p}")
            # check 11: zero3/tp parallel pin-match (both directions)
            for p in parallel_problems(rec, rid):
                problems.append(f"{perf_path}:{lineno}: {p}")
            # check 12: fleet-router pin-match (both directions)
            for p in router_problems(rec, rid):
                problems.append(f"{perf_path}:{lineno}: {p}")
            if rec.get("resumed_from") is not None \
                    and COLD_RE.search(para):
                problems.append(
                    f"{perf_path}:{lineno}: paragraph makes a cold-"
                    f"start claim but cites ledger:{rid}, which "
                    f"RESUMED from checkpoint "
                    f"{rec['resumed_from'].get('ckpt') if isinstance(rec['resumed_from'], dict) else '?'}"
                    f" — a restored run is not a cold start")
            if rec.get("dispatch_overhead_ms") is not None:
                overheads[rid] = rec["dispatch_overhead_ms"]
        if not overheads:
            continue
        # a stated overhead must match AT LEAST ONE cited record — an
        # A/B paragraph cites two records with two different overheads,
        # and each stated number belongs to one of them
        for m in OVERHEAD_RE.finditer(para):
            lo = float(m.group(1))
            hi = float(m.group(2)) if m.group(2) else None
            if hi is None:
                ok = any(abs(lo - want) <= TOL_MS
                         for want in overheads.values())
                stated = f"{lo:g} ms"
            else:
                ok = any(lo - TOL_MS <= want <= hi + TOL_MS
                         for want in overheads.values())
                stated = f"{lo:g}-{hi:g} ms"
            if not ok:
                measured = ", ".join(f"{rid}: {want:g} ms"
                                     for rid, want in overheads.items())
                problems.append(
                    f"{perf_path}:{lineno}: caption states dispatch "
                    f"overhead {stated} but no cited record measured "
                    f"that ({measured}) — label drift")
    return problems, cited


def check_dispatch_table(path, records):
    """Validate every dispatch-table entry against the ledger (check 3).
    A missing table file is clean (the subsystem is additive); corrupt
    lines — which runtime lookups skip with a silent fallback — are
    findings here, so corruption can't persist in the committed table."""
    if not os.path.exists(path):
        return [], 0
    by_id = {r.get("id"): r for r in records}
    entries, problems = dispatch_mod.load_table(path)
    problems = [f"dispatch table {p}" for p in problems]
    for key, entry in sorted(entries.items(),
                             key=lambda kv: tuple(map(str, kv[0]))):
        tag = (f"{path}: entry {entry.get('op')}/{entry.get('bucket')}"
               f"/{entry.get('dtype')}/{entry.get('backend')}")
        for p in dispatch_mod.validate_entry(entry, by_id):
            problems.append(f"{tag}: {p}")
        # check 4: tile params payloads — legality under the shared
        # tile model + citation + pin agreement
        for p in dispatch_mod.validate_params(entry, by_id):
            problems.append(f"{tag}: {p}")
        # a dispatch default must never be decided by an injected run:
        # neither the entry itself nor any record it cites may carry
        # the APEX_FAULT_PLAN stamp
        if entry.get("fault_plan"):
            problems.append(f"{tag}: entry carries a fault_plan stamp "
                            f"({entry['fault_plan']}) — produced under "
                            f"injection")
        params_payload = entry.get("params") \
            if isinstance(entry.get("params"), dict) else {}
        cited = [entry.get("ledger")] + [
            m.get("ledger") for m in (entry.get("measured") or {}).values()
            if isinstance(m, dict)] + [
            m.get("ledger")
            for m in (params_payload.get("measured") or {}).values()
            if isinstance(m, dict)]
        for rid in cited:
            rec = by_id.get(rid)
            if rec is not None and rec.get("fault_plan"):
                problems.append(
                    f"{tag}: cites FAULT-INJECTED record {rid} "
                    f"(fault_plan={rec['fault_plan']})")
            if rec is not None:
                # check 5 on the table side: a dispatch default decided
                # by a resumed run must pin-match its checkpoint
                for p in resume_problems(rec, rid):
                    problems.append(f"{tag}: {p}")
                # check 6 on the table side: same arithmetic teeth
                for p in mfu_problems(rec, rid):
                    problems.append(f"{tag}: {p}")
                # check 7 on the table side: a grad_comm entry decided
                # by a compressed row must cite a knob-pinned record
                for p in comm_compress_problems(rec, rid):
                    problems.append(f"{tag}: {p}")
                # check 8 on the table side: a decode_attention entry
                # decided by a serving row must cite a knob-pinned one
                for p in serving_problems(rec, rid):
                    problems.append(f"{tag}: {p}")
                # check 9 on the table side: same slo teeth
                for p in slo_pin_problems(rec, rid):
                    problems.append(f"{tag}: {p}")
                # check 10 on the table side: an overlap_buckets (or
                # any) entry decided by an overlap-measured row must
                # cite a knob-pinned, claim-consistent record
                for p in overlap_problems(rec, rid):
                    problems.append(f"{tag}: {p}")
                # check 11 on the table side: a default decided by a
                # zero3/tp-sharded row must cite a knob-pinned,
                # claim-consistent record
                for p in parallel_problems(rec, rid):
                    problems.append(f"{tag}: {p}")
                # check 12 on the table side: a default decided by a
                # fleet-routed row must cite a knob-pinned,
                # claim-consistent record
                for p in router_problems(rec, rid):
                    problems.append(f"{tag}: {p}")
    return problems, len(entries)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--perf", default=os.path.join(REPO, "PERF.md"))
    ap.add_argument("--ledger",
                    default=os.path.join(REPO, "benchmarks", "ledger.jsonl"))
    ap.add_argument("--table", default=dispatch_mod.default_path())
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)

    try:
        records = ledger_mod.read_ledger(args.ledger)
    except FileNotFoundError:
        print(f"FAIL: ledger {args.ledger} does not exist")
        return 1
    except ValueError as e:
        # read_ledger names the offending file:lineno for corrupt,
        # truncated and non-object lines — the finding, not a traceback
        print(f"FAIL: {e}")
        return 1
    problems = check_ledger(records)

    with open(args.perf) as f:
        perf_text = f.read()
    cap_problems, cited = check_captions(perf_text, args.perf, records)
    problems += cap_problems

    table_problems, n_entries = check_dispatch_table(args.table, records)
    problems += table_problems

    if args.verbose:
        print(f"{len(records)} ledger records; {cited} PERF.md citations "
              f"checked; {n_entries} dispatch-table entries validated")
    if problems:
        for p in problems:
            print(f"DRIFT: {p}")
        print(f"FAIL: {len(problems)} problem(s)")
        return 1
    print("OK: ledger schema valid, no caption drift, dispatch table "
          "resolves")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except SystemExit:
        raise
    except Exception as e:  # a checker that crashes is a checker that
        # silently stops gating: any unexpected error becomes a FAIL
        # finding (tier-1 sees exit 1 + a message, never a traceback)
        print(f"FAIL: checker error: {type(e).__name__}: {e}")
        sys.exit(1)
