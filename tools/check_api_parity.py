#!/usr/bin/env python
"""Audit apex_tpu's public surface against the reference's exports.

Walks every public ``def``/``class`` name in the reference tree
(default ``/root/reference/apex``), checks each resolves somewhere in
``apex_tpu/`` (a def/class at module or class level, or a module-level
assignment alias), and reports what is missing beyond the
documented-N/A allowlist below.

Known precision limit: matching is by NAME across the whole package,
not per module — a reference export whose identifier also appears as an
unrelated repo method (``init``, ``step``, ``update``) counts as
resolved. The audit is a coverage floor and an allowlist ledger, not a
proof of per-module parity; the per-module mapping lives in each
module's reference-citation docstrings.

Usage:  python tools/check_api_parity.py [--reference PATH] [--verbose]
Exit status: 0 when every non-allowlisted name resolves, 1 otherwise.

The allowlist encodes the porting decisions the docstrings record — a
name belongs here only with a category justifying why it has no TPU
analog. The categories:

  autograd-plumbing  torch.autograd.Function internals whose capability
                     ships as a function with JAX AD (documented in the
                     owning module; e.g. tensor_parallel/layers.py).
  cuda-runtime       CUDA stream/IPC/bucket machinery replaced wholesale
                     by XLA (parallel/distributed.py docstring).
  monkey-patching    the amp O1 patch registry — replaced by dtype
                     policies (amp/__init__.py ADR).
  torch-compat       shims for pre-1.0 torch API splits (same ADR).
  fx-graph           torch.fx graph walking inside the ASP offline
                     permutation exporter; the repo's batched search
                     (contrib/sparsity/permutation_search.py) replaces
                     the whole pipeline.
  host-loop          per-element host loops the repo realizes as one
                     batched program (their inner helpers have no
                     standalone analog).
  reference-test     helpers private to the reference's own test files.
  object-api         methods of stateful torch objects whose capability
                     ships through the functional API (documented per
                     module; e.g. RNN cells, optimizer internals).
"""

import argparse
import ast
import os
import sys

ALLOWLIST = {
    # --- autograd-plumbing (Function classes + their /)
    "autograd-plumbing": """
    AmpOptimizerState BottleneckFunction CheckpointFunction
    ConvBiasMaskReLU_ ConvBiasReLU_ ConvBias_ DenseNoBiasFunc
    EncdecAttnFunc  FastEncdecAttnFunc FastEncdecAttnNormAddFunc
    FastLayerNormFN FastSelfAttnFunc FastSelfAttnNormAddFunc
    FusedDenseFunc FusedDenseGeluDenseFunc FusedLayerNormAffineFunction
    FusedLayerNormAffineMixedDtypesFunction FusedLayerNormFunction
    FusedRMSNormAffineFunction FusedRMSNormAffineMixedDtypesFunction
    FusedRMSNormFunction IndexMul2dBackward_ IndexMul2d_
    LinearWithGradAccumulationAndAsyncCommunication MlpFunction
    SelfAttnFunc SpatialBottleneckFunction SyncBatchnormFunction
    TransducerJointFunc TransducerLossFunc O2StateDictHook
     symbolic   backward_step forward_step
    get_tensor_shapes placeholder_handler
    """,
    # --- cuda-runtime
    "cuda-runtime": """
    AtomicCounter GradientBucket GradientStatus L2_grad_norm
    ParameterFragment StateBucket allreduce_bucket allreduce_fallback
     allreduce_maybe_retain
    apply_flat_dist_call comm_ready_buckets complete_reductions
    create_hooks disable_allreduce enable_allreduce extract_tensors
    flat_dist_call get_peer_buffers global_scale grad_buffer_view
    grad_norm grad_sync
    import_flatten_impl no_sync
     set_global_scale set_is_accumulation_step
    set_last_step split_by_type split_half_float_double
    sync_bucket_structure sync_wait
     bn_NHWC_impl bn_addrelu_NHWC_impl
    compute_scale_bias_method compute_scale_bias_one drelu_dscale1
    drelu_dscale2 get_scale_bias_callable init_checkpointed_activations_memory_buffer
    reset_checkpointed_activations_memory_buffer
    """,
    # --- monkey-patching / torch-compat (amp legacy glue, ADR'd)
    "monkey-patching": """
    applier as_inplace axpby_check_overflow_python cached_cast
    casted_args check_models check_optimizers check_params_fp32
    clear_overflow_state collect_fp_tensor_types
    err_if_any_half err_if_arg0_half  get_cuda_version
    get_func has_func has_old_rnns lazy_init_no_master_weights
    lazy_init_with_master_weights make_cast_wrapper make_promote_wrapper
    maybe_float maybe_half   new_rnn_cast
     new_synthesize_flattened_rnn_weights
      post_backward_models_are_masters
    post_backward_no_master_weights post_backward_no_master_weights_FusedSGD
    post_backward_with_master_weights post_backward_with_master_weights_FusedSGD
    prepare_backward_no_master_weights prepare_backward_no_master_weights_FusedSGD
    prepare_backward_with_master_weights prepare_backward_with_master_weights_FusedSGD
    promote promote_match_arg0 rnn_cast
    sequence_promote scale_check_overflow_python
    set_func set_func_save should_cache synthesize_flattened_rnn_weights
    to_type type_string unscale_python unscale_with_stashed
    unscale_with_stashed_python verbosify whitelist_rnn_cells
    OptimWrapper VariableFunctionsShim scalar_python_val filter_attrs
    is_cuda_enabled is_floating_point is_fp_tensor is_nested
    is_tensor_like tensor_is_float_tensor tensor_is_variable
    variable_is_tensor update_master_grads inspect_master_grad_data
    check_cudnn_version_and_warn check_torch_ucc_availability
    """,
    # --- fx-graph (ASP offline permutation exporter)
    "fx-graph": """
    Permutation apply_offline_permutation apply_permutation_in_C_dim
    apply_permutation_in_K_dim build_fx_graph build_offline_permutation_graph
    convert_fx_node_name extract_all_unique_siblings
    fetch_C_permutation_sequence_value fetch_K_permutation_sequence_value
    find_real_children find_real_parents find_real_siblings
    get_node_parent_children init_permutation_flag print_raw_fx_graph
    recursive_find_real_children save_graph_to_json
    set_permutation_params_from_asp set_permutation_saving_params
    transfer_to_dense_mask  already_init_asp_model
     eligible_modules init_optimizer_for_pruning
    is_sparsity_enabled restore_pruned_weights set_identical_seed
    """,
    # --- host-loop (per-stripe permutation-search inner helpers; the
    # repo's batched scorer replaces the whole family)
    "host-loop": """
    Channel_Swap Exhaustive_Search apply_2_to_4
    apply_stripe_group_permutation build_stripe_map build_stripe_pairs
    build_swap_map collect_stripes columns_to_stripes_and_swap_idx
    common_groups compute_swap_map compute_valid_1d_patterns dictify
    find_permutation generate_all_unique_combinations
    generate_stripe_groups generate_unique_combinations group_differences
    is_canonical  make_grouped
    move_groups_to_match move_permutation_towards permutation_distance
    predict_unique_combinations remove_common_groups reshape_1d
    search_for_good_permutation search_matrix stripes_and_swap_idx_to_columns
    swap_and_correct  try_permutations_on_matrix try_swap
    unstructured_prune use_gpu use_stripe_map use_swap_map


    """,
    # --- reference-test helpers
    "reference-test": """
    MyLayer MyModel ToyParallelMLPFwdBwdStepFunc
     fwd_step_func   mlp_provider_func
    model_provider_func process_batch
    transducer_joint_reference transducer_loss_reference module_size
    local_minibatch_size
    """,
    # --- object-api (stateful-object methods; functional analog shipped)
    "object-api": """
    RNNCell mLSTMCell mLSTMRNNCell detach_hidden init_hidden
    init_inference new_like reset_hidden reset_parameters flatten_list
    is_iterable add_param_group parameters
    extra_repr state_dict_for_save_checkpoint set_input_tensor
    initialize_word_embeddings word_embeddings_weight zero_parameters
    add_tokentype_embeddings post_language_model_processing
       get_model_type
    conv1x1 conv3x3 kaiming_uniform_
       backwards_debug_hook
    CoreAttention MegatronModule


    """,
}


def _collect_public_names(path, include_assigns=True):
    """Public defs/classes (+ class-body methods — reference optimizers
    expose ``step`` etc. as methods) and module-top-level assignment
    aliases, from a package directory or a single ``.py`` file (one
    visitor so the two spellings cannot drift). Function-local closures
    and local/class-body variables do NOT count — they are neither
    importable API nor a resolution of one (a local ``fill = ...`` must
    not mark the reference's public ``fill`` ported)."""
    names = set()
    skip_dirs = {"csrc", "test", "tests", "examples", "__pycache__",
                 "permutation_tests"}

    def visit_body(body, depth):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.ClassDef)):
                if not node.name.startswith("_"):
                    names.add(node.name)
                if isinstance(node, ast.ClassDef):
                    visit_body(node.body, depth + 1)
            elif (include_assigns and depth == 0
                  and isinstance(node, ast.Assign)):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) \
                            and not tgt.id.startswith("_"):
                        names.add(tgt.id)

    def visit_file(fpath):
        try:
            with open(fpath, encoding="utf-8") as fh:
                tree = ast.parse(fh.read())
        except (SyntaxError, UnicodeDecodeError, OSError):
            return
        visit_body(tree.body, 0)

    if os.path.isfile(path):
        visit_file(path)
        return names
    for root, dirs, files in os.walk(path):
        dirs[:] = [d for d in dirs if d not in skip_dirs]
        for f in files:
            if f.endswith(".py"):
                visit_file(os.path.join(root, f))
    return names


def reference_names(ref_root):
    # defs/classes only: the reference's module-level assignments are
    # constants and Function-apply instances, not API to port
    return _collect_public_names(ref_root, include_assigns=False)


# Per-module audit map: reference subtree -> repo subtrees a name may
# resolve in. Scoping the match kills the package-wide name-collision
# blind spot (``init``/``step``/``update`` resolving against unrelated
# defs). Extra repo dirs encode DOCUMENTED relocations only (each cited
# in the owning module's docstring).
PER_MODULE = [
    ("amp", ["amp", "multi_tensor_apply", "utils.py"]),
    ("fp16_utils", ["fp16_utils", "amp"]),
    ("optimizers", ["optimizers", "multi_tensor_apply"]),
    ("parallel", ["parallel", "multi_tensor_apply"]),
    ("normalization", ["normalization", "ops"]),
    ("mlp", ["mlp"]),
    ("fused_dense", ["fused_dense"]),
    ("RNN", ["RNN"]),
    ("transformer/tensor_parallel",
     ["transformer/tensor_parallel", "transformer/parallel_state.py",
      "transformer/utils.py"]),
    ("transformer/pipeline_parallel",
     ["transformer/pipeline_parallel", "transformer/microbatches.py",
      "transformer/parallel_state.py", "transformer/testing/global_vars.py"]),
    ("transformer/functional", ["transformer/functional", "ops"]),
    ("contrib/optimizers", ["contrib/optimizers", "optimizers",
                            "fp16_utils"]),
    ("contrib/sparsity", ["contrib/sparsity"]),
    ("contrib/xentropy", ["contrib/xentropy", "ops"]),
    ("contrib/fmha", ["contrib/fmha"]),
    ("contrib/multihead_attn", ["contrib/multihead_attn"]),
    ("contrib/transducer", ["contrib/transducer"]),
    ("contrib/groupbn", ["contrib/groupbn"]),
    ("contrib/clip_grad", ["contrib/clip_grad"]),
    ("contrib/focal_loss", ["contrib/focal_loss"]),
]

# torch object-protocol methods: nn.Module / Optimizer / autograd
# Function surface whose capability ships through the functional JAX API
# everywhere (optax-style transforms, custom_vjp). The package-wide
# audit resolved these by name collision; the scoped audit names the
# category instead of pretending they resolve.
TORCH_OBJECT_PROTOCOL = frozenset(
    "forward backward step zero_grad state_dict load_state_dict add "
    "update_scale loss_scale clip_grad_norm".split())


def per_module_report(ref_root, repo_pkg, allow, verbose):
    """Scoped resolution for the PER_MODULE groups. Returns #missing."""
    total_missing = 0
    for ref_sub, repo_subs in PER_MODULE:
        ref_dir = os.path.join(ref_root, ref_sub)
        if not os.path.isdir(ref_dir):
            print(f"[{ref_sub}] reference subtree absent; skipped")
            continue
        names = reference_names(ref_dir)
        repo_names = set()
        for sub in repo_subs:
            repo_names |= _collect_public_names(os.path.join(repo_pkg, sub))
        missing = []
        n_allowed = n_proto = 0
        for n in sorted(names):
            if n in repo_names:
                continue
            if n in TORCH_OBJECT_PROTOCOL:
                n_proto += 1
                continue
            if n in allow:
                n_allowed += 1
                continue
            missing.append(n)
        total_missing += len(missing)
        status = "ok" if not missing else "MISSING " + " ".join(missing)
        print(f"[{ref_sub}] {len(names)} names: "
              f"{len(names) - n_allowed - n_proto - len(missing)} resolve "
              f"in {'+'.join(repo_subs)}, {n_allowed} n/a, "
              f"{n_proto} object-protocol — {status}")
    return total_missing


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference", default="/root/reference/apex")
    ap.add_argument("--repo-pkg", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "apex_tpu"))
    ap.add_argument("--verbose", action="store_true")
    ap.add_argument("--per-module", action="store_true",
                    help="scoped audit of the PER_MODULE map (no "
                         "package-wide name matching)")
    args = ap.parse_args()

    if not os.path.isdir(args.reference):
        print(f"reference tree not found at {args.reference}; skipping")
        return 0

    allow = {}
    for category, block in ALLOWLIST.items():
        for n in block.split():
            allow[n] = category

    if args.per_module:
        return 1 if per_module_report(args.reference, args.repo_pkg,
                                      allow, args.verbose) else 0

    names = reference_names(args.reference)
    repo_names = _collect_public_names(args.repo_pkg)
    missing, allowed = [], []
    for n in sorted(names):
        if n in repo_names:
            continue
        (allowed if n in allow else missing).append(n)

    # allowlist hygiene: entries the collector can never match (nested
    # helpers, typos) or that the repo resolves anyway are rot — a typo
    # in a needed entry would otherwise fail silently as MISSING
    stale = sorted(n for n in allow
                   if n not in names or n in repo_names)
    if stale:
        print(f"STALE allowlist: {len(stale)} entries are inert (not "
              f"collected from the reference, or resolving in the repo "
              f"— prune them): {' '.join(stale)}")

    print(f"{len(names)} reference names; "
          f"{len(names) - len(missing) - len(allowed)} resolve, "
          f"{len(allowed)} documented-N/A, {len(missing)} MISSING")
    if args.verbose:
        for n in allowed:
            print(f"  n/a [{allow[n]}] {n}")
    for n in missing:
        print(f"  MISSING {n}")
    return 1 if (missing or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
