"""Config-driven GPT/BERT pretraining (BASELINE configs 3 and 4).

Capability port of the reference pretrain entries
(tests/L0/run_transformer/run_gpt_minimal_test.py + megatron's
pretrain_{gpt,bert}.py pattern) driven by the Megatron argument bundle
(apex_tpu.transformer.testing.arguments).

TPU-first loop shape: the reference dispatches one fwd/bwd per Python step
(torch eager); here ``log_interval`` training steps run inside ONE jitted
``lax.scan`` dispatch over the (dp, tp) mesh — the host only sees a loss
trace per chunk. Synthetic data (the reference minimal tests use synthetic
ids too).

Run (BERT-large + FusedLAMB, BASELINE config 3):
    python examples/transformer/pretrain.py --model bert \
        --num-layers 24 --hidden-size 1024 --num-attention-heads 16 \
        --max-position-embeddings 512 --seq-length 512 \
        --micro-batch-size 4 --optimizer lamb --lr 1e-4 --bf16 \
        --train-iters 30 --log-interval 10

GPT-2 345M TP (BASELINE config 4): --model gpt --num-layers 24
    --hidden-size 1024 ... --tensor-model-parallel-size 2
"""

import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.amp.scaler import LossScaler
from apex_tpu.optimizers.fused_adam import fused_adam
from apex_tpu.optimizers.fused_lamb import fused_lamb
from apex_tpu.optimizers.fused_sgd import fused_sgd
from apex_tpu.transformer.parallel_state import DATA_AXIS, TENSOR_AXIS
from apex_tpu.transformer.testing import (
    BertModel,
    GPTModel,
    global_vars,
    parse_args,
)


def _extra_args(parser):
    parser.add_argument("--model", choices=("gpt", "bert"), default="gpt")
    parser.add_argument("--vocab-size", type=int, default=50257)
    return parser


def make_lr_schedule(args):
    """Warmup + {constant|linear|cosine} decay to min_lr, driven by the
    Megatron lr arg group (reference: the AnnealingLR scheduler those
    args configure). Returns a jit-safe ``step -> lr`` callable; the
    fused optimizers call it with their on-device step count."""
    base, mn = args.lr, args.min_lr
    decay_iters = args.lr_decay_iters or args.train_iters
    warmup = args.lr_warmup_iters
    if args.lr_warmup_fraction is not None:
        warmup = int(args.lr_warmup_fraction * decay_iters)
    style = args.lr_decay_style

    def sched(step):
        step = jnp.asarray(step, jnp.float32)
        warm_lr = base * step / max(warmup, 1)
        frac = jnp.clip((step - warmup) / max(decay_iters - warmup, 1),
                        0.0, 1.0)
        if style == "constant":
            decayed = jnp.asarray(base, jnp.float32)
        elif style == "linear":
            decayed = base - (base - mn) * frac
        elif style == "cosine":
            decayed = mn + (base - mn) * 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
        else:
            raise ValueError(f"unknown lr_decay_style {style!r}")
        return jnp.where(step < warmup, warm_lr, decayed)

    return sched


def make_optimizer(args):
    """args.optimizer → fused transform (reference _add_training_args
    --optimizer {adam,sgd} + the LAMB path of the BERT recipe), with the
    lr arg group's warmup/decay schedule."""
    lr = make_lr_schedule(args)
    if args.optimizer == "adam":
        return fused_adam(learning_rate=lr, betas=(args.adam_beta1,
                                                   args.adam_beta2),
                          eps=args.adam_eps, weight_decay=args.weight_decay)
    if args.optimizer == "lamb":
        return fused_lamb(learning_rate=lr, betas=(args.adam_beta1,
                                                   args.adam_beta2),
                          eps=args.adam_eps, weight_decay=args.weight_decay)
    if args.optimizer == "sgd":
        return fused_sgd(learning_rate=lr, momentum=args.sgd_momentum,
                         weight_decay=args.weight_decay)
    raise ValueError(f"unknown optimizer {args.optimizer}")


def main(argv=None):
    # no-op unless launched by ``python -m apex_tpu.parallel.multiproc``;
    # afterwards jax.devices() is the GLOBAL list and the (dp, tp) mesh
    # spans hosts (collectives ride ICI within a host, DCN across)
    from apex_tpu.parallel.multiproc import init_distributed

    init_distributed()
    devices = jax.devices()
    args = global_vars.set_global_variables(
        argv, extra_args_provider=_extra_args,
        world_size=len(devices), ignore_unknown_args=False)
    args.rank = jax.process_index()
    timers = global_vars.get_timers()

    tp = args.tensor_model_parallel_size
    if args.pipeline_model_parallel_size != 1:
        raise NotImplementedError(
            "pretrain.py drives the (dp, tp) mesh; pipeline-parallel "
            "training lives in apex_tpu.transformer.testing.minimal")
    dp = args.data_parallel_size
    mesh = Mesh(np.asarray(devices[:dp * tp]).reshape(dp, tp),
                (DATA_AXIS, TENSOR_AXIS))

    vocab = args.pad_vocab_size(args.vocab_size)
    cfg = args.to_transformer_config()
    s = args.seq_length
    b_local = args.micro_batch_size  # per-dp-rank batch
    model_cls = GPTModel if args.model == "gpt" else BertModel
    model = model_cls(cfg)

    # every process builds the same full batch (same seed) and places it
    # ONCE onto the global dp-sharded layout — host numpy is a valid
    # multi-process input but would re-stage host->device every chunk
    from jax.sharding import NamedSharding

    rs = np.random.RandomState(args.seed)
    sh_data = NamedSharding(mesh, P(DATA_AXIS))
    ids = jax.device_put(
        rs.randint(0, vocab, (dp * b_local, s)).astype(np.int32), sh_data)
    labels = jax.device_put(
        rs.randint(0, vocab, (dp * b_local, s)).astype(np.int32), sh_data)
    pos = jax.device_put(
        np.ascontiguousarray(np.broadcast_to(
            np.arange(s, dtype=np.int32)[None], ids.shape)), sh_data)

    scaler = LossScaler(loss_scale="dynamic" if args.fp16
                        else float(args.loss_scale or 1.0))
    tx = make_optimizer(args)

    def fwd_loss(p, ids, pos, labels, scale):
        mutable = ["intermediates"] if cfg.num_moe_experts else False
        if args.model == "gpt":
            out = model.apply({"params": p}, ids, pos, None, labels,
                              mutable=mutable)
        else:
            out = model.apply({"params": p}, ids, jnp.ones_like(ids),
                              lm_labels=labels, mutable=mutable)
        if mutable:
            out, new_vars = out
        per_tok = out[0] if args.model == "bert" else out
        loss = jnp.mean(per_tok)
        if mutable:
            # Switch aux loss: explicit objective term, not a side effect
            from apex_tpu.transformer.moe import collect_moe_aux

            loss = loss + cfg.moe_aux_loss_coeff * collect_moe_aux(
                new_vars["intermediates"])
        return loss * scale

    def init_fn(ids, pos, labels):
        if args.model == "gpt":
            return model.init(jax.random.PRNGKey(args.seed), ids, pos,
                              None)["params"]
        return model.init(jax.random.PRNGKey(args.seed), ids,
                          jnp.ones_like(ids))["params"]

    def chunk_fn(n_steps):
        """n_steps training steps under one dispatch."""
        def local(params, opt_state, scaler_state, ids, pos, labels):
            def body(carry, _):
                p, o, ss = carry
                scale = scaler.scale(jnp.float32(1.0), ss)
                loss, grads = jax.value_and_grad(fwd_loss)(
                    p, ids, pos, labels, scale)
                grads = jax.tree_util.tree_map(
                    lambda g: lax.pmean(g, DATA_AXIS), grads)
                grads, found_inf = scaler.unscale(grads, ss)
                found_inf = lax.pmax(found_inf, TENSOR_AXIS)
                nss = scaler.update(ss, found_inf)
                updates, no = tx.update(grads, o, p)
                np_ = jax.tree_util.tree_map(
                    lambda a, u: jnp.where(found_inf, a,
                                           a + u.astype(a.dtype)),
                    p, updates)
                no = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(found_inf, old, new), no, o)
                return (np_, no, nss), lax.pmean(loss, DATA_AXIS) / scale

            carry, losses = lax.scan(
                body, (params, opt_state, scaler_state), jnp.arange(n_steps))
            return carry + (losses,)

        def step(params, opt_state, scaler_state, ids, pos, labels):
            return jax.shard_map(
                local, mesh=mesh,
                in_specs=(P(), P(), P(), P(DATA_AXIS), P(DATA_AXIS),
                          P(DATA_AXIS)),
                out_specs=P(), check_vma=False)(
                params, opt_state, scaler_state, ids, pos, labels)

        return jax.jit(step, donate_argnums=(0, 1, 2))

    params = jax.jit(jax.shard_map(
        init_fn, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(), check_vma=False))(ids, pos, labels)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    opt_state = jax.jit(lambda p: tx.init(p))(params)
    # host scalars (replicated-consistent multi-process jit inputs)
    scaler_state = jax.tree_util.tree_map(np.asarray, scaler.init())

    # --- checkpoint/resume (reference checkpointing args :646-669) ---
    start_iter = 0
    if args.load:
        from apex_tpu import checkpoint as ckpt_mod
        from jax.sharding import NamedSharding

        # everything in this (dp, tp) entry is replicated outside
        # shard_map — restore directly onto the replicated mesh sharding
        # (a plain concrete template would inherit whatever mix of
        # committed devices each state happened to be created on)
        repl = NamedSharding(mesh, P())

        with ckpt_mod.CheckpointManager(args.load) as lm:
            step0 = lm.latest_step()
            # keys None = metadata unreadable → optimistically try the
            # full restore (a failure there surfaces, as it should)
            keys = lm.tree_keys(step0) if step0 is not None else None
            # --finetune loads weights ONLY (megatron semantics): a
            # restored optimizer count would pin the lr schedule at the
            # old run's decay floor
            full = (step0 is not None and not args.no_load_optim
                    and not args.finetune
                    and (keys is None or "opt" in keys))
            if step0 is not None and full:
                tmpl = {"params": ckpt_mod.abstract_like(params, repl),
                        "opt": ckpt_mod.abstract_like(opt_state, repl),
                        "scaler": ckpt_mod.abstract_like(scaler_state,
                                                         repl)}
                restored = lm.restore(step0, tmpl)
                params = restored["params"]
                opt_state = restored["opt"]
                scaler_state = restored["scaler"]
            elif step0 is not None:
                # params-only: checkpoint was written with
                # --no-save-optim, or --no-load-optim was passed
                # (megatron's warn-and-continue posture)
                if (args.rank == 0 and not args.no_load_optim
                        and not args.finetune):
                    # reached without an explicit weights-only flag: the
                    # checkpoint itself lacks the opt subtree
                    print("checkpoint has no optimizer state (saved with "
                          "--no-save-optim); loading params only",
                          flush=True)
                params = lm.restore(
                    step0,
                    {"params": ckpt_mod.abstract_like(params, repl)},
                    partial=True)["params"]
        if step0 is None:
            # the Megatron posture: warn loudly, start from scratch
            if args.rank == 0:
                print(f"WARNING: no checkpoint found in {args.load}; "
                      "training from random initialization", flush=True)
        else:
            if not args.finetune:
                start_iter = step0
            if args.rank == 0:
                print(f"loaded checkpoint {args.load} @ iter {step0}"
                      f"{' (finetune: iter reset)' if args.finetune else ''}",
                      flush=True)

    save_mgr = None
    if args.save:
        from apex_tpu import checkpoint as ckpt_mod

        save_mgr = ckpt_mod.CheckpointManager(args.save)

    def save_state(step):
        # orbax's FixedIntervalPolicy saves only at step % N == 0, which
        # a chunked step grid (done = start + k*log_n) can miss forever —
        # the interval-crossing check below throttles instead, so the
        # manager itself is un-throttled; skip steps that already exist
        # (e.g. rerunning into a dir left by a longer previous run)
        if save_mgr is None or step in save_mgr.all_steps():
            return
        state = {"params": params} if args.no_save_optim else {
            "params": params, "opt": opt_state, "scaler": scaler_state}
        save_mgr.save(step, state)

    log_n = max(1, min(args.log_interval, args.train_iters))
    run_chunk = chunk_fn(log_n)

    if args.rank == 0:
        print(f"{args.model} pretrain | params {n_params/1e6:.1f}M | "
              f"mesh dp={dp} tp={tp} | mbs {b_local} seq {s} | "
              f"opt {args.optimizer}", flush=True)

    done = start_iter
    if done >= args.train_iters and args.rank == 0:
        print(f"checkpoint iter {done} >= --train-iters "
              f"{args.train_iters}: nothing left to train (pass "
              "--finetune to reset the iteration count)", flush=True)
    first_chunk = True
    last_loss = float("nan")
    tokens_per_sec = 0.0
    compile_and_run = None
    timers("interval-time").start()
    while done < args.train_iters:
        params, opt_state, scaler_state, losses = run_chunk(
            params, opt_state, scaler_state, ids, pos, labels)
        # 1-element fetch = device sync (axon block_until_ready caveat)
        last_loss = float(np.asarray(losses[-1]))
        done += log_n
        # save when a multiple of save_interval falls inside this chunk
        # (correct on any chunk grid, aligned or not)
        if args.save_interval and done % args.save_interval < log_n:
            save_state(done)
        elapsed = timers("interval-time").elapsed()
        if first_chunk:
            first_chunk = False
            # first chunk includes compile; don't count it in throughput
            compile_and_run = elapsed
            if args.rank == 0:
                print(f" iter {done}: loss {last_loss:.4f} "
                      f"(first chunk incl. compile {compile_and_run:.1f}s)",
                      flush=True)
            continue
        tokens_per_sec = log_n * dp * b_local * s / elapsed
        if args.rank == 0:
            print(f" iter {done}: loss {last_loss:.4f}  "
                  f"{tokens_per_sec:,.0f} tokens/s  "
                  f"({elapsed/log_n*1e3:.1f} ms/iter)", flush=True)
    if tokens_per_sec == 0.0 and compile_and_run:
        # single-chunk run: report throughput from the compile chunk rather
        # than a misleading 0 (flagged as compile-inclusive)
        tokens_per_sec = log_n * dp * b_local * s / compile_and_run
        if args.rank == 0:
            print(f" tokens/s {tokens_per_sec:,.0f} "
                  "(single chunk, INCLUDES compile)", flush=True)

    if save_mgr is not None:
        save_state(done)  # final state (no-op if that step exists)
        save_mgr.close()

    global_vars.destroy_global_vars()
    from apex_tpu.transformer.pipeline_parallel.utils import (
        destroy_microbatch_calculator,
    )
    try:
        destroy_microbatch_calculator()
    except Exception:
        pass
    return {"loss": last_loss, "tokens_per_sec": tokens_per_sec,
            "n_params": n_params}


if __name__ == "__main__":
    main()
