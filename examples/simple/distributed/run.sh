#!/bin/bash
# Reference: torch.distributed.launch --nproc_per_node=2 → the multiproc
# launcher spawns one process per (virtual) host and wires the
# jax.distributed coordinator env.
exec python -m apex_tpu.parallel.multiproc --nproc 2 \
    "$(dirname "$0")/distributed_data_parallel.py"
