"""Minimal multi-process data-parallel training with amp.

Capability port of the reference walkthrough
(examples/simple/distributed/distributed_data_parallel.py): a linear model
on fake data, amp O1, gradients averaged across processes. The TPU-native
translation of each "FOR DISTRIBUTED" step:

  * ``torch.distributed.launch``      → ``python -m apex_tpu.parallel.multiproc``
    (see run.sh; one process per host, JAX owns that host's chips)
  * ``init_process_group('nccl')``    → ``multiproc.init_distributed()``
    (jax.distributed over the coordinator; collectives ride ICI/DCN)
  * ``DistributedDataParallel(model)``→ ``allreduce_gradients`` inside the
    jitted step (one fused pmean over the "data" axis — there is no
    hook/bucket machinery to configure)
  * ``amp.scale_loss(...).backward()``→ ``amp.value_and_scaled_grad``

Run:  ./run.sh        (2 localhost processes)
      python distributed_data_parallel.py    (single process also works)
"""

import numpy as np

import jax

# Single-host CPU demo backend unless a real accelerator is the default;
# must be set before distributed init (same rule as tests/conftest.py).
jax.config.update("jax_platforms", "cpu")

from apex_tpu.parallel.multiproc import init_distributed  # noqa: E402

distributed = init_distributed()

import jax.numpy as jnp  # noqa: E402
from jax import shard_map  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

from apex_tpu import amp  # noqa: E402
from apex_tpu.optimizers import fused_sgd  # noqa: E402
from apex_tpu.parallel.distributed import allreduce_gradients  # noqa: E402

N, D_in, D_out = 64, 1024, 16
rank = jax.process_index()

# each process gets its own batch of fake data (reference comment applies)
rs = np.random.RandomState(42 + rank)
x = jnp.asarray(rs.randn(N, D_in), jnp.float32)
y = jnp.asarray(rs.randn(N, D_out), jnp.float32)

rs_w = np.random.RandomState(0)  # identical init on every process
params = {
    "w": jnp.asarray(rs_w.randn(D_in, D_out) / np.sqrt(D_in), jnp.float32),
    "b": jnp.zeros((D_out,), jnp.float32),
}

tx = fused_sgd(learning_rate=1e-3)
params, opt = amp.initialize(params, tx, opt_level="O1")
state = opt.init(params)

mesh = Mesh(np.asarray(jax.devices()), ("data",))

if distributed and jax.process_count() > 1:
    # multi-process jit takes GLOBAL arrays: stitch each process's local
    # batch into the data-sharded global batch; params/state replicate
    from jax.sharding import NamedSharding

    sh_data = NamedSharding(mesh, P("data"))
    sh_rep = NamedSharding(mesh, P())
    x = jax.make_array_from_process_local_data(sh_data, np.asarray(x))
    y = jax.make_array_from_process_local_data(sh_data, np.asarray(y))
    params = jax.device_put(params, sh_rep)
    state = jax.device_put(state, sh_rep)


def loss_fn(p, x, y):
    pred = x @ p["w"].astype(x.dtype) + p["b"].astype(x.dtype)
    return jnp.mean((pred - y) ** 2)


@jax.jit
def step(params, state, x, y):
    def local(params, state, x, y):
        f = amp.value_and_scaled_grad(
            lambda p: loss_fn(p, x, y), opt)
        loss, grads, found_inf = f(params, state)
        grads = allreduce_gradients(grads, "data")
        # skip-step must be a GLOBAL decision: one rank's overflow reaches
        # every rank through the grad allreduce (same rule as
        # transformer.amp.GradScaler)
        found_inf = jax.lax.pmax(found_inf, "data")
        params, state, _ = opt.apply_gradients(
            grads, state, params, grads_already_unscaled=True,
            found_inf=found_inf)
        return params, state, jax.lax.pmean(loss, "data")

    return shard_map(local, mesh=mesh,
                     in_specs=(P(), P(), P("data"), P("data")),
                     out_specs=(P(), P(), P()), check_vma=False)(
        params, state, x, y)


def main(iters=500):
    global params, state
    loss = None
    for _ in range(iters):
        params, state, loss = step(params, state, x, y)
    loss = float(np.asarray(loss))
    if rank == 0:
        print(f"final loss = {loss:.6f}", flush=True)
    return loss


if __name__ == "__main__":
    main()
